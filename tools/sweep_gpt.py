#!/usr/bin/env python
"""On-chip GPT-350M train-step sweep: remat policy x batch x optimizer
layout (companion to tools/profile_bert.py; same hard-sync protocol)."""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np

from _timing import sync as _sync, time_steps as _time  # noqa: E402


def make_step(batch, remat, policy, leaf, accum=1):
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.optimizers import FusedAdam

    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, max_seq_len=1024, remat=remat,
                    remat_policy=policy, dtype=jnp.bfloat16)
    seq = 1024
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    adam = FusedAdam(lr=1e-4, bucketed=not leaf)
    opt_state = adam.init(params)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (accum, batch, seq)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                      (accum, batch, seq)))

    from bench import _accumulated_grads  # shared accumulation numerics

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens, targets):
        loss, grads = _accumulated_grads(model.loss, params, tokens,
                                         targets, accum)
        new_params, new_opt = adam.step(grads, params, opt_state)
        return loss, new_params, new_opt

    holder = {"p": params, "o": opt_state}

    def run(tokens, targets):
        loss, holder["p"], holder["o"] = train_step(holder["p"],
                                                    holder["o"], tokens,
                                                    targets)
        return loss

    return run, (tokens, targets), accum * batch * seq


def main():
    configs = [
        ("b16_dots_leaf", dict(batch=16, remat=True, policy="dots",
                               leaf=True)),
        ("b8_none_leaf", dict(batch=8, remat=False, policy="full",
                              leaf=True)),
        ("b12_none_leaf", dict(batch=12, remat=False, policy="full",
                               leaf=True)),
        ("b16_none_leaf", dict(batch=16, remat=False, policy="full",
                               leaf=True)),
        ("b16_dots", dict(batch=16, remat=True, policy="dots",
                          leaf=False)),
        ("b8x2_none_leaf", dict(batch=8, remat=False, policy="full",
                                leaf=True, accum=2)),
        ("b8x4_none_leaf", dict(batch=8, remat=False, policy="full",
                                leaf=True, accum=4)),
    ]
    if len(sys.argv) > 1:
        names = set(sys.argv[1].split(","))
        configs = [c for c in configs if c[0] in names]
    for name, kw in configs:
        try:
            run, args, tok = make_step(**kw)
            dt = _time(run, args)
            print(f"{name}: {tok / dt:,.0f} tok/s (step {dt * 1e3:.1f} ms)",
                  flush=True)
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:120]}", flush=True)
        jax.clear_caches()


if __name__ == "__main__":
    main()
