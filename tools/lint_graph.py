#!/usr/bin/env python
"""Lint the canonical train/serve programs against the committed
baseline.

Runs the ``apex_tpu.analysis`` registry (dtype / donation / host-sync /
recompile / sharding / overlap + the peak-memory estimator) over the
six canonical programs — the GPT train step at dp, tp=2 + sequence
parallelism, pp=2; the anomaly-guarded step; serving prefill and
decode — and diffs every finding against the accepted baseline.  Any
NEW finding exits nonzero: this is the CI gate (``__graft_entry__``'s
``_dryrun_lint`` leg and ``bench.py lint`` both drive this file).

Linting is compile-only (nothing executes), so it runs anywhere —
including a 1-core CPU host with the 8-device mesh forced below.

Usage:
    python tools/lint_graph.py                        # table vs baseline
    python tools/lint_graph.py --json                 # machine-readable
    python tools/lint_graph.py --programs decode,prefill
    python tools/lint_graph.py --write-baseline       # accept findings
    python tools/lint_graph.py --baseline my.json --devices 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "lint_baseline.json")


def main() -> int:
    ap = argparse.ArgumentParser(
        description="lint the canonical programs against the baseline")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset (default: all six)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON document instead of tables")
    ap.add_argument("--table", action="store_true",
                    help="force the table view (default)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"accepted-findings file (default "
                         f"{os.path.relpath(DEFAULT_BASELINE)})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report everything; never exit nonzero")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings into --baseline")
    ap.add_argument("--devices", type=int, default=8,
                    help="forced CPU device count (default 8)")
    args = ap.parse_args()

    # environment BEFORE jax imports: the lint mesh is always host CPU
    # (the axon TPU plugin force-registers otherwise), with the device
    # count the canonical programs expect
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{args.devices}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    from apex_tpu.analysis import lint, load_baseline, save_baseline
    from apex_tpu.analysis.canonical import canonical_programs

    names = args.programs.split(",") if args.programs else None
    reports = [lint(p) for p in
               canonical_programs(names, n_devices=args.devices)]

    baseline = {}
    if not args.no_baseline and not args.write_baseline \
            and os.path.exists(args.baseline):
        baseline = load_baseline(args.baseline)
    new = {r.program: r.new_findings(baseline.get(r.program, []))
           for r in reports}
    n_new = sum(len(v) for v in new.values())

    if args.write_baseline:
        save_baseline(args.baseline, reports)
        print(f"wrote {args.baseline}: "
              + ", ".join(f"{r.program}={len(r.findings)}"
                          for r in reports))
        return 0

    if args.as_json:
        doc = {"programs": [r.to_dict() for r in reports],
               "baseline": args.baseline if baseline else None,
               "new_findings": {k: [f.to_dict() for f in v]
                                for k, v in new.items() if v}}
        print(json.dumps(doc, indent=2))
    else:
        for r in reports:
            print(r.format_table())
            fresh = new[r.program]
            if fresh:
                print(f"  !! {len(fresh)} NEW finding(s) not in baseline:")
                for f in fresh:
                    print(f"     {f.key}")
            print()
        total = sum(len(r.findings) for r in reports)
        print(f"{len(reports)} program(s), {total} finding(s), "
              f"{n_new} new vs baseline")

    if args.no_baseline:
        return 0
    return 1 if n_new else 0


if __name__ == "__main__":
    sys.exit(main())
