#!/usr/bin/env python
"""Open-loop load generator + chaos scenario suite for apex_tpu serving.

Synthesizes realistic serving traffic against a multi-replica
:class:`~apex_tpu.serving.Router` of paged engines and reports the
numbers an operator actually tunes against:

* **arrivals**: open-loop Poisson process at ``--rate`` requests/s —
  open-loop because closed-loop (wait-for-response) generators hide
  overload by self-throttling, exactly the regime worth measuring;
* **prompt lengths**: heavy-tail Pareto (bounded) — serving traffic is
  never Gaussian, and the tail prompts are what chunked prefill exists
  for;
* **prefix sharing**: each request draws a shared system prompt with
  probability ``--shared-prefix-prob`` (one of ``--num-prefixes``
  variants), exercising the radix-trie block reuse;
* **SLO pressure**: every replica gets a TTFT SLOTarget; the router's
  burn-rate admission and queue-depth shedding run live, and the
  report separates served from shed traffic;
* **client backoff**: a shed request is NOT silently dropped — with
  ``--client-retries`` > 0 the client honors the shed's machine-readable
  ``retry_after_s`` with jitter and resubmits, the way a real client
  maps a 429.  The report counts every outcome (eos/length/timeout/
  evicted/shed/...) separately instead of silently excluding failures
  from the percentiles.

Reported: TTFT p50/p90/p99 (engine-measured, submit → first token),
TPOT (per-token decode latency after the first), end-to-end latency
percentiles (host-tracked, submit → completion), throughput
(tokens/s over the drive wall time), shed fraction, per-outcome
counts, and the pool's prefix-cache hit rate.

``--overload`` submits the whole workload as an instantaneous burst
(rate → ∞), deterministically driving queue depths past the admission
bound so the shedding path is exercised regardless of host speed — the
mode the dryrun gate runs.

**Chaos scenarios** (``--scenario``): the fleet-level suite.  The stack
becomes a :class:`~apex_tpu.serving.FleetRouter` (health checks, retry/
hedging, cross-replica migration, degradation ladder) on a
:class:`~apex_tpu.serving.VirtualClock`, so fault timing, backoff and
SLO burn are deterministic on any host:

* ``steady`` — the baseline: no faults, same fleet machinery;
* ``replica_kill`` — a replica crashes mid-burst (``--kill-tick``);
  its in-flight requests migrate and resume token-bitwise;
* ``slow_replica`` — one replica silently degrades
  (``--slow-s`` extra seconds/tick); the straggler detector marks it
  SUSPECT and hedged dispatch covers the tail;
* ``diurnal`` — a sin²-modulated arrival rate (the traffic shape
  ROADMAP item 4's capacity shifting trains against);
* ``bursty`` — synchronized arrival bursts driving overload, the
  degradation ladder, shedding with retry_after, and client backoff;
* ``capacity_diurnal`` — the day-in-the-life capacity-shifting sim:
  diurnal traffic against a fleet whose chip budget is shared with a
  live :class:`~apex_tpu.resilience.elastic.ElasticTrainer` under a
  burn-driven :class:`~apex_tpu.resilience.capacity.CapacityController`
  (delegates to ``tools/day_in_life.py``, which owns the training side
  and the hard gates);
* ``autopilot_drift`` — the self-driving-parallelism day (ROADMAP
  item 3): diurnal traffic beside a live trainer whose
  :class:`~apex_tpu.resilience.autopilot.ParallelismAutopilot` must
  DETECT a mid-day interconnect drift from refitted telemetry, commit
  a re-ranked plan through the measured drain→gate protocol, then ROLL
  BACK a second adoption whose commit gate an injected
  ``plan_regression`` poisons; GATES on exactly-once delivery, SLO
  attainment ≥ 0.9, ≥ 1 commit AND ≥ 1 rollback with counters matching
  the applied-fault log, a flap-free audit, and training state bitwise
  vs an uninterrupted fixed-plan reference (delegates to
  ``tools/day_in_life.py --autopilot``);
* ``disagg_diurnal`` — a mixed day against a
  :class:`~apex_tpu.serving.DisaggregatedFleet`: a prefill-heavy
  morning (long prompts, short generations) flips mid-day into a
  decode-heavy afternoon (short prompts, long generations), and a
  :class:`~apex_tpu.resilience.capacity.PoolCapacityController` moves
  a replica prefill→decode at the flip; GATES on the exactly-once
  ledger, per-phase SLO attainment ≥ 0.9, and a clean capacity audit;
* ``disagg_longctx_fair`` — multi-tenant fairness on the same
  disaggregated stack: one tenant submits near-context-limit prompts
  while the others run short interactive traffic; GATES on the
  exactly-once ledger and per-TENANT SLO attainment ≥ 0.9 — the
  long-context tenant must not starve the short ones of first tokens
  (that isolation is the point of a separate prefill pool);
* ``disagg_quant`` — the ``disagg_diurnal`` mixed day (same workload,
  same mid-day pool flip) on the fully-quantized stack: int8 decode
  weights (``GPTConfig(weight_quant="int8")``, every replica
  quantizes once at init) × int8 KV blocks over the handoff channel;
  GATES on the exactly-once ledger and per-phase SLO attainment
  ≥ 0.9 — quantization must not cost a response or an SLO.

Every scenario report carries the exactly-once ledger (``submitted`` /
``lost`` / ``duplicated``), per-outcome counts, SLO attainment over the
virtual clock, the fleet's health/fault logs, and the
detection→migration→first-resumed-token recovery timeline.

Usage::

    python tools/loadgen.py --requests 64 --rate 32 --replicas 2
    python tools/loadgen.py --overload --json
    python tools/loadgen.py --scenario replica_kill --replicas 3 --json
    python tools/loadgen.py --scenario bursty --client-retries 5
"""

from __future__ import annotations

import argparse
import bisect
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax            # noqa: E402
import numpy as np    # noqa: E402

SCENARIOS = ("steady", "replica_kill", "slow_replica", "diurnal", "bursty",
             "capacity_diurnal", "autopilot_drift", "disagg_diurnal",
             "disagg_longctx_fair", "disagg_quant")

DISAGG_SCENARIOS = ("disagg_diurnal", "disagg_longctx_fair",
                    "disagg_quant")

# scenarios that run the disagg_diurnal mixed-day workload (and its
# mid-day pool flip)
_DIURNAL_MIX = ("disagg_diurnal", "disagg_quant")


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs else 0.0


def _build_model(args):
    from apex_tpu.models.gpt import GPTConfig, GPTModel

    wq = getattr(args, "weight_quant", None)
    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_attention_heads=args.heads,
                    max_seq_len=args.max_seq,
                    weight_quant=None if wq in (None, "none") else wq)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    return model, params


def _build_replicas(args, model, params, clock, tracers=None):
    from apex_tpu.observability.slo import SLOMonitor, SLOTarget
    from apex_tpu.serving import PagedInferenceEngine, TickScheduler
    from apex_tpu.utils.profiling import ServingMetrics

    replicas = []
    for i in range(args.replicas):
        slo = SLOMonitor([SLOTarget("ttft", args.ttft_slo_s,
                                    objective=0.9)], clock=clock)
        metrics = ServingMetrics(clock, slo=slo)
        replicas.append(PagedInferenceEngine(
            model, params, max_slots=args.max_slots,
            block_size=args.block_size,
            chunked_prefill=args.chunked,
            scheduler=TickScheduler(token_budget=args.token_budget),
            metrics=metrics, max_queue=args.max_queue, clock=clock,
            tracer=tracers[i] if tracers else None))
    return replicas


def build_stack(args):
    """(router, replicas): paged engines behind an SLO-aware router."""
    from apex_tpu.serving import Router

    model, params = _build_model(args)
    replicas = _build_replicas(args, model, params, time.monotonic)
    router = Router(replicas, max_queue_depth=args.max_queue_depth,
                    burn_threshold=args.burn_threshold,
                    burn_window_s=args.burn_window_s)
    return router, replicas


def synthesize(args):
    """The workload: (arrival_time, Request) pairs, pre-generated so a
    run is reproducible from ``--seed`` alone."""
    from apex_tpu.inference import Request

    rng = np.random.RandomState(args.seed)
    prefixes = [list(rng.randint(1, args.vocab,
                                 args.shared_prefix_len).astype(int))
                for _ in range(args.num_prefixes)]
    work, t = [], 0.0
    for i in range(args.requests):
        t += float(rng.exponential(1.0 / args.rate))
        # bounded Pareto: heavy tail, but it must fit the cache row
        tail = min(int(rng.pareto(args.pareto_shape) * args.min_prompt)
                   + args.min_prompt, args.max_seq - args.max_new - 1)
        toks = list(rng.randint(1, args.vocab, tail).astype(int))
        if rng.rand() < args.shared_prefix_prob:
            toks = (prefixes[rng.randint(args.num_prefixes)]
                    + toks)[:args.max_seq - args.max_new - 1]
        work.append((0.0 if args.overload else t,
                     Request(i, toks, max_new_tokens=args.max_new)))
    return work


def _outcome_counts(responses, shed_client: int) -> dict:
    out: dict = {}
    for rep in responses.values():
        out[rep.finish_reason] = out.get(rep.finish_reason, 0) + 1
    if shed_client:
        out["shed_client"] = shed_client
    return out


def run_loadgen(args) -> dict:
    from apex_tpu.serving import RequestShed

    router, replicas = build_stack(args)
    work = synthesize(args)
    client_retries = int(getattr(args, "client_retries", 0))
    crng = np.random.RandomState(getattr(args, "seed", 0) + 1)
    placed: dict = {}                    # request_id -> replica index
    submit_t: dict = {}
    shed = 0
    retried = 0
    t0 = time.monotonic()
    # (arrival, tiebreak, request, retries_left) — the tiebreak keeps
    # bisect away from comparing Request objects
    pending = [(t, i, req, client_retries)
               for i, (t, req) in enumerate(work)]
    seq = len(pending)
    while pending or any(e._queue or e._active for e in replicas):
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            _, _, req, retries = pending.pop(0)
            submit_t.setdefault(req.request_id, time.monotonic())
            try:
                placed[req.request_id] = router.submit(req)
            except RequestShed as e:
                if retries > 0:
                    # honor the hint, jittered so backed-off clients
                    # return staggered instead of as a second burst
                    back = e.retry_after_s * (1.0 + 0.5 * crng.rand())
                    bisect.insort(pending,
                                  (now + back, seq, req, retries - 1))
                    seq += 1
                    retried += 1
                else:
                    shed += 1
        router.step()
    wall = time.monotonic() - t0

    done_t = time.monotonic()
    responses = {r.request_id: r for r in router.completed}
    e2e, tpots, tokens = [], [], 0
    for rid, rep in responses.items():
        # steady-state completions all land by the final step; the
        # residual after-loop skew is bounded by one engine tick
        e2e.append(done_t - submit_t[rid]
                   if rid in submit_t else 0.0)
        tokens += len(rep.tokens)
        eng = replicas[placed[rid]]
        ttft = eng.metrics.ttft.get(rid)
        if ttft is not None and len(rep.tokens) > 1:
            tpots.append((e2e[-1] - ttft) / (len(rep.tokens) - 1))
    ttfts = [t for e in replicas for t in e.metrics.ttft.values()]
    hit = lookup = 0
    for e in replicas:
        hit += e.pool.prefix_hit_tokens
        lookup += e.pool.prefix_lookup_tokens
    report = {
        "requests": args.requests,
        "served": len(responses),
        "shed": shed,
        "shed_fraction": shed / args.requests if args.requests else 0.0,
        "client_retries": retried,
        "outcomes": _outcome_counts(responses, shed),
        "wall_s": wall,
        "tokens": tokens,
        "throughput_tok_s": tokens / wall if wall else 0.0,
        "ttft_p50_s": _pct(ttfts, 50),
        "ttft_p90_s": _pct(ttfts, 90),
        "ttft_p99_s": _pct(ttfts, 99),
        "tpot_p50_s": _pct(tpots, 50),
        "tpot_p90_s": _pct(tpots, 90),
        "e2e_p50_s": _pct(e2e, 50),
        "e2e_p99_s": _pct(e2e, 99),
        "prefix_hit_rate": hit / lookup if lookup else 0.0,
        "replicas": [{"served": sum(1 for v in placed.values() if v == i),
                      "pool": e.pool.stats()}
                     for i, e in enumerate(replicas)],
    }
    return report


# -- chaos scenarios ---------------------------------------------------------


def _scenario_injector(args):
    from apex_tpu.serving import ServingFault, ServingFaultInjector

    s = args.scenario
    if s == "replica_kill":
        return ServingFaultInjector([ServingFault(
            args.kill_tick, args.kill_replica % args.replicas,
            "replica_crash", duration=args.kill_duration)])
    if s == "slow_replica":
        return ServingFaultInjector([ServingFault(
            args.slow_tick, 1 % args.replicas, "slow_replica",
            magnitude=args.slow_s, duration=args.slow_duration)])
    return None     # steady / diurnal / bursty shape the LOAD, not faults


def synthesize_scenario(args):
    """Virtual-time arrivals per scenario + the usual heavy-tail
    prompts; reproducible from ``--seed`` alone."""
    from apex_tpu.inference import Request

    rng = np.random.RandomState(args.seed)
    prefixes = [list(rng.randint(1, args.vocab,
                                 args.shared_prefix_len).astype(int))
                for _ in range(args.num_prefixes)]
    n = args.requests
    times = []
    if args.scenario == "bursty":
        t = 0.0
        while len(times) < n:
            times.extend([t] * min(args.burst_n, n - len(times)))
            t += args.burst_gap_s
    elif args.scenario in ("diurnal", "capacity_diurnal",
                           "autopilot_drift"):
        # thinning: candidate arrivals at the peak rate, accepted with
        # probability rate(t)/peak where rate(t) ~ sin^2 over --period-s
        t = 0.0
        while len(times) < n:
            t += float(rng.exponential(1.0 / args.rate))
            frac = 0.1 + 0.9 * float(
                np.sin(np.pi * t / args.period_s) ** 2)
            if rng.rand() < frac:
                times.append(t)
    else:
        t = 0.0
        for _ in range(n):
            t += float(rng.exponential(1.0 / args.rate))
            times.append(t)
    work = []
    for i, t in enumerate(times):
        tail = min(int(rng.pareto(args.pareto_shape) * args.min_prompt)
                   + args.min_prompt, args.max_seq - args.max_new - 1)
        toks = list(rng.randint(1, args.vocab, tail).astype(int))
        if rng.rand() < args.shared_prefix_prob:
            toks = (prefixes[rng.randint(args.num_prefixes)]
                    + toks)[:args.max_seq - args.max_new - 1]
        work.append((t, Request(i, toks, max_new_tokens=args.max_new,
                                seed=i)))
    return work


def build_fleet(args, clock):
    """(fleet, replicas, injector): the fault-tolerant stack on an
    injectable clock, fully traced — one Tracer per replica plus a
    router lane, so every scenario run can assert flow-chain
    continuity over the merged timeline, and a FlightRecorder so
    replica deaths / ladder escalations cut correlated snapshots."""
    from apex_tpu.observability import FlightRecorder, Tracer
    from apex_tpu.serving import DegradationLadder, FleetRouter

    model, params = _build_model(args)
    tracers = [Tracer(clock=clock, id_tag=f"r{i}")
               for i in range(args.replicas)]
    replicas = _build_replicas(args, model, params, clock,
                               tracers=tracers)
    injector = _scenario_injector(args)
    ladder = DegradationLadder(
        thresholds=(args.burn_threshold / 7.2, args.burn_threshold / 2.4,
                    args.burn_threshold),
        step_down_s=args.ladder_step_down_s)
    fleet = FleetRouter(
        replicas, injector=injector, clock=clock,
        max_queue_depth=args.max_queue_depth,
        burn_threshold=args.burn_threshold,
        burn_window_s=args.burn_window_s,
        retry_budget=args.retry_budget,
        hedge_after_s=args.hedge_after_s,
        ladder=ladder, seed=args.seed,
        tracer=Tracer(clock=clock, id_tag="router"),
        recorder=FlightRecorder(clock=clock))
    return fleet, replicas, injector


def fleet_collector(fleet, replicas):
    """A :class:`FleetCollector` over the stack's tracers (router lane
    first, then one per replica)."""
    from apex_tpu.observability import FleetCollector

    fc = FleetCollector()
    fc.add_replica("router", tracer=fleet.tracer)
    for i, e in enumerate(replicas):
        fc.add_replica(f"r{i}", tracer=e.trace.tracer)
    return fc


def run_scenario(args) -> dict:
    """Drive one chaos scenario on the virtual clock; returns the
    asserting-ready report (exactly-once ledger, SLO attainment,
    health/fault logs, recovery timeline)."""
    from apex_tpu.serving import RequestShed, VirtualClock

    clock = VirtualClock()
    fleet, replicas, injector = build_fleet(args, clock)
    work = synthesize_scenario(args)
    crng = np.random.RandomState(args.seed + 1)
    pending = [(t, i, req, int(args.client_retries))
               for i, (t, req) in enumerate(work)]
    seq = len(pending)
    submit_t: dict = {}
    finish_t: dict = {}
    submitted: set = set()
    shed_client: dict = {}               # request_id -> final shed reason
    ticks = 0
    seen = 0
    degraded_max = 0
    while True:
        now = clock()
        while pending and pending[0][0] <= now:
            _, _, req, retries = pending.pop(0)
            try:
                fleet.submit(req)
                submitted.add(req.request_id)
                submit_t.setdefault(req.request_id, now)
                shed_client.pop(req.request_id, None)
            except RequestShed as e:
                if retries > 0:
                    back = e.retry_after_s * (1.0 + 0.5 * crng.rand())
                    bisect.insort(pending,
                                  (now + back, seq, req, retries - 1))
                    seq += 1
                else:
                    shed_client[req.request_id] = e.reason.value
        busy = fleet.step()
        clock.advance(args.tick_s)
        ticks += 1
        if fleet.ladder is not None:
            degraded_max = max(degraded_max, fleet.ladder.level)
        done = fleet.completed
        while seen < len(done):
            finish_t[done[seen].request_id] = clock()
            seen += 1
        if not pending and not busy \
                and not any(e._queue or e._active for e in replicas):
            break
        if ticks >= args.max_ticks:
            break
    responses = {r.request_id: r for r in fleet.completed}
    dup_client = sum(1 for _ in fleet.completed) - len(responses)
    lost = sorted(submitted - set(responses))
    e2e_ok = [finish_t[rid] - submit_t[rid] for rid, rep in
              responses.items()
              if rep.finish_reason in ("eos", "length")
              and rid in finish_t and rid in submit_t]
    attainment = (sum(1 for v in e2e_ok if v <= args.e2e_slo_s)
                  / len(e2e_ok)) if e2e_ok else 0.0
    ttfts = [t for e in replicas for t in e.metrics.ttft.values()]
    tokens = sum(len(r.tokens) for r in responses.values())
    cont = fleet_collector(fleet, replicas).continuity()
    return {
        "scenario": args.scenario,
        "requests": args.requests,
        "submitted": len(submitted),
        "responses": len(responses),
        "lost": lost,
        "duplicated": dup_client,
        "engine_duplicates_suppressed": fleet.duplicate_responses,
        "shed_client": len(shed_client),
        "outcomes": _outcome_counts(responses, len(shed_client)),
        "fleet_pending": fleet.pending,
        "ticks": ticks,
        "virtual_s": clock(),
        "tokens": tokens,
        "e2e_served": len(e2e_ok),
        "e2e_p50_s": _pct(e2e_ok, 50),
        "e2e_p99_s": _pct(e2e_ok, 99),
        "slo_attainment": attainment,
        "ttft_p50_s": _pct(ttfts, 50),
        "retries": fleet.retries,
        "hedges": fleet.hedges,
        "migrations": fleet.migrations,
        "degraded_max_level": degraded_max,
        "health_log": list(fleet.health_log),
        "fault_log": list(injector.log) if injector is not None else [],
        "recovery": fleet.recovery_report(),
        "trace_continuity": {
            "chains": len(cont["chains"]),
            "complete": len(cont["complete"]),
            "broken": cont["broken"],
            "orphans": cont["orphans"],
            "migrated_chains": sorted(
                tid for tid, c in cont["chains"].items()
                if c["migrated"]),
        },
        "flight_snapshots": len(fleet.recorder.dumps),
    }


# -- disaggregated scenarios --------------------------------------------------


def build_disagg_fleet(args, clock):
    """(fleet, controller): a 2-pool DisaggregatedFleet (prefill pool of
    ``prefill_only`` chunked engines, decode pool of ordinary ones, same
    cache kind on both sides so handoffs install bitwise) under a
    :class:`PoolCapacityController` sizing the pools on TTFT-burn vs
    TPOT-burn.  Fully traced for flow-chain continuity assertions."""
    from apex_tpu.observability import FlightRecorder, Tracer
    from apex_tpu.observability.slo import SLOMonitor, SLOTarget
    from apex_tpu.resilience import PoolCapacityController
    from apex_tpu.serving import (DegradationLadder, DisaggregatedFleet,
                                  KvChannel, PagedInferenceEngine,
                                  TickScheduler)
    from apex_tpu.utils.profiling import ServingMetrics

    model, params = _build_model(args)
    kv_quant = None if args.kv_quant in (None, "none") else args.kv_quant

    def engine(prefill_only, tracer=None):
        slo = SLOMonitor(
            [SLOTarget("ttft", args.ttft_slo_s, objective=0.9),
             SLOTarget("token_latency", args.tpot_slo_s, objective=0.9)],
            clock=clock)
        return PagedInferenceEngine(
            model, params, max_slots=args.max_slots,
            block_size=args.block_size, chunked_prefill=True,
            prefill_only=prefill_only, kv_quant=kv_quant,
            scheduler=TickScheduler(token_budget=args.token_budget),
            metrics=ServingMetrics(clock, slo=slo),
            max_queue=args.max_queue, clock=clock, tracer=tracer)

    tracers = {f"p{i}": Tracer(clock=clock, id_tag=f"p{i}")
               for i in range(args.prefill_replicas)}
    tracers.update({f"d{i}": Tracer(clock=clock, id_tag=f"d{i}")
                    for i in range(args.decode_replicas)})
    prefill = [engine(True, tracers[f"p{i}"])
               for i in range(args.prefill_replicas)]
    decode = [engine(False, tracers[f"d{i}"])
              for i in range(args.decode_replicas)]
    ladder = DegradationLadder(
        thresholds=(args.burn_threshold / 7.2, args.burn_threshold / 2.4,
                    args.burn_threshold),
        step_down_s=args.ladder_step_down_s)
    fleet = DisaggregatedFleet(
        prefill, decode, clock=clock, channel=KvChannel(),
        ladder=ladder, seed=args.seed,
        recorder=FlightRecorder(clock=clock),
        tracer=Tracer(clock=clock, id_tag="router"),
        prefill_kw=dict(max_queue_depth=args.max_queue_depth,
                        burn_threshold=args.burn_threshold,
                        burn_window_s=args.burn_window_s,
                        retry_budget=args.retry_budget),
        decode_kw=dict(max_queue_depth=args.max_queue_depth,
                       burn_threshold=args.burn_threshold,
                       burn_window_s=args.burn_window_s,
                       retry_budget=args.retry_budget))
    def factory(pool):
        # a shifted-in replica traces like the original ones, or the
        # continuity gate would see its finishes vanish mid-chain
        tag = f"{pool[0]}x{len(tracers)}"
        tracers[tag] = Tracer(clock=clock, id_tag=tag)
        return engine(pool == "prefill", tracers[tag])

    controller = PoolCapacityController(
        {"prefill": fleet.prefill, "decode": fleet.decode}, factory,
        burn_high=args.burn_threshold, burn_low=1.0,
        burn_window_s=args.burn_window_s,
        confirm_ticks=3, cooldown_s=2.0, clock=clock)
    fleet._tracers = tracers            # for the continuity collector
    return fleet, controller


def synthesize_disagg(args):
    """(arrival, Request, tag) triples for the disagg scenarios.

    ``disagg_diurnal``: the first half of the workload is
    ``prefill_heavy`` (prompts ~4× the baseline, generations ~¼), the
    second half ``decode_heavy`` (short prompts, full-length
    generations) — the mid-day mix flip the pool controller reacts to.
    ``disagg_longctx_fair``: ``--tenants`` round-robin tenants; tenant
    0 submits near-context-limit prompts, the rest short interactive
    ones."""
    from apex_tpu.inference import Request

    rng = np.random.RandomState(args.seed)
    n = args.requests
    work, t = [], 0.0
    cap = args.max_seq - args.max_new - 1
    for i in range(n):
        t += float(rng.exponential(1.0 / args.rate))
        if args.scenario in _DIURNAL_MIX:
            heavy = i < n // 2
            tag = "prefill_heavy" if heavy else "decode_heavy"
            base = args.min_prompt * 4 if heavy else args.min_prompt
            new = max(2, args.max_new // 4) if heavy else args.max_new
            tail = min(int(rng.pareto(args.pareto_shape) * base) + base,
                       args.max_seq - new - 1)
        else:
            tenant = i % args.tenants
            tag = f"tenant{tenant}"
            new = args.max_new
            if tenant == 0:             # the long-context tenant
                tail = cap - int(rng.randint(0, max(1, cap // 8)))
                tail = min(tail, args.max_seq - new - 1)
            else:
                tail = min(int(rng.pareto(args.pareto_shape)
                               * args.min_prompt) + args.min_prompt,
                           args.max_seq - new - 1)
        toks = list(rng.randint(1, args.vocab, tail).astype(int))
        work.append((t, Request(i, toks, max_new_tokens=new, seed=i),
                     tag))
    return work


def run_disagg_scenario(args) -> dict:
    """Drive one disaggregated scenario on the virtual clock.  The
    report carries the exactly-once ledger, per-phase (or per-tenant)
    SLO attainment, the handoff ledger, the capacity audit, and a
    ``gates`` dict the CI legs assert every value of."""
    from apex_tpu.observability import FleetCollector
    from apex_tpu.serving import RequestShed, VirtualClock

    if args.scenario == "disagg_quant":
        # the fully-quantized serving arm: int8 decode weights x int8
        # KV blocks over the same mixed day as disagg_diurnal
        args.kv_quant = "int8"
        args.weight_quant = "int8"
    clock = VirtualClock()
    fleet, controller = build_disagg_fleet(args, clock)
    work = synthesize_disagg(args)
    tags = {req.request_id: tag for _, req, tag in work}
    mid_t = work[len(work) // 2][0]
    crng = np.random.RandomState(args.seed + 1)
    pending = [(t, i, req, int(args.client_retries))
               for i, (t, req, _) in enumerate(work)]
    seq = len(pending)
    submit_t: dict = {}
    finish_t: dict = {}
    submitted: set = set()
    shed_client: dict = {}
    ticks = seen = 0
    shift_requested = False
    while True:
        now = clock()
        if args.scenario in _DIURNAL_MIX and not shift_requested \
                and now >= mid_t:
            # the mid-day flip: decode-heavy afternoon needs the chip
            # more than the now-quiet prefill pool does
            controller.request_shift("to_decode")
            shift_requested = True
        while pending and pending[0][0] <= now:
            _, _, req, retries = pending.pop(0)
            try:
                fleet.submit(req)
                submitted.add(req.request_id)
                submit_t.setdefault(req.request_id, now)
                shed_client.pop(req.request_id, None)
            except RequestShed as e:
                if retries > 0:
                    back = e.retry_after_s * (1.0 + 0.5 * crng.rand())
                    bisect.insort(pending,
                                  (now + back, seq, req, retries - 1))
                    seq += 1
                else:
                    shed_client[req.request_id] = e.reason.value
        busy = fleet.step()
        controller.tick()
        clock.advance(args.tick_s)
        ticks += 1
        done = fleet.completed
        while seen < len(done):
            finish_t[done[seen].request_id] = clock()
            seen += 1
        if not pending and not busy and fleet.pending == 0 \
                and not controller.shifting:
            break
        if ticks >= args.max_ticks:
            break
    responses = {r.request_id: r for r in fleet.completed}
    lost = sorted(submitted - set(responses))
    per_phase: dict = {}
    for rid, rep in responses.items():
        if rep.finish_reason not in ("eos", "length") \
                or rid not in finish_t or rid not in submit_t:
            continue
        per_phase.setdefault(tags[rid], []).append(
            finish_t[rid] - submit_t[rid])
    attainment = {
        tag: sum(1 for v in xs if v <= args.e2e_slo_s) / len(xs)
        for tag, xs in sorted(per_phase.items())}
    fc = FleetCollector()
    fc.add_replica("router", tracer=fleet.prefill.tracer)
    for name, tr in fleet._tracers.items():
        fc.add_replica(name, tracer=tr)
    cont = fc.continuity()
    audit = controller.audit()
    gates = {
        "exactly_once": not lost and fleet.duplicate_responses == 0
        and fleet.pending == 0,
        "slo_attainment": bool(attainment)
        and all(a >= 0.9 for a in attainment.values()),
        "capacity_audit_clean": audit == [],
        "no_broken_chains": not cont["broken"],
    }
    return {
        "scenario": args.scenario,
        "requests": args.requests,
        "submitted": len(submitted),
        "responses": len(responses),
        "lost": lost,
        "duplicated": fleet.duplicate_responses,
        "shed_client": len(shed_client),
        "outcomes": _outcome_counts(responses, len(shed_client)),
        "fleet_pending": fleet.pending,
        "ticks": ticks,
        "virtual_s": clock(),
        "tokens": sum(len(r.tokens) for r in responses.values()),
        "slo_attainment": attainment,
        "handoffs": fleet.handoffs,
        "fallbacks": fleet.fallbacks,
        "handoff_bytes": fleet.channel.handoff_bytes,
        "weight_bytes_per_replica":
            fleet.decode.replicas[0].weight_bytes,
        "pool_split": controller.split,
        "pool_shifts": controller.stats["shifts"],
        "capacity_audit": audit,
        "trace_continuity": {
            "chains": len(cont["chains"]),
            "complete": len(cont["complete"]),
            "broken": cont["broken"],
            "orphans": cont["orphans"],
        },
        "gates": gates,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=16.0,
                    help="Poisson arrival rate, requests/s")
    ap.add_argument("--overload", action="store_true",
                    help="submit everything as one burst (forces "
                    "deterministic shedding)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--max-queue-depth", type=int, default=8,
                    help="router admission bound per replica")
    ap.add_argument("--burn-threshold", type=float, default=14.4)
    ap.add_argument("--burn-window-s", type=float, default=60.0)
    ap.add_argument("--ttft-slo-s", type=float, default=0.5)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--chunked", action="store_true",
                    help="chunked prefill via the tick scheduler")
    ap.add_argument("--token-budget", type=int, default=64)
    ap.add_argument("--client-retries", type=int, default=3,
                    help="client resubmits a shed request up to N times, "
                    "honoring its retry_after_s with jitter (0: drop)")
    # chaos scenarios (FleetRouter on a virtual clock)
    ap.add_argument("--scenario", choices=SCENARIOS, default=None,
                    help="run a fleet chaos scenario instead of the "
                    "wall-clock loadgen")
    ap.add_argument("--tick-s", type=float, default=0.02,
                    help="virtual seconds per fleet tick")
    ap.add_argument("--e2e-slo-s", type=float, default=3.0,
                    help="end-to-end SLO asserted by the scenarios "
                    "(virtual seconds)")
    ap.add_argument("--max-ticks", type=int, default=5000)
    ap.add_argument("--retry-budget", type=int, default=4)
    ap.add_argument("--hedge-after-s", type=float, default=None,
                    help="hedge a first-token-less request after this "
                    "many (virtual) seconds; default: no hedging")
    ap.add_argument("--ladder-step-down-s", type=float, default=0.5)
    ap.add_argument("--kill-tick", type=int, default=6)
    ap.add_argument("--kill-replica", type=int, default=1)
    ap.add_argument("--kill-duration", type=int, default=10 ** 6,
                    help="crash length in ticks (default: permanent)")
    ap.add_argument("--slow-tick", type=int, default=4)
    ap.add_argument("--slow-s", type=float, default=0.1,
                    help="extra virtual seconds per tick on the slow "
                    "replica")
    ap.add_argument("--slow-duration", type=int, default=40)
    ap.add_argument("--burst-n", type=int, default=8)
    ap.add_argument("--burst-gap-s", type=float, default=0.5)
    ap.add_argument("--period-s", type=float, default=4.0,
                    help="diurnal modulation period (virtual seconds)")
    # disaggregated scenarios
    ap.add_argument("--prefill-replicas", type=int, default=2)
    ap.add_argument("--decode-replicas", type=int, default=2)
    ap.add_argument("--weight-quant", choices=("none", "int8"),
                    default="none",
                    help="int8 decode weights (GPTConfig.weight_quant); "
                         "disagg_quant forces int8")
    ap.add_argument("--kv-quant", choices=("none", "int8"),
                    default="none",
                    help="decode+prefill pool KV cache storage")
    ap.add_argument("--tpot-slo-s", type=float, default=0.5)
    ap.add_argument("--tenants", type=int, default=3,
                    help="round-robin tenants for disagg_longctx_fair "
                    "(tenant 0 is the long-context one)")
    # workload shape
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-prompt", type=int, default=8)
    ap.add_argument("--pareto-shape", type=float, default=2.5)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--shared-prefix-prob", type=float, default=0.5)
    ap.add_argument("--shared-prefix-len", type=int, default=16)
    ap.add_argument("--num-prefixes", type=int, default=2)
    # model shape (small defaults: the loadgen measures the SERVING
    # layer; model quality is irrelevant to scheduling behavior)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--max-seq", type=int, default=128)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.scenario == "capacity_diurnal":
        # the capacity sim owns a training side too — delegate to the
        # day-in-the-life driver, which reuses this module's fleet and
        # workload helpers and adds the capacity gates
        import day_in_life
        report = day_in_life.run_day(day_in_life.day_args(
            seed=args.seed, requests=args.requests, json_out=args.json))
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            day_in_life.print_report(report)
        return 0 if all(report["gates"].values()) else 1

    if args.scenario == "autopilot_drift":
        # ditto: the autopilot sim owns a training side — delegate to
        # the day-in-the-life driver's autopilot day
        import day_in_life
        report = day_in_life.run_autopilot_day(day_in_life.autopilot_args(
            seed=args.seed, requests=args.requests, json_out=args.json))
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            day_in_life.print_autopilot_report(report)
        return 0 if all(report["gates"].values()) else 1

    if args.scenario in DISAGG_SCENARIOS:
        report = run_disagg_scenario(args)
        if args.json:
            print(json.dumps(report, indent=2))
        else:
            print(f"scenario {report['scenario']}: "
                  f"{report['responses']}/{report['submitted']} answered "
                  f"(lost {len(report['lost'])}, "
                  f"dup {report['duplicated']}) in {report['ticks']} "
                  f"ticks / {report['virtual_s']:.2f}s virtual")
            print(f"  outcomes {report['outcomes']}")
            print(f"  handoffs {report['handoffs']}  "
                  f"fallbacks {report['fallbacks']}  "
                  f"bytes {report['handoff_bytes']}")
            print(f"  pool split {report['pool_split']}  "
                  f"shifts {report['pool_shifts']}  "
                  f"audit {report['capacity_audit']}")
            for tag, a in report["slo_attainment"].items():
                print(f"  slo[{tag}] {a:.0%}")
            print(f"  gates {report['gates']}")
        return 0 if all(report["gates"].values()) else 1

    if args.scenario is not None:
        report = run_scenario(args)
        if args.json:
            print(json.dumps(report, indent=2))
            return 0
        print(f"scenario {report['scenario']}: "
              f"{report['responses']}/{report['submitted']} answered "
              f"(lost {len(report['lost'])}, dup {report['duplicated']}, "
              f"client-shed {report['shed_client']}) "
              f"in {report['ticks']} ticks / {report['virtual_s']:.2f}s "
              "virtual")
        print(f"  outcomes {report['outcomes']}")
        print(f"  slo attainment {report['slo_attainment']:.0%} "
              f"(e2e p50 {report['e2e_p50_s'] * 1e3:.0f} ms, "
              f"p99 {report['e2e_p99_s'] * 1e3:.0f} ms vs "
              f"{args.e2e_slo_s:.1f}s)")
        print(f"  retries {report['retries']}  hedges {report['hedges']}  "
              f"migrations {report['migrations']}  "
              f"degraded<= {report['degraded_max_level']}")
        if report["health_log"]:
            print(f"  health transitions {report['health_log']}")
        tc = report["trace_continuity"]
        print(f"  trace continuity: {tc['complete']}/{tc['chains']} "
              f"chains complete, {len(tc['broken'])} broken, "
              f"{len(tc['orphans'])} orphans, "
              f"{len(tc['migrated_chains'])} migrated; "
              f"{report['flight_snapshots']} flight snapshot(s)")
        rec = report["recovery"]
        if rec["first_dead"]:
            print(f"  recovery: dead@{rec['first_dead']}  "
                  f"migrated@{rec['first_migration']}  "
                  f"resumed@{rec['first_resumed_token']}")
        return 0

    report = run_loadgen(args)
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    print(f"served {report['served']}/{report['requests']} "
          f"(shed {report['shed']}, "
          f"{report['shed_fraction']:.0%}) in {report['wall_s']:.2f}s "
          f"-> {report['throughput_tok_s']:.0f} tok/s")
    print(f"  outcomes {report['outcomes']}  "
          f"client retries {report['client_retries']}")
    print(f"  ttft  p50 {report['ttft_p50_s'] * 1e3:8.1f} ms   "
          f"p90 {report['ttft_p90_s'] * 1e3:8.1f} ms   "
          f"p99 {report['ttft_p99_s'] * 1e3:8.1f} ms")
    print(f"  tpot  p50 {report['tpot_p50_s'] * 1e3:8.1f} ms   "
          f"p90 {report['tpot_p90_s'] * 1e3:8.1f} ms")
    print(f"  e2e   p50 {report['e2e_p50_s'] * 1e3:8.1f} ms   "
          f"p99 {report['e2e_p99_s'] * 1e3:8.1f} ms")
    print(f"  prefix-cache hit rate {report['prefix_hit_rate']:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
