#!/usr/bin/env python
"""Open-loop load generator for the apex_tpu serving stack.

Synthesizes realistic serving traffic against a multi-replica
:class:`~apex_tpu.serving.Router` of paged engines and reports the
numbers an operator actually tunes against:

* **arrivals**: open-loop Poisson process at ``--rate`` requests/s —
  open-loop because closed-loop (wait-for-response) generators hide
  overload by self-throttling, exactly the regime worth measuring;
* **prompt lengths**: heavy-tail Pareto (bounded) — serving traffic is
  never Gaussian, and the tail prompts are what chunked prefill exists
  for;
* **prefix sharing**: each request draws a shared system prompt with
  probability ``--shared-prefix-prob`` (one of ``--num-prefixes``
  variants), exercising the radix-trie block reuse;
* **SLO pressure**: every replica gets a TTFT SLOTarget; the router's
  burn-rate admission and queue-depth shedding run live, and the
  report separates served from shed traffic.

Reported: TTFT p50/p90/p99 (engine-measured, submit → first token),
TPOT (per-token decode latency after the first), end-to-end latency
percentiles (host-tracked, submit → completion), throughput
(tokens/s over the drive wall time), shed fraction, and the pool's
prefix-cache hit rate.

``--overload`` submits the whole workload as an instantaneous burst
(rate → ∞), deterministically driving queue depths past the admission
bound so the shedding path is exercised regardless of host speed — the
mode the dryrun gate runs.

Usage::

    python tools/loadgen.py --requests 64 --rate 32 --replicas 2
    python tools/loadgen.py --overload --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax            # noqa: E402
import numpy as np    # noqa: E402


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs, np.float64), q)) if xs else 0.0


def build_stack(args):
    """(router, replicas): paged engines behind an SLO-aware router."""
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.observability.slo import SLOMonitor, SLOTarget
    from apex_tpu.serving import PagedInferenceEngine, Router, TickScheduler
    from apex_tpu.utils.profiling import ServingMetrics

    cfg = GPTConfig(vocab_size=args.vocab, hidden_size=args.hidden,
                    num_layers=args.layers,
                    num_attention_heads=args.heads,
                    max_seq_len=args.max_seq)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    replicas = []
    for _ in range(args.replicas):
        slo = SLOMonitor([SLOTarget("ttft", args.ttft_slo_s,
                                    objective=0.9)])
        metrics = ServingMetrics(time.monotonic, slo=slo)
        replicas.append(PagedInferenceEngine(
            model, params, max_slots=args.max_slots,
            block_size=args.block_size,
            chunked_prefill=args.chunked,
            scheduler=TickScheduler(token_budget=args.token_budget),
            metrics=metrics, max_queue=args.max_queue))
    router = Router(replicas, max_queue_depth=args.max_queue_depth,
                    burn_threshold=args.burn_threshold,
                    burn_window_s=args.burn_window_s)
    return router, replicas


def synthesize(args):
    """The workload: (arrival_time, Request) pairs, pre-generated so a
    run is reproducible from ``--seed`` alone."""
    from apex_tpu.inference import Request

    rng = np.random.RandomState(args.seed)
    prefixes = [list(rng.randint(1, args.vocab,
                                 args.shared_prefix_len).astype(int))
                for _ in range(args.num_prefixes)]
    work, t = [], 0.0
    for i in range(args.requests):
        t += float(rng.exponential(1.0 / args.rate))
        # bounded Pareto: heavy tail, but it must fit the cache row
        tail = min(int(rng.pareto(args.pareto_shape) * args.min_prompt)
                   + args.min_prompt, args.max_seq - args.max_new - 1)
        toks = list(rng.randint(1, args.vocab, tail).astype(int))
        if rng.rand() < args.shared_prefix_prob:
            toks = (prefixes[rng.randint(args.num_prefixes)]
                    + toks)[:args.max_seq - args.max_new - 1]
        work.append((0.0 if args.overload else t,
                     Request(i, toks, max_new_tokens=args.max_new)))
    return work


def run_loadgen(args) -> dict:
    from apex_tpu.serving import RequestShed

    router, replicas = build_stack(args)
    work = synthesize(args)
    placed: dict = {}                    # request_id -> replica index
    submit_t: dict = {}
    shed = 0
    t0 = time.monotonic()
    pending = list(work)
    while pending or any(e._queue or e._active for e in replicas):
        now = time.monotonic() - t0
        while pending and pending[0][0] <= now:
            _, req = pending.pop(0)
            submit_t[req.request_id] = time.monotonic()
            try:
                placed[req.request_id] = router.submit(req)
            except RequestShed:
                shed += 1
        router.step()
    wall = time.monotonic() - t0

    done_t = time.monotonic()
    responses = {r.request_id: r for r in router.completed}
    e2e, tpots, tokens = [], [], 0
    for rid, rep in responses.items():
        # steady-state completions all land by the final step; the
        # residual after-loop skew is bounded by one engine tick
        e2e.append(done_t - submit_t[rid]
                   if rid in submit_t else 0.0)
        tokens += len(rep.tokens)
        eng = replicas[placed[rid]]
        ttft = eng.metrics.ttft.get(rid)
        if ttft is not None and len(rep.tokens) > 1:
            tpots.append((e2e[-1] - ttft) / (len(rep.tokens) - 1))
    ttfts = [t for e in replicas for t in e.metrics.ttft.values()]
    hit = lookup = 0
    for e in replicas:
        hit += e.pool.prefix_hit_tokens
        lookup += e.pool.prefix_lookup_tokens
    report = {
        "requests": args.requests,
        "served": len(responses),
        "shed": shed,
        "shed_fraction": shed / args.requests if args.requests else 0.0,
        "wall_s": wall,
        "tokens": tokens,
        "throughput_tok_s": tokens / wall if wall else 0.0,
        "ttft_p50_s": _pct(ttfts, 50),
        "ttft_p90_s": _pct(ttfts, 90),
        "ttft_p99_s": _pct(ttfts, 99),
        "tpot_p50_s": _pct(tpots, 50),
        "tpot_p90_s": _pct(tpots, 90),
        "e2e_p50_s": _pct(e2e, 50),
        "e2e_p99_s": _pct(e2e, 99),
        "prefix_hit_rate": hit / lookup if lookup else 0.0,
        "replicas": [{"served": sum(1 for v in placed.values() if v == i),
                      "pool": e.pool.stats()}
                     for i, e in enumerate(replicas)],
    }
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=16.0,
                    help="Poisson arrival rate, requests/s")
    ap.add_argument("--overload", action="store_true",
                    help="submit everything as one burst (forces "
                    "deterministic shedding)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--max-queue-depth", type=int, default=8,
                    help="router admission bound per replica")
    ap.add_argument("--burn-threshold", type=float, default=14.4)
    ap.add_argument("--burn-window-s", type=float, default=60.0)
    ap.add_argument("--ttft-slo-s", type=float, default=0.5)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--chunked", action="store_true",
                    help="chunked prefill via the tick scheduler")
    ap.add_argument("--token-budget", type=int, default=64)
    # workload shape
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--min-prompt", type=int, default=8)
    ap.add_argument("--pareto-shape", type=float, default=2.5)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--shared-prefix-prob", type=float, default=0.5)
    ap.add_argument("--shared-prefix-len", type=int, default=16)
    ap.add_argument("--num-prefixes", type=int, default=2)
    # model shape (small defaults: the loadgen measures the SERVING
    # layer; model quality is irrelevant to scheduling behavior)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--max-seq", type=int, default=128)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    report = run_loadgen(args)
    if args.json:
        print(json.dumps(report, indent=2))
        return 0
    print(f"served {report['served']}/{report['requests']} "
          f"(shed {report['shed']}, "
          f"{report['shed_fraction']:.0%}) in {report['wall_s']:.2f}s "
          f"-> {report['throughput_tok_s']:.0f} tok/s")
    print(f"  ttft  p50 {report['ttft_p50_s'] * 1e3:8.1f} ms   "
          f"p90 {report['ttft_p90_s'] * 1e3:8.1f} ms   "
          f"p99 {report['ttft_p99_s'] * 1e3:8.1f} ms")
    print(f"  tpot  p50 {report['tpot_p50_s'] * 1e3:8.1f} ms   "
          f"p90 {report['tpot_p90_s'] * 1e3:8.1f} ms")
    print(f"  e2e   p50 {report['e2e_p50_s'] * 1e3:8.1f} ms   "
          f"p99 {report['e2e_p99_s'] * 1e3:8.1f} ms")
    print(f"  prefix-cache hit rate {report['prefix_hit_rate']:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
