#!/usr/bin/env python
"""Step anatomy CLI: where did every second of an MPMD step go?

Feeds a Chrome trace (the ``MpmdPipeline`` ``trace=True`` /
``measure_ops=True`` events, saved via ``Tracer.save`` or a
``FleetCollector`` merge) through
:mod:`apex_tpu.observability.anatomy`:

* reconstruct the measured per-stage, per-op schedule;
* attribute each stage's window to compute / exposed-ici /
  exposed-dcn / pipeline-bubble / host-gap (sums to the makespan
  exactly);
* with ``--diff-simulated``, align it against ``simulate()``'s
  predicted schedule — per-op latency ratios, mis-ordered ops,
  unpredicted bubbles, one drift score.  The prediction's op costs
  default to the MEASURED medians (so the diff isolates structure
  from scale); override with ``--t-fwd``/``--t-bwd``/``--link-s``.

Usage:
    python tools/step_anatomy.py --trace step.trace.json
    python tools/step_anatomy.py --trace step.trace.json --json
    python tools/step_anatomy.py --trace step.trace.json \\
        --plan ckpt_dir/MPMD_PLAN.json --diff-simulated \\
        --out annotated.trace.json

``--out`` writes the original events back out with per-stage
attribution counter lanes merged in — one Perfetto file showing the
ops AND why each gap exists.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _median(xs):
    ss = sorted(xs)
    n = len(ss)
    if n == 0:
        return 0.0
    mid = n // 2
    return ss[mid] if n % 2 else 0.5 * (ss[mid - 1] + ss[mid])


def load_trace(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if isinstance(obj, dict):
        return obj.get("traceEvents", [])
    if isinstance(obj, list):
        return obj
    raise ValueError(f"{path}: expected a trace-event list or a "
                     "{'traceEvents': [...]} object")


def predicted_from_measured(tl, *, schedule=None, t_fwd=None,
                            t_bwd=None, link_s=None):
    """A ``simulate()`` run of the plan's schedule priced from the
    measured timeline: per-kind median op durations, per-edge median
    transfer times (async sends — the MPMD execution model).  The
    resulting diff is pure STRUCTURE: a uniformly slow machine diffs
    clean, a schedule the model can't explain does not."""
    from apex_tpu.mpmd.schedule import SCHEDULES, simulate

    name = schedule or tl.schedule or "1f1b"
    if name not in SCHEDULES:
        raise ValueError(f"unknown schedule {name!r}; "
                         f"one of {sorted(SCHEDULES)}")
    order = SCHEDULES[name](tl.n_stages, tl.n_microbatches)
    durs = {"fwd": [], "bwd": []}
    for o in tl.ops:
        durs[str(o["kind"])].append(float(o["end"]) - float(o["start"]))
    tf = float(t_fwd) if t_fwd is not None else (
        _median(durs["fwd"]) or _median(durs["bwd"]) or 1e-6)
    tb = float(t_bwd) if t_bwd is not None else (
        _median(durs["bwd"]) or tf)
    link_seconds, link_classes = {}, {}
    by_edge = {}
    for x in tl.xfers:
        if int(x["mb"]) < 0:
            continue
        e = min(int(x["src"]), int(x["dst"]))
        by_edge.setdefault(e, []).append(
            float(x["end"]) - float(x["start"]))
        link_classes[e] = str(x["link_class"])
    for e, ts in by_edge.items():
        link_seconds[e] = float(link_s) if link_s is not None \
            else _median(ts)
    sim = simulate(order, tl.n_stages, tl.n_microbatches,
                   t_fwd=tf, t_bwd=tb, link_seconds=link_seconds,
                   link_classes=link_classes or None,
                   blocking_sends=False)
    sim["priced_with"] = {"schedule": name, "t_fwd": tf, "t_bwd": tb,
                          "link_seconds": {str(k): v for k, v
                                           in link_seconds.items()}}
    return sim


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", required=True,
                    help="Chrome trace JSON with mpmd_op/mpmd_xfer "
                         "events (MpmdPipeline trace=True)")
    ap.add_argument("--step", type=int, default=None,
                    help="step to reconstruct (default: newest in "
                         "the trace)")
    ap.add_argument("--plan", default=None,
                    help="MPMD_PLAN.json for stage-count cross-check "
                         "and the schedule name when the trace lacks "
                         "its mpmd_schedule marker")
    ap.add_argument("--diff-simulated", action="store_true",
                    help="also diff measured vs the simulated "
                         "schedule (priced from measured medians)")
    ap.add_argument("--t-fwd", type=float, default=None,
                    help="override predicted per-op fwd seconds")
    ap.add_argument("--t-bwd", type=float, default=None,
                    help="override predicted per-op bwd seconds")
    ap.add_argument("--link-s", type=float, default=None,
                    help="override predicted per-edge link seconds")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON (schema: "
                         "{schedule, attribution, diff})")
    ap.add_argument("--table", action="store_true",
                    help="emit text tables (the default)")
    ap.add_argument("--out", default=None,
                    help="write the input events + attribution "
                         "counter lanes as one merged Perfetto trace")
    args = ap.parse_args(argv)

    from apex_tpu.observability.anatomy import (
        attribute, attribution_counter_events, diff_timelines,
        reconstruct, render_attribution_table, render_diff)

    events = load_trace(args.trace)
    tl = reconstruct(events, step=args.step)

    schedule = tl.schedule
    if args.plan:
        with open(args.plan, encoding="utf-8") as f:
            stamp = json.load(f)
        n_stages = int(stamp.get("n_stages", tl.n_stages))
        if n_stages != tl.n_stages:
            raise SystemExit(
                f"plan stamp says {n_stages} stages but the trace "
                f"reconstructs {tl.n_stages} — wrong trace/plan pair")
        schedule = schedule or stamp.get("plan", {}).get("schedule")

    attr = attribute(tl)
    diff = None
    sim = None
    if args.diff_simulated:
        sim = predicted_from_measured(
            tl, schedule=schedule, t_fwd=args.t_fwd, t_bwd=args.t_bwd,
            link_s=args.link_s)
        # the engine folds the last stage's fwd into its joint bwd
        # program exactly when no last-stage fwd op was traced
        folded = not any(int(o["stage"]) == tl.n_stages - 1
                         and str(o["kind"]) == "fwd" for o in tl.ops)
        diff = diff_timelines(tl, sim, fold_last_fwd=folded)

    if args.out:
        merged = list(events) + attribution_counter_events(attr)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": merged,
                       "displayTimeUnit": "ms"}, f)

    if args.json:
        report = {
            "schedule": {
                "name": schedule,
                "step": tl.step,
                "n_stages": tl.n_stages,
                "n_microbatches": tl.n_microbatches,
                "n_ops": len(tl.ops),
                "makespan_s": tl.makespan,
                "busy_s": tl.busy,
            },
            "attribution": {
                "makespan": attr["makespan"],
                "totals": attr["totals"],
                "fractions": attr["fractions"],
                "per_stage": [
                    {k: v for k, v in st.items() if k != "segments"}
                    for st in attr["per_stage"]],
            },
            "diff": diff,
        }
        if sim is not None:
            report["predicted"] = sim["priced_with"]
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        print(f"step {tl.step}: {tl.n_stages} stages x "
              f"{tl.n_microbatches} microbatches "
              f"({len(tl.ops)} measured ops, "
              f"schedule {schedule or 'unknown'})")
        print(render_attribution_table(attr))
        if diff is not None:
            print()
            print(render_diff(diff))
    return 0


if __name__ == "__main__":
    sys.exit(main())
