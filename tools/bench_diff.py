#!/usr/bin/env python
"""Bench-trajectory regression gate: diff bench.py outputs across rounds.

The repo accumulates one committed ``BENCH_rNN.json`` per round — the
bench trajectory — but until now nothing READ that trajectory; a leg
that quietly lost 20% would sit in the diff of two JSON blobs nobody
rendered.  This tool is the automated reader:

* extracts the per-leg metric dicts from a bench artifact — the
  ``parsed`` field when the round recorded one, else the last
  ``{"metric": ...}`` JSON line in the captured ``tail``, else (the
  tail is a byte-truncated suffix, so the line may be headless) a
  balanced-brace scan that recovers every complete per-leg dict;
* pairs the numeric series leg-by-leg between the two rounds,
  classifies each key's direction (``mfu`` / ``*_speedup`` /
  ``tokens_per_s`` higher-better; ``*_s`` / ``*overhead*`` / latency
  percentiles lower-better; unknown keys are reported, never flagged);
* flags relative regressions beyond ``--threshold`` (default 10%);
* under ``--strict``, a regressed leg whose rounds BOTH have an
  ``X.anatomy.json`` attribution sidecar (written by ``bench.py
  --legs anatomy`` / ``tools/step_anatomy.py``) also gets its
  component-level attribution delta printed — "ffn compute +12%, dcn
  exposed flat" instead of a bare slower-step number.

Usage:
    python tools/bench_diff.py                  # two newest committed rounds
    python tools/bench_diff.py current.json     # current output vs newest
    python tools/bench_diff.py --threshold 0.2 --json
    python tools/bench_diff.py --strict         # exit 1 on regression

``__graft_entry__`` runs this as a NON-fatal report step after the CI
legs — the gate informs; the tier-1 tests decide.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# direction classification by key content; HIGHER is matched first so
# "tokens_per_s" lands as higher-better despite its "_s" suffix
HIGHER_BETTER = ("speedup", "mfu", "tokens_per_s", "tok_s", "throughput",
                 "attainment", "goodput", "acceptance", "accepted",
                 "hit_rate", "flops", "fraction")
LOWER_BETTER = ("overhead", "bubble", "ttft", "tpot", "latency",
                "_us", "_s", "seconds", "bytes")


def direction(key: str) -> int:
    """+1 higher-better, -1 lower-better, 0 unknown."""
    k = key.lower()
    # ``*_advisory`` keys are informational (e.g. the off-TPU fused-FFN
    # "speedup" where both arms run the same reference): never a
    # regression signal, whatever substring they carry
    if k.endswith("_advisory"):
        return 0
    for pat in HIGHER_BETTER:
        if pat in k:
            return 1
    for pat in LOWER_BETTER:
        if pat in k:
            return -1
    return 0


def committed_rounds():
    """Committed bench artifacts, oldest -> newest (by round number;
    ``*_local`` scratch files are skipped)."""
    out = []
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", os.path.basename(p))
        if m:
            out.append((int(m.group(1)), p))
    return [p for _, p in sorted(out)]


def _scan_legs(text: str) -> dict:
    """Recover complete ``"name": {...}`` dicts with numeric leaves
    from (possibly head-truncated) bench output text."""
    legs = {}
    for m in re.finditer(r'"([A-Za-z0-9_]+)":\s*\{', text):
        start = m.end() - 1
        depth = 0
        for i in range(start, len(text)):
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
                if depth == 0:
                    try:
                        obj = json.loads(text[start:i + 1])
                    except ValueError:
                        break
                    if isinstance(obj, dict) and any(
                            isinstance(v, (int, float))
                            and not isinstance(v, bool)
                            for v in obj.values()):
                        legs.setdefault(m.group(1), obj)
                    break
        if depth > 0:               # unterminated: tail ends mid-dict
            break
    legs.pop("extra", None)         # the container, not a leg
    return legs


def _record_legs(rec: dict) -> dict:
    legs = {k: v for k, v in rec.get("extra", {}).items()
            if isinstance(v, dict)}
    if "value" in rec and isinstance(rec.get("value"), (int, float)):
        legs["headline"] = {rec.get("metric", "value"): rec["value"]}
    return legs


def extract_legs(path: str) -> dict:
    """Per-leg numeric dicts from a bench artifact: a round file
    (``parsed``/``tail``), a raw bench stdout capture, or a bare bench
    JSON line."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict):
        if isinstance(obj.get("parsed"), dict) and "metric" in obj["parsed"]:
            return _record_legs(obj["parsed"])
        if "metric" in obj:
            return _record_legs(obj)
        text = obj.get("tail", "") or text
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            return _record_legs(rec)
    return _scan_legs(text)


def _flatten(d: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, f"{key}."))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def diff_legs(old: dict, new: dict, threshold: float = 0.1,
              noise_floor: float = 1e-4) -> dict:
    """Compare leg-by-leg; returns ``{"rows": [...], "regressions":
    [...], "legs_compared": n, "legs_only_old": [...],
    "legs_only_new": [...]}``.

    ``noise_floor`` is the smallest ABSOLUTE change that can flag: the
    bench rounds timings to ~1e-5, so a 5e-05 -> 6e-05 micro-timing is
    one ULP of the recorded value — 20% relative, zero information.
    Sub-floor moves still appear in ``rows``, they just never gate."""
    rows, regressions = [], []
    shared = sorted(set(old) & set(new))
    for leg in shared:
        fo, fn = _flatten(old[leg]), _flatten(new[leg])
        for key in sorted(set(fo) & set(fn)):
            vo, vn = fo[key], fn[key]
            d = direction(key)
            if abs(vo) < 1e-12:
                continue
            rel = (vn - vo) / abs(vo)
            regressed = ((d == 1 and rel < -threshold)
                         or (d == -1 and rel > threshold)) \
                and abs(vn - vo) >= noise_floor
            row = {"leg": leg, "key": key, "old": vo, "new": vn,
                   "rel_change": rel,
                   "direction": {1: "higher_better", -1: "lower_better",
                                 0: "unknown"}[d],
                   "regressed": bool(regressed)}
            rows.append(row)
            if regressed:
                regressions.append(row)
    return {"rows": rows, "regressions": regressions,
            "legs_compared": len(shared),
            "legs_only_old": sorted(set(old) - set(new)),
            "legs_only_new": sorted(set(new) - set(old))}


def anatomy_sidecar(path: str) -> dict:
    """The attribution sidecar next to a bench artifact —
    ``X.anatomy.json`` for ``X.json``, holding ``{leg: {category:
    seconds}}`` (what ``bench.py --legs anatomy`` and
    ``tools/step_anatomy.py --json`` record).  Missing or malformed
    sidecars return ``{}``: attribution deltas are best-effort
    context, never a gate of their own."""
    side = os.path.splitext(path)[0] + ".anatomy.json"
    try:
        with open(side, encoding="utf-8") as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return {}
    return obj if isinstance(obj, dict) else {}


def attribution_delta(regressions, old_path: str,
                      new_path: str) -> list:
    """Component-level rows ("ffn compute +12%, dcn exposed flat")
    for each regressed leg both rounds have attribution for."""
    old_a, new_a = anatomy_sidecar(old_path), anatomy_sidecar(new_path)
    rows = []
    for leg in sorted({r["leg"] for r in regressions}):
        o, n = old_a.get(leg), new_a.get(leg)
        if not isinstance(o, dict) or not isinstance(n, dict):
            continue
        fo, fn = _flatten(o), _flatten(n)
        for cat in sorted(set(fo) & set(fn)):
            rows.append({"leg": leg, "category": cat,
                         "old": fo[cat], "new": fn[cat],
                         "delta": fn[cat] - fo[cat]})
    return rows


def render_attribution(rows: list, out=sys.stdout) -> None:
    leg = None
    for r in rows:
        if r["leg"] != leg:
            leg = r["leg"]
            out.write(f"attribution delta for regressed leg "
                      f"{leg}:\n")
        rel = (f" ({(r['new'] - r['old']) / abs(r['old']):+.1%})"
               if abs(r["old"]) > 1e-12 else "")
        out.write(f"  {r['category']}: {r['old']:.6g} -> "
                  f"{r['new']:.6g}{rel}\n")


def render(result: dict, old_path: str, new_path: str,
           threshold: float, out=sys.stdout) -> None:
    out.write(f"bench diff: {os.path.basename(old_path)} -> "
              f"{os.path.basename(new_path)} "
              f"(threshold {threshold:.0%})\n")
    out.write(f"legs compared: {result['legs_compared']}")
    if result["legs_only_old"]:
        out.write(f"  dropped: {','.join(result['legs_only_old'])}")
    if result["legs_only_new"]:
        out.write(f"  new: {','.join(result['legs_only_new'])}")
    out.write("\n")
    regs = result["regressions"]
    if not regs:
        out.write("no per-leg regressions beyond threshold\n")
    for r in regs:
        out.write(f"REGRESSION {r['leg']}.{r['key']}: "
                  f"{r['old']:.6g} -> {r['new']:.6g} "
                  f"({r['rel_change']:+.1%}, {r['direction']})\n")
    # the biggest movers either way, for trend-watching
    movers = sorted((r for r in result["rows"]
                     if r["direction"] != "unknown"),
                    key=lambda r: -abs(r["rel_change"]))[:5]
    if movers:
        out.write("top movers:\n")
        for r in movers:
            out.write(f"  {r['leg']}.{r['key']}: "
                      f"{r['old']:.6g} -> {r['new']:.6g} "
                      f"({r['rel_change']:+.1%})\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("current", nargs="?", default=None,
                    help="current bench output (file with the bench "
                         "JSON line); default: the newest committed "
                         "round, compared against the one before it")
    ap.add_argument("--against", default=None,
                    help="baseline artifact; default: newest committed "
                         "BENCH_r*.json (or second-newest when no "
                         "current file is given)")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="relative regression threshold (default 0.10)")
    ap.add_argument("--noise-floor", type=float, default=1e-4,
                    help="smallest absolute change that can flag "
                         "(default 1e-4: sub-resolution micro-timing "
                         "jitter never gates)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full diff as JSON")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any leg regressed")
    args = ap.parse_args(argv)

    rounds = committed_rounds()
    if args.current is not None:
        new_path = args.current
        old_path = args.against or (rounds[-1] if rounds else None)
    else:
        if args.against is not None:
            old_path = args.against
            new_path = rounds[-1] if rounds else None
        elif len(rounds) >= 2:
            old_path, new_path = rounds[-2], rounds[-1]
        else:
            old_path = new_path = None
    if old_path is None or new_path is None:
        print("bench_diff: need two artifacts to compare "
              "(no committed BENCH_r*.json rounds found)")
        return 0

    old_legs, new_legs = extract_legs(old_path), extract_legs(new_path)
    if not old_legs or not new_legs:
        print(f"bench_diff: could not extract per-leg metrics "
              f"({old_path}: {len(old_legs)} legs, "
              f"{new_path}: {len(new_legs)} legs)")
        return 0
    result = diff_legs(old_legs, new_legs, threshold=args.threshold,
                       noise_floor=args.noise_floor)
    attrib = []
    if args.strict and result["regressions"]:
        attrib = attribution_delta(result["regressions"], old_path,
                                   new_path)
    if args.json:
        payload = {"old": old_path, "new": new_path,
                   "threshold": args.threshold, **result}
        if attrib:
            payload["attribution_delta"] = attrib
        json.dump(payload, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        render(result, old_path, new_path, args.threshold)
        if attrib:
            render_attribution(attrib)
    return 1 if (args.strict and result["regressions"]) else 0


if __name__ == "__main__":
    sys.exit(main())
