#!/usr/bin/env python
"""On-chip GPT-350M decode sweep: slot-batch x cache-depth steady-state
decode throughput + prefill latency (companion to tools/sweep_gpt.py;
same hard-sync protocol).  Informs the engine's max_slots/max_seq
choices: decode is cache-bandwidth bound, so tokens/s should scale with
slots until the KV reads saturate HBM."""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from _timing import sync as _sync, time_steps as _time  # noqa: E402


def make_decode(slots, depth, cache_dtype=jnp.bfloat16, max_seq=1024):
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    from apex_tpu.utils.platform import is_tpu_backend

    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, max_seq_len=max_seq,
                    dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    cache = jnp.zeros((slots, cfg.num_layers, 2, max_seq,
                       cfg.num_attention_heads, cfg.head_dim), cache_dtype)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (slots,)))
    positions = jnp.full((slots,), depth, jnp.int32)
    step = jax.jit(model.decode_step,
                   donate_argnums=(2,) if is_tpu_backend() else ())
    holder = {"c": cache}

    def run(tokens, positions):
        logits, holder["c"] = step(params, tokens, holder["c"],
                                   positions)
        return logits

    return run, (tokens, positions), slots


def make_prefill(prompt_len):
    from apex_tpu.models.gpt import GPTConfig, GPTModel

    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_attention_heads=16, max_seq_len=1024,
                    dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, cfg.vocab_size, (1, prompt_len)))
    prefill = jax.jit(model.prefill)

    def run(toks):
        return prefill(params, toks)[0]

    return run, (toks,), prompt_len


def main():
    configs = [
        ("decode_s1_d512", lambda: make_decode(1, 512)),
        ("decode_s4_d512", lambda: make_decode(4, 512)),
        ("decode_s8_d512", lambda: make_decode(8, 512)),
        ("decode_s16_d512", lambda: make_decode(16, 512)),
        ("decode_s8_d128", lambda: make_decode(8, 128)),
        ("decode_s8_d1016", lambda: make_decode(8, 1016)),
        ("decode_s8_d512_f32", lambda: make_decode(8, 512, jnp.float32)),
        ("prefill_p128", lambda: make_prefill(128)),
        ("prefill_p512", lambda: make_prefill(512)),
    ]
    if len(sys.argv) > 1:
        names = set(sys.argv[1].split(","))
        configs = [c for c in configs if c[0] in names]
    for name, make in configs:
        try:
            run, args, tok = make()
            dt = _time(run, args)
            print(f"{name}: {tok / dt:,.0f} tok/s (step {dt * 1e3:.1f} ms)",
                  flush=True)
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:120]}", flush=True)
        jax.clear_caches()


if __name__ == "__main__":
    main()
