#!/usr/bin/env python
"""Measured-cost auto-parallel planner (ROADMAP item 1).

Enumerates the joint (dp, tp, pp, sequence-parallel, overlap-chunk,
virtual-stage, microbatch, remat, ZeRO, transport-dtype) space as
validated :class:`~apex_tpu.parallel.plan.ParallelPlan` candidates,
then drives each survivor through three measured gates:

1. **memory prune** — compile the candidate's ACTUAL train step
   (pipeline + optimizer, the program that would run) and reject it
   when :func:`apex_tpu.analysis.memory.estimate_peak_memory` exceeds
   the per-device HBM budget.  No closed-form activation guesses: the
   estimate walks the lowered HLO's live ranges.
2. **cost rank** — predicted step time = compute roofline (flops from
   the 6ND rule, 8ND under remat, calibrated against a matmul timed on
   THIS host, divided by the pipeline's utilization
   ``1 - bubble_fraction``) + communication from
   ``CostModel.predict_stats`` over the candidate's own optimized-HLO
   collectives, with alpha-beta coefficients fitted from ring
   microbenchmarks (``tools/comms_probe.py`` profile, or probed
   in-process when none is given).
3. **measure** — the top-K ranked candidates run for real under the
   hard-sync timing protocol; the measured winner is emitted.

The emitted JSON is versioned and round-trips through
``ParallelPlan.from_dict``; hand ``load_plan(path)`` to
``HostSignals.request_replan`` and a live ``ElasticTrainer`` re-shards
onto it without a restart.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        JAX_PLATFORMS=cpu python tools/autotune.py --devices 8 \\
        --out plan.json
    python tools/autotune.py --devices 8 --profile comms_profile.json \\
        --hbm-gb 0.5 --top-k 3 --out plan.json

``--rank-only`` stops after gate 2 (:func:`rank_plans`): enumerate,
prune and rank against the profile without measuring — the shadow
re-rank the parallelism autopilot
(:class:`apex_tpu.resilience.autopilot.ParallelismAutopilot`) runs in
the background when a REFRESHED profile drifts, leaving the live
measurement to its own K-step commit gate:

    python tools/autotune.py --devices 8 --rank-only \\
        --profile refreshed_profile.json --out reranked_plan.json

``--mpmd`` switches to the two-tier cross-pod planner: enumerate
``(pp, per-stage dp x tp, M)`` plans for ``--pods`` pod blocks, price
each under both MPMD schedules with the
:func:`apex_tpu.mpmd.schedule.simulate` event model (ICI edges from
the profile's ``ici`` fits, DCN edges from its ``dcn`` fits or an
explicit ``--dcn alpha,beta``), and emit the winning plan + schedule:

    python tools/autotune.py --devices 8 --mpmd --pods 2 \\
        --dcn 1e-3,1e-9 --out mpmd_plan.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Any, List, Optional, Sequence, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

AUTOTUNE_VERSION = 1

# tiny-GPT default workload: big enough that dp/tp/pp/microbatching all
# change the lowered program, small enough to compile dozens of
# candidates on a CPU host
DEFAULT_MODEL = dict(vocab_size=64, hidden_size=32, num_layers=4,
                     num_attention_heads=4, max_seq_len=16)


@dataclasses.dataclass
class Candidate:
    """One point of the search space and everything measured about it.

    ``status`` walks ``enumerated -> built -> ranked -> measured`` or
    dead-ends at ``rejected`` (invalid knob combination, with the
    validation error as ``reason``) / ``pruned`` (over the HBM budget)
    / ``failed`` (compile error — recorded, not fatal)."""
    plan: Any
    status: str = "enumerated"
    reason: str = ""
    peak_bytes: Optional[int] = None
    xla_peak_bytes: Optional[int] = None
    xla_ratio: Optional[float] = None
    compute_s: Optional[float] = None
    comm_s: Optional[float] = None
    predicted_s: Optional[float] = None
    measured_s: Optional[float] = None

    def to_dict(self) -> dict:
        d = {"plan": (self.plan.to_dict()
                      if hasattr(self.plan, "to_dict") else self.plan),
             "status": self.status}
        for f in ("reason", "peak_bytes", "xla_peak_bytes", "xla_ratio",
                  "compute_s", "comm_s", "predicted_s", "measured_s"):
            v = getattr(self, f)
            if v not in (None, ""):
                d[f] = v
        return d


# -- search-space enumeration -------------------------------------------------


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def _reject_weight_quant(cfg_kw: dict) -> None:
    """The autotuner enumerates TRAINING plans — every candidate is a
    compiled grad step (build_train_step -> pipeline_step), which int8
    decode weights cannot feed.  Reject at the door with the fix."""
    if cfg_kw.get("weight_quant") is not None:
        raise ValueError(
            f"cfg_kw['weight_quant']={cfg_kw['weight_quant']!r}: the "
            "autotune space is training plans (pipeline_step grad "
            "builds), and weight_quant is decode/prefill-only — drop it "
            "from cfg_kw here and set it on the serving GPTConfig, "
            "where the inference engine quantizes at init")


def enumerate_space(n_devices: int, *, n_layers: int, n_heads: int,
                    batch: int, seq: int, max_tp: Optional[int] = None,
                    max_pp: Optional[int] = None, zero: bool = True,
                    remat_options: Sequence[bool] = (False, True),
                    overlap_options: Sequence[int] = (0, 2),
                    ) -> List[Candidate]:
    """All candidate plans for ``n_devices``, valid and rejected alike.

    Rejections are kept (status ``rejected`` with the reason) so the
    emitted report shows WHY a corner of the space is empty — the
    engine constraints (TP-in-pipeline requires SP, interleaved needs
    ``M % pp == 0``, ZeRO layouts are global-shape-only so
    ``zero_shard > 1`` is gated to ``tp == pp == 1``) prune far more
    than the divisibility arithmetic does.
    """
    from apex_tpu.parallel.plan import ParallelPlan

    out: List[Candidate] = []
    seen = set()

    def reject(reason, **kw):
        key = ("r", tuple(sorted(kw.items())))
        if key not in seen:
            seen.add(key)
            out.append(Candidate(plan=dict(kw), status="rejected",
                                 reason=reason))

    def add(**kw):
        key = ("p", tuple(sorted(kw.items())))
        if key in seen:
            return
        seen.add(key)
        try:
            out.append(Candidate(plan=ParallelPlan(**kw)))
        except ValueError as e:
            out.append(Candidate(plan=dict(kw), status="rejected",
                                 reason=str(e)))

    for dp in _divisors(n_devices):
        for tp in _divisors(n_devices // dp):
            pp = n_devices // (dp * tp)
            if max_tp is not None and tp > max_tp:
                continue
            if max_pp is not None and pp > max_pp:
                continue
            if n_heads % tp:
                reject(f"num_attention_heads={n_heads} not divisible "
                       f"by tp={tp}", dp=dp, tp=tp, pp=pp)
                continue
            if batch % dp:
                reject(f"batch={batch} not divisible by dp={dp}",
                       dp=dp, tp=tp, pp=pp)
                continue
            if n_layers % pp:
                reject(f"num_layers={n_layers} not divisible by pp={pp}",
                       dp=dp, tp=tp, pp=pp)
                continue
            sp_options = [False]
            if tp > 1:
                # the ring engine composes TP only with SP (non-SP TP
                # cotangents are unsound under shard_map); record the
                # non-SP corner as rejected rather than silently absent
                reject("pipeline TP requires sequence parallelism "
                       "(non-SP TP grads are unsound under shard_map)",
                       dp=dp, tp=tp, pp=pp, sequence_parallel=False)
                if seq % tp:
                    reject(f"seq={seq} not divisible by tp={tp} "
                           "(SP shards the sequence axis)",
                           dp=dp, tp=tp, pp=pp, sequence_parallel=True)
                    continue
                sp_options = [True]
            m_options = [1, 2] if pp == 1 else [pp, 2 * pp]
            for sp in sp_options:
                overlaps = [0] + [c for c in overlap_options
                                  if c and sp] if sp else [0]
                for M in m_options:
                    if (batch // dp) % M:
                        reject(f"per-dp batch {batch // dp} not "
                               f"divisible by n_microbatches={M}",
                               dp=dp, tp=tp, pp=pp, n_microbatches=M)
                        continue
                    v_options = [1]
                    if pp > 1 and n_layers % (pp * 2) == 0 and M % pp == 0:
                        v_options.append(2)
                    for v in v_options:
                        if n_layers % (pp * v):
                            continue
                        for remat in remat_options:
                            for ov in overlaps:
                                zeros = [1]
                                if zero and dp > 1 and tp == 1 and pp == 1:
                                    # ZeRO bucket layouts are computed on
                                    # global shapes; only a unit tp x pp
                                    # mesh keeps local == global
                                    zeros.append(dp)
                                for z in zeros:
                                    dtypes = ([None, "bf16"] if z > 1
                                              else [None])
                                    for ad in dtypes:
                                        add(dp=dp, tp=tp, pp=pp,
                                            sequence_parallel=sp,
                                            overlap_chunks=ov,
                                            n_virtual=v,
                                            n_microbatches=M,
                                            remat=remat,
                                            allreduce_dtype=ad,
                                            zero_shard=z)
    return out


# -- candidate train-step construction ----------------------------------------


def build_train_step(plan, cfg_kw: dict, batch: int, seq: int, devices):
    """The candidate's real program: pipelined grad step + optimizer.

    Returns ``(train_step, args, n_params)``.  ``zero_shard > 1``
    candidates route the stacked per-device grads through
    ``DistributedFusedAdam.make_step`` (the reduce-scatter IS the
    gradient reduction); everything else psum-means over ``data``
    inside the region and applies ``FusedAdam`` outside it.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import (GPTConfig, GPTModel,
                                     pack_for_shard_map, pipeline_step)
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import DistributedFusedAdam
    from apex_tpu.resilience.elastic import ElasticPlan
    from apex_tpu.utils.collectives import shard_map_compat

    eplan = ElasticPlan.build(plan, devices=devices)
    mesh = eplan.mesh
    serial = GPTModel(GPTConfig(**cfg_kw))
    params = serial.init_params(jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))
    par = GPTModel(GPTConfig(plan=plan, **cfg_kw))
    tensor_axis = "model" if plan.tp > 1 else None
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
        par, params, n_stages=plan.pp, tensor_axis=tensor_axis,
        n_virtual=plan.n_virtual)
    M = plan.n_microbatches
    mb = batch // (plan.dp * M)
    if mb < 1:
        raise ValueError(f"batch={batch} too small for dp={plan.dp} x "
                         f"M={M}")
    rng = np.random.RandomState(0)
    vocab = cfg_kw["vocab_size"]
    tokens = jnp.asarray(rng.randint(0, vocab, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, vocab, (batch, seq)))
    is_spec = lambda x: isinstance(x, P)  # noqa: E731

    if plan.zero_shard > 1:
        opt = DistributedFusedAdam(lr=1e-3, plan=plan)
        opt_state = opt.make_init(mesh)(packed)
        zero_step = opt.make_step(mesh)

        def grad_step(sp_, tk_, tg_):
            tk = tk_.reshape(M, mb, seq)
            tg = tg_.reshape(M, mb, seq)
            # data_axis=None: grads stay per-device — the ZeRO step's
            # reduce-scatter is the gradient reduction
            loss, g = pipeline_step(par, local_fn(sp_), tk, tg,
                                    pipe_axis="pipe", data_axis=None,
                                    n_virtual=plan.n_virtual)
            # new unit leading axis -> P("data", ...) out_specs stack
            # the per-device grads to (world_size, *param.shape), the
            # layout make_step's reduce-scatter consumes
            g = jax.tree_util.tree_map(lambda x: x[None], repack_fn(g))
            return loss[None], g

        g_specs = jax.tree_util.tree_map(lambda s: P("data", *s),
                                         in_specs, is_leaf=is_spec)

        def train_step(packed_, opt_state_, tk_, tg_):
            loss, grads = shard_map_compat(
                grad_step, mesh=mesh,
                in_specs=(in_specs, P("data"), P("data")),
                out_specs=(P("data"), g_specs))(packed_, tk_, tg_)
            new_p, new_s = zero_step(grads, packed_, opt_state_)
            return loss.mean(), new_p, new_s
    else:
        opt = FusedAdam(lr=1e-3)
        opt_state = opt.init(packed)

        def grad_step(sp_, tk_, tg_):
            tk = tk_.reshape(M, mb, seq)
            tg = tg_.reshape(M, mb, seq)
            loss, g = pipeline_step(par, local_fn(sp_), tk, tg,
                                    pipe_axis="pipe", data_axis="data",
                                    n_virtual=plan.n_virtual)
            return loss, repack_fn(g)

        def train_step(packed_, opt_state_, tk_, tg_):
            loss, grads = shard_map_compat(
                grad_step, mesh=mesh,
                in_specs=(in_specs, P("data"), P("data")),
                out_specs=(P(), in_specs))(packed_, tk_, tg_)
            new_p, new_s = opt.step(grads, packed_, opt_state_)
            return loss, new_p, new_s

    return train_step, (packed, opt_state, tokens, targets), n_params


# -- cost prediction ----------------------------------------------------------


def calibrate_matmul_flops(n: int = 192) -> float:
    """Achievable matmul flops/s on one device of THIS host — the
    roofline's peak.  A measured constant, not a spec-sheet number, so
    candidate rankings stay meaningful on CPU hosts too."""
    import jax
    import jax.numpy as jnp

    from tools._timing import time_steps

    a = jnp.ones((n, n), jnp.float32)
    f = jax.jit(lambda x, y: x @ y)
    t = time_steps(f, (a, a), warmup=1, iters=4, rounds=3)
    return 2.0 * n ** 3 / max(t, 1e-9)


def predict_compute_s(plan, n_params: int, batch: int, seq: int,
                      flops_per_s: float) -> float:
    """6ND-rule roofline: ``6 * params * tokens`` matmul flops for
    fwd+bwd (8ND under full remat — the recomputed forward), spread
    over the plan's devices, divided by pipeline utilization."""
    from apex_tpu.transformer.pipeline_parallel.ring import bubble_fraction

    flops = 6.0 * float(n_params) * batch * seq
    if plan.remat:
        flops *= 8.0 / 6.0
    t = flops / (plan.n_devices * flops_per_s)
    if plan.pp > 1:
        util = 1.0 - bubble_fraction(plan.n_microbatches, plan.pp,
                                     plan.n_virtual)
        t /= max(util, 1e-9)
    return t


def predict_comm_s(compiled, cost_model, group_size: int) -> float:
    """Communication seconds from the candidate's OWN optimized HLO:
    every collective the compiler actually emitted, priced by the
    fitted alpha-beta ring model."""
    from apex_tpu.observability.comms import hlo_collective_stats

    stats = hlo_collective_stats(compiled.as_text())
    return cost_model.predict_stats(stats, group_size=group_size)["total_s"]


def _default_cost_model(n_devices: int):
    """Probe a minimal in-process profile when no ``--profile`` is
    given: f32-only, three sizes spanning 4K-1M and EVERY ring width
    the mesh supports — the fit extrapolates badly outside the probed
    range (in bytes and in hops alike), and the candidates' gradient
    reductions sit at the top of both."""
    from apex_tpu.observability.costmodel import (fit_cost_model,
                                                  probe_collectives)

    groups = [k for k in (2, 4, 8) if n_devices % k == 0
              and k <= n_devices]
    ms = probe_collectives(dtypes=("f32",),
                           sizes=(1 << 12, 1 << 16, 1 << 20),
                           group_sizes=groups or None, iters=2, rounds=2)
    return fit_cost_model(ms, meta={"source": "autotune-inline-probe"})


# -- two-tier MPMD planner ----------------------------------------------------


def enumerate_mpmd_space(n_devices: int, *, n_layers: int, n_heads: int,
                         batch: int, seq: int, n_pods: int,
                         max_tp: Optional[int] = None) -> List[Candidate]:
    """Cross-pod candidates: ``pp`` stages (a multiple of ``n_pods``)
    times a per-stage ``dp x tp`` mesh, each stage its own program
    (``apex_tpu.mpmd``).  Same keep-the-rejections convention as
    :func:`enumerate_space`; every valid plan carries ``n_pods``."""
    from apex_tpu.parallel.plan import ParallelPlan

    out: List[Candidate] = []
    seen = set()

    def reject(reason, **kw):
        key = ("r", tuple(sorted(kw.items())))
        if key not in seen:
            seen.add(key)
            out.append(Candidate(plan=dict(kw), status="rejected",
                                 reason=reason))

    for pp in _divisors(n_devices):
        if pp < 2 or pp % n_pods:
            continue
        if n_layers % pp:
            reject(f"num_layers={n_layers} not divisible by pp={pp}",
                   pp=pp, n_pods=n_pods)
            continue
        for dp in _divisors(n_devices // pp):
            tp = n_devices // (pp * dp)
            if max_tp is not None and tp > max_tp:
                continue
            if n_heads % tp:
                reject(f"num_attention_heads={n_heads} not divisible "
                       f"by tp={tp}", dp=dp, tp=tp, pp=pp,
                       n_pods=n_pods)
                continue
            if batch % dp:
                reject(f"batch={batch} not divisible by dp={dp}",
                       dp=dp, tp=tp, pp=pp, n_pods=n_pods)
                continue
            sp = tp > 1
            if sp and seq % tp:
                reject(f"seq={seq} not divisible by tp={tp} "
                       "(SP shards the sequence axis)",
                       dp=dp, tp=tp, pp=pp, n_pods=n_pods,
                       sequence_parallel=True)
                continue
            for M in (pp, 2 * pp):
                if (batch // dp) % M:
                    reject(f"per-dp batch {batch // dp} not divisible "
                           f"by n_microbatches={M}", dp=dp, tp=tp,
                           pp=pp, n_pods=n_pods, n_microbatches=M)
                    continue
                key = ("p", dp, tp, pp, M)
                if key in seen:
                    continue
                seen.add(key)
                try:
                    out.append(Candidate(plan=ParallelPlan(
                        dp=dp, tp=tp, pp=pp, sequence_parallel=sp,
                        n_microbatches=M, n_pods=n_pods)))
                except ValueError as e:
                    out.append(Candidate(
                        plan=dict(dp=dp, tp=tp, pp=pp, n_pods=n_pods,
                                  n_microbatches=M),
                        status="rejected", reason=str(e)))
    return out


def simulate_mpmd(plan, schedule_name: str, *, n_params: int,
                  batch: int, seq: int, hidden: int,
                  flops_per_s: float, cost_model=None,
                  dcn: Optional[Tuple[float, float]] = None) -> dict:
    """Price one cross-pod candidate with the schedule simulator.

    Stage compute comes from the 6ND roofline split over ``pp`` stage
    chunks and each stage's ``dp * tp`` devices (backward = 2x
    forward); each edge carries one microbatch's global activation
    (``batch/M * seq * hidden`` f32) priced on ITS link class —
    ``ppermute`` fits from ``cost_model``, or an explicit ``dcn``
    ``(alpha_s, beta_s_per_byte)`` override for the DCN edges.  The
    ``1f1b`` schedule runs with blocking sends (the lockstep/SPMD
    model: every hop sits on the critical path) and ``dcn_hiding``
    with asynchronous sends (the MPMD host model) — the two execution
    semantics the two engines actually have.
    """
    from apex_tpu.mpmd.schedule import (SCHEDULES, edge_link_classes,
                                        simulate)

    S, M = plan.pp, plan.n_microbatches
    tokens_per_mb = (batch // M) * seq
    stage_flops_fwd = 2.0 * (float(n_params) / S) * tokens_per_mb
    t_fwd = stage_flops_fwd / (plan.dp * plan.tp * flops_per_s)
    t_bwd = 2.0 * t_fwd
    act_bytes = (batch // M) * seq * hidden * 4
    classes = edge_link_classes(S, plan.n_pods)
    link_seconds = {}
    for e, lc in classes.items():
        if lc == "dcn" and dcn is not None:
            link_seconds[e] = dcn[0] + dcn[1] * act_bytes
        elif cost_model is not None:
            link_seconds[e] = cost_model.predict(
                "ppermute", act_bytes, 2, link_class=lc)
        else:
            link_seconds[e] = 0.0
    order = SCHEDULES[schedule_name](S, M)
    sim = simulate(order, S, M, t_fwd=t_fwd, t_bwd=t_bwd,
                   link_seconds=link_seconds, link_classes=classes,
                   blocking_sends=(schedule_name == "1f1b"))
    sim["t_fwd"] = t_fwd
    sim["t_bwd"] = t_bwd
    sim["act_bytes"] = act_bytes
    sim["link_seconds"] = {str(e): s for e, s in link_seconds.items()}
    return sim


def autotune_mpmd(n_devices: int, *, cfg_kw: Optional[dict] = None,
                  batch: int = 8, seq: Optional[int] = None,
                  n_pods: int = 2, cost_model=None,
                  dcn: Optional[Tuple[float, float]] = None,
                  max_tp: Optional[int] = None,
                  verbose: bool = True) -> dict:
    """Enumerate and rank two-tier (ICI + DCN) MPMD plans.

    Pure simulation — no per-candidate compiles: the cross-pod search
    only has to order plans by how well their schedule hides the DCN
    edges, and the simulator prices exactly that.  Every candidate is
    scored under BOTH schedules; the report's winner carries the
    schedule name to hand to :class:`~apex_tpu.mpmd.MpmdPipeline`.
    """
    import jax
    import numpy as np

    def say(msg):
        if verbose:
            print(msg, flush=True)

    cfg_kw = dict(cfg_kw or DEFAULT_MODEL)
    _reject_weight_quant(cfg_kw)
    seq = seq if seq is not None else cfg_kw["max_seq_len"]
    if cost_model is None and dcn is None:
        say("no comms profile or --dcn given; probing ici in-process")
        cost_model = _default_cost_model(n_devices)

    from apex_tpu.models.gpt import GPTConfig, GPTModel
    serial = GPTModel(GPTConfig(**cfg_kw))
    params = serial.init_params(jax.random.PRNGKey(0))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params))
    flops_per_s = calibrate_matmul_flops()

    cands = enumerate_mpmd_space(
        n_devices, n_layers=cfg_kw["num_layers"],
        n_heads=cfg_kw["num_attention_heads"], batch=batch, seq=seq,
        n_pods=n_pods, max_tp=max_tp)
    valid = [c for c in cands if c.status == "enumerated"]
    say(f"enumerated {len(cands)} cross-pod points: {len(valid)} valid")
    if not valid:
        raise RuntimeError(
            f"no valid MPMD plan for {n_devices} devices / "
            f"{n_pods} pods — see the report's rejection reasons")

    rows = []
    for c in valid:
        for name in ("1f1b", "dcn_hiding"):
            sim = simulate_mpmd(
                c.plan, name, n_params=n_params, batch=batch, seq=seq,
                hidden=cfg_kw["hidden_size"], flops_per_s=flops_per_s,
                cost_model=cost_model, dcn=dcn)
            rows.append({"plan": c.plan.to_dict(), "schedule": name,
                         "predicted_s": sim["makespan"],
                         "bubble_fraction": sim["bubble_fraction"],
                         "dcn_hidden_fraction":
                             sim["hidden_fraction"]["dcn"]})
        c.status = "ranked"
        c.predicted_s = min(r["predicted_s"] for r in rows[-2:])
    rows.sort(key=lambda r: r["predicted_s"])
    win = rows[0]
    say(f"winner: {win['plan']} schedule={win['schedule']} "
        f"pred={win['predicted_s'] * 1e3:.3f} ms/step "
        f"bubble={win['bubble_fraction']:.3f} "
        f"dcn_hidden={win['dcn_hidden_fraction']:.3f}")
    return {
        "version": AUTOTUNE_VERSION,
        "mode": "mpmd",
        "n_devices": n_devices,
        "n_pods": n_pods,
        "model": cfg_kw,
        "batch": batch,
        "seq": seq,
        "flops_per_s": flops_per_s,
        "plan": win["plan"],
        "schedule": win["schedule"],
        "predicted_s": win["predicted_s"],
        "ranked": rows,
        "candidates": [c.to_dict() for c in cands],
    }


# -- the planner --------------------------------------------------------------


def _rank(n_devices, *, cfg_kw, batch, seq, hbm_bytes, cost_model,
          max_tp, max_pp, zero, remat_options, devices, say):
    """Shared enumerate -> compile -> memory-prune -> cost-rank pass.
    Returns ``(cands, ranked, flops_per_s, compiled_by_id)`` — the
    ranked survivors best-first plus the compiled programs keyed by
    candidate identity, so :func:`autotune` can measure the top K
    without recompiling."""
    import jax

    from apex_tpu.analysis.memory import estimate_peak_memory

    cands = enumerate_space(
        n_devices, n_layers=cfg_kw["num_layers"],
        n_heads=cfg_kw["num_attention_heads"], batch=batch, seq=seq,
        max_tp=max_tp, max_pp=max_pp, zero=zero,
        remat_options=remat_options)
    valid = [c for c in cands if c.status == "enumerated"]
    say(f"enumerated {len(cands)} points: {len(valid)} valid plans, "
        f"{len(cands) - len(valid)} rejected")
    if not valid:
        raise RuntimeError("search space is empty; every candidate was "
                           "rejected — see the report's rejection "
                           "reasons")

    flops_per_s = calibrate_matmul_flops()
    say(f"calibrated matmul roofline: {flops_per_s / 1e9:.2f} Gflop/s "
        "per device")

    compiled_by_id = {}
    for c in valid:
        plan = c.plan
        try:
            step, args, n_params = build_train_step(
                plan, cfg_kw, batch, seq, devices)
            compiled = jax.jit(step).lower(*args).compile()
        except Exception as e:  # noqa: BLE001 — a candidate that cannot
            # compile is a data point, not a crash
            c.status, c.reason = "failed", f"{type(e).__name__}: {e}"
            continue
        est = estimate_peak_memory(compiled)
        c.peak_bytes = int(est.peak_bytes)
        c.xla_peak_bytes = est.xla_peak_bytes
        c.xla_ratio = est.xla_ratio
        if est.peak_bytes > hbm_bytes:
            c.status = "pruned"
            c.reason = (f"estimated peak {est.peak_bytes} B over the "
                        f"{int(hbm_bytes)} B per-device budget")
            continue
        c.compute_s = predict_compute_s(plan, n_params, batch, seq,
                                        flops_per_s)
        c.comm_s = predict_comm_s(compiled, cost_model,
                                  group_size=max(plan.dp, plan.tp,
                                                 plan.pp))
        c.predicted_s = c.compute_s + c.comm_s
        c.status = "ranked"
        compiled_by_id[id(c)] = (compiled, args)
    ranked = sorted((c for c in valid if c.status == "ranked"),
                    key=lambda c: c.predicted_s)
    say(f"memory prune: {len(ranked)} survivors of {len(valid)} "
        f"({sum(1 for c in valid if c.status == 'pruned')} over budget, "
        f"{sum(1 for c in valid if c.status == 'failed')} failed)")
    if not ranked:
        raise RuntimeError("no candidate fits the HBM budget; raise "
                           "--hbm-gb or shrink the model")
    return cands, ranked, flops_per_s, compiled_by_id


def rank_plans(n_devices: int, *, cfg_kw: Optional[dict] = None,
               batch: int = 8, seq: Optional[int] = None,
               hbm_bytes: float = 0.5 * (1 << 30), cost_model=None,
               max_tp: Optional[int] = None,
               max_pp: Optional[int] = None, zero: bool = True,
               remat_options: Sequence[bool] = (False, True),
               devices=None, verbose: bool = True) -> dict:
    """Rank-only pass: enumerate -> compile -> prune -> rank against
    the given CostModel WITHOUT the measure phase — the background
    re-rank entry point the parallelism autopilot
    (:class:`apex_tpu.resilience.autopilot.ParallelismAutopilot`) runs
    against a REFRESHED profile: ranking costs compiles, not training
    steps, so it can shadow a live job; the winner is then proven by
    the autopilot's own K-step commit gate instead of an offline
    measurement.  Returns the same report shape as :func:`autotune`
    with ``mode="rank"`` and no ``measured_s``."""
    import jax

    def say(msg):
        if verbose:
            print(msg, flush=True)

    cfg_kw = dict(cfg_kw or DEFAULT_MODEL)
    _reject_weight_quant(cfg_kw)
    seq = seq if seq is not None else cfg_kw["max_seq_len"]
    devices = (list(devices) if devices is not None
               else jax.devices()[:n_devices])
    if len(devices) < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have "
                           f"{len(devices)}")
    if cost_model is None:
        say("no comms profile given; probing a minimal one in-process")
        cost_model = _default_cost_model(n_devices)

    cands, ranked, flops_per_s, _ = _rank(
        n_devices, cfg_kw=cfg_kw, batch=batch, seq=seq,
        hbm_bytes=hbm_bytes, cost_model=cost_model, max_tp=max_tp,
        max_pp=max_pp, zero=zero, remat_options=remat_options,
        devices=devices, say=say)
    winner = ranked[0]
    say(f"winner (ranked, unmeasured): {winner.plan.describe()} "
        f"({winner.predicted_s * 1e3:.3f} ms/step predicted)")
    return {
        "version": AUTOTUNE_VERSION,
        "mode": "rank",
        "n_devices": n_devices,
        "model": cfg_kw,
        "batch": batch,
        "seq": seq,
        "hbm_bytes": int(hbm_bytes),
        "flops_per_s": flops_per_s,
        "plan": winner.plan.to_dict(),
        "predicted_s": winner.predicted_s,
        "candidates": [c.to_dict() for c in cands],
    }


def autotune(n_devices: int, *, cfg_kw: Optional[dict] = None,
             batch: int = 8, seq: Optional[int] = None,
             hbm_bytes: float = 0.5 * (1 << 30), cost_model=None,
             top_k: int = 3, max_tp: Optional[int] = None,
             max_pp: Optional[int] = None, zero: bool = True,
             remat_options: Sequence[bool] = (False, True),
             devices=None, measure_iters: int = 2,
             measure_rounds: int = 2,
             verbose: bool = True) -> dict:
    """Full prune -> rank -> measure pass; returns the report dict
    (the same structure :func:`emit_plan` writes)."""
    import jax

    from tools._timing import time_steps

    def say(msg):
        if verbose:
            print(msg, flush=True)

    cfg_kw = dict(cfg_kw or DEFAULT_MODEL)
    _reject_weight_quant(cfg_kw)
    seq = seq if seq is not None else cfg_kw["max_seq_len"]
    devices = (list(devices) if devices is not None
               else jax.devices()[:n_devices])
    if len(devices) < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have "
                           f"{len(devices)}")
    if cost_model is None:
        say("no comms profile given; probing a minimal one in-process")
        cost_model = _default_cost_model(n_devices)

    cands, ranked, flops_per_s, compiled_by_id = _rank(
        n_devices, cfg_kw=cfg_kw, batch=batch, seq=seq,
        hbm_bytes=hbm_bytes, cost_model=cost_model, max_tp=max_tp,
        max_pp=max_pp, zero=zero, remat_options=remat_options,
        devices=devices, say=say)

    for c in ranked[:top_k]:
        compiled, args = compiled_by_id[id(c)]
        c.measured_s = time_steps(compiled, args, warmup=1,
                                  iters=measure_iters,
                                  rounds=measure_rounds)
        c.status = "measured"
        say(f"  measured {c.plan.describe():<55} "
            f"pred={c.predicted_s * 1e3:8.3f} ms  "
            f"meas={c.measured_s * 1e3:8.3f} ms")
    measured = sorted((c for c in ranked if c.status == "measured"),
                      key=lambda c: c.measured_s)
    winner = measured[0]
    say(f"winner: {winner.plan.describe()} "
        f"({winner.measured_s * 1e3:.3f} ms/step measured)")

    return {
        "version": AUTOTUNE_VERSION,
        "n_devices": n_devices,
        "model": cfg_kw,
        "batch": batch,
        "seq": seq,
        "hbm_bytes": int(hbm_bytes),
        "flops_per_s": flops_per_s,
        "plan": winner.plan.to_dict(),
        "predicted_s": winner.predicted_s,
        "measured_s": winner.measured_s,
        "candidates": [c.to_dict() for c in cands],
    }


# -- emit / load --------------------------------------------------------------


def emit_plan(path: str, report: dict) -> None:
    with open(path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")


def load_plan(path: str):
    """The winning :class:`~apex_tpu.parallel.plan.ParallelPlan` from
    an emitted report — hand it straight to
    ``HostSignals.request_replan``.  Version-checked at both layers
    (report envelope here, plan dict in ``ParallelPlan.from_dict``)."""
    from apex_tpu.parallel.plan import ParallelPlan

    with open(path) as f:
        report = json.load(f)
    v = report.get("version")
    if v != AUTOTUNE_VERSION:
        raise ValueError(
            f"autotune report version {v!r} != {AUTOTUNE_VERSION}; "
            "re-run tools/autotune.py to emit a current report")
    return ParallelPlan.from_dict(report["plan"])


# -- CLI ----------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=None,
                    help="mesh size to plan for (default: all visible)")
    ap.add_argument("--out", default="autotune_plan.json")
    ap.add_argument("--profile", default=None,
                    help="comms profile JSON from tools/comms_probe.py "
                         "(default: probe a minimal one in-process)")
    ap.add_argument("--hbm-gb", type=float, default=0.5,
                    help="per-device HBM budget for the memory prune")
    ap.add_argument("--top-k", type=int, default=3,
                    help="ranked candidates to measure for real")
    ap.add_argument("--rank-only", action="store_true",
                    help="skip the measure phase: enumerate, prune and "
                         "rank against the profile only — the shadow "
                         "re-rank the parallelism autopilot runs on a "
                         "refreshed profile (the commit gate measures "
                         "the winner live instead)")
    ap.add_argument("--batch", type=int, default=8,
                    help="global batch rows for the probe workload")
    ap.add_argument("--max-tp", type=int, default=None)
    ap.add_argument("--max-pp", type=int, default=None)
    ap.add_argument("--mpmd", action="store_true",
                    help="plan a cross-pod MPMD pipeline "
                         "(apex_tpu.mpmd) instead of a single mesh")
    ap.add_argument("--pods", type=int, default=2,
                    help="pod count for --mpmd (stages split into "
                         "this many contiguous blocks; adjacent "
                         "blocks joined by DCN)")
    ap.add_argument("--dcn", default=None, metavar="ALPHA,BETA",
                    help="price DCN edges as alpha_s,beta_s_per_byte "
                         "instead of a profile's dcn fits (e.g. "
                         "1e-3,1e-9)")
    ap.add_argument("--no-zero", action="store_true",
                    help="drop ZeRO (zero_shard > 1) candidates")
    ap.add_argument("--no-remat", action="store_true",
                    help="search remat=False only (faster compiles)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    import jax

    # the axon TPU plugin ignores JAX_PLATFORMS=cpu from the env; flip
    # the config knob before backend init when the caller asked for cpu
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    n = args.devices or len(jax.devices())
    cost_model = None
    if args.profile is not None:
        from apex_tpu.observability.costmodel import load_profile
        cost_model, _ = load_profile(args.profile)

    if args.mpmd:
        dcn = None
        if args.dcn is not None:
            a, b = args.dcn.split(",")
            dcn = (float(a), float(b))
        report = autotune_mpmd(
            n, batch=args.batch, n_pods=args.pods,
            cost_model=cost_model, dcn=dcn, max_tp=args.max_tp,
            verbose=not args.quiet)
    elif args.rank_only:
        report = rank_plans(
            n, hbm_bytes=args.hbm_gb * (1 << 30), cost_model=cost_model,
            batch=args.batch, max_tp=args.max_tp,
            max_pp=args.max_pp, zero=not args.no_zero,
            remat_options=(False,) if args.no_remat else (False, True),
            verbose=not args.quiet)
    else:
        report = autotune(
            n, hbm_bytes=args.hbm_gb * (1 << 30), cost_model=cost_model,
            top_k=args.top_k, batch=args.batch, max_tp=args.max_tp,
            max_pp=args.max_pp, zero=not args.no_zero,
            remat_options=(False,) if args.no_remat else (False, True),
            verbose=not args.quiet)
    emit_plan(args.out, report)
    if not args.quiet:
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
