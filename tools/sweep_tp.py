#!/usr/bin/env python
"""On-chip tensor-parallel overlap sweep: sequence-parallel GPT train
step across ``overlap_chunks`` (ring granularity) x tp width, against
the replicated-activation baseline (companion to tools/sweep_gpt.py;
same hard-sync protocol).

``chunks=r`` is the replicated (pre-sequence-parallel) arm; ``chunks=0``
is sequence-parallel with monolithic gather/scatter collectives; higher
chunk counts split each TP-edge collective+GEMM pair into that many
ring sub-steps, trading launch overhead for collective/compute overlap.
The sweet spot is topology-dependent — on a CPU host mesh (no real ICI)
chunking only adds overhead; sweep on the target slice.

Usage: ``python tools/sweep_tp.py [name,name,...]`` where names look
like ``tp4_c2`` / ``tp4_repl`` (default: every arm that fits the
device count).
"""

from __future__ import annotations

import sys

import jax
import jax.numpy as jnp
import numpy as np

from _timing import time_steps as _time  # noqa: E402


def make_step(tp, chunks, replicated=False, batch=4, seq=512):
    from jax.sharding import PartitionSpec as P

    from apex_tpu.models.gpt import GPTConfig, GPTModel, pack_for_shard_map
    from apex_tpu.utils.collectives import shard_map_compat

    cfg = GPTConfig(vocab_size=8192, hidden_size=512, num_layers=4,
                    num_attention_heads=8, max_seq_len=seq, rotary=True,
                    tensor_parallel_size=tp, axis_name="model",
                    sequence_parallel=not replicated,
                    overlap_chunks=0 if replicated else chunks,
                    dtype=jnp.bfloat16)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))

    mesh = jax.make_mesh((tp,), ("model",))
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(model, params)

    def step(sp, tokens, targets):
        loss, g = jax.value_and_grad(model.loss)(local_fn(sp), tokens,
                                                 targets)
        return loss, repack_fn(g)

    run = jax.jit(shard_map_compat(step, mesh=mesh,
                                   in_specs=(in_specs, P(), P()),
                                   out_specs=(P(), in_specs)))

    def timed(tokens, targets):
        loss, _ = run(packed, tokens, targets)
        return loss

    return timed, (tokens, targets), batch * seq


def main():
    n_dev = len(jax.devices())
    configs = []
    for tp in (2, 4, 8):
        if tp > n_dev:
            break
        configs.append((f"tp{tp}_repl", dict(tp=tp, chunks=0,
                                             replicated=True)))
        for chunks in (0, 1, 2, 4, 8):
            configs.append((f"tp{tp}_c{chunks}", dict(tp=tp,
                                                      chunks=chunks)))
    if not configs:
        print(f"needs >=2 devices for tensor parallelism, have {n_dev}",
              flush=True)
        return
    if len(sys.argv) > 1:
        names = set(sys.argv[1].split(","))
        configs = [c for c in configs if c[0] in names]
    base = {}  # tp -> replicated step time, for the speedup column
    for name, kw in configs:
        try:
            run, args, tok = make_step(**kw)
            dt = _time(run, args)
            extra = ""
            if kw.get("replicated"):
                base[kw["tp"]] = dt
            elif kw["tp"] in base:
                extra = f"  [{base[kw['tp']] / dt:.3f}x vs replicated]"
            print(f"{name}: {tok / dt:,.0f} tok/s (step {dt * 1e3:.1f} ms)"
                  f"{extra}", flush=True)
        except Exception as e:
            print(f"{name}: FAILED {type(e).__name__}: "
                  f"{str(e).splitlines()[0][:120]}", flush=True)
        jax.clear_caches()


if __name__ == "__main__":
    main()
