#!/usr/bin/env python
"""On-chip fused-FFN tuning sweep (ISSUE 17).

Times the Pallas fused bias-GELU FFN kernel fwd+bwd across
``(block_m, block_f)`` tilings at the model FFN shapes, and races the
unfused XLA chain (GEMM + epilogue-fused bias/GELU + GEMM) at each —
the fused win is the HBM round-trip of the ``(tokens, ffn_hidden)``
activation between the two GEMMs, so the crossover and the best tiling
are measured facts, not guesses.  Measured rows feed the autotune
CostModel's FFN term and the kernel's ``block_m``/``block_f`` defaults.

Usage: python tools/sweep_ffn.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from _timing import time_steps as _time  # noqa: E402 (sets sys.path)

from apex_tpu.ops.fused_ffn import (fused_ffn,                # noqa: E402
                                    fused_ffn_reference)


def grad_fn(ffn):
    def f(x, w1, b1, w2, b2):
        return jnp.sum(ffn(x, w1, b1, w2, b2).astype(jnp.float32))
    return jax.jit(jax.grad(f, argnums=(0, 1, 2, 3, 4)))


def main():
    rng = np.random.RandomState(0)
    # (label, tokens, hidden, ffn_hidden) — BERT-large headline step
    # (16x512 tokens), GPT-350M (8x1024), and a 2x-width arm
    shapes = [("bert", 16 * 512, 1024, 4096),
              ("gpt", 8 * 1024, 1024, 4096),
              ("wide", 4 * 1024, 2048, 8192)]
    blocks = [(128, 512), (256, 256), (256, 512), (512, 512),
              (256, 1024), (512, 1024)]
    for label, m, h, f in shapes:
        x = jnp.asarray(rng.randn(m, h), jnp.bfloat16)
        w1 = jnp.asarray(rng.randn(f, h) * 0.02, jnp.bfloat16)
        b1 = jnp.asarray(rng.randn(f) * 0.02, jnp.bfloat16)
        w2 = jnp.asarray(rng.randn(h, f) * 0.02, jnp.bfloat16)
        b2 = jnp.asarray(rng.randn(h) * 0.02, jnp.bfloat16)
        args = (x, w1, b1, w2, b2)

        unfused = grad_fn(fused_ffn_reference)
        try:
            dt = _time(unfused, args)
            print(f"{label} m={m} f={f} unfused(XLA): {dt * 1e3:8.2f} ms",
                  flush=True)
        except Exception as e:
            print(f"{label} m={m} f={f} unfused(XLA): FAILED "
                  f"{str(e).splitlines()[0][:100]}", flush=True)

        for bm, bf in blocks:
            if bm > m or bf > f:
                continue
            fl = grad_fn(lambda x, w1, b1, w2, b2, _bm=bm, _bf=bf:
                         fused_ffn(x, w1, b1, w2, b2, block_m=_bm,
                                   block_f=_bf))
            try:
                dt = _time(fl, args)
                print(f"{label} m={m} f={f} fused({bm},{bf}): "
                      f"{dt * 1e3:8.2f} ms", flush=True)
            except Exception as e:
                print(f"{label} m={m} f={f} fused({bm},{bf}): FAILED "
                      f"{str(e).splitlines()[0][:100]}", flush=True)
        jax.clear_caches()


if __name__ == "__main__":
    main()
