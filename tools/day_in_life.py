#!/usr/bin/env python
"""Day-in-the-life capacity-shifting chaos sim (ROADMAP item 4).

One virtual day for a pod whose chip budget is SHARED between training
and serving: diurnal traffic (the ``capacity_diurnal`` loadgen
scenario) drives a fleet of paged engines while an
:class:`~apex_tpu.resilience.elastic.ElasticTrainer` trains on the
same budget, and a burn-driven
:class:`~apex_tpu.resilience.capacity.CapacityController` shifts chips
between them — under injected chaos:

* a ``capacity_change`` serving fault fails the FIRST shift mid-flight
  (partial mutation, then the recovery rollback; the retry commits);
* an injected hard :class:`~apex_tpu.resilience.faults.Preemption`
  kills the trainer mid-day; a fresh trainer restores the stamped
  topology and resumes;
* three consecutive ``nan_grads`` anomalies trigger the guard's
  K-anomaly rollback (``once=True``: the rolled-back re-run is clean).

Hard gates (the run FAILS unless every one holds):

* exactly-once serving delivery: ``lost == []`` and zero duplicates,
  across every migration, drain, replica add/remove and rollback;
* SLO attainment >= 0.9 over the virtual clock;
* the trainer finishes all its steps and its params + every optimizer
  slot match an UNINTERRUPTED fixed-capacity reference at the same
  step count BITWISE;
* at least one mid-shift-fault rollback AND >= 2 committed shifts;
* :meth:`CapacityController.audit` returns ``[]`` — no shift ever
  started inside the hysteresis band or before cooldown expiry;
* all leased capacity is returned: training ends at its base dp with
  zero outstanding leases.

Run directly (forces 4 XLA CPU devices when jax is not yet loaded)::

    python tools/day_in_life.py --json

or through the loadgen scenario suite (set
``XLA_FLAGS=--xla_force_host_platform_device_count=4`` first)::

    python tools/loadgen.py --scenario capacity_diurnal

``--autopilot`` runs the self-driving-parallelism day instead
(= loadgen ``--scenario autopilot_drift``): same fleet + diurnal
traffic, but the capacity controller is replaced by a
:class:`~apex_tpu.resilience.autopilot.ParallelismAutopilot` and the
chaos is a mid-day interconnect drift — links go 16x slower (the
autopilot must DETECT it from refitted telemetry and commit dp 4 -> 2
through the measured gate), then recover with an injected
``plan_regression`` poisoning the re-adoption's commit gate (forced
measured rollback).  Gates: exactly-once delivery, SLO attainment
>= 0.9, >= 1 commit AND >= 1 rollback, adoption counters matching the
applied-fault log, a flap-free :meth:`ParallelismAutopilot.audit`, and
the finished training state bitwise vs an uninterrupted fixed-plan
reference.
"""

from __future__ import annotations

import argparse
import bisect
import json
import os
import shutil
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))
if _HERE not in sys.path:
    sys.path.insert(1, _HERE)

# the training side needs >= base_dp devices; force them before jax
# loads (same idiom as tools/crash_matrix.py) — a no-op when the caller
# (loadgen, pytest) already imported jax or set XLA_FLAGS itself
if "jax" not in sys.modules and "XLA_FLAGS" not in os.environ:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax            # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np    # noqa: E402

import loadgen        # noqa: E402


def day_args(seed: int = 0, requests: int = 240,
             json_out: bool = False, **overrides) -> argparse.Namespace:
    """The full knob set, loadgen-compatible where the helpers are
    shared (workload/model/replica shape) plus the capacity-side knobs.
    ``overrides`` patch any field."""
    ns = argparse.Namespace(
        scenario="capacity_diurnal", seed=seed, requests=requests,
        json_out=json_out,
        # traffic + drive loop
        rate=100.0, period_s=3.0, tick_s=0.02, max_ticks=4000,
        client_retries=3, e2e_slo_s=3.0,
        # workload shape
        min_prompt=8, pareto_shape=2.5, max_new=8,
        shared_prefix_prob=0.5, shared_prefix_len=16, num_prefixes=2,
        # model (tiny: the sim measures the CONTROL plane)
        vocab=64, hidden=32, layers=2, heads=2, max_seq=128,
        # base fleet
        replicas=2, max_slots=4, max_queue=64, max_queue_depth=8,
        block_size=8, chunked=False, token_budget=64,
        ttft_slo_s=0.05, burn_threshold=14.4, burn_window_s=60.0,
        retry_budget=4, hedge_after_s=None,
        # training side
        base_dp=4, min_train_dp=2, train_steps=40, train_every=8,
        preempt_step=12, anomaly_step=20,
        # capacity controller
        burn_high=6.0, burn_low=1.0, cap_burn_window_s=1.0,
        confirm_ticks=5, cooldown_s=2.0, drain_timeout_ticks=150,
    )
    for k, v in overrides.items():
        setattr(ns, k, v)
    return ns


# -- training side (the _dryrun_elastic model: tiny linear regression,
# replicated global batch => dp changes resume bitwise) ----------------------


def _loss_fn(p, x, y):
    return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))


def _batch_fn(step, plan):
    r = np.random.RandomState(60_000 + step)
    return (jnp.asarray(r.randn(8, 8).astype(np.float32)),
            jnp.asarray(r.randn(8, 4).astype(np.float32)))


def _factory(plan, ckpt, inj):
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.resilience import ElasticComponents, GuardedTrainStep

    opt = FusedAdam(lr=1e-2)
    guard = GuardedTrainStep(_loss_fn, opt, warmup_steps=1,
                             checkpoint=ckpt, fault_injector=inj)
    r = np.random.RandomState(3)
    params = plan.put(
        {"w": jnp.asarray(r.randn(8, 4).astype(np.float32)),
         "b": jnp.zeros((4,), jnp.float32)})
    return ElasticComponents(guard, params, opt.init(params),
                             guard.init_state())


def _flat(tr):
    out = list(jax.tree_util.tree_leaves(tr.params))
    st = tr.opt_state
    for key in sorted(st["buckets"]):
        for slot in sorted(st["buckets"][key]):
            v = st["buckets"][key][slot]
            out.extend(v if isinstance(v, list) else [v])
    return [np.asarray(x) for x in out]


def _bitwise_ok(got, ref):
    return (len(got) == len(ref)
            and all(np.array_equal(a, b) for a, b in zip(got, ref)))


def _train_injector(args, with_preempt: bool):
    """Three consecutive nan_grads (=> one terminating guard rollback;
    ``once=True`` makes the rolled-back re-run clean) and, for the day
    run only, a hard preemption.  The reference run gets the SAME
    anomalies so the two trajectories are comparable bitwise."""
    from apex_tpu.resilience import Fault, FaultInjector

    faults = [Fault(args.anomaly_step + k, "nan_grads", once=True)
              for k in range(3)]
    if with_preempt:
        faults.append(Fault(args.preempt_step, "preempt_at_step",
                            once=True))
    return FaultInjector(faults)


# -- the day -----------------------------------------------------------------


def run_day(args) -> dict:
    from apex_tpu.observability import (FlightRecorder, MetricsRegistry,
                                        Tracer)
    from apex_tpu.observability.slo import SLOMonitor, SLOTarget
    from apex_tpu.resilience import (CapacityController, ElasticPlan,
                                     ElasticTrainer, Preemption,
                                     TopologySpec)
    from apex_tpu.serving import (FleetRouter, PagedInferenceEngine,
                                  RequestShed, ServingFault,
                                  ServingFaultInjector, TickScheduler,
                                  VirtualClock)
    from apex_tpu.utils.profiling import ServingMetrics

    if jax.device_count() < args.base_dp:
        return {"skipped": f"needs >= {args.base_dp} devices "
                           f"(have {jax.device_count()}); set XLA_FLAGS="
                           "--xla_force_host_platform_device_count=4",
                "gates": {}}

    clock = VirtualClock()
    recorder = FlightRecorder(clock=clock)
    registry = MetricsRegistry()
    devices = jax.devices()[:args.base_dp]

    model, params = loadgen._build_model(args)
    replicas = loadgen._build_replicas(args, model, params, clock)
    # one fleet-scoped capacity_change active all day: the FIRST shift
    # (whenever burn triggers it) crashes mid-flight; consume-once, so
    # the post-rollback retry commits
    injector = ServingFaultInjector([ServingFault(
        0, 0, "capacity_change", magnitude=0.0, duration=10 ** 9)])
    fleet = FleetRouter(
        replicas, injector=injector, clock=clock,
        max_queue_depth=args.max_queue_depth,
        burn_threshold=args.burn_threshold,
        burn_window_s=args.burn_window_s,
        retry_budget=args.retry_budget,
        hedge_after_s=args.hedge_after_s,
        seed=args.seed, tracer=Tracer(clock=clock, id_tag="router"),
        recorder=recorder)

    def make_replica():
        slo = SLOMonitor([SLOTarget("ttft", args.ttft_slo_s,
                                    objective=0.9)], clock=clock)
        return PagedInferenceEngine(
            model, params, max_slots=args.max_slots,
            block_size=args.block_size, chunked_prefill=args.chunked,
            scheduler=TickScheduler(token_budget=args.token_budget),
            metrics=ServingMetrics(clock, slo=slo),
            max_queue=args.max_queue, clock=clock)

    root = tempfile.mkdtemp(prefix="apex_tpu_day_")
    try:
        el_inj = _train_injector(args, with_preempt=True)
        base = TopologySpec(dp=args.base_dp)
        trainer = ElasticTrainer(
            _factory, ElasticPlan.build(base, devices=devices),
            directory=root + "/day", fault_injector=el_inj,
            save_every=1, devices=devices, recorder=recorder)
        controller = CapacityController(
            trainer, fleet, make_replica,
            min_train_dp=args.min_train_dp,
            burn_high=args.burn_high, burn_low=args.burn_low,
            burn_window_s=args.cap_burn_window_s,
            confirm_ticks=args.confirm_ticks,
            cooldown_s=args.cooldown_s,
            drain_timeout_ticks=args.drain_timeout_ticks,
            injector=el_inj, serving_injector=injector,
            registry=registry, recorder=recorder, clock=clock)

        work = loadgen.synthesize_scenario(args)
        crng = np.random.RandomState(args.seed + 1)
        pending = [(t, i, req, int(args.client_retries))
                   for i, (t, req) in enumerate(work)]
        seq = len(pending)
        submit_t: dict = {}
        finish_t: dict = {}
        submitted: set = set()
        shed_client: dict = {}
        ticks = seen = preemptions = 0
        while True:
            now = clock()
            while pending and pending[0][0] <= now:
                _, _, req, retries = pending.pop(0)
                try:
                    fleet.submit(req)
                    submitted.add(req.request_id)
                    submit_t.setdefault(req.request_id, now)
                    shed_client.pop(req.request_id, None)
                except RequestShed as e:
                    if retries > 0:
                        back = e.retry_after_s * (1.0 + 0.5 * crng.rand())
                        bisect.insort(
                            pending, (now + back, seq, req, retries - 1))
                        seq += 1
                    else:
                        shed_client[req.request_id] = e.reason.value
            busy = fleet.step()
            if ticks % args.train_every == 0 \
                    and trainer.current_step < args.train_steps:
                try:
                    trainer.step_once(_batch_fn)
                except Preemption:
                    # hard kill: restart semantics are a FRESH trainer
                    # on the CURRENT topology, same directory + same
                    # injector (once-consumed faults stay consumed)
                    preemptions += 1
                    trainer = ElasticTrainer(
                        _factory,
                        ElasticPlan.build(trainer.plan.spec,
                                          devices=devices),
                        directory=root + "/day", fault_injector=el_inj,
                        save_every=1, devices=devices,
                        recorder=recorder)
                    trainer.start()
                    controller.trainer = trainer
            controller.tick()
            clock.advance(args.tick_s)
            ticks += 1
            done = fleet.completed
            while seen < len(done):
                finish_t[done[seen].request_id] = clock()
                seen += 1
            if not pending and not busy \
                    and trainer.current_step >= args.train_steps \
                    and not controller.shifting \
                    and controller.outstanding_leases == 0 \
                    and not any(e is not None and (e._queue or e._active)
                                for e in fleet.replicas):
                break
            if ticks >= args.max_ticks:
                break

        responses = {r.request_id: r for r in fleet.completed}
        dup = len(fleet.completed) - len(responses)
        lost = sorted(submitted - set(responses))
        e2e_ok = [finish_t[rid] - submit_t[rid]
                  for rid, rep in responses.items()
                  if rep.finish_reason in ("eos", "length")
                  and rid in finish_t and rid in submit_t]
        attainment = (sum(1 for v in e2e_ok if v <= args.e2e_slo_s)
                      / len(e2e_ok)) if e2e_ok else 0.0

        # the uninterrupted fixed-capacity reference: same anomalies,
        # no preemption, no shifts — the elastic day must match it
        # bitwise at the same step count
        ref = ElasticTrainer(
            _factory, ElasticPlan.build(base, devices=devices),
            directory=root + "/ref",
            fault_injector=_train_injector(args, with_preempt=False),
            save_every=1, devices=devices)
        ref.train(_batch_fn, args.train_steps)
        bitwise = (trainer.current_step >= args.train_steps
                   and trainer.plan.spec.dp == args.base_dp
                   and _bitwise_ok(_flat(trainer), _flat(ref)))

        audit = controller.audit()
        gates = {
            "exactly_once_lost": lost == [],
            "exactly_once_dup": dup == 0,
            "slo_attainment": attainment >= 0.9,
            "train_completed":
                trainer.current_step >= args.train_steps,
            "train_bitwise": bitwise,
            "shift_rollback": controller.stats["rollbacks"] >= 1,
            "shifts_committed": controller.stats["shifts"] >= 2,
            "no_out_of_band_flaps": audit == [],
            "capacity_returned":
                trainer.plan.spec.dp == args.base_dp
                and controller.outstanding_leases == 0,
        }
        return {
            "scenario": "capacity_diurnal",
            "requests": args.requests,
            "submitted": len(submitted),
            "responses": len(responses),
            "lost": lost,
            "duplicated": dup,
            "shed_client": len(shed_client),
            "outcomes": loadgen._outcome_counts(responses,
                                                len(shed_client)),
            "ticks": ticks,
            "virtual_s": clock(),
            "e2e_served": len(e2e_ok),
            "e2e_p50_s": loadgen._pct(e2e_ok, 50),
            "e2e_p99_s": loadgen._pct(e2e_ok, 99),
            "slo_attainment": attainment,
            "migrations": fleet.migrations,
            "preemptions": preemptions,
            "train": {
                "steps": trainer.current_step,
                "final_dp": trainer.plan.spec.dp,
                "anomalies_injected": sum(
                    1 for _, k in el_inj.log if k == "nan_grads"),
            },
            "capacity": {
                "shifts": controller.stats["shifts"],
                "rollbacks": controller.stats["rollbacks"],
                "outstanding_leases": controller.outstanding_leases,
                "split": list(controller.split),
                "last_shift": controller.stats["last_shift"],
                "shift_log": controller.shift_log,
                "audit": audit,
                "serving_fault_log": list(injector.log),
            },
            "flight_snapshots": len(recorder.dumps),
            "gates": gates,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def print_report(report: dict) -> None:
    if report.get("skipped"):
        print(f"day_in_life SKIPPED: {report['skipped']}")
        return
    cap = report["capacity"]
    print(f"day_in_life: {report['responses']}/{report['submitted']} "
          f"answered (lost {len(report['lost'])}, "
          f"dup {report['duplicated']}, "
          f"client-shed {report['shed_client']}) over "
          f"{report['ticks']} ticks / {report['virtual_s']:.1f}s virtual")
    print(f"  outcomes {report['outcomes']}")
    print(f"  slo attainment {report['slo_attainment']:.0%} "
          f"(e2e p50 {report['e2e_p50_s'] * 1e3:.0f} ms, "
          f"p99 {report['e2e_p99_s'] * 1e3:.0f} ms)")
    print(f"  train: {report['train']['steps']} steps, "
          f"final dp={report['train']['final_dp']}, "
          f"{report['preemptions']} preemption(s), "
          f"{report['train']['anomalies_injected']} injected anomalies")
    print(f"  capacity: {cap['shifts']} shift(s) committed, "
          f"{cap['rollbacks']} rollback(s), split {cap['split']}, "
          f"{cap['outstanding_leases']} outstanding lease(s)")
    for e in cap["shift_log"]:
        print(f"    tick {e['tick']:5d} {e['direction']:<12} "
              f"burn {e['burn']:5.2f} -> {e['outcome']}"
              + (f" ({e['reason']})" if e["reason"] else ""))
    print(f"  {report['flight_snapshots']} flight snapshot(s)")
    ok = all(report["gates"].values())
    for name, passed in report["gates"].items():
        print(f"  gate {name:<22} {'PASS' if passed else 'FAIL'}")
    print(f"day_in_life {'OK: all gates pass' if ok else 'FAILED'}")


# -- the autopilot day (ROADMAP item 3: self-driving parallelism) ------------


def autopilot_args(seed: int = 0, requests: int = 240,
                   json_out: bool = False,
                   **overrides) -> argparse.Namespace:
    """Knobs for the ``autopilot_drift`` day: the capacity day's fleet
    + workload shape, with the capacity controller replaced by a
    :class:`~apex_tpu.resilience.autopilot.ParallelismAutopilot` and a
    mid-day interconnect drift schedule."""
    ns = day_args(seed=seed, requests=requests, json_out=json_out)
    ns.scenario = "autopilot_drift"
    # the simulated interconnect: dcn-class alpha-beta coefficients
    # shared by the autopilot's loaded profile and the driver's
    # synthetic step-time model, so detection is honest (refit-driven)
    ns.link_alpha = 2e-3
    ns.link_beta = 1e-9
    ns.serial_s = 0.12
    # drift schedule, in TRAINER steps: links drift_scale x slower
    # mid-morning (=> commit dp 4 -> 2), recover mid-afternoon with an
    # injected plan_regression poisoning the re-adoption's commit gate
    # (=> measured rollback to dp 2)
    ns.drift_step = 6
    ns.recover_step = 22
    ns.drift_scale = 16.0
    ns.regression_scale = 4.0
    # autopilot knobs (cooldown on the VIRTUAL clock)
    ns.drift_threshold = 0.3
    ns.confirm_windows = 2
    ns.min_measurements = 8
    ns.adopt_cooldown_s = 0.5
    ns.gate_steps = 2
    ns.gate_tolerance = 1.2
    for k, v in overrides.items():
        setattr(ns, k, v)
    return ns


_GRAD_BYTES = 8 * 4 * 4 + 4 * 4   # _factory's params: w (8x4 f32) + b


def _drift_dt(step: int, dp: int, args) -> float:
    """Synthetic measured step time under the drift schedule: perfectly
    dp-scalable serial compute + the alpha-beta price of the gradient
    all-reduce at the CURRENTLY drifted link coefficients."""
    from apex_tpu.observability.costmodel import CostFit

    scale = 1.0
    if step >= args.drift_step:
        scale *= args.drift_scale
    if step >= args.recover_step:
        scale /= args.drift_scale
    fit = CostFit(args.link_alpha * scale, args.link_beta * scale)
    comm = fit.predict("psum", _GRAD_BYTES, dp) if dp > 1 else 0.0
    return args.serial_s / dp + comm


def run_autopilot_day(args) -> dict:
    from apex_tpu.observability import (FlightRecorder, MetricsRegistry,
                                        Tracer)
    from apex_tpu.observability.costmodel import (
        fit_cost_model, simulate_link_measurements)
    from apex_tpu.observability.slo import SLOMonitor, SLOTarget
    from apex_tpu.resilience import (ElasticPlan, ElasticTrainer, Fault,
                                     FaultInjector, ParallelismAutopilot,
                                     TopologySpec)
    from apex_tpu.serving import (FleetRouter, PagedInferenceEngine,
                                  RequestShed, TickScheduler, VirtualClock)
    from apex_tpu.utils.profiling import ServingMetrics

    if jax.device_count() < args.base_dp:
        return {"skipped": f"needs >= {args.base_dp} devices "
                           f"(have {jax.device_count()}); set XLA_FLAGS="
                           "--xla_force_host_platform_device_count=4",
                "gates": {}}

    clock = VirtualClock()
    recorder = FlightRecorder(clock=clock)
    registry = MetricsRegistry()
    devices = jax.devices()[:args.base_dp]

    model, params = loadgen._build_model(args)
    replicas = loadgen._build_replicas(args, model, params, clock)
    fleet = FleetRouter(
        replicas, clock=clock,
        max_queue_depth=args.max_queue_depth,
        burn_threshold=args.burn_threshold,
        burn_window_s=args.burn_window_s,
        retry_budget=args.retry_budget,
        hedge_after_s=args.hedge_after_s,
        seed=args.seed, tracer=Tracer(clock=clock, id_tag="router"),
        recorder=recorder)

    profile = fit_cost_model(
        simulate_link_measurements(args.link_alpha, args.link_beta,
                                   link_class="dcn", ops=("psum",))
        + simulate_link_measurements(1e-6, 1e-10, link_class="ici",
                                     ops=("psum",)),
        meta={"source": "autopilot_day"})
    inj = FaultInjector([
        Fault(args.drift_step, "cost_drift",
              magnitude=args.drift_scale),
        Fault(args.recover_step, "cost_drift",
              magnitude=1.0 / args.drift_scale),
        Fault(args.recover_step, "plan_regression",
              magnitude=args.regression_scale)])

    root = tempfile.mkdtemp(prefix="apex_tpu_autopilot_day_")
    try:
        base = TopologySpec(dp=args.base_dp)
        trainer = ElasticTrainer(
            _factory, ElasticPlan.build(base, devices=devices),
            directory=root + "/day", fault_injector=inj,
            save_every=1, devices=devices, recorder=recorder)
        autopilot = ParallelismAutopilot(
            trainer, profile, min_dp=args.min_train_dp,
            link_class="dcn", drift_threshold=args.drift_threshold,
            confirm_windows=args.confirm_windows,
            min_measurements=args.min_measurements,
            cooldown_s=args.adopt_cooldown_s,
            gate_steps=args.gate_steps,
            gate_tolerance=args.gate_tolerance,
            injector=inj, registry=registry, recorder=recorder,
            tracer=Tracer(clock=clock, id_tag="autopilot"),
            clock=clock)

        work = loadgen.synthesize_scenario(args)
        crng = np.random.RandomState(args.seed + 1)
        pending = [(t, i, req, int(args.client_retries))
                   for i, (t, req) in enumerate(work)]
        seq = len(pending)
        submit_t: dict = {}
        finish_t: dict = {}
        submitted: set = set()
        shed_client: dict = {}
        ticks = seen = 0
        while True:
            now = clock()
            while pending and pending[0][0] <= now:
                _, _, req, retries = pending.pop(0)
                try:
                    fleet.submit(req)
                    submitted.add(req.request_id)
                    submit_t.setdefault(req.request_id, now)
                    shed_client.pop(req.request_id, None)
                except RequestShed as e:
                    if retries > 0:
                        back = e.retry_after_s * (1.0 + 0.5 * crng.rand())
                        bisect.insort(
                            pending, (now + back, seq, req, retries - 1))
                        seq += 1
                    else:
                        shed_client[req.request_id] = e.reason.value
            busy = fleet.step()
            if ticks % args.train_every == 0 \
                    and trainer.current_step < args.train_steps:
                step = trainer.current_step
                trainer.step_once(_batch_fn)
                autopilot.record_step(
                    _drift_dt(step, trainer.plan.spec.dp, args))
                autopilot.tick()
                autopilot.tick()
            clock.advance(args.tick_s)
            ticks += 1
            done = fleet.completed
            while seen < len(done):
                finish_t[done[seen].request_id] = clock()
                seen += 1
            if not pending and not busy \
                    and trainer.current_step >= args.train_steps \
                    and not autopilot.adopting \
                    and not any(e is not None and (e._queue or e._active)
                                for e in fleet.replicas):
                break
            if ticks >= args.max_ticks:
                break

        responses = {r.request_id: r for r in fleet.completed}
        dup = len(fleet.completed) - len(responses)
        lost = sorted(submitted - set(responses))
        e2e_ok = [finish_t[rid] - submit_t[rid]
                  for rid, rep in responses.items()
                  if rep.finish_reason in ("eos", "length")
                  and rid in finish_t and rid in submit_t]
        attainment = (sum(1 for v in e2e_ok if v <= args.e2e_slo_s)
                      / len(e2e_ok)) if e2e_ok else 0.0

        # the full cycle must leave training bit-identical to a run
        # that never drifted: same batches, fixed plan, no autopilot
        ref = ElasticTrainer(
            _factory, ElasticPlan.build(base, devices=devices),
            directory=root + "/ref", save_every=1, devices=devices)
        ref.train(_batch_fn, args.train_steps)
        bitwise = (trainer.current_step >= args.train_steps
                   and _bitwise_ok(_flat(trainer), _flat(ref)))

        audit = autopilot.audit()
        drifts = sum(1 for _, k in inj.log if k == "cost_drift")
        regressions = sum(1 for _, k in inj.log
                          if k == "plan_regression")
        commits = registry.get("autopilot_adoptions_total").value(
            outcome="commit")
        rollbacks = registry.get("autopilot_adoptions_total").value(
            outcome="rollback")
        gates = {
            "exactly_once_lost": lost == [],
            "exactly_once_dup": dup == 0,
            "slo_attainment": attainment >= 0.9,
            "train_completed":
                trainer.current_step >= args.train_steps,
            "train_bitwise": bitwise,
            "adoption_committed": autopilot.stats["adoptions"] >= 1,
            "regression_rolled_back":
                autopilot.stats["rollbacks"] >= 1,
            "no_out_of_band_flaps": audit == [],
            "counters_match_faults":
                commits + rollbacks == drifts
                and rollbacks == regressions
                and autopilot.queued == 0,
        }
        return {
            "scenario": "autopilot_drift",
            "requests": args.requests,
            "submitted": len(submitted),
            "responses": len(responses),
            "lost": lost,
            "duplicated": dup,
            "shed_client": len(shed_client),
            "outcomes": loadgen._outcome_counts(responses,
                                                len(shed_client)),
            "ticks": ticks,
            "virtual_s": clock(),
            "e2e_served": len(e2e_ok),
            "e2e_p50_s": loadgen._pct(e2e_ok, 50),
            "e2e_p99_s": loadgen._pct(e2e_ok, 99),
            "slo_attainment": attainment,
            "migrations": fleet.migrations,
            "train": {
                "steps": trainer.current_step,
                "final_dp": trainer.plan.spec.dp,
            },
            "autopilot": {
                "refits": autopilot.stats["refits"],
                "drift_confirmed": autopilot.stats["drift_confirmed"],
                "adoptions": autopilot.stats["adoptions"],
                "rollbacks": autopilot.stats["rollbacks"],
                "no_change": autopilot.stats["no_change"],
                "last_drift": autopilot.stats["last_drift"],
                "last_adoption": autopilot.stats["last_adoption"],
                "adoption_log": autopilot.adoption_log,
                "audit": audit,
                "fault_log": list(inj.log),
            },
            "flight_snapshots": len(recorder.dumps),
            "gates": gates,
        }
    finally:
        shutil.rmtree(root, ignore_errors=True)


def print_autopilot_report(report: dict) -> None:
    if report.get("skipped"):
        print(f"autopilot_day SKIPPED: {report['skipped']}")
        return
    ap = report["autopilot"]
    print(f"autopilot_day: {report['responses']}/{report['submitted']} "
          f"answered (lost {len(report['lost'])}, "
          f"dup {report['duplicated']}, "
          f"client-shed {report['shed_client']}) over "
          f"{report['ticks']} ticks / {report['virtual_s']:.1f}s virtual")
    print(f"  outcomes {report['outcomes']}")
    print(f"  slo attainment {report['slo_attainment']:.0%} "
          f"(e2e p50 {report['e2e_p50_s'] * 1e3:.0f} ms, "
          f"p99 {report['e2e_p99_s'] * 1e3:.0f} ms)")
    print(f"  train: {report['train']['steps']} steps, "
          f"final dp={report['train']['final_dp']}")
    print(f"  autopilot: {ap['refits']} refit windows, "
          f"{ap['drift_confirmed']} drift confirmation(s), "
          f"{ap['adoptions']} commit(s), {ap['rollbacks']} rollback(s)")
    for e in ap["adoption_log"]:
        print(f"    tick {e['tick']:5d} {e['old']} -> {e['new']}: "
              f"{e['outcome']}"
              + (f" ({e['reason']})" if e["reason"] else ""))
    print(f"  faults applied: {ap['fault_log']}")
    print(f"  {report['flight_snapshots']} flight snapshot(s)")
    ok = all(report["gates"].values())
    for name, passed in report["gates"].items():
        print(f"  gate {name:<22} {'PASS' if passed else 'FAIL'}")
    print(f"autopilot_day {'OK: all gates pass' if ok else 'FAILED'}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--requests", type=int, default=140)
    ap.add_argument("--train-steps", type=int, default=40)
    ap.add_argument("--max-ticks", type=int, default=4000)
    ap.add_argument("--autopilot", action="store_true",
                    help="run the autopilot_drift day (self-driving "
                         "parallelism) instead of the capacity day")
    ap.add_argument("--json", action="store_true")
    a = ap.parse_args(argv)
    if a.autopilot:
        report = run_autopilot_day(autopilot_args(
            seed=a.seed, requests=a.requests, json_out=a.json,
            train_steps=a.train_steps, max_ticks=a.max_ticks))
    else:
        report = run_day(day_args(seed=a.seed, requests=a.requests,
                                  json_out=a.json,
                                  train_steps=a.train_steps,
                                  max_ticks=a.max_ticks))
    if a.json:
        print(json.dumps(report, indent=2))
    elif a.autopilot:
        print_autopilot_report(report)
    else:
        print_report(report)
    return 0 if report["gates"] and all(report["gates"].values()) else 1


if __name__ == "__main__":
    sys.exit(main())
