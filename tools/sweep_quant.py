#!/usr/bin/env python
"""On-chip dequant-GEMM tuning sweep (ISSUE 18).

Times the Pallas int8 dequantize-then-matmul kernel across
``(block_n, block_k)`` tilings at the decode GEMM shapes (small token
batch against each dense weight of the serving configs), and reports
the achieved HBM bytes/s against a calibrated streaming roofline — at
decode batch sizes the GEMM is weight-bandwidth-bound, so bytes/s vs
the measured copy ceiling says how close each tiling gets to the win
the int8 weights bought.  Measured rows feed the kernel's
``block_n``/``block_k`` defaults (mirror of ``tools/sweep_ffn.py``).

Usage: python tools/sweep_quant.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from _timing import time_steps as _time  # noqa: E402 (sets sys.path)

from apex_tpu.ops.quant_gemm import (quant_gemm,              # noqa: E402
                                     quantize_weight)


def calibrate_copy_bytes(nbytes: int = 64 * 1024 * 1024) -> float:
    """Measured streaming bytes/s: a device-wide f32 copy (read +
    write), the same ceiling the dequant-GEMM's weight stream is
    bounded by.  A measured constant, not a spec-sheet number."""
    x = jnp.zeros(nbytes // 4, jnp.float32)
    f = jax.jit(lambda a: a + 1.0)
    dt = _time(f, (x,))
    return 2 * x.nbytes / dt


def gemm_bytes(m: int, n: int, k: int, act_itemsize: int) -> int:
    """HBM traffic of one dequant-GEMM call: int8 weight + f32 scale
    stream, activation read, f32 output write."""
    return n * k + n * 4 + m * k * act_itemsize + m * n * 4


def main():
    rng = np.random.RandomState(0)
    ceiling = calibrate_copy_bytes()
    print(f"calibrated copy roofline: {ceiling / 1e9:8.2f} GB/s",
          flush=True)
    # (label, m, n, k) — decode-batch GEMMs of the serving configs:
    # qkv/fc1 (3h x h / 4h x h), fc2 (h x 4h), lm head (vocab x h)
    shapes = [("qkv_1k", 8, 3 * 1024, 1024),
              ("fc1_1k", 8, 4 * 1024, 1024),
              ("fc2_1k", 8, 1024, 4 * 1024),
              ("head_32k", 8, 32768, 1024),
              ("fc1_2k_b32", 32, 8192, 2048)]
    blocks = [(256, 256), (256, 512), (512, 512), (512, 1024),
              (1024, 512), (1024, 1024)]
    for label, m, n, k in shapes:
        x = jnp.asarray(rng.randn(m, k), jnp.bfloat16)
        w8, scale = quantize_weight(
            jnp.asarray(rng.randn(n, k) * 0.02, jnp.float32))
        nbytes = gemm_bytes(m, n, k, x.dtype.itemsize)
        for bn, bk in blocks:
            if bn > n or bk > k:
                continue
            f = jax.jit(lambda x, w8, s, _bn=bn, _bk=bk:
                        quant_gemm(x, w8, s, block_n=_bn, block_k=_bk))
            try:
                dt = _time(f, (x, w8, scale))
                bps = nbytes / dt
                print(f"{label} m={m} n={n} k={k} blocks=({bn},{bk}): "
                      f"{dt * 1e6:8.1f} us  {bps / 1e9:7.2f} GB/s "
                      f"({bps / ceiling:5.1%} of roofline)", flush=True)
            except Exception as e:
                print(f"{label} m={m} n={n} k={k} blocks=({bn},{bk}): "
                      f"FAILED {str(e).splitlines()[0][:100]}",
                      flush=True)
        jax.clear_caches()


if __name__ == "__main__":
    main()
