#!/usr/bin/env python
"""Kill-matrix sweep for the resilience stack (ISSUE 4 satellite).

``tests/test_resilience.py`` and the ``__graft_entry__`` dryrun prove
kill-and-resume parity at ONE kill step; this tool sweeps the full
matrix — every kill step x every fault kind — and prints one PASS/FAIL
cell per combination:

* ``preempt``           — :class:`Preemption` raised before the kill
  step runs; a fresh manager restores the latest complete checkpoint
  and the resumed run must match the uninterrupted run BITWISE (f32
  params and optimizer slots) after ``--steps`` total steps.
* ``corrupt``           — same preemption, but the latest checkpoint's
  payload is also torn post-commit; restore must detect the sha256
  mismatch, fall back one step, and the resumed run (replaying the
  lost step) must STILL be bitwise identical.
* ``nan`` / ``inf`` / ``spike`` — the anomaly fires AT the kill step
  instead of a preemption; the guard must skip exactly that one update
  (optimizer state stays consistent) and the run must finish with
  finite parameters.

Runs on the fake 8-device CPU mesh by default (same two-lane contract
as ``tests/conftest.py``); ``APEX_TPU_ON_CHIP=1`` leaves the real
backend in place.  ``--sp`` adds the dp=2 x tp=2 sequence-parallel GPT
component next to the default dp=2 data-parallel one; ``--pp`` adds the
ring-pipeline components — dp=2 x pp=2 and tp2 x pp=2 + SP — whose
grad_fn is the 1F1B ``pipeline_step`` scan under shard_map.

``--topology`` sweeps the ELASTIC kill-step x topology matrix instead
(ISSUE 9): each cell schedules a ``topology_change`` at the kill step
(the pod shrinks; the step runs on the new plan) and a hard
``preempt_at_step`` one step later, then restarts a fresh
:class:`~apex_tpu.resilience.elastic.ElasticTrainer` on the cell's
restart topology — restoring the shrunken-topology checkpoint,
re-sharding, and finishing.  Transitions and what each asserts:

* ``dp8->dp4->dp8``    per-leaf FusedAdam, replicated batch, no
  collectives: gradient math is topology-invariant, so params AND
  every optimizer slot must match the uninterrupted run BITWISE.
* ``zero4->zero2->zero4``  ZeRO (DistributedFusedAdam) reduce-scatter
  shards re-partitioned across the world-size change: the LOGICAL f32
  moments/master weights must match BITWISE (the packed padding moves;
  the values may not).  World sizes pinned to {2, 4}: XLA CPU's
  reduction of identical per-replica copies is pairwise-exact up to 4
  participants but not at 8 (measured), so an 8-way ZeRO transition is
  trajectory-equivalent, not bitwise, on this backend.
* ``dp2xtp2+sp->dp4``  the TP dimension collapses into dp; TP grads
  differ from serial at rounding level (~1e-7), so this cell is the
  documented TRAJECTORY-EQUIVALENT one: unpacked serial params must
  be allclose, not bitwise.
* ``dp2xpp2->dp4->dp2xpp2``  pipeline on -> off -> on via
  ``pipeline_step`` at pp=2 and pp=1 (pp=1 is the bitwise reference
  schedule), replicated batch: BITWISE.

Usage::

    python tools/crash_matrix.py [--steps 5] [--sp] [--pp] [--topology]
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
import warnings

# env must be set before jax initializes (see tests/conftest.py)
ON_CHIP = os.environ.get("APEX_TPU_ON_CHIP") == "1"
if not ON_CHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if not ON_CHIP:
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from apex_tpu.models.gpt import (GPTConfig, GPTModel,  # noqa: E402
                                 pack_for_shard_map)
from apex_tpu.optimizers import FusedAdam  # noqa: E402
from apex_tpu.resilience import (CheckpointManager,  # noqa: E402
                                 CheckpointNotFound, Fault, FaultInjector,
                                 GuardedTrainStep, Preemption)
from apex_tpu.utils.collectives import shard_map_compat  # noqa: E402

ANOMALY_KINDS = {"nan": "nan_grads", "inf": "inf_loss",
                 "spike": "grad_spike"}


def _tree_bitwise(a, b) -> bool:
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b), strict=True):
        if not np.array_equal(np.asarray(x), np.asarray(y)):
            return False
    return True


def _drive(guard, params, opt_state, gstate, batch_fn, n_steps,
           start=0):
    step = start
    while step < n_steps:
        x, y = batch_fn(step)
        res = guard(params, opt_state, gstate, x, y, step=step)
        params, opt_state, gstate = (res.params, res.opt_state,
                                     res.guard_state)
        step = res.next_step
        guard.save(step, params, opt_state, gstate)
    return params, opt_state


def _run_cell(make_parts, batch_fn, n_steps, kill_at, fault, ref):
    """One matrix cell; returns (ok, detail)."""
    root = tempfile.mkdtemp(prefix="apex_tpu_crash_")
    try:
        if fault in ANOMALY_KINDS:
            # anomaly at kill_at: no restart — the guard must skip
            # exactly that one update and the run must end finite
            inj = FaultInjector([Fault(step=kill_at,
                                       kind=ANOMALY_KINDS[fault],
                                       magnitude=1e6)])
            guard, params, opt_state, gstate = make_parts(root, inj)
            got_p, _ = _drive(guard, params, opt_state, gstate,
                              batch_fn, n_steps)
            if guard.counters["skipped"] != 1:
                return False, f"skipped={guard.counters['skipped']}"
            for leaf in jax.tree_util.tree_leaves(got_p):
                if not np.all(np.isfinite(np.asarray(leaf))):
                    return False, "non-finite params leaked through"
            return True, f"skipped@{kill_at}"

        faults = [Fault(step=kill_at, kind="preempt_at_step")]
        if fault == "corrupt":
            # tear the last checkpoint that commits before the kill
            faults.append(Fault(step=kill_at, kind="corrupt_checkpoint"))
        inj = FaultInjector(faults)
        guard, params, opt_state, gstate = make_parts(root, inj)
        try:
            _drive(guard, params, opt_state, gstate, batch_fn, n_steps)
            return False, "preemption did not fire"
        except Preemption:
            pass

        # fresh restart: only the checkpoint directory survives
        guard2, p0, o0, g0 = make_parts(root, None)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")  # corruption noise
                restored, ck_step = guard2.checkpoint.restore(
                    guard2._template(p0, o0, g0, None))
            start = int(np.asarray(restored["step"]))
            p, o, g = (restored["params"], restored["opt"],
                       restored["guard"])
        except CheckpointNotFound:
            # every candidate torn (corrupt at kill@1): start over —
            # the init state is deterministic, so parity must still hold
            ck_step, start, p, o, g = 0, 0, p0, o0, g0
        expect = kill_at - 1 if fault == "corrupt" else kill_at
        if ck_step != expect:
            return False, f"resumed@{ck_step}, expected {expect}"
        got_p, got_o = _drive(guard2, p, o, g, batch_fn, n_steps,
                              start=start)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    if not _tree_bitwise(got_p, ref[0]):
        return False, "params diverged"
    if not _tree_bitwise(got_o, ref[1]):
        return False, "opt slots diverged"
    return True, f"resume@{ck_step} bitwise"


def _component_dp2():
    mesh = jax.make_mesh((2,), ("data",), devices=jax.devices()[:2])

    def loss_fn(p, x, y):
        return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

    def body(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        return (jax.lax.pmean(loss, "data"),
                jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, "data"), g))

    grad_fn = shard_map_compat(body, mesh=mesh,
                               in_specs=(P(), P("data"), P("data")),
                               out_specs=(P(), P()))

    def make_parts(ckpt_dir, injector):
        opt = FusedAdam(lr=1e-2)
        guard = GuardedTrainStep(
            grad_fn=grad_fn, optimizer=opt, warmup_steps=1,
            checkpoint=CheckpointManager(ckpt_dir, keep=3,
                                         fault_injector=injector),
            fault_injector=injector)
        r = np.random.RandomState(0)
        rep = NamedSharding(mesh, P())
        params = jax.device_put(
            {"w": jnp.asarray(r.randn(8, 4).astype(np.float32)),
             "b": jnp.zeros((4,), jnp.float32)}, rep)
        return (guard, params, jax.device_put(opt.init(params), rep),
                jax.device_put(guard.init_state(), rep))

    def batch_fn(step):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randn(8, 8).astype(np.float32)),
                jnp.asarray(r.randn(8, 4).astype(np.float32)))

    return make_parts, batch_fn


def _component_dp2tp2_sp():
    kw = dict(vocab_size=32, hidden_size=16, num_layers=2,
              num_attention_heads=4, max_seq_len=8)
    par = GPTModel(GPTConfig(tensor_parallel_size=2, axis_name="model",
                             sequence_parallel=True, **kw))
    init = GPTModel(GPTConfig(**kw)).init_params(jax.random.PRNGKey(9))
    mesh = jax.make_mesh((2, 2), ("data", "model"),
                         devices=jax.devices()[:4])
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(par, init)

    def body(sp, tk, tg):
        loss, g = jax.value_and_grad(par.loss)(local_fn(sp), tk, tg)
        return (jax.lax.pmean(loss, "data"),
                jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, "data"), repack_fn(g)))

    grad_fn = shard_map_compat(body, mesh=mesh,
                               in_specs=(in_specs, P("data"), P("data")),
                               out_specs=(P(), in_specs))

    def make_parts(ckpt_dir, injector):
        opt = FusedAdam(lr=1e-2)
        guard = GuardedTrainStep(
            grad_fn=grad_fn, optimizer=opt, warmup_steps=1,
            checkpoint=CheckpointManager(ckpt_dir, keep=3,
                                         fault_injector=injector),
            fault_injector=injector)
        rep = NamedSharding(mesh, P())
        p = jax.device_put(packed, rep)
        return (guard, p, jax.device_put(opt.init(p), rep),
                jax.device_put(guard.init_state(), rep))

    def batch_fn(step):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randint(0, 32, (4, 8))),
                jnp.asarray(r.randint(0, 32, (4, 8))))

    return make_parts, batch_fn


def _component_dp2pp2():
    from apex_tpu.models.gpt import pipeline_step

    model = GPTModel(GPTConfig(vocab_size=32, hidden_size=16,
                               num_layers=2, num_attention_heads=4,
                               max_seq_len=8))
    init = model.init_params(jax.random.PRNGKey(7))
    mesh = jax.make_mesh((2, 2), ("data", "pipe"),
                         devices=jax.devices()[:4])
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
        model, init, n_stages=2, tensor_axis=None)
    M, mb, seq = 2, 2, 8

    def body(sp, tk, tg):
        # pipeline_step reduces loss/grads over data_axis itself
        loss, g = pipeline_step(model, local_fn(sp),
                                tk.reshape(M, mb, seq),
                                tg.reshape(M, mb, seq),
                                pipe_axis="pipe", data_axis="data")
        return loss, repack_fn(g)

    grad_fn = shard_map_compat(body, mesh=mesh,
                               in_specs=(in_specs, P("data"), P("data")),
                               out_specs=(P(), in_specs))

    def make_parts(ckpt_dir, injector):
        opt = FusedAdam(lr=1e-2)
        guard = GuardedTrainStep(
            grad_fn=grad_fn, optimizer=opt, warmup_steps=1,
            checkpoint=CheckpointManager(ckpt_dir, keep=3,
                                         fault_injector=injector),
            fault_injector=injector)
        rep = NamedSharding(mesh, P())
        p = jax.device_put(packed, rep)
        return (guard, p, jax.device_put(opt.init(p), rep),
                jax.device_put(guard.init_state(), rep))

    def batch_fn(step):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randint(0, 32, (2 * M * mb, seq))),
                jnp.asarray(r.randint(0, 32, (2 * M * mb, seq))))

    return make_parts, batch_fn


def _component_tp2pp2_sp():
    from apex_tpu.models.gpt import pipeline_step

    kw = dict(vocab_size=32, hidden_size=16, num_layers=2,
              num_attention_heads=4, max_seq_len=8)
    # the ring pipeline's TP composition requires sequence parallelism
    par = GPTModel(GPTConfig(tensor_parallel_size=2, axis_name="model",
                             sequence_parallel=True, **kw))
    init = GPTModel(GPTConfig(**kw)).init_params(jax.random.PRNGKey(9))
    mesh = jax.make_mesh((2, 2), ("model", "pipe"),
                         devices=jax.devices()[:4])
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
        par, init, n_stages=2, tensor_axis="model")
    M, mb, seq = 2, 2, 8

    def body(sp, tk, tg):
        loss, g = pipeline_step(par, local_fn(sp),
                                tk.reshape(M, mb, seq),
                                tg.reshape(M, mb, seq),
                                pipe_axis="pipe")
        return loss, repack_fn(g)

    grad_fn = shard_map_compat(body, mesh=mesh,
                               in_specs=(in_specs, P(), P()),
                               out_specs=(P(), in_specs))

    def make_parts(ckpt_dir, injector):
        opt = FusedAdam(lr=1e-2)
        guard = GuardedTrainStep(
            grad_fn=grad_fn, optimizer=opt, warmup_steps=1,
            checkpoint=CheckpointManager(ckpt_dir, keep=3,
                                         fault_injector=injector),
            fault_injector=injector)
        rep = NamedSharding(mesh, P())
        p = jax.device_put(packed, rep)
        return (guard, p, jax.device_put(opt.init(p), rep),
                jax.device_put(guard.init_state(), rep))

    def batch_fn(step):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randint(0, 32, (M * mb, seq))),
                jnp.asarray(r.randint(0, 32, (M * mb, seq))))

    return make_parts, batch_fn


# -- elastic topology matrix (ISSUE 9) ---------------------------------------

def _toggle_trainer(shrink_spec):
    """An :class:`ElasticTrainer` whose injected ``topology_change``
    toggles base <-> the cell's shrink spec (the stock auto-toggle only
    moves dp; these cells also move tp/pp/zero)."""
    from apex_tpu.resilience import ElasticTrainer

    class _Toggle(ElasticTrainer):
        def _auto_spec(self, magnitude):
            return (shrink_spec if self.plan.spec == self._base_spec
                    else self._base_spec)

    return _Toggle


def _flat_state(trainer):
    """Params + per-leaf optimizer slots, flattened deterministically."""
    out = list(jax.tree_util.tree_leaves(trainer.params))
    st = trainer.opt_state
    for key in sorted(st["buckets"]):
        for slot in sorted(st["buckets"][key]):
            v = st["buckets"][key][slot]
            out.extend(v if isinstance(v, list) else [v])
    return [np.asarray(x) for x in out]


def _topo_component_dp8():
    """dp=8 -> dp=4 -> dp=8, per-leaf FusedAdam: bitwise."""
    from apex_tpu.resilience import ElasticComponents, TopologySpec

    base, shrink = TopologySpec(dp=8), TopologySpec(dp=4)

    def loss_fn(p, x, y):
        return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

    def factory(plan, ckpt, inj):
        opt = FusedAdam(lr=1e-2)
        guard = GuardedTrainStep(loss_fn, opt, warmup_steps=1,
                                 checkpoint=ckpt, fault_injector=inj)
        r = np.random.RandomState(0)
        params = plan.put(
            {"w": jnp.asarray(r.randn(8, 4).astype(np.float32)),
             "b": jnp.zeros((4,), jnp.float32)})
        return ElasticComponents(guard, params, opt.init(params),
                                 guard.init_state())

    def batch_fn(step, plan):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randn(8, 8).astype(np.float32)),
                jnp.asarray(r.randn(8, 4).astype(np.float32)))

    return dict(base=base, shrink=shrink, restart=base, factory=factory,
                batch_fn=batch_fn, canon=_flat_state,
                compare="bitwise", n_dev=8)


def _topo_component_zero():
    """ZeRO dp=4/ws=4 -> dp=2/ws=2 -> dp=4/ws=4: logical state bitwise."""
    from apex_tpu.multi_tensor_apply import bucketing as B
    from apex_tpu.parallel import DistributedFusedAdam
    from apex_tpu.resilience import (ElasticComponents, TopologySpec,
                                     ZeROGuardAdapter)

    base = TopologySpec(dp=4, zero_shard=4)
    shrink = TopologySpec(dp=2, zero_shard=2)

    def loss_fn(p, x, y):
        return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))

    def _params(plan):
        r = np.random.RandomState(1)
        return plan.put(
            {"w": jnp.asarray((r.randn(8, 4) * 0.1).astype(np.float32)),
             "b": jnp.zeros((4,), jnp.float32)})

    def factory(plan, ckpt, inj):
        inner = DistributedFusedAdam(lr=1e-2,
                                     world_size=plan.spec.zero_shard,
                                     axis_name="data", block_rows=8)
        adapter = ZeROGuardAdapter(inner, plan.mesh)
        guard = GuardedTrainStep(loss_fn, adapter, warmup_steps=1,
                                 checkpoint=ckpt, fault_injector=inj)
        params = _params(plan)
        return ElasticComponents(guard, params, adapter.init(params),
                                 guard.init_state(), optimizer=inner)

    def batch_fn(step, plan):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randn(8, 8).astype(np.float32)),
                jnp.asarray(r.randn(8, 4).astype(np.float32)))

    def canon(trainer):
        # compare LOGICAL leaves: the packed padding depends on the
        # world size, the values must not
        opt = DistributedFusedAdam(lr=1e-2, world_size=base.zero_shard,
                                   axis_name="data", block_rows=8)
        lay = opt._layout(trainer.params)
        out = [np.asarray(x)
               for x in jax.tree_util.tree_leaves(trainer.params)]
        st = trainer.opt_state
        for info in lay.buckets:
            for slot in sorted(st["buckets"][info.key]):
                arr = jnp.asarray(np.asarray(st["buckets"][info.key][slot]))
                out.extend(np.asarray(x) for x in B.unflatten_bucket(
                    arr, info.meta._replace(dtype=jnp.float32)))
        return out

    return dict(base=base, shrink=shrink, restart=base, factory=factory,
                batch_fn=batch_fn, canon=canon, compare="bitwise", n_dev=4)


def _topo_component_tp_collapse():
    """dp=2 x tp=2 + SP -> dp=4 serial: trajectory-equivalent.

    TP matmul partial sums round differently from the serial product
    (~1e-7 per step), so after the collapse the run tracks the
    uninterrupted dp2xtp2 reference to allclose tolerance, not bitwise
    — the documented data-order/reduction-order cell of the matrix.
    """
    from apex_tpu.models.gpt import unpack_from_shard_map
    from apex_tpu.resilience import ElasticComponents, TopologySpec

    kw = dict(vocab_size=32, hidden_size=16, num_layers=2,
              num_attention_heads=4, max_seq_len=8)
    serial = GPTModel(GPTConfig(**kw))
    par = GPTModel(GPTConfig(tensor_parallel_size=2, axis_name="model",
                             sequence_parallel=True, **kw))
    init = serial.init_params(jax.random.PRNGKey(9))
    base = TopologySpec(dp=2, tp=2, sequence_parallel=True)
    shrink = TopologySpec(dp=4)

    def factory(plan, ckpt, inj):
        opt = FusedAdam(lr=1e-2)
        if plan.spec.tp == 2:
            packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
                par, init)

            def body(sp, tk, tg):
                loss, g = jax.value_and_grad(par.loss)(local_fn(sp),
                                                       tk, tg)
                return (jax.lax.pmean(loss, "data"),
                        jax.tree_util.tree_map(
                            lambda a: jax.lax.pmean(a, "data"),
                            repack_fn(g)))

            grad_fn = shard_map_compat(
                body, mesh=plan.mesh,
                in_specs=(in_specs, P("data"), P("data")),
                out_specs=(P(), in_specs))
            params = plan.put(packed)
            transform = None          # the cell never grows back to tp=2
        else:
            def body(p, tk, tg):
                loss, g = jax.value_and_grad(serial.loss)(p, tk, tg)
                return (jax.lax.pmean(loss, "data"),
                        jax.tree_util.tree_map(
                            lambda a: jax.lax.pmean(a, "data"), g))

            grad_fn = shard_map_compat(
                body, mesh=plan.mesh,
                in_specs=(P(), P("data"), P("data")),
                out_specs=(P(), P()))
            params = plan.put(init)

            def transform(tree, old_plan):
                if old_plan.spec.tp == 2:
                    return unpack_from_shard_map(par, tree)
                return tree

        guard = GuardedTrainStep(grad_fn=grad_fn, optimizer=opt,
                                 warmup_steps=1, checkpoint=ckpt,
                                 fault_injector=inj)
        return ElasticComponents(guard, params, opt.init(params),
                                 guard.init_state(), transform=transform)

    def batch_fn(step, plan):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randint(0, 32, (4, 8))),
                jnp.asarray(r.randint(0, 32, (4, 8))))

    def canon(trainer):
        p = trainer.params
        if trainer.plan.spec.tp == 2:
            p = unpack_from_shard_map(par, p)
        return [np.asarray(x) for x in jax.tree_util.tree_leaves(p)]

    return dict(base=base, shrink=shrink, restart=shrink, factory=factory,
                batch_fn=batch_fn, canon=canon, compare="allclose",
                n_dev=4)


def _topo_component_pp_toggle():
    """dp=2 x pp=2 -> dp=4 (pp off) -> dp=2 x pp=2: bitwise.

    Both plans run :func:`pipeline_step` — at pp=1 it is the bitwise
    reference schedule for pp=2 (PR 6 contract) — on a batch
    REPLICATED over the data axis, so the pmean folds identical copies
    (exact at 2 and 4 participants) and the whole cycle stays bitwise.
    """
    from apex_tpu.models.gpt import pipeline_step, unpack_from_shard_map
    from apex_tpu.resilience import ElasticComponents, TopologySpec

    model = GPTModel(GPTConfig(vocab_size=32, hidden_size=16,
                               num_layers=2, num_attention_heads=4,
                               max_seq_len=8))
    init = model.init_params(jax.random.PRNGKey(7))
    base = TopologySpec(dp=2, pp=2)
    shrink = TopologySpec(dp=4)
    M, mb, seq = 2, 2, 8

    def factory(plan, ckpt, inj):
        pp = plan.spec.pp
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            model, init, n_stages=pp, tensor_axis=None)

        def body(sp, tk, tg):
            loss, g = pipeline_step(model, local_fn(sp),
                                    tk.reshape(M, mb, seq),
                                    tg.reshape(M, mb, seq),
                                    pipe_axis="pipe", data_axis="data")
            return loss, repack_fn(g)

        grad_fn = shard_map_compat(body, mesh=plan.mesh,
                                   in_specs=(in_specs, P(), P()),
                                   out_specs=(P(), in_specs))

        def transform(tree, old_plan):
            serial = unpack_from_shard_map(model, tree,
                                           n_stages=old_plan.spec.pp)
            return pack_for_shard_map(model, serial, n_stages=pp,
                                      tensor_axis=None)[0]

        opt = FusedAdam(lr=1e-2)
        guard = GuardedTrainStep(grad_fn=grad_fn, optimizer=opt,
                                 warmup_steps=1, checkpoint=ckpt,
                                 fault_injector=inj)
        params = plan.put(packed)
        return ElasticComponents(guard, params, opt.init(params),
                                 guard.init_state(), transform=transform)

    def batch_fn(step, plan):
        r = np.random.RandomState(50_000 + step)
        return (jnp.asarray(r.randint(0, 32, (M * mb, seq))),
                jnp.asarray(r.randint(0, 32, (M * mb, seq))))

    return dict(base=base, shrink=shrink, restart=base, factory=factory,
                batch_fn=batch_fn, canon=_flat_state, compare="bitwise",
                n_dev=4)


def _topo_cell(comp, kill_at, steps, ref_canon):
    """One elastic matrix cell: shrink@kill_at, hard kill one step
    later, restart on the cell's restart topology, compare against the
    uninterrupted reference.  Returns (ok, detail)."""
    from apex_tpu.resilience import ElasticPlan, ElasticTrainer

    root = tempfile.mkdtemp(prefix="apex_tpu_topo_")
    try:
        inj = FaultInjector([
            Fault(kill_at, "topology_change"),
            Fault(kill_at + 1, "preempt_at_step")])
        Toggle = _toggle_trainer(comp["shrink"])
        tr = Toggle(comp["factory"], ElasticPlan.build(comp["base"]),
                    directory=root, fault_injector=inj)
        try:
            tr.train(comp["batch_fn"], steps)
            return False, "preemption did not fire"
        except Preemption:
            pass
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")   # the mismatch warning is
            tr2 = ElasticTrainer(             # the expected path here
                comp["factory"], ElasticPlan.build(comp["restart"]),
                directory=root)
            out = tr2.train(comp["batch_fn"], steps)
        if out["step"] != steps:
            return False, f"restart ended at step {out['step']}"
        got = comp["canon"](tr2)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    worst = 0.0
    for x, y in zip(ref_canon, got):
        if comp["compare"] == "bitwise":
            if not np.array_equal(x, y):
                return False, f"diverged, max|d|={np.abs(x - y).max():.3g}"
        else:
            worst = max(worst, float(np.abs(x - y).max()))
            if not np.allclose(x, y, rtol=2e-3, atol=1e-4):
                return False, f"beyond tolerance, max|d|={worst:.3g}"
    tag = ("bitwise" if comp["compare"] == "bitwise"
           else f"allclose max|d|={worst:.3g}")
    return True, tag


def _run_topology_matrix(steps: int) -> int:
    n_dev = len(jax.devices())
    builders = [("dp8->dp4->dp8", _topo_component_dp8),
                ("zero4->zero2->zero4", _topo_component_zero),
                ("dp2xtp2+sp->dp4", _topo_component_tp_collapse),
                ("dp2xpp2->dp4->dp2xpp2", _topo_component_pp_toggle)]
    failures = 0
    # kill_at runs the shrunken step; the hard kill lands one step
    # later, and the restart still needs >=1 step to run
    kill_steps = range(1, steps - 1)
    for name, build in builders:
        comp = build()
        if n_dev < comp["n_dev"]:
            print(f"\ncomponent: {name} — needs {comp['n_dev']} devices, "
                  f"have {n_dev}; skipped")
            continue
        from apex_tpu.resilience import ElasticPlan, ElasticTrainer
        ref_root = tempfile.mkdtemp(prefix="apex_tpu_topo_ref_")
        try:
            ref = ElasticTrainer(comp["factory"],
                                 ElasticPlan.build(comp["base"]),
                                 directory=ref_root)
            ref.train(comp["batch_fn"], steps)
            ref_canon = comp["canon"](ref)
        finally:
            shutil.rmtree(ref_root, ignore_errors=True)
        print(f"\ncomponent: {name}  ({steps} steps, "
              f"{comp['compare']} contract)")
        for k in kill_steps:
            ok, detail = _topo_cell(comp, k, steps, ref_canon)
            print(f"  shrink@{k} kill@{k + 1} restart@"
                  f"{comp['restart'].describe()}: "
                  f"{'PASS' if ok else 'FAIL'} ({detail})")
            if not ok:
                failures += 1
    print(f"\ncrash_matrix --topology: "
          f"{'OK' if failures == 0 else 'FAILED'} "
          f"({failures} failing cell(s))")
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=5,
                    help="total train steps per run (default 5)")
    ap.add_argument("--sp", action="store_true",
                    help="also sweep the dp=2 x tp=2 + SP GPT component")
    ap.add_argument("--pp", action="store_true",
                    help="also sweep the ring-pipeline components: "
                         "dp=2 x pp=2 and tp=2 x pp=2 + SP")
    ap.add_argument("--topology", action="store_true",
                    help="sweep the elastic kill-step x topology matrix "
                         "(shrink, hard kill, restart+reshard) instead "
                         "of the fault-kind matrix")
    args = ap.parse_args(argv)

    n_dev = len(jax.devices())
    if n_dev < 2:
        print(f"crash_matrix: needs >=2 devices, have {n_dev} — skipped")
        return 0

    if args.topology:
        return _run_topology_matrix(args.steps)

    components = [("dp2", _component_dp2)]
    if args.sp:
        if n_dev < 4:
            print("crash_matrix: --sp needs >=4 devices — skipped")
        else:
            components.append(("dp2xtp2+sp", _component_dp2tp2_sp))
    if args.pp:
        if n_dev < 4:
            print("crash_matrix: --pp needs >=4 devices — skipped")
        else:
            components.append(("dp2xpp2", _component_dp2pp2))
            components.append(("tp2xpp2+sp", _component_tp2pp2_sp))

    faults = ["preempt", "corrupt", "nan", "inf", "spike"]
    kill_steps = range(1, args.steps)   # step 0 has no checkpoint yet
    failures = 0
    for name, build in components:
        make_parts, batch_fn = build()
        # the reference arm: one clean uninterrupted run per component
        guard, params, opt_state, gstate = make_parts(
            tempfile.mkdtemp(prefix="apex_tpu_crash_ref_"), None)
        ref = _drive(guard, params, opt_state, gstate, batch_fn,
                     args.steps)
        shutil.rmtree(guard.checkpoint.directory, ignore_errors=True)

        print(f"\ncomponent: {name}  ({args.steps} steps)")
        header = "kill@ " + "".join(f"{f:>10}" for f in faults)
        print(header)
        for k in kill_steps:
            cells = []
            for fault in faults:
                ok, detail = _run_cell(make_parts, batch_fn, args.steps,
                                       k, fault, ref)
                cells.append("PASS" if ok else "FAIL")
                if not ok:
                    failures += 1
                    print(f"  FAIL {name} kill@{k} {fault}: {detail}")
            print(f"{k:>5} " + "".join(f"{c:>10}" for c in cells))

    print(f"\ncrash_matrix: {'OK' if failures == 0 else 'FAILED'} "
          f"({failures} failing cell(s))")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
