#!/usr/bin/env python
"""Fleet-wide observability report: N replicas, one timeline, one view.

``tools/metrics_report.py`` reads ONE replica's JSONL stream (and
optionally merges it with one span trace).  This is the N-replica
generalization, built on
:class:`~apex_tpu.observability.fleetobs.FleetCollector`:

* a per-replica table — last known health, requests finished, slot
  occupancy, per-target SLO burn over the merged window;
* fleet-level burn (every replica's raw histogram observations
  replayed, in clock-aligned order, into one fleet SLOMonitor) and
  ``fleet_*`` counter rollups;
* trace-continuity summary over the merged flow events
  (:func:`~apex_tpu.observability.fleetobs.check_flows`): complete vs
  broken chains, orphan request slices;
* ``--out merged.json`` — the single Perfetto-loadable merged timeline
  with one process lane per replica and the applied clock offsets in
  the trace metadata.

Usage:
    python tools/fleet_report.py \\
        --replica r0=r0_trace.json,r0_metrics.jsonl \\
        --replica r1=r1_trace.json,r1_metrics.jsonl \\
        --out fleet_timeline.json

Each ``--replica`` is ``NAME=TRACE_JSON[,METRICS_JSONL]`` (either file
part may be empty, e.g. ``NAME=,METRICS_JSONL`` for a stream-only
replica).  ``--json`` emits the whole report machine-readable.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.observability.fleetobs import FleetCollector  # noqa: E402


def parse_replica(spec: str):
    """``NAME=TRACE[,JSONL]`` -> (name, trace_path | None,
    jsonl_path | None)."""
    if "=" not in spec:
        raise ValueError(
            f"--replica {spec!r}: want NAME=TRACE_JSON[,METRICS_JSONL]")
    name, _, paths = spec.partition("=")
    trace_path, _, jsonl_path = paths.partition(",")
    return name, (trace_path or None), (jsonl_path or None)


def build_collector(specs) -> FleetCollector:
    fc = FleetCollector()
    for spec in specs:
        name, trace_path, jsonl_path = parse_replica(spec)
        fc.add_replica(name, trace_path=trace_path,
                       jsonl_path=jsonl_path)
    return fc


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def report(fc: FleetCollector, out=sys.stdout) -> dict:
    rows = fc.replica_table()
    burn = fc.fleet_burn()
    series = fc.fleet_series()
    cont = fc.continuity(require_finish=False)
    data = {"replicas": rows, "fleet_burn": burn,
            "fleet_series": series,
            "continuity": {
                "chains": len(cont["chains"]),
                "complete": len(cont["complete"]),
                "broken": cont["broken"],
                "orphans": cont["orphans"]},
            "offsets_us": fc.offsets_us()}

    out.write("== replicas ==\n")
    burn_keys = sorted({k for r in rows for k in r["burn"]})
    header = ["replica", "health", "requests", "occupancy"] + \
        [f"burn:{k}" for k in burn_keys] + ["span_events"]
    table = [header]
    for r in rows:
        table.append([r["replica"], _fmt(r["health"]),
                      _fmt(r["requests"]), _fmt(r["occupancy"])]
                     + [_fmt(r["burn"].get(k)) for k in burn_keys]
                     + [_fmt(r["span_events"])])
    widths = [max(len(row[c]) for row in table)
              for c in range(len(header))]
    for row in table:
        out.write("  ".join(c.ljust(w)
                            for c, w in zip(row, widths)).rstrip() + "\n")

    out.write("\n== fleet burn (merged streams) ==\n")
    for k in sorted(burn):
        out.write(f"{k}: {_fmt(burn[k])}\n")
    if series:
        out.write("\n== fleet rollups ==\n")
        for k in sorted(series):
            out.write(f"{k}: {_fmt(series[k])}\n")
    out.write("\n== trace continuity ==\n")
    out.write(f"chains: {len(cont['chains'])}  "
              f"complete: {len(cont['complete'])}  "
              f"broken: {len(cont['broken'])}  "
              f"orphans: {len(cont['orphans'])}\n")
    for tid, problems in sorted(cont["broken"].items()):
        out.write(f"  {tid}: {'; '.join(problems)}\n")
    offs = {k: v for k, v in fc.offsets_us().items() if v}
    if offs:
        out.write("\nclock offsets applied (us): "
                  f"{json.dumps(offs)}\n")
    return data


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replica", action="append", required=True,
                    metavar="NAME=TRACE[,JSONL]",
                    help="one replica's trace file and/or JSONL stream "
                         "(repeatable)")
    ap.add_argument("--out", default=None, metavar="MERGED_JSON",
                    help="also write the merged Perfetto timeline here")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of text")
    args = ap.parse_args(argv)
    fc = build_collector(args.replica)
    if args.json:
        data = report(fc, out=open(os.devnull, "w"))
        json.dump(data, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        report(fc)
    if args.out:
        fc.save(args.out)
        n = len(fc.merged_timeline()["traceEvents"])
        print(f"\nwrote {args.out}: {n} events")


if __name__ == "__main__":
    main()
