#!/usr/bin/env python
"""Render an apex_tpu JSONL metrics stream as a human-readable report.

The stream is whatever a :class:`~apex_tpu.observability.MetricsRegistry`
appended — declare records, per-mutation metric events, and free-form
records like the training monitor's per-step ``train_step`` lines or
``bench.py``'s per-leg ``bench_leg`` results.  The report replays the
stream into a fresh registry (exactly — declare records carry help text
and bucket boundaries) and prints:

* a per-metric table (counters/gauges: current value per label set;
  histograms: count / mean / sum),
* a training rollup over the ``train_step`` records (steps, mean/p50
  step time, tokens/s, loss trajectory endpoints, anomaly count),
* the tail of any other free-form records.

Usage:
    python tools/metrics_report.py metrics.jsonl            # report
    python tools/metrics_report.py metrics.jsonl --prom     # Prometheus
        text snapshot of the replayed registry instead
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.observability import Histogram, replay_jsonl  # noqa: E402


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def report(lines, out=sys.stdout):
    reg, records = replay_jsonl(lines)
    snap = reg.snapshot()
    if snap:
        out.write("== metrics ==\n")
    for name in sorted(snap):
        m = reg.get(name)
        info = snap[name]
        for key, val in sorted(info["series"].items()):
            labels = ",".join(f"{n}={v}" for n, v in
                              zip(info["labelnames"], key))
            label_s = f"{{{labels}}}" if labels else ""
            if isinstance(m, Histogram):
                mean = val["sum"] / val["count"] if val["count"] else 0.0
                out.write(f"{name}{label_s}  count={val['count']} "
                          f"mean={_fmt(mean)} sum={_fmt(val['sum'])}\n")
            else:
                out.write(f"{name}{label_s}  {_fmt(val)}\n")

    steps = [r for r in records if r.get("event") == "train_step"]
    if steps:
        times = sorted(r["step_time_s"] for r in steps
                       if "step_time_s" in r)
        losses = [r["loss"] for r in steps if "loss" in r]
        anomalies = max((r.get("anomalies", 0) for r in steps), default=0)
        out.write("\n== training ==\n")
        out.write(f"steps: {len(steps)}\n")
        if times:
            mean = sum(times) / len(times)
            out.write(f"step_time_s: mean={_fmt(mean)} "
                      f"p50={_fmt(times[len(times) // 2])} "
                      f"max={_fmt(times[-1])}\n")
            last = next((r for r in reversed(steps)
                         if "tokens_per_s" in r), None)
            if last is not None:
                out.write(f"tokens_per_s (last): "
                          f"{_fmt(last['tokens_per_s'])}\n")
        if losses:
            out.write(f"loss: first={_fmt(losses[0])} "
                      f"last={_fmt(losses[-1])}\n")
        out.write(f"anomalies: {anomalies}\n")

    other = [r for r in records if r.get("event") != "train_step"]
    if other:
        out.write("\n== events ==\n")
        for r in other[-20:]:
            kind = r.get("event", "?")
            rest = {k: v for k, v in r.items() if k not in ("event", "ts")}
            out.write(f"{kind}: {rest}\n")
    return reg


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("stream", help="JSONL metrics stream file")
    ap.add_argument("--prom", action="store_true",
                    help="print a Prometheus text snapshot instead")
    args = ap.parse_args(argv)
    with open(args.stream, encoding="utf-8") as f:
        lines = f.readlines()
    if args.prom:
        reg, _ = replay_jsonl(lines)
        sys.stdout.write(reg.prometheus())
    else:
        report(lines)


if __name__ == "__main__":
    main()
