#!/usr/bin/env python
"""Render an apex_tpu JSONL metrics stream as a human-readable report.

The stream is whatever a :class:`~apex_tpu.observability.MetricsRegistry`
appended — declare records, per-mutation metric events, and free-form
records like the training monitor's per-step ``train_step`` lines or
``bench.py``'s per-leg ``bench_leg`` results.  The report replays the
stream into a fresh registry (exactly — declare records carry help text
and bucket boundaries) and prints:

* a per-metric table (counters/gauges: current value per label set;
  histograms: count / mean / sum),
* a training rollup over the ``train_step`` records (steps, mean/p50
  step time, tokens/s, loss trajectory endpoints, anomaly count),
* the tail of any other free-form records.

``--trace spans.json`` merges a :class:`~apex_tpu.observability.Tracer`
Chrome-trace file and the JSONL stream onto ONE timeline: metric
mutations become counter tracks (``ph: "C"`` — counters replayed to
running totals, gauges/histogram samples as-is), free-form records
become instants on a dedicated "metrics (JSONL)" process lane, and the
result is still a Chrome trace — one Perfetto load answers "what
happened at step N / request R".  Both producers are expected to share
a clock (the registry and tracer both take ``clock=``); when the two
time ranges are completely disjoint (different epochs), the JSONL side
is shifted min-to-min and the applied offset is recorded in the trace
metadata.

Usage:
    python tools/metrics_report.py metrics.jsonl            # report
    python tools/metrics_report.py metrics.jsonl --prom     # Prometheus
        text snapshot of the replayed registry instead
    python tools/metrics_report.py metrics.jsonl \\
        --trace spans.json --out merged.json    # merged timeline
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from apex_tpu.observability import Histogram, replay_jsonl  # noqa: E402
from apex_tpu.observability.fleetobs import align_offset  # noqa: E402


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def report(lines, out=sys.stdout):
    reg, records = replay_jsonl(lines)
    snap = reg.snapshot()
    if snap:
        out.write("== metrics ==\n")
    for name in sorted(snap):
        m = reg.get(name)
        info = snap[name]
        for key, val in sorted(info["series"].items()):
            labels = ",".join(f"{n}={v}" for n, v in
                              zip(info["labelnames"], key))
            label_s = f"{{{labels}}}" if labels else ""
            if isinstance(m, Histogram):
                mean = val["sum"] / val["count"] if val["count"] else 0.0
                out.write(f"{name}{label_s}  count={val['count']} "
                          f"mean={_fmt(mean)} sum={_fmt(val['sum'])}\n")
            else:
                out.write(f"{name}{label_s}  {_fmt(val)}\n")

    steps = [r for r in records if r.get("event") == "train_step"]
    if steps:
        times = sorted(r["step_time_s"] for r in steps
                       if "step_time_s" in r)
        losses = [r["loss"] for r in steps if "loss" in r]
        anomalies = max((r.get("anomalies", 0) for r in steps), default=0)
        out.write("\n== training ==\n")
        out.write(f"steps: {len(steps)}\n")
        if times:
            mean = sum(times) / len(times)
            out.write(f"step_time_s: mean={_fmt(mean)} "
                      f"p50={_fmt(times[len(times) // 2])} "
                      f"max={_fmt(times[-1])}\n")
            last = next((r for r in reversed(steps)
                         if "tokens_per_s" in r), None)
            if last is not None:
                out.write(f"tokens_per_s (last): "
                          f"{_fmt(last['tokens_per_s'])}\n")
        if losses:
            out.write(f"loss: first={_fmt(losses[0])} "
                      f"last={_fmt(losses[-1])}\n")
        out.write(f"anomalies: {anomalies}\n")

    other = [r for r in records if r.get("event") != "train_step"]
    if other:
        out.write("\n== events ==\n")
        for r in other[-20:]:
            kind = r.get("event", "?")
            rest = {k: v for k, v in r.items() if k not in ("event", "ts")}
            out.write(f"{kind}: {rest}\n")
    return reg


def merge_trace(trace_events, lines):
    """Merge Tracer events + JSONL metric/record events into one
    Chrome trace-event dict (see module docstring).  Returns
    ``(trace_dict, info)`` where ``info`` reports the event counts and
    any clock offset applied."""
    events = list(trace_events)
    span_ts = [e["ts"] for e in events if "ts" in e]

    metric_events = []      # (ts_s, name, labels, kind, value)
    records = []            # (ts_s, event, fields)
    for line in lines:
        line = line.strip()
        if not line:
            continue
        rec = json.loads(line)
        kind = rec.get("event")
        if kind == "declare" or "ts" not in rec:
            continue
        if kind in ("counter", "gauge", "histogram") and "name" in rec:
            metric_events.append((rec["ts"], rec["name"],
                                  rec.get("labels", {}), kind,
                                  rec["value"]))
        elif kind not in ("counter", "gauge", "histogram"):
            records.append((rec["ts"],) + (kind,
                           {k: v for k, v in rec.items()
                            if k not in ("event", "ts")}))

    jsonl_ts = [t * 1e6 for t, *_ in metric_events] \
        + [t * 1e6 for t, _, _ in records]
    # shared clock -> overlapping ranges -> no shift; disjoint ranges
    # (different epochs, e.g. perf_counter vs time.time) -> align mins
    # (align_offset is the same rule the FleetCollector applies per
    # replica stream)
    offset_us = align_offset(
        (min(span_ts), max(span_ts)) if span_ts else None,
        (min(jsonl_ts), max(jsonl_ts)) if jsonl_ts else None)

    mpid = max((e.get("pid", 0) for e in events
                if isinstance(e.get("pid"), int)), default=0) + 1
    merged = list(events)
    merged.append({"name": "process_name", "ph": "M", "pid": mpid,
                   "args": {"name": "metrics (JSONL)"}})
    counters = {}
    for ts, name, labels, kind, value in metric_events:
        label_s = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        series = f"{name}{{{label_s}}}" if label_s else name
        if kind == "counter":      # deltas -> running total
            counters[series] = counters.get(series, 0.0) + value
            value = counters[series]
        merged.append({"name": series, "ph": "C", "pid": mpid,
                       "ts": ts * 1e6 + offset_us,
                       "args": {"value": value}})
    for ts, kind, fields in records:
        merged.append({"name": kind, "ph": "i", "s": "p", "pid": mpid,
                       "tid": 0, "ts": ts * 1e6 + offset_us,
                       "args": fields})
    info = {"span_events": len(events),
            "metric_events": len(metric_events),
            "records": len(records),
            "offset_us": offset_us}
    return ({"traceEvents": merged, "displayTimeUnit": "ms",
             "metadata": {"apex_tpu.merge_offset_us": offset_us}},
            info)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("stream", help="JSONL metrics stream file")
    ap.add_argument("--prom", action="store_true",
                    help="print a Prometheus text snapshot instead")
    ap.add_argument("--trace", metavar="SPANS_JSON", default=None,
                    help="merge this Chrome-trace file with the stream "
                         "onto one timeline")
    ap.add_argument("--out", default="merged_trace.json",
                    help="merged trace output path (with --trace)")
    args = ap.parse_args(argv)
    with open(args.stream, encoding="utf-8") as f:
        lines = f.readlines()
    if args.trace:
        with open(args.trace, encoding="utf-8") as f:
            tr = json.load(f)
        trace_events = tr["traceEvents"] if isinstance(tr, dict) else tr
        merged, info = merge_trace(trace_events, lines)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(merged, f)
        print(f"wrote {args.out}: {info['span_events']} span events + "
              f"{info['metric_events']} metric samples + "
              f"{info['records']} records"
              + (f" (clock offset {info['offset_us']:.0f}us applied)"
                 if info["offset_us"] else ""))
    elif args.prom:
        reg, _ = replay_jsonl(lines)
        sys.stdout.write(reg.prometheus())
    else:
        report(lines)


if __name__ == "__main__":
    main()
