"""Shared hard-sync timing protocol for the on-chip tools.

Single home for the tools' copy of bench.py's measurement discipline:
``jax.block_until_ready`` can return before device work retires through
the axon remote-device tunnel (see BASELINE.md round-4 correction), so
every timing hard-synchronizes with a 1-element device->host readback.
bench.py keeps its own copy by contract — the driver runs it as a
standalone single-file benchmark — so a change to the protocol must be
mirrored there (and vice versa; bench.py::_sync points back here).
"""

from __future__ import annotations

import os
import sys
import time

# make `import apex_tpu` work regardless of the caller's CWD
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax                                                   # noqa: E402
import numpy as np                                           # noqa: E402


def sync(x):
    """Hard synchronization: 1-element device->host read of a leaf
    (single-element index, not ravel — an out-of-jit ravel dispatches a
    full-size reshape that transiently doubles the leaf's HBM)."""
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))
    return x


def time_steps(fn, args, warmup=2, iters=8, rounds=3):
    """Median seconds per call over ``rounds`` hard-synced windows."""
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        sync(out)
        times.append((time.perf_counter() - t0) / iters)
    times.sort()
    return times[len(times) // 2]
