#!/usr/bin/env python
"""On-chip flash-attention tuning sweep (VERDICT r4 item 4).

Times the Pallas flash kernel fwd+bwd across block sizes and sequence
lengths at BERT/GPT-like shapes, and races XLA's dense (materialized)
attention at short sequence — if dense wins at seq <= 512, the public
wrapper should dispatch on length.

Usage: python tools/sweep_flash.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from _timing import sync as _sync, time_steps as _time  # noqa: E402 (sets sys.path)

from apex_tpu.ops.flash_attention import (flash_attention,          # noqa: E402
                                          flash_attention_reference)


def grad_fn(attn, causal):
    def f(q, k, v):
        return jnp.sum(attn(q, k, v, causal).astype(jnp.float32))
    return jax.jit(jax.grad(f, argnums=(0, 1, 2)))


def main():
    rng = np.random.RandomState(0)
    # (label, b, h, s, d, causal) — BERT-large (s 512, non-causal),
    # GPT-350M (s 1024, causal), long-seq (s 2048, causal)
    shapes = [("bert", 32, 16, 512, 64, False),
              ("gpt", 16, 16, 1024, 64, True),
              ("long", 4, 16, 2048, 64, True)]
    blocks = [(256, 256), (512, 512), (1024, 1024), (256, 512),
              (512, 256), (512, 1024)]
    for label, b, h, s, d, causal in shapes:
        q = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)

        dense = grad_fn(lambda q, k, v, c: flash_attention_reference(
            q, k, v, causal=c), causal)
        try:
            dt = _time(dense, (q, k, v))
            print(f"{label} s={s} dense(XLA): {dt * 1e3:8.2f} ms",
                  flush=True)
        except Exception as e:
            print(f"{label} s={s} dense(XLA): FAILED "
                  f"{str(e).splitlines()[0][:100]}", flush=True)

        for bq, bk in blocks:
            if bq > s or bk > s:
                continue
            fl = grad_fn(lambda q, k, v, c, _bq=bq, _bk=bk:
                         flash_attention(q, k, v, causal=c, block_q=_bq,
                                         block_k=_bk), causal)
            try:
                dt = _time(fl, (q, k, v))
                print(f"{label} s={s} flash({bq},{bk}): {dt * 1e3:8.2f} ms",
                      flush=True)
            except Exception as e:
                print(f"{label} s={s} flash({bq},{bk}): FAILED "
                      f"{str(e).splitlines()[0][:100]}", flush=True)
        jax.clear_caches()


if __name__ == "__main__":
    main()
