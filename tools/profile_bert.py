#!/usr/bin/env python
"""On-chip BERT-large profiling: remat/batch sweep + per-component
breakdown (VERDICT r4 items 1+2).

Runs each candidate train-step config with the bench.py hard-sync
protocol and prints tokens/s; then times isolated sub-components at the
headline step's shapes (batch 16 x seq 512, x2 accumulation
microbatches; the optimizer runs once per step) so the bench can ship a
`breakdown` dict whose component seconds sum comparably to the headline
step.

Usage:
    python tools/profile_bert.py sweep      # remat/batch sweep
    python tools/profile_bert.py breakdown  # per-component attribution
"""

from __future__ import annotations

import functools
import sys

import jax
import jax.numpy as jnp
import numpy as np

from _timing import sync as _sync, time_steps as _time  # noqa: E402 (sets sys.path)


def make_step(batch, remat, policy, accum=1, leaf=False):
    from apex_tpu import amp
    from apex_tpu.models.bert import BertConfig, BertModel
    from apex_tpu.optimizers import FusedLAMB

    cfg = BertConfig(hidden_size=1024, num_layers=24,
                     num_attention_heads=16, max_seq_len=512,
                     remat=remat, remat_policy=policy,
                     dtype=jnp.bfloat16)
    seq = 512
    model = BertModel(cfg)
    lamb = FusedLAMB(lr=1e-3, bucketed=not leaf)
    state = amp.initialize(model.loss, lamb, opt_level="O2")
    params = state.cast_params(model.init_params(jax.random.PRNGKey(0)))
    opt_state = lamb.init(params)

    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                     (accum, batch, seq)))
    labels = np.where(rng.rand(accum, batch, seq) < 0.15,
                      rng.randint(0, cfg.vocab_size, (accum, batch, seq)),
                      -1)
    labels = jnp.asarray(labels)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens, labels):
        if accum == 1:
            loss, grads = jax.value_and_grad(state.apply_fn)(
                params, tokens[0], labels[0])
        else:
            def mb(carry, tl):
                tk, lb = tl
                l, g = jax.value_and_grad(state.apply_fn)(params, tk, lb)
                acc_l, acc_g = carry
                return (acc_l + l,
                        jax.tree_util.tree_map(jnp.add, acc_g, g)), None
            zero = (jnp.zeros(()),
                    jax.tree_util.tree_map(jnp.zeros_like, params))
            (loss, grads), _ = jax.lax.scan(mb, zero, (tokens, labels))
            inv = 1.0 / accum
            loss = loss * inv
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
        new_params, new_opt = lamb.step(grads, params, opt_state)
        return loss, new_params, new_opt

    holder = {"params": params, "opt": opt_state}

    def run(tokens, labels):
        loss, holder["params"], holder["opt"] = train_step(
            holder["params"], holder["opt"], tokens, labels)
        return loss

    return run, (tokens, labels), batch * accum * seq


def sweep():
    configs = [
        ("b32_full", dict(batch=32, remat=True, policy="full")),
        ("b16_dots", dict(batch=16, remat=True, policy="dots")),
        ("b24_dots", dict(batch=24, remat=True, policy="dots")),
        ("b32_dots", dict(batch=32, remat=True, policy="dots")),
        ("b16x2_dots", dict(batch=16, remat=True, policy="dots",
                            accum=2)),
        ("b8_none", dict(batch=8, remat=False, policy="full")),
        ("b16_none", dict(batch=16, remat=False, policy="full")),
        ("b32_dots_leaf", dict(batch=32, remat=True, policy="dots",
                               leaf=True)),
        ("b16x2_dots_leaf", dict(batch=16, remat=True, policy="dots",
                                 accum=2, leaf=True)),
        ("b24_dots_leaf", dict(batch=24, remat=True, policy="dots",
                               leaf=True)),
        ("b16_none_leaf", dict(batch=16, remat=False, policy="full",
                               leaf=True)),
        ("b24_none_leaf", dict(batch=24, remat=False, policy="full",
                               leaf=True)),
        ("b32_none_leaf", dict(batch=32, remat=False, policy="full",
                               leaf=True)),
        ("b16x2_none_leaf", dict(batch=16, remat=False, policy="full",
                                 accum=2, leaf=True)),
    ]
    if len(sys.argv) > 2:                  # run a subset by name
        names = set(sys.argv[2].split(","))
        configs = [c for c in configs if c[0] in names]
    for name, kw in configs:
        try:
            run, args, tokens_per_step = make_step(**kw)
            dt = _time(run, args)
            print(f"{name}: {tokens_per_step / dt:,.0f} tok/s "
                  f"(step {dt * 1e3:.1f} ms)", flush=True)
        except Exception as e:  # OOM etc.
            msg = str(e).split("\n")[0][:160]
            print(f"{name}: FAILED {type(e).__name__}: {msg}", flush=True)
        # free everything between configs
        jax.clear_caches()


def breakdown():
    from apex_tpu.normalization import MixedFusedLayerNorm
    from apex_tpu.ops.flash_attention import flash_attention
    from apex_tpu.ops.lm_head import fused_linear_cross_entropy
    from apex_tpu.optimizers import FusedLAMB

    b, s, h, nh, L, V = 16, 512, 1024, 16, 24, 30528
    accum = 2                     # headline: batch 16 x 2 accum
    hd = h // nh
    f = 4 * h
    rng = np.random.RandomState(0)
    bf = jnp.bfloat16

    def t_grad(fn, *args, iters=8):
        """fwd+bwd time of mean(fn) w.r.t. all args."""
        g = jax.jit(jax.grad(lambda *a: jnp.mean(fn(*a).astype(
            jnp.float32)), argnums=tuple(range(len(args)))))
        return _time(g, args, iters=iters)

    def t_chain(fn_one, x0, *consts, reps=24):
        """fwd+bwd of ``reps`` chained applications inside ONE jitted
        program (per-dispatch tunnel overhead ~5-8 ms would otherwise
        dominate a single-op program); returns seconds PER application."""
        def loss(x, *cs):
            def body(c, _):
                return fn_one(c, *cs), None
            y, _ = jax.lax.scan(body, x, None, length=reps)
            return jnp.mean(y.astype(jnp.float32))
        g = jax.jit(jax.grad(loss, argnums=tuple(range(1 + len(consts)))))
        return _time(g, (x0,) + consts) / reps

    out = {}

    def done(name, sec):
        out[name] = sec
        print(f"  {name:>16}: {sec * 1e3:7.1f} ms", flush=True)
        jax.clear_caches()

    # attention: chained flash fwd+bwd (q carries), per-layer x L
    q = jnp.asarray(rng.randn(b, nh, s, hd), bf)
    k = jnp.asarray(rng.randn(b, nh, s, hd), bf)
    v = jnp.asarray(rng.randn(b, nh, s, hd), bf)
    done("attention", accum * L * t_chain(
        lambda q, k, v: flash_attention(q, k, v, causal=False), q, k, v))
    del q, k, v

    # qkv + proj GEMMs: (b*s, h) x (h, 3h) and (b*s, h) x (h, h)
    x = jnp.asarray(rng.randn(b * s, h), bf)
    wqkv = jnp.asarray(rng.randn(h, 3 * h) * 0.02, bf)
    wproj = jnp.asarray(rng.randn(h, h) * 0.02, bf)
    done("qkv_proj_gemms", accum * L * t_chain(
        lambda x, a, c: ((x @ a)[:, :h] @ c), x, wqkv, wproj))
    del wqkv, wproj

    # FFN: (b*s, h) -> 4h -> gelu -> h (reps capped: the scan saves the
    # (b*s, 4h) gelu inputs per rep, ~300 MB each)
    w1 = jnp.asarray(rng.randn(h, f) * 0.02, bf)
    w2 = jnp.asarray(rng.randn(f, h) * 0.02, bf)
    done("ffn", accum * L * t_chain(
        lambda x, w1, w2: jax.nn.gelu(x @ w1, approximate=True) @ w2,
        x, w1, w2, reps=8))
    del w1, w2

    # layer norm: 2 per layer + embedding/mlm LNs ~ 2L
    ln = MixedFusedLayerNorm(h)
    lp = ln.init_params()
    xf = jnp.asarray(rng.randn(b, s, h), bf)
    done("layernorm", accum * 2 * L * t_chain(
        lambda x, p: ln(p, x), xf, lp, reps=48))
    del xf, lp

    # LM head: fused linear CE over the full vocab (device work per
    # dispatch ~50 ms, overhead negligible — no chaining needed)
    emb = jnp.asarray(rng.randn(V, h) * 0.02, bf)
    tgt = jnp.asarray(rng.randint(0, V, (b * s,)))
    done("lm_head_ce", accum * t_grad(
        lambda hd_, w: fused_linear_cross_entropy(hd_, w, tgt),
        x, emb, iters=4))
    del x, emb, tgt

    # optimizer: FusedLAMB step on the BERT census
    shapes = []
    for _ in range(L):
        shapes += [(3 * h, h), (3 * h,), (h, h), (h,), (f, h), (f,),
                   (h, f), (h,), (h,), (h,), (h,), (h,)]
    shapes += [(V, h), (512, h), (2, h), (h, h), (h,), (h,), (h,)]
    params = [jnp.asarray(rng.randn(*sh).astype(np.float32) * 0.02)
              for sh in shapes]
    grads = [jnp.asarray(rng.randn(*sh).astype(np.float32) * 1e-3)
             for sh in shapes]
    lamb = FusedLAMB(lr=1e-3)
    lstate = lamb.init(params)

    reps = 4

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def lamb_steps(grads, params, state):
        def body(c, _):
            p, s = c
            return lamb.step(grads, p, s), None
        (p, s), _ = jax.lax.scan(body, (params, state), None, length=reps)
        return p, s

    def run(grads):
        nonlocal params, lstate
        params, lstate = lamb_steps(grads, params, lstate)
        return params

    done("optimizer_lamb", _time(run, (grads,), iters=4) / reps)

    total = sum(out.values())
    print("component breakdown (fwd+bwd isolated, x layer count x 2 "
          "accum; optimizer once per step):")
    for k_, v_ in sorted(out.items(), key=lambda kv: -kv[1]):
        print(f"  {k_:>16}: {v_ * 1e3:7.1f} ms  ({v_ / total:5.1%})")
    print(f"  {'sum':>16}: {total * 1e3:7.1f} ms")


if __name__ == "__main__":
    {"sweep": sweep, "breakdown": breakdown}[sys.argv[1]]()
