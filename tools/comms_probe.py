#!/usr/bin/env python
"""Probe the ring collectives and fit a machine cost profile.

Microbenchmarks ``psum`` / ``all_gather`` / ``psum_scatter`` /
``ppermute`` across message sizes, ring sizes and dtypes on the current
mesh, fits per-(op, dtype) alpha-beta ring coefficients by least
squares, validates the fit on a held-out split, and writes a VERSIONED
machine-profile JSON — the measured communication model the
auto-parallel planner (``tools/autotune.py``, ROADMAP item 1) will
consume via ``CostModel.predict`` / ``predict_stats``.

Usage:
    python tools/comms_probe.py --out profile.json
    python tools/comms_probe.py --ops psum,all_gather --dtypes f32,int8 \\
        --sizes 4096,65536,1048576 --groups 2,4 --out profile.json
    python tools/comms_probe.py --check profile.json   # re-validate a
        saved profile's fits against its own stored measurements

Two-tier (MPMD cross-pod) profiles: ``--link-class dcn`` tags the
probed measurements as the slow tier (run it on a mesh whose rings
actually cross the data-center network); ``--simulate-dcn alpha,beta``
instead synthesizes an exact dcn curve from the given per-hop latency
(seconds) and inverse bandwidth (seconds/byte) — the CPU-only CI path
for exercising the two-tier fit, e.g. ``--simulate-dcn 1e-3,1e-8``.
Both land in the same profile JSON; curves carry a ``link_class``
field and pre-link-class profiles load as ici.

On a CPU host, 8 virtual devices come from
``XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _csv(cast):
    return lambda s: [cast(v) for v in s.split(",") if v]


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="comms_profile.json",
                    help="machine-profile JSON path")
    ap.add_argument("--ops", type=_csv(str), default=None,
                    help="comma list from psum,all_gather,psum_scatter,"
                         "ppermute (default: all)")
    ap.add_argument("--dtypes", type=_csv(str),
                    default=["f32", "bf16", "int8"],
                    help="comma list from f32,bf16,int8")
    ap.add_argument("--sizes", type=_csv(int), default=None,
                    help="per-device local buffer bytes (default "
                         "4K..1M powers of 4)")
    ap.add_argument("--groups", type=_csv(int), default=None,
                    help="ring sizes (default: 2,4,8 where they divide "
                         "the device count)")
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--holdout", type=int, default=3,
                    help="hold out every Nth point per curve for "
                         "validation (0: fit on everything)")
    ap.add_argument("--tolerance", type=float, default=2.0,
                    help="validation gate on held-out pred/meas ratio")
    ap.add_argument("--check", metavar="PROFILE", default=None,
                    help="skip probing; re-validate PROFILE against "
                         "its stored measurements")
    ap.add_argument("--max-age-s", type=float, default=None,
                    help="with --check: additionally gate on profile "
                         "staleness — fail when the probe stamp is "
                         "older than this many seconds or missing "
                         "entirely (never probed)")
    ap.add_argument("--link-class", default="ici",
                    help="fabric tag for the probed measurements "
                         "(ici | dcn; default ici)")
    ap.add_argument("--simulate-dcn", metavar="ALPHA,BETA", default=None,
                    help="also inject a synthetic dcn curve with the "
                         "given per-hop latency (s) and inverse "
                         "bandwidth (s/byte), e.g. 1e-3,1e-8 — the "
                         "CPU-only CI path for two-tier fits")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    simulate_dcn = None
    if args.simulate_dcn is not None:
        parts = [p for p in args.simulate_dcn.split(",") if p]
        if len(parts) != 2:
            ap.error("--simulate-dcn wants 'alpha,beta' "
                     "(seconds, seconds/byte), e.g. 1e-3,1e-8")
        simulate_dcn = (float(parts[0]), float(parts[1]))

    import jax

    # the axon TPU plugin ignores JAX_PLATFORMS=cpu from the env; flip
    # the config knob before backend init when the caller asked for cpu
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass

    from apex_tpu.observability.costmodel import (
        Measurement, fit_cost_model, holdout_split, load_profile,
        probe_collectives, simulate_link_measurements)

    if args.check:
        model, ms = load_profile(args.check)
        if not ms:
            print("profile carries no raw measurements; nothing to "
                  "re-validate", file=sys.stderr)
            return 2
        report = model.validate(ms, tolerance=args.tolerance)
        out = {k: v for k, v in report.items() if k != "rows"}
        # staleness is orthogonal to fit quality: a profile can still
        # predict its OWN stored measurements perfectly while being a
        # year out of date (drifted), or carry no stamp at all (never
        # probed on this fleet) — surface both so the autopilot's
        # max_profile_age_s gate has the same data offline
        age = model.profile_age()
        out["profile_age_s"] = age
        out["n_measurements"] = model.meta.get("n_measurements")
        if args.max_age_s is not None:
            out["stale"] = model.is_stale(args.max_age_s)
            out["max_age_s"] = args.max_age_s
        print(json.dumps(out, indent=1))
        if args.max_age_s is not None and out["stale"]:
            reason = ("no probe stamp (never probed)" if age is None
                      else f"probed {age:.0f}s ago")
            print(f"profile is stale: {reason} (gate "
                  f"{args.max_age_s:.0f}s)", file=sys.stderr)
            return 1
        return 0 if report["within_tolerance"] else 1

    from apex_tpu.observability.costmodel import COLLECTIVE_OPS

    ops = args.ops or list(COLLECTIVE_OPS)
    sizes = args.sizes or [1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20]
    measurements = probe_collectives(
        ops=ops, dtypes=args.dtypes, sizes=sizes,
        group_sizes=args.groups, iters=args.iters, rounds=args.rounds,
        link_class=args.link_class, verbose=not args.quiet)
    if not measurements:
        print("probe produced no measurements", file=sys.stderr)
        return 2
    if simulate_dcn is not None:
        alpha, beta = simulate_dcn
        measurements += simulate_link_measurements(
            alpha, beta, link_class="dcn", ops=ops, dtypes=["f32"],
            sizes=sizes, group_sizes=args.groups or (2, 4))

    if args.holdout:
        train, held = holdout_split(measurements, every=args.holdout)
    else:
        train, held = list(measurements), []
    model = fit_cost_model(train, meta={
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "n_devices": len(jax.devices()),
        "iters": args.iters, "rounds": args.rounds,
    })
    model.save(args.out, measurements=measurements)

    curves = model.curves()
    print(f"wrote {args.out}: {len(curves)} fitted curves over "
          f"{len(train)} points "
          f"(link classes: {', '.join(model.link_classes)})")
    for (op, dtype, lc), fit in sorted(curves.items()):
        print(f"  {op:<13} {dtype:<5} {lc:<4} "
              f"alpha={fit.alpha_s * 1e6:8.2f}us/hop"
              f"  beta={fit.beta_s_per_byte * 1e9:8.3f}ns/B"
              f"  fit_err<={fit.max_rel_err:.2f}")
    if held:
        report = model.validate(held, tolerance=args.tolerance)
        ok = "OK" if report["within_tolerance"] else "FAIL"
        print(f"held-out validation [{ok}]: {report['n']} points, "
              f"worst ratio {report['worst_ratio']:.2f}x "
              f"(gate {args.tolerance}x)")
        return 0 if report["within_tolerance"] else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
