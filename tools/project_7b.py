#!/usr/bin/env python
"""Measure one 7B pipeline stage on the real chip and project
tokens/sec/chip for the BASELINE.md row-2 workload (GPT ~7B via TP x PP
on a v5e-64 pod) from measured stage time + modeled ICI boundary cost.

Method (written into BASELINE.md):

* The 7B recipe (examples/gpt7b: hidden 4096, 32 layers, seq 2048,
  tp=4 x pp=4 x dp=4 on 64 chips) gives each pipeline stage 8 layers,
  each layer's GEMMs sharded 4-way over TP.  A single chip therefore
  executes per microbatch tick: 8 layers at hidden 4096 with 1/4 of
  every GEMM's output features (qkv 4096->3072, proj 1024->4096,
  fc1 4096->4096, fc2 4096->4096 per-rank shards).
* This script times exactly that stage (fwd+bwd, bf16, remat off) on
  one chip at micro-batch 1 x seq 2048.
* The pipeline bubble is (pp-1)/(M+pp-1) with M microbatches per rank;
  the stage-boundary ppermute moves (mb, s, h) bf16 = 16 MB per tick
  over ICI (~45 GB/s effective per link on v5e) ~ 0.4 ms, overlapped
  with the next tick's compute by XLA's latency-hiding scheduler — it
  is carried as an error term, not a serial cost.
* tokens/sec/chip = mb*s*M / (T_stage*(M+pp-1) + eps) / 1 chip-of-64,
  where each of the 64 chips holds one (tp, pp) shard and dp=4 scales
  tokens and chips together (cancels).

Known error term this script CANNOT measure on one chip: the TP
all-reduces inside each layer (2 psums fwd + 2 bwd of the (mb, s, h)
activation over the 4-chip ring, ~26 ms/tick serial worst case vs the
~60 ms measured compute).  BASELINE.md carries the projection as a
range whose lower bound charges them fully serial and whose upper
bound assumes full overlap.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from _timing import sync as _sync, time_steps as _time  # noqa: E402

H, L_STAGE, SEQ, TP, PP, M = 4096, 8, 2048, 4, 4, 8
FFN = 4 * H
HEADS_LOCAL = 32 // TP


def stage_fwd(params, x):
    """8 TP-sharded GPT layers, one microbatch (1, s, h/1) local math.

    The TP collectives themselves ride ICI and are not measurable on
    one chip; their FLOPs/bytes are the sharded GEMMs below, which ARE
    measured.  (Collective cost rides the error bar.)"""
    from apex_tpu.ops.flash_attention import flash_attention

    def layer(x, lp):
        h_ = x
        qkv = h_ @ lp["wqkv"]                       # (1, s, 3h/tp)
        b, s, _ = qkv.shape
        q, k, v = jnp.split(qkv.reshape(b, s, HEADS_LOCAL, 3 * 128), 3,
                            axis=-1)
        ctx = flash_attention(q.transpose(0, 2, 1, 3),
                              k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3), causal=True)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, -1)
        x = x + ctx @ lp["wproj"]                   # row-parallel local
        h2 = x @ lp["w1"]
        h2 = jax.nn.gelu(h2, approximate=True)
        return x + h2 @ lp["w2"], None

    x, _ = jax.lax.scan(layer, x, params)
    return x


def main():
    rng = np.random.RandomState(0)
    bf = jnp.bfloat16
    params = {
        "wqkv": jnp.asarray(rng.randn(L_STAGE, H, 3 * H // TP) * 0.02, bf),
        "wproj": jnp.asarray(rng.randn(L_STAGE, H // TP, H) * 0.02, bf),
        "w1": jnp.asarray(rng.randn(L_STAGE, H, FFN // TP) * 0.02, bf),
        "w2": jnp.asarray(rng.randn(L_STAGE, FFN // TP, H) * 0.02, bf),
    }
    x = jnp.asarray(rng.randn(1, SEQ, H), bf)

    grad = jax.jit(jax.grad(
        lambda p, x: jnp.sum(stage_fwd(p, x).astype(jnp.float32)),
        argnums=(0, 1)))
    t_stage = _time(grad, (params, x), warmup=2, iters=4, rounds=3)
    print(f"stage fwd+bwd (8 layers, h={H}, tp={TP} shard, mb=1 x "
          f"s={SEQ}): {t_stage * 1e3:.1f} ms", flush=True)

    # per-stage FLOPs for an MFU cross-check: GEMMs (fwd 2x + bwd 4x =
    # 6x weight size per token) + flash attention (12*s*h per token per
    # layer, fwd; x3 for fwd+bwd, local heads = 1/tp share)
    w_els = sum(int(np.prod(p.shape[1:])) for p in params.values()) * L_STAGE
    flops = 6 * w_els * SEQ + 3 * 12 * L_STAGE * (H // TP) * SEQ * SEQ
    print(f"stage FLOPs ~{flops / 1e12:.2f} T -> "
          f"{flops / t_stage / 1e12:.1f} TF/s sustained")

    # projection: 1F1B with M microbatches; boundary ppermute 16 MB
    # per tick over ICI (overlappable; carried as +/- term)
    ticks = M + PP - 1
    t_step = t_stage * ticks
    boundary = 16e6 / 45e9                        # s per tick, if serial
    tokens = M * 1 * SEQ                          # per pipeline replica
    # each replica spans tp*pp = 16 chips; tokens/sec/chip divides by 16
    chips = TP * PP
    lo = tokens / ((t_step + ticks * boundary) * chips)
    hi = tokens / (t_step * chips)
    print(f"1F1B ticks={ticks} bubble={(PP - 1) / ticks:.2%}")
    print(f"projected tokens/sec/chip (7B, tp4 x pp4, M={M}, mb=1): "
          f"{lo:,.0f} - {hi:,.0f}")


if __name__ == "__main__":
    main()
