#!/usr/bin/env python
"""Multi-chip MFU measurement (ISSUE 17): per-chip achieved FLOPs and
model-FLOPs utilization for dp x tp train steps with the fused-FFN knob
on, held against the autotune planner's own predictions.

For each plan the tool builds the planner's REAL candidate program
(``tools/autotune.build_train_step``: pipelined grad step + optimizer
over an ElasticPlan mesh), measures it with the bench hard-sync
protocol, and reports:

* ``achieved_flops_per_chip`` — 6ND model flops (8ND under remat) over
  ``n_devices x measured_s``;
* ``mfu`` — achieved per-chip flops over the same calibrated matmul
  roofline the planner ranks with (``calibrate_matmul_flops``: a
  measured constant on THIS host, not a spec sheet, so the number is
  honest on CPU hosts too);
* ``predicted_s`` / ``gap`` — the planner's compute+comm prediction for
  the plan and its relative distance from the wall clock, i.e. the
  same predicted-vs-measured accounting ``bench.py``'s autotune leg
  tracks, evaluated at the plans the fused-FFN work actually targets.

Usage:
    python tools/mfu_multichip.py --devices 8 [--batch 8] [--out f.json]
"""

from __future__ import annotations

import argparse
import json
import sys

from _timing import time_steps  # noqa: E402 (sets sys.path)

from autotune import (_default_cost_model, DEFAULT_MODEL,  # noqa: E402
                      build_train_step, calibrate_matmul_flops,
                      predict_comm_s, predict_compute_s)


def _plans(n_devices: int):
    from apex_tpu.parallel.plan import ParallelPlan

    plans = [("dp%d_fused" % n_devices,
              ParallelPlan(dp=n_devices, fused_ffn=True))]
    if n_devices >= 4 and n_devices % 2 == 0:
        tp = 2
        dp = n_devices // tp
        plans.append((f"dp{dp}_tp{tp}_sp",
                      ParallelPlan(dp=dp, tp=tp, sequence_parallel=True)))
        plans.append((f"dp{dp}_tp{tp}_sp_fused",
                      ParallelPlan(dp=dp, tp=tp, sequence_parallel=True,
                                   fused_ffn=True)))
    return plans


def measure(n_devices: int, batch: int, *, cfg_kw=None, quiet=False):
    import jax

    def say(msg):
        if not quiet:
            print(msg, flush=True)

    cfg_kw = dict(cfg_kw or DEFAULT_MODEL)
    seq = cfg_kw["max_seq_len"]
    devices = jax.devices()[:n_devices]
    if len(devices) < n_devices:
        raise RuntimeError(f"need {n_devices} devices, have "
                           f"{len(devices)}")
    flops_per_s = calibrate_matmul_flops()
    say(f"calibrated matmul roofline: {flops_per_s / 1e9:.2f} Gflop/s "
        "per device")
    cost_model = _default_cost_model(n_devices)

    rows = {}
    for name, plan in _plans(n_devices):
        step, args, n_params = build_train_step(plan, cfg_kw, batch, seq,
                                                devices)
        compiled = jax.jit(step).lower(*args).compile()
        measured_s = time_steps(compiled, args, warmup=1, iters=4,
                                rounds=3)
        flops = 6.0 * float(n_params) * batch * seq
        if plan.remat:
            flops *= 8.0 / 6.0
        per_chip = flops / (n_devices * measured_s)
        compute_s = predict_compute_s(plan, n_params, batch, seq,
                                      flops_per_s)
        comm_s = predict_comm_s(compiled, cost_model,
                                group_size=max(plan.dp, plan.tp, plan.pp))
        predicted_s = compute_s + comm_s
        rows[name] = {
            "plan": plan.describe(),
            "measured_s": round(measured_s, 6),
            "predicted_s": round(predicted_s, 6),
            "gap": round(abs(predicted_s - measured_s) / measured_s, 4),
            "achieved_flops_per_chip": round(per_chip, 1),
            "mfu": round(per_chip / flops_per_s, 4),
        }
        say(f"  {name:<22} meas={measured_s * 1e3:8.3f} ms  "
            f"pred={predicted_s * 1e3:8.3f} ms  "
            f"mfu={rows[name]['mfu']:.4f}")
        jax.clear_caches()

    fused = {k: v for k, v in rows.items() if k.endswith("fused")}
    best = max(fused, key=lambda k: fused[k]["mfu"])
    report = {
        "n_devices": n_devices,
        "batch": batch,
        "seq": seq,
        "model": cfg_kw,
        "n_params": n_params,
        "flops_per_s_per_chip": round(flops_per_s, 1),
        "rows": rows,
        "best_fused_plan": best,
        "mfu": rows[best]["mfu"],
        "gap_max": max(r["gap"] for r in rows.values()),
    }
    if "dp%d_tp2_sp" % (n_devices // 2) in rows:
        base = rows["dp%d_tp2_sp" % (n_devices // 2)]
        tuned = rows["dp%d_tp2_sp_fused" % (n_devices // 2)]
        report["fused_speedup_dp_tp_sp"] = round(
            base["measured_s"] / tuned["measured_s"], 4)
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-chip MFU for dp x tp fused-FFN train steps")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="write the report JSON here (else stdout)")
    ap.add_argument("--quiet", action="store_true")
    ns = ap.parse_args(argv)
    report = measure(ns.devices, ns.batch, quiet=ns.quiet)
    text = json.dumps(report, indent=1, sort_keys=True) + "\n"
    if ns.out:
        with open(ns.out, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


if __name__ == "__main__":
    main()
