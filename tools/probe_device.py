#!/usr/bin/env python
"""Device capability probe — reproduces the round-5 'silicon as
delivered' numbers cited in BASELINE.md and BASELINE.json
(``recorded_best``): sustained HBM bandwidth (chained 1 GB axpy) and
bf16/f32 matmul rates (chained DEPENDENT 4096^3 matmuls, the same probe
as bench.py's raw calibration).  On the tunnel-attached v5e this lands
around 350 GB/s / 100 TF/s — roughly half the public spec sheet — which
caps spec-MFU near 0.51 regardless of program quality."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from _timing import sync as _sync, time_steps as _time  # noqa: E402


def hbm_bandwidth():
    n = 256 * 1024 * 1024  # 1 GB f32
    x = jnp.ones((n // 128, 128), jnp.float32)
    reps = 8

    @functools.partial(jax.jit, donate_argnums=(0,))
    def axpy_chain(x):
        def body(c, _):
            return c * 1.000001 + 1e-7, None
        y, _ = jax.lax.scan(body, x, None, length=reps)
        return y

    holder = [x]

    def run():
        holder[0] = axpy_chain(holder[0])
        return holder[0]

    dt = _time(lambda _=None: run(), (None,), warmup=1, iters=4,
               rounds=3) / reps
    gb = 2 * x.size * 4 / 1e9  # read + write per rep
    print(f"HBM axpy: {gb / dt:.0f} GB/s ({dt * 1e3:.2f} ms per "
          f"1GB-rw pass)", flush=True)


def matmul_rate(dtype):
    n = 4096
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), dtype)
    b = jax.random.normal(key, (n, n), dtype)
    chain_len = 48

    @functools.partial(jax.jit, donate_argnums=(0,))
    def chain(a, b):
        def body(c, _):
            c = jnp.dot(c, b, preferred_element_type=dtype)
            c = c * (1.0 / jnp.maximum(jnp.max(jnp.abs(c)),
                                       1.0)).astype(dtype)
            return c, None
        c, _ = jax.lax.scan(body, a, None, length=chain_len)
        return c

    holder = [a]

    def run():
        holder[0] = chain(holder[0], b)
        return holder[0]

    dt = _time(lambda _=None: run(), (None,), warmup=1, iters=2,
               rounds=3) / chain_len
    print(f"matmul {jnp.dtype(dtype).name} {n}^3: "
          f"{2 * n ** 3 / dt / 1e12:.1f} TF/s", flush=True)


if __name__ == "__main__":
    hbm_bandwidth()
    jax.clear_caches()
    matmul_rate(jnp.bfloat16)
    matmul_rate(jnp.float32)
