"""apex_tpu.observability.fleetobs: causal traces, merged fleet
timelines, the anomaly flight recorder, and the bench-diff gate.

The fleet-observability contract:

* a :class:`TraceContext` minted at submission threads one request's
  flow events (``ph: "s"/"t"/"f"``) through every hop with unbroken
  ``parent -> span`` linkage, and :func:`check_flows` MEASURES that
  linkage — one start, a terminal end, no dangling parents, migrated
  chains spanning >= 2 replicas, no orphan request slices;
* :class:`FleetCollector` folds N replicas' traces and JSONL streams
  onto one clock (overlap = shared clock, disjoint = min-to-min),
  per-replica process lanes, fleet-level SLO burn and ``fleet_*``
  rollups;
* :class:`FlightRecorder` keeps bounded rings and cuts bounded,
  window-filtered snapshots;
* ``tools/bench_diff.py`` classifies metric direction, recovers legs
  from truncated tails, and flags regressions in BOTH directions;
* the replica_kill chaos scenario ends with every flow chain complete
  and connected — the acceptance criterion of the observability PR.
"""

import argparse
import importlib
import io
import json
import os
import sys

import pytest

from apex_tpu.observability import (FleetCollector, FlightRecorder,
                                    MetricsRegistry, Tracer,
                                    TraceContext, check_flows,
                                    emit_flow)
from apex_tpu.observability.fleetobs import align_offset


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _tools():
    """Import a module from tools/ (they are scripts, not a package)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    try:
        return importlib.import_module("bench_diff")
    finally:
        sys.path.pop(0)


# -- TraceContext ------------------------------------------------------------

class TestTraceContext:
    def test_mint(self):
        ctx = TraceContext.mint(7)
        assert ctx.trace_id == "req:7"
        assert ctx.parent == "root"
        assert ctx.hop == 0 and not ctx.started and ctx.seq == 0

    def test_next_hop_mutates_in_place(self):
        ctx = TraceContext.mint(1)
        out = ctx.next_hop("r2")
        assert out is ctx
        assert ctx.hop == 1 and ctx.replica == "r2"
        ctx.next_hop("r0")
        assert ctx.hop == 2 and ctx.replica == "r0"

    def test_dict_roundtrip(self):
        ctx = TraceContext.mint(3)
        ctx.next_hop("r1")
        ctx.started = True
        ctx.parent = "req:3#0.enqueue.0"
        assert TraceContext.from_dict(ctx.to_dict()) == ctx


class TestEmitFlow:
    def test_s_t_f_sequence_and_parent_chain(self):
        clk = FakeClock()
        tr = Tracer(clock=clk, id_tag="r0")
        ctx = TraceContext.mint(1)
        e1 = emit_flow(tr, ctx, "enqueue", request_id=1)
        clk.advance(0.5)
        e2 = emit_flow(tr, ctx, "prefill")
        clk.advance(0.5)
        e3 = emit_flow(tr, ctx, "finish", final=True)
        assert [e["ph"] for e in (e1, e2, e3)] == ["s", "t", "f"]
        assert e3["bp"] == "e"      # flow end binds to enclosing slice
        assert e1["args"]["parent"] == "root"
        assert e2["args"]["parent"] == e1["args"]["span"]
        assert e3["args"]["parent"] == e2["args"]["span"]
        assert e1["args"]["span"] == "req:1#0.enqueue.0"
        assert all(e["id"] == "req:1" for e in (e1, e2, e3))
        assert all(e["args"]["replica"] == "r0" for e in (e1, e2, e3))
        rep = check_flows(tr.events)
        assert rep["complete"] == ["req:1"] and not rep["broken"]
        info = rep["chains"]["req:1"]
        assert info["replicas"] == ["r0"] and not info["migrated"]

    def test_noop_without_tracer_or_context(self):
        ctx = TraceContext.mint(1)
        assert emit_flow(None, ctx, "enqueue") is None
        assert not ctx.started and ctx.seq == 0     # untouched
        assert emit_flow(Tracer(clock=FakeClock()), None, "x") is None

    def test_hop_lands_in_span_id(self):
        tr = Tracer(clock=FakeClock(), id_tag="r1")
        ctx = TraceContext.mint(4)
        emit_flow(tr, ctx, "enqueue")
        ctx.next_hop("r1")
        ev = emit_flow(tr, ctx, "migrate_in")
        assert ev["args"]["span"].startswith("req:4#1.migrate_in.")
        assert ev["args"]["hop"] == 1


# -- check_flows -------------------------------------------------------------

def _flow(ph, tid, ts, span, parent, phase, replica, **extra):
    args = {"span": span, "parent": parent, "phase": phase,
            "replica": replica, **extra}
    ev = {"name": "request", "ph": ph, "cat": "reqflow", "id": tid,
          "ts": ts, "pid": 1, "tid": 1, "args": args}
    if ph == "f":
        ev["bp"] = "e"
    return ev


def _chain(tid="req:0", replica="r0"):
    return [
        _flow("s", tid, 0.0, "a", "root", "enqueue", replica),
        _flow("t", tid, 1.0, "b", "a", "prefill", replica),
        _flow("f", tid, 2.0, "c", "b", "finish", replica),
    ]


class TestCheckFlows:
    def test_happy_path(self):
        rep = check_flows(_chain())
        assert rep["complete"] == ["req:0"]
        assert rep["broken"] == {} and rep["orphans"] == []
        assert rep["chains"]["req:0"]["phases"] == \
            ["enqueue", "prefill", "finish"]

    def test_double_start(self):
        evs = _chain() + [_flow("s", "req:0", 0.5, "z", "root",
                                "enqueue", "r0")]
        rep = check_flows(evs)
        assert any("flow starts" in p
                   for p in rep["broken"]["req:0"])

    def test_missing_finish(self):
        evs = _chain()[:2]
        rep = check_flows(evs)
        assert any("no flow end" in p for p in rep["broken"]["req:0"])
        # the in-flight view tolerates unfinished chains
        assert check_flows(evs, require_finish=False)["broken"] == {}

    def test_dangling_parent(self):
        evs = _chain()
        evs[1]["args"]["parent"] = "never-emitted"
        rep = check_flows(evs)
        assert any("dangling parent" in p
                   for p in rep["broken"]["req:0"])

    def test_event_after_last_end(self):
        evs = _chain() + [_flow("t", "req:0", 5.0, "d", "c",
                                "late", "r0")]
        rep = check_flows(evs)
        assert any("after the last flow end" in p
                   for p in rep["broken"]["req:0"])

    def test_migrated_must_span_two_replicas(self):
        evs = [
            _flow("s", "req:1", 0.0, "a", "root", "enqueue", "r0"),
            _flow("t", "req:1", 1.0, "b", "a", "migrate_out", "r0"),
            _flow("f", "req:1", 2.0, "c", "b", "finish", "r0"),
        ]
        rep = check_flows(evs)
        assert any("single replica" in p
                   for p in rep["broken"]["req:1"])
        evs[2]["args"]["replica"] = "r2"     # the adopted hop
        rep = check_flows(evs)
        assert rep["complete"] == ["req:1"]
        assert rep["chains"]["req:1"]["migrated"]
        assert rep["chains"]["req:1"]["replicas"] == ["r0", "r2"]

    def test_orphan_request_slices(self):
        claimed = _chain(replica="r0")
        claimed[0]["args"]["request_id"] = 5
        slices = [
            {"name": "request", "ph": "b", "cat": "request",
             "id": "r0/5", "ts": 0.0},
            {"name": "request", "ph": "b", "cat": "request",
             "id": "r9/42", "ts": 0.0},
        ]
        rep = check_flows(claimed + slices)
        assert rep["orphans"] == ["r9/42"]


# -- clock alignment and the merged timeline ---------------------------------

class TestAlignment:
    def test_align_offset_rules(self):
        assert align_offset(None, (0.0, 1.0)) == 0.0
        assert align_offset((0.0, 1.0), None) == 0.0
        # overlapping ranges share a clock: no shift
        assert align_offset((0.0, 10.0), (5.0, 15.0)) == 0.0
        # disjoint ranges: min-to-min
        assert align_offset((0.0, 10.0), (100.0, 110.0)) == -100.0
        assert align_offset((100.0, 110.0), (0.0, 10.0)) == 100.0

    def test_collector_incremental_union(self):
        fc = FleetCollector()
        # r0 anchors at 100..200 us; r1 is on a disjoint epoch;
        # r2 overlaps the union after r1 folded in, so it stays put
        fc.add_replica("r0", trace_events=[
            {"name": "x", "ph": "X", "ts": 100.0, "dur": 1.0},
            {"name": "x", "ph": "X", "ts": 200.0, "dur": 1.0}])
        fc.add_replica("r1", trace_events=[
            {"name": "y", "ph": "X", "ts": 1e6, "dur": 1.0}])
        fc.add_replica("r2", trace_events=[
            {"name": "z", "ph": "X", "ts": 150.0, "dur": 1.0}])
        offs = fc.offsets_us()
        assert offs["r0"] == 0.0
        assert offs["r1"] == 100.0 - 1e6
        assert offs["r2"] == 0.0

    def test_events_lanes_and_order(self):
        fc = FleetCollector()
        fc.add_replica("r0", trace_events=[
            {"name": "a0", "ph": "X", "ts": 5.0, "tid": 7},
            {"name": "a1", "ph": "X", "ts": 50.0, "tid": 7}])
        fc.add_replica("r1", trace_events=[
            {"name": "b", "ph": "X", "ts": 10.0, "tid": 9}])
        evs = fc.events()
        # overlapping ranges share the clock; output is ts-sorted
        assert [e["name"] for e in evs] == ["a0", "b", "a1"]
        by_name = {e["name"]: e for e in evs}
        assert by_name["a0"]["pid"] == FleetCollector.PID_BASE
        assert by_name["b"]["pid"] == FleetCollector.PID_BASE + 1
        assert by_name["a0"]["tid"] == by_name["a0"]["pid"]

    def test_merged_timeline_shape(self, tmp_path):
        fc = FleetCollector()
        fc.add_replica("r0", trace_events=[
            {"name": "a", "ph": "X", "ts": 1.0}])
        fc.add_replica("r1", trace_events=[])
        tl = fc.merged_timeline()
        lanes = [e for e in tl["traceEvents"] if e["ph"] == "M"]
        assert [e["args"]["name"] for e in lanes] == \
            ["replica:r0", "replica:r1"]
        assert "apex_tpu.fleet_offsets_us" in tl["metadata"]
        path = fc.save(str(tmp_path / "merged.json"))
        with open(path, encoding="utf-8") as f:
            assert json.load(f)["displayTimeUnit"] == "ms"


# -- fleet-level aggregation over real registries ----------------------------

def _replica_stream(clk, ttfts, requests, occupancy, health=None):
    """One replica's JSONL stream, produced by the real registry."""
    buf = io.StringIO()
    reg = MetricsRegistry(clock=clk)
    reg.attach_stream(buf)
    c = reg.counter("serving_requests_total", "done",
                    labelnames=("reason",))
    g = reg.gauge("serving_slot_occupancy", "busy/total")
    h = reg.histogram("serving_ttft_seconds", "ttft",
                      buckets=(0.05, 0.1, 0.25, 0.5, 1.0))
    for v in ttfts:
        clk.advance(0.1)
        h.observe(v)
    for _ in range(requests):
        clk.advance(0.1)
        c.inc(reason="finished")
    clk.advance(0.1)
    g.set(occupancy)
    if health is not None:
        reg.event("replica_health", replica=health[0], state=health[1])
    return buf.getvalue().splitlines()


class TestFleetAggregation:
    def test_fleet_series_sums_across_replicas(self):
        clk = FakeClock(10.0)
        fc = FleetCollector()
        fc.add_replica("r0", jsonl_lines=_replica_stream(
            clk, [0.02, 0.03], requests=3, occupancy=0.5))
        fc.add_replica("r1", jsonl_lines=_replica_stream(
            clk, [0.04], requests=2, occupancy=0.25))
        series = fc.fleet_series()
        assert series["fleet_serving_requests_total"] == 5.0
        assert series["fleet_serving_ttft_seconds_count"] == 3.0
        assert series["fleet_serving_ttft_seconds_sum"] == \
            pytest.approx(0.09)

    def test_fleet_burn_counts_bad_observations(self):
        clk = FakeClock(10.0)
        good = FleetCollector()
        good.add_replica("r0", jsonl_lines=_replica_stream(
            clk, [0.01] * 8, requests=0, occupancy=0.0))
        assert good.fleet_burn()["ttft_le_0.5"] == 0.0
        bad = FleetCollector()
        bad.add_replica("r0", jsonl_lines=_replica_stream(
            clk, [0.01] * 4, requests=0, occupancy=0.0))
        bad.add_replica("r1", jsonl_lines=_replica_stream(
            clk, [2.0] * 4, requests=0, occupancy=0.0))
        # 4/8 observations blow the 0.5 s target, objective 0.95:
        # burn = (4/8) / 0.05 = 10x budget
        assert bad.fleet_burn()["ttft_le_0.5"] == pytest.approx(10.0)

    def test_replica_table(self):
        clk = FakeClock(10.0)
        fc = FleetCollector()
        fc.add_replica("r0", jsonl_lines=_replica_stream(
            clk, [0.02], requests=4, occupancy=0.75,
            health=(0, "healthy")))
        fc.add_replica("r1", jsonl_lines=_replica_stream(
            clk, [], requests=1, occupancy=0.0, health=(1, "dead")))
        rows = {r["replica"]: r for r in fc.replica_table()}
        assert rows["r0"]["requests"] == 4
        assert rows["r0"]["occupancy"] == 0.75
        assert rows["r0"]["health"] == "healthy"
        assert rows["r1"]["health"] == "dead"
        assert "ttft_le_0.5" in rows["r0"]["burn"]


# -- flight recorder ---------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_bounded(self):
        clk = FakeClock()
        fr = FlightRecorder(clock=clk, keep=4)
        for i in range(10):
            clk.advance(0.1)
            fr.record("router", "tick", n=i)
        snap = fr.trigger("test")
        ns = [e["n"] for e in snap["sources"]["router"]]
        assert ns == [6, 7, 8, 9]

    def test_window_filter(self):
        clk = FakeClock()
        fr = FlightRecorder(clock=clk, window_s=30.0)
        fr.record("eng", "early", n=0)          # t=0
        clk.t = 100.0
        fr.record("eng", "late", n=1)           # t=100
        clk.t = 105.0
        snap = fr.trigger("replica_dead", replica=1)
        kinds = [e["kind"] for e in snap["sources"]["eng"]]
        assert kinds == ["late"]                # t=0 outside +/-30 s
        assert snap["details"] == {"replica": 1}
        assert snap["ts"] == 105.0

    def test_dump_retention_and_counter(self):
        clk = FakeClock()
        reg = MetricsRegistry(clock=clk)
        fr = FlightRecorder(clock=clk, max_dumps=2, registry=reg)
        assert fr.last is None
        for i in range(3):
            fr.trigger("ladder_escalation", step=i)
        assert len(fr.dumps) == 2
        assert fr.last["seq"] == 2              # newest survives
        assert fr.dumps[0]["seq"] == 1          # oldest evicted
        snap = reg.snapshot()["flight_recorder_snapshots_total"]
        assert sum(snap["series"].values()) == 3.0

    def test_save(self, tmp_path):
        fr = FlightRecorder(clock=FakeClock())
        fr.record("src", "k", a=1)
        fr.trigger("guard_rollback")
        path = fr.save(str(tmp_path / "blackbox.json"))
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        assert data["snapshots"][0]["trigger"] == "guard_rollback"


# -- bench-diff regression gate ----------------------------------------------

class TestBenchDiff:
    def test_direction(self):
        bd = _tools()
        assert bd.direction("bert_tokens_per_s") == 1    # despite _s
        assert bd.direction("mfu") == 1
        assert bd.direction("pipeline_bubble_fraction") == 1
        assert bd.direction("ttft_p99_s") == -1
        assert bd.direction("step_time_s") == -1
        assert bd.direction("allreduce_overhead") == -1
        assert bd.direction("num_layers") == 0

    def test_scan_legs_recovers_truncated_tail(self):
        bd = _tools()
        # a byte-truncated suffix: headless start, complete middle
        # legs, a final leg cut mid-dict
        text = ('456}, "lamb": {"tokens_per_s": 10.0, "mfu": 0.3}, '
                '"extra": {"note": 1}, '
                '"cut": {"tokens_per_s": 9')
        legs = bd._scan_legs(text)
        assert legs == {"lamb": {"tokens_per_s": 10.0, "mfu": 0.3}}

    def test_diff_legs_flags_both_directions(self):
        bd = _tools()
        old = {"leg": {"tokens_per_s": 100.0, "step_time_s": 1.0,
                       "num_layers": 12.0}}
        new = {"leg": {"tokens_per_s": 80.0, "step_time_s": 1.5,
                       "num_layers": 24.0}}
        res = bd.diff_legs(old, new, threshold=0.1)
        flagged = {r["key"] for r in res["regressions"]}
        # throughput fell AND latency rose -> both regress;
        # unknown-direction keys are reported but never flagged
        assert flagged == {"tokens_per_s", "step_time_s"}
        assert res["legs_compared"] == 1
        improved = bd.diff_legs(new, old, threshold=0.1)
        assert improved["regressions"] == []

    def test_diff_legs_noise_floor(self):
        bd = _tools()
        # one recorded-resolution ULP: 20% relative, zero information
        old = {"leg": {"rank_s": 5e-05, "step_time_s": 1.0}}
        new = {"leg": {"rank_s": 6e-05, "step_time_s": 1.5}}
        res = bd.diff_legs(old, new, threshold=0.1)
        assert {r["key"] for r in res["regressions"]} == {"step_time_s"}
        # still reported as a row, just never gating
        assert any(r["key"] == "rank_s" and not r["regressed"]
                   for r in res["rows"])
        # floor 0 restores the old behavior
        res0 = bd.diff_legs(old, new, threshold=0.1, noise_floor=0.0)
        assert {r["key"] for r in res0["regressions"]} \
            == {"rank_s", "step_time_s"}

    def test_diff_legs_skips_near_zero_and_disjoint(self):
        bd = _tools()
        res = bd.diff_legs({"a": {"mfu": 0.0}, "gone": {"x": 1.0}},
                           {"a": {"mfu": 0.5}, "added": {"y": 1.0}})
        assert res["rows"] == []                # |old| < eps skipped
        assert res["legs_only_old"] == ["gone"]
        assert res["legs_only_new"] == ["added"]

    def test_extract_legs_round_file_and_tail(self, tmp_path):
        bd = _tools()
        rnd = tmp_path / "round.json"
        rnd.write_text(json.dumps({
            "rc": 0, "parsed": {
                "metric": "tokens_per_s", "value": 123.0,
                "extra": {"lamb": {"mfu": 0.4}, "note": "str"}}}))
        legs = bd.extract_legs(str(rnd))
        assert legs["headline"] == {"tokens_per_s": 123.0}
        assert legs["lamb"] == {"mfu": 0.4} and "note" not in legs
        raw = tmp_path / "stdout.txt"
        raw.write_text("noise\n"
                       '{"metric": "mfu", "value": 0.5}\n')
        assert bd.extract_legs(str(raw))["headline"] == {"mfu": 0.5}

    def test_committed_rounds_skips_local_scratch(self):
        paths = [os.path.basename(p)
                 for p in _tools().committed_rounds()]
        assert all(p.endswith(".json") and "_local" not in p
                   for p in paths)
        assert paths == sorted(
            paths, key=lambda p: int(p[len("BENCH_r"):-len(".json")]))

    def test_render(self):
        bd = _tools()
        res = bd.diff_legs({"leg": {"tokens_per_s": 100.0}},
                           {"leg": {"tokens_per_s": 50.0}})
        out = io.StringIO()
        bd.render(res, "old.json", "new.json", 0.1, out=out)
        text = out.getvalue()
        assert "REGRESSION leg.tokens_per_s" in text
        assert "-50.0%" in text

    def test_main_is_nonfatal_report(self):
        # the committed-rounds comparison never fails without --strict
        assert _tools().main([]) == 0


# -- the acceptance criterion: continuity under chaos ------------------------

def _scenario_ns(**kw):
    base = dict(
        scenario="replica_kill", requests=8, rate=1e9, replicas=3,
        max_slots=2, max_queue=64, max_queue_depth=4,
        burn_threshold=14.4, burn_window_s=60.0, ttft_slo_s=0.5,
        block_size=4, chunked=False, token_budget=32, client_retries=3,
        tick_s=0.02, e2e_slo_s=3.0, max_ticks=600, retry_budget=4,
        hedge_after_s=None, ladder_step_down_s=0.5, kill_tick=3,
        kill_replica=1, kill_duration=10 ** 6, slow_tick=4, slow_s=0.1,
        slow_duration=40, burst_n=4, burst_gap_s=0.3, period_s=2.0,
        seed=0, min_prompt=4, pareto_shape=2.5, max_new=4,
        shared_prefix_prob=0.5, shared_prefix_len=8, num_prefixes=2,
        vocab=32, hidden=16, layers=2, heads=2, max_seq=32)
    base.update(kw)
    return argparse.Namespace(**base)


def _loadgen():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    try:
        return importlib.import_module("loadgen")
    finally:
        sys.path.pop(0)


class TestChaosContinuity:
    def test_replica_kill_chains_stay_connected(self):
        rep = _loadgen().run_scenario(_scenario_ns())
        cont = rep["trace_continuity"]
        # every submitted request's flow chain survived the kill,
        # migration and resume with linkage intact
        assert cont["chains"] == rep["submitted"]
        assert cont["complete"] == cont["chains"]
        assert cont["broken"] == {} and cont["orphans"] == []
        # the kill actually migrated work, and the migrated chains are
        # visible as such on the merged timeline
        assert rep["migrations"] > 0
        assert len(cont["migrated_chains"]) > 0
        # the replica death cut exactly one flight-recorder snapshot
        assert rep["flight_snapshots"] == 1
