"""Data-parallel layer tests on the fake 8-device CPU mesh.

Apex pattern (``tests/distributed/DDP``, ``tests/distributed/
synced_batchnorm``): every parallel feature is checked against its serial
equivalent on the same total batch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.utils.collectives import shard_map_compat as shard_map
from apex_tpu.parallel import (DistributedDataParallel, SyncBatchNorm,
                               sync_batch_norm, allreduce_gradients, LARC,
                               Reducer)
from apex_tpu.parallel.distributed import _has_axis

# vma (varying-axes) tracking — and with it mark_local / invariant-grad
# detection — only exists on JAX ≥0.6; on older JAX every shard_map value
# is implicitly varying and jax.grad of replicated inputs auto-psums.
requires_vma = pytest.mark.skipif(
    not hasattr(jax, "typeof"),
    reason="needs vma tracking (jax.typeof); this JAX auto-psums grads "
           "of replicated shard_map inputs")
from apex_tpu.parallel.sync_batchnorm import BatchNormState
from apex_tpu.contrib.clip_grad import clip_grad_norm_
from apex_tpu.optimizers import FusedSGD


@pytest.fixture
def mesh():
    return jax.make_mesh((8,), ("data",))


def loss_fn(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


class TestDDP:
    def test_sharded_training_matches_serial(self, rng, mesh):
        """GSPMD path: jit with a batch-sharded input must produce the same
        grads as single-device full batch."""
        params = {"w": jnp.asarray(rng.randn(16, 4).astype(np.float32)),
                  "b": jnp.zeros((4,), jnp.float32)}
        x = jnp.asarray(rng.randn(64, 16).astype(np.float32))
        y = jnp.asarray(rng.randn(64, 4).astype(np.float32))
        serial = jax.grad(loss_fn)(params, x, y)

        ddp = DistributedDataParallel(mesh=mesh)
        params_r = ddp.broadcast_params(params)
        x_s, y_s = ddp.scatter(x), ddp.scatter(y)
        sharded = jax.jit(jax.grad(loss_fn))(params_r, x_s, y_s)
        for a, b in zip(jax.tree_util.tree_leaves(serial),
                        jax.tree_util.tree_leaves(sharded)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    @requires_vma
    def test_shard_map_reduce_matches_serial(self, rng, mesh):
        """Explicit-collective path: per-device grads + ddp.reduce =
        full-batch grads."""
        params = {"w": jnp.asarray(rng.randn(8, 2).astype(np.float32)),
                  "b": jnp.zeros((2,), jnp.float32)}
        x = jnp.asarray(rng.randn(32, 8).astype(np.float32))
        y = jnp.asarray(rng.randn(32, 2).astype(np.float32))
        ddp = DistributedDataParallel(mesh=mesh)

        @jax.jit
        def per_device_grads(params, x, y):
            def step(params, x, y):
                params = ddp.mark_local(params)   # apex staging: local grads
                g = jax.grad(loss_fn)(params, x, y)
                return ddp.reduce(g)              # ONE explicit allreduce
            return shard_map(step, mesh=mesh,
                             in_specs=(P(), P("data"), P("data")),
                             out_specs=P())(params, x, y)

        got = per_device_grads(params, x, y)
        ref = jax.grad(loss_fn)(params, x, y)
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    @requires_vma
    def test_reduce_of_invariant_grads_no_double_count(self, rng, mesh):
        """Grads computed WITHOUT mark_local come out device-invariant
        (jax.grad already psummed them); reduce() must not multiply them by
        world size again (JAX 0.9 vma regression)."""
        params = {"w": jnp.asarray(rng.randn(8, 2).astype(np.float32)),
                  "b": jnp.zeros((2,), jnp.float32)}
        x = jnp.asarray(rng.randn(32, 8).astype(np.float32))
        y = jnp.asarray(rng.randn(32, 2).astype(np.float32))
        ddp = DistributedDataParallel(mesh=mesh)

        @jax.jit
        def run(params, x, y):
            def step(params, x, y):
                g = jax.grad(loss_fn)(params, x, y)  # invariant (auto-psum)
                return ddp.reduce(g)
            return shard_map(step, mesh=mesh,
                             in_specs=(P(), P("data"), P("data")),
                             out_specs=P())(params, x, y)

        got = run(params, x, y)["w"]
        # auto-psum sums the 8 per-shard mean-grads; average divides by 8,
        # recovering the full-batch grad — NOT 8x it.
        ref = jax.grad(loss_fn)(params, x, y)["w"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_allreduce_under_vmap_axis(self):
        """vmap axes have no vma tracking; the invariant-skip must not
        fire there — psum runs normally."""
        out = jax.vmap(lambda g: allreduce_gradients(g, "data",
                                                     average=False),
                       axis_name="data")(jnp.arange(4.0))
        np.testing.assert_allclose(np.asarray(out), 6.0)

    def test_gradient_average_off(self, rng, mesh):
        params = {"w": jnp.ones((4, 2), jnp.float32)}
        grads = {"w": jnp.ones((8, 4, 2), jnp.float32)}  # per-device stack

        @jax.jit
        def run(g):
            ddp = DistributedDataParallel(mesh=mesh,
                                          gradient_average=False)
            return shard_map(lambda g: ddp.reduce(g[0]), mesh=mesh,
                             in_specs=(P("data"),), out_specs=P())(g)

        out = run(grads["w"])
        np.testing.assert_allclose(np.asarray(out), 8.0)

    def test_predivide_factor(self, rng, mesh):
        g = jnp.ones((8, 4, 128), jnp.float32)

        @jax.jit
        def run(g):
            ddp = DistributedDataParallel(mesh=mesh,
                                          gradient_predivide_factor=4.0)
            return shard_map(lambda g: ddp.reduce(g[0]), mesh=mesh,
                             in_specs=(P("data"),), out_specs=P())(g)

        np.testing.assert_allclose(np.asarray(run(g)), 1.0, rtol=1e-6)

    def test_predivide_factor_sum_mode(self, rng, mesh):
        """gradient_predivide_factor with gradient_average=False: apex's
        staging nets out to sum/factor (pre-divide runs unconditionally,
        the post-scale only fires when averaging)."""
        g = jnp.ones((8, 4, 128), jnp.float32)

        @jax.jit
        def run(g):
            ddp = DistributedDataParallel(mesh=mesh,
                                          gradient_predivide_factor=4.0,
                                          gradient_average=False)
            return shard_map(lambda g: ddp.reduce(g[0]), mesh=mesh,
                             in_specs=(P("data"),), out_specs=P())(g)

        # sum(1/4 each of 8 devices) = 2.0, no post-scale
        np.testing.assert_allclose(np.asarray(run(g)), 2.0, rtol=1e-6)

    def test_predivide_factor_fp32_sum_mode(self, rng, mesh):
        """Both post-scale-skipping knobs together: bf16 grads upcast by
        allreduce_always_fp32, predivided, summed — never rescaled."""
        g = jnp.full((8, 4, 128), 0.5, jnp.bfloat16)

        @jax.jit
        def run(g):
            ddp = DistributedDataParallel(mesh=mesh,
                                          gradient_predivide_factor=2.0,
                                          gradient_average=False,
                                          allreduce_always_fp32=True)
            return shard_map(lambda g: ddp.reduce(g[0]), mesh=mesh,
                             in_specs=(P("data"),), out_specs=P())(g)

        out = run(g)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), 2.0, rtol=1e-6)

    @pytest.mark.parametrize("mode,tol", [("f32", 0.0), ("bf16", 5e-3),
                                          ("int8", 2e-2)])
    def test_allreduce_dtype_modes(self, rng, mesh, mode, tol):
        """allreduce_dtype transport knob on ddp.reduce: f32 bitwise-
        equal to the default psum, bf16/int8 within documented error."""
        g = jnp.asarray(rng.randn(8, 16, 128).astype(np.float32))
        ref = np.mean(np.asarray(g), axis=0)

        @jax.jit
        def run(g):
            ddp = DistributedDataParallel(mesh=mesh, allreduce_dtype=mode)
            return shard_map(lambda g: ddp.reduce(g[0]), mesh=mesh,
                             in_specs=(P("data"),), out_specs=P())(g)

        out = np.asarray(run(g))
        if mode == "f32":
            base = DistributedDataParallel(mesh=mesh)

            @jax.jit
            def run_base(g):
                return shard_map(lambda g: base.reduce(g[0]), mesh=mesh,
                                 in_specs=(P("data"),), out_specs=P())(g)

            np.testing.assert_array_equal(out, np.asarray(run_base(g)))
        else:
            err = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
            assert err < tol, err

    def test_allreduce_dtype_requires_mesh(self):
        with pytest.raises(ValueError, match="mesh"):
            DistributedDataParallel(allreduce_dtype="int8")

    def test_reducer(self, mesh):
        r = Reducer()
        vals = jnp.arange(8.0)

        @jax.jit
        def run(v):
            return shard_map(lambda v: r.reduce(v, average=False),
                             mesh=mesh, in_specs=(P("data"),),
                             out_specs=P())(v)

        np.testing.assert_allclose(float(run(vals)[0]), 28.0)


class TestSyncBatchNorm:
    def test_matches_full_batch_bn(self, rng, mesh):
        """SyncBN over 8 shards == plain BN over the full batch (apex
        tests/distributed/synced_batchnorm)."""
        n, c, h, w = 32, 6, 4, 4
        x = jnp.asarray(rng.randn(n, c, h, w).astype(np.float32))
        bn = SyncBatchNorm(c, process_group="data")
        params = bn.init_params()
        state = bn.init_state()

        @jax.jit
        def sharded(x):
            def f(x):
                y, st = bn(params, state, x, training=True)
                return y, st
            return shard_map(f, mesh=mesh, in_specs=(P("data"),),
                             out_specs=(P("data"), P()))(x)

        y_sync, st_sync = sharded(x)
        bn_serial = SyncBatchNorm(c, process_group=None)
        y_ref, st_ref = bn_serial(params, state, x, training=True)
        np.testing.assert_allclose(np.asarray(y_sync), np.asarray(y_ref),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(st_sync.running_mean),
                                   np.asarray(st_ref.running_mean),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(st_sync.running_var),
                                   np.asarray(st_ref.running_var),
                                   rtol=1e-4, atol=1e-5)

    def test_eval_uses_running_stats(self, rng):
        bn = SyncBatchNorm(3)
        params, state = bn.init_params(), bn.init_state()
        state = BatchNormState(jnp.asarray([1.0, 2.0, 3.0]),
                               jnp.asarray([4.0, 4.0, 4.0]),
                               jnp.ones((), jnp.int32))
        x = jnp.zeros((2, 3, 2, 2))
        y, st = bn(params, state, x, training=False)
        # (0 - mean)/2
        np.testing.assert_allclose(np.asarray(y[0, :, 0, 0]),
                                   [-0.5, -1.0, -1.5], rtol=1e-5)

    def test_no_track_running_stats_uses_batch_stats(self, rng):
        """track_running_stats=False in training: normalize with BATCH
        stats (torch/apex semantics), state untouched."""
        x = jnp.asarray(rng.randn(16, 4, 3, 3).astype(np.float32))
        bn = SyncBatchNorm(4, track_running_stats=False)
        params, state = bn.init_params(), bn.init_state()
        y, st = bn(params, state, x, training=True)
        m = np.asarray(y).transpose(0, 2, 3, 1).reshape(-1, 4).mean(0)
        np.testing.assert_allclose(m, 0.0, atol=1e-5)  # batch-normalized
        np.testing.assert_allclose(np.asarray(st.running_mean),
                                   np.asarray(state.running_mean))
        assert int(st.num_batches_tracked) == 0
        # eval mode: torch still uses BATCH stats when not tracking
        y_ev, _ = bn(params, state, x, training=False)
        m_ev = np.asarray(y_ev).transpose(0, 2, 3, 1).reshape(-1, 4).mean(0)
        np.testing.assert_allclose(m_ev, 0.0, atol=1e-5)

    def test_channel_last(self, rng):
        x = jnp.asarray(rng.randn(8, 4, 4, 6).astype(np.float32))
        bn = SyncBatchNorm(6, channel_last=True)
        y, _ = bn(bn.init_params(), bn.init_state(), x, training=True)
        m = np.asarray(y).reshape(-1, 6).mean(0)
        np.testing.assert_allclose(m, 0.0, atol=1e-5)

    def test_grad_flows(self, rng):
        x = jnp.asarray(rng.randn(8, 4, 2, 2).astype(np.float32))
        bn = SyncBatchNorm(4)
        params, state = bn.init_params(), bn.init_state()
        g = jax.grad(lambda p: jnp.sum(bn(p, state, x)[0] ** 2))(params)
        assert np.all(np.isfinite(np.asarray(g["weight"])))


class TestLARCAndClipGrad:
    def test_larc_clips_adaptive_lr(self, rng):
        params = {"w": jnp.asarray(rng.randn(32, 32).astype(np.float32))}
        grads = {"w": jnp.asarray(
            rng.randn(32, 32).astype(np.float32) * 100.0)}
        base = FusedSGD(lr=0.1)
        opt = LARC(base, trust_coefficient=0.001)
        state = opt.init(params)
        p1, _ = opt.step(grads, params, state)
        # huge grads → adaptive lr ≪ base lr → small update
        delta = float(jnp.max(jnp.abs(p1["w"] - params["w"])))
        p_ref, _ = base.step(grads, params, base.init(params))
        delta_ref = float(jnp.max(jnp.abs(p_ref["w"] - params["w"])))
        assert delta < delta_ref * 0.1

    def test_larc_scale_formula(self, rng):
        p = jnp.ones((4, 4)) * 2.0
        g = jnp.ones((4, 4)) * 0.5
        params, grads = {"w": p}, {"w": g}
        base = FusedSGD(lr=0.1)
        opt = LARC(base, trust_coefficient=0.02, clip=True)
        p1, _ = opt.step(grads, params, opt.init(params))
        pn, gn = float(jnp.linalg.norm(p)), float(jnp.linalg.norm(g))
        adaptive = 0.02 * pn / gn
        scale = min(adaptive / 0.1, 1.0)
        ref = np.asarray(p) - 0.1 * scale * np.asarray(g)
        np.testing.assert_allclose(np.asarray(p1["w"]), ref, rtol=1e-5)

    def test_clip_grad_norm(self, rng):
        grads = {"a": jnp.asarray(rng.randn(100).astype(np.float32) * 10),
                 "b": jnp.asarray(rng.randn(50).astype(np.float32) * 10)}
        clipped, norm = clip_grad_norm_(grads, max_norm=1.0)
        total = np.sqrt(sum(float(jnp.sum(g ** 2))
                            for g in jax.tree_util.tree_leaves(grads)))
        np.testing.assert_allclose(float(norm), total, rtol=1e-5)
        new_norm = np.sqrt(sum(float(jnp.sum(g ** 2))
                               for g in
                               jax.tree_util.tree_leaves(clipped)))
        np.testing.assert_allclose(new_norm, 1.0, rtol=1e-3)

    def test_clip_noop_when_small(self, rng):
        grads = {"a": jnp.asarray([0.1, 0.1], dtype=jnp.float32)}
        clipped, norm = clip_grad_norm_(grads, max_norm=10.0)
        np.testing.assert_allclose(np.asarray(clipped["a"]),
                                   np.asarray(grads["a"]), rtol=1e-6)


class TestMainGradAccumulation:
    """apex gradient_accumulation_fusion / main_grad contract: microbatch
    grads accumulate in fp32 regardless of model dtype
    (reference fused_weight_gradient_mlp_cuda)."""

    def test_accumulate_fp32_main_grad(self):
        g_bf16 = {"w": jnp.full((4,), 0.1, jnp.bfloat16)}
        acc = DistributedDataParallel.accumulate(
            None, g_bf16, main_grad_dtype=jnp.float32)
        assert acc["w"].dtype == jnp.float32
        for _ in range(63):
            acc = DistributedDataParallel.accumulate(
                acc, g_bf16, main_grad_dtype=jnp.float32)
        # 64 accumulations of bf16(0.1): fp32 accumulation keeps the sum
        # accurate to bf16(0.1)*64, bf16 accumulation would have drifted
        expect = 64 * float(jnp.bfloat16(0.1))
        np.testing.assert_allclose(np.asarray(acc["w"]), expect,
                                   rtol=1e-6)

    def test_accumulate_default_keeps_dtype(self):
        g = {"w": jnp.ones((4,), jnp.bfloat16)}
        acc = DistributedDataParallel.accumulate(None, g)
        assert acc["w"].dtype == jnp.bfloat16


class TestHasAxis:
    """_has_axis must treat every 'unbound axis name' exception flavor —
    NameError classically, but newer JAX generations raise KeyError /
    ValueError / TypeError from the axis-env lookup — as False."""

    def test_unbound_axis_outside_trace(self):
        assert _has_axis("no_such_axis") is False

    def test_bound_axis_inside_shard_map(self, mesh):
        seen = []

        def body(x):
            seen.append((_has_axis("data"), _has_axis("bogus")))
            return x

        shard_map(body, mesh=mesh, in_specs=(P("data"),),
                  out_specs=P("data"))(jnp.arange(8.0))
        assert seen and seen[0] == (True, False)

    def test_bound_axis_under_vmap(self):
        seen = []

        def body(x):
            seen.append(_has_axis("batch"))
            return x

        jax.vmap(body, axis_name="batch")(jnp.arange(4.0))
        assert seen == [True]


class TestContribOptimizerShims:
    def test_deprecated_reexports(self):
        from apex_tpu.contrib import optimizers as co
        from apex_tpu.fp16_utils import FP16_Optimizer
        from apex_tpu.optimizers import FusedAdam, FusedLAMB
        assert co.FusedAdam is FusedAdam
        assert co.FusedLamb is FusedLAMB
        assert co.FP16_Optimizer is FP16_Optimizer
