"""Flash attention: Pallas kernel vs materialized-scores reference.

Mirrors the reference's contrib attention tests
(``apex/contrib/test/fmha/test_fmha.py``,
``test/multihead_attn/test_self_multihead_attn.py``): the fused op is
compared against the unfused reference on the same inputs, fwd and bwd,
at dtype-appropriate tolerances.  The Pallas path runs in interpret mode
on CPU; the same tests re-run on hardware via the on-chip lane.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_reference,
)
from apex_tpu.utils import set_force_pallas


@pytest.fixture(autouse=True)
def _force_pallas():
    set_force_pallas(True)
    yield
    set_force_pallas(None)


def _inputs(rng, b, h, sq, sk, d, dtype):
    q = jnp.asarray(rng.randn(b, h, sq, d), dtype)
    k = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    v = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    return q, k, v


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=2e-5, atol=2e-5)


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_matches_reference(self, rng, causal, dtype):
        q, k, v = _inputs(rng, 2, 3, 256, 256, 64, dtype)
        out = flash_attention(q, k, v, causal=causal)
        ref = flash_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   **_tol(dtype))

    def test_non_multiple_seq(self, rng):
        # seq not a multiple of the 128 block: padding must wash out
        q, k, v = _inputs(rng, 1, 2, 200, 200, 48, jnp.float32)
        out = flash_attention(q, k, v, causal=True)
        ref = flash_attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_cross_attention_seqs(self, rng):
        q, k, v = _inputs(rng, 2, 2, 128, 384, 64, jnp.float32)
        out = flash_attention(q, k, v)
        ref = flash_attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_kv_seqlens_padding(self, rng):
        q, k, v = _inputs(rng, 3, 2, 128, 256, 32, jnp.float32)
        lens = jnp.asarray([256, 100, 17], jnp.int32)
        out = flash_attention(q, k, v, kv_seqlens=lens)
        ref = flash_attention_reference(q, k, v, kv_seqlens=lens)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_custom_scale(self, rng):
        q, k, v = _inputs(rng, 1, 2, 128, 128, 64, jnp.float32)
        out = flash_attention(q, k, v, softmax_scale=0.5)
        ref = flash_attention_reference(q, k, v, softmax_scale=0.5)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, rng, causal):
        q, k, v = _inputs(rng, 2, 2, 256, 256, 64, jnp.float32)

        def fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

        def ref(q, k, v):
            return jnp.sum(
                flash_attention_reference(q, k, v, causal=causal) ** 2)

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=5e-5, atol=5e-5)

    def test_grads_non_multiple_seq(self, rng):
        q, k, v = _inputs(rng, 1, 2, 200, 200, 48, jnp.float32)

        def fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def ref(q, k, v):
            return jnp.sum(
                flash_attention_reference(q, k, v, causal=True) ** 2)

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=5e-5, atol=5e-5)

    def test_grads_kv_seqlens(self, rng):
        q, k, v = _inputs(rng, 2, 2, 128, 256, 32, jnp.float32)
        lens = jnp.asarray([256, 77], jnp.int32)

        def fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, kv_seqlens=lens) ** 2)

        def ref(q, k, v):
            return jnp.sum(
                flash_attention_reference(q, k, v, kv_seqlens=lens) ** 2)

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=5e-5, atol=5e-5)

    def test_grads_bf16(self, rng):
        q, k, v = _inputs(rng, 1, 2, 128, 128, 64, jnp.bfloat16)

        def fused(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True).astype(jnp.float32))

        def ref(q, k, v):
            return jnp.sum(flash_attention_reference(
                q, k, v, causal=True).astype(jnp.float32))

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(np.asarray(gf, np.float32),
                                       np.asarray(gr, np.float32),
                                       rtol=5e-2, atol=5e-2)

    def test_jit_grad_composes(self, rng):
        q, k, v = _inputs(rng, 1, 1, 128, 128, 64, jnp.float32)
        g = jax.jit(jax.grad(
            lambda q: jnp.sum(flash_attention(q, k, v, causal=True))))(q)
        assert np.all(np.isfinite(g))


class TestFusedDropout:
    """Fused probability dropout (reference: apex's philox-fused attention
    dropout, ``apex/contrib/csrc/multihead_attn/dropout.cuh``): the keep
    mask is a counter-hash pure function of (seed, bh, q_pos, k_pos), so
    the kernel's mask can be replayed densely and the fused path compared
    EXACTLY (not just statistically) against the materialized reference."""

    RATE, SEED = 0.2, 987

    def _mask(self, b, h, sq, sk):
        from apex_tpu.ops.flash_attention import dropout_keep_scale
        return dropout_keep_scale(self.SEED, b * h, sq, sk,
                                  self.RATE).reshape(b, h, sq, sk)

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_replayed_mask(self, rng, causal):
        q, k, v = _inputs(rng, 2, 3, 256, 256, 64, jnp.float32)
        out = flash_attention(q, k, v, causal=causal, dropout=self.RATE,
                              dropout_seed=self.SEED)
        ref = flash_attention_reference(q, k, v, causal=causal,
                                        dropout_mask=self._mask(2, 3, 256,
                                                                256))
        np.testing.assert_allclose(out, ref, rtol=5e-5, atol=5e-5)

    def test_grads_match_replayed_mask(self, rng):
        q, k, v = _inputs(rng, 2, 2, 256, 256, 64, jnp.float32)
        mask = self._mask(2, 2, 256, 256)

        def fused(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=True, dropout=self.RATE,
                dropout_seed=self.SEED) ** 2)

        def ref(q, k, v):
            return jnp.sum(flash_attention_reference(
                q, k, v, causal=True, dropout_mask=mask) ** 2)

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=1e-4, atol=1e-4)

    def test_deterministic_and_seed_sensitive(self, rng):
        q, k, v = _inputs(rng, 1, 2, 128, 128, 32, jnp.float32)
        a = flash_attention(q, k, v, dropout=self.RATE, dropout_seed=7)
        b = flash_attention(q, k, v, dropout=self.RATE, dropout_seed=7)
        c = flash_attention(q, k, v, dropout=self.RATE, dropout_seed=8)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert float(jnp.max(jnp.abs(a - c))) > 0.0

    def test_block_size_invariant(self, rng):
        # the mask hashes GLOBAL positions, so retiling cannot change it
        q, k, v = _inputs(rng, 1, 2, 256, 256, 64, jnp.float32)
        a = flash_attention(q, k, v, dropout=self.RATE,
                            dropout_seed=self.SEED, block_q=128,
                            block_k=128)
        b = flash_attention(q, k, v, dropout=self.RATE,
                            dropout_seed=self.SEED, block_q=64,
                            block_k=128)
        np.testing.assert_allclose(a, b, rtol=5e-5, atol=5e-5)

    def test_keep_statistics(self):
        from apex_tpu.ops.flash_attention import dropout_keep_scale
        m = dropout_keep_scale(42, 4, 512, 512, 0.3)
        keep = float(jnp.mean(m > 0))
        assert abs(keep - 0.7) < 0.01, keep
        # inverted dropout: E[D] == 1
        assert abs(float(jnp.mean(m)) - 1.0) < 0.02

    def test_mean_preserving_vs_no_dropout(self, rng):
        # E over masks of the dropped output == undropped output, row by
        # row (inverted dropout scales keeps by 1/(1-r)); with many seeds
        # the average converges
        q, k, v = _inputs(rng, 1, 1, 128, 128, 32, jnp.float32)
        base = flash_attention(q, k, v)
        acc = jnp.zeros_like(base)
        n = 32
        for s in range(n):
            acc = acc + flash_attention(q, k, v, dropout=0.5,
                                        dropout_seed=s)
        err = float(jnp.max(jnp.abs(acc / n - base)))
        assert err < 0.35, err    # 1/sqrt(32) Monte-Carlo band

    def test_dropout_needs_seed(self, rng):
        q, k, v = _inputs(rng, 1, 1, 128, 128, 32, jnp.float32)
        with pytest.raises(ValueError, match="dropout_seed"):
            flash_attention(q, k, v, dropout=0.5)
        with pytest.raises(ValueError, match="dropout must be"):
            flash_attention(q, k, v, dropout=1.5, dropout_seed=0)

    def test_fallback_path_identical_mask(self, rng):
        # the jnp fallback replays the SAME hash mask the kernel uses —
        # bit-identical dropout pattern on every backend
        q, k, v = _inputs(rng, 1, 2, 128, 128, 32, jnp.float32)
        fused = flash_attention(q, k, v, dropout=self.RATE,
                                dropout_seed=self.SEED)
        set_force_pallas(False)
        try:
            fallback = flash_attention(q, k, v, dropout=self.RATE,
                                       dropout_seed=self.SEED)
        finally:
            set_force_pallas(True)
        np.testing.assert_allclose(fused, fallback, rtol=5e-5, atol=5e-5)
