"""Flash attention: Pallas kernel vs materialized-scores reference.

Mirrors the reference's contrib attention tests
(``apex/contrib/test/fmha/test_fmha.py``,
``test/multihead_attn/test_self_multihead_attn.py``): the fused op is
compared against the unfused reference on the same inputs, fwd and bwd,
at dtype-appropriate tolerances.  The Pallas path runs in interpret mode
on CPU; the same tests re-run on hardware via the on-chip lane.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_reference,
)
from apex_tpu.utils import set_force_pallas


@pytest.fixture(autouse=True)
def _force_pallas():
    set_force_pallas(True)
    yield
    set_force_pallas(None)


def _inputs(rng, b, h, sq, sk, d, dtype):
    q = jnp.asarray(rng.randn(b, h, sq, d), dtype)
    k = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    v = jnp.asarray(rng.randn(b, h, sk, d), dtype)
    return q, k, v


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=2e-5, atol=2e-5)


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_matches_reference(self, rng, causal, dtype):
        q, k, v = _inputs(rng, 2, 3, 256, 256, 64, dtype)
        out = flash_attention(q, k, v, causal=causal)
        ref = flash_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   **_tol(dtype))

    def test_non_multiple_seq(self, rng):
        # seq not a multiple of the 128 block: padding must wash out
        q, k, v = _inputs(rng, 1, 2, 200, 200, 48, jnp.float32)
        out = flash_attention(q, k, v, causal=True)
        ref = flash_attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_cross_attention_seqs(self, rng):
        q, k, v = _inputs(rng, 2, 2, 128, 384, 64, jnp.float32)
        out = flash_attention(q, k, v)
        ref = flash_attention_reference(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_kv_seqlens_padding(self, rng):
        q, k, v = _inputs(rng, 3, 2, 128, 256, 32, jnp.float32)
        lens = jnp.asarray([256, 100, 17], jnp.int32)
        out = flash_attention(q, k, v, kv_seqlens=lens)
        ref = flash_attention_reference(q, k, v, kv_seqlens=lens)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_custom_scale(self, rng):
        q, k, v = _inputs(rng, 1, 2, 128, 128, 64, jnp.float32)
        out = flash_attention(q, k, v, softmax_scale=0.5)
        ref = flash_attention_reference(q, k, v, softmax_scale=0.5)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, rng, causal):
        q, k, v = _inputs(rng, 2, 2, 256, 256, 64, jnp.float32)

        def fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

        def ref(q, k, v):
            return jnp.sum(
                flash_attention_reference(q, k, v, causal=causal) ** 2)

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=5e-5, atol=5e-5)

    def test_grads_non_multiple_seq(self, rng):
        q, k, v = _inputs(rng, 1, 2, 200, 200, 48, jnp.float32)

        def fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

        def ref(q, k, v):
            return jnp.sum(
                flash_attention_reference(q, k, v, causal=True) ** 2)

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=5e-5, atol=5e-5)

    def test_grads_kv_seqlens(self, rng):
        q, k, v = _inputs(rng, 2, 2, 128, 256, 32, jnp.float32)
        lens = jnp.asarray([256, 77], jnp.int32)

        def fused(q, k, v):
            return jnp.sum(flash_attention(q, k, v, kv_seqlens=lens) ** 2)

        def ref(q, k, v):
            return jnp.sum(
                flash_attention_reference(q, k, v, kv_seqlens=lens) ** 2)

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(gf, gr, rtol=5e-5, atol=5e-5)

    def test_grads_bf16(self, rng):
        q, k, v = _inputs(rng, 1, 2, 128, 128, 64, jnp.bfloat16)

        def fused(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True).astype(jnp.float32))

        def ref(q, k, v):
            return jnp.sum(flash_attention_reference(
                q, k, v, causal=True).astype(jnp.float32))

        g_fused = jax.grad(fused, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(ref, argnums=(0, 1, 2))(q, k, v)
        for gf, gr in zip(g_fused, g_ref):
            np.testing.assert_allclose(np.asarray(gf, np.float32),
                                       np.asarray(gr, np.float32),
                                       rtol=5e-2, atol=5e-2)

    def test_jit_grad_composes(self, rng):
        q, k, v = _inputs(rng, 1, 1, 128, 128, 64, jnp.float32)
        g = jax.jit(jax.grad(
            lambda q: jnp.sum(flash_attention(q, k, v, causal=True))))(q)
        assert np.all(np.isfinite(g))
