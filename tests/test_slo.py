"""apex_tpu.observability.slo: rolling percentiles + burn-rate alerts.

Everything runs against an injected fake clock, so window expiry and
multi-window alert gating are exact — no sleeps, no wall-clock flake.
"""

import pytest

from apex_tpu.observability import (
    BurnWindow,
    MetricsRegistry,
    RollingPercentiles,
    SLOMonitor,
    SLOTarget,
)
from apex_tpu.observability.slo import DEFAULT_BURN_WINDOWS, _WindowedCounts


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestRollingPercentiles:
    def test_interpolation_within_bucket(self):
        clk = FakeClock()
        rp = RollingPercentiles(buckets=(1.0, 2.0, 4.0), window_s=60,
                                slots=6, clock=clk)
        for _ in range(10):
            rp.observe(1.5)            # all land in the (1, 2] bucket
        assert rp.count() == 10
        # rank interpolates linearly across the bucket span
        assert rp.percentile(0.5) == pytest.approx(1.5)
        assert rp.percentile(1.0) == pytest.approx(2.0)
        # first bucket interpolates from 0
        rp2 = RollingPercentiles(buckets=(1.0, 2.0), window_s=60,
                                 slots=6, clock=clk)
        rp2.observe(0.2)
        assert 0.0 < rp2.percentile(0.5) <= 1.0

    def test_overflow_saturates_at_top_boundary(self):
        rp = RollingPercentiles(buckets=(1.0, 2.0), window_s=60,
                                slots=6, clock=FakeClock())
        rp.observe(100.0)
        assert rp.percentile(0.99) == 2.0

    def test_empty_window_is_zero(self):
        rp = RollingPercentiles(window_s=60, slots=6, clock=FakeClock())
        assert rp.percentile(0.95) == 0.0 and rp.count() == 0

    def test_window_forgets(self):
        clk = FakeClock()
        rp = RollingPercentiles(buckets=(1.0, 2.0, 4.0), window_s=60,
                                slots=6, clock=clk)
        rp.observe(3.0)
        assert rp.count() == 1
        clk.advance(61.0)              # past the window -> slot expires
        assert rp.count() == 0
        rp.observe(1.5)                # fresh slot still works
        assert rp.count() == 1 and rp.percentile(0.5) < 2.0

    def test_memory_bounded_by_slots(self):
        clk = FakeClock()
        rp = RollingPercentiles(window_s=60, slots=6, clock=clk)
        for _ in range(100):
            rp.observe(0.1)
            clk.advance(10.0)          # one slot per observation
        assert len(rp._ring) <= rp.slots

    def test_validation(self):
        with pytest.raises(ValueError):
            RollingPercentiles(buckets=())
        with pytest.raises(ValueError):
            RollingPercentiles(window_s=0)
        with pytest.raises(ValueError):
            RollingPercentiles(slots=0)


class TestSLOTarget:
    def test_default_name(self):
        t = SLOTarget("ttft", 0.5)
        assert t.name == "ttft_le_0.5" and t.objective == 0.99

    def test_explicit_name_kept(self):
        assert SLOTarget("ttft", 0.5, name="gold").name == "gold"

    def test_objective_validated(self):
        with pytest.raises(ValueError):
            SLOTarget("ttft", 0.5, objective=1.0)
        with pytest.raises(ValueError):
            SLOTarget("ttft", 0.5, objective=0.0)

    def test_burn_window_label(self):
        assert BurnWindow(300.0, 3600.0, 14.4).label == "300s/3600s"
        assert len(DEFAULT_BURN_WINDOWS) == 2


class TestWindowedCounts:
    def test_rates_respect_lookback(self):
        clk = FakeClock()
        wc = _WindowedCounts(slot_s=10.0, max_window_s=100.0, clock=clk)
        wc.add(False)                  # bad at t=0
        clk.advance(50.0)
        wc.add(True)                   # good at t=50
        assert wc.rates(100.0) == (1, 2)
        assert wc.rates(20.0) == (0, 1)   # old bad event out of range

    def test_old_slots_dropped(self):
        clk = FakeClock()
        wc = _WindowedCounts(slot_s=10.0, max_window_s=30.0, clock=clk)
        for _ in range(10):
            wc.add(True)
            clk.advance(10.0)
        assert len(wc._ring) <= wc.max_slots


def monitor(clk, *, registry=None, objective=0.9):
    # short window 100s (slot 10s), long 300s; threshold 2x
    return SLOMonitor(
        [SLOTarget("ttft", 0.5, objective=objective, name="ttft_slo")],
        clock=clk, registry=registry,
        burn_windows=(BurnWindow(100.0, 300.0, 2.0),),
        slots_per_window=10)


class TestSLOMonitor:
    def test_burn_rate_math(self):
        clk = FakeClock()
        mon = monitor(clk, objective=0.9)      # budget = 10% bad
        for i in range(10):                    # 2 bad of 10 = 20% bad
            mon.observe("ttft", 1.0 if i < 2 else 0.1)
        t = mon.targets[0]
        assert mon.burn_rate(t, 100.0) == pytest.approx(2.0)
        # no events in window -> 0.0, not NaN
        clk.advance(1000.0)
        assert mon.burn_rate(t, 100.0) == 0.0

    def test_untargeted_metric_ignored(self):
        mon = monitor(FakeClock())
        mon.observe("queue_wait", 99.0)        # no target -> no-op
        assert mon.snapshot()["alerts"] == []

    def test_alert_needs_both_windows(self):
        clk = FakeClock(1000.0)
        mon = monitor(clk, objective=0.9)
        # burn only the SHORT window: all-bad burst right now, after a
        # long good history that keeps the long window under threshold
        for _ in range(200):
            mon.observe("ttft", 0.1)
            clk.advance(1.0)                   # good events, t=1000..1200
        for _ in range(30):
            mon.observe("ttft", 9.9)           # bad burst in final slot
        t = mon.targets[0]
        assert mon.burn_rate(t, 100.0) > 2.0
        assert mon.burn_rate(t, 300.0) <= 2.0
        assert mon.alerts() == []              # long window vetoes
        # now saturate the long window too -> alert fires
        for _ in range(300):
            mon.observe("ttft", 9.9)
            clk.advance(1.0)
        alerts = mon.alerts()
        assert len(alerts) == 1
        a = alerts[0]
        assert a["slo"] == "ttft_slo" and a["window"] == "100s/300s"
        assert a["burn_short"] > 2.0 and a["burn_long"] > 2.0

    def test_duplicate_target_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            SLOMonitor([SLOTarget("a", 1.0, name="x"),
                        SLOTarget("b", 1.0, name="x")],
                       clock=FakeClock())

    def test_registry_export(self):
        clk = FakeClock()
        reg = MetricsRegistry()
        mon = monitor(clk, registry=reg)
        for i in range(4):
            mon.observe("ttft", 0.1 if i % 2 else 1.0)
        snap = mon.snapshot()
        text = reg.prometheus()
        assert 'slo_events_total{slo="ttft_slo",good="true"} 2' in text
        assert 'slo_events_total{slo="ttft_slo",good="false"} 2' in text
        assert 'slo_burn_rate{slo="ttft_slo",window="100s/300s"}' in text
        assert 'slo_alert{slo="ttft_slo",window="100s/300s"}' in text
        assert ('slo_latency_quantile{metric="ttft",quantile="p95"}'
                in text)
        # snapshot structure
        wins = snap["targets"]["ttft_slo"]["windows"]["100s/300s"]
        assert set(wins) == {"burn_short", "burn_long", "threshold",
                             "firing"}
        assert snap["percentiles"]["ttft"]["n"] == 4
        assert snap["percentiles"]["ttft"]["p50"] > 0.0

    def test_snapshot_alert_flags(self):
        clk = FakeClock()
        mon = monitor(clk, objective=0.9)
        for _ in range(50):
            mon.observe("ttft", 9.9)           # 100% bad -> 10x burn
        snap = mon.snapshot()
        win = snap["targets"]["ttft_slo"]["windows"]["100s/300s"]
        assert win["firing"]
        assert snap["alerts"] == [("ttft_slo", "100s/300s")]


class TestResetWindows:
    def test_reset_clears_burn_and_percentiles(self):
        clk = FakeClock()
        mon = monitor(clk, objective=0.9)
        for _ in range(50):
            mon.observe("ttft", 9.9)           # 100% bad -> 10x burn
        t = mon.targets[0]
        assert mon.burn_rate(t, 100.0) > 2.0
        assert mon.snapshot()["percentiles"]["ttft"]["n"] == 50
        mon.reset_windows("shift-1")
        # all windows forgotten: burn is 0 until traffic refills them
        assert mon.burn_rate(t, 100.0) == 0.0
        assert mon.burn_rate(t, 300.0) == 0.0
        assert mon.alerts() == []
        assert mon.snapshot()["percentiles"]["ttft"]["n"] == 0
        # post-reset observations accumulate from scratch
        mon.observe("ttft", 0.1)
        assert mon.burn_rate(t, 100.0) == 0.0  # 0 bad of 1
        assert mon.snapshot()["percentiles"]["ttft"]["n"] == 1

    def test_reset_bumps_epoch_and_tag(self):
        mon = monitor(FakeClock())
        assert mon.epoch == 0 and mon.epoch_tag is None
        mon.reset_windows("shift-1")
        assert mon.epoch == 1 and mon.epoch_tag == "shift-1"
        mon.reset_windows()                    # tag optional
        assert mon.epoch == 2 and mon.epoch_tag is None

    def test_reset_exports_epoch_gauge(self):
        clk = FakeClock()
        reg = MetricsRegistry()
        mon = monitor(clk, registry=reg)
        mon.observe("ttft", 9.9)
        mon.reset_windows("shift-3")
        mon.reset_windows("shift-4")
        assert reg.get("slo_window_epoch").value() == 2
        assert "slo_window_epoch 2" in reg.prometheus()
