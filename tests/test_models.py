"""ResNet + BERT model families (reference: apex wires its CNN pieces
into torchvision ResNet in ``examples/imagenet/main_amp.py`` and its
BERT-era kernels into MLPerf BERT; serial-golden + parallel-parity
testing mirrors ``tests/test_gpt.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.bert import BertConfig, BertModel
from apex_tpu.models.resnet import ResNet, ResNetConfig


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def tiny_resnet(**kw):
    kw.setdefault("depths", (1, 1))
    kw.setdefault("width", 8)
    kw.setdefault("num_classes", 5)
    return ResNet(ResNetConfig(**kw))


def tiny_bert(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("hidden_size", 32)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("max_seq_len", 16)
    return BertModel(BertConfig(**kw))


class TestResNet:
    def test_shapes_and_state_threading(self, rng):
        model = tiny_resnet()
        params = model.init_params(jax.random.PRNGKey(0))
        state = model.init_state()
        x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
        logits, new_state = jax.jit(
            lambda p, s, x: model.apply(p, s, x, training=True))(
                params, state, x)
        assert logits.shape == (2, 5)
        # training mode must advance BN running stats
        old = state["stem"].num_batches_tracked
        assert int(new_state["stem"].num_batches_tracked) == int(old) + 1
        assert not np.allclose(np.asarray(new_state["stem"].running_mean),
                               np.asarray(state["stem"].running_mean))

    def test_eval_uses_running_stats(self, rng):
        model = tiny_resnet()
        params = model.init_params(jax.random.PRNGKey(0))
        state = model.init_state()
        x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)
        y1, s1 = model.apply(params, state, x, training=False)
        y2, s2 = model.apply(params, state, x, training=False)
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
        # eval mode leaves state untouched
        np.testing.assert_array_equal(
            np.asarray(s1["stem"].running_mean),
            np.asarray(state["stem"].running_mean))

    def test_loss_decreases(self, rng):
        model = tiny_resnet()
        params = model.init_params(jax.random.PRNGKey(1))
        state = model.init_state()
        x = jnp.asarray(rng.randn(4, 32, 32, 3), jnp.float32)
        y = jnp.asarray(rng.randint(0, 5, (4,)))

        @jax.jit
        def step(params, state):
            (loss, new_state), grads = jax.value_and_grad(
                model.loss, has_aux=True)(params, state, x, y)
            params = jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, params, grads)
            return params, new_state, loss

        losses = []
        for _ in range(5):
            params, state, loss = step(params, state)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_syncbn_matches_serial_big_batch(self, rng):
        """DP over 4 devices with axis_name BN == serial big-batch BN."""
        model_p = tiny_resnet(axis_name="data")
        model_s = tiny_resnet()
        params = model_p.init_params(jax.random.PRNGKey(0))
        state = model_p.init_state()
        x = jnp.asarray(rng.randn(4, 16, 16, 3), jnp.float32)
        y_ref, _ = jax.jit(
            lambda p, s, x: model_s.apply(p, s, x, training=True))(
                params, state, x)

        mesh = jax.make_mesh((4,), ("data",))
        y_par = jax.jit(shard_map(
            lambda p, s, x: model_p.apply(p, s, x, training=True)[0],
            mesh=mesh, in_specs=(P(), P(), P("data")),
            out_specs=P("data")))(params, state, x)
        np.testing.assert_allclose(np.asarray(y_ref), np.asarray(y_par),
                                   rtol=2e-4, atol=2e-4)


class TestResNetAmp:
    def test_o1_autocast_tracks_f32(self, rng):
        """amp O1 over the conv/BN family: the autocast interpreter must
        reclassify convs to half while keeping BN stats math in f32, and
        outputs must track the f32 run within bf16 tolerance."""
        from apex_tpu import amp

        model = tiny_resnet()
        params = model.init_params(jax.random.PRNGKey(0))
        state = model.init_state()
        x = jnp.asarray(rng.randn(2, 32, 32, 3), jnp.float32)

        def fwd(params, state, x):
            return model.apply(params, state, x, training=True)

        ref, _ = jax.jit(fwd)(params, state, x)
        auto = amp.autocast(fwd)
        got, new_state = jax.jit(auto)(params, state, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=5e-2, atol=5e-2)
        # the cast really happened: half-precision numerics differ
        # bitwise from the pure-f32 run (a no-op autocast would be exact)
        assert not np.array_equal(np.asarray(got), np.asarray(ref))
        # grads flow through the autocast interpreter
        def loss(params):
            logits, _ = auto(params, state, x)
            return jnp.sum(logits.astype(jnp.float32) ** 2)

        g = jax.jit(jax.grad(loss))(params)
        assert all(bool(jnp.all(jnp.isfinite(l)))
                   for l in jax.tree_util.tree_leaves(g))


class TestBert:
    def test_mlm_loss_masks_correctly(self, rng):
        model = tiny_bert()
        params = model.init_params(jax.random.PRNGKey(0))
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        labels_none = jnp.full((2, 16), -1)
        labels_all = tokens

        # no masked positions: guarded denominator, finite zero-ish loss
        l_none = float(jax.jit(model.loss)(params, tokens, labels_none))
        assert np.isfinite(l_none) and l_none == 0.0

        l_all = float(jax.jit(model.loss)(params, tokens, labels_all))
        # manual reference: mean full-vocab xent over all positions
        hidden = model.apply(params, tokens)
        logits = model.mlm_logits(params, hidden)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ref = -np.mean(np.take_along_axis(
            np.asarray(logp), np.asarray(tokens)[..., None], -1))
        np.testing.assert_allclose(l_all, ref, rtol=1e-5)

    def test_partial_mask_equals_subset_mean(self, rng):
        model = tiny_bert()
        params = model.init_params(jax.random.PRNGKey(1))
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        mask = rng.rand(2, 16) < 0.3
        labels = jnp.asarray(np.where(mask, np.asarray(tokens), -1))
        loss = float(jax.jit(model.loss)(params, tokens, labels))

        hidden = model.apply(params, tokens)
        logp = jax.nn.log_softmax(model.mlm_logits(params, hidden), -1)
        per = -np.take_along_axis(np.asarray(logp),
                                  np.asarray(tokens)[..., None], -1)[..., 0]
        ref = per[mask].mean()
        np.testing.assert_allclose(loss, ref, rtol=1e-5)

    def test_nsp_head(self, rng):
        model = tiny_bert()
        params = model.init_params(jax.random.PRNGKey(2))
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        labels = jnp.full((2, 16), -1).at[:, 3].set(5)
        nsp = jnp.asarray([0, 1])
        l0 = float(model.loss(params, tokens, labels))
        l1 = float(model.loss(params, tokens, labels, nsp_labels=nsp))
        assert l1 > l0          # adds a positive xent term

    def test_seqlens_padding_ignored(self, rng):
        """Positions past seqlen must not affect earlier outputs."""
        model = tiny_bert()
        params = model.init_params(jax.random.PRNGKey(3))
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        seqlens = jnp.asarray([8, 8])
        h1 = model.apply(params, tokens, seqlens=seqlens)
        corrupted = tokens.at[:, 8:].set(7)
        h2 = model.apply(params, corrupted, seqlens=seqlens)
        np.testing.assert_allclose(np.asarray(h1[:, :8]),
                                   np.asarray(h2[:, :8]),
                                   rtol=2e-5, atol=2e-5)

    def test_gspmd_tp2_parity(self, rng):
        """Idiomatic TPU path: jit the serial form with partition_specs
        over a 2-device model axis (tests/test_gpt.py GSPMD pattern)."""
        from jax.sharding import NamedSharding

        serial = tiny_bert()
        params = serial.init_params(jax.random.PRNGKey(4))
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        mask = rng.rand(2, 16) < 0.3
        labels = jnp.asarray(np.where(mask, np.asarray(tokens), -1))
        ref = float(jax.jit(serial.loss)(params, tokens, labels))

        mesh = jax.make_mesh((2,), ("model",),
                             axis_types=(jax.sharding.AxisType.Auto,))
        specs = serial.partition_specs()
        sharded = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        got = float(jax.jit(serial.loss)(sharded, tokens, labels))
        np.testing.assert_allclose(got, ref, rtol=1e-5)
