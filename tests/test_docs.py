"""Docs build lane (reference ships a buildable Sphinx project under
``docs/``; VERDICT r3 item 8a).  Two paths:

* with a sphinx wheel present: ``sphinx-build`` over ``docs/conf.py``
  must exit 0;
* always: the dependency-free ``docs/build.py`` renderer must produce
  the page set (user pages + live-introspection API pages for
  amp/optimizers/transformer/parallel/inference).
"""

import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent


def test_fallback_builder(tmp_path):
    out = tmp_path / "html"
    proc = subprocess.run(
        [sys.executable, str(ROOT / "docs" / "build.py"), str(out)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr
    pages = {p.name for p in out.glob("*.html")}
    assert "index.html" in pages
    for pkg in ["apex_tpu_amp", "apex_tpu_optimizers",
                "apex_tpu_transformer", "apex_tpu_parallel",
                "apex_tpu_inference"]:
        assert f"{pkg}.html" in pages, pages
    # API pages carry real introspected content, not empty shells
    amp = (out / "apex_tpu_amp.html").read_text()
    assert "initialize" in amp and "scale_loss" in amp
    inf = (out / "apex_tpu_inference.html").read_text()
    assert "InferenceEngine" in inf and "KVCache" in inf


def test_sphinx_build(tmp_path):
    pytest.importorskip("sphinx")
    pytest.importorskip("myst_parser")   # conf.py extensions require it
    out = tmp_path / "sphinx"
    proc = subprocess.run(
        ["sphinx-build", "-b", "html", str(ROOT / "docs"), str(out)],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr
    assert (out / "index.html").exists()
