"""Fused logit-free LM-head cross entropy vs the materialized reference
(pattern: the flash-attention suite — fused op against the unfused
baseline on identical inputs, fwd and bwd; Pallas runs in interpret mode
on CPU, the on-chip lane re-runs the parity on hardware)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.lm_head import (
    fused_linear_cross_entropy,
    fused_linear_cross_entropy_reference,
)
from apex_tpu.utils import set_force_pallas


@pytest.fixture(autouse=True)
def _force_pallas():
    set_force_pallas(True)
    yield
    set_force_pallas(None)


def _case(rng, n, h, v, dtype=jnp.float32):
    x = jnp.asarray(rng.randn(n, h).astype(np.float32) * 0.5, dtype)
    w = jnp.asarray(rng.randn(v, h).astype(np.float32) * 0.1, dtype)
    t = jnp.asarray(rng.randint(0, v, (n,)))
    return x, w, t


class TestFusedLMHead:
    def test_forward_matches_reference(self, rng):
        x, w, t = _case(rng, 256, 128, 1024)
        out = fused_linear_cross_entropy(x, w, t, block_t=64, block_v=256)
        ref = fused_linear_cross_entropy_reference(x, w, t)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_non_multiple_shapes(self, rng):
        # N, V, H all off the block grid: padding must wash out
        x, w, t = _case(rng, 200, 96, 1000)
        out = fused_linear_cross_entropy(x, w, t, block_t=64, block_v=128)
        ref = fused_linear_cross_entropy_reference(x, w, t)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_grads_match_reference(self, rng):
        x, w, t = _case(rng, 192, 128, 512)

        def f(x, w):
            return jnp.mean(fused_linear_cross_entropy(
                x, w, t, block_t=64, block_v=128))

        def r(x, w):
            return jnp.mean(fused_linear_cross_entropy_reference(x, w, t))

        gx, gw = jax.grad(f, argnums=(0, 1))(x, w)
        rx, rw = jax.grad(r, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gx, rx, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(gw, rw, rtol=1e-5, atol=1e-6)

    def test_weighted_cotangent(self, rng):
        # non-uniform upstream cotangent (e.g. masked-mean losses)
        x, w, t = _case(rng, 128, 64, 256)
        coef = jnp.asarray(rng.rand(128).astype(np.float32))

        def f(x, w):
            return jnp.sum(coef * fused_linear_cross_entropy(
                x, w, t, block_t=64, block_v=128))

        def r(x, w):
            return jnp.sum(
                coef * fused_linear_cross_entropy_reference(x, w, t))

        gx, gw = jax.grad(f, argnums=(0, 1))(x, w)
        rx, rw = jax.grad(r, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gx, rx, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(gw, rw, rtol=1e-5, atol=1e-6)

    def test_bf16_inputs(self, rng):
        x, w, t = _case(rng, 128, 128, 512, jnp.bfloat16)
        out = fused_linear_cross_entropy(x, w, t, block_t=64, block_v=128)
        ref = fused_linear_cross_entropy_reference(x, w, t)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)
        gx = jax.grad(lambda x: jnp.mean(fused_linear_cross_entropy(
            x, w, t, block_t=64, block_v=128)))(x)
        assert gx.dtype == jnp.bfloat16
        assert bool(jnp.all(jnp.isfinite(gx.astype(jnp.float32))))

    def test_jit_grad_composes(self, rng):
        x, w, t = _case(rng, 128, 64, 256)
        g = jax.jit(jax.grad(lambda x: jnp.sum(fused_linear_cross_entropy(
            x, w, t, block_t=64, block_v=128))))(x)
        assert np.all(np.isfinite(g))


class TestGPTFusedHead:
    """The flagship integration: fused_lm_head=True (default) must match
    the materialized head exactly, serial and pipelined."""

    def _cfg(self, fused, **kw):
        from apex_tpu.models.gpt import GPTConfig
        base = dict(vocab_size=64, hidden_size=32, num_layers=2,
                    num_attention_heads=2, max_seq_len=16,
                    fused_lm_head=fused)
        base.update(kw)
        return GPTConfig(**base)

    def test_serial_loss_and_grads_match(self, rng):
        from apex_tpu.models.gpt import GPTModel

        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        out = {}
        for fused in (True, False):
            m = GPTModel(self._cfg(fused))
            p = m.init_params(jax.random.PRNGKey(0))
            loss, g = jax.value_and_grad(m.loss)(p, tokens, tokens)
            out[fused] = (float(loss), g)
        np.testing.assert_allclose(out[True][0], out[False][0], rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(out[True][1]),
                        jax.tree_util.tree_leaves(out[False][1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)

    def test_pipeline_head_matches_serial(self, rng):
        from jax.sharding import PartitionSpec as P

        from apex_tpu.utils.collectives import shard_map_compat as shard_map

        from apex_tpu.models.gpt import (GPTModel, pack_for_shard_map,
                                         pipeline_step)

        # fallback path: interpret-mode Pallas inside the pipeline's
        # shard_map trips kernel-INTERIOR vma strictness (a CPU-lane
        # artifact — compiled kernels are opaque inside; operand/output
        # vma is declared via sds_like and exercised by the ring/on-chip
        # lanes).  This lane pins the pipeline+fused-head integration.
        set_force_pallas(False)
        m = GPTModel(self._cfg(True))
        params = m.init_params(jax.random.PRNGKey(1))
        M, mb, seq = 2, 2, 16
        tokens = jnp.asarray(rng.randint(0, 64, (M * mb, seq)))
        ref = float(jax.jit(m.loss)(params, tokens, tokens))
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            m, params, n_stages=2, tensor_axis=None)
        mesh = jax.make_mesh((2,), ("pipe",), devices=jax.devices()[:2])
        loss = float(jax.jit(shard_map(
            lambda sp, tk, tg: pipeline_step(
                m, local_fn(sp), tk.reshape(M, mb, seq),
                tg.reshape(M, mb, seq), pipe_axis="pipe")[0],
            mesh=mesh, in_specs=(in_specs, P(), P()),
            out_specs=P()))(packed, tokens, tokens))
        np.testing.assert_allclose(loss, ref, rtol=1e-5)


class TestBertFusedHead:
    def test_mlm_loss_fused_matches_materialized(self, rng):
        from apex_tpu.models.bert import BertConfig, BertModel

        kw = dict(vocab_size=128, hidden_size=32, num_layers=2,
                  num_attention_heads=2, max_seq_len=16)
        tokens = jnp.asarray(rng.randint(0, 128, (2, 16)))
        labels = np.where(rng.rand(2, 16) < 0.3,
                          rng.randint(0, 128, (2, 16)), -1)
        labels = jnp.asarray(labels)
        out = {}
        for fused in (True, False):
            m = BertModel(BertConfig(fused_lm_head=fused, **kw))
            p = m.init_params(jax.random.PRNGKey(0))
            loss, g = jax.value_and_grad(m.loss)(p, tokens, labels)
            out[fused] = (float(loss), g)
        np.testing.assert_allclose(out[True][0], out[False][0], rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(out[True][1]),
                        jax.tree_util.tree_leaves(out[False][1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)
