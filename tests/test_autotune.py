"""ParallelPlan unification + tools/autotune.py (ISSUE 11).

The contract under test:

* ``ParallelPlan`` rejects every invalid knob combination the engines
  would choke on — overlap without SP, SP without TP, interleaved
  schedules whose microbatch count doesn't divide by the stage count,
  ``zero_shard`` not in ``{1, dp}``, unknown transport dtypes — so a
  plan that constructs is a plan every consumer accepts;
* ``TopologySpec`` is a lossless projection: ``plan.topology()`` /
  ``spec.to_plan()`` round-trip, and a PR-9-format stamped manifest
  dict (version-less) lifts into a plan whose projection equals the
  original spec;
* per-knob kwargs keep working WITHOUT warnings (back-compat shims);
  a conflicting non-default knob next to an attached plan warns
  ``DeprecationWarning`` and the plan wins;
* checkpoint manifests keep the PR-9 ``topology`` schema byte-for-byte
  and stamp the full plan under the separate ``parallel_plan`` key;
* the planner's memory prune orders canonical programs by their real
  compiled peaks, and the emitted report round-trips through
  ``load_plan`` version-checked;
* (8-device mesh) the full prune -> rank -> measure pass at
  dp/tp/pp <= 2 lands the cost-model-ranked winner inside the measured
  top-3.

Tier-1 runs single-device, so the mesh-driving tests carry ``needs8``.
"""

import json
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.models.gpt import GPTConfig
from apex_tpu.parallel import (DistributedFusedAdam, ParallelPlan,
                               PLAN_VERSION)
from apex_tpu.resilience import (CheckpointManager, ElasticPlan,
                                 ElasticSignal, GuardedTrainStep,
                                 HostSignals, TopologySpec)
from tools.autotune import (AUTOTUNE_VERSION, Candidate, autotune,
                            emit_plan, enumerate_space, load_plan,
                            predict_compute_s)

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs the 8-device CPU mesh")


# -- plan validation ----------------------------------------------------------


class TestPlanValidation:
    def test_defaults_are_serial(self):
        p = ParallelPlan()
        assert p.n_devices == 1 and p.axis_name is None

    @pytest.mark.parametrize("kw", [
        dict(overlap_chunks=2, tp=2, sequence_parallel=False),
        dict(overlap_chunks=2),                      # overlap without SP
        dict(sequence_parallel=True),                # SP without TP
        dict(dp=2, zero_shard=3),                    # zero not in {1, dp}
        dict(n_virtual=2),                           # interleave without pp
        dict(pp=2, n_virtual=2, n_microbatches=3),   # M % pp != 0
        dict(allreduce_dtype="int4"),
        dict(remat_policy="everything"),
        dict(dp=0),
        dict(tp=-2),
        dict(overlap_chunks=-1),
    ])
    def test_invalid_combinations_raise(self, kw):
        with pytest.raises(ValueError):
            ParallelPlan(**kw)

    def test_interleaved_divisibility_matches_ring_engine(self):
        # the plan-level gate mirrors the ring engine's trace-time
        # raise ("interleaved schedule needs n_microbatches % n_stages
        # == 0", ring.py) so a bad schedule never reaches compile
        with pytest.raises(ValueError, match="n_microbatches"):
            ParallelPlan(pp=2, n_virtual=2, n_microbatches=3)

    def test_f32_transport_normalizes_to_none(self):
        assert ParallelPlan(allreduce_dtype="f32").allreduce_dtype is None

    def test_describe_and_dict_round_trip(self):
        p = ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True,
                         overlap_chunks=2, n_virtual=2, n_microbatches=4,
                         remat=True, remat_policy="dots",
                         allreduce_dtype="bf16")
        d = p.to_dict()
        assert d["version"] == PLAN_VERSION
        assert ParallelPlan.from_dict(d) == p
        assert "tp=2" in p.describe()

    def test_version_mismatch_refuses(self):
        d = ParallelPlan(dp=2).to_dict()
        d["version"] = PLAN_VERSION + 1
        with pytest.raises(ValueError, match="version"):
            ParallelPlan.from_dict(d)


# -- TopologySpec projection + PR-9 manifest compat ---------------------------


class TestTopologyProjection:
    def test_round_trip(self):
        p = ParallelPlan(dp=2, tp=2, pp=2, sequence_parallel=True,
                         n_microbatches=2, zero_shard=1)
        spec = p.topology()
        assert isinstance(spec, TopologySpec)
        assert (spec.dp, spec.tp, spec.pp) == (2, 2, 2)
        assert spec.to_plan(n_microbatches=2) == p

    def test_pr9_manifest_dict_lifts_losslessly(self):
        # a version-less topology dict exactly as PR 9's
        # CheckpointManager stamped it
        spec = TopologySpec(dp=4, tp=2, pp=1, sequence_parallel=True,
                            zero_shard=1)
        old_manifest_dict = spec.to_dict()
        assert "version" not in old_manifest_dict
        p = ParallelPlan.from_dict(old_manifest_dict)
        assert p.topology() == spec
        assert p.topology().to_dict() == old_manifest_dict

    def test_elastic_plan_builds_from_parallel_plan(self):
        ep = ElasticPlan.build(ParallelPlan(dp=1),
                               devices=jax.devices()[:1])
        assert isinstance(ep.spec, TopologySpec)
        assert ep.parallel == ParallelPlan(dp=1)
        # plain spec keeps parallel unset
        ep2 = ElasticPlan.build(TopologySpec(dp=1),
                                devices=jax.devices()[:1])
        assert ep2.parallel is None

    def test_signals_accept_plans(self):
        hs = HostSignals()
        hs.request_replan(ParallelPlan(dp=2))
        sig = hs.poll()
        assert sig.kind == "replan" and sig.spec == ParallelPlan(dp=2)
        with pytest.raises(ValueError, match="target"):
            ElasticSignal("replan")


# -- back-compat shims --------------------------------------------------------


class TestBackCompat:
    _kw = dict(vocab_size=32, hidden_size=16, num_layers=2,
               num_attention_heads=4, max_seq_len=8)

    def test_per_knob_kwargs_still_work_silently(self):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            cfg = GPTConfig(tensor_parallel_size=2, axis_name="model",
                            sequence_parallel=True, **self._kw)
            opt = DistributedFusedAdam(lr=1e-3, world_size=1,
                                       allreduce_dtype="bf16")
        assert cfg.tensor_parallel_size == 2
        assert opt.allreduce_dtype == "bf16"

    def test_plan_fills_config_knobs(self):
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            cfg = GPTConfig(plan=ParallelPlan(tp=2, sequence_parallel=True,
                                              remat=True,
                                              remat_policy="dots"),
                            **self._kw)
        assert cfg.tensor_parallel_size == 2
        assert cfg.sequence_parallel and cfg.remat
        assert cfg.remat_policy == "dots"
        assert cfg.axis_name == "model"

    def test_conflicting_knob_warns_and_plan_wins(self):
        with pytest.warns(DeprecationWarning, match="superseded"):
            cfg = GPTConfig(tensor_parallel_size=4, axis_name="model",
                            sequence_parallel=True,
                            plan=ParallelPlan(tp=2,
                                              sequence_parallel=True),
                            **self._kw)
        assert cfg.tensor_parallel_size == 2

    def test_optimizer_conflict_warns_and_plan_wins(self):
        plan = ParallelPlan(dp=2, zero_shard=2, allreduce_dtype="bf16")
        with pytest.warns(DeprecationWarning, match="zero_shard"):
            opt = DistributedFusedAdam(lr=1e-3, world_size=4, plan=plan)
        assert opt.world_size == 2
        assert opt.allreduce_dtype == "bf16"

    def test_guard_cross_checks_zero_shard(self):
        opt = DistributedFusedAdam(lr=1e-3, world_size=2)
        with pytest.raises(ValueError, match="zero_shard"):
            GuardedTrainStep(lambda p, x, y: 0.0, opt,
                             plan=ParallelPlan(dp=4, zero_shard=4))

    def test_engine_rejects_mismatched_plan(self):
        from apex_tpu.inference.engine import InferenceEngine
        from apex_tpu.models.gpt import GPTModel
        model = GPTModel(GPTConfig(**self._kw))
        params = model.init_params(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="pipeline"):
            InferenceEngine(model, params, plan=ParallelPlan(pp=2))
        with pytest.raises(ValueError, match="sequence_parallel"):
            InferenceEngine(model, params,
                            plan=ParallelPlan(tp=2,
                                              sequence_parallel=True))
        with pytest.raises(ValueError, match="tensor_parallel_size"):
            InferenceEngine(model, params, plan=ParallelPlan(tp=2))
        # a matching plan is fine
        eng = InferenceEngine(model, params, plan=ParallelPlan())
        assert eng.plan == ParallelPlan()


# -- checkpoint manifest stamping ---------------------------------------------


class TestManifestPlan:
    def test_topology_key_schema_unchanged(self, tmp_path):
        plan = ParallelPlan(dp=2, n_microbatches=2, remat=True)
        mgr = CheckpointManager(str(tmp_path), topology=plan.topology(),
                                parallel_plan=plan)
        mgr.save(3, {"a": jnp.arange(4.0)})
        man = json.loads(
            (tmp_path / "step_00000003" / "MANIFEST.json").read_text())
        # the PR-9 consumers keep reading exactly what they always did
        assert man["topology"] == plan.topology().to_dict()
        assert man["mesh_shape"] == {"data": 2, "pipe": 1, "model": 1}
        # the full plan rides in its own key and round-trips
        assert ParallelPlan.from_dict(man["parallel_plan"]) == plan
        assert ParallelPlan.from_dict(mgr.plan_of(3)) == plan

    def test_old_checkpoints_read_none(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), topology=TopologySpec(dp=2))
        mgr.save(1, {"a": jnp.arange(4.0)})
        assert mgr.plan_of(1) is None

    def test_restore_stays_silent_with_plan_attached(self, tmp_path):
        plan = ParallelPlan(dp=2)
        mgr = CheckpointManager(str(tmp_path), topology=plan.topology(),
                                parallel_plan=plan)
        mgr.save(1, {"a": jnp.arange(4.0)})
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            _, step = mgr.restore({"a": jnp.zeros(4)},
                                  topology=plan.topology())
        assert step == 1


# -- search-space enumeration -------------------------------------------------


class TestEnumeration:
    def test_engine_constraints_recorded_as_rejections(self):
        cands = enumerate_space(8, n_layers=4, n_heads=4, batch=8,
                                seq=16)
        reasons = [c.reason for c in cands if c.status == "rejected"]
        assert any("requires sequence parallelism" in r for r in reasons)
        assert any("not divisible" in r for r in reasons)
        # every surviving plan is a real validated ParallelPlan
        valid = [c for c in cands if c.status == "enumerated"]
        assert valid and all(isinstance(c.plan, ParallelPlan)
                             for c in valid)
        assert all(c.plan.n_devices == 8 for c in valid)

    def test_zero_gated_to_unit_tp_pp(self):
        cands = enumerate_space(8, n_layers=4, n_heads=4, batch=8,
                                seq=16)
        for c in cands:
            if c.status == "enumerated" and c.plan.zero_shard > 1:
                assert c.plan.tp == 1 and c.plan.pp == 1

    def test_restriction_flags(self):
        cands = enumerate_space(8, n_layers=4, n_heads=4, batch=8,
                                seq=16, max_tp=1, max_pp=1, zero=False,
                                remat_options=(False,))
        valid = [c.plan for c in cands if c.status == "enumerated"]
        assert valid == [ParallelPlan(dp=8)]


# -- cost + memory models -----------------------------------------------------


class TestCostModel:
    def test_roofline_monotonic_in_devices_and_remat(self):
        base = predict_compute_s(ParallelPlan(dp=2), 1000, 8, 16, 1e9)
        more_dev = predict_compute_s(ParallelPlan(dp=4), 1000, 8, 16, 1e9)
        remat = predict_compute_s(ParallelPlan(dp=2, remat=True),
                                  1000, 8, 16, 1e9)
        assert more_dev < base < remat

    def test_pipeline_bubble_penalizes_few_microbatches(self):
        few = predict_compute_s(
            ParallelPlan(pp=2, n_microbatches=2), 1000, 8, 16, 1e9)
        many = predict_compute_s(
            ParallelPlan(pp=2, n_microbatches=8), 1000, 8, 16, 1e9)
        assert many < few

    def test_memory_prune_orders_canonical_programs(self):
        # two programs with a known peak ordering: the prune criterion
        # (estimated peak vs budget) must separate them at any budget
        # between the two compiled peaks
        from apex_tpu.analysis.memory import estimate_peak_memory
        small = jax.jit(lambda x: (x * 2.0).sum()).lower(
            jnp.ones((64,), jnp.float32)).compile()
        big = jax.jit(lambda x: (x @ x.T).sum()).lower(
            jnp.ones((256, 256), jnp.float32)).compile()
        e_small = estimate_peak_memory(small)
        e_big = estimate_peak_memory(big)
        assert e_small.peak_bytes < e_big.peak_bytes
        budget = (e_small.peak_bytes + e_big.peak_bytes) / 2
        assert e_small.peak_bytes <= budget < e_big.peak_bytes

    def test_candidate_report_dict(self):
        c = Candidate(plan=ParallelPlan(dp=2), status="ranked",
                      peak_bytes=123, predicted_s=0.5)
        d = c.to_dict()
        assert d["plan"]["dp"] == 2 and d["peak_bytes"] == 123
        assert "measured_s" not in d


# -- emitted-report round-trip ------------------------------------------------


class TestReportRoundTrip:
    def test_load_plan_version_checked(self, tmp_path):
        plan = ParallelPlan(dp=2, remat=True)
        path = tmp_path / "plan.json"
        emit_plan(str(path), {"version": AUTOTUNE_VERSION,
                              "plan": plan.to_dict(), "candidates": []})
        assert load_plan(str(path)) == plan
        emit_plan(str(path), {"version": AUTOTUNE_VERSION + 1,
                              "plan": plan.to_dict()})
        with pytest.raises(ValueError, match="version"):
            load_plan(str(path))


# -- the full planner on the 8-device mesh ------------------------------------


@needs8
class TestAutotuneOnMesh:
    def test_rank_agreement_dp_tp_pp_2(self, tmp_path):
        """Prune -> rank -> measure over the dp/tp/pp <= 2 corner of the
        space (includes the full 2x2x2 mesh): every survivor's memory
        estimate is inside the 1.5x XLA gate, the cost-model-ranked
        winner lands inside the measured top-3, and its measured time is
        within bounded regret of the measured best — on a 1-core host
        the measured spread between good candidates is scheduler noise,
        so the agreement bound is a regret ratio, not a strict rank."""
        cfg_kw = dict(vocab_size=64, hidden_size=32, num_layers=2,
                      num_attention_heads=4, max_seq_len=16)
        report = autotune(8, cfg_kw=cfg_kw, batch=8, hbm_bytes=1 << 30,
                          top_k=3, max_tp=2, max_pp=2, zero=False,
                          remat_options=(False,), verbose=False)
        cands = report["candidates"]
        ranked = [c for c in cands
                  if c["status"] in ("ranked", "measured")]
        assert any(c["plan"]["dp"] == 2 and c["plan"]["tp"] == 2
                   and c["plan"]["pp"] == 2 for c in ranked)
        for c in ranked:
            if c.get("xla_ratio") is not None:
                assert 1 / 1.5 <= c["xla_ratio"] <= 1.5, c
        measured = sorted((c for c in cands if c["status"] == "measured"),
                          key=lambda c: c["measured_s"])
        assert len(measured) == 3
        # the measured set IS the predicted top-3 of the ranked pool
        pred_sorted = sorted(ranked, key=lambda c: c["predicted_s"])
        assert {json.dumps(c["plan"], sort_keys=True) for c in measured} \
            == {json.dumps(c["plan"], sort_keys=True)
                for c in pred_sorted[:3]}
        predicted_best = min(measured, key=lambda c: c["predicted_s"])
        top3 = [c["plan"] for c in measured[:3]]
        assert predicted_best["plan"] in top3, (
            f"cost-model winner {predicted_best['plan']} not in "
            f"measured top-3 {top3}")
        assert predicted_best["measured_s"] <= 2.5 * \
            measured[0]["measured_s"]
        # the emitted winner is the measured fastest and round-trips
        path = tmp_path / "plan.json"
        emit_plan(str(path), report)
        assert load_plan(str(path)) == ParallelPlan.from_dict(
            measured[0]["plan"])
        assert report["plan"] == measured[0]["plan"]

    def test_memory_budget_prunes(self):
        cfg_kw = dict(vocab_size=64, hidden_size=32, num_layers=2,
                      num_attention_heads=4, max_seq_len=16)
        with pytest.raises(RuntimeError, match="budget"):
            autotune(8, cfg_kw=cfg_kw, batch=8, hbm_bytes=1024,
                     max_tp=1, max_pp=1, zero=False,
                     remat_options=(False,), verbose=False)
