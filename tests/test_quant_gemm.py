"""apex_tpu.ops.quant_gemm: int8 decode weights (ISSUE 18).

The subsystem's correctness contract:

* :func:`quantize_weight` is per-OUTPUT-channel symmetric int8: the
  reconstruction error is ``<= scale / 2`` per element, an all-zero
  row gets scale 1.0 (zeros round-trip bitwise), and quantization is
  a pure function of the values (bitwise-deterministic across loads);
* the Pallas kernel (interpret mode) matches the unfused
  dequantize-then-matmul reference at dtype-appropriate tolerances,
  and off-TPU the public :func:`quant_gemm` IS the reference, bitwise;
* quantization commutes with :func:`shard_params_for_tp`: BITWISE on
  the ColumnParallel / vocab row-shard direction, and on the
  RowParallel column-shard direction per-shard scales never exceed
  the full-tensor scale (local amax <= full amax) except all-zero
  shard rows, which reconstruct exactly anyway;
* a TP=2 shard_map decode over per-shard-quantized trees greedily
  matches the tp=1 quantized decode;
* the int8 decode path agrees greedily with f32 on the contiguous and
  paged engines at the CI config, within a pinned logits tolerance,
  at < 0.30x the f32 weight bytes;
* every training entry point rejects quantized trees with an
  actionable message: ``GPTConfig`` (fused_ffn / MoE composition),
  ``pipeline_step``, ``GuardedTrainStep``, and the autotuner's
  ``cfg_kw``.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import (GPTConfig, GPTModel, pipeline_step,
                                 quantize_decode_params,
                                 shard_params_for_tp)
from apex_tpu.ops.quant_gemm import (dequantize_weight, quant_gemm,
                                     quant_gemm_reference, quantize_weight)
from apex_tpu.utils import set_force_pallas
from apex_tpu.utils.collectives import shard_map_compat

# int8 weights must keep decode logits this close to f32 on the CI
# config (measured worst |dlogits| is ~7e-3; ~7x margin)
WEIGHT_QUANT_LOGITS_TOL = 5e-2

# big enough that greedy argmax is stable under quantization error and
# the LN/bias f32 remainder is < 30% of the weight bytes (measured
# ratio 0.274)
CI_KW = dict(vocab_size=256, hidden_size=64, num_layers=2,
             num_attention_heads=4, max_seq_len=64)


@pytest.fixture(scope="module")
def ci_model():
    model = GPTModel(GPTConfig(**CI_KW))
    return model, model.init_params(jax.random.PRNGKey(0))


# ---------------------------------------------------------------------------
# quantize_weight
# ---------------------------------------------------------------------------

class TestQuantizeWeight:
    def test_error_bound_half_scale(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 48))
        w8, scale = quantize_weight(w)
        assert w8.dtype == jnp.int8 and scale.dtype == jnp.float32
        assert w8.shape == w.shape and scale.shape == (64,)
        err = np.abs(np.asarray(dequantize_weight(w8, scale)) -
                     np.asarray(w, np.float32))
        bound = np.asarray(scale)[:, None] / 2 * (1 + 1e-6)
        assert (err <= bound).all()

    def test_zero_row_scale_one_roundtrips(self):
        w = jnp.zeros((4, 8)).at[1].set(jnp.arange(8, dtype=jnp.float32))
        w8, scale = quantize_weight(w)
        assert float(scale[0]) == 1.0
        np.testing.assert_array_equal(
            np.asarray(dequantize_weight(w8, scale))[0], np.zeros(8))

    def test_bitwise_deterministic(self):
        w = jax.random.normal(jax.random.PRNGKey(3), (32, 32))
        a8, asc = quantize_weight(w)
        b8, bsc = quantize_weight(jnp.array(np.asarray(w)))
        assert np.asarray(a8).tobytes() == np.asarray(b8).tobytes()
        assert np.asarray(asc).tobytes() == np.asarray(bsc).tobytes()

    def test_rejects_non_2d(self):
        with pytest.raises(ValueError, match="2D"):
            quantize_weight(jnp.zeros((2, 3, 4)))


# ---------------------------------------------------------------------------
# kernel vs reference
# ---------------------------------------------------------------------------

class TestKernel:
    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5),
                                           (jnp.bfloat16, 2e-2)])
    @pytest.mark.parametrize("m,n,k", [(5, 130, 200), (16, 512, 512)])
    def test_interpret_matches_reference(self, dtype, tol, m, n, k):
        kx, kw = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(kx, (m, k)).astype(dtype)
        w8, scale = quantize_weight(jax.random.normal(kw, (n, k)) * 0.1)
        ref = quant_gemm_reference(x, w8, scale)
        set_force_pallas(True)
        try:
            out = quant_gemm(x, w8, scale, block_n=128, block_k=128)
        finally:
            set_force_pallas(None)
        assert out.dtype == jnp.float32 and out.shape == (m, n)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=tol, atol=tol)

    def test_off_tpu_dispatch_is_reference_bitwise(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (4, 64),
                              dtype=jnp.float32)
        w8, scale = quantize_weight(
            jax.random.normal(jax.random.PRNGKey(5), (96, 64)))
        out = quant_gemm(x, w8, scale)
        ref = quant_gemm_reference(x, w8, scale)
        assert np.asarray(out).tobytes() == np.asarray(ref).tobytes()

    def test_leading_dims_flatten(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 32))
        w8, scale = quantize_weight(
            jax.random.normal(jax.random.PRNGKey(6), (48, 32)))
        out = quant_gemm(x, w8, scale)
        assert out.shape == (2, 3, 48)
        np.testing.assert_array_equal(
            np.asarray(out).reshape(6, 48),
            np.asarray(quant_gemm(x.reshape(6, 32), w8, scale)))

    def test_rejects_bad_operands(self):
        x = jnp.zeros((2, 8))
        with pytest.raises(ValueError, match="int8"):
            quant_gemm(x, jnp.zeros((4, 8), jnp.float32), jnp.ones(4))
        with pytest.raises(ValueError, match="features"):
            quant_gemm(x, jnp.zeros((4, 9), jnp.int8), jnp.ones(4))
        with pytest.raises(ValueError, match="scale"):
            quant_gemm(x, jnp.zeros((4, 8), jnp.int8), jnp.ones(5))


# ---------------------------------------------------------------------------
# TP sharding: quantize/shard commutation
# ---------------------------------------------------------------------------

class TestTensorParallel:
    @pytest.mark.parametrize("sp", [False, True])
    def test_column_shard_quantize_commutes_bitwise(self, ci_model, sp):
        model, params = ci_model
        cfg_tp = GPTConfig(tensor_parallel_size=2, axis_name="model",
                           sequence_parallel=sp, **CI_KW)
        qfull = quantize_decode_params(params)
        for rank in range(2):
            a = shard_params_for_tp(cfg_tp, qfull, rank)
            b = quantize_decode_params(
                shard_params_for_tp(cfg_tp, params, rank))
            for (pa, xa), (pb, xb) in zip(
                    jax.tree_util.tree_leaves_with_path(a),
                    jax.tree_util.tree_leaves_with_path(b), strict=True):
                key = jax.tree_util.keystr(pa)
                assert xa.shape == xb.shape, key
                if "proj" in key or "fc2" in key:
                    continue          # RowParallel: scale-bound test below
                assert np.asarray(xa).tobytes() == \
                    np.asarray(xb).tobytes(), key

    def test_row_shard_scales_only_tighten(self, ci_model):
        model, params = ci_model
        cfg_tp = GPTConfig(tensor_parallel_size=2, axis_name="model",
                           **CI_KW)
        full = quantize_decode_params(params)
        for rank in range(2):
            local = quantize_decode_params(
                shard_params_for_tp(cfg_tp, params, rank))
            for li, lp in enumerate(local["layers"]):
                for group in (("attention", "proj"), ("mlp", "fc2")):
                    ls = np.asarray(lp[group[0]][group[1]]["weight_scale"])
                    fs = np.asarray(
                        full["layers"][li][group[0]][group[1]]
                        ["weight_scale"])
                    # local amax <= full amax, except an all-zero shard
                    # row snaps to scale 1.0 (and reconstructs exactly)
                    ok = (ls <= fs + 1e-12) | (ls == 1.0)
                    assert ok.all(), (li, group, rank)

    def test_tp2_quantized_decode_matches_tp1_greedy(self, ci_model):
        model, params = ci_model
        cfg = model.cfg
        cfg_tp = GPTConfig(tensor_parallel_size=2, axis_name="model",
                           **CI_KW)
        qmodel = GPTModel(GPTConfig(weight_quant="int8", **CI_KW))
        par = GPTModel(GPTConfig(weight_quant="int8",
                                 tensor_parallel_size=2,
                                 axis_name="model", **CI_KW))
        shards = [quantize_decode_params(
            shard_params_for_tp(cfg_tp, params, r)) for r in range(2)]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *shards)
        specs = jax.tree_util.tree_map(lambda _: P("model"), stacked)
        mesh = jax.make_mesh((2,), ("model",))
        qparams = quantize_decode_params(params)
        tokens = jnp.asarray([[1, 2, 3, 4]])
        b, p = 1, 4

        lg, kv = jax.jit(qmodel.prefill)(qparams, tokens)

        def local_prefill(sp, toks):
            lp = jax.tree_util.tree_map(lambda a: a[0], sp)
            return par.prefill(lp, toks)

        lg2, _ = jax.jit(shard_map_compat(
            local_prefill, mesh=mesh, in_specs=(specs, P()),
            out_specs=(P(None, None, "model"),
                       P(None, None, None, None, "model"))))(stacked,
                                                             tokens)
        np.testing.assert_allclose(np.asarray(lg), np.asarray(lg2),
                                   atol=WEIGHT_QUANT_LOGITS_TOL)
        assert int(np.argmax(np.asarray(lg)[0, -1])) == \
            int(np.argmax(np.asarray(lg2)[0, -1]))

        cache = jnp.zeros((b, cfg.num_layers, 2, cfg.max_seq_len,
                           cfg.num_attention_heads, cfg.head_dim),
                          jnp.float32)
        cache = cache.at[:, :, :, :p].set(kv.transpose(2, 0, 1, 3, 4, 5))
        cache2 = cache.copy()

        def local_decode(sp, toks, cache, pos):
            lp = jax.tree_util.tree_map(lambda a: a[0], sp)
            return par.decode_step(lp, toks, cache, pos)

        cache_spec = P(None, None, None, None, "model")
        step2 = jax.jit(shard_map_compat(
            local_decode, mesh=mesh,
            in_specs=(specs, P(), cache_spec, P()),
            out_specs=(P(None, "model"), cache_spec)))
        step1 = jax.jit(qmodel.decode_step)
        tok = jnp.asarray([int(np.argmax(np.asarray(lg)[0, -1]))])
        tok2 = tok
        for i in range(p, p + 5):
            pos = jnp.full((b,), i, jnp.int32)
            l1, cache = step1(qparams, tok, cache, pos)
            l2, cache2 = step2(stacked, tok2, cache2, pos)
            np.testing.assert_allclose(
                np.asarray(l1), np.asarray(l2),
                atol=WEIGHT_QUANT_LOGITS_TOL)
            tok = jnp.asarray([int(np.argmax(np.asarray(l1)[0]))])
            tok2 = jnp.asarray([int(np.argmax(np.asarray(l2)[0]))])
            assert int(tok[0]) == int(tok2[0]), i


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

def _greedy(model, params, reqs):
    import dataclasses as _dc

    from apex_tpu.inference import InferenceEngine
    eng = InferenceEngine(model, params, max_slots=4)
    for r in reqs:
        eng.submit(_dc.replace(r))
    return {r.request_id: r.tokens for r in eng.run()}, eng


def _greedy_paged(model, params, reqs):
    import dataclasses as _dc

    from apex_tpu.serving import PagedInferenceEngine
    eng = PagedInferenceEngine(model, params, max_slots=4, block_size=8,
                               chunked_prefill=True)
    for r in reqs:
        eng.submit(_dc.replace(r))
    return {r.request_id: r.tokens for r in eng.run()}, eng


class TestEngineIntegration:
    @pytest.fixture(scope="class")
    def reqs(self):
        from apex_tpu.inference import Request
        rng = np.random.RandomState(7)
        return [Request(i, list(rng.randint(1, 256, 6 + i)),
                        max_new_tokens=8) for i in range(4)]

    @pytest.fixture(scope="class")
    def contiguous(self, ci_model, reqs):
        model, params = ci_model
        qmodel = GPTModel(dataclasses.replace(model.cfg,
                                              weight_quant="int8"))
        ref, feng = _greedy(model, params, reqs)
        got, qeng = _greedy(qmodel, params, reqs)
        return ref, got, feng, qeng

    def test_contiguous_greedy_matches_f32(self, contiguous):
        ref, got, _, qeng = contiguous
        assert got == ref
        # the engine quantized at init: int8 leaves in its tree
        leaves = jax.tree_util.tree_leaves(qeng.params)
        assert any(l.dtype == jnp.int8 for l in leaves)

    def test_paged_greedy_matches_f32(self, ci_model, reqs):
        model, params = ci_model
        qmodel = GPTModel(dataclasses.replace(model.cfg,
                                              weight_quant="int8"))
        ref, _ = _greedy_paged(model, params, reqs)
        got, _ = _greedy_paged(qmodel, params, reqs)
        assert got == ref

    def test_weight_bytes_ratio(self, contiguous):
        _, _, feng, qeng = contiguous
        ratio = qeng.weight_bytes / feng.weight_bytes
        assert ratio < 0.30, ratio

    def test_pinned_logits_tolerance(self, ci_model):
        model, params = ci_model
        qparams = quantize_decode_params(params)
        qmodel = GPTModel(dataclasses.replace(model.cfg,
                                              weight_quant="int8"))
        toks = jnp.asarray([[1, 2, 3, 4, 5]])
        lf, _ = jax.jit(model.prefill)(params, toks)
        lq, _ = jax.jit(qmodel.prefill)(qparams, toks)
        delta = float(np.max(np.abs(np.asarray(lf) - np.asarray(lq))))
        assert delta < WEIGHT_QUANT_LOGITS_TOL, delta

    def test_quantized_tree_bitwise_deterministic(self, ci_model):
        model, params = ci_model
        a = quantize_decode_params(params)
        b = quantize_decode_params(
            jax.tree_util.tree_map(lambda l: jnp.array(np.asarray(l)),
                                   params))
        for (pa, xa), (pb, xb) in zip(
                jax.tree_util.tree_leaves_with_path(a),
                jax.tree_util.tree_leaves_with_path(b), strict=True):
            assert np.asarray(xa).tobytes() == np.asarray(xb).tobytes(), \
                jax.tree_util.keystr(pa)


# ---------------------------------------------------------------------------
# training rejections
# ---------------------------------------------------------------------------

class TestTrainingRejections:
    def test_config_rejects_bad_mode(self):
        with pytest.raises(ValueError, match="weight_quant"):
            GPTConfig(weight_quant="fp8", **CI_KW)

    def test_config_rejects_fused_ffn(self):
        with pytest.raises(ValueError, match="fused_ffn"):
            GPTConfig(weight_quant="int8", fused_ffn=True, **CI_KW)

    def test_config_rejects_moe(self):
        kw = dict(CI_KW)
        with pytest.raises(ValueError, match="expert"):
            GPTConfig(weight_quant="int8", n_experts=2, **kw)

    def test_pipeline_step_rejects(self):
        cfg = GPTConfig(weight_quant="int8", **CI_KW)
        model = GPTModel(cfg)
        with pytest.raises(ValueError,
                           match="decode/prefill-only"):
            pipeline_step(model, {}, jnp.zeros((1, 1, 8), jnp.int32),
                          jnp.zeros((1, 1, 8), jnp.int32))

    def test_guarded_train_step_rejects_int8_leaves(self, ci_model):
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.resilience import GuardedTrainStep
        model, params = ci_model
        qparams = quantize_decode_params(params)
        guard = GuardedTrainStep(model.loss, FusedAdam(lr=1e-3))
        opt = guard.optimizer.init(params)
        state = guard.init_state()
        tk = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="int8 leaves"):
            guard(qparams, opt, state, tk, tk)

    def test_autotune_rejects_weight_quant_cfg(self):
        from tools.autotune import autotune
        with pytest.raises(ValueError, match="decode/prefill-only"):
            autotune(2, cfg_kw=dict(weight_quant="int8", **CI_KW))

    def test_quantize_rejects_moe_tree(self):
        cfg = GPTConfig(n_experts=2, **CI_KW)
        params = GPTModel(cfg).init_params(jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="MoE"):
            quantize_decode_params(params)
