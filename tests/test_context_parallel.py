"""Context parallelism: ring attention + Ulysses all-to-all
(beyond-reference — SURVEY §5 long-context extension).  Parity vs
serial attention on the 8-device mesh, forward AND gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.flash_attention import flash_attention_reference
from apex_tpu.transformer.context_parallel import (ring_attention,
                                                   ulysses_attention)


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def make_qkv(rng, b=1, h=4, s=64, d=16):
    def one():
        return jnp.asarray(rng.randn(b, h, s, d) * 0.3, jnp.float32)
    return one(), one(), one()


def run_sharded(fn, mesh, q, k, v):
    """Shard the sequence dim (axis 2) over 'context' and run fn."""
    spec = P(None, None, "context", None)
    return jax.jit(shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec))(q, k, v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("n_dev", [2, 4])
    def test_matches_serial(self, rng, causal, n_dev):
        q, k, v = make_qkv(rng)
        ref = flash_attention_reference(q, k, v, causal=causal)
        mesh = jax.make_mesh((n_dev,), ("context",))
        got = run_sharded(
            lambda q, k, v: ring_attention(q, k, v, "context",
                                           causal=causal),
            mesh, q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_serial(self, rng, causal):
        q, k, v = make_qkv(rng, s=32)
        mesh = jax.make_mesh((4,), ("context",))

        def serial_loss(q, k, v):
            out = flash_attention_reference(q, k, v, causal=causal)
            return jnp.sum(out ** 2)

        ref_grads = jax.grad(serial_loss, argnums=(0, 1, 2))(q, k, v)

        def ring_loss(q, k, v):
            out = ring_attention(q, k, v, "context", causal=causal)
            return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2),
                                "context")

        spec = P(None, None, "context", None)
        grads = jax.jit(shard_map(
            lambda q, k, v: jax.grad(ring_loss, argnums=(0, 1, 2))(
                q, k, v),
            mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(spec, spec, spec)))(q, k, v)
        for g, r in zip(grads, ref_grads, strict=True):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=5e-4, atol=5e-5)

    def test_single_device_axis(self, rng):
        q, k, v = make_qkv(rng, s=32)
        mesh = jax.make_mesh((1,), ("context",))
        ref = flash_attention_reference(q, k, v, causal=True)
        got = run_sharded(
            lambda q, k, v: ring_attention(q, k, v, "context",
                                           causal=True),
            mesh, q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_remat_off_matches(self, rng):
        q, k, v = make_qkv(rng, s=32)
        mesh = jax.make_mesh((4,), ("context",))
        a = run_sharded(
            lambda q, k, v: ring_attention(q, k, v, "context",
                                           remat=False), mesh, q, k, v)
        b = run_sharded(
            lambda q, k, v: ring_attention(q, k, v, "context",
                                           remat=True), mesh, q, k, v)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6)


class TestGPTContextParallel:
    """The flagship model with its sequence sharded over a context axis:
    loss AND grads must match the serial model on the same batch."""

    @pytest.mark.parametrize("mechanism", ["ring", "ulysses"])
    def test_loss_and_grads_match_serial(self, rng, mechanism):
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        kw = dict(vocab_size=32, hidden_size=16, num_layers=2,
                  num_attention_heads=4, max_seq_len=32)
        serial = GPTModel(GPTConfig(**kw))
        params = serial.init_params(jax.random.PRNGKey(0))
        tokens = jnp.asarray(rng.randint(0, 32, (2, 32)))
        targets = jnp.asarray(rng.randint(0, 32, (2, 32)))
        ref_loss = float(jax.jit(serial.loss)(params, tokens, targets))
        ref_grads = jax.jit(jax.grad(serial.loss))(params, tokens, targets)

        cp = GPTModel(GPTConfig(context_axis="context",
                                context_mechanism=mechanism, **kw))
        mesh = jax.make_mesh((4,), ("context",))
        seq_spec = P(None, "context")

        from apex_tpu.utils.collectives import psum_if_varying

        def step(params, tokens, targets):
            loss, grads = jax.value_and_grad(cp.loss)(params, tokens,
                                                      targets)
            # leaves still varying over the ring hold partial sums; the
            # invariant ones were auto-psummed (same staging as DP)
            return loss, psum_if_varying(grads, "context")

        loss, grads = jax.jit(shard_map(
            step, mesh=mesh, in_specs=(P(), seq_spec, seq_spec),
            out_specs=(P(), P())))(params, tokens, targets)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        for g, r in zip(jax.tree_util.tree_leaves(grads),
                        jax.tree_util.tree_leaves(ref_grads),
                        strict=True):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=5e-4, atol=1e-5)

    def test_learned_positions_cp(self, rng):
        """Non-rotary (learned position embedding) path under CP: the
        shard offset must select the right embedding rows."""
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        kw = dict(vocab_size=32, hidden_size=16, num_layers=1,
                  num_attention_heads=4, max_seq_len=32, rotary=False)
        serial = GPTModel(GPTConfig(**kw))
        params = serial.init_params(jax.random.PRNGKey(1))
        tokens = jnp.asarray(rng.randint(0, 32, (2, 32)))
        targets = jnp.asarray(rng.randint(0, 32, (2, 32)))
        ref = float(jax.jit(serial.loss)(params, tokens, targets))

        cp = GPTModel(GPTConfig(context_axis="context", **kw))
        mesh = jax.make_mesh((4,), ("context",))
        seq_spec = P(None, "context")
        loss = jax.jit(shard_map(
            cp.loss, mesh=mesh, in_specs=(P(), seq_spec, seq_spec),
            out_specs=P()))(params, tokens, targets)
        np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_serial(self, rng, causal):
        q, k, v = make_qkv(rng, h=8)
        ref = flash_attention_reference(q, k, v, causal=causal)
        mesh = jax.make_mesh((4,), ("context",))
        got = run_sharded(
            lambda q, k, v: ulysses_attention(q, k, v, "context",
                                              causal=causal),
            mesh, q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_grads_match_serial(self, rng):
        q, k, v = make_qkv(rng, h=4, s=32)
        mesh = jax.make_mesh((2,), ("context",))

        def serial_loss(q, k, v):
            out = flash_attention_reference(q, k, v, causal=True)
            return jnp.sum(out ** 2)

        ref_grads = jax.grad(serial_loss, argnums=(0, 1, 2))(q, k, v)

        def ul_loss(q, k, v):
            out = ulysses_attention(q, k, v, "context", causal=True)
            return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2),
                                "context")

        spec = P(None, None, "context", None)
        grads = jax.jit(shard_map(
            lambda q, k, v: jax.grad(ul_loss, argnums=(0, 1, 2))(q, k, v),
            mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=(spec, spec, spec)))(q, k, v)
        for g, r in zip(grads, ref_grads, strict=True):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=5e-4, atol=5e-5)

    def test_heads_must_divide(self, rng):
        q, k, v = make_qkv(rng, h=2)
        mesh = jax.make_mesh((4,), ("context",))
        with pytest.raises(ValueError,
                           match="divisible by the context axis"):
            run_sharded(
                lambda q, k, v: ulysses_attention(q, k, v, "context"),
                mesh, q, k, v)
