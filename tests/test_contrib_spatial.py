"""bottleneck / SpatialBottleneck / halo exchange vs serial references
(pattern: apex ``contrib/test/bottleneck``; spatial parity = the
reference's SpatialBottleneck-vs-Bottleneck equivalence check)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.bottleneck import Bottleneck, SpatialBottleneck
from apex_tpu.contrib.peer_memory import (
    PeerHaloExchanger1d,
    halo_exchange_1d,
)


class TestHaloExchange:
    def test_matches_manual_neighbors(self, rng):
        # H axis (dim 1) of (1, 8, 3, 5) sharded over 4 devices: each
        # holds 2 rows and must receive its neighbors' edge rows
        mesh = jax.make_mesh((4,), ("spatial",))
        x = jnp.asarray(rng.randn(1, 8, 3, 5).astype(np.float32))
        out = np.asarray(jax.shard_map(
            lambda x: halo_exchange_1d(x, 1, "spatial", dim=1),
            mesh=mesh, in_specs=(P(None, "spatial"),),
            out_specs=P(None, "spatial"), check_vma=False)(x))
        out = out.reshape(4, 4, 3, 5)      # per device: halo+2rows+halo
        xs = np.asarray(x)[0]
        for d in range(4):
            got = out[d]
            top = xs[2 * d - 1] if d > 0 else np.zeros((3, 5))
            bot = xs[2 * d + 2] if d < 3 else np.zeros((3, 5))
            np.testing.assert_allclose(got[0], top)
            np.testing.assert_allclose(got[1:3], xs[2 * d:2 * d + 2])
            np.testing.assert_allclose(got[3], bot)

    def test_exchanger_surface(self, rng):
        mesh = jax.make_mesh((2,), ("spatial",))
        ex = PeerHaloExchanger1d("spatial", halo=1)
        x = jnp.asarray(rng.randn(2, 4, 2, 3).astype(np.float32))
        out = jax.shard_map(ex, mesh=mesh, in_specs=(P(None, "spatial"),),
                            out_specs=P(None, "spatial"),
                            check_vma=False)(x)
        assert out.shape == (2, 8, 2, 3)  # +1 halo per side per shard


class TestBottleneck:
    def test_shapes_and_residual(self, rng):
        m = Bottleneck(16, 8, 16, stride=1)
        params = m.init_params(jax.random.PRNGKey(0))
        assert "downsample" not in params
        x = jnp.asarray(rng.randn(2, 8, 8, 16).astype(np.float32))
        y = m(params, x)
        assert y.shape == x.shape
        assert float(y.min()) >= 0.0

    def test_strided_downsample(self, rng):
        m = Bottleneck(16, 8, 32, stride=2)
        params = m.init_params(jax.random.PRNGKey(1))
        assert "downsample" in params
        x = jnp.asarray(rng.randn(2, 8, 8, 16).astype(np.float32))
        y = m(params, x)
        assert y.shape == (2, 4, 4, 32)

    def test_grad_flows(self, rng):
        m = Bottleneck(8, 4, 8)
        params = m.init_params(jax.random.PRNGKey(2))
        x = jnp.asarray(rng.randn(1, 4, 4, 8).astype(np.float32))
        g = jax.grad(lambda p: jnp.sum(m(p, x) ** 2))(params)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.all(np.isfinite(leaf))


class TestSpatialBottleneck:
    def test_parity_with_serial(self, rng):
        """H sharded over 4 devices must equal the serial block exactly
        (the halo exchange supplies the cross-shard 3x3 rows)."""
        mesh = jax.make_mesh((4,), ("spatial",))
        serial = Bottleneck(8, 4, 8, stride=1)
        params = serial.init_params(jax.random.PRNGKey(3))
        spatial = SpatialBottleneck(8, 4, 8, axis_name="spatial")
        x = jnp.asarray(rng.randn(2, 16, 6, 8).astype(np.float32))

        y_serial = serial(params, x)
        y_spatial = jax.shard_map(
            lambda x: spatial(params, x), mesh=mesh,
            in_specs=(P(None, "spatial"),),
            out_specs=P(None, "spatial"), check_vma=False)(x)
        np.testing.assert_allclose(np.asarray(y_spatial),
                                   np.asarray(y_serial),
                                   rtol=1e-5, atol=1e-5)

    def test_stride_rejected(self):
        with pytest.raises(ValueError):
            SpatialBottleneck(8, 4, 8, stride=2)
