"""On-chip lane: Pallas kernels + amp composition on the real TPU.

Run with ``APEX_TPU_ON_CHIP=1 python -m pytest tests/test_on_chip.py -m tpu``.
The default (CPU) lane skips these — interpret mode cannot enforce TPU
tiling or VMEM limits, which is exactly what this lane exists to catch
(the round-2 amp x Pallas breakage survived a green CPU suite).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module", autouse=True)
def _require_tpu():
    if jax.default_backend() != "tpu":
        pytest.skip("real TPU backend required")


class TestKernelParityOnChip:
    def test_layer_norm_fwd_bwd(self, rng):
        from apex_tpu.ops.layer_norm import fused_layer_norm_affine

        x = jnp.asarray(rng.randn(64, 1024).astype(np.float32))
        w = jnp.asarray(rng.randn(1024).astype(np.float32))
        b = jnp.asarray(rng.randn(1024).astype(np.float32))

        def ref(x, w, b):
            m = x.mean(-1, keepdims=True)
            v = x.var(-1, keepdims=True)
            return (x - m) / jnp.sqrt(v + 1e-5) * w + b

        out = fused_layer_norm_affine(x, w, b)
        np.testing.assert_allclose(out, ref(x, w, b), rtol=1e-4, atol=1e-4)
        g = jax.grad(lambda x, w, b: jnp.sum(
            fused_layer_norm_affine(x, w, b) ** 2), (0, 1, 2))(x, w, b)
        gr = jax.grad(lambda x, w, b: jnp.sum(ref(x, w, b) ** 2),
                      (0, 1, 2))(x, w, b)
        for a, r in zip(g, gr):
            np.testing.assert_allclose(a, r, rtol=1e-3, atol=1e-3)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_attention_fwd_bwd(self, rng, dtype, causal):
        from apex_tpu.ops.flash_attention import (
            flash_attention, flash_attention_reference)

        q = jnp.asarray(rng.randn(2, 4, 256, 64), dtype)
        k = jnp.asarray(rng.randn(2, 4, 256, 64), dtype)
        v = jnp.asarray(rng.randn(2, 4, 256, 64), dtype)
        # on-chip f32 matmuls ride the MXU at bf16-pass precision (the
        # jnp reference drifts the same ~0.2% from a HIGHEST-precision
        # run), so tolerances are set to that floor, not CPU f32
        out = flash_attention(q, k, v, causal=causal)
        ref = flash_attention_reference(q, k, v, causal=causal)
        tol = 5e-2 if dtype == jnp.bfloat16 else 2e-2
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=tol, atol=tol)
        gf = jax.grad(lambda q: jnp.sum(flash_attention(
            q, k, v, causal=causal).astype(jnp.float32)))(q)
        gr = jax.grad(lambda q: jnp.sum(flash_attention_reference(
            q, k, v, causal=causal).astype(jnp.float32)))(q)
        tol = 1e-1 if dtype == jnp.bfloat16 else 5e-2
        np.testing.assert_allclose(np.asarray(gf, np.float32),
                                   np.asarray(gr, np.float32),
                                   rtol=tol, atol=tol)

    def test_multi_tensor_adam_step(self, rng):
        from apex_tpu.optimizers import FusedAdam

        params = [jnp.asarray(rng.randn(257, 130).astype(np.float32)),
                  jnp.asarray(rng.randn(33).astype(np.float32))]
        grads = [jnp.asarray(rng.randn(257, 130).astype(np.float32)),
                 jnp.asarray(rng.randn(33).astype(np.float32))]
        adam = FusedAdam(lr=1e-3)
        state = adam.init(params)
        new_params, _ = jax.jit(adam.step)(grads, params, state)
        import optax
        opt = optax.adamw(1e-3, b1=0.9, b2=0.999, eps=1e-8,
                          weight_decay=0.0)
        ostate = opt.init(params)
        upd, _ = opt.update(grads, ostate, params)
        ref = optax.apply_updates(params, upd)
        for a, r in zip(new_params, ref):
            np.testing.assert_allclose(a, r, rtol=1e-5, atol=1e-5)

    def test_xentropy_and_softmax(self, rng):
        from apex_tpu.ops.softmax import scaled_upper_triang_masked_softmax
        from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

        x = jnp.asarray(rng.randn(8, 128, 128).astype(np.float32))
        y = scaled_upper_triang_masked_softmax(x, 0.5)
        assert bool(jnp.all(jnp.isfinite(y)))
        logits = jnp.asarray(rng.randn(32, 512).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 512, (32,)))
        loss = softmax_cross_entropy_loss(logits, labels)
        ref = -jax.nn.log_softmax(logits)[jnp.arange(32), labels]
        np.testing.assert_allclose(loss, ref, rtol=1e-5, atol=1e-5)


class TestAmpComposition:
    def test_grad_autocast_over_pallas_layer_norm(self, rng):
        """THE round-2 breakage: grad(autocast(loss)) over FusedLayerNorm
        on the chip."""
        from apex_tpu import amp
        from apex_tpu.normalization import FusedLayerNorm

        ln = FusedLayerNorm(256)
        params = {"ln": ln.init_params(),
                  "w": jnp.asarray(rng.randn(256, 256).astype(np.float32))}
        x = jnp.asarray(rng.randn(8, 256).astype(np.float32))

        def loss(params, x):
            return jnp.sum(ln(params["ln"], x @ params["w"]) ** 2)

        g = jax.grad(amp.autocast(loss))(params, x)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.all(np.isfinite(np.asarray(leaf, np.float32)))


class TestTrainStepSmoke:
    def test_gpt_2layer_train_step(self, rng):
        from apex_tpu.models.gpt import GPTConfig, GPTModel
        from apex_tpu.optimizers import FusedAdam

        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=2,
                        num_attention_heads=4, max_seq_len=256,
                        dtype=jnp.bfloat16)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        adam = FusedAdam(lr=1e-3)
        opt_state = adam.init(params)
        tokens = jnp.asarray(rng.randint(0, 512, (4, 256)))
        targets = jnp.asarray(rng.randint(0, 512, (4, 256)))

        @jax.jit
        def step(params, opt_state):
            loss, grads = jax.value_and_grad(model.loss)(params, tokens,
                                                         targets)
            params, opt_state = adam.step(grads, params, opt_state)
            return loss, params, opt_state

        losses = []
        for _ in range(5):
            loss, params, opt_state = step(params, opt_state)
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses


class TestRound3SurfacesOnChip:
    """New round-3 surfaces exercised where they actually run."""

    def test_moe_fwd_bwd(self, rng):
        from apex_tpu.transformer.expert_parallel import MoEConfig, MoEMLP

        m = MoEMLP(MoEConfig(hidden_size=256, ffn_hidden_size=1024,
                             n_experts=8))
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(512, 256), jnp.bfloat16)
        out, aux = jax.jit(m)(params, x)
        assert out.shape == x.shape
        assert bool(jnp.isfinite(aux))
        g = jax.jit(jax.grad(
            lambda p: m(p, x)[0].astype(jnp.float32).sum()))(params)
        for leaf in jax.tree_util.tree_leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_openfold_attention_flash_path(self, rng):
        from apex_tpu.contrib.openfold_triton import attention_core
        from apex_tpu.ops.flash_attention import flash_attention_reference

        q = jnp.asarray(rng.randn(2, 4, 256, 64) * 0.3, jnp.bfloat16)
        out = jax.jit(attention_core)(q, q, q)
        ref = flash_attention_reference(q.astype(jnp.float32),
                                        q.astype(jnp.float32),
                                        q.astype(jnp.float32))
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), rtol=2e-2, atol=2e-2)

    def test_flash_attention_varlen(self, rng):
        from apex_tpu.ops.flash_attention import (flash_attention,
                                                  flash_attention_reference)

        q = jnp.asarray(rng.randn(3, 2, 256, 64) * 0.3, jnp.bfloat16)
        lens = jnp.asarray([256, 192, 64])
        out = jax.jit(lambda q: flash_attention(
            q, q, q, kv_seqlens=lens))(q)
        ref = flash_attention_reference(q.astype(jnp.float32),
                                        q.astype(jnp.float32),
                                        q.astype(jnp.float32),
                                        kv_seqlens=lens)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), rtol=2e-2, atol=2e-2)

    def test_gds_roundtrip_device_arrays(self, rng, tmp_path):
        from apex_tpu.contrib import gpu_direct_storage as gds

        tree = {"w": jnp.asarray(rng.randn(512, 512), jnp.bfloat16),
                "b": jnp.asarray(rng.randn(512), jnp.float32)}
        p = str(tmp_path / "ck.apxt")
        gds.save(p, tree)
        out = gds.load(p, tree_like=tree)
        np.testing.assert_array_equal(
            np.asarray(tree["w"]).view(np.uint8), out["w"].view(np.uint8))

    def test_ring_attention_single_device_path(self, rng):
        """n=1 context axis falls through to the flash kernel on chip."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.ops.flash_attention import flash_attention_reference
        from apex_tpu.transformer.context_parallel import ring_attention

        mesh = jax.make_mesh((1,), ("context",))
        q = jnp.asarray(rng.randn(1, 2, 256, 64) * 0.3, jnp.bfloat16)
        spec = P(None, None, "context", None)
        out = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "context",
                                           causal=True),
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec))(q, q, q)
        ref = flash_attention_reference(
            q.astype(jnp.float32), q.astype(jnp.float32),
            q.astype(jnp.float32), causal=True)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), rtol=2e-2, atol=2e-2)


class TestXlaFusionClaim:
    """SURVEY sanctions mlp/fused_dense as jnp-only because 'XLA already
    fuses GEMM+bias+activation'; this pins the claim to the compiled
    program: the ENTRY computation may contain only GEMMs, fusions and
    plumbing — any standalone elementwise kernel (bias add, gelu, relu)
    means an un-fused epilogue and fails here."""

    # any of these appearing as a standalone ENTRY instruction means an
    # un-fused elementwise kernel (HLO type grammar is too gnarly to
    # whitelist-parse robustly, so assert the negative directly)
    _ELEMENTWISE = ("add", "subtract", "multiply", "divide", "maximum",
                    "minimum", "exponential", "tanh", "logistic", "rsqrt",
                    "power", "select", "compare")

    def _entry_strays(self, compiled_text):
        import re
        blocks = re.split(r"\n\s*\n", compiled_text)
        entry = next(b for b in blocks if "ENTRY" in b)
        pat = re.compile(
            r"= .*? (%s)\(" % "|".join(self._ELEMENTWISE))
        return [l.strip()[:120] for l in entry.splitlines()
                if " = " in l and pat.search(l)]

    def test_mlp_forward_epilogues_fused(self):
        from apex_tpu.mlp import MLP

        m = MLP([1024, 4096, 1024], activation="relu")
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.ones((512, 1024), jnp.bfloat16)
        hlo = jax.jit(m.apply).lower(params, x).compile().as_text()
        strays = self._entry_strays(hlo)
        assert not strays, f"unfused entry ops: {strays}"
        # the chain compiles to fused kernels (GEMMs absorbed into
        # fusions on this backend), never standalone elementwise ops
        assert " fusion(" in hlo

    def test_fused_dense_gelu_dense_grad_fused(self):
        from apex_tpu.fused_dense import FusedDenseGeluDense

        m = FusedDenseGeluDense(1024, 4096, 1024)
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.ones((256, 1024), jnp.bfloat16)

        def loss(params, x):
            return m(params, x).astype(jnp.float32).sum()

        hlo = jax.jit(jax.grad(loss)).lower(params,
                                            x).compile().as_text()
        strays = self._entry_strays(hlo)
        assert not strays, f"unfused entry ops: {strays}"


class TestRound4SurfacesOnChip:
    """Round-4 surfaces on the real chip: fused flash dropout (compiled
    Mosaic incl. the uint32 counter-hash), selective remat, GPT dropout
    end-to-end, bf16 TP GEMM dtype, and the big-bucket bf16 packing that
    OOMed compile before the per-leaf reshape fix."""

    def test_flash_dropout_parity_and_determinism(self, rng):
        from apex_tpu.ops.flash_attention import (
            dropout_keep_scale, flash_attention, flash_attention_reference)

        b, h, s, d = 2, 4, 256, 64
        q = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
        rate, seed = 0.2, 321
        out = flash_attention(q, k, v, causal=True, dropout=rate,
                              dropout_seed=seed)
        mask = dropout_keep_scale(seed, b * h, s, s,
                                  rate).reshape(b, h, s, s)
        ref = flash_attention_reference(q, k, v, causal=True,
                                        dropout_mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-2, atol=2e-2)  # MXU f32 tol
        again = flash_attention(q, k, v, causal=True, dropout=rate,
                                dropout_seed=seed)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(again))
        # backward compiles and is finite with the regenerated mask
        g = jax.jit(jax.grad(lambda q: flash_attention(
            q, k, v, causal=True, dropout=rate,
            dropout_seed=seed).astype(jnp.float32).sum()))(q)
        assert bool(jnp.all(jnp.isfinite(g)))

    def test_gpt_dropout_train_step(self, rng):
        from apex_tpu.models.gpt import GPTConfig, GPTModel
        from apex_tpu.optimizers import FusedAdam

        cfg = GPTConfig(vocab_size=512, hidden_size=256, num_layers=2,
                        num_attention_heads=4, max_seq_len=128,
                        attention_dropout=0.1, dtype=jnp.bfloat16)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        adam = FusedAdam(lr=1e-3)
        state = adam.init(params)
        tokens = jnp.asarray(rng.randint(0, 512, (4, 128)))

        @jax.jit
        def step(params, state, seed):
            loss, g = jax.value_and_grad(model.loss)(
                params, tokens, tokens, dropout_seed=seed)
            params, state = adam.step(g, params, state)
            return loss, params, state

        losses = []
        for i in range(4):
            loss, params, state = step(params, state, jnp.int32(i))
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses

    def test_selective_remat_compiles_and_matches(self, rng):
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        kw = dict(vocab_size=512, hidden_size=256, num_layers=2,
                  num_attention_heads=4, max_seq_len=128, remat=True,
                  dtype=jnp.bfloat16)
        tokens = jnp.asarray(rng.randint(0, 512, (4, 128)))
        out = {}
        for pol in ("full", "dots"):
            m = GPTModel(GPTConfig(remat_policy=pol, **kw))
            p = m.init_params(jax.random.PRNGKey(0))
            # the policy only changes the BACKWARD (which residuals are
            # saved vs recomputed) — grads are the real comparison
            loss, g = jax.jit(jax.value_and_grad(m.loss))(p, tokens,
                                                          tokens)
            out[pol] = (float(loss), g)
        np.testing.assert_allclose(out["full"][0], out["dots"][0],
                                   rtol=1e-3)
        for a, b in zip(jax.tree_util.tree_leaves(out["full"][1]),
                        jax.tree_util.tree_leaves(out["dots"][1])):
            np.testing.assert_allclose(np.asarray(a, np.float32),
                                       np.asarray(b, np.float32),
                                       rtol=2e-2, atol=2e-2)

    def test_tp_linear_bf16_gemm_dtype(self, rng):
        """The serial TP linear must emit a bf16 dot for bf16 activations
        (the round-4 dtype-contract fix) — checked in the optimized HLO."""
        from apex_tpu.transformer import tensor_parallel as tp

        lin = tp.ColumnParallelLinear(256, 512, axis_name=None)
        params = lin.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(8, 256), jnp.bfloat16)
        hlo = jax.jit(lambda p, x: lin(p, x)[0]).lower(params, x)\
            .compile().as_text()
        # the dot/convolution op itself must produce bf16 (not merely
        # mention bf16 somewhere — the input declaration already does);
        # a silent f32 promotion would emit "f32[...] dot|convolution"
        import re
        ops = re.findall(r"(\w+)\[[^\]]*\]\S* (?:dot|convolution)\(", hlo)
        assert ops and all(o == "bf16" for o in ops), (ops, hlo[:500])
        out, _ = jax.jit(lambda p, x: lin(p, x))(params, x)
        assert out.dtype == jnp.bfloat16

    def test_large_bf16_bucket_flatten_unflatten(self, rng):
        """~50M-element bf16 bucket round-trips through the packing (the
        pre-fix concat-then-reshape compile would OOM at this scale on
        larger models; per-leaf packing must stay layout-safe)."""
        from apex_tpu.multi_tensor_apply import bucketing as B

        shapes = [(4096, 4096), (4096,), (4096, 4096), (16384, 1024),
                  (1000, 333)]
        meta = B.bucket_meta(shapes, jnp.bfloat16)
        leaves = [jnp.asarray(rng.randn(*s).astype(np.float32),
                              jnp.bfloat16) for s in shapes]
        packed = jax.jit(lambda ls: B.flatten_bucket(ls, meta))(leaves)
        assert packed.shape == (meta.nrows, 128)
        outs = jax.jit(lambda p: B.unflatten_bucket(p, meta))(packed)
        for a, b in zip(outs, leaves):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_fused_lm_head_parity(self, rng):
        """Logit-free LM-head CE (ops/lm_head.py) compiled on Mosaic:
        fwd + both grads against the materialized reference."""
        from apex_tpu.ops.lm_head import (
            fused_linear_cross_entropy, fused_linear_cross_entropy_reference)

        N, H, V = 1024, 512, 8192
        x = jnp.asarray(rng.randn(N, H).astype(np.float32) * 0.5)
        w = jnp.asarray(rng.randn(V, H).astype(np.float32) * 0.1)
        t = jnp.asarray(rng.randint(0, V, (N,)))
        out = fused_linear_cross_entropy(x, w, t)
        ref = fused_linear_cross_entropy_reference(x, w, t)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-3, atol=2e-3)
        gx, gw = jax.jit(jax.grad(
            lambda x, w: jnp.mean(fused_linear_cross_entropy(x, w, t)),
            argnums=(0, 1)))(x, w)
        rx, rw = jax.jit(jax.grad(
            lambda x, w: jnp.mean(
                fused_linear_cross_entropy_reference(x, w, t)),
            argnums=(0, 1)))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                                   rtol=2e-3, atol=2e-4)


class TestPerfGuard:
    """Round-5 regression armor (VERDICT r4 item 8): the headline bench
    step must not silently give back its measured best.  Margin is wide
    (30%) because tunnel timing drifts between sessions; a real
    regression (the packed-optimizer or remat tax returning) costs
    ~45-90%, which this still catches."""

    MARGIN = 1.30

    def _recorded(self, key):
        import json
        import pathlib

        root = pathlib.Path(__file__).resolve().parents[1]
        return json.loads((root / "BASELINE.json").read_text())[
            "recorded_best"][key]

    def test_bert_headline_step_time(self):
        import sys
        import pathlib

        root = pathlib.Path(__file__).resolve().parents[1]
        sys.path.insert(0, str(root))
        import bench

        run, args, _, _, _ = bench._make_bert_lamb_step(
            16, 2, remat=False, bucketed=False)
        # odd round count: times[len//2] is a true median (2 rounds
        # would return the slower one and flake on tunnel drift)
        dt = bench._time_steps(run, args, warmup=1, iters=4, rounds=3)
        best = self._recorded("bert_b16x2_none_perleaf_step_s")
        assert dt < best * self.MARGIN, (
            f"BERT headline step regressed: {dt * 1e3:.1f} ms vs recorded "
            f"best {best * 1e3:.1f} ms (margin {self.MARGIN}x) — see "
            "BASELINE.json recorded_best and BENCH_r05_local.json")


class TestScheduledCollectiveEvidence:
    """VERDICT r4 item 5: pin the 'XLA does the overlap/bucketing' claims
    (transformer/tensor_parallel/layers.py module docstring) with
    compiled evidence instead of assertion.

    One real chip cannot EXECUTE a 4-device program, but the axon AOT
    compiler can COMPILE for a real v5e:2x2 topology
    (jax.experimental.topologies); ``compiled.as_text()`` is the
    post-scheduling TPU module.  TPU HLO keeps all-reduce as one
    synchronous instruction (the ICI pipelining lives inside the ring
    emitter), so the checkable facts are:

    * TP psums lower to ``all-reduce`` with an ICI RING strategy;
    * the backward's per-weight gradient psums are COMBINED into one
      bucketed all-reduce (apex DDP's flattened-bucket allreduce,
      performed by XLA's combiner);
    * the schedule interleaves async data movement (slice/copy
      start..done) with compute fusions — at least one async pair has
      compute scheduled between start and done.
    """

    def _compiled_tp_block_text(self):
        from jax.experimental import topologies
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P
        from jax import shard_map

        from apex_tpu.transformer import tensor_parallel as tp

        try:
            topo = topologies.get_topology_desc("v5e:2x2", platform="tpu")
        except Exception as e:  # noqa: BLE001
            pytest.skip(f"no AOT topology compiler here: {e}")
        mesh = Mesh(np.array(topo.devices[:4]).reshape(2, 2),
                    ("data", "model"))

        col = tp.ColumnParallelLinear(1024, 4096, gather_output=False,
                                      world_size=2, axis_name="model")
        row = tp.RowParallelLinear(4096, 1024, input_is_parallel=True,
                                   world_size=2, axis_name="model")

        def block(p, x):
            h, _ = col(p["c"], x)
            h = jax.nn.gelu(h, approximate=True)
            y, _ = row(p["r"], h)
            h2, _ = col(p["c2"], y)
            h2 = jax.nn.gelu(h2, approximate=True)
            y2, _ = row(p["r2"], h2)
            return jnp.sum(y2.astype(jnp.float32))

        def grad_fn(p, x):
            return jax.grad(block, argnums=0)(p, x)

        cspec = {"weight": P("model", None), "bias": P("model")}
        rspec = {"weight": P(None, "model"), "bias": P()}
        pspec = {"c": cspec, "r": rspec, "c2": cspec, "r2": rspec}
        f = shard_map(grad_fn, mesh=mesh,
                      in_specs=(pspec, P("data", None)), out_specs=pspec)

        def sds(shape, spec):
            return jax.ShapeDtypeStruct(
                shape, jnp.bfloat16, sharding=NamedSharding(mesh, spec))

        p = {k: {"weight": sds((4096, 1024) if k.startswith("c")
                               else (1024, 4096), pspec[k]["weight"]),
                 "bias": sds((4096,) if k.startswith("c") else (1024,),
                             pspec[k]["bias"])}
             for k in ("c", "r", "c2", "r2")}
        x = sds((512, 1024), P("data", None))
        return jax.jit(f).lower(p, x).compile().as_text()

    def test_ring_collectives_bucketed_allreduce_and_async_interleave(self):
        import re

        txt = self._compiled_tp_block_text()

        # (1) psum -> all-reduce on an ICI ring (whole lines: the
        # combined op's result-tuple dtypes precede the op name)
        ars = re.findall(r"[^\n]*= [^\n]*all-reduce\([^\n]*", txt)
        assert ars, "no all-reduce in the compiled TP block"
        assert any("RingStrategy" in a or "StrategyRing" in a
                   for a in ars), "no ICI ring strategy on any all-reduce"

        # (2) the data-parallel wgrad psums are COMBINED: one all-reduce
        # carries multiple weight-shaped operands (XLA's combiner = the
        # bucketed flattened allreduce apex DDP hand-rolls)
        assert any(a.count("bf16[") >= 4 for a in ars), (
            "gradient all-reduces were not combined/bucketed")

        # (3) async data movement interleaved with compute: some
        # start..done pair — matched BY NAME, the done op consumes its
        # start op as an operand — has a fusion scheduled between (a
        # loose cross-pair regex would pass even on a fully serialized
        # schedule)
        lines = txt.splitlines()
        interleaved = False
        for i, ln in enumerate(lines):
            m = re.match(r"\s*(%\S*-start\S*) = ", ln)
            if not m:
                continue
            name = m.group(1)
            for j in range(i + 1, len(lines)):
                if "-done" in lines[j] and (
                        name + ")" in lines[j] or name + "," in lines[j]):
                    if any("%fusion" in lines[k] for k in range(i + 1, j)):
                        interleaved = True
                    break
            if interleaved:
                break
        assert interleaved, (
            "no async start/compute/done interleaving in the schedule")
