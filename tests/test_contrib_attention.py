"""contrib.multihead_attn / contrib.fmha vs unfused references
(pattern: ``apex/contrib/test/multihead_attn/``, ``test/fmha/``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.fmha import FMHAFun, fmha
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)
from apex_tpu.utils import set_force_pallas


@pytest.fixture(autouse=True)
def _force_pallas():
    set_force_pallas(True)
    yield
    set_force_pallas(None)


def _ref_mha(q, k, v, heads, causal=False, pad_mask=None):
    """(s, b, hidden) torch-style reference."""
    sq, b, hidden = q.shape
    sk = k.shape[0]
    d = hidden // heads
    qh = q.reshape(sq, b, heads, d).transpose(1, 2, 0, 3)
    kh = k.reshape(sk, b, heads, d).transpose(1, 2, 0, 3)
    vh = v.reshape(sk, b, heads, d).transpose(1, 2, 0, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * d ** -0.5
    if pad_mask is not None:
        s = jnp.where(pad_mask[:, None, None, :], -1e30, s)
    if causal:
        s = jnp.where(jnp.arange(sk)[None, None, None, :]
                      > jnp.arange(sq)[None, None, :, None], -1e30, s)
    p = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return ctx.transpose(2, 0, 1, 3).reshape(sq, b, hidden)


def _lin(p, x):
    y = x @ p["weight"].T
    if "bias" in p:
        y = y + p["bias"]
    return y


class TestSelfMultiheadAttn:
    def test_matches_reference(self, rng):
        m = SelfMultiheadAttn(64, 4, bias=True)
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(16, 2, 64), jnp.float32)
        out = m(params, x)
        qkv = _lin(params["in_proj"], x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        ref = _lin(params["out_proj"], _ref_mha(q, k, v, 4))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_norm_add(self, rng):
        m = SelfMultiheadAttn(64, 4, include_norm_add=True)
        params = m.init_params(jax.random.PRNGKey(1))
        x = jnp.asarray(rng.randn(8, 2, 64), jnp.float32)
        out = m(params, x)
        # residual add must be the RAW input (apex norm_add semantics)
        xn = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        xn = xn * params["lyr_nrm"]["weight"] + params["lyr_nrm"]["bias"]
        qkv = _lin(params["in_proj"], xn)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        ref = _lin(params["out_proj"], _ref_mha(q, k, v, 4)) + x
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_key_padding_mask(self, rng):
        m = SelfMultiheadAttn(32, 2)
        params = m.init_params(jax.random.PRNGKey(2))
        x = jnp.asarray(rng.randn(8, 3, 32), jnp.float32)
        mask = jnp.asarray(rng.rand(3, 8) > 0.7)
        out = m(params, x, key_padding_mask=mask)
        qkv = _lin(params["in_proj"], x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        ref = _lin(params["out_proj"],
                   _ref_mha(q, k, v, 2, pad_mask=mask))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_grad_flows(self, rng):
        m = SelfMultiheadAttn(32, 2, bias=True)
        params = m.init_params(jax.random.PRNGKey(3))
        x = jnp.asarray(rng.randn(8, 2, 32), jnp.float32)
        g = jax.grad(lambda p: jnp.sum(m(p, x) ** 2))(params)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.all(np.isfinite(leaf))
            assert float(jnp.abs(leaf).max()) > 0

    def test_dropout_requires_rng(self, rng):
        m = SelfMultiheadAttn(32, 2, dropout=0.5)
        params = m.init_params(jax.random.PRNGKey(4))
        x = jnp.asarray(rng.randn(4, 1, 32), jnp.float32)
        with pytest.raises(ValueError):
            m(params, x)
        out = m(params, x, dropout_rng=jax.random.PRNGKey(5))
        assert out.shape == x.shape
        # eval mode: dropout off, deterministic
        o1 = m(params, x, is_training=False)
        o2 = m(params, x, is_training=False)
        np.testing.assert_array_equal(o1, o2)


class TestMaterializedPathSemantics:
    """The materialized (mask/dropout) path must keep the SAME masking
    semantics as the fused path — review findings from round 3."""

    def test_kv_seqlens_respected_with_padding_mask(self, rng):
        # both kv_seqlens and key_padding_mask present → the materialized
        # path must apply BOTH (kv_seqlens used to be dropped)
        m = SelfMultiheadAttn(32, 2)
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(8, 2, 32), jnp.float32)
        lens = jnp.asarray([5, 8], jnp.int32)
        mask = jnp.zeros((2, 8), bool).at[0, 1].set(True)
        out = m(params, x, key_padding_mask=mask, kv_seqlens=lens)
        # equivalent single mask: padded OR explicitly masked
        combined = mask | (jnp.arange(8)[None, :] >= lens[:, None])
        ref = m(params, x, key_padding_mask=combined)
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    def test_fully_masked_row_outputs_zero(self, rng):
        m = SelfMultiheadAttn(32, 2)
        params = m.init_params(jax.random.PRNGKey(1))
        x = jnp.asarray(rng.randn(4, 2, 32), jnp.float32)
        mask = jnp.zeros((2, 4), bool).at[1].set(True)  # row 1 all masked
        out = m(params, x, key_padding_mask=mask)
        # fully masked row: attention context is exactly 0, so the output
        # is only the out_proj bias (bias=False here → 0)
        np.testing.assert_allclose(np.asarray(out[:, 1]), 0.0, atol=1e-6)


class TestEncdecMultiheadAttn:
    def test_matches_reference(self, rng):
        m = EncdecMultiheadAttn(64, 4, bias=True)
        params = m.init_params(jax.random.PRNGKey(0))
        q_in = jnp.asarray(rng.randn(8, 2, 64), jnp.float32)
        mem = jnp.asarray(rng.randn(16, 2, 64), jnp.float32)
        out = m(params, q_in, mem)
        q = _lin(params["q_proj"], q_in)
        kv = _lin(params["kv_proj"], mem)
        k, v = jnp.split(kv, 2, axis=-1)
        ref = _lin(params["out_proj"], _ref_mha(q, k, v, 4))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


class TestFMHA:
    def test_packed_matches_per_sequence(self, rng):
        h, d = 2, 32
        lens = [5, 12, 8]
        total = sum(lens)
        cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
        qkv = jnp.asarray(rng.randn(total, 3, h, d), jnp.float32)
        out = fmha(qkv, cu, max_s=16)
        # reference: attend each sequence independently at full density
        for i, L in enumerate(lens):
            seg = qkv[int(cu[i]):int(cu[i + 1])]      # (L, 3, h, d)
            q = seg[:, 0].transpose(1, 0, 2)          # (h, L, d)
            k = seg[:, 1].transpose(1, 0, 2)
            v = seg[:, 2].transpose(1, 0, 2)
            s = jnp.einsum("hqd,hkd->hqk", q, k) * d ** -0.5
            p = jax.nn.softmax(s, axis=-1)
            ref = jnp.einsum("hqk,hkd->hqd", p, v).transpose(1, 0, 2)
            np.testing.assert_allclose(out[int(cu[i]):int(cu[i + 1])],
                                       ref, rtol=2e-5, atol=2e-5)

    def test_causal(self, rng):
        h, d = 2, 32
        lens = [10, 6]
        cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
        qkv = jnp.asarray(rng.randn(sum(lens), 3, h, d), jnp.float32)
        out = fmha(qkv, cu, max_s=16, causal=True)
        for i, L in enumerate(lens):
            seg = qkv[int(cu[i]):int(cu[i + 1])]
            q = seg[:, 0].transpose(1, 0, 2)
            k = seg[:, 1].transpose(1, 0, 2)
            v = seg[:, 2].transpose(1, 0, 2)
            s = jnp.einsum("hqd,hkd->hqk", q, k) * d ** -0.5
            s = jnp.where(jnp.arange(L)[None, None, :]
                          > jnp.arange(L)[None, :, None], -1e30, s)
            p = jax.nn.softmax(s, axis=-1)
            ref = jnp.einsum("hqk,hkd->hqd", p, v).transpose(1, 0, 2)
            np.testing.assert_allclose(out[int(cu[i]):int(cu[i + 1])],
                                       ref, rtol=2e-5, atol=2e-5)

    def test_apply_wrapper_and_grad(self, rng):
        lens = [7, 9]
        cu = jnp.asarray(np.cumsum([0] + lens), jnp.int32)
        qkv = jnp.asarray(rng.randn(sum(lens), 3, 2, 32), jnp.float32)
        out = FMHAFun.apply(qkv, cu, None, 0.0, 16)
        assert out.shape == (sum(lens), 2, 32)
        g = jax.grad(lambda x: jnp.sum(
            fmha(x, cu, max_s=16) ** 2))(qkv)
        assert np.all(np.isfinite(g))
        assert float(jnp.abs(g).max()) > 0
