"""fp16_utils surface (reference: ``apex/fp16_utils/{fp16util,
loss_scaler,fp16_optimizer}.py`` — the pre-amp manual mixed-precision
tier, tested upstream in ``tests/L0/run_fp16util``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.fp16_utils import (BN_convert_float, DynamicLossScaler,
                                 FP16_Optimizer, master_params_to_model_params,
                                 model_grads_to_master_grads,
                                 network_to_half, prep_param_lists)
from apex_tpu.optimizers import FusedAdam


@pytest.fixture
def params():
    rng = np.random.RandomState(0)
    return {
        "linear": {"weight": jnp.asarray(rng.randn(8, 8), jnp.float32),
                   "bias": jnp.zeros((8,), jnp.float32)},
        "bn": {"weight": jnp.ones((8,), jnp.float32),
               "bias": jnp.zeros((8,), jnp.float32)},
        "step": jnp.zeros((), jnp.int32),
    }


class TestFp16Util:
    def test_network_to_half_keeps_norm_fp32(self, params):
        half = network_to_half(params)
        assert half["linear"]["weight"].dtype == jnp.bfloat16
        assert half["bn"]["weight"].dtype == jnp.float32      # BN stays
        assert half["step"].dtype == jnp.int32                # non-float

    def test_bn_convert_float(self, params):
        all_half = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
        fixed = BN_convert_float(all_half)
        assert fixed["bn"]["weight"].dtype == jnp.float32
        assert fixed["linear"]["weight"].dtype == jnp.bfloat16

    def test_prep_and_sync_roundtrip(self, params):
        half = network_to_half(params)
        model_p, master_p = prep_param_lists(half)
        assert master_p["linear"]["weight"].dtype == jnp.float32
        # perturb master, sync down, dtypes follow the model pytree
        master_p = jax.tree_util.tree_map(
            lambda x: x + 1 if jnp.issubdtype(x.dtype, jnp.floating)
            else x, master_p)
        synced = master_params_to_model_params(model_p, master_p)
        assert synced["linear"]["weight"].dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(synced["bn"]["weight"]),
            np.asarray(params["bn"]["weight"]) + 1)

    def test_model_grads_to_master_grads(self, params):
        g = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            {"linear": params["linear"]})
        mg = model_grads_to_master_grads(g)
        assert mg["linear"]["weight"].dtype == jnp.float32


class TestFP16Optimizer:
    def _tiny(self):
        rng = np.random.RandomState(1)
        params = {"w": jnp.asarray(rng.randn(16, 16) * 0.1, jnp.bfloat16)}
        grads = {"w": jnp.asarray(rng.randn(16, 16) * 0.01, jnp.bfloat16)}
        return params, grads

    def test_step_matches_fp32_adam(self):
        params, grads = self._tiny()
        opt = FP16_Optimizer(FusedAdam(lr=1e-2))
        state = opt.init(params)
        p = params
        for _ in range(3):
            p, state = opt.step(grads, p, state)
        assert p["w"].dtype == jnp.bfloat16

        ref_opt = FusedAdam(lr=1e-2)
        rp = {"w": params["w"].astype(jnp.float32)}
        rs = ref_opt.init(rp)
        rg = {"w": grads["w"].astype(jnp.float32)}
        for _ in range(3):
            rp, rs = ref_opt.step(rg, rp, rs)
        np.testing.assert_allclose(np.asarray(p["w"], np.float32),
                                   np.asarray(rp["w"]),
                                   rtol=2e-2, atol=2e-2)

    def test_scaled_loss_and_overflow_skip(self):
        params, grads = self._tiny()
        opt = FP16_Optimizer(FusedAdam(lr=1e-2), dynamic_loss_scale=True,
                             dynamic_loss_args={"init_scale": 2.0 ** 8})
        state = opt.init(params)
        loss = opt.scale_loss(jnp.float32(2.0), state)
        assert float(loss) == 2.0 * 2.0 ** 8

        inf_grads = {"w": jnp.full_like(grads["w"], jnp.inf)}
        p1, s1 = opt.step(inf_grads, params, state)
        # overflow: params unchanged, scale halved
        np.testing.assert_array_equal(
            np.asarray(p1["w"], np.float32),
            np.asarray(params["w"], np.float32))
        assert float(s1["loss_scaler"].loss_scale) < 2.0 ** 8

    def test_dynamic_loss_scaler_alias(self):
        s = DynamicLossScaler(init_scale=2.0 ** 10)
        st = s.init()
        assert float(st.loss_scale) == 2.0 ** 10
        st2 = s.update(st, jnp.float32(1.0))     # overflow -> backoff
        assert float(st2.loss_scale) < 2.0 ** 10
