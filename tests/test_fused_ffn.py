"""Fused FFN Pallas kernel (ISSUE 17): kernel-vs-reference parity fwd+bwd
(interpret-mode Pallas at flash tolerances; off-TPU dispatch is bitwise),
and the ``fused_ffn`` knob threaded through every parallelism tier —
serial, remat, TP=2 + sequence parallel, pipeline pp=2, MPMD dp2 x pp2 —
plus the config/plan validation surface.

Mirrors ``tests/test_flash_attention.py`` for the kernel half and
``tests/test_gpt.py`` for the tier parity half.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.bert import BertConfig, BertModel
from apex_tpu.models.gpt import (GPTConfig, GPTModel, pack_for_shard_map,
                                 pipeline_step)
from apex_tpu.ops.fused_ffn import (fused_ffn, fused_ffn_reference,
                                    fused_ffn_tp)
from apex_tpu.parallel.plan import ParallelPlan
from apex_tpu.utils import set_force_pallas


def _inputs(rng, m, k, f, n, dtype):
    x = jnp.asarray(rng.randn(m, k), dtype)
    w1 = jnp.asarray(rng.randn(f, k) * 0.05, dtype)
    b1 = jnp.asarray(rng.randn(f) * 0.05, dtype)
    w2 = jnp.asarray(rng.randn(n, f) * 0.05, dtype)
    b2 = jnp.asarray(rng.randn(n) * 0.05, dtype)
    return x, w1, b1, w2, b2


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 \
        else dict(rtol=2e-5, atol=2e-5)


def _grads(ffn, args):
    def f(*a):
        return jnp.sum(ffn(*a).astype(jnp.float32))
    return jax.grad(f, argnums=tuple(range(len(args))))(*args)


# ---------------------------------------------------------------------------
# kernel vs reference — Pallas forced on (interpret mode on CPU)
# ---------------------------------------------------------------------------


class TestKernelParity:
    @pytest.fixture(autouse=True)
    def _force_pallas(self):
        set_force_pallas(True)
        yield
        set_force_pallas(None)

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_forward_matches_reference(self, rng, dtype):
        args = _inputs(rng, 256, 128, 512, 128, dtype)
        out = fused_ffn(*args)
        ref = fused_ffn_reference(*args)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   **_tol(dtype))

    def test_forward_odd_shapes(self, rng):
        # every extent off the 128-lane / block grid: padding must wash out
        args = _inputs(rng, 200, 96, 300, 80, jnp.float32)
        out = fused_ffn(*args, block_m=128, block_f=128)
        ref = fused_ffn_reference(*args)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_forward_no_b2(self, rng):
        x, w1, b1, w2, _ = _inputs(rng, 128, 64, 256, 64, jnp.float32)
        out = fused_ffn(x, w1, b1, w2)
        ref = fused_ffn_reference(x, w1, b1, w2)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_leading_batch_dims(self, rng):
        x, w1, b1, w2, b2 = _inputs(rng, 4 * 64, 64, 256, 64, jnp.float32)
        x3 = x.reshape(4, 64, 64)
        out = fused_ffn(x3, w1, b1, w2, b2)
        assert out.shape == (4, 64, 64)
        ref = fused_ffn_reference(x3, w1, b1, w2, b2)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_grads_match_reference_f32(self, rng):
        args = _inputs(rng, 256, 128, 512, 128, jnp.float32)
        got = _grads(fused_ffn, args)
        ref = _grads(fused_ffn_reference, args)
        for g, r in zip(got, ref, strict=True):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=5e-5, atol=5e-5)

    def test_grads_odd_shapes(self, rng):
        args = _inputs(rng, 200, 96, 300, 80, jnp.float32)
        got = _grads(lambda *a: fused_ffn(*a, block_m=128, block_f=128),
                     args)
        ref = _grads(fused_ffn_reference, args)
        for g, r in zip(got, ref, strict=True):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=5e-5, atol=5e-5)

    def test_grads_bf16_norm_relative(self, rng):
        # the kernel accumulates f32 where the unfused bf16 chain rounds
        # per-op, so element-wise rtol on near-zero entries is meaningless;
        # bound the error relative to the gradient's own magnitude instead
        args = _inputs(rng, 256, 128, 512, 128, jnp.bfloat16)
        got = _grads(fused_ffn, args)
        ref = _grads(fused_ffn_reference, args)
        for g, r in zip(got, ref, strict=True):
            g = np.asarray(g, np.float32)
            r = np.asarray(r, np.float32)
            assert np.abs(g - r).max() / (np.abs(r).max() + 1e-6) < 2e-2

    def test_jit_grad_composes(self, rng):
        args = _inputs(rng, 128, 64, 256, 64, jnp.float32)

        @jax.jit
        def f(*a):
            return jnp.sum(fused_ffn(*a) ** 2)

        g = jax.jit(jax.grad(f, argnums=(0, 1)))(*args)
        assert all(np.all(np.isfinite(np.asarray(t))) for t in g)


# ---------------------------------------------------------------------------
# off-TPU dispatch contract — knob on must be BITWISE the unfused chain
# ---------------------------------------------------------------------------


class TestOffTpuDispatch:
    def test_forward_bitwise(self, rng):
        args = _inputs(rng, 64, 32, 128, 32, jnp.float32)
        set_force_pallas(None)
        out = fused_ffn(*args)
        ref = fused_ffn_reference(*args)
        assert np.asarray(out).tobytes() == np.asarray(ref).tobytes()

    def test_grads_bitwise(self, rng):
        args = _inputs(rng, 64, 32, 128, 32, jnp.float32)
        got = _grads(fused_ffn, args)
        ref = _grads(fused_ffn_reference, args)
        for g, r in zip(got, ref, strict=True):
            assert np.asarray(g).tobytes() == np.asarray(r).tobytes()

    def test_force_toggle_switches_paths(self, rng):
        # both paths agree within interpret-mode tolerance on the same
        # inputs, proving the dispatch toggle selects real alternatives
        args = _inputs(rng, 128, 64, 128, 64, jnp.float32)
        try:
            set_force_pallas(False)
            ref = fused_ffn(*args)
            set_force_pallas(True)
            out = fused_ffn(*args)
        finally:
            set_force_pallas(None)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# validation surface
# ---------------------------------------------------------------------------


class TestValidation:
    def test_shape_mismatch_raises(self, rng):
        x, w1, b1, w2, b2 = _inputs(rng, 64, 32, 128, 32, jnp.float32)
        with pytest.raises(ValueError, match="w2"):
            fused_ffn(x, w1, b1, w2[:, :100], b2)

    def test_gpt_moe_conflict_raises(self):
        with pytest.raises(ValueError, match="one or the other"):
            GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                      num_attention_heads=2, max_seq_len=8,
                      fused_ffn=True, n_experts=2)

    def test_mlp_forward_wrong_shape_raises(self, rng):
        from apex_tpu.mlp import MLP, mlp_forward
        m = MLP([16, 32, 32, 16], activation="gelu")
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(4, 16), jnp.float32)
        with pytest.raises(ValueError,
                           match="2-layer biased GELU"):
            mlp_forward(params, x, activation="gelu", fused_ffn=True)
        with pytest.raises(ValueError,
                           match="2-layer biased GELU"):
            m2 = MLP([16, 32, 16], activation="relu")
            mlp_forward(m2.init_params(jax.random.PRNGKey(0)), x,
                        activation="relu", fused_ffn=True)

    def test_plan_roundtrip(self):
        plan = ParallelPlan(tp=2, sequence_parallel=True, fused_ffn=True)
        d = plan.to_dict()
        assert d["fused_ffn"] is True
        assert ParallelPlan.from_dict(d) == plan
        assert "ffn=fused" in plan.describe()
        # default plans must serialize byte-identically to pre-knob writers
        assert "fused_ffn" not in ParallelPlan().to_dict()

    def test_plan_applies_to_config(self):
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                         num_attention_heads=4, max_seq_len=16,
                         plan=ParallelPlan(fused_ffn=True))
        assert cfg.fused_ffn is True

    def test_plan_conflict_warns(self):
        with pytest.warns(DeprecationWarning):
            GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                      num_attention_heads=2, max_seq_len=8,
                      fused_ffn=True, plan=ParallelPlan())


# ---------------------------------------------------------------------------
# module rewire: fused_dense / mlp route onto the same kernel
# ---------------------------------------------------------------------------


class TestModuleRewire:
    def test_fused_dense_gelu_dense_bitwise(self, rng):
        from apex_tpu.fused_dense import FusedDenseGeluDense
        off = FusedDenseGeluDense(32, 128, 32)
        on = FusedDenseGeluDense(32, 128, 32, fused_ffn=True)
        params = off.init_params(jax.random.PRNGKey(3))
        x = jnp.asarray(rng.randn(8, 32), jnp.float32)
        assert np.asarray(on(params, x)).tobytes() \
            == np.asarray(off(params, x)).tobytes()

    def test_mlp_bitwise(self, rng):
        from apex_tpu.mlp import MLP
        off = MLP([16, 64, 16], activation="gelu")
        on = MLP([16, 64, 16], activation="gelu", fused_ffn=True)
        params = off.init_params(jax.random.PRNGKey(4))
        x = jnp.asarray(rng.randn(8, 16), jnp.float32)
        assert np.asarray(on(params, x)).tobytes() \
            == np.asarray(off(params, x)).tobytes()


# ---------------------------------------------------------------------------
# model threading: serial / remat / TP+SP / pipeline / MPMD
# ---------------------------------------------------------------------------

_GPT_KW = dict(vocab_size=32, hidden_size=16, num_layers=2,
               num_attention_heads=2, max_seq_len=8)


def _gpt_data(rng, batch=4, seq=8):
    tokens = jnp.asarray(rng.randint(0, 32, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, 32, (batch, seq)))
    return tokens, targets


def _loss_and_grads(model, params, tokens, targets):
    return jax.jit(jax.value_and_grad(model.loss))(params, tokens, targets)


class TestModelThreading:
    def test_gpt_serial_bitwise(self, rng):
        params = GPTModel(GPTConfig(**_GPT_KW)).init_params(
            jax.random.PRNGKey(0))
        tokens, targets = _gpt_data(rng)
        l0, g0 = _loss_and_grads(GPTModel(GPTConfig(**_GPT_KW)),
                                 params, tokens, targets)
        l1, g1 = _loss_and_grads(
            GPTModel(GPTConfig(fused_ffn=True, **_GPT_KW)),
            params, tokens, targets)
        assert np.asarray(l0).tobytes() == np.asarray(l1).tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1), strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_gpt_remat_bitwise(self, rng):
        params = GPTModel(GPTConfig(**_GPT_KW)).init_params(
            jax.random.PRNGKey(1))
        tokens, targets = _gpt_data(rng)
        l0, g0 = _loss_and_grads(
            GPTModel(GPTConfig(remat=True, **_GPT_KW)),
            params, tokens, targets)
        l1, g1 = _loss_and_grads(
            GPTModel(GPTConfig(fused_ffn=True, remat=True, **_GPT_KW)),
            params, tokens, targets)
        assert np.asarray(l0).tobytes() == np.asarray(l1).tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1), strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_bert_serial_bitwise(self, rng):
        kw = dict(vocab_size=64, hidden_size=32, num_layers=2,
                  num_attention_heads=4, max_seq_len=16)
        params = BertModel(BertConfig(**kw)).init_params(
            jax.random.PRNGKey(2))
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        labels = tokens
        l0, g0 = _loss_and_grads(BertModel(BertConfig(**kw)),
                                 params, tokens, labels)
        l1, g1 = _loss_and_grads(
            BertModel(BertConfig(fused_ffn=True, **kw)),
            params, tokens, labels)
        assert np.asarray(l0).tobytes() == np.asarray(l1).tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1), strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_tp2_sp_parity(self, rng):
        serial = GPTModel(GPTConfig(**_GPT_KW))
        params = serial.init_params(jax.random.PRNGKey(5))
        tokens, targets = _gpt_data(rng)
        ref_loss = float(jax.jit(serial.loss)(params, tokens, targets))
        ref_grads = jax.jit(jax.grad(serial.loss))(params, tokens, targets)

        par = GPTModel(GPTConfig(tensor_parallel_size=2, axis_name="model",
                                 sequence_parallel=True, fused_ffn=True,
                                 **_GPT_KW))
        mesh = jax.make_mesh((2,), ("model",), devices=jax.devices()[:2])
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            par, params)

        def step(sp, tk, tg):
            loss, g = jax.value_and_grad(par.loss)(local_fn(sp), tk, tg)
            return loss, repack_fn(g)

        loss, grads = jax.jit(shard_map(
            step, mesh=mesh, in_specs=(in_specs, P(), P()),
            out_specs=(P(), in_specs)))(packed, tokens, targets)

        assert abs(float(loss) - ref_loss) <= 7e-7
        ref_packed, _, _, _ = pack_for_shard_map(par, ref_grads)
        for got, ref in zip(jax.tree_util.tree_leaves(grads),
                            jax.tree_util.tree_leaves(ref_packed),
                            strict=True):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=5e-4, atol=1e-5)

    def _pp_run(self, model, params, tokens, targets, S):
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            model, params, n_stages=S, tensor_axis=None)
        mesh = jax.make_mesh((S,), ("pipe",), devices=jax.devices()[:S])

        def step(sp, tk, tg):
            loss, g = pipeline_step(model, local_fn(sp), tk, tg,
                                    pipe_axis="pipe")
            return loss, repack_fn(g)

        return jax.jit(shard_map(
            step, mesh=mesh, in_specs=(in_specs, P(), P()),
            out_specs=(P(), in_specs)))(packed, tokens, targets)

    def test_pp2_bitwise(self, rng):
        model = GPTModel(GPTConfig(fused_ffn=True, **_GPT_KW))
        params = model.init_params(jax.random.PRNGKey(7))
        M, mb, seq = 4, 2, 8
        tokens = jnp.asarray(rng.randint(0, 32, (M, mb, seq)))
        targets = jnp.asarray(rng.randint(0, 32, (M, mb, seq)))

        loss1, g1 = self._pp_run(model, params, tokens, targets, 1)
        loss2, g2 = self._pp_run(model, params, tokens, targets, 2)
        assert np.asarray(loss1).tobytes() == np.asarray(loss2).tobytes()
        # pp packs layers per stage; compare leaf bytes after sorting by
        # shape-erased flattening per key, stage dim first
        for k in ("embedding", "final_layernorm"):
            for a, b in zip(jax.tree_util.tree_leaves(g1[k]),
                            jax.tree_util.tree_leaves(g2[k]),
                            strict=True):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(g1["layers"]),
                        jax.tree_util.tree_leaves(g2["layers"]),
                        strict=True):
            a, b = np.asarray(a), np.asarray(b)
            np.testing.assert_array_equal(a.reshape(b.shape), b)

    def test_mpmd_dp2_pp2_bitwise(self, rng):
        from apex_tpu.mpmd import MpmdPipeline
        params = GPTModel(GPTConfig(**_GPT_KW)).init_params(
            jax.random.PRNGKey(9))
        plan = ParallelPlan(dp=2, pp=2, n_microbatches=2)
        tokens = jnp.asarray(rng.randint(0, 32, (8, 8)))
        targets = jnp.asarray(rng.randint(0, 32, (8, 8)))

        runs = []
        for fused in (False, True):
            kw = dict(_GPT_KW, fused_ffn=fused)
            eng = MpmdPipeline(kw, params, plan,
                               devices=jax.devices()[:4])
            runs.append(eng.loss_and_grads(tokens, targets, step=0))
        (l0, g0), (l1, g1) = runs
        assert np.float32(l0).tobytes() == np.float32(l1).tobytes()
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1), strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
