"""Fused optimizer tests vs unfused references.

Apex pattern (``tests/L0/run_optimizers/test_fused_optimizer.py``): run the
fused optimizer and a plain reference implementation step-by-step on the
same inputs and compare parameters at each step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from apex_tpu.optimizers import (FusedAdam, FusedSGD, FusedLAMB,
                                 FusedNovoGrad, FusedAdagrad)


def _packed(cls, **kw):
    """Construct with the packed multi_tensor engine.

    The ctor opt-in was removed after two bench rounds measured the
    packed single-chip step at 0.49-0.53x optax (``bucketed=True`` on a
    plain optimizer now raises); the engine survives only as the
    ZeRO/distributed optimizers' sharding unit.  The kernel tests below
    still pin it directly — by attribute, the same way the distributed
    mixin selects it."""
    opt = cls(**kw)
    opt.bucketed = True
    return opt


def make_params(rng, dtype=np.float32):
    return {
        "dense": {"kernel": jnp.asarray(rng.randn(17, 31).astype(dtype)),
                  "bias": jnp.asarray(rng.randn(31).astype(dtype))},
        "ln": {"scale": jnp.asarray(rng.rand(17).astype(dtype) + 0.5)},
    }


def make_grads(rng, params, scale=1.0):
    return jax.tree_util.tree_map(
        lambda p: jnp.asarray(
            rng.randn(*p.shape).astype(np.float32) * scale).astype(p.dtype),
        params)


def tree_allclose(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


class TestFusedAdam:
    def test_matches_optax_adamw(self, rng):
        lr, wd = 1e-2, 0.05
        params = make_params(rng)
        opt = _packed(FusedAdam, lr=lr, weight_decay=wd,
                        adam_w_mode=True)
        state = opt.init(params)
        ref = optax.adamw(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=wd)
        ref_params = params
        ref_state = ref.init(params)
        step = jax.jit(opt.step)
        for i in range(5):
            grads = make_grads(rng, params)
            params, state = step(grads, params, state)
            upd, ref_state = ref.update(grads, ref_state, ref_params)
            ref_params = optax.apply_updates(ref_params, upd)
            tree_allclose(params, ref_params, rtol=2e-5, atol=1e-6)

    def test_classic_adam_l2_mode(self, rng):
        # adam_w_mode=False folds decay into grads = optax.adam on g + wd*p
        lr, wd = 1e-2, 0.1
        params = make_params(rng)
        opt = _packed(FusedAdam, lr=lr, weight_decay=wd,
                        adam_w_mode=False)
        state = opt.init(params)
        ref = optax.adam(lr, b1=0.9, b2=0.999, eps=1e-8)
        ref_params, ref_state = params, ref.init(params)
        for i in range(3):
            grads = make_grads(rng, params)
            params, state = opt.step(grads, params, state)
            l2g = jax.tree_util.tree_map(lambda g, p: g + wd * p, grads,
                                         ref_params)
            upd, ref_state = ref.update(l2g, ref_state)
            ref_params = optax.apply_updates(ref_params, upd)
            tree_allclose(params, ref_params, rtol=2e-5, atol=1e-6)

    def test_noop_skips_step_and_count(self, rng):
        params = make_params(rng)
        opt = _packed(FusedAdam, lr=0.1)
        state = opt.init(params)
        grads = make_grads(rng, params)
        p1, s1 = opt.step(grads, params, state, noop_flag=1)
        tree_allclose(p1, params)
        assert int(s1["step"]) == 0
        p2, s2 = opt.step(grads, params, state, noop_flag=0)
        assert int(s2["step"]) == 1
        with np.testing.assert_raises(AssertionError):
            tree_allclose(p2, params)

    def test_grad_scale_fused_unscaling(self, rng):
        params = make_params(rng)
        opt = _packed(FusedAdam, lr=1e-2)
        state = opt.init(params)
        grads = make_grads(rng, params)
        scaled = jax.tree_util.tree_map(lambda g: g * 128.0, grads)
        p_a, _ = opt.step(grads, params, state)
        p_b, _ = opt.step(scaled, params, state, grad_scale=1.0 / 128.0)
        tree_allclose(p_a, p_b, rtol=1e-5)

    def test_master_weights_bf16(self, rng):
        params = make_params(rng, dtype=np.float32)
        bf16_params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params)
        opt = _packed(FusedAdam, lr=1e-3, master_weights=True)
        state = opt.init(bf16_params)
        # master copies exist for the bf16 bucket
        assert any("master" in b for b in state["buckets"].values())
        grads = make_grads(rng, bf16_params)
        p1, s1 = opt.step(grads, bf16_params, state)
        assert all(p.dtype == jnp.bfloat16
                   for p in jax.tree_util.tree_leaves(p1))
        # 100 tiny steps: master accumulates beyond bf16 resolution
        fp32_opt = _packed(FusedAdam, lr=1e-3)
        fp32_state = fp32_opt.init(params)
        fp32_p = params
        for _ in range(3):
            p1, s1 = opt.step(grads, p1, s1)
            fp32_p, fp32_state = fp32_opt.step(
                jax.tree_util.tree_map(lambda g: g.astype(jnp.float32),
                                       grads), fp32_p, fp32_state)
        tree_allclose(p1, fp32_p, rtol=2e-2, atol=2e-2)

    def test_param_groups_no_decay(self, rng):
        params = make_params(rng)
        no_decay = lambda path: "no_decay" if ("bias" in path or
                                               "scale" in path) else "default"
        opt = _packed(FusedAdam, lr=1e-2, weight_decay=0.5,
                        param_group_fn=no_decay,
                        param_groups={"no_decay": {"weight_decay": 0.0}})
        state = opt.init(params)
        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, params)
        p1, _ = opt.step(zero_grads, params, state)
        # decayed: kernel moved; un-decayed: bias/scale unchanged
        assert not np.allclose(p1["dense"]["kernel"],
                               params["dense"]["kernel"])
        np.testing.assert_allclose(p1["dense"]["bias"],
                                   params["dense"]["bias"], atol=1e-7)
        np.testing.assert_allclose(p1["ln"]["scale"], params["ln"]["scale"],
                                   atol=1e-7)

    def test_amsgrad_raises(self):
        with pytest.raises(RuntimeError):
            _packed(FusedAdam, amsgrad=True)

    def test_as_optax(self, rng):
        params = make_params(rng)
        tx = _packed(FusedAdam, lr=1e-2).as_optax()
        state = tx.init(params)
        grads = make_grads(rng, params)
        upd, state = tx.update(grads, state, params)
        new_p = optax.apply_updates(params, upd)
        ref_p, _ = _packed(FusedAdam, lr=1e-2).step(
            grads, params, _packed(FusedAdam, lr=1e-2).init(params))
        tree_allclose(new_p, ref_p, rtol=1e-5)


class TestFusedSGD:
    def test_matches_optax_sgd_momentum(self, rng):
        lr, mu = 0.1, 0.9
        params = make_params(rng)
        opt = _packed(FusedSGD, lr=lr, momentum=mu)
        state = opt.init(params)
        ref = optax.sgd(lr, momentum=mu, nesterov=False)
        ref_params, ref_state = params, ref.init(params)
        for _ in range(4):
            grads = make_grads(rng, params)
            params, state = opt.step(grads, params, state)
            upd, ref_state = ref.update(grads, ref_state)
            ref_params = optax.apply_updates(ref_params, upd)
            tree_allclose(params, ref_params, rtol=1e-5)

    def test_nesterov(self, rng):
        lr, mu = 0.05, 0.9
        params = make_params(rng)
        opt = _packed(FusedSGD, lr=lr, momentum=mu, nesterov=True)
        state = opt.init(params)
        ref = optax.sgd(lr, momentum=mu, nesterov=True)
        ref_params, ref_state = params, ref.init(params)
        for _ in range(4):
            grads = make_grads(rng, params)
            params, state = opt.step(grads, params, state)
            upd, ref_state = ref.update(grads, ref_state)
            ref_params = optax.apply_updates(ref_params, upd)
            tree_allclose(params, ref_params, rtol=1e-5)

    def test_weight_decay(self, rng):
        params = make_params(rng)
        opt = _packed(FusedSGD, lr=0.1, weight_decay=0.01)
        state = opt.init(params)
        grads = make_grads(rng, params)
        p1, _ = opt.step(grads, params, state)
        ref = jax.tree_util.tree_map(
            lambda p, g: p - 0.1 * (g + 0.01 * p), params, grads)
        tree_allclose(p1, ref, rtol=1e-5)


def _lamb_reference(params, grads, m, v, step, lr, b1, b2, eps, wd,
                    max_grad_norm=1.0):
    """Plain numpy LAMB (adamw mode, grad averaging, bias correction)."""
    leaves_p = jax.tree_util.tree_leaves(params)
    leaves_g = jax.tree_util.tree_leaves(grads)
    gnorm = np.sqrt(sum(float(np.sum(np.asarray(g) ** 2))
                        for g in leaves_g))
    clip = max_grad_norm / gnorm if gnorm > max_grad_norm else 1.0
    new_p, new_m, new_v = [], [], []
    for p, g, mi, vi in zip(leaves_p, leaves_g, m, v):
        p, g = np.asarray(p, np.float64), np.asarray(g, np.float64) * clip
        mi = b1 * mi + (1 - b1) * g
        vi = b2 * vi + (1 - b2) * g * g
        u = (mi / (1 - b1 ** step)) / \
            (np.sqrt(vi / (1 - b2 ** step)) + eps) + wd * p
        pn, un = np.linalg.norm(p), np.linalg.norm(u)
        ratio = pn / un if (pn > 0 and un > 0) else 1.0
        new_p.append(p - lr * ratio * u)
        new_m.append(mi)
        new_v.append(vi)
    return new_p, new_m, new_v


class TestFusedLAMB:
    def test_matches_reference(self, rng):
        lr, wd = 1e-2, 0.01
        params = make_params(rng)
        opt = _packed(FusedLAMB, lr=lr, weight_decay=wd)
        state = opt.init(params)
        leaves = jax.tree_util.tree_leaves(params)
        ref_p = [np.asarray(p, np.float64) for p in leaves]
        ref_m = [np.zeros_like(p) for p in ref_p]
        ref_v = [np.zeros_like(p) for p in ref_p]
        for t in range(1, 4):
            grads = make_grads(rng, params)
            params, state = opt.step(grads, params, state)
            ref_p, ref_m, ref_v = _lamb_reference(
                jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(params), ref_p),
                grads, ref_m, ref_v, t, lr, 0.9, 0.999, 1e-6, wd)
            for a, b in zip(jax.tree_util.tree_leaves(params), ref_p):
                np.testing.assert_allclose(np.asarray(a), b, rtol=3e-4,
                                           atol=1e-6)

    def test_grad_clipping_engages(self, rng):
        params = make_params(rng)
        opt = _packed(FusedLAMB, lr=1e-2, max_grad_norm=0.5)
        state = opt.init(params)
        big_grads = make_grads(rng, params, scale=100.0)
        p1, _ = opt.step(big_grads, params, state)
        # params should move a bounded amount despite huge grads
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(params)):
            assert float(jnp.max(jnp.abs(a - b))) < 1.0


class TestFusedMixedPrecisionLamb:
    """apex ``fused_mixed_precision_lamb.py``: LAMB over low-precision
    model params with fp32 master copies (the BERT O2 recipe optimizer)."""

    def test_master_copy_exists_and_tracks_fp32_lamb(self, rng):
        from apex_tpu.optimizers import FusedLAMB, FusedMixedPrecisionLamb

        params = make_params(rng, dtype=np.float32)
        bf16_params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params)
        opt = FusedMixedPrecisionLamb(lr=1e-2,
                                      reduced_precision_dtype=jnp.bfloat16)
        state = opt.init(bf16_params)
        assert any("master" in b for b in state["buckets"].values())

        ref_opt = _packed(FusedLAMB, lr=1e-2)
        ref_state = ref_opt.init(params)
        grads = make_grads(rng, bf16_params)
        f32_grads = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), grads)
        p, s, rp, rs = bf16_params, state, params, ref_state
        for _ in range(3):
            p, s = opt.step(grads, p, s)
            rp, rs = ref_opt.step(f32_grads, rp, rs)
        assert all(x.dtype == jnp.bfloat16
                   for x in jax.tree_util.tree_leaves(p))
        tree_allclose(p, rp, rtol=2e-2, atol=2e-2)

    def test_noop_flag_freezes_master(self, rng):
        from apex_tpu.optimizers import FusedMixedPrecisionLamb

        params = make_params(rng, dtype=np.float32)
        bf16_params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params)
        opt = FusedMixedPrecisionLamb(lr=1e-2)
        state = opt.init(bf16_params)
        grads = make_grads(rng, bf16_params)
        p1, s1 = opt.step(grads, bf16_params, state,
                          noop_flag=jnp.ones((), jnp.int32))
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(bf16_params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert int(s1["step"]) == 0


class TestFusedNovoGradAdagrad:
    def test_novograd_first_step(self, rng):
        params = make_params(rng)
        opt = _packed(FusedNovoGrad, lr=0.1, bias_correction=False,
                            grad_averaging=False, weight_decay=0.0)
        state = opt.init(params)
        grads = make_grads(rng, params)
        p1, s1 = opt.step(grads, params, state)
        # step 1: v = ||g||² per tensor, m = g/||g||, p -= lr*m
        for (a, p, g) in zip(jax.tree_util.tree_leaves(p1),
                             jax.tree_util.tree_leaves(params),
                             jax.tree_util.tree_leaves(grads)):
            gn = float(jnp.linalg.norm(g))
            ref = np.asarray(p) - 0.1 * np.asarray(g) / (gn + 1e-8)
            np.testing.assert_allclose(np.asarray(a), ref, rtol=1e-4,
                                       atol=1e-6)

    def test_adagrad_matches_optax(self, rng):
        params = make_params(rng)
        opt = _packed(FusedAdagrad, lr=0.1, eps=1e-10)
        state = opt.init(params)
        ref = optax.adagrad(0.1, initial_accumulator_value=0.0, eps=1e-10)
        ref_params, ref_state = params, ref.init(params)
        for _ in range(3):
            grads = make_grads(rng, params)
            params, state = opt.step(grads, params, state)
            upd, ref_state = ref.update(grads, ref_state)
            ref_params = optax.apply_updates(ref_params, upd)
            tree_allclose(params, ref_params, rtol=1e-4, atol=1e-6)


class TestMasterParams:
    """apex amp.master_params: extract the fp32 master copies."""

    def test_masters_match_fp32_trajectory(self, rng):
        from apex_tpu import amp

        params = make_params(rng, dtype=np.float32)
        bf16 = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params)
        opt = _packed(FusedAdam, lr=1e-3, master_weights=True)
        state = opt.init(bf16)
        grads = make_grads(rng, bf16)
        p, s = opt.step(grads, bf16, state)
        masters = amp.master_params(opt, p, s)
        for m, mp in zip(jax.tree_util.tree_leaves(masters),
                         jax.tree_util.tree_leaves(p)):
            assert m.dtype == jnp.float32
            # model params are the bf16 round-trip of the masters
            np.testing.assert_array_equal(
                np.asarray(m.astype(jnp.bfloat16)), np.asarray(mp))

    def test_fp32_params_pass_through(self, rng):
        from apex_tpu import amp

        params = make_params(rng, dtype=np.float32)
        opt = _packed(FusedAdam, lr=1e-3)
        state = opt.init(params)
        masters = amp.master_params(opt, params, state)
        for m, p in zip(jax.tree_util.tree_leaves(masters),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_array_equal(np.asarray(m), np.asarray(p))


class TestPerLeafLayout:
    """bucketed=False: the per-leaf layout must walk the SAME trajectory
    as the packed engine (identical _*_math single-source updates), for
    every optimizer family, including masters, noop and param groups."""

    OPTS = [
        (FusedAdam, dict(lr=1e-2, weight_decay=0.05)),
        (FusedAdam, dict(lr=1e-2, weight_decay=0.1, adam_w_mode=False,
                         bias_correction=False)),
        (FusedSGD, dict(lr=1e-2, momentum=0.9, weight_decay=0.01)),
        (FusedLAMB, dict(lr=1e-2, weight_decay=0.01)),
        (FusedLAMB, dict(lr=1e-2, use_nvlamb=True, grad_averaging=False)),
        (FusedNovoGrad, dict(lr=1e-2, weight_decay=0.01)),
        (FusedAdagrad, dict(lr=1e-2, weight_decay=0.01)),
        (FusedAdagrad, dict(lr=1e-2, weight_decay=0.01,
                            adagrad_w_mode=True)),
    ]

    @pytest.mark.parametrize("cls,kw", OPTS,
                             ids=lambda o: getattr(o, "__name__", None))
    def test_matches_packed_trajectory(self, rng, cls, kw):
        params = make_params(rng)
        packed = _packed(cls, **kw)
        leaf = cls(bucketed=False, **kw)
        ps, ss = params, packed.init(params)
        pl_, sl = params, leaf.init(params)
        pstep, lstep = jax.jit(packed.step), jax.jit(leaf.step)
        for _ in range(4):
            grads = make_grads(rng, params)
            ps, ss = pstep(grads, ps, ss)
            pl_, sl = lstep(grads, pl_, sl)
            tree_allclose(ps, pl_, rtol=1e-6, atol=1e-7)
        assert int(sl["step"]) == 4

    def test_master_weights_and_noop(self, rng):
        params32 = make_params(rng)
        bf16 = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params32)
        packed = _packed(FusedLAMB, lr=1e-2, master_weights=True)
        leaf = FusedLAMB(lr=1e-2, master_weights=True, bucketed=False)
        ps, ss = bf16, packed.init(bf16)
        pl_, sl = bf16, leaf.init(bf16)
        for i in range(3):
            grads = make_grads(rng, bf16)
            noop = jnp.asarray(1 if i == 1 else 0)  # skip the middle step
            ps, ss = packed.step(grads, ps, ss, noop_flag=noop)
            pl_, sl = leaf.step(grads, pl_, sl, noop_flag=noop)
            tree_allclose(ps, pl_, rtol=1e-6, atol=1e-7)
        assert int(ss["step"]) == int(sl["step"]) == 2
        # per-leaf masters are leaf-shaped fp32
        from apex_tpu import amp
        m = amp.master_params(leaf, pl_, sl)
        for lm, lp in zip(jax.tree_util.tree_leaves(m),
                          jax.tree_util.tree_leaves(pl_)):
            assert lm.dtype == jnp.float32 and lm.shape == lp.shape

    def test_param_groups(self, rng):
        params = make_params(rng)
        group_fn = lambda path: ("no_decay" if "bias" in path or "scale"
                                 in path else "default")
        kw = dict(lr=1e-2, weight_decay=0.1, param_group_fn=group_fn,
                  param_groups={"no_decay": {"weight_decay": 0.0}})
        packed = _packed(FusedAdam, **kw)
        leaf = FusedAdam(bucketed=False, **kw)
        ps, ss = params, packed.init(params)
        pl_, sl = params, leaf.init(params)
        for _ in range(3):
            grads = make_grads(rng, params)
            ps, ss = packed.step(grads, ps, ss)
            pl_, sl = leaf.step(grads, pl_, sl)
        tree_allclose(ps, pl_, rtol=1e-6, atol=1e-7)

    def test_zero_requires_bucketed(self):
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        with pytest.raises(ValueError, match="bucketed"):
            DistributedFusedAdam(lr=1e-3, world_size=2, axis_name="data",
                                 bucketed=False)

    def test_default_layout_per_leaf_and_packed_raises(self):
        """Post-BENCH_r05 layouts: plain optimizers are per-leaf-only
        (packed measured ~2x slower on a single chip, both rounds); the
        ZeRO subclasses keep bucketed (their sharding unit); an explicit
        packed request on a plain optimizer is rejected outright."""
        from apex_tpu.contrib.optimizers import DistributedFusedAdam
        assert FusedAdam(lr=1e-3).bucketed is False
        assert DistributedFusedAdam(lr=1e-3, world_size=2,
                                    axis_name="data").bucketed is True
        with pytest.raises(ValueError, match="per-leaf"):
            FusedAdam(lr=1e-3, bucketed=True)

    def test_grad_scale_parity(self, rng):
        """amp's fused unscaling (grad_scale=1/loss_scale) must walk the
        same trajectory in both layouts AND match stepping on pre-divided
        grads — LAMB is the interesting case because grad_scale also
        enters the global-norm clip (the third arm catches a shared-code
        bug that drops/double-applies grad_scale in both layouts)."""
        params = make_params(rng)
        packed = _packed(FusedLAMB, lr=1e-2)
        leaf = FusedLAMB(lr=1e-2, bucketed=False)
        unscaled = FusedLAMB(lr=1e-2, bucketed=False)
        ps, ss = params, packed.init(params)
        pl_, sl = params, leaf.init(params)
        pu, su = params, unscaled.init(params)
        for _ in range(3):
            grads = make_grads(rng, params, scale=128.0)  # "scaled" grads
            pre = jax.tree_util.tree_map(lambda g: g / 128.0, grads)
            ps, ss = packed.step(grads, ps, ss, grad_scale=1 / 128.0)
            pl_, sl = leaf.step(grads, pl_, sl, grad_scale=1 / 128.0)
            pu, su = unscaled.step(pre, pu, su)
            tree_allclose(ps, pl_, rtol=1e-6, atol=1e-7)
            tree_allclose(pl_, pu, rtol=1e-5, atol=1e-7)
