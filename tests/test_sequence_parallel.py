"""Sequence parallelism + chunked overlap rings vs the replicated TP path.

Megatron SP (the ISSUE 3 tentpole): activations between TP regions stay
sequence-sharded (LayerNorm/dropout/residual on ``(b, s/t, h)``), the
column edge all-gathers along seq and the row edge reduce-scatters; the
``overlap_chunks`` knob replaces each gather→GEMM / GEMM→reduce-scatter
pair with a ``ppermute`` ring whose custom VJP rings the backward too.

Gradient references are the SERIAL model, not the replicated-TP
shard_map run: on this JAX generation the cotangents of replicated
(``P()``) leaves come back as per-device partials from a shard_map body
(no automatic psum of invariant grads), so replicated-TP grads-in-body
are themselves unreliable — the SP path carries explicit
identity-fwd/psum-bwd syncs on the sequence-region LN/bias params
(Megatron's SP grad allreduce) and matches the serial model exactly.
Forward losses ARE compared bitwise against the replicated TP run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import GPTConfig, GPTModel, pack_for_shard_map
from apex_tpu.transformer.tensor_parallel import mappings as M
from apex_tpu.utils.collectives import shard_map_compat as shard_map


def tiny_cfg(**kw):
    base = dict(vocab_size=32, hidden_size=16, num_layers=2,
                num_attention_heads=4, max_seq_len=8)
    base.update(kw)
    return GPTConfig(**base)


def make_data(rng, cfg, batch, seq):
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    return tokens, targets


def tree_allclose(a, b, rtol=1e-5, atol=1e-6):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


# -- sequence mappings --------------------------------------------------------

class TestSequenceMappings:
    def test_scatter_gather_round_trip(self, rng):
        x = jnp.asarray(rng.randn(2, 8, 6).astype(np.float32))
        mesh = jax.make_mesh((4,), ("model",))

        def body(x):
            s = M.scatter_to_sequence_parallel_region(x, "model", 1)
            assert s.shape == (2, 2, 6)
            return M.gather_from_sequence_parallel_region(s, "model", 1)

        y = jax.jit(shard_map(body, mesh=mesh, in_specs=(P(),),
                              out_specs=P()))(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))

    def test_gather_bwd_is_reduce_scatter(self, rng):
        """d(sum over devices of <gather(x), c_dev>)/dx = seq shard of the
        summed cotangents — the reduce-scatter pairing."""
        t = 4
        x = jnp.asarray(rng.randn(t, 2, 6).astype(np.float32))
        c = jnp.asarray(rng.randn(t, t * 2, 6).astype(np.float32))
        mesh = jax.make_mesh((t,), ("model",))

        def body(x, c):
            x, c = x[0], c[0]
            f = lambda x: jnp.sum(
                M.gather_from_sequence_parallel_region(x, "model", 0) * c)
            return jax.grad(f)(x)[None]

        g = jax.jit(shard_map(body, mesh=mesh,
                              in_specs=(P("model"), P("model")),
                              out_specs=P("model")))(x, c)
        ref = np.sum(np.asarray(c), axis=0).reshape(t, 2, 6)
        np.testing.assert_allclose(np.asarray(g), ref, rtol=1e-6,
                                   atol=1e-6)


# -- overlap rings vs monolithic GEMM+collective ------------------------------

class TestOverlapRings:
    """Ring forms must match the (collective, GEMM) pair they replace —
    forward and backward, at every chunk count."""

    @pytest.mark.parametrize("t", [2, 4])
    @pytest.mark.parametrize("chunks", [1, 2])
    def test_column_ring_fwd_bitwise(self, rng, t, chunks):
        x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
        w = jnp.asarray(rng.randn(24, 16).astype(np.float32))
        ref = np.asarray(x @ w.T)      # (2, 8, 24)
        mesh = jax.make_mesh((t,), ("model",))

        y = jax.jit(shard_map(
            lambda xs, ws: M.column_parallel_linear_overlap(
                xs, ws, "model", 1, chunks),
            mesh=mesh, in_specs=(P(None, "model"), P("model")),
            out_specs=P(None, None, "model")))(x, w)
        # each ring step writes gather-shard @ W_local verbatim — the
        # decomposition reorders no contraction, so f32 is bitwise
        np.testing.assert_array_equal(np.asarray(y), ref)

    @pytest.mark.parametrize("t", [2, 4])
    @pytest.mark.parametrize("chunks", [1, 2])
    def test_column_ring_bwd(self, rng, t, chunks):
        x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
        w = jnp.asarray(rng.randn(24, 16).astype(np.float32))
        c = jnp.asarray(rng.randn(2, 8, 24).astype(np.float32))
        mesh = jax.make_mesh((t,), ("model",))

        def body(xs, ws, cs):
            f = lambda xs, ws: jnp.sum(
                M.column_parallel_linear_overlap(xs, ws, "model", 1,
                                                 chunks) * cs)
            return jax.grad(f, argnums=(0, 1))(xs, ws)

        dx, dw = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "model"), P("model"),
                      P(None, None, "model")),
            out_specs=(P(None, "model"), P("model"))))(x, w, c)
        ref_dx, ref_dw = jax.grad(
            lambda x, w: jnp.sum((x @ w.T) * c), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(ref_dw),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("t", [2, 4])
    @pytest.mark.parametrize("chunks", [1, 2])
    def test_row_ring_fwd(self, rng, t, chunks):
        x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
        w = jnp.asarray(rng.randn(24, 16).astype(np.float32))
        ref = np.asarray(x @ w.T)
        mesh = jax.make_mesh((t,), ("model",))

        y = jax.jit(shard_map(
            lambda xs, ws: M.row_parallel_linear_overlap(
                xs, ws, "model", 1, chunks),
            mesh=mesh, in_specs=(P(None, None, "model"),
                                 P(None, "model")),
            out_specs=P(None, "model")))(x, w)
        # cross-device partials sum in ring order — epsilon, not bitwise
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5,
                                   atol=1e-5)

    @pytest.mark.parametrize("t", [2, 4])
    @pytest.mark.parametrize("chunks", [1, 2])
    def test_row_ring_bwd(self, rng, t, chunks):
        x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
        w = jnp.asarray(rng.randn(24, 16).astype(np.float32))
        c = jnp.asarray(rng.randn(2, 8, 24).astype(np.float32))
        mesh = jax.make_mesh((t,), ("model",))

        def body(xs, ws, cs):
            f = lambda xs, ws: jnp.sum(
                M.row_parallel_linear_overlap(xs, ws, "model", 1,
                                              chunks) * cs)
            return jax.grad(f, argnums=(0, 1))(xs, ws)

        dx, dw = jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(None, None, "model"), P(None, "model"),
                      P(None, "model")),
            out_specs=(P(None, None, "model"), P(None, "model"))))(x, w, c)
        ref_dx, ref_dw = jax.grad(
            lambda x, w: jnp.sum((x @ w.T) * c), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(ref_dx),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(ref_dw),
                                   rtol=1e-5, atol=1e-5)


# -- GPT end-to-end -----------------------------------------------------------

def _run_gpt_tp(par, params, tokens, targets):
    t = par.cfg.tensor_parallel_size
    mesh = jax.make_mesh((t,), ("model",))
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(par, params)

    def step(sp, tokens, targets):
        loss, g = jax.value_and_grad(par.loss)(local_fn(sp), tokens,
                                               targets)
        return loss, repack_fn(g)

    loss, grads = jax.jit(shard_map(
        step, mesh=mesh, in_specs=(in_specs, P(), P()),
        out_specs=(P(), in_specs)))(packed, tokens, targets)
    return loss, grads


class TestGPTSequenceParallel:
    @pytest.mark.parametrize("t", [2, 4])
    @pytest.mark.parametrize("chunks", [0, 2])
    def test_sp_matches_serial_and_replicated(self, rng, t, chunks):
        """Forward loss: SP == replicated TP bitwise (f32).  Grads: SP ==
        serial (see module docstring for why serial is the reference)."""
        cfg_s = tiny_cfg()
        serial = GPTModel(cfg_s)
        params = serial.init_params(jax.random.PRNGKey(1))
        tokens, targets = make_data(rng, cfg_s, 2, 8)
        ref_loss = float(jax.jit(serial.loss)(params, tokens, targets))
        ref_grads = jax.jit(jax.grad(serial.loss))(params, tokens,
                                                   targets)

        rep = GPTModel(tiny_cfg(tensor_parallel_size=t,
                                axis_name="model"))
        rep_loss, _ = _run_gpt_tp(rep, params, tokens, targets)

        par = GPTModel(tiny_cfg(tensor_parallel_size=t, axis_name="model",
                                sequence_parallel=True,
                                overlap_chunks=chunks))
        sp_loss, sp_grads = _run_gpt_tp(par, params, tokens, targets)

        if chunks == 0:
            # monolithic SP reorders no contraction vs replicated TP
            assert float(sp_loss) == float(rep_loss) == ref_loss
        else:
            np.testing.assert_allclose(float(sp_loss), ref_loss,
                                       rtol=1e-6)
        ref_packed, _, _, _ = pack_for_shard_map(par, ref_grads)
        tree_allclose(sp_grads, ref_packed, rtol=5e-4, atol=1e-5)

    def test_sp_remat_compat(self, rng):
        """remat=True + sequence_parallel=True: the seq-sharded residual
        stream must checkpoint/replay cleanly through the rings."""
        cfg_s = tiny_cfg()
        serial = GPTModel(cfg_s)
        params = serial.init_params(jax.random.PRNGKey(2))
        tokens, targets = make_data(rng, cfg_s, 2, 8)
        ref_loss = float(jax.jit(serial.loss)(params, tokens, targets))
        ref_grads = jax.jit(jax.grad(serial.loss))(params, tokens,
                                                   targets)

        par = GPTModel(tiny_cfg(tensor_parallel_size=2, axis_name="model",
                                sequence_parallel=True, overlap_chunks=2,
                                remat=True))
        loss, grads = _run_gpt_tp(par, params, tokens, targets)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-6)
        ref_packed, _, _, _ = pack_for_shard_map(par, ref_grads)
        tree_allclose(grads, ref_packed, rtol=5e-4, atol=1e-5)

    def test_sp_bf16_allclose(self, rng):
        """bf16 activations: SP vs replicated forward within bf16 noise
        (collective orders differ, so not bitwise in half precision)."""
        kw = dict(tensor_parallel_size=2, axis_name="model",
                  dtype=jnp.bfloat16)
        params = GPTModel(tiny_cfg()).init_params(jax.random.PRNGKey(3))
        tokens, targets = make_data(rng, tiny_cfg(), 2, 8)
        rep_loss, _ = _run_gpt_tp(GPTModel(tiny_cfg(**kw)), params,
                                  tokens, targets)
        sp_loss, _ = _run_gpt_tp(
            GPTModel(tiny_cfg(sequence_parallel=True, overlap_chunks=2,
                              **kw)), params, tokens, targets)
        np.testing.assert_allclose(float(sp_loss), float(rep_loss),
                                   rtol=2e-2, atol=2e-2)

    def test_seq_len_must_divide(self, rng):
        par = GPTModel(tiny_cfg(tensor_parallel_size=4, axis_name="model",
                                sequence_parallel=True))
        params = GPTModel(tiny_cfg()).init_params(jax.random.PRNGKey(4))
        tokens, targets = make_data(rng, tiny_cfg(), 2, 6)  # 6 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            _run_gpt_tp(par, params, tokens, targets)


# -- BERT end-to-end ----------------------------------------------------------

class TestBertSequenceParallel:
    @pytest.mark.parametrize("chunks", [0, 2])
    def test_sp_matches_serial(self, rng, chunks):
        from apex_tpu.models.bert import BertConfig, BertModel

        def mk(**kw):
            base = dict(vocab_size=64, hidden_size=16, num_layers=2,
                        num_attention_heads=4, ffn_hidden_size=32,
                        max_seq_len=16)
            base.update(kw)
            return BertModel(BertConfig(**base))

        serial = mk()
        params = serial.init_params(jax.random.PRNGKey(5))
        tokens = jnp.asarray(rng.randint(0, 64, (2, 16)))
        mask = rng.rand(2, 16) < 0.3
        labels = jnp.asarray(np.where(mask, np.asarray(tokens), -1))
        ref_loss = float(jax.jit(serial.loss)(params, tokens, labels))
        ref_grads = jax.jit(jax.grad(serial.loss))(params, tokens, labels)

        par = mk(tensor_parallel_size=2, axis_name="model",
                 sequence_parallel=True, overlap_chunks=chunks)
        mesh = jax.make_mesh((2,), ("model",))
        specs = par.partition_specs()
        loss, grads = jax.jit(shard_map(
            jax.value_and_grad(par.loss), mesh=mesh,
            in_specs=(specs, P(), P()),
            out_specs=(P(), specs)))(params, tokens, labels)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-6)
        tree_allclose(grads, ref_grads, rtol=5e-4, atol=1e-5)


# -- config validation --------------------------------------------------------

class TestConfigValidation:
    def test_overlap_chunks_requires_sp(self):
        with pytest.raises(ValueError, match="sequence_parallel"):
            tiny_cfg(tensor_parallel_size=2, axis_name="model",
                     overlap_chunks=2)

    def test_sp_excludes_context_parallel(self):
        with pytest.raises(ValueError, match="context"):
            tiny_cfg(tensor_parallel_size=2, axis_name="model",
                     sequence_parallel=True, context_axis="context")

    def test_sp_excludes_moe(self):
        with pytest.raises(ValueError, match="MoE"):
            tiny_cfg(tensor_parallel_size=2, axis_name="model",
                     sequence_parallel=True, n_experts=2,
                     expert_axis=None)

    def test_layer_overlap_requires_sp(self):
        from apex_tpu.transformer import tensor_parallel as tp
        with pytest.raises(RuntimeError, match="sequence_parallel"):
            tp.ColumnParallelLinear(16, 32, gather_output=False,
                                    world_size=2, axis_name="model",
                                    overlap_chunks=2)
        with pytest.raises(RuntimeError, match="sequence_parallel"):
            tp.RowParallelLinear(32, 16, input_is_parallel=True,
                                 world_size=2, axis_name="model",
                                 overlap_chunks=2)

    def test_decode_rejects_sp(self, rng):
        cfg = tiny_cfg(tensor_parallel_size=2, axis_name="model",
                       sequence_parallel=True)
        model = GPTModel(cfg)
        params = GPTModel(tiny_cfg()).init_params(jax.random.PRNGKey(6))
        tokens = jnp.asarray(rng.randint(0, 32, (1, 8)))
        with pytest.raises(ValueError, match="sequence_parallel"):
            model.prefill(params, tokens)
