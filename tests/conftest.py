"""Test configuration: run everything on a fake 8-device CPU mesh.

Apex's distributed tests spawn one process per GPU
(``apex/transformer/testing/distributed_test_base.py``) and skip without
hardware.  The TPU rebuild does better: XLA can emulate N devices on CPU, so
every TP/PP/DP test runs hardware-free in one process.  These env vars must
be set before JAX initializes, hence at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override axon/TPU: tests are CPU-only
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin force-registers itself (jax_platforms becomes
# "axon,cpu" regardless of the env var) — override after import.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
assert jax.default_backend() == "cpu"

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import numpy as np
    return np.random.RandomState(1234)
