"""Test configuration: two lanes.

* Default lane — everything on a fake 8-device CPU mesh.  Apex's
  distributed tests spawn one process per GPU
  (``apex/transformer/testing/distributed_test_base.py``) and skip without
  hardware; XLA can emulate N devices on CPU, so every TP/PP/DP test runs
  hardware-free in one process.  These env vars must be set before JAX
  initializes, hence at conftest import time.
* On-chip lane — ``APEX_TPU_ON_CHIP=1 pytest -m tpu`` leaves the real TPU
  backend in place and runs the hardware-marked tests (Pallas kernel
  parity, amp x Pallas composition, train-step smoke) where the kernels
  actually run.  The reference runs every kernel test on real hardware;
  this is the equivalent gate (CPU interpret mode does not enforce TPU
  tiling/VMEM limits).
"""

import os

ON_CHIP = os.environ.get("APEX_TPU_ON_CHIP") == "1"

if not ON_CHIP:
    os.environ["JAX_PLATFORMS"] = "cpu"  # override axon/TPU
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    # Persistent XLA compilation cache: the CI host has one CPU core and
    # the suite is compile-bound, so warm reruns of the tier-1 command
    # drop well under its time budget.  Env vars (not config.update) so
    # the example-script subprocesses in test_examples.py inherit it.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.abspath(os.path.join(os.path.dirname(__file__),
                                     os.pardir, ".jax_cache")))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")

import jax  # noqa: E402

if not ON_CHIP:
    # The axon TPU plugin force-registers itself (jax_platforms becomes
    # "axon,cpu" regardless of the env var) — override after import.
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", False)
    assert jax.default_backend() == "cpu"

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: requires the real TPU chip "
                   "(run via APEX_TPU_ON_CHIP=1 pytest -m tpu)")


def pytest_collection_modifyitems(config, items):
    skip_tpu = pytest.mark.skip(
        reason="on-chip lane only (APEX_TPU_ON_CHIP=1 pytest -m tpu)")
    for item in items:
        if "tpu" in item.keywords and not ON_CHIP:
            item.add_marker(skip_tpu)


@pytest.fixture
def rng():
    import numpy as np
    return np.random.RandomState(1234)
