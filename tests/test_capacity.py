"""apex_tpu.resilience.capacity: burn-driven train<->serve shifting.

The controller's correctness contract:

* hysteresis: burn oscillating strictly inside ``(burn_low,
  burn_high)`` NEVER shifts, no matter how long; burn AT the band edge
  counts toward the confirm streak (>= / <= semantics); a broken
  streak resets the count;
* cooldown: no shift starts within ``cooldown_s`` of the previous
  commit OR rollback; :meth:`CapacityController.audit` proves both
  properties over the full shift history;
* one shift at a time: requests made mid-shift queue and run after —
  the shift log never interleaves;
* every injected failure mode (mid-shift crash, stuck drain, failed
  re-shard) rolls the split back to the prior one exactly — and, with
  a real :class:`ElasticTrainer` underneath, restores the trainer's
  params and optimizer slots BITWISE;
* appending ``capacity_change`` to the fault-kind tuples changed no
  pre-existing ``from_seed`` schedule (rate-0 kinds consume no rng
  stream state) — the determinism promise both docstrings make.

The full day-in-the-life proof (diurnal traffic, preemptions, guard
rollbacks, mid-shift faults, exactly-once + bitwise gates) lives in
``tools/day_in_life.py`` / ``__graft_entry__._dryrun_capacity``.
"""

import dataclasses
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.resilience import (CAPACITY_FAULT_MODES, CapacityBudget,
                                 CapacityController, ElasticComponents,
                                 ElasticPlan, ElasticTrainer, Fault,
                                 FaultInjector, GuardedTrainStep,
                                 TopologySpec, fault_mode)
from apex_tpu.resilience.faults import FAULT_KINDS, seeded_schedule
from apex_tpu.serving import (SERVING_FAULT_KINDS, ServingFault,
                              ServingFaultInjector)


# -- fakes: the controller only needs the trainer/fleet surface --------------


class FakeSLO:
    def __init__(self, owner):
        self.owner = owner
        self.targets = [SimpleNamespace(name="ttft")]
        self.resets = []

    def burn_rate(self, target, window_s):
        return self.owner.burn

    def reset_windows(self, epoch=None):
        self.resets.append(epoch)


class FakeEngine:
    def __init__(self, burn=0.0):
        self.burn = burn
        self.metrics = SimpleNamespace(slo=FakeSLO(self))


class FakeFleet:
    def __init__(self, n=2, clock=lambda: 0.0):
        self.clock = clock
        self.replicas = [FakeEngine() for _ in range(n)]
        self.draining = set()
        self.drain_done = True       # tests flip this for slow drains

    def _live(self):
        return [(i, e) for i, e in enumerate(self.replicas)
                if e is not None]

    def add_replica(self, engine):
        for j, e in enumerate(self.replicas):
            if e is None:
                self.replicas[j] = engine
                return j
        self.replicas.append(engine)
        return len(self.replicas) - 1

    def begin_drain(self, i):
        if self.replicas[i] is None:
            raise ValueError(f"replica {i} was removed")
        self.draining.add(i)

    def cancel_drain(self, i):
        self.draining.discard(i)

    def drained(self, i):
        return self.drain_done

    def remove_replica(self, i):
        eng = self.replicas[i]
        self.replicas[i] = None
        self.draining.discard(i)
        return eng

    def set_burn(self, burn):
        for _, e in self._live():
            e.burn = burn


class FakeTrainer:
    def __init__(self, dp=4):
        self.plan = SimpleNamespace(spec=TopologySpec(dp=dp))
        self.stats = {"last_checkpoint_s": 0.0, "last_reshard_s": 0.0}
        self.current_step = 0
        self.replans = []

    def replan_to(self, spec, *, checkpoint_first=True):
        self.replans.append(spec.dp)
        self.plan = SimpleNamespace(spec=spec)


def make_controller(clockv=None, *, dp=4, fleet=None, trainer=None, **kw):
    clockv = clockv if clockv is not None else [0.0]
    clock = lambda: clockv[0]                                # noqa: E731
    fleet = fleet if fleet is not None else FakeFleet(clock=clock)
    trainer = trainer if trainer is not None else FakeTrainer(dp=dp)
    kw.setdefault("min_train_dp", 2)
    kw.setdefault("burn_high", 6.0)
    kw.setdefault("burn_low", 1.0)
    kw.setdefault("confirm_ticks", 3)
    kw.setdefault("cooldown_s", 10.0)
    ctl = CapacityController(trainer, fleet, FakeEngine, clock=clock,
                             **kw)
    return ctl, trainer, fleet, clockv


# -- basics ------------------------------------------------------------------


def test_fault_mode_mapping():
    assert fault_mode(0) == "mid_shift_crash"
    assert fault_mode(1) == "mid_shift_crash"
    assert fault_mode(2) == "stuck_drain"
    assert fault_mode(3) == "failed_reshard"
    assert fault_mode(99) == "mid_shift_crash"
    assert set(CAPACITY_FAULT_MODES) == {
        "mid_shift_crash", "stuck_drain", "failed_reshard"}


def test_budget_validates_split():
    CapacityBudget(6, 4, 2)
    with pytest.raises(ValueError):
        CapacityBudget(6, 4, 3)
    with pytest.raises(ValueError):
        CapacityBudget(6, 4, 2, chips_per_replica=0)


def test_controller_rejects_inverted_band():
    with pytest.raises(ValueError):
        make_controller(burn_high=1.0, burn_low=6.0)


# -- hysteresis + cooldown ---------------------------------------------------


def test_burn_inside_band_never_shifts():
    ctl, trainer, fleet, _ = make_controller()
    for i in range(200):
        # oscillate hard against both edges but strictly inside
        fleet.set_burn(1.0001 if i % 2 else 5.9999)
        ctl.tick()
    assert ctl.stats["shifts"] == 0 and ctl.shift_log == []
    assert trainer.replans == []
    assert ctl.audit() == []


def test_burn_at_threshold_counts_toward_streak():
    # exactly AT burn_high for confirm_ticks ticks => shift (>= edge)
    ctl, trainer, fleet, _ = make_controller(confirm_ticks=3)
    fleet.set_burn(6.0)
    for _ in range(3):
        ctl.tick()
    assert ctl.stats["shifts"] == 1
    assert trainer.plan.spec.dp == 2 and ctl.split == (2, 4)
    # the audit treats an at-edge start as outside the band
    assert ctl.audit() == []


def test_burn_just_below_threshold_never_shifts():
    ctl, trainer, fleet, _ = make_controller(confirm_ticks=3)
    fleet.set_burn(5.999999)
    for _ in range(50):
        ctl.tick()
    assert ctl.stats["shifts"] == 0 and trainer.replans == []


def test_broken_streak_resets_confirm_count():
    ctl, trainer, fleet, _ = make_controller(confirm_ticks=3)
    for _ in range(10):
        fleet.set_burn(7.0)
        ctl.tick()
        ctl.tick()
        fleet.set_burn(3.0)           # inside band: streak resets
        ctl.tick()
    assert ctl.stats["shifts"] == 0


def test_cooldown_blocks_followup_shift():
    ctl, trainer, fleet, clockv = make_controller(
        confirm_ticks=2, cooldown_s=10.0)
    fleet.set_burn(8.0)
    ctl.tick()
    ctl.tick()
    assert ctl.stats["shifts"] == 1             # dp 4 -> 2
    # burn collapses, but the cooldown holds the reverse shift
    fleet.set_burn(0.0)
    for _ in range(20):
        clockv[0] += 0.1
        ctl.tick()
    assert ctl.stats["shifts"] == 1
    clockv[0] += 10.0                           # past the cooldown
    for _ in range(3):
        ctl.tick()
    assert ctl.stats["shifts"] == 2
    assert trainer.plan.spec.dp == 4 and ctl.split == (4, 2)
    assert ctl.outstanding_leases == 0
    assert ctl.audit() == []


def test_slo_windows_reset_on_commit():
    ctl, trainer, fleet, _ = make_controller(confirm_ticks=1)
    survivors = [e for _, e in fleet._live()]
    fleet.set_burn(9.0)
    ctl.tick()
    assert ctl.stats["shifts"] == 1
    for e in survivors:
        assert e.metrics.slo.resets == ["shift-1"]


# -- one shift at a time -----------------------------------------------------


def test_shift_during_shift_queues_never_interleaves():
    ctl, trainer, fleet, clockv = make_controller(cooldown_s=0.0)
    assert ctl.request_shift("to_serving") == "queued"
    ctl.tick()
    assert ctl.stats["shifts"] == 1 and ctl.outstanding_leases == 1
    # a slow drain keeps the to_training shift in flight for ticks
    fleet.drain_done = False
    ctl.request_shift("to_training")
    ctl.tick()
    assert ctl.shifting
    ctl.request_shift("to_serving")             # arrives mid-shift
    for _ in range(5):
        ctl.tick()
    # still the SAME in-flight shift; the request queued, not mixed in
    assert ctl.shifting and ctl._shift.direction == "to_training"
    assert len(ctl.shift_log) == 2
    fleet.drain_done = True
    ctl.tick()                                  # drain converges, commit
    assert not ctl.shifting and ctl.stats["shifts"] == 2
    ctl.tick()                                  # queued request starts
    assert ctl.stats["shifts"] == 3
    assert [e["direction"] for e in ctl.shift_log] == [
        "to_serving", "to_training", "to_serving"]
    assert all(e["outcome"] == "commit" for e in ctl.shift_log)


def test_infeasible_queued_shift_is_dropped():
    ctl, trainer, fleet, _ = make_controller()
    ctl.request_shift("to_training")            # nothing leased
    ctl.tick()
    assert ctl.stats["shifts"] == 0 and not ctl.shifting
    with pytest.raises(ValueError):
        ctl.request_shift("sideways")


# -- injected failure modes roll back the split ------------------------------


def test_stuck_drain_times_out_and_rolls_back():
    sinj = ServingFaultInjector([ServingFault(
        0, 0, "capacity_change", magnitude=2.0, duration=10 ** 9)])
    ctl, trainer, fleet, _ = make_controller(
        cooldown_s=0.0, drain_timeout_ticks=5, serving_injector=sinj)
    ctl.request_shift("to_serving")
    for _ in range(8):
        ctl.tick()
    assert ctl.stats["rollbacks"] == 1 and ctl.stats["shifts"] == 0
    assert ctl.split == (4, 2) and trainer.replans == []
    assert "timed out" in ctl.shift_log[0]["reason"]


def test_failed_reshard_rolls_back_without_mutation():
    sinj = ServingFaultInjector([ServingFault(
        0, 0, "capacity_change", magnitude=3.0, duration=10 ** 9)])
    ctl, trainer, fleet, _ = make_controller(
        cooldown_s=0.0, serving_injector=sinj)
    ctl.request_shift("to_serving")
    ctl.tick()
    assert ctl.stats["rollbacks"] == 1
    assert ctl.split == (4, 2) and trainer.replans == []
    assert len(fleet._live()) == 2
    # the fault was consumed: the retry commits
    ctl.request_shift("to_serving")
    ctl.tick()
    assert ctl.stats["shifts"] == 1 and ctl.split == (2, 4)


def test_mid_shift_crash_on_drain_back_cancels_drain():
    ctl, trainer, fleet, clockv = make_controller(cooldown_s=0.0)
    ctl.request_shift("to_serving")
    ctl.tick()
    assert ctl.outstanding_leases == 1
    inj = FaultInjector([Fault(0, "capacity_change")])
    ctl.injector = inj
    ctl.request_shift("to_training")
    ctl.tick()
    assert ctl.stats["rollbacks"] == 1
    assert ctl.outstanding_leases == 1          # lease survives rollback
    assert fleet.draining == set()              # drain was cancelled
    assert ctl.split == (2, 4)
    # consumed: the retry drains and commits
    ctl.request_shift("to_training")
    for _ in range(3):
        ctl.tick()
    assert ctl.stats["shifts"] == 2 and ctl.split == (4, 2)


# -- rollback restores a REAL trainer bitwise --------------------------------


def _loss(p, x, y):
    return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))


def _batch(step, plan):
    r = np.random.RandomState(60_000 + step)
    return (jnp.asarray(r.randn(8, 8).astype(np.float32)),
            jnp.asarray(r.randn(8, 4).astype(np.float32)))


def _factory(plan, ckpt, inj):
    from apex_tpu.optimizers import FusedAdam

    opt = FusedAdam(lr=1e-2)
    guard = GuardedTrainStep(_loss, opt, warmup_steps=1,
                             checkpoint=ckpt, fault_injector=inj)
    r = np.random.RandomState(3)
    params = plan.put(
        {"w": jnp.asarray(r.randn(8, 4).astype(np.float32)),
         "b": jnp.zeros((4,), jnp.float32)})
    return ElasticComponents(guard, params, opt.init(params),
                             guard.init_state())


def _flat(tr):
    out = list(jax.tree_util.tree_leaves(tr.params))
    st = tr.opt_state
    for key in sorted(st["buckets"]):
        for slot in sorted(st["buckets"][key]):
            v = st["buckets"][key][slot]
            out.extend(v if isinstance(v, list) else [v])
    return [np.asarray(x) for x in out]


@pytest.mark.skipif(len(jax.devices()) < 4, reason="needs 4 devices")
def test_mid_shift_crash_restores_real_trainer_bitwise(tmp_path):
    devices = jax.devices()[:4]
    trainer = ElasticTrainer(
        _factory, ElasticPlan.build(TopologySpec(dp=4), devices=devices),
        directory=str(tmp_path), save_every=1, devices=devices)
    clockv = [0.0]
    fleet = FakeFleet(clock=lambda: clockv[0])
    inj = FaultInjector([Fault(2, "capacity_change")])
    ctl = CapacityController(trainer, fleet, FakeEngine, min_train_dp=2,
                             cooldown_s=0.0, injector=inj,
                             clock=lambda: clockv[0])
    for _ in range(2):
        trainer.step_once(_batch)
    pre = _flat(trainer)
    ctl.request_shift("to_serving")
    ctl.tick()
    # the injected mid-shift crash rolled back: split AND state bitwise
    assert ctl.stats["rollbacks"] == 1 and ctl.stats["shifts"] == 0
    assert trainer.plan.spec.dp == 4 and ctl.split == (4, 2)
    for got, want in zip(_flat(trainer), pre, strict=True):
        np.testing.assert_array_equal(got, want)
    # the retry commits; training continues on the shrunk plan
    ctl.request_shift("to_serving")
    ctl.tick()
    assert ctl.stats["shifts"] == 1 and trainer.plan.spec.dp == 2
    trainer.step_once(_batch)
    assert trainer.current_step == 3


# -- schedule determinism across the kind-tuple append -----------------------


def test_train_from_seed_schedule_unchanged_by_capacity_kind():
    # kinds newer than capacity_change (e.g. dcn_fault) append AFTER it
    idx = FAULT_KINDS.index("capacity_change")
    rates = {k: 0.15 for k in FAULT_KINDS[:idx]}
    inj = FaultInjector.from_seed(5, 40, rates)
    # the schedule must equal the one generated over the PRE-EXISTING
    # kind tuple: a rate-0 kind consumes no rng stream state
    expected = seeded_schedule(5, 40, FAULT_KINDS[:idx], rates)
    assert [(f.step, f.kind) for f in inj.schedule] == expected
    assert expected                               # non-vacuous


def test_serving_from_seed_schedule_unchanged_by_capacity_kind():
    assert SERVING_FAULT_KINDS[-1] == "capacity_change"
    rates = {k: 0.1 for k in SERVING_FAULT_KINDS
             if k != "capacity_change"}
    inj = ServingFaultInjector.from_seed(3, 30, 2, rates)
    old = [k for k in SERVING_FAULT_KINDS if k != "capacity_change"]
    keys = [(rep, kind) for rep in range(2) for kind in old]
    expected = seeded_schedule(3, 30, keys,
                               {(rep, k): rates[k] for rep, k in keys})
    assert [(f.tick, (f.replica, f.kind)) for f in inj.schedule] \
        == expected
    assert expected


def test_capacity_change_consumed_once():
    inj = FaultInjector([Fault(4, "capacity_change", magnitude=3.0)])
    f = inj.check_capacity_change(4)
    assert f is not None and fault_mode(f.magnitude) == "failed_reshard"
    assert inj.check_capacity_change(4) is None
    assert inj.log == [(4, "capacity_change")]

    sinj = ServingFaultInjector([ServingFault(
        2, 1, "capacity_change", duration=100)])
    assert sinj.capacity_change_at(1) is None     # not active yet
    f = sinj.capacity_change_at(10)
    assert f is not None
    assert sinj.capacity_change_at(11) is None    # consume-once
    assert sinj.log == [(10, 1, "capacity_change")]
