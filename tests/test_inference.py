"""apex_tpu.inference: KV-cache decode + continuous-batching engine.

Correctness contract under test (beyond-reference serving leg):

* the single-query decode kernel matches its masked reference AND the
  full-sequence flash kernel's last position;
* ``prefill`` + N ``decode_step`` calls reproduce the full forward's
  logits token-for-token (serial f32 exactly; bf16 cache within bf16
  tolerance; TP=2 shard_map identically to serial);
* the engine's batched greedy decode is token-identical to decoding
  every request in isolation, across admission/slot-reuse/eviction.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

try:                                    # jax >= 0.5 exports it top-level
    from jax import shard_map
except ImportError:                     # pragma: no cover - version skew
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.inference import (InferenceEngine, KVCache, Request,
                                SamplingParams, sample)
from apex_tpu.models.gpt import GPTConfig, GPTModel, pack_for_shard_map
from apex_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_decode,
    flash_attention_decode_reference,
)
from apex_tpu.utils import set_force_pallas


def tiny_cfg(**kw):
    base = dict(vocab_size=32, hidden_size=16, num_layers=2,
                num_attention_heads=2, max_seq_len=16)
    base.update(kw)
    return GPTConfig(**base)


def _model_and_params(key=0, **kw):
    model = GPTModel(tiny_cfg(**kw))
    return model, model.init_params(jax.random.PRNGKey(key))


def _clone(req: Request) -> Request:
    return dataclasses.replace(req)


# -- decode attention kernel -------------------------------------------------

class TestDecodeKernel:
    @pytest.fixture(autouse=True)
    def _force_pallas(self):
        set_force_pallas(True)
        yield
        set_force_pallas(None)

    @pytest.mark.parametrize("cache_dtype", [jnp.float32, jnp.bfloat16])
    def test_matches_reference_ragged_lens(self, rng, cache_dtype):
        b, S, h, d = 4, 160, 3, 64
        q = jnp.asarray(rng.randn(b, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, S, h, d), cache_dtype)
        v = jnp.asarray(rng.randn(b, S, h, d), cache_dtype)
        # lengths hitting the edges: 1 token, mid-block, block boundary,
        # full cache
        lens = jnp.asarray([1, 97, 128, S], jnp.int32)
        out = flash_attention_decode(q, k, v, lens)
        ref = flash_attention_decode_reference(q, k, v, lens)
        tol = 2e-5 if cache_dtype == jnp.float32 else 2e-2
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=tol, atol=tol)

    def test_matches_full_sequence_kernel(self, rng):
        """Decode of the last token over a full cache == the causal
        full-sequence kernel's last position."""
        b, s, h, d = 2, 128, 2, 32
        q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32)
        full = flash_attention(q, k, v, causal=True)       # (b, h, s, d)
        dec = flash_attention_decode(
            q[:, :, -1], k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
            jnp.full((b,), s, jnp.int32))
        np.testing.assert_allclose(np.asarray(dec),
                                   np.asarray(full[:, :, -1]),
                                   rtol=2e-5, atol=2e-5)

    def test_masked_rows_do_not_leak(self, rng):
        """Garbage beyond each row's length must not affect the output."""
        b, S, h, d = 2, 256, 2, 32
        q = jnp.asarray(rng.randn(b, h, d), jnp.float32)
        k = jnp.asarray(rng.randn(b, S, h, d), jnp.float32)
        v = jnp.asarray(rng.randn(b, S, h, d), jnp.float32)
        lens = jnp.asarray([40, 200], jnp.int32)
        out = flash_attention_decode(q, k, v, lens)
        poisoned_k = k.at[0, 40:].set(1e4).at[1, 200:].set(1e4)
        poisoned_v = v.at[0, 40:].set(1e4).at[1, 200:].set(1e4)
        out_p = flash_attention_decode(q, poisoned_k, poisoned_v, lens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_p),
                                   rtol=1e-6, atol=1e-6)


# -- prefill + decode vs full forward ----------------------------------------

def _decode_tail(model, params, tokens, prefill_len, cache_dtype):
    """Prefill ``prefill_len`` tokens, decode the rest; returns the
    decode-step logits stacked ``(b, s - prefill_len, vocab)``."""
    cfg = model.cfg
    b, s = tokens.shape
    logits_p, kv = model.prefill(params, tokens[:, :prefill_len])
    cache = jnp.zeros((b, cfg.num_layers, 2, cfg.max_seq_len,
                       cfg.local_heads, cfg.head_dim), cache_dtype)
    cache = cache.at[:, :, :, :prefill_len].set(
        kv.transpose(2, 0, 1, 3, 4, 5).astype(cache_dtype))
    step = jax.jit(model.decode_step)
    out = []
    for i in range(prefill_len, s):
        lg, cache = step(params, tokens[:, i], cache,
                         jnp.full((b,), i, jnp.int32))
        out.append(lg)
    return logits_p, jnp.stack(out, axis=1)


class TestPrefillDecodeParity:
    @pytest.mark.parametrize("rotary", [True, False])
    def test_serial_f32_exact(self, rng, rotary):
        model, params = _model_and_params(rotary=rotary)
        tokens = jnp.asarray(rng.randint(0, 32, (2, 12)))
        full = model(params, tokens)
        logits_p, dec = _decode_tail(model, params, tokens, 7, jnp.float32)
        np.testing.assert_allclose(np.asarray(logits_p),
                                   np.asarray(full[:, :7]),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(dec),
                                   np.asarray(full[:, 7:]),
                                   rtol=1e-5, atol=1e-5)

    def test_bf16_cache(self, rng):
        model, params = _model_and_params()
        tokens = jnp.asarray(rng.randint(0, 32, (2, 12)))
        full = model(params, tokens)
        _, dec = _decode_tail(model, params, tokens, 7, jnp.bfloat16)
        np.testing.assert_allclose(np.asarray(dec),
                                   np.asarray(full[:, 7:]),
                                   rtol=5e-2, atol=5e-2)

    def test_tp2_shard_map_matches_serial(self, rng):
        """Prefill + decode under TP=2 shard_map: vocab-parallel logits
        gathered over the model axis must match the serial decode
        token-for-token (the TP layers are reused unchanged)."""
        model, params = _model_and_params(key=1)
        tokens = jnp.asarray(rng.randint(0, 32, (2, 10)))
        p = 6
        full = model(params, tokens)

        cfg_p = tiny_cfg(tensor_parallel_size=2, axis_name="model")
        par = GPTModel(cfg_p)
        mesh = jax.make_mesh((2,), ("model",))
        packed, in_specs, local_fn, _ = pack_for_shard_map(par, params)

        def prefill(sp, toks):
            return par.prefill(local_fn(sp), toks)

        # logits are vocab-parallel (gather last axis); kv is
        # head-parallel (gather axis 4)
        logits_p, kv = jax.jit(shard_map(
            prefill, mesh=mesh, in_specs=(in_specs, P()),
            out_specs=(P(None, None, "model"),
                       P(None, None, None, None, "model"))))(
            packed, tokens[:, :p])
        np.testing.assert_allclose(np.asarray(logits_p),
                                   np.asarray(full[:, :p]),
                                   rtol=1e-4, atol=1e-4)

        b = tokens.shape[0]
        cache = jnp.zeros((b, cfg_p.num_layers, 2, cfg_p.max_seq_len,
                           cfg_p.num_attention_heads, cfg_p.head_dim),
                          jnp.float32)
        cache = cache.at[:, :, :, :p].set(kv.transpose(2, 0, 1, 3, 4, 5))

        def decode(sp, toks, cache, pos):
            return par.decode_step(local_fn(sp), toks, cache, pos)

        cache_spec = P(None, None, None, None, "model")
        step = jax.jit(shard_map(
            decode, mesh=mesh,
            in_specs=(in_specs, P(), cache_spec, P()),
            out_specs=(P(None, "model"), cache_spec)))
        for i in range(p, tokens.shape[1]):
            lg, cache = step(packed, tokens[:, i], cache,
                             jnp.full((b,), i, jnp.int32))
            np.testing.assert_allclose(np.asarray(lg),
                                       np.asarray(full[:, i]),
                                       rtol=1e-4, atol=1e-4)


# -- KV cache manager --------------------------------------------------------

class TestKVCache:
    def _cache(self, slots=3):
        return KVCache(slots, layers=2, max_seq=8, kv_heads=2, head_dim=4,
                       dtype=jnp.bfloat16)

    def test_allocate_free_reuse(self):
        c = self._cache(2)
        a, b = c.allocate(), c.allocate()
        assert {a, b} == {0, 1}
        assert c.allocate() is None          # exhausted
        c.free(a)
        assert c.allocate() == a             # freed slot comes back
        with pytest.raises(ValueError):
            c.free(b)
            c.free(b)                        # double free

    def test_write_prompt_casts_and_masks(self, rng):
        c = self._cache()
        kv = jnp.asarray(rng.randn(2, 2, 8, 2, 4), jnp.float32)
        c.write_prompt(1, kv, length=5)
        assert c.data.dtype == jnp.bfloat16
        assert c.lengths[1] == 5
        np.testing.assert_allclose(np.asarray(c.data[1], np.float32),
                                   np.asarray(kv.astype(jnp.bfloat16),
                                              np.float32))
        c.advance(1)
        assert c.lengths[1] == 6

    def test_write_prompt_validation(self, rng):
        c = self._cache()
        with pytest.raises(ValueError):
            c.write_prompt(0, jnp.zeros((2, 2, 9, 2, 4)), 9)  # > max_seq
        with pytest.raises(ValueError):
            c.write_prompt(0, jnp.zeros((2, 2, 8, 2, 4)), 0)  # empty

    def test_byte_accounting(self, rng):
        """free_bytes is slot-granular (allocatable capacity);
        used_bytes/occupancy are token-granular (valid entries) — the
        gap between them is the internal fragmentation the paged cache
        exists to remove."""
        c = self._cache(2)                       # 2 slots x 8 positions
        assert c.free_bytes() == 2 * c.slot_bytes
        assert c.used_bytes() == 0 and c.occupancy() == 0.0
        slot = c.allocate()
        c.write_prompt(slot, jnp.asarray(rng.randn(2, 2, 8, 2, 4),
                                         jnp.float32), length=4)
        assert c.free_bytes() == 1 * c.slot_bytes
        assert c.used_bytes() == c.slot_bytes // 2   # 4 of 8 positions
        assert c.occupancy() == pytest.approx(4 / 16)
        c.advance(slot)
        assert c.occupancy() == pytest.approx(5 / 16)
        c.free(slot)
        assert c.free_bytes() == 2 * c.slot_bytes and c.occupancy() == 0.0


# -- sampling ----------------------------------------------------------------

class TestSampling:
    def test_greedy_is_argmax(self):
        logits = jnp.asarray([[0.1, 2.0, -1.0], [3.0, 0.0, 1.0]])
        np.testing.assert_array_equal(np.asarray(sample(logits)), [1, 0])

    def test_stochastic_requires_key(self):
        with pytest.raises(ValueError):
            sample(jnp.zeros((4,)), SamplingParams(temperature=1.0))

    def test_top_k_restricts_support(self):
        logits = jnp.asarray([5.0, 4.0, -10.0, -10.0])
        p = SamplingParams(temperature=1.0, top_k=2)
        draws = {int(sample(logits, p, jax.random.PRNGKey(i)))
                 for i in range(32)}
        assert draws <= {0, 1} and len(draws) == 2

    def test_top_p_restricts_to_nucleus(self):
        # probs ~ [0.64, 0.24, 0.09, 0.03]: a 0.7 nucleus keeps the top
        # two (the crossing token is included), never tokens 2 or 3
        logits = jnp.asarray([4.0, 3.0, 2.0, 1.0])
        p = SamplingParams(temperature=1.0, top_p=0.7)
        draws = {int(sample(logits, p, jax.random.PRNGKey(i)))
                 for i in range(64)}
        assert draws <= {0, 1} and len(draws) == 2

    def test_top_p_always_keeps_one_token(self):
        # a tiny nucleus still samples: the argmax survives even when
        # its probability alone exceeds top_p
        logits = jnp.asarray([10.0, 0.0, 0.0, 0.0])
        p = SamplingParams(temperature=1.0, top_p=0.01)
        assert all(int(sample(logits, p, jax.random.PRNGKey(i))) == 0
                   for i in range(8))

    def test_top_p_composes_with_top_k(self):
        # k=3 keeps {0,1,2}; the 0.75 nucleus over the survivors' mass
        # then drops token 2 as well
        logits = jnp.asarray([4.0, 3.0, 2.0, 1.9])
        p = SamplingParams(temperature=1.0, top_k=3, top_p=0.75)
        draws = {int(sample(logits, p, jax.random.PRNGKey(i)))
                 for i in range(64)}
        assert draws <= {0, 1}

    def test_top_p_one_is_full_vocab(self):
        logits = jnp.asarray([0.0, 0.1, 0.2, 0.3])
        p = SamplingParams(temperature=5.0, top_p=1.0)
        draws = {int(sample(logits, p, jax.random.PRNGKey(i)))
                 for i in range(128)}
        assert draws == {0, 1, 2, 3}

    def test_invalid_params(self):
        with pytest.raises(ValueError):
            SamplingParams(temperature=-1.0)
        with pytest.raises(ValueError):
            SamplingParams(top_k=0)
        with pytest.raises(ValueError):
            SamplingParams(top_p=0.0)
        with pytest.raises(ValueError):
            SamplingParams(top_p=1.5)


# -- continuous-batching engine ----------------------------------------------

class TestEngine:
    def _requests(self, rng, n=8, vocab=32):
        return [Request(request_id=i,
                        prompt=[int(t) for t in
                                rng.randint(1, vocab,
                                            int(rng.randint(2, 9)))],
                        max_new_tokens=int(rng.randint(1, 7)))
                for i in range(n)]

    def test_mixed_batch_matches_isolated_greedy(self, rng):
        """The headline invariant: every response from a mixed 8-request
        workload on 3 slots is identical to running that request alone."""
        model, params = _model_and_params()
        reqs = self._requests(rng)
        eng = InferenceEngine(model, params, max_slots=3,
                              cache_dtype=jnp.float32)
        for r in reqs:
            eng.submit(_clone(r))
        batched = {r.request_id: r.tokens for r in eng.run()}
        assert len(batched) == len(reqs)
        # no deadlines in this workload: the eviction counter must stay 0
        assert eng.metrics.summary()["evicted"] == 0
        for r in reqs:
            solo = InferenceEngine(model, params, max_slots=1,
                                   cache_dtype=jnp.float32)
            solo.submit(_clone(r))
            assert solo.run()[0].tokens == batched[r.request_id], \
                f"request {r.request_id} diverged under batching"

    def test_slot_reuse_and_admission_under_full_occupancy(self, rng):
        """More requests than slots: the engine must queue, admit as
        slots free, and reuse every slot without leaking."""
        model, params = _model_and_params()
        reqs = self._requests(rng, n=6)
        eng = InferenceEngine(model, params, max_slots=2,
                              cache_dtype=jnp.float32)
        for r in reqs:
            eng.submit(r)
        # after one step both slots are busy and the rest are queued
        eng.step()
        assert eng.cache.free_slots == 0 or len(eng.completed) > 0
        assert len(eng._queue) <= 4
        out = eng.run()
        assert sorted(r.request_id for r in out) == list(range(6))
        assert eng.cache.free_slots == 2         # all slots returned
        occ = [a for a, _ in eng.metrics.occupancy]
        assert max(occ) == 2                     # full occupancy reached

    def test_deadline_eviction(self, rng):
        """A fake clock advances one unit per reading: requests whose
        deadline passes mid-decode are evicted with partial output."""
        model, params = _model_and_params()
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]

        eng = InferenceEngine(model, params, max_slots=2, clock=clock,
                              cache_dtype=jnp.float32)
        eng.submit(Request(request_id=0, prompt=[1, 2, 3],
                           max_new_tokens=100, deadline=30.0))
        eng.submit(Request(request_id=1, prompt=[4, 5],
                           max_new_tokens=3))
        out = {r.request_id: r for r in eng.run(max_steps=200)}
        assert out[1].finish_reason == "length"
        assert out[0].finish_reason == "evicted"
        assert 0 < len(out[0].tokens) < 100
        # the eviction reached the serving stats (not just the Response)
        assert eng.metrics.summary()["evicted"] == 1
        # queued-but-never-run requests past deadline evict empty
        eng2 = InferenceEngine(model, params, max_slots=1, clock=clock,
                               cache_dtype=jnp.float32)
        eng2.submit(Request(request_id=7, prompt=[1], deadline=t[0] - 1))
        (r,) = eng2.run()
        assert r.finish_reason == "evicted" and r.tokens == []
        assert eng2.metrics.summary()["evicted"] == 1

    def test_eos_and_cache_exhaustion(self, rng):
        model, params = _model_and_params()
        eng = InferenceEngine(model, params, max_slots=1,
                              cache_dtype=jnp.float32)
        # find the greedy continuation, then rerun with its first token
        # as eos — the request must stop immediately after emitting it
        eng.submit(Request(request_id=0, prompt=[3, 4, 5],
                           max_new_tokens=4))
        first = eng.run()[0].tokens[0]
        eng2 = InferenceEngine(model, params, max_slots=1,
                               cache_dtype=jnp.float32)
        eng2.submit(Request(request_id=1, prompt=[3, 4, 5],
                            max_new_tokens=4, eos_id=first))
        (r,) = eng2.run()
        assert r.finish_reason == "eos" and r.tokens == [first]
        # a request that would overrun max_seq stops with "length"
        eng3 = InferenceEngine(model, params, max_slots=1,
                               cache_dtype=jnp.float32)
        eng3.submit(Request(request_id=2, prompt=[1] * 14,
                            max_new_tokens=100))
        (r,) = eng3.run()
        assert r.finish_reason == "length"
        # cache rows allow decode feeds at positions 14 and 15; with the
        # prefill-sampled token that is max_seq - prompt_len + 1 outputs
        # (the final sample needs no cache write of its own)
        assert len(r.tokens) == 16 - 14 + 1

    def test_prompt_validation(self, rng):
        model, params = _model_and_params()
        eng = InferenceEngine(model, params, max_slots=1)
        with pytest.raises(ValueError):
            eng.submit(Request(request_id=0, prompt=[]))
        with pytest.raises(ValueError):
            eng.submit(Request(request_id=1, prompt=[1] * 16))

    def test_serving_metrics(self, rng):
        model, params = _model_and_params()
        t = [0.0]

        def clock():
            t[0] += 0.5
            return t[0]

        eng = InferenceEngine(model, params, max_slots=2, clock=clock,
                              cache_dtype=jnp.float32)
        for i in range(3):
            eng.submit(Request(request_id=i, prompt=[1 + i, 2],
                               max_new_tokens=3))
        eng.run()
        s = eng.metrics.summary()
        assert s["requests"] == 3
        assert s["tokens"] == 9
        assert s["tokens_per_s"] > 0
        assert s["ttft_p50_s"] > 0
        assert s["token_latency_p50_s"] > 0
        assert 0 < s["slot_occupancy_mean"] <= 1
