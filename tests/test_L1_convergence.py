"""L1 cross-product convergence tests (reference: ``tests/L1/`` —
``common/main_amp.py`` trains the same model at every opt level x
{fused, unfused} optimizer and ``common/compare.py`` asserts the loss
trajectories stay within tolerance of each other).

Here the cross product is run in-process on a small MLP classifier:
O0 fp32 is the golden trajectory; every other (opt_level, optimizer)
cell must track it within half-precision tolerances, and fused must
track unfused at the same level much tighter.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam

STEPS = 10
LR = 1e-2


def _data():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(64, 32), jnp.float32)
    y = jnp.asarray(rng.randint(0, 8, (64,)))
    return x, y


def _init_params():
    rng = np.random.RandomState(1)
    return {
        "w1": jnp.asarray(rng.randn(32, 64) * 0.1, jnp.float32),
        "b1": jnp.zeros((64,), jnp.float32),
        "w2": jnp.asarray(rng.randn(64, 8) * 0.1, jnp.float32),
        "b2": jnp.zeros((8,), jnp.float32),
    }


def _model(params, x):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _raw_loss(apply_fn, params, x, y):
    logits = apply_fn(params, x).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))


def run_trajectory(opt_level: str, fused: bool, half_dtype=None):
    """Train STEPS steps, return the loss trajectory (floats)."""
    x, y = _data()
    params = _init_params()

    optimizer = FusedAdam(lr=LR) if fused else None
    kw = {} if half_dtype is None else {"half_dtype": half_dtype}
    state = amp.initialize(_model, optimizer, opt_level=opt_level, **kw)
    params = state.cast_params(params)
    scaler_state = state.scaler.init()

    if fused:
        opt_state = optimizer.init(params)
    else:
        # unfused comparator: hand-written Adam in plain jnp (the eager
        # baseline the reference compares FusedAdam against)
        opt_state = {
            "m": jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "v": jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params),
            "t": jnp.zeros((), jnp.int32),
        }

    @jax.jit
    def step(params, opt_state, scaler_state):
        def loss_fn(p):
            return amp.scale_loss(
                _raw_loss(state.apply_fn, p, x, y), scaler_state)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        loss = loss / scaler_state.loss_scale
        if fused:
            params, opt_state, scaler_state, _ = amp.unscale_step(
                optimizer, grads, params, opt_state, state.scaler,
                scaler_state)
        else:
            inv = 1.0 / scaler_state.loss_scale
            t = opt_state["t"] + 1
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree_util.tree_map(
                lambda m, g: b1 * m + (1 - b1) * g.astype(jnp.float32)
                * inv, opt_state["m"], grads)
            v = jax.tree_util.tree_map(
                lambda v, g: b2 * v + (1 - b2)
                * (g.astype(jnp.float32) * inv) ** 2, opt_state["v"],
                grads)
            tf = t.astype(jnp.float32)
            params = jax.tree_util.tree_map(
                lambda p, m_, v_: (p.astype(jnp.float32) - LR
                                   * (m_ / (1 - b1 ** tf))
                                   / (jnp.sqrt(v_ / (1 - b2 ** tf))
                                      + eps)).astype(p.dtype),
                params, m, v)
            opt_state = {"m": m, "v": v, "t": t}
            scaler_state = state.scaler.update(
                scaler_state, amp.LossScaler.found_inf(grads))
        return params, opt_state, scaler_state, loss

    traj = []
    for _ in range(STEPS):
        params, opt_state, scaler_state, loss = step(
            params, opt_state, scaler_state)
        traj.append(float(loss))
    return traj


@pytest.fixture(scope="module")
def golden():
    """O0 + fused is the golden trajectory (apex compare.py baseline)."""
    return run_trajectory("O0", fused=True)


class TestL1CrossProduct:
    def test_golden_converges(self, golden):
        assert golden[-1] < golden[0] * 0.7, golden

    @pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
    @pytest.mark.parametrize("fused", [True, False])
    def test_trajectory_tracks_golden(self, golden, opt_level, fused):
        traj = run_trajectory(opt_level, fused)
        assert all(np.isfinite(traj)), (opt_level, fused, traj)
        # fp32 cells must match near-exactly; half-precision cells within
        # bf16 tolerance (reference compare.py: loose for half)
        tol = 1e-4 if opt_level == "O0" else 7e-2
        np.testing.assert_allclose(traj, golden, rtol=tol, atol=tol,
                                   err_msg=f"{opt_level} fused={fused}")
        assert traj[-1] < traj[0] * 0.8, (opt_level, fused, traj)

    @pytest.mark.parametrize("opt_level", ["O1", "O2"])
    def test_fp16_dynamic_scaling_lane(self, golden, opt_level):
        """The apex-faithful fp16 path: half_dtype=float16 resolves to
        DYNAMIC loss scaling (bf16 defaults to static 1.0) and the
        trajectory still tracks the fp32 golden.  (Scaler growth
        mechanics are asserted in test_fp16_dynamic_scaler_engages.)"""
        # guard the property this lane exists for: fp16 => dynamic
        probe = amp.initialize(_model, None, opt_level=opt_level,
                               half_dtype=jnp.float16)
        assert probe.scaler.dynamic
        traj = run_trajectory(opt_level, fused=True,
                              half_dtype=jnp.float16)
        assert all(np.isfinite(traj)), (opt_level, traj)
        np.testing.assert_allclose(traj, golden, rtol=7e-2, atol=7e-2,
                                   err_msg=f"fp16 {opt_level}")
        assert traj[-1] < traj[0] * 0.8, (opt_level, traj)

    def test_fp16_dynamic_scaler_engages(self):
        """Under fp16 the scaler state is live: initialize() resolves a
        dynamic scaler and its scale grows over non-overflow steps when
        the growth window is short."""
        x, y = _data()
        params = _init_params()
        opt = FusedAdam(lr=LR)
        state = amp.initialize(_model, opt, opt_level="O2",
                               half_dtype=jnp.float16)
        assert state.scaler.dynamic       # fp16 resolves to dynamic
        state.scaler.scale_window = 2
        params = state.cast_params(params)
        sstate = state.scaler.init()
        opt_state = opt.init(params)
        scale0 = float(sstate.loss_scale)

        @jax.jit
        def step(params, opt_state, sstate):
            def loss_fn(p):
                return amp.scale_loss(
                    _raw_loss(state.apply_fn, p, x, y), sstate)
            _, grads = jax.value_and_grad(loss_fn)(params)
            return amp.unscale_step(opt, grads, params, opt_state,
                                    state.scaler, sstate)

        for _ in range(5):
            params, opt_state, sstate, finf = step(params, opt_state,
                                                   sstate)
            assert not bool(finf > 0)
        assert float(sstate.loss_scale) > scale0

    def test_fused_vs_unfused_same_level_tight(self):
        """Fused and unfused Adam are the same math: per-level pairs must
        agree far tighter than the cross-level tolerance."""
        for lvl in ["O0", "O1", "O2", "O3"]:
            f = run_trajectory(lvl, fused=True)
            u = run_trajectory(lvl, fused=False)
            np.testing.assert_allclose(
                f, u, rtol=5e-3, atol=5e-3,
                err_msg=f"fused vs unfused diverge at {lvl}")
