"""apex_tpu.analysis: analyzer fixtures (positive + negative), the
memory estimator's accuracy gate, baseline bookkeeping, the canonical
programs vs the committed baseline, and the applied donation fixes
(inference-engine decode, guarded train step) staying bitwise-clean.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from apex_tpu.analysis import (Finding, LintConfig, LintProgram, LintReport,
                               estimate_from_hlo_text, lint, lint_fn,
                               load_baseline, parse_hlo_module,
                               save_baseline, scope_of, shape_bytes)
from apex_tpu.analysis.canonical import BUILDERS, canonical_programs
from apex_tpu.utils.collectives import shard_map_compat

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "lint_baseline.json")


def _rules(report):
    return [f.rule for f in report.findings]


# -- jaxpr-level analyzers ---------------------------------------------------


class TestDtypeRule:
    def test_bf16_upcast_matmul_trips(self):
        def step(w, x):
            return x @ w.astype(jnp.float32)        # bf16 -> f32 upcast

        rep = lint_fn(step, jnp.zeros((16, 16), jnp.bfloat16),
                      jnp.ones((4, 16), jnp.float32),
                      config=LintConfig(estimate_memory=False))
        assert "dtype/bf16-upcast-matmul" in _rules(rep)
        (f,) = [f for f in rep.findings
                if f.rule == "dtype/bf16-upcast-matmul"]
        assert f.details["source_dtype"] == "bfloat16"
        assert f.fix_hint

    def test_preferred_element_type_is_clean(self):
        def step(w, x):
            # the sanctioned AMP idiom: bf16 operands, f32 accumulate
            return jax.lax.dot_general(
                x.astype(jnp.bfloat16), w,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

        rep = lint_fn(step, jnp.zeros((16, 16), jnp.bfloat16),
                      jnp.ones((4, 16), jnp.float32),
                      config=LintConfig(estimate_memory=False))
        assert "dtype/bf16-upcast-matmul" not in _rules(rep)

    def test_f64_trips_and_is_error(self):
        from jax.experimental import enable_x64
        with enable_x64():
            def step(x):
                return x * np.float64(2.0)

            rep = lint_fn(step, jnp.ones((8,), jnp.float64),
                          config=LintConfig(estimate_memory=False))
        (f,) = [f for f in rep.findings if f.rule == "dtype/f64-op"]
        assert f.severity == "error"

    def test_f32_program_has_no_f64_finding(self):
        rep = lint_fn(lambda x: x * 2.0, jnp.ones((8,), jnp.float32),
                      config=LintConfig(estimate_memory=False))
        assert "dtype/f64-op" not in _rules(rep)


class TestDonationRule:
    def _step(self, params, opt, x):
        g = jax.tree_util.tree_map(lambda p: p * 0.9, params)
        return (jax.tree_util.tree_map(lambda a, b: a + b, params, g),
                opt, x.sum())

    def test_missing_donation_trips_per_argnum(self):
        params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((64,))}
        opt = {"m": jnp.zeros((64, 64))}
        rep = lint_fn(self._step, params, opt, jnp.ones((4, 64)),
                      config=LintConfig(estimate_memory=False))
        hits = [f for f in rep.findings if f.rule == "donation/missing"]
        assert {f.details["argnum"] for f in hits} == {0, 1}
        f0 = next(f for f in hits if f.details["argnum"] == 0)
        assert f0.details["aliasable_bytes"] >= 64 * 64 * 4
        assert f0.details["example_path"]
        assert f0.scope == "arg0"

    def test_donated_program_is_clean(self):
        params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((64,))}
        opt = {"m": jnp.zeros((64, 64))}
        rep = lint_fn(self._step, params, opt, jnp.ones((4, 64)),
                      donate_argnums=(0, 1),
                      config=LintConfig(estimate_memory=False))
        assert "donation/missing" not in _rules(rep)

    def test_tiny_aliasable_leaves_are_ignored(self):
        rep = lint_fn(lambda c: c + 1, jnp.zeros((4,), jnp.float32),
                      config=LintConfig(estimate_memory=False))
        assert "donation/missing" not in _rules(rep)


class TestHostSyncRule:
    def test_debug_print_trips(self):
        def step(x):
            jax.debug.print("loss={v}", v=x.sum())
            return x * 2

        rep = lint_fn(step, jnp.ones((8,)),
                      config=LintConfig(estimate_memory=False))
        hits = [f for f in rep.findings if f.rule == "host-sync/callback"]
        assert hits and hits[0].severity == "warning"

    def test_pure_callback_trips(self):
        def step(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a) * 2.0,
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)
            return y.sum()

        rep = lint_fn(step, jnp.ones((8,)),
                      config=LintConfig(estimate_memory=False))
        assert "host-sync/callback" in _rules(rep)

    def test_pure_program_is_clean(self):
        rep = lint_fn(lambda x: x * 2, jnp.ones((8,)),
                      config=LintConfig(estimate_memory=False))
        assert "host-sync/callback" not in _rules(rep)


class TestRecompileRule:
    def test_unhashable_static_is_error(self):
        from apex_tpu.analysis.jaxpr_rules import analyze_recompile
        prog = LintProgram("p", fn=lambda x, cfg: x * cfg[0],
                           args=(jnp.ones(4), [2.0]), static_argnums=(1,))
        (f,) = analyze_recompile(prog, LintConfig())
        assert f.rule == "recompile/unhashable-static"
        assert f.severity == "error"

    def test_identity_hash_static_warns(self):
        from apex_tpu.analysis.jaxpr_rules import analyze_recompile

        class Cfg:                      # no __eq__/__hash__: identity
            scale = 2.0

        prog = LintProgram("p", fn=lambda x, cfg: x * cfg.scale,
                           args=(jnp.ones(4), Cfg()), static_argnums=(1,))
        (f,) = analyze_recompile(prog, LintConfig())
        assert f.rule == "recompile/identity-static"

    def test_hashable_value_static_is_clean(self):
        from apex_tpu.analysis.jaxpr_rules import analyze_recompile
        prog = LintProgram("p", fn=lambda x, k: x * k,
                           args=(jnp.ones(4), 2.0), static_argnums=(1,))
        assert analyze_recompile(prog, LintConfig()) == []


# -- HLO-level analyzers -----------------------------------------------------


class _FakeProgram:
    """Stub carrying a pre-parsed module into the HLO analyzers."""

    def __init__(self, text):
        self._mod = parse_hlo_module(text)

    def hlo_module(self):
        return self._mod


class TestOverlapRule:
    def test_chained_psums_trip(self):
        # the pp loss pattern: psum over one axis feeding psum over the
        # other with nothing between — two serialized all-reduces
        mesh = jax.make_mesh((2, 2), ("dp", "tp"),
                             devices=jax.devices()[:4])

        def f(x):
            return jax.lax.psum(jax.lax.psum(x, "dp"), "tp")

        g = shard_map_compat(f, mesh=mesh, in_specs=P("dp"),
                             out_specs=P())
        rep = lint_fn(g, jnp.ones((8, 16)),
                      config=LintConfig(estimate_memory=False))
        hits = [f for f in rep.findings
                if f.rule == "overlap/serialized-collectives"]
        assert hits and hits[0].details["upstream_op"] == "all-reduce"

    def test_compute_between_collectives_is_clean(self):
        mesh = jax.make_mesh((4,), ("tp",), devices=jax.devices()[:4])

        def f(x):
            y = jax.lax.psum(x, "tp")
            return jax.lax.psum(jnp.tanh(y) @ jnp.ones((16, 16)), "tp")

        g = shard_map_compat(f, mesh=mesh, in_specs=P("tp"),
                             out_specs=P())
        rep = lint_fn(g, jnp.ones((8, 16)),
                      config=LintConfig(estimate_memory=False))
        assert "overlap/serialized-collectives" not in _rules(rep)


_ROUNDTRIP_HLO = """\
HloModule g, is_scheduled=true, num_partitions=4

ENTRY %main (p0: f32[64,16]) -> f32[64,16] {
  %p0 = f32[64,16]{1,0} parameter(0)
  %rs = f32[16,16]{1,0} reduce-scatter(f32[64,16]{1,0} %p0), replica_groups={{0,1,2,3}}, dimensions={0}, to_apply=%add
  %cp = f32[16,16]{1,0} copy(f32[16,16]{1,0} %rs)
  ROOT %ag = f32[64,16]{1,0} all-gather(f32[16,16]{1,0} %cp), replica_groups={{0,1,2,3}}, dimensions={0}, metadata={op_name="jit(f)/jit(main)/mlp/all_gather"}
}
"""


class TestShardingRule:
    def test_gather_roundtrip_trips(self):
        from apex_tpu.analysis.hlo_rules import analyze_sharding
        findings = analyze_sharding(_FakeProgram(_ROUNDTRIP_HLO),
                                    LintConfig())
        (f,) = [f for f in findings
                if f.rule == "sharding/gather-roundtrip"]
        assert f.details["scatter"] == "rs"
        assert f.scope == "mlp/all_gather"

    def test_large_gather_without_roundtrip_is_info(self):
        from apex_tpu.analysis.hlo_rules import analyze_sharding
        text = _ROUNDTRIP_HLO.replace("reduce-scatter", "dynamic-slice")
        findings = analyze_sharding(_FakeProgram(text),
                                    LintConfig(large_bytes=1024))
        rules = [f.rule for f in findings]
        assert "sharding/gather-roundtrip" not in rules
        (f,) = [f for f in findings if f.rule == "sharding/large-gather"]
        assert f.severity == "info"

    def test_replicated_large_trips(self):
        mesh = jax.make_mesh((8,), ("tp",), devices=jax.devices()[:8])
        w = jnp.zeros((64, 64), jnp.float32)          # 16 KiB
        x = jnp.ones((8, 64), jnp.float32)
        f = jax.jit(lambda w, x: x @ w,
                    in_shardings=(NamedSharding(mesh, P()),
                                  NamedSharding(mesh, P("tp"))),
                    out_shardings=NamedSharding(mesh, P("tp")))
        prog = LintProgram("repl", lowered=f.lower(w, x))
        cfg = LintConfig(large_bytes=4096, estimate_memory=False,
                         analyzers=("sharding",))
        rep = lint(prog, cfg)
        hits = [f for f in rep.findings
                if f.rule == "sharding/replicated-large"]
        assert hits and hits[0].details["partitions"] == 8

    def test_sharded_weight_is_clean(self):
        mesh = jax.make_mesh((8,), ("tp",), devices=jax.devices()[:8])
        w = jnp.zeros((64, 64), jnp.float32)
        x = jnp.ones((8, 64), jnp.float32)
        f = jax.jit(lambda w, x: x @ w,
                    in_shardings=(NamedSharding(mesh, P(None, "tp")),
                                  NamedSharding(mesh, P())),
                    out_shardings=NamedSharding(mesh, P(None, "tp")))
        prog = LintProgram("shrd", lowered=f.lower(w, x))
        cfg = LintConfig(large_bytes=4096, estimate_memory=False,
                         analyzers=("sharding",))
        assert "sharding/replicated-large" not in _rules(lint(prog, cfg))

    def test_single_partition_skips(self):
        from apex_tpu.analysis.hlo_rules import analyze_sharding
        text = _ROUNDTRIP_HLO.replace(", num_partitions=4", "")
        assert analyze_sharding(_FakeProgram(text), LintConfig()) == []


# -- HLO parsing + memory estimator ------------------------------------------

_SYNTH = """\
HloModule synth, is_scheduled=true, input_output_alias={ {}: (0, {}, may-alias) }, entry_computation_layout={(f32[1024]{0}, f32[1024]{0})->f32[1024]{0}}

ENTRY %main (p0: f32[1024], p1: f32[1024]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %p1 = f32[1024]{0} parameter(1)
  %add = f32[1024]{0} add(f32[1024]{0} %p0, f32[1024]{0} %p1), metadata={op_name="jit(f)/jit(main)/layer/add"}
  %mul = f32[1024]{0} multiply(f32[1024]{0} %add, f32[1024]{0} %p1)
  ROOT %out = f32[1024]{0} add(f32[1024]{0} %mul, f32[1024]{0} %add)
}
"""

_WHILE_HLO = """\
HloModule w, is_scheduled=true

%body (bp: (f32[256], s32[])) -> (f32[256], s32[]) {
  %bp = (f32[256]{0}, s32[]) parameter(0)
  %v = f32[256]{0} get-tuple-element((f32[256]{0}, s32[]) %bp), index=0
  %i = s32[] get-tuple-element((f32[256]{0}, s32[]) %bp), index=1
  %v2 = f32[256]{0} add(f32[256]{0} %v, f32[256]{0} %v)
  %one = s32[] constant(1)
  %i2 = s32[] add(s32[] %i, s32[] %one)
  ROOT %t = (f32[256]{0}, s32[]) tuple(f32[256]{0} %v2, s32[] %i2)
}

%cond (cp: (f32[256], s32[])) -> pred[] {
  %cp = (f32[256]{0}, s32[]) parameter(0)
  %ci = s32[] get-tuple-element((f32[256]{0}, s32[]) %cp), index=1
  %n = s32[] constant(8)
  ROOT %lt = pred[] compare(s32[] %ci, s32[] %n), direction=LT
}

ENTRY %main (a: f32[256]) -> f32[256] {
  %a = f32[256]{0} parameter(0)
  %z = s32[] constant(0)
  %init = (f32[256]{0}, s32[]) tuple(f32[256]{0} %a, s32[] %z)
  %w = (f32[256]{0}, s32[]) while((f32[256]{0}, s32[]) %init), condition=%cond, body=%body
  ROOT %r = f32[256]{0} get-tuple-element((f32[256]{0}, s32[]) %w), index=0
}
"""


class TestHloParsing:
    def test_shape_bytes(self):
        assert shape_bytes("f32[128,4]") == 128 * 4 * 4
        assert shape_bytes("bf16[8]{0}") == 16
        assert shape_bytes("(f32[4], s32[2])") == 16 + 8
        assert shape_bytes("pred[]") == 1

    def test_scope_of_drops_jit_frames(self):
        assert scope_of("jit(f)/jit(main)/attn/psum") == "attn/psum"
        assert scope_of(None) == ""

    def test_synthetic_module(self):
        mod = parse_hlo_module(_SYNTH)
        assert mod.is_scheduled
        assert mod.input_output_aliases == [(0, 0)]
        e = mod.entry
        assert [p.param_number for p in e.params] == [0, 1]
        add = e.by_name()["add"]
        assert add.scope == "layer/add"
        assert add.nbytes == 4096
        assert e.root.name == "out"

    def test_while_attr_list_does_not_bleed(self):
        # `condition=%cond, body=%body` must parse as two names, not
        # one comma-slurped blob (the bug that hid every while body
        # from the estimator)
        mod = parse_hlo_module(_WHILE_HLO)
        w = mod.entry.by_name()["w"]
        assert w.called == ["cond", "body"]
        assert set(mod.computations) == {"body", "cond", "main"}


class TestMemoryEstimator:
    def test_synthetic_estimate(self):
        est = estimate_from_hlo_text(_SYNTH)
        # params (2 x 4 KiB, live throughout) + add & mul both live at
        # the mul; the ROOT writes in place over donated p0
        assert est.argument_bytes == 8192
        assert est.aliased_bytes == 4096
        assert est.peak_bytes == 8192 + 8192
        assert est.top_live[0][0] == 4096

    def test_undonated_synthetic_costs_one_more_buffer(self):
        text = _SYNTH.replace(
            "input_output_alias={ {}: (0, {}, may-alias) }, ", "")
        est = estimate_from_hlo_text(text)
        assert est.aliased_bytes == 0
        # at the ROOT: params + add + mul + the (now undonated) output
        assert est.peak_bytes == 8192 + 8192 + 4096

    def test_while_carry_counted_once(self):
        # XLA aliases a while's init, body carry and result into one
        # allocation: one 1 KiB carry + the tiny loop counter, not two
        # or three copies
        est = estimate_from_hlo_text(_WHILE_HLO)
        assert 256 * 4 <= est.peak_bytes <= 256 * 4 + 64


# -- canonical programs vs the committed baseline ----------------------------


@pytest.fixture(scope="module")
def canonical_reports():
    from apex_tpu.transformer import parallel_state
    reports = {}
    for prog in canonical_programs():
        reports[prog.name] = lint(prog)
    parallel_state.destroy_model_parallel()
    return reports


class TestCanonical:
    def test_all_six_lint(self, canonical_reports):
        assert set(canonical_reports) == set(BUILDERS)
        for rep in canonical_reports.values():
            assert isinstance(rep, LintReport)
            assert rep.analyzers            # something actually ran

    def test_committed_baseline_accepts_everything(self,
                                                   canonical_reports):
        baseline = load_baseline(BASELINE)
        for name, rep in canonical_reports.items():
            fresh = rep.new_findings(baseline.get(name, []))
            assert fresh == [], (
                f"{name}: new findings vs committed baseline: "
                f"{[f.key for f in fresh]}")

    def test_donation_clean_after_fixes(self, canonical_reports):
        # the applied fixes: decode donates the cache, the guarded step
        # donates the train state, both train steps donate params + opt
        for name, rep in canonical_reports.items():
            assert "donation/missing" not in _rules(rep), name

    def test_memory_estimates_within_1p5x_of_xla(self,
                                                 canonical_reports):
        for name, rep in canonical_reports.items():
            m = rep.memory
            assert m is not None and m.peak_bytes > 0, name
            if m.xla_ratio is None:
                continue
            assert 1 / 1.5 <= m.xla_ratio <= 1.5, (
                f"{name}: estimate {m.peak_bytes} vs XLA "
                f"{m.xla_peak_bytes} ({m.xla_ratio:.2f}x)")

    def test_reports_carry_provenance(self, canonical_reports):
        rep = canonical_reports["gpt_train_tp_sp"]
        assert any("mlp" in f.scope for f in rep.findings)


# -- findings + baseline bookkeeping -----------------------------------------


class TestBaseline:
    def _reports(self):
        f1 = Finding(rule="a/x", severity="warning", message="m",
                     scope="s1", details={"bytes": 123})
        f2 = Finding(rule="a/y", severity="error", message="m2",
                     scope="s2")
        return [LintReport(program="p", findings=[f1, f2])]

    def test_roundtrip_and_details_excluded_from_key(self, tmp_path):
        path = str(tmp_path / "b.json")
        save_baseline(path, self._reports())
        loaded = load_baseline(path)
        assert loaded == {"p": ["a/x|s1", "a/y|s2"]}
        # a size change does not churn the key
        again = Finding(rule="a/x", severity="warning", message="m",
                        scope="s1", details={"bytes": 999})
        assert again.key in loaded["p"]

    def test_new_findings_gate(self):
        (rep,) = self._reports()
        assert rep.new_findings([]) != []
        assert rep.new_findings([f.key for f in rep.findings]) == []

    def test_version_mismatch_raises(self, tmp_path):
        path = tmp_path / "b.json"
        path.write_text(json.dumps({"version": 99, "programs": {}}))
        with pytest.raises(ValueError, match="version"):
            load_baseline(str(path))

    def test_severity_validated(self):
        with pytest.raises(ValueError, match="severity"):
            Finding(rule="r", severity="fatal", message="m")


# -- the applied donation fixes stay bitwise-clean ---------------------------


def _tiny_model():
    from apex_tpu.models.gpt import GPTConfig, GPTModel
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                    num_attention_heads=4, max_seq_len=16)
    model = GPTModel(cfg)
    return model, model.init_params(jax.random.PRNGKey(0))


class TestAppliedFixes:
    def test_engine_decode_donation_bitwise_vs_undonated(self):
        from apex_tpu.inference.engine import InferenceEngine, Request

        model, params = _tiny_model()

        def run(donate):
            eng = InferenceEngine(model, params, max_slots=2,
                                  cache_dtype=jnp.float32)
            if not donate:       # reference: the pre-fix undonated jit
                eng._decode = jax.jit(model.decode_step)
            for rid, prompt in ((1, [1, 2, 3]), (2, [4, 5])):
                eng.submit(Request(request_id=rid, prompt=prompt,
                                   max_new_tokens=6))
            return {r.request_id: r.tokens for r in eng.run()}

        assert run(True) == run(False)

    def test_engine_decode_lint_before_after(self):
        # the lint evidence that motivated the fix: without donation
        # the decode step holds the cache twice
        from apex_tpu.analysis.canonical import make_decode
        prog = make_decode(1)
        fixed = lint(prog)
        broken = lint(LintProgram("decode_undonated", fn=prog.fn,
                                  args=prog.args))
        assert "donation/missing" in _rules(broken)
        assert "donation/missing" not in _rules(fixed)
        cache_bytes = int(np.prod(prog.args[2].shape)) * 4
        assert fixed.memory.aliased_bytes >= cache_bytes
        assert broken.memory.peak_bytes > fixed.memory.peak_bytes

    def test_guard_donate_bitwise_parity(self):
        from apex_tpu.optimizers import FusedAdam
        from apex_tpu.resilience import GuardedTrainStep

        model, params = _tiny_model()
        rng = np.random.RandomState(7)
        batches = [(jnp.asarray(rng.randint(0, 32, (2, 16))),
                    jnp.asarray(rng.randint(0, 32, (2, 16))))
                   for _ in range(3)]

        def drive(donate):
            guard = GuardedTrainStep(model.loss, FusedAdam(lr=1e-3),
                                     donate=donate)
            # fresh buffers per run: the donated path consumes them
            p = jax.tree_util.tree_map(jnp.array, params)
            o = guard.optimizer.init(p)
            g = guard.init_state()
            for i, (tk, tg) in enumerate(batches):
                res = guard(p, o, g, tk, tg, step=i)
                p, o, g = res.params, res.opt_state, res.guard_state
            return p, res.loss_value

        p_don, loss_don = drive(True)
        p_ref, loss_ref = drive(False)
        assert loss_don == loss_ref
        for a, b in zip(jax.tree_util.tree_leaves(p_don),
                        jax.tree_util.tree_leaves(p_ref), strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- comms scope attribution (satellite) -------------------------------------


class TestCommsScope:
    def test_collective_ops_carry_scope(self):
        from apex_tpu.observability.comms import (collective_stats,
                                                  format_stats)

        mesh = jax.make_mesh((4,), ("tp",), devices=jax.devices()[:4])

        def f(x):
            with jax.named_scope("attn"):
                y = jax.lax.psum(x * 2, "tp")
            with jax.named_scope("mlp"):
                z = jax.lax.all_gather(x, "tp")
            return y, z

        g = shard_map_compat(f, mesh=mesh, in_specs=P("tp"),
                             out_specs=(P(), P("tp")))
        st = collective_stats(g, jnp.ones((8, 16)))
        assert any("attn" in op["scope"]
                   for op in st["all_reduce"]["ops"])
        assert any("mlp" in op["scope"]
                   for op in st["all_gather"]["ops"])
        table = format_stats(st, by_scope=True)
        assert "attn" in table and "all_reduce" in table

    def test_synthetic_scope_parse(self):
        from apex_tpu.observability.comms import hlo_collective_stats
        line = ('  %ar = f32[64]{0} all-reduce(f32[64]{0} %x), '
                'replica_groups={{0,1}}, to_apply=%sum, '
                'metadata={op_name="jit(step)/jit(main)/layer0/psum"}')
        st = hlo_collective_stats("HloModule m\n" + line)
        (op,) = st["all_reduce"]["ops"]
        assert op["scope"] == "layer0/psum"
        assert op["bytes"] == 256
        assert op["group_size"] == 2


# -- the CLI -----------------------------------------------------------------


class TestCli:
    def test_lint_graph_json_and_gate(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_graph.py"),
             "--programs", "decode,prefill", "--json"],
            capture_output=True, text=True, env=env, cwd=str(tmp_path),
            timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        doc = json.loads(out.stdout)
        names = [p["program"] for p in doc["programs"]]
        assert names == ["decode", "prefill"]
        for p in doc["programs"]:
            assert p["memory"]["peak_bytes"] > 0
            assert p["elapsed_s"] < 10.0
        assert doc["new_findings"] == {}
