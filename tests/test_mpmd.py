"""Cross-pod MPMD pipeline (ISSUE 14): plan validation, two-tier cost
model, DCN channel + faults, schedules, and the engine's bitwise parity
against the single-mesh ring engine."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

try:
    from jax import shard_map
except ImportError:  # jax < 0.6 keeps it in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import (GPTConfig, GPTModel, _is_sharded,
                                 _is_spec_leaf, pack_for_shard_map,
                                 pipeline_step)
from apex_tpu.mpmd import (SCHEDULES, DcnTimeout, Edge, LocalDcnChannel,
                           MpmdPipeline, Op, edge_link_classes,
                           merge_stage_ops, schedule_1f1b,
                           schedule_dcn_hiding, simulate, stage_ops_1f1b,
                           validate_order)
from apex_tpu.mpmd.engine import MPMD_PLAN_FILE
from apex_tpu.parallel.plan import ParallelPlan
from apex_tpu.resilience.faults import (FAULT_KINDS, Fault, FaultInjector,
                                        seeded_schedule)


# ---------------------------------------------------------------------------
# ParallelPlan cross-pod validation (each message pinned)
# ---------------------------------------------------------------------------


def test_plan_n_pods_must_divide_pp():
    with pytest.raises(ValueError, match=r"n_pods \(3\) must divide pp"):
        ParallelPlan(pp=4, n_pods=3)


def test_plan_n_pods_positive_int():
    with pytest.raises(ValueError, match="n_pods must be a positive int"):
        ParallelPlan(n_pods=0)


def test_plan_n_pods_rejects_interleaving():
    with pytest.raises(ValueError,
                       match="does not compose with n_pods"):
        ParallelPlan(pp=4, n_pods=2, n_virtual=2, n_microbatches=4)


def test_plan_stage_plans_need_pods():
    with pytest.raises(ValueError,
                       match="stage_plans given but n_pods is 1"):
        ParallelPlan(pp=2, stage_plans=(ParallelPlan(), ParallelPlan()))


def test_plan_stage_plans_count_must_match():
    with pytest.raises(ValueError,
                       match="has 1 entries but n_pods is 2"):
        ParallelPlan(pp=2, n_pods=2, stage_plans=(ParallelPlan(),))


def test_plan_stage_plans_must_be_intra_pod():
    with pytest.raises(ValueError, match=r"stage_plans\[0\] must be an "
                                         "intra-pod SPMD plan"):
        ParallelPlan(pp=2, n_pods=2,
                     stage_plans=(ParallelPlan(pp=2, n_microbatches=2),
                                  ParallelPlan()))


def test_plan_stage_plans_dp_must_match():
    with pytest.raises(ValueError, match=r"stage_plans\[1\].dp \(2\) "
                                         "must equal"):
        ParallelPlan(dp=1, pp=2, n_pods=2,
                     stage_plans=(ParallelPlan(dp=1),
                                  ParallelPlan(dp=2)))


def test_plan_stage_plans_not_a_sequence():
    with pytest.raises(ValueError, match="must be a sequence"):
        ParallelPlan(pp=2, n_pods=2, stage_plans=ParallelPlan())


def test_plan_cross_pod_dict_round_trip():
    plan = ParallelPlan(dp=2, pp=4, n_microbatches=4, n_pods=2,
                        stage_plans=(
                            ParallelPlan(dp=2),
                            ParallelPlan(dp=2, tp=2,
                                         sequence_parallel=True)))
    back = ParallelPlan.from_dict(plan.to_dict())
    assert back == plan
    assert back.stage_plans[1].tp == 2
    # heterogeneous pods: 2 stages/pod x (2*1 + 2*2) devices
    assert plan.n_devices == 2 * (2 + 4)
    assert "pods=2" in plan.describe()


def test_plan_single_pod_dict_stays_pre_mpmd():
    d = ParallelPlan(dp=2).to_dict()
    assert "n_pods" not in d and "stage_plans" not in d


# ---------------------------------------------------------------------------
# dcn_fault kind: appended last, byte-identical schedules, consume-once
# ---------------------------------------------------------------------------


def test_dcn_fault_precedes_later_appended_kinds():
    # dcn_fault was appended last in its PR; later kinds (cost_drift,
    # plan_regression) append AFTER it, never before — rate-0 kinds
    # consume no rng, so the relative order is what keeps every
    # pre-existing from_seed schedule byte-identical.
    assert FAULT_KINDS.index("dcn_fault") == len(FAULT_KINDS) - 3
    assert FAULT_KINDS[-2:] == ("cost_drift", "plan_regression")


def test_dcn_fault_rate0_consumes_no_rng():
    # schedules for the pre-existing kinds must be byte-identical
    # whether or not the dcn_fault kind exists in the key list
    rates = {"nan_grads": 0.2, "preempt_at_step": 0.1}
    old = seeded_schedule(3, 50, FAULT_KINDS[:-1], rates)
    new = seeded_schedule(3, 50, FAULT_KINDS, rates)
    assert old == new
    inj = FaultInjector.from_seed(3, 50, rates)
    assert [(f.step, f.kind) for f in inj.schedule] == old


def test_check_dcn_consumes_once():
    inj = FaultInjector([Fault(4, "dcn_fault")])
    assert inj.check_dcn(3) is None
    f = inj.check_dcn(4)
    assert f is not None and f.kind == "dcn_fault"
    assert inj.check_dcn(4) is None            # consumed: retry runs clean
    assert inj.log == [(4, "dcn_fault")]


# ---------------------------------------------------------------------------
# the DCN channel
# ---------------------------------------------------------------------------


def test_channel_send_is_byte_exact_and_accounted():
    ch = LocalDcnChannel(alpha_s=1e-3, beta_s_per_byte=1e-9)
    x = {"a": jnp.arange(6, dtype=jnp.float32),
         "b": jnp.ones((2, 3), jnp.int32)}
    out = ch.send(x, step=0, edge=Edge(0, 1, "dcn"))
    for a, b in zip(jax.tree_util.tree_leaves(out),
                    jax.tree_util.tree_leaves(x), strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert ch.sends == 1
    assert ch.bytes_sent == 6 * 4 + 6 * 4
    assert ch.simulated_seconds == pytest.approx(
        1e-3 + 1e-9 * ch.bytes_sent)


def test_channel_ici_edge_never_faults_or_bills():
    inj = FaultInjector([Fault(0, "dcn_fault")])
    ch = LocalDcnChannel(alpha_s=1.0, fault_injector=inj)
    ch.send(jnp.zeros(4), step=0, edge=Edge(0, 1, "ici"))
    assert ch.simulated_seconds == 0.0
    assert inj.log == []                        # fault left un-consumed


def test_channel_retry_recovers_one_fault():
    inj = FaultInjector([Fault(2, "dcn_fault")])
    ch = LocalDcnChannel(fault_injector=inj, max_retries=2)
    out = ch.send_with_retry(jnp.arange(4), step=2, edge=Edge(0, 1))
    np.testing.assert_array_equal(np.asarray(out), np.arange(4))
    assert ch.retries == 1 and ch.sends == 1
    assert inj.log == [(2, "dcn_fault")]


def test_channel_retry_budget_exhausts():
    inj = FaultInjector([Fault(0, "dcn_fault") for _ in range(5)])
    ch = LocalDcnChannel(fault_injector=inj, max_retries=1)
    with pytest.raises(DcnTimeout) as e:
        ch.send_with_retry(jnp.zeros(2), step=0, edge=Edge(1, 2))
    assert e.value.attempt == 1 and e.value.edge.src == 1
    assert ch.retries == 2


def test_channel_places_on_dst_sharding():
    dev = jax.devices()[1]
    sh = jax.sharding.SingleDeviceSharding(dev)
    ch = LocalDcnChannel()
    out = ch.send(jnp.arange(3), sh)
    assert out.devices() == {dev}


def test_channel_from_cost_model():
    from apex_tpu.observability.costmodel import (
        fit_cost_model, simulate_link_measurements)
    model = fit_cost_model(simulate_link_measurements(1e-3, 1e-8))
    ch = LocalDcnChannel.from_cost_model(model)
    assert ch.alpha_s == pytest.approx(1e-3, rel=1e-3)
    assert ch.beta_s_per_byte == pytest.approx(1e-8, rel=1e-3)


# ---------------------------------------------------------------------------
# two-tier cost model (link_class) round trip
# ---------------------------------------------------------------------------


def test_costmodel_link_class_fits_and_fallback(tmp_path):
    from apex_tpu.observability.costmodel import (
        Measurement, fit_cost_model, load_profile)
    ms = ([Measurement("ppermute", "f32", 2, 1 << 14, 1e-5)]
          + [Measurement("ppermute", "f32", 2, n, 1e-3 + 1e-8 * n,
                         link_class="dcn")
             for n in (1 << 12, 1 << 16, 1 << 20)])
    model = fit_cost_model(ms)
    assert model.link_classes == ("dcn", "ici")
    slow = model.predict("ppermute", 1 << 16, 2, link_class="dcn")
    fast = model.predict("ppermute", 1 << 16, 2)
    assert slow > 10 * fast
    # un-probed link class falls back to ici curves
    assert model.predict("ppermute", 1 << 16, 2,
                         link_class="pcie") == pytest.approx(fast)
    path = os.path.join(tmp_path, "profile.json")
    model.save(path, measurements=ms)
    loaded, back = load_profile(path)
    assert loaded.curves().keys() == model.curves().keys()
    assert {m.link_class for m in back} == {"ici", "dcn"}


def test_costmodel_pre_link_class_measurement_loads_as_ici():
    from apex_tpu.observability.costmodel import Measurement
    m = Measurement.from_dict({"op": "psum", "dtype": "f32",
                               "group_size": 4, "nbytes": 1024,
                               "time_s": 1e-5})
    assert m.link_class == "ici"


def test_comms_probe_simulate_dcn_cli(tmp_path):
    from tools.comms_probe import main
    out = os.path.join(tmp_path, "profile.json")
    rc = main(["--out", out, "--ops", "ppermute", "--dtypes", "f32",
               "--sizes", "4096,65536", "--groups", "2", "--iters", "1",
               "--rounds", "1", "--holdout", "0",
               "--simulate-dcn", "1e-3,1e-8", "--quiet"])
    assert rc in (0, None)
    from apex_tpu.observability.costmodel import load_profile
    model, ms = load_profile(out)
    assert "dcn" in model.link_classes and "ici" in model.link_classes
    assert any(m.link_class == "dcn" for m in ms)


# ---------------------------------------------------------------------------
# schedules + simulator
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("S,M", [(2, 2), (2, 4), (4, 8), (3, 5)])
@pytest.mark.parametrize("name", ["1f1b", "dcn_hiding"])
def test_schedules_are_valid_orders(S, M, name):
    order = SCHEDULES[name](S, M)
    validate_order(order, S, M)
    assert len(order) == 2 * S * M


def test_1f1b_warmup_depth():
    # warmup of S-1-s fwds, then the steady state opens with one more
    # fwd before the first bwd: S-s leading fwds per stage
    per_stage = stage_ops_1f1b(4, 8)
    for s, ops in enumerate(per_stage):
        warm = 0
        for op in ops:
            if op.kind != "fwd":
                break
            warm += 1
        assert warm == 4 - s


def test_backwards_drain_in_ascending_microbatch_order():
    # the ring accumulates grads ascending m; both schedules must
    # replay that per-stage order for bitwise parity
    for name in SCHEDULES:
        for op_list in (SCHEDULES[name](2, 4), SCHEDULES[name](4, 4)):
            by_stage = {}
            for op in op_list:
                if op.kind == "bwd":
                    by_stage.setdefault(op.stage, []).append(op.mb)
            for mbs in by_stage.values():
                assert mbs == sorted(mbs)


def test_merge_stage_ops_deadlock_raises():
    bad = [[Op(0, "bwd", 0), Op(0, "fwd", 0)],
           [Op(1, "fwd", 0), Op(1, "bwd", 0)]]
    with pytest.raises(ValueError, match="deadlock"):
        merge_stage_ops(bad)


def test_validate_order_pins_violations():
    with pytest.raises(ValueError, match="before upstream fwd"):
        validate_order([Op(1, "fwd", 0)], 2, 1)
    with pytest.raises(ValueError, match="before its own fwd"):
        validate_order([Op(1, "bwd", 0)], 2, 1)
    with pytest.raises(ValueError, match="issued twice"):
        validate_order([Op(0, "fwd", 0), Op(0, "fwd", 0)], 1, 1)
    with pytest.raises(ValueError, match="want 4"):
        validate_order([Op(0, "fwd", 0), Op(0, "bwd", 0)], 1, 2)


def test_edge_link_classes_two_tier():
    assert edge_link_classes(4, 2) == {0: "ici", 1: "dcn", 2: "ici"}
    assert edge_link_classes(4, 1) == {0: "ici", 1: "ici", 2: "ici"}
    assert edge_link_classes(2, 2) == {0: "dcn"}
    with pytest.raises(ValueError, match="must divide"):
        edge_link_classes(4, 3)


def test_simulator_no_links_matches_analytic_bubble():
    S, M = 4, 8
    sim = simulate(schedule_1f1b(S, M), S, M, t_fwd=1.0, t_bwd=2.0)
    # ideal 1F1B with t_bwd = 2*t_fwd: makespan = (M + S - 1) * 3
    assert sim["makespan"] == pytest.approx((M + S - 1) * 3.0)
    assert sim["bubble_fraction"] == pytest.approx(
        (S - 1) / (M + S - 1))
    assert sim["hidden_fraction"] == {"ici": 1.0, "dcn": 1.0}


def test_dcn_hiding_beats_blocking_1f1b_under_slow_link():
    S, M = 4, 8
    classes = edge_link_classes(S, 2)
    link = {e: (1.5 if lc == "dcn" else 0.05)
            for e, lc in classes.items()}
    base = simulate(schedule_1f1b(S, M), S, M, t_fwd=1.0, t_bwd=2.0,
                    link_seconds=link, link_classes=classes,
                    blocking_sends=True)
    tuned = simulate(schedule_dcn_hiding(S, M), S, M, t_fwd=1.0,
                     t_bwd=2.0, link_seconds=link, link_classes=classes,
                     blocking_sends=False)
    assert tuned["bubble_fraction"] < base["bubble_fraction"]
    assert tuned["makespan"] < base["makespan"]
    # some (not necessarily all) DCN time stays hidden under compute
    assert 0.0 < tuned["hidden_fraction"]["dcn"] <= 1.0


# ---------------------------------------------------------------------------
# the engine: bitwise parity, faults, checkpoints, tracing
# ---------------------------------------------------------------------------

_KW = dict(vocab_size=32, hidden_size=16, num_layers=4,
           num_attention_heads=4, max_seq_len=16)
_DP, _S, _M, _MB, _SEQ = 2, 2, 4, 2, 16


def _data():
    rng = np.random.RandomState(11)
    tokens = jnp.asarray(rng.randint(0, 32, (_DP * _M * _MB, _SEQ)))
    targets = jnp.asarray(rng.randint(0, 32, (_DP * _M * _MB, _SEQ)))
    return tokens, targets


def _ring_reference(model, params, tokens, targets):
    packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
        model, params, n_stages=_S, tensor_axis=None)
    mesh = jax.make_mesh((_DP, _S), ("data", "pipe"),
                         devices=jax.devices()[:_DP * _S])

    def grad_step(sp, tk, tg):
        tk = tk.reshape(_M, _MB, _SEQ)
        tg = tg.reshape(_M, _MB, _SEQ)
        loss, g = pipeline_step(model, local_fn(sp), tk, tg,
                                pipe_axis="pipe", data_axis="data")
        return loss, repack_fn(g)

    return jax.jit(shard_map(
        grad_step, mesh=mesh,
        in_specs=(in_specs, P("data"), P("data")),
        out_specs=(P(), in_specs)))(packed, tokens, targets)


@pytest.fixture(scope="module")
def parity_run():
    model = GPTModel(GPTConfig(**_KW))
    params = model.init_params(jax.random.PRNGKey(11))
    tokens, targets = _data()
    ring_loss, ring_grads = _ring_reference(model, params, tokens,
                                            targets)
    plan = ParallelPlan(dp=_DP, pp=_S, n_microbatches=_M, n_pods=_S)
    inj = FaultInjector([Fault(0, "dcn_fault")])
    eng = MpmdPipeline(_KW, params, plan,
                       devices=jax.devices()[:_DP * _S],
                       fault_injector=inj, schedule="dcn_hiding",
                       trace=True)
    loss, grads = eng.loss_and_grads(tokens, targets, step=0)
    return dict(model=model, ring_loss=ring_loss, ring_grads=ring_grads,
                eng=eng, inj=inj, loss=loss, grads=grads,
                tokens=tokens, targets=targets)


def test_engine_loss_bitwise_vs_ring(parity_run):
    assert (np.float32(parity_run["loss"]).tobytes()
            == np.float32(parity_run["ring_loss"]).tobytes())


def test_engine_grads_bitwise_vs_ring(parity_run):
    grads, ring_grads = parity_run["grads"], parity_run["ring_grads"]
    layer_specs = parity_run["model"].partition_specs()["layers"][0]
    for i in range(_S):
        def cmp(s, a, b):
            ax = 1 if _is_sharded(s) else 0
            np.testing.assert_array_equal(
                np.take(np.asarray(a), 0, ax),
                np.take(np.asarray(b), i, ax))
        jax.tree_util.tree_map(cmp, layer_specs, grads[i]["layers"],
                               ring_grads["layers"],
                               is_leaf=_is_spec_leaf)
    # tied embedding: BOTH replicas carry the identical total gradient
    for sub in (grads[0]["embedding"], grads[-1]["embedding"]):
        for a, b in zip(jax.tree_util.tree_leaves(sub),
                        jax.tree_util.tree_leaves(
                            ring_grads["embedding"]), strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
            jax.tree_util.tree_leaves(grads[-1]["final_layernorm"]),
            jax.tree_util.tree_leaves(ring_grads["final_layernorm"]),
            strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_engine_retried_scheduled_dcn_fault(parity_run):
    # the Fault(0, "dcn_fault") dropped one transfer; the bitwise
    # results above came from the retry
    assert parity_run["eng"].channel.retries == 1
    assert (0, "dcn_fault") in parity_run["inj"].log


def test_engine_flow_chains_unbroken(parity_run):
    cont = parity_run["eng"].collector().continuity()
    assert not cont["broken"] and not cont["orphans"]
    assert len(cont["complete"]) == _M + 1   # per-microbatch + sync


def test_engine_checkpoint_kill_one_stage(parity_run, tmp_path):
    eng = parity_run["eng"]
    tokens, targets = parity_run["tokens"], parity_run["targets"]
    root = os.path.join(tmp_path, "ckpt")
    eng.save_checkpoint(root, step=0)
    assert os.path.exists(os.path.join(root, MPMD_PLAN_FILE))
    before = jax.tree_util.tree_map(np.asarray, eng.stages[0].state)
    eng.train_step(tokens, targets)
    assert eng.restore_stage(0, root) == 0
    for a, b in zip(jax.tree_util.tree_leaves(eng.stages[0].state),
                    jax.tree_util.tree_leaves(before), strict=True):
        np.testing.assert_array_equal(np.asarray(a), b)
    assert eng.restore_checkpoint(root) == 0


def test_engine_checkpoint_plan_stamp_mismatch(parity_run, tmp_path):
    eng = parity_run["eng"]
    root = os.path.join(tmp_path, "stamp")
    eng.save_checkpoint(root, step=0)
    with open(os.path.join(root, MPMD_PLAN_FILE)) as f:
        doc = json.load(f)
    doc["plan"]["n_microbatches"] = 64
    with open(os.path.join(root, MPMD_PLAN_FILE), "w") as f:
        json.dump(doc, f)
    with pytest.raises(ValueError, match="saved under cross-pod plan"):
        eng.restore_checkpoint(root)


def test_engine_rejects_bad_plans():
    model = GPTModel(GPTConfig(**_KW))
    params = model.init_params(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="MPMD needs pp >= 2"):
        MpmdPipeline(_KW, params, ParallelPlan(dp=2))
    with pytest.raises(ValueError, match="unknown schedule"):
        MpmdPipeline(_KW, params,
                     ParallelPlan(pp=2, n_microbatches=2, n_pods=2),
                     schedule="zigzag")


def test_elastic_build_rejects_cross_pod_plans():
    from apex_tpu.resilience.elastic import ElasticPlan
    with pytest.raises(ValueError, match="MpmdPipeline"):
        ElasticPlan.build(ParallelPlan(pp=2, n_microbatches=2,
                                       n_pods=2))


def test_stage_rejects_moe_and_bare_tp():
    from apex_tpu.mpmd.stage import StageProgram
    cfg = GPTConfig(n_experts=2, **_KW)
    with pytest.raises(ValueError, match="does not support MoE"):
        StageProgram(cfg, {}, stage_index=0, n_stages=2,
                     n_microbatches=2, plan=ParallelPlan(),
                     devices=jax.devices()[:1])
    cfg = GPTConfig(tensor_parallel_size=2, axis_name="model", **_KW)
    with pytest.raises(ValueError, match="require\\s+sequence_parallel"):
        StageProgram(cfg, {}, stage_index=0, n_stages=2,
                     n_microbatches=2, plan=ParallelPlan(tp=2),
                     devices=jax.devices()[:2])


# ---------------------------------------------------------------------------
# the two-tier autotune planner
# ---------------------------------------------------------------------------


def test_autotune_mpmd_enumeration_and_ranking(tmp_path):
    from tools.autotune import autotune_mpmd, emit_plan, load_plan
    report = autotune_mpmd(
        8, cfg_kw=dict(_KW, num_layers=4), batch=8, n_pods=2,
        dcn=(1e-3, 1e-9), verbose=False)
    assert report["mode"] == "mpmd" and report["n_pods"] == 2
    win = ParallelPlan.from_dict(report["plan"])
    assert win.n_pods == 2 and win.pp % 2 == 0
    assert report["schedule"] in SCHEDULES
    # ranking is total order over (plan, schedule) rows
    preds = [r["predicted_s"] for r in report["ranked"]]
    assert preds == sorted(preds)
    # rejections carry reasons
    rej = [c for c in report["candidates"] if c["status"] == "rejected"]
    assert all(c["reason"] for c in rej)
    path = os.path.join(tmp_path, "plan.json")
    emit_plan(path, report)
    assert load_plan(path) == win


def test_autotune_mpmd_rejects_impossible_pods():
    from tools.autotune import autotune_mpmd
    with pytest.raises(RuntimeError, match="no valid MPMD plan"):
        autotune_mpmd(8, cfg_kw=dict(_KW, num_layers=4), batch=8,
                      n_pods=5, dcn=(1e-3, 1e-9), verbose=False)
