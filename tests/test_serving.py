"""apex_tpu.serving: paged KV cache, paged engine, scheduler, router.

The serving tier's correctness contract:

* the block pool's allocator/refcount/trie bookkeeping is exact (block
  counts, prefix sharing, LRU eviction, copy-on-write);
* paged decode attention equals the contiguous decode path BITWISE on
  the jnp route (same reference math over a gathered pool) and within
  kernel tolerance under forced-Pallas interpret mode;
* the paged engine's outputs are token-identical to the contiguous
  engine for greedy AND seeded stochastic sampling — with prefix
  sharing on, with chunked prefill, with speculative decoding, and
  across a ``preempt()`` requeue;
* the router places by load, sheds when every replica is overloaded,
  and honors SLO burn-rate pressure.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import (InferenceEngine, Request, SamplingParams)
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.observability.slo import SLOMonitor, SLOTarget
from apex_tpu.ops.flash_attention import (
    flash_attention_chunk_paged,
    flash_attention_decode_paged,
    flash_attention_decode_reference,
    gather_paged_kv,
)
from apex_tpu.serving import (PagedInferenceEngine, PagedKVCache,
                              RequestShed, Router, SpeculativeConfig,
                              TickScheduler)
from apex_tpu.utils import set_force_pallas
from apex_tpu.utils.profiling import ServingMetrics


def tiny_cfg(**kw):
    base = dict(vocab_size=32, hidden_size=16, num_layers=2,
                num_attention_heads=2, max_seq_len=16)
    base.update(kw)
    return GPTConfig(**base)


@pytest.fixture(scope="module")
def tiny():
    model = GPTModel(tiny_cfg())
    return model, model.init_params(jax.random.PRNGKey(0))


def _clone(req: Request) -> Request:
    return dataclasses.replace(req)


def _mixed_requests(vocab=32):
    """Greedy + seeded-stochastic (temp / top-k / top-p) in one batch —
    the full sampling surface the parity guarantee covers."""
    return [
        Request(0, [1, 2, 3, 4, 5], max_new_tokens=6),
        Request(1, [1, 2, 3, 9], max_new_tokens=5, seed=7,
                sampling=SamplingParams(temperature=0.8, top_k=5)),
        Request(2, [1, 2, 3, 4, 5, 6, 7], max_new_tokens=4, seed=3,
                sampling=SamplingParams(temperature=1.1, top_p=0.9)),
        Request(3, [4, 4, 4], max_new_tokens=5, seed=11,
                sampling=SamplingParams(temperature=1.0, top_k=8,
                                        top_p=0.8)),
    ]


def _run(engine, reqs):
    for r in reqs:
        engine.submit(_clone(r))
    return {r.request_id: (r.tokens, r.finish_reason)
            for r in engine.run()}


# -- block pool --------------------------------------------------------------

class TestPagedKVCache:
    def _pool(self, blocks=9, bs=4, **kw):
        return PagedKVCache(blocks, bs, layers=2, kv_heads=2, head_dim=4,
                            dtype=jnp.float32, **kw)

    def test_accounting_and_reserved_block(self):
        p = self._pool()
        assert p.usable_blocks == 8 and p.free_blocks == 8
        seq = p.acquire([1] * 10)                # 3 blocks
        assert p.used_blocks == 3 and p.free_blocks == 5
        assert p.free_bytes() == 5 * p.block_bytes
        assert p.occupancy() == pytest.approx(3 / 8)
        p.release(seq)
        assert p.used_blocks == 0 and p.free_blocks == 8
        # block 0 is the garbage block: never handed out
        assert 0 not in seq.block_ids

    def test_prefix_sharing_stores_shared_blocks_once(self):
        p = self._pool(blocks=17)
        sysp = [1, 2, 3, 4, 5, 6, 7, 8]          # 2 full blocks
        a = p.acquire(sysp + [9])
        p.register_prefix(a, sysp + [9])
        b = p.acquire(sysp + [10])
        # b reuses a's two full prefix blocks, allocates only its tail
        assert b.shared_tokens == 8
        assert b.block_ids[:2] == a.block_ids[:2]
        assert p.used_blocks == 3 + 1            # NOT 3 + 3
        assert p.shared_blocks == 2
        assert p.prefix_hit_tokens == 8

    def test_prefix_cap_leaves_one_token_to_compute(self):
        p = self._pool()
        ctx = [1, 2, 3, 4, 5, 6, 7, 8]
        a = p.acquire(ctx)
        p.register_prefix(a, ctx)
        b = p.acquire(ctx)                       # fully cached context
        # capped at (n-1)//bs blocks: the last token stays uncached so
        # admission still has logits to sample from
        assert b.shared_tokens == 4

    def test_trie_retention_and_lru_eviction(self):
        p = self._pool(blocks=5, bs=4)           # 4 usable
        a = p.acquire([1] * 8)                   # 2 blocks
        p.register_prefix(a, [1] * 8)
        p.release(a)
        assert p.used_blocks == 2                # trie retains the KV
        # demand for 4 blocks forces LRU leaf eviction of the cached pair
        b = p.acquire([9] * 16)
        assert b is not None and len(b.block_ids) == 4
        assert p.evicted_blocks == 2
        assert p.acquire([5] * 4) is None        # truly exhausted

    def test_fork_copy_on_write(self):
        p = self._pool()
        a = p.acquire([1, 2, 3, 4, 5])
        b = p.fork(a)
        assert b.block_ids == a.block_ids
        tail = len(a.block_ids) - 1
        shared_id = a.block_ids[tail]
        new = p.ensure_writable(b, tail)
        assert new != shared_id and b.block_ids[tail] == new
        assert a.block_ids[tail] == shared_id    # a untouched
        assert p.cow_copies == 1
        # exclusive block: writable in place, no copy
        assert p.ensure_writable(a, tail) == shared_id
        assert p.cow_copies == 1

    def test_gauges_exported(self):
        from apex_tpu.observability import MetricsRegistry
        reg = MetricsRegistry()
        p = self._pool(registry=reg)
        p.acquire([1] * 10)
        text = reg.prometheus()
        assert "serving_paged_blocks_used" in text
        assert 'cache="pool0"' in text


# -- paged attention kernels -------------------------------------------------

class TestPagedAttention:
    def _paged(self, rng, b=3, nb=4, bs=8, h=2, d=16, pool_blocks=32):
        pool_k = jnp.asarray(rng.randn(pool_blocks, bs, h, d), jnp.float32)
        pool_v = jnp.asarray(rng.randn(pool_blocks, bs, h, d), jnp.float32)
        tables = jnp.asarray(
            rng.choice(pool_blocks, size=(b, nb), replace=False)
            .reshape(b, nb), jnp.int32)
        q = jnp.asarray(rng.randn(b, h, d), jnp.float32)
        lens = jnp.asarray([1, 17, nb * bs], jnp.int32)
        return q, pool_k, pool_v, tables, lens

    def test_gather_layout(self, rng):
        q, pk, pv, tbl, lens = self._paged(rng)
        g = gather_paged_kv(pk, tbl)
        b, nb = tbl.shape
        bs = pk.shape[1]
        for i in range(b):
            for p in (0, 9, nb * bs - 1):
                np.testing.assert_array_equal(
                    np.asarray(g[i, p]),
                    np.asarray(pk[int(tbl[i, p // bs]), p % bs]))

    def test_jnp_path_bitwise_vs_reference(self, rng):
        """Off-TPU the paged decode IS the contiguous reference over a
        gathered pool — equality is exact, not approximate."""
        q, pk, pv, tbl, lens = self._paged(rng)
        out = flash_attention_decode_paged(q, pk, pv, tbl, lens)
        ref = flash_attention_decode_reference(
            q, gather_paged_kv(pk, tbl), gather_paged_kv(pv, tbl), lens)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_pallas_interpret_matches_reference(self, rng):
        q, pk, pv, tbl, lens = self._paged(rng)
        ref = flash_attention_decode_reference(
            q, gather_paged_kv(pk, tbl), gather_paged_kv(pv, tbl), lens)
        set_force_pallas(True)
        try:
            out = flash_attention_decode_paged(q, pk, pv, tbl, lens)
        finally:
            set_force_pallas(None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_chunk_matches_per_position_decode(self, rng):
        b, nb, bs, h, d, c = 2, 3, 8, 2, 16, 4
        pk = jnp.asarray(rng.randn(16, bs, h, d), jnp.float32)
        pv = jnp.asarray(rng.randn(16, bs, h, d), jnp.float32)
        tbl = jnp.asarray(rng.choice(16, size=(b, nb), replace=False)
                          .reshape(b, nb), jnp.int32)
        q = jnp.asarray(rng.randn(b, h, c, d), jnp.float32)
        qpos = jnp.asarray([[3, 4, 5, 6], [10, 11, 12, 13]], jnp.int32)
        out = flash_attention_chunk_paged(q, pk, pv, tbl, qpos)
        gk, gv = gather_paged_kv(pk, tbl), gather_paged_kv(pv, tbl)
        for j in range(c):
            ref = flash_attention_decode_reference(
                q[:, :, j], gk, gv, qpos[:, j] + 1)
            np.testing.assert_allclose(np.asarray(out[:, :, j]),
                                       np.asarray(ref),
                                       rtol=1e-5, atol=1e-5)


# -- tick scheduler ----------------------------------------------------------

class TestTickScheduler:
    def test_budget_split_and_caps(self):
        s = TickScheduler(token_budget=32, min_chunk=4, max_chunk=16)
        plan = s.plan(8, [(0, 100), (1, 100)])
        # 8 decode tokens leave 24: head gets max_chunk, next the rest
        assert plan.chunks == {0: 16, 1: 8} and plan.decode

    def test_head_progress_guarantee(self):
        s = TickScheduler(token_budget=8, min_chunk=4, max_chunk=16)
        plan = s.plan(8, [(0, 100), (1, 100)])   # decode exceeds budget
        assert plan.chunks == {0: 4}             # head still advances

    def test_speculative_cost_accounting(self):
        s = TickScheduler(token_budget=32, min_chunk=4, max_chunk=16)
        assert s.plan(4, [(0, 100)], spec_tokens=3).chunks == {0: 16}
        assert s.plan(7, [(0, 100)], spec_tokens=3).chunks == {0: 4}

    def test_validation(self):
        with pytest.raises(ValueError):
            TickScheduler(token_budget=0)
        with pytest.raises(ValueError):
            TickScheduler(min_chunk=8, max_chunk=4)


# -- paged engine parity -----------------------------------------------------

class TestPagedEngine:
    def _ref(self, tiny, reqs, **kw):
        model, params = tiny
        return _run(InferenceEngine(model, params, max_slots=4,
                                    cache_dtype=jnp.float32, **kw), reqs)

    def test_decode_logits_bitwise(self, tiny):
        """Below the token level: the paged decode step's logits are
        BITWISE the contiguous decode step's, prompt through decode."""
        model, params = tiny
        base = InferenceEngine(model, params, max_slots=2,
                               cache_dtype=jnp.float32)
        paged = PagedInferenceEngine(model, params, max_slots=2,
                                     block_size=4,
                                     cache_dtype=jnp.float32)
        prompts = [[1, 2, 3, 4, 5], [7, 8, 9]]
        for i, pr in enumerate(prompts):
            base.submit(Request(i, pr, max_new_tokens=8))
            paged.submit(Request(i, pr, max_new_tokens=8))
        base._evict_expired(); base._admit()
        paged._evict_expired(); paged._admit()
        for _ in range(5):
            n = base.cache.slots
            toks = np.zeros((n,), np.int32)
            pos = np.zeros((n,), np.int32)
            for s, st in base._active.items():
                toks[s], pos[s] = st.next_token, st.position
                assert paged._grow(s, st.position + 1)
            bl, base.cache.data = base._decode(
                base.params, jnp.asarray(toks), base.cache.data,
                jnp.asarray(pos))
            pl, paged.pool.data = paged._decode_paged(
                paged.params, jnp.asarray(toks), paged.pool.data,
                jnp.asarray(paged._tables), jnp.asarray(pos))
            np.testing.assert_array_equal(
                np.asarray(bl).view(np.uint32),
                np.asarray(pl).view(np.uint32))
            base._advance_slots(sorted(base._active), np.asarray(bl))
            paged._advance_slots(sorted(paged._active), np.asarray(pl))

    def test_token_parity_greedy_and_seeded(self, tiny):
        model, params = tiny
        reqs = _mixed_requests()
        ref = self._ref(tiny, reqs)
        out = _run(PagedInferenceEngine(model, params, max_slots=4,
                                        block_size=4,
                                        cache_dtype=jnp.float32), reqs)
        assert out == ref

    def test_prefix_sharing_parity_and_block_savings(self, tiny):
        model, params = tiny
        sysp = [1, 2, 3, 4, 5, 6, 7, 8]
        reqs = [Request(i, sysp + [9 + i], max_new_tokens=3)
                for i in range(4)]
        shared = PagedInferenceEngine(model, params, max_slots=4,
                                      block_size=4,
                                      cache_dtype=jnp.float32)
        unshared = PagedInferenceEngine(model, params, max_slots=4,
                                        block_size=4, share_prefixes=False,
                                        cache_dtype=jnp.float32)
        for r in reqs:
            shared.submit(_clone(r)); unshared.submit(_clone(r))
        shared.step(); unshared.step()
        # the 2-block system prompt is stored ONCE, not once per request
        assert shared.pool.shared_blocks == 2
        assert shared.pool.used_blocks == unshared.pool.used_blocks - 6
        a = {r.request_id: r.tokens for r in shared.run()}
        b = {r.request_id: r.tokens for r in unshared.run()}
        assert a == b == {r.request_id: self._ref(tiny, [r])[
            r.request_id][0] for r in reqs}

    def test_chunked_prefill_parity(self, tiny):
        model, params = tiny
        reqs = _mixed_requests()
        ref = self._ref(tiny, reqs)
        out = _run(PagedInferenceEngine(
            model, params, max_slots=4, block_size=4,
            cache_dtype=jnp.float32, chunked_prefill=True,
            scheduler=TickScheduler(token_budget=8, min_chunk=2,
                                    max_chunk=4)), reqs)
        assert out == ref

    def test_speculative_parity_and_perfect_draft_accepts(self, tiny):
        model, params = tiny
        reqs = _mixed_requests()
        ref = self._ref(tiny, reqs)
        eng = PagedInferenceEngine(
            model, params, max_slots=4, block_size=4,
            cache_dtype=jnp.float32,
            speculative=SpeculativeConfig(model, params, num_tokens=2))
        out = _run(eng, reqs)
        assert out == ref
        # draft == target => every greedy proposal matches the canonical
        # stream; stochastic rows share the (seed, index) keys too
        assert eng.spec_proposed > 0
        assert eng.spec_accept_rate == 1.0

    def test_speculative_with_chunked_prefill_parity(self, tiny):
        model, params = tiny
        reqs = _mixed_requests()
        out = _run(PagedInferenceEngine(
            model, params, max_slots=4, block_size=4,
            cache_dtype=jnp.float32, chunked_prefill=True,
            speculative=SpeculativeConfig(model, params, num_tokens=3)),
            reqs)
        assert out == self._ref(tiny, reqs)

    def test_speculative_config_validation(self, tiny):
        model, params = tiny
        with pytest.raises(ValueError):
            SpeculativeConfig(model, params, num_tokens=0)
        other = GPTModel(tiny_cfg(vocab_size=64))
        with pytest.raises(ValueError):
            SpeculativeConfig(other, params).validate_against(model)

    def test_block_size_must_divide_max_seq(self, tiny):
        model, params = tiny
        with pytest.raises(ValueError):
            PagedInferenceEngine(model, params, block_size=5)

    def test_kv_gauges_exported(self, tiny):
        model, params = tiny
        eng = PagedInferenceEngine(model, params, max_slots=2,
                                   block_size=4)
        eng.submit(Request(0, [1, 2, 3], max_new_tokens=2))
        eng.step()
        text = eng.metrics.registry.prometheus()
        assert "serving_kv_free_bytes" in text
        assert "serving_paged_blocks_used" in text


# -- preemption x paged cache (resilience satellite) -------------------------

class TestPagedPreemption:
    def test_preempt_releases_blocks_and_resumes_token_identical(
            self, tiny):
        model, params = tiny
        reqs = [Request(i, [1 + i, 2, 3, 4, 5], max_new_tokens=8)
                for i in range(2)]
        ref = _run(InferenceEngine(model, params, max_slots=2,
                                   cache_dtype=jnp.float32), reqs)
        eng = PagedInferenceEngine(model, params, max_slots=2,
                                   block_size=4, cache_dtype=jnp.float32)
        for r in reqs:
            eng.submit(_clone(r))
        eng.step(); eng.step()
        held = {b for s in eng._seqs.values() for b in s.block_ids}
        before = eng.pool.used_blocks
        assert eng.preempt() == 2
        assert eng.active_requests == 0
        # exclusive blocks returned; only trie-retained prefix blocks
        # (ref held by the trie alone, so not "shared") may remain
        assert eng.pool.used_blocks < before
        # resume: re-acquired tables may differ, tokens must not
        out = {r.request_id: (r.tokens, r.finish_reason)
               for r in eng.run()}
        assert out == ref
        assert held  # sanity: the engine really was holding blocks

    def test_pool_pressure_preempts_victim_and_recovers(self, tiny):
        """An undersized pool forces mid-decode preemption of the most
        recently admitted request; everything still completes with the
        contiguous engine's exact tokens."""
        model, params = tiny
        reqs = [Request(i, [1 + i, 2, 3, 4, 5], max_new_tokens=8)
                for i in range(3)]
        ref = _run(InferenceEngine(model, params, max_slots=3,
                                   cache_dtype=jnp.float32), reqs)
        eng = PagedInferenceEngine(model, params, max_slots=3,
                                   block_size=4, num_blocks=7,
                                   cache_dtype=jnp.float32)
        for r in reqs:
            eng.submit(_clone(r))
        out = {r.request_id: (r.tokens, r.finish_reason)
               for r in eng.run(max_steps=500)}
        assert out == ref
        assert eng.metrics.requeued > 0          # pressure really hit


# -- router ------------------------------------------------------------------

class _StubEngine:
    """Router-surface stub: queue/active/metrics without device work."""

    def __init__(self, depth=0, active=0, slo=None, max_queue=None):
        self._q = depth
        self._a = active
        self.metrics = ServingMetrics(slo=slo)
        self.max_queue = max_queue
        self.submitted = []

    @property
    def queue_depth(self):
        return self._q

    @property
    def active_requests(self):
        return self._a

    def submit(self, request):
        from apex_tpu.inference.engine import QueueFull
        if self.max_queue is not None and self._q >= self.max_queue:
            raise QueueFull("full")
        self.submitted.append(request)
        self._q += 1


class TestRouter:
    def test_places_least_loaded(self):
        a, b = _StubEngine(depth=3, active=2), _StubEngine(depth=0,
                                                           active=1)
        r = Router([a, b], max_queue_depth=8)
        assert r.submit(Request(0, [1, 2])) == 1
        assert b.submitted and not a.submitted

    def test_sheds_when_all_queues_deep(self):
        r = Router([_StubEngine(depth=8), _StubEngine(depth=9)],
                   max_queue_depth=8)
        with pytest.raises(RequestShed):
            r.submit(Request(0, [1, 2]))
        assert r.shed_requests == 1

    def test_burn_rate_sheds_backlogged_replica(self):
        t = [0.0]

        def clock():
            t[0] += 0.01
            return t[0]

        def burning(depth):
            slo = SLOMonitor([SLOTarget("ttft", 0.1, objective=0.9)],
                             clock=clock)
            for _ in range(50):
                slo.observe("ttft", 5.0)         # every event bad
            return _StubEngine(depth=depth, slo=slo)

        # burn = 1.0 / (1 - 0.9) = 10x on both replicas
        r = Router([burning(1), burning(2)], max_queue_depth=8,
                   burn_threshold=5.0, burn_window_s=60.0)
        with pytest.raises(RequestShed):
            r.submit(Request(0, [1, 2]))
        # an IDLE burning replica still accepts (stale burn, empty queue)
        r2 = Router([burning(0)], max_queue_depth=8, burn_threshold=5.0)
        assert r2.submit(Request(1, [1, 2])) == 0

    def test_queue_full_falls_through_to_next_replica(self):
        a = _StubEngine(depth=0, max_queue=0)    # accepts then raises
        b = _StubEngine(depth=5)
        r = Router([a, b], max_queue_depth=8)
        assert r.submit(Request(0, [1, 2])) == 1

    def test_end_to_end_multi_replica_drain(self, tiny):
        model, params = tiny
        reps = [PagedInferenceEngine(model, params, max_slots=2,
                                     block_size=4,
                                     cache_dtype=jnp.float32)
                for _ in range(2)]
        router = Router(reps, max_queue_depth=8)
        reqs = [Request(i, [1 + i % 3, 2, 3], max_new_tokens=3)
                for i in range(6)]
        for r in reqs:
            router.submit(_clone(r))
        out = router.run()
        assert sorted(r.request_id for r in out) == list(range(6))
        ref = _run(InferenceEngine(model, params, max_slots=2,
                                   cache_dtype=jnp.float32), reqs)
        assert {r.request_id: (r.tokens, r.finish_reason)
                for r in out} == ref

    def test_validation(self):
        with pytest.raises(ValueError):
            Router([])
        with pytest.raises(ValueError):
            Router([_StubEngine()], max_queue_depth=0)


# -- loadgen (importable surface) --------------------------------------------

class TestLoadgen:
    def test_overload_run_sheds_and_reports(self):
        import importlib
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        try:
            loadgen = importlib.import_module("loadgen")
        finally:
            sys.path.pop(0)
        import argparse
        ns = argparse.Namespace(
            requests=12, rate=1e9, overload=True, replicas=2,
            max_slots=2, max_queue=64, max_queue_depth=2,
            burn_threshold=14.4, burn_window_s=60.0, ttft_slo_s=0.5,
            block_size=4, chunked=False, token_budget=32, seed=0,
            min_prompt=4, pareto_shape=2.5, max_new=3,
            shared_prefix_prob=0.5, shared_prefix_len=8,
            num_prefixes=2, vocab=32, hidden=16, layers=2, heads=2,
            max_seq=32)
        report = loadgen.run_loadgen(ns)
        assert report["shed"] > 0                # shedding engaged
        assert report["served"] == 12 - report["shed"]
        assert report["served"] > 0
        assert report["ttft_p99_s"] >= report["ttft_p50_s"] >= 0.0
        assert 0.0 <= report["prefix_hit_rate"] <= 1.0
