"""ZeRO distributed optimizers vs their unsharded counterparts on the
8-device CPU mesh (pattern: apex ``DistributedFusedAdam`` is validated
against ``FusedAdam`` on identical reduced gradients)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.contrib.optimizers import (
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers import FusedAdam, FusedLAMB
from apex_tpu.utils.collectives import shard_map_compat

N = 8


@pytest.fixture
def mesh():
    return jax.make_mesh((N,), ("data",))


def _params(rng):
    return {"w1": jnp.asarray(rng.randn(33, 17).astype(np.float32)),
            "b1": jnp.asarray(rng.randn(17).astype(np.float32)),
            "w2": jnp.asarray(rng.randn(129, 40).astype(np.float32))}


def _per_device_grads(rng, params):
    """Stack of N distinct per-device grads; the reduced grad is their
    mean (what DDP would hand an unsharded optimizer)."""
    stacked = jax.tree_util.tree_map(
        lambda p: jnp.asarray(
            rng.randn(N, *p.shape).astype(np.float32) * 0.1), params)
    mean = jax.tree_util.tree_map(lambda g: jnp.mean(g, axis=0), stacked)
    return stacked, mean


def _run_dist(opt, mesh, params, stacked_grads, n_steps=3):
    specs = opt.state_specs(params)
    g_specs = jax.tree_util.tree_map(lambda _: P("data"), params)

    init = shard_map_compat(opt.init, mesh=mesh, in_specs=(P(),),
                            out_specs=specs)
    state = init(params)

    def local_step(g, p, s):
        g = jax.tree_util.tree_map(lambda x: x[0], g)  # drop device axis
        return opt.step(g, p, s)

    step = jax.jit(shard_map_compat(
        local_step, mesh=mesh, in_specs=(g_specs, P(), specs),
        out_specs=(P(), specs)))
    for _ in range(n_steps):
        params, state = step(stacked_grads, params, state)
    return params, state


class TestDistributedFusedAdam:
    def test_parity_with_fused_adam(self, rng, mesh):
        params = _params(rng)
        stacked, mean = _per_device_grads(rng, params)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8,
                                   weight_decay=0.01)
        dist_params, dist_state = _run_dist(opt, mesh, params, stacked)

        ref_opt = FusedAdam(lr=1e-2, block_rows=8, weight_decay=0.01)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(3):
            ref_params, ref_state = ref_opt.step(mean, ref_params,
                                                 ref_state)
        for k in params:
            np.testing.assert_allclose(dist_params[k], ref_params[k],
                                       rtol=1e-5, atol=1e-5)
        assert int(dist_state["step"]) == 3

    def test_state_is_sharded(self, rng, mesh):
        """ZeRO accounting: each device holds 1/N of every moment bucket."""
        params = _params(rng)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        init = shard_map_compat(opt.init, mesh=mesh, in_specs=(P(),),
                                out_specs=opt.state_specs(params))
        state = init(params)
        for key, bucket in state["buckets"].items():
            for name, arr in bucket.items():
                nrows = arr.shape[0]
                assert nrows % N == 0
                shard, = {s.data.shape
                          for s in arr.addressable_shards}
                assert shard == (nrows // N, 128), (key, name, shard)

    def test_master_weights_sharded(self, rng, mesh):
        params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), _params(rng))
        stacked, mean = _per_device_grads(rng, params)
        stacked = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.bfloat16), stacked)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8,
                                   master_weights=True)
        dist_params, dist_state = _run_dist(opt, mesh, params, stacked,
                                            n_steps=2)
        for bucket in dist_state["buckets"].values():
            assert "master" in bucket
            assert bucket["master"].dtype == jnp.float32
        ref_opt = FusedAdam(lr=1e-2, block_rows=8, master_weights=True)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(2):
            ref_params, ref_state = ref_opt.step(
                jax.tree_util.tree_map(lambda g: g.astype(jnp.bfloat16),
                                       mean), ref_params, ref_state)
        # psum_scatter sums grads in bf16 while the reference means them
        # in f32; a one-ulp grad difference can move a bf16 param one
        # rounding step after the adam update — tolerance covers one ulp
        for k in params:
            np.testing.assert_allclose(
                np.asarray(dist_params[k], np.float32),
                np.asarray(ref_params[k], np.float32),
                rtol=5e-2, atol=5e-2)

    def test_noop_flag_skips(self, rng, mesh):
        params = _params(rng)
        stacked, _ = _per_device_grads(rng, params)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        specs = opt.state_specs(params)
        g_specs = jax.tree_util.tree_map(lambda _: P("data"), params)
        init = shard_map_compat(opt.init, mesh=mesh, in_specs=(P(),),
                                out_specs=specs)
        state = init(params)

        def local_step(g, p, s):
            g = jax.tree_util.tree_map(lambda x: x[0], g)
            return opt.step(g, p, s, noop_flag=jnp.ones(()))

        step = shard_map_compat(
            local_step, mesh=mesh, in_specs=(g_specs, P(), specs),
            out_specs=(P(), specs))
        new_params, new_state = step(stacked, params, state)
        for k in params:
            np.testing.assert_array_equal(np.asarray(new_params[k]),
                                          np.asarray(params[k]))
        assert int(new_state["step"]) == 0


class TestDistributedFusedLAMB:
    def test_parity_with_fused_lamb(self, rng, mesh):
        params = _params(rng)
        stacked, mean = _per_device_grads(rng, params)
        opt = DistributedFusedLAMB(lr=1e-2, world_size=N, block_rows=8,
                                   weight_decay=0.01)
        dist_params, _ = _run_dist(opt, mesh, params, stacked)

        ref_opt = FusedLAMB(lr=1e-2, block_rows=8, weight_decay=0.01)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(3):
            ref_params, ref_state = ref_opt.step(mean, ref_params,
                                                 ref_state)
        for k in params:
            np.testing.assert_allclose(dist_params[k], ref_params[k],
                                       rtol=1e-4, atol=1e-4)

    def test_trust_ratio_spans_shards(self, rng, mesh):
        """A single big tensor straddles every shard; the trust ratio must
        still be the GLOBAL per-tensor ‖p‖/‖u‖ (not per-shard)."""
        params = {"w": jnp.asarray(rng.randn(257, 65).astype(np.float32))}
        stacked, mean = _per_device_grads(rng, params)
        opt = DistributedFusedLAMB(lr=5e-3, world_size=N, block_rows=8)
        dist_params, _ = _run_dist(opt, mesh, params, stacked, n_steps=2)
        ref_opt = FusedLAMB(lr=5e-3, block_rows=8)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(2):
            ref_params, ref_state = ref_opt.step(mean, ref_params,
                                                 ref_state)
        np.testing.assert_allclose(dist_params["w"], ref_params["w"],
                                   rtol=1e-4, atol=1e-4)


class TestMakeStep:
    """VERDICT r3 item 6: the optimizer owns the ``check_vma=False``
    shard_map region — ``make_init``/``make_step`` replace the manual
    recipe, and misuse fails loudly at trace time."""

    def test_parity_with_manual_recipe(self, rng, mesh):
        params = _params(rng)
        stacked, _ = _per_device_grads(rng, params)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8,
                                   weight_decay=0.01)
        manual_params, manual_state = _run_dist(opt, mesh, params, stacked)

        state = opt.make_init(mesh)(params)
        step = opt.make_step(mesh)
        api_params = params
        for _ in range(3):
            api_params, state = step(stacked, api_params, state)
        for k in params:
            np.testing.assert_allclose(api_params[k], manual_params[k],
                                       rtol=1e-6, atol=1e-6)
        assert int(state["step"]) == int(manual_state["step"])

    def test_lamb_make_step_runs(self, rng, mesh):
        params = _params(rng)
        stacked, mean = _per_device_grads(rng, params)
        opt = DistributedFusedLAMB(lr=1e-2, world_size=N, block_rows=8)
        state = opt.make_init(mesh)(params)
        step = opt.make_step(mesh)
        new_params, state = step(stacked, params, state)
        ref_opt = FusedLAMB(lr=1e-2, block_rows=8)
        ref_params, _ = ref_opt.step(mean, params, ref_opt.init(params))
        for k in params:
            np.testing.assert_allclose(new_params[k], ref_params[k],
                                       rtol=1e-4, atol=1e-4)

    def test_noop_flag_via_api(self, rng, mesh):
        params = _params(rng)
        stacked, _ = _per_device_grads(rng, params)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        state = opt.make_init(mesh)(params)
        step = opt.make_step(mesh)
        new_params, new_state = step(stacked, params, state,
                                     noop_flag=jnp.ones(()))
        for k in params:
            np.testing.assert_array_equal(np.asarray(new_params[k]),
                                          np.asarray(params[k]))
        assert int(new_state["step"]) == 0

    def test_wrong_mesh_axis_raises(self, rng):
        bad_mesh = jax.make_mesh((N,), ("model",))
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        with pytest.raises(ValueError, match="axis 'data'"):
            opt.make_step(bad_mesh)

    def test_wrong_world_size_raises(self, rng):
        mesh = jax.make_mesh((2, 4), ("data", "model"))
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        with pytest.raises(ValueError, match="world_size=8"):
            opt.make_step(mesh)

    def test_unstacked_grads_raise(self, rng, mesh):
        params = _params(rng)
        _, mean = _per_device_grads(rng, params)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        state = opt.make_init(mesh)(params)
        step = opt.make_step(mesh)
        with pytest.raises(ValueError, match="STACKED per-device"):
            step(mean, params, state)     # forgot the device axis

    def test_mismatched_tree_raises(self, rng, mesh):
        params = _params(rng)
        stacked, _ = _per_device_grads(rng, params)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        state = opt.make_init(mesh)(params)
        step = opt.make_step(mesh)
        del stacked["w2"]
        with pytest.raises(ValueError, match="tree"):
            step(stacked, params, state)


class TestAllreduceDtype:
    """The quantized-transport knob (compressed_allreduce): f32 is
    bitwise-identical to the default path; bf16/int8 track it within the
    documented tolerance of the grad reduce-scatter."""

    def test_f32_mode_bitwise_exact(self, rng, mesh):
        params = _params(rng)
        stacked, _ = _per_device_grads(rng, params)
        base = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        f32 = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8,
                                   allreduce_dtype="f32")
        p_base, _ = _run_dist(base, mesh, params, stacked, n_steps=2)
        p_f32, _ = _run_dist(f32, mesh, params, stacked, n_steps=2)
        for k in params:
            np.testing.assert_array_equal(np.asarray(p_base[k]),
                                          np.asarray(p_f32[k]))

    @pytest.mark.parametrize("mode", ["bf16", "int8"])
    def test_quantized_tracks_exact(self, rng, mesh, mode):
        """Adam normalizes per element, so a quantization-induced sign
        flip on a near-zero-grad element costs up to a full ±lr step —
        the worst-case divergence bound is ``2 * lr * n_steps`` (the
        documented tolerance), while typical elements barely move."""
        lr, n_steps = 1e-2, 2
        params = _params(rng)
        stacked, mean = _per_device_grads(rng, params)
        opt = DistributedFusedAdam(lr=lr, world_size=N, block_rows=8,
                                   allreduce_dtype=mode)
        dist_params, _ = _run_dist(opt, mesh, params, stacked,
                                   n_steps=n_steps)
        ref_opt = FusedAdam(lr=lr, block_rows=8)
        ref_state = ref_opt.init(params)
        ref_params = params
        for _ in range(n_steps):
            ref_params, ref_state = ref_opt.step(mean, ref_params,
                                                 ref_state)
        bound = 2 * lr * n_steps
        for k in params:
            diff = np.abs(np.asarray(dist_params[k])
                          - np.asarray(ref_params[k]))
            assert diff.max() <= bound * 1.01, (k, diff.max())
            # the sign-flip worst case is rare: the bulk of the update
            # must agree to ~transport precision
            assert np.mean(diff) < bound / 20, (k, np.mean(diff))

    def test_lamb_int8_via_make_step(self, rng, mesh):
        params = _params(rng)
        stacked, mean = _per_device_grads(rng, params)
        opt = DistributedFusedLAMB(lr=1e-2, world_size=N, block_rows=8,
                                   allreduce_dtype="int8")
        state = opt.make_init(mesh)(params)
        new_params, state = opt.make_step(mesh)(stacked, params, state)
        ref_opt = FusedLAMB(lr=1e-2, block_rows=8)
        ref_params, _ = ref_opt.step(mean, params, ref_opt.init(params))
        for k in params:
            np.testing.assert_allclose(new_params[k], ref_params[k],
                                       rtol=2e-2, atol=2e-2)

    def test_bad_mode_raises(self):
        with pytest.raises(ValueError, match="allreduce_dtype"):
            DistributedFusedAdam(lr=1e-2, world_size=N,
                                 allreduce_dtype="fp8")


class TestMessageSize:
    """apex bucket semantics: ``message_size`` caps each packed bucket in
    BYTES (dtype-aware), splitting the layout into more buckets without
    changing the math."""

    def test_split_layout_parity(self, rng, mesh):
        params = _params(rng)
        stacked, _ = _per_device_grads(rng, params)
        one = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8)
        # 16 KiB cap forces each ~LANE-padded f32 tensor into its own
        # bucket (w2 alone is 129*40*4 ≈ 20 KiB padded)
        split = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8,
                                     message_size=16 * 1024)
        assert len(split._layout(params).buckets) > \
            len(one._layout(params).buckets)
        p_one, _ = _run_dist(one, mesh, params, stacked, n_steps=2)
        p_split, _ = _run_dist(split, mesh, params, stacked, n_steps=2)
        for k in params:
            np.testing.assert_allclose(np.asarray(p_one[k]),
                                       np.asarray(p_split[k]),
                                       rtol=1e-6, atol=1e-6)


class TestDistributedMasterParams:
    def test_master_params_gathers_shards(self, rng, mesh):
        """master_params on ZeRO state must all-gather the row-sharded
        master buckets — the inherited unsharded unflatten would slice
        garbage silently."""
        params = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), _params(rng))
        stacked, _ = _per_device_grads(rng, params)
        stacked = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.bfloat16), stacked)
        opt = DistributedFusedAdam(lr=1e-2, world_size=N, block_rows=8,
                                   master_weights=True)
        new_params, state = _run_dist(opt, mesh, params, stacked,
                                      n_steps=1)

        specs = opt.state_specs(params)
        masters = jax.jit(shard_map_compat(
            opt.master_params, mesh=mesh, in_specs=(P(), specs),
            out_specs=P()))(new_params, state)
        for k in params:
            assert masters[k].dtype == jnp.float32
            # model params are the bf16 round-trip of the masters
            np.testing.assert_array_equal(
                np.asarray(masters[k].astype(jnp.bfloat16)),
                np.asarray(new_params[k]))
