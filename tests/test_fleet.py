"""apex_tpu.serving.fleet: fault injection, health-checked routing,
cross-replica migration, degradation.

The fleet's correctness contract:

* the serving fault injector is deterministic from its seed and keeps
  an applied-fault log, like the training injector it mirrors;
* the health state machine walks healthy → suspect → dead on missed
  heartbeats and dead → recovering → healthy once beats return;
* a dead replica's in-flight requests migrate and resume TOKEN-BITWISE
  (greedy and seeded sampling, contiguous and paged engines) — the
  ``(seed, token-index)`` stream plus re-prefill of prompt+streamed
  tokens makes the interruption invisible in the output;
* a migrated context that no longer fits the target finishes with
  ``reason="preempted"``; admission retries exhaust their budget into
  ``reason="shed"``; hedged dispatch completes exactly once;
* the degradation ladder escalates on burn immediately and de-escalates
  with hysteresis, and flushing the prefix trie frees its blocks.
"""

import argparse
import collections
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import (InferenceEngine, QueueFull, Request,
                                SamplingParams)
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.serving import (DegradationLadder, FleetRouter,
                              PagedInferenceEngine, PagedKVCache,
                              ReplicaHealth, RequestShed, Router,
                              ServingFault, ServingFaultInjector,
                              ShedReason, VirtualClock)
from apex_tpu.utils.profiling import ServingMetrics


def tiny_cfg(**kw):
    base = dict(vocab_size=32, hidden_size=16, num_layers=2,
                num_attention_heads=2, max_seq_len=16)
    base.update(kw)
    return GPTConfig(**base)


@pytest.fixture(scope="module")
def tiny():
    model = GPTModel(tiny_cfg())
    return model, model.init_params(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def tiny32():
    model = GPTModel(tiny_cfg(max_seq_len=32))
    return model, model.init_params(jax.random.PRNGKey(0))


def _clone(req: Request) -> Request:
    return dataclasses.replace(req)


def _mixed_requests():
    return [
        Request(0, [1, 2, 3, 4, 5], max_new_tokens=6),
        Request(1, [1, 2, 3, 9], max_new_tokens=5, seed=7,
                sampling=SamplingParams(temperature=0.8, top_k=5)),
        Request(2, [1, 2, 3, 4, 5, 6, 7], max_new_tokens=4, seed=3,
                sampling=SamplingParams(temperature=1.1, top_p=0.9)),
        Request(3, [4, 4, 4], max_new_tokens=5, seed=11,
                sampling=SamplingParams(temperature=1.0, top_k=8,
                                        top_p=0.8)),
    ]


def _engine(model, params, paged, clock, **kw):
    cls = PagedInferenceEngine if paged else InferenceEngine
    if paged:
        kw.setdefault("block_size", 4)
    return cls(model, params, max_slots=2,
               metrics=ServingMetrics(clock), clock=clock, **kw)


def _fleet(model, params, *, n=2, paged=False, injector=None, **kw):
    clock = VirtualClock()
    replicas = [_engine(model, params, paged, clock) for _ in range(n)]
    kw.setdefault("suspect_after", 1)
    kw.setdefault("dead_after", 2)
    kw.setdefault("recover_after", 2)
    fleet = FleetRouter(replicas, injector=injector, clock=clock, **kw)
    return fleet, replicas, clock


# -- fault injector ----------------------------------------------------------

class TestServingFaultInjector:
    def test_seed_determinism(self):
        rates = {"replica_crash": 0.05, "slow_replica": 0.1,
                 "reject_admission": 0.2}
        a = ServingFaultInjector.from_seed(3, 40, 2, rates)
        b = ServingFaultInjector.from_seed(3, 40, 2, rates)
        assert a.schedule == b.schedule and a.schedule
        c = ServingFaultInjector.from_seed(4, 40, 2, rates)
        assert a.schedule != c.schedule

    def test_duration_window_and_log(self):
        f = ServingFault(3, 0, "stuck_decode", duration=2)
        inj = ServingFaultInjector([f])
        assert inj.faults_at(2, 0) == ()
        assert inj.faults_at(3, 0) == (f,) and inj.faults_at(4, 0) == (f,)
        assert inj.faults_at(5, 0) == ()
        assert inj.faults_at(3, 1) == ()        # other replica unaffected
        # activate records ONCE, at first application
        inj.activate(3, 0)
        inj.activate(4, 0)
        assert inj.log == [(3, 0, "stuck_decode")]

    def test_validation(self):
        with pytest.raises(ValueError):
            ServingFault(0, 0, "grad_spike")     # training kind
        with pytest.raises(ValueError):
            ServingFault(0, 0, "replica_crash", duration=0)
        with pytest.raises(ValueError):
            ServingFaultInjector.from_seed(0, 10, 2,
                                           {"nan_grads": 0.5})


# -- engine migration primitives ---------------------------------------------

class TestEnginePrimitives:
    @pytest.mark.parametrize("paged", [False, True])
    def test_export_then_adopt_resumes_bitwise(self, tiny, paged):
        model, params = tiny
        clock = VirtualClock()
        ref = _engine(model, params, paged, clock)
        reqs = _mixed_requests()
        for r in reqs:
            ref.submit(_clone(r))
        want = {r.request_id: (r.tokens, r.finish_reason)
                for r in ref.run()}

        src = _engine(model, params, paged, clock)
        dst = _engine(model, params, paged, clock)
        for r in reqs:
            src.submit(_clone(r))
        for _ in range(3):                      # mid-decode on src
            src.step()
        moved = src.export_inflight()
        assert moved and not src._active and not src._queue
        assert src.metrics.migrated == len(moved)
        # src emitted Responses only for requests that FINISHED there
        finished_on_src = {r.request_id: (r.tokens, r.finish_reason)
                           for r in src.completed}
        for req, progress in moved:
            assert req.request_id not in finished_on_src
            dst.adopt(req, progress)
        got = dict(finished_on_src)
        got.update({r.request_id: (r.tokens, r.finish_reason)
                    for r in dst.run()})
        assert got == want                      # token-bitwise continuation

    def test_adopt_rejects_overflow(self, tiny):
        model, params = tiny
        eng = _engine(model, params, False, VirtualClock())
        with pytest.raises(ValueError):
            eng.adopt(Request(0, [1] * 10), progress=[2] * 6)

    def test_cancel_active_and_queued(self, tiny):
        model, params = tiny
        eng = _engine(model, params, False, VirtualClock())
        for i in range(3):                      # 2 slots -> 1 queued
            eng.submit(Request(i, [1, 2, 3], max_new_tokens=4))
        eng.step()
        assert eng.cancel(0) and eng.cancel(2)  # one active, one queued
        assert not eng.cancel(99)
        assert eng.metrics.cancelled == 2
        out = {r.request_id for r in eng.run()}
        assert out == {1}                       # no Response for cancelled

    def test_injected_admission_faults(self, tiny):
        model, params = tiny
        eng = _engine(model, params, False, VirtualClock())
        eng.injected_faults = frozenset({"reject_admission"})
        with pytest.raises(QueueFull):
            eng.submit(Request(0, [1, 2, 3]))
        eng.injected_faults = frozenset({"kv_pool_exhaustion"})
        eng.submit(Request(1, [1, 2, 3], max_new_tokens=2))
        eng.step()
        assert eng.queue_depth == 1             # admission stalled
        eng.injected_faults = frozenset()
        eng.step()
        assert eng.queue_depth == 0


# -- health state machine ----------------------------------------------------

class TestHealth:
    def test_crash_walks_suspect_dead_recovering_healthy(self, tiny):
        model, params = tiny
        inj = ServingFaultInjector([
            ServingFault(1, 1, "replica_crash", duration=3)])
        fleet, _, _ = _fleet(model, params, injector=inj)
        for _ in range(8):
            fleet.step()
        assert [(r, a, b) for _, r, a, b in fleet.health_log] == [
            (1, "healthy", "suspect"), (1, "suspect", "dead"),
            (1, "dead", "recovering"), (1, "recovering", "healthy")]
        assert fleet.health(1) is ReplicaHealth.HEALTHY
        assert inj.log == [(1, 1, "replica_crash")]

    def test_placement_excludes_non_healthy(self, tiny):
        model, params = tiny
        inj = ServingFaultInjector([
            ServingFault(1, 0, "replica_crash", duration=100)])
        fleet, replicas, _ = _fleet(model, params)
        fleet.injector = inj
        fleet.step()                            # replica 0 -> suspect
        assert fleet.health(0) is ReplicaHealth.SUSPECT
        for i in range(4):
            assert fleet.submit(Request(i, [1, 2, 3],
                                        max_new_tokens=2)) == 1
        assert replicas[0].queue_depth + replicas[0].active_requests == 0

    def test_slow_replica_goes_suspect_not_dead(self, tiny):
        model, params = tiny
        inj = ServingFaultInjector([
            ServingFault(1, 1, "slow_replica", magnitude=0.5,
                         duration=6)])
        fleet, _, _ = _fleet(model, params, n=3, injector=inj,
                             slow_after=2)
        # keep the fleet busy so ticks measure real work
        for i in range(6):
            fleet.submit(Request(i, [1, 2, 3], max_new_tokens=8))
        for _ in range(6):
            fleet.step()
        assert fleet.health(1) is ReplicaHealth.SUSPECT
        assert ("dead" not in
                {b for _, r, _, b in fleet.health_log if r == 1})
        for _ in range(8):                      # fault over: normalizes
            fleet.step()
        assert fleet.health(1) is ReplicaHealth.HEALTHY


# -- cross-replica migration -------------------------------------------------

class TestMigration:
    @pytest.mark.parametrize("paged", [False, True])
    def test_replica_kill_token_parity(self, tiny, paged):
        """Kill a replica mid-decode: every request — greedy and seeded,
        migrated or not — matches the uninterrupted single-engine run."""
        model, params = tiny
        reqs = _mixed_requests()
        ref = _engine(model, params, paged, VirtualClock())
        for r in reqs:
            ref.submit(_clone(r))
        want = {r.request_id: (r.tokens, r.finish_reason)
                for r in ref.run()}

        inj = ServingFaultInjector([
            ServingFault(3, 0, "replica_crash", duration=10 ** 6)])
        fleet, _, _ = _fleet(model, params, paged=paged, injector=inj)
        for r in reqs:
            fleet.submit(_clone(r))
        out = {r.request_id: (r.tokens, r.finish_reason)
               for r in fleet.run(max_steps=200)}
        assert fleet.migrations > 0             # the kill hit live work
        assert out == want
        assert fleet.pending == 0
        assert fleet.duplicate_responses == 0

    def test_migration_overflow_finishes_preempted(self, tiny, tiny32):
        """Heterogeneous fleet: the dead replica's context no longer
        fits the survivor's max_seq -> reason='preempted' with the
        already-streamed tokens, not a hang and not a loss."""
        model16, params16 = tiny
        model32, params32 = tiny32
        clock = VirtualClock()
        big = _engine(model32, params32, False, clock)    # max_seq 32
        small = _engine(model16, params16, False, clock)  # max_seq 16
        inj = ServingFaultInjector([
            ServingFault(3, 0, "replica_crash", duration=10 ** 6)])
        fleet = FleetRouter([big, small], injector=inj, clock=clock,
                            suspect_after=1, dead_after=2)
        req = Request(0, [1] * 20, max_new_tokens=8)
        assert fleet.submit(req) == 0           # only fits the big one
        out = {r.request_id: r for r in fleet.run(max_steps=50)}
        assert out[0].finish_reason == "preempted"
        assert len(out[0].tokens) >= 1          # progress preserved
        assert fleet.pending == 0

    def test_retry_budget_exhaustion_sheds(self, tiny):
        model, params = tiny
        inj = ServingFaultInjector([
            ServingFault(1, 0, "replica_crash", duration=10 ** 6)])
        fleet, _, clock = _fleet(model, params, n=1, injector=inj,
                                 retry_budget=2, retry_base_s=0.01)
        fleet.step()
        fleet.step()                            # replica 0 now DEAD
        assert fleet.health(0) is ReplicaHealth.DEAD
        assert fleet.submit(Request(0, [1, 2, 3])) == -1   # parked
        for _ in range(30):
            fleet.step()
            clock.advance(0.05)
        out = {r.request_id: r for r in fleet.completed}
        assert out[0].finish_reason == "shed"
        assert fleet.retries > 0 and fleet.pending == 0

    def test_submit_raises_no_healthy_when_budget_zero(self, tiny):
        model, params = tiny
        inj = ServingFaultInjector([
            ServingFault(1, 0, "replica_crash", duration=10 ** 6)])
        fleet, _, _ = _fleet(model, params, n=1, injector=inj,
                             retry_budget=0)
        fleet.step()
        fleet.step()
        with pytest.raises(RequestShed) as ei:
            fleet.submit(Request(0, [1, 2, 3]))
        assert ei.value.reason is ShedReason.NO_HEALTHY_REPLICA
        assert ei.value.retry_after_s > 0


# -- hedging -----------------------------------------------------------------

class TestHedging:
    def test_stuck_replica_hedge_completes_exactly_once(self, tiny):
        model, params = tiny
        ref = _engine(model, params, False, VirtualClock())
        req = Request(0, [1, 2, 3, 4], max_new_tokens=5)
        ref.submit(_clone(req))
        want = ref.run()[0]

        inj = ServingFaultInjector([
            ServingFault(1, 0, "stuck_decode", duration=10 ** 6)])
        fleet, _, clock = _fleet(model, params, injector=inj,
                                 suspect_after=2, dead_after=6,
                                 hedge_after_s=0.1)
        fleet.submit(_clone(req))               # lands on replica 0
        for _ in range(30):
            fleet.step()
            clock.advance(0.05)
        out = [r for r in fleet.completed]
        assert len(out) == 1                    # exactly once
        assert fleet.hedges == 1
        assert (out[0].tokens, out[0].finish_reason) == \
            (want.tokens, want.finish_reason)


# -- degradation -------------------------------------------------------------

class TestDegradation:
    def test_ladder_escalates_immediately_steps_down_slowly(self):
        lad = DegradationLadder(thresholds=(2.0, 6.0, 14.4),
                                step_down_s=1.0)
        assert lad.update(1.0, 0.0) == 0
        assert lad.update(7.0, 0.1) == 2        # straight to L2
        assert lad.update(20.0, 0.2) == 3
        assert lad.update(0.0, 0.3) == 3        # hysteresis holds
        assert lad.update(0.0, 1.4) == 2        # one level per window
        assert lad.update(0.0, 2.5) == 1
        assert lad.update(7.0, 2.6) == 2        # re-escalates instantly

    def test_ladder_validation(self):
        with pytest.raises(ValueError):
            DegradationLadder(thresholds=(6.0, 2.0, 14.4))
        with pytest.raises(ValueError):
            DegradationLadder(ctx_cap_frac=0.0)

    def test_flush_prefixes_frees_trie_blocks(self):
        pool = PagedKVCache(9, 4, layers=2, kv_heads=2, head_dim=4,
                            dtype=jnp.float32)
        seq = pool.acquire([1] * 8)             # 2 blocks
        pool.register_prefix(seq, [1] * 8)
        pool.release(seq)
        assert pool.free_blocks == 6            # trie still holds 2
        assert pool.flush_prefixes() == 2
        assert pool.free_blocks == 8
        assert pool.flush_prefixes() == 0       # idempotent

    def test_fleet_degrade_sheds_with_reason(self, tiny):
        model, params = tiny
        fleet, _, _ = _fleet(model, params)
        fleet.ladder = DegradationLadder()
        fleet.ladder.level = 3
        with pytest.raises(RequestShed) as ei:
            fleet.submit(Request(0, [1, 2, 3]))
        assert ei.value.reason is ShedReason.DEGRADED
        fleet.ladder.level = 2
        with pytest.raises(RequestShed) as ei:
            fleet.submit(Request(1, [1] * 12))  # over the 50% ctx cap
        assert ei.value.reason is ShedReason.CONTEXT_CAP
        assert fleet.submit(Request(2, [1, 2, 3])) >= 0   # short: admitted


# -- shed metadata on the plain router ---------------------------------------

class TestShedMetadata:
    def test_overload_shed_carries_reason_and_hint(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        replicas = [_engine(model, params, False, clock)
                    for _ in range(2)]
        router = Router(replicas, max_queue_depth=1)
        for i in range(8):
            try:
                router.submit(Request(i, [1, 2, 3]))
            except RequestShed as e:
                assert e.reason is ShedReason.OVERLOAD
                assert e.retry_after_s > 0
                break
        else:
            pytest.fail("router never shed")


# -- chaos scenario smoke (the loadgen suite) --------------------------------

def _scenario_ns(**kw):
    base = dict(
        scenario="replica_kill", requests=8, rate=1e9, replicas=3,
        max_slots=2, max_queue=64, max_queue_depth=4,
        burn_threshold=14.4, burn_window_s=60.0, ttft_slo_s=0.5,
        block_size=4, chunked=False, token_budget=32, client_retries=3,
        tick_s=0.02, e2e_slo_s=3.0, max_ticks=600, retry_budget=4,
        hedge_after_s=None, ladder_step_down_s=0.5, kill_tick=3,
        kill_replica=1, kill_duration=10 ** 6, slow_tick=4, slow_s=0.1,
        slow_duration=40, burst_n=4, burst_gap_s=0.3, period_s=2.0,
        seed=0, min_prompt=4, pareto_shape=2.5, max_new=4,
        shared_prefix_prob=0.5, shared_prefix_len=8, num_prefixes=2,
        vocab=32, hidden=16, layers=2, heads=2, max_seq=32)
    base.update(kw)
    return argparse.Namespace(**base)


class TestScenarios:
    def _loadgen(self):
        import importlib
        import os
        import sys
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools"))
        try:
            return importlib.import_module("loadgen")
        finally:
            sys.path.pop(0)

    def test_replica_kill_exactly_once(self):
        rep = self._loadgen().run_scenario(_scenario_ns())
        assert rep["submitted"] == 8
        assert rep["responses"] == 8
        assert rep["lost"] == [] and rep["duplicated"] == 0
        assert rep["migrations"] > 0
        assert rep["fleet_pending"] == 0
        # the dead replica's transitions are on the health log
        assert ("suspect", "dead") in {(a, b) for _, r, a, b in
                                       rep["health_log"] if r == 1}
        assert rep["recovery"]["first_dead"] is not None
        assert rep["recovery"]["first_resumed_token"] is not None

    def test_scenario_determinism(self):
        a = self._loadgen().run_scenario(_scenario_ns())
        b = self._loadgen().run_scenario(_scenario_ns())
        assert a == b                           # virtual clock: bitwise


# -- capacity lifecycle (drain / remove / add) -------------------------------

class TestCapacityLifecycle:
    def test_begin_drain_migrates_token_bitwise(self, tiny):
        """Drain a replica mid-decode: its work migrates NOW and every
        stream still matches the uninterrupted single-engine run."""
        model, params = tiny
        reqs = _mixed_requests()
        ref = _engine(model, params, False, VirtualClock())
        for r in reqs:
            ref.submit(_clone(r))
        want = {r.request_id: (r.tokens, r.finish_reason)
                for r in ref.run()}

        fleet, replicas, _ = _fleet(model, params)
        for r in reqs:
            fleet.submit(_clone(r))
        fleet.step()
        fleet.begin_drain(0)
        assert fleet.health(0) is ReplicaHealth.DRAINING
        assert fleet.migrations > 0             # live work moved off
        out = {r.request_id: (r.tokens, r.finish_reason)
               for r in fleet.run(max_steps=200)}
        assert out == want
        assert fleet.drained(0)
        assert fleet.duplicate_responses == 0 and fleet.pending == 0

    def test_draining_is_never_marked_dead(self, tiny):
        """A crash fault landing on a DRAINING replica must not produce
        a death verdict — that would migrate the work a second time."""
        model, params = tiny
        inj = ServingFaultInjector([
            ServingFault(2, 0, "replica_crash", duration=100)])
        fleet, _, _ = _fleet(model, params, injector=inj)
        fleet.step()
        fleet.begin_drain(0)
        fleet.begin_drain(0)                    # idempotent
        for _ in range(10):
            fleet.step()
        assert fleet.health(0) is ReplicaHealth.DRAINING
        states = {b for _, r, _, b in fleet.health_log if r == 0}
        assert states == {"draining"}           # one transition, no dead

    def test_draining_excluded_from_placement(self, tiny):
        model, params = tiny
        fleet, replicas, _ = _fleet(model, params)
        fleet.begin_drain(0)
        for i in range(4):
            assert fleet.submit(Request(i, [1, 2, 3],
                                        max_new_tokens=2)) == 1
        assert replicas[0].queue_depth + replicas[0].active_requests == 0

    def test_cancel_drain_restores_healthy(self, tiny):
        model, params = tiny
        fleet, _, _ = _fleet(model, params)
        fleet.begin_drain(1)
        fleet.cancel_drain(1)
        assert fleet.health(1) is ReplicaHealth.HEALTHY
        assert [(r, a, b) for _, r, a, b in fleet.health_log] == [
            (1, "healthy", "draining"), (1, "draining", "healthy")]
        # back in the placement rotation
        assert fleet.submit(Request(9, [1, 2], max_new_tokens=2)) in (0, 1)

    def test_drain_on_dead_or_removed_raises(self, tiny):
        model, params = tiny
        inj = ServingFaultInjector([
            ServingFault(1, 0, "replica_crash", duration=10 ** 6)])
        fleet, _, _ = _fleet(model, params, injector=inj)
        fleet.step()
        fleet.step()                            # replica 0 now DEAD
        with pytest.raises(ValueError, match="dead"):
            fleet.begin_drain(0)
        fleet.remove_replica(1)
        with pytest.raises(ValueError, match="removed"):
            fleet.begin_drain(1)

    def test_drained_semantics(self, tiny):
        model, params = tiny
        fleet, replicas, _ = _fleet(model, params)
        assert fleet.drained(0) and fleet.drained(1)
        i = fleet.submit(Request(0, [1, 2, 3], max_new_tokens=3))
        assert not fleet.drained(i)             # in-flight entry points at i
        list(fleet.run(max_steps=50))
        assert fleet.drained(i)
        fleet.remove_replica(0)
        assert fleet.drained(0)                 # tombstone is trivially dry

    def test_remove_add_reuses_tombstone_exactly_once(self, tiny):
        model, params = tiny
        fleet, replicas, _ = _fleet(model, params)
        for i in range(3):
            fleet.submit(Request(i, [1, 2, 3], max_new_tokens=3))
        done = list(fleet.run(max_steps=100))
        assert len(done) == 3
        eng = fleet.remove_replica(1)
        assert eng is replicas[1] and fleet.replicas[1] is None
        assert [i for i, _ in fleet._live()] == [0]
        # rollback path: the SAME engine comes back into its old slot;
        # responses already harvested from it must not re-deliver
        assert fleet.add_replica(eng) == 1
        assert fleet.health(1) is ReplicaHealth.HEALTHY
        fleet.submit(Request(7, [4, 5], max_new_tokens=2))
        # completed is cumulative + deduplicated: the re-added engine's
        # _done list still holds its earlier responses, but each id
        # appears exactly once and nothing counts as a duplicate
        out = list(fleet.run(max_steps=50))
        assert sorted(r.request_id for r in out) == [0, 1, 2, 7]
        assert fleet.duplicate_responses == 0
        trans = [(r, a, b) for _, r, a, b in fleet.health_log]
        assert (1, "healthy", "removed") in trans
        assert (1, "removed", "healthy") in trans

    def test_shed_reason_draining_with_depth_scaled_hint(self, tiny):
        model, params = tiny
        fleet, _, _ = _fleet(model, params, n=1, retry_budget=0)
        fleet.begin_drain(0)
        with pytest.raises(RequestShed) as ei:
            fleet.submit(Request(0, [1, 2, 3]))
        assert ei.value.reason is ShedReason.DRAINING
        assert ei.value.retry_after_s > 0
