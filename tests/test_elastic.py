"""apex_tpu.resilience.elastic: elastic, preemption-native training.

The contract under test (ISSUE 9):

* :class:`TopologySpec` round-trips through the checkpoint manifest,
  restore warns on a topology mismatch, and ``topology_of`` reads the
  stamp without touching the payload;
* ``reshard_optimizer_state`` re-partitions optimizer state across dp
  changes with the LOGICAL values bitwise intact — per-leaf FusedAdam
  slots and packed ZeRO (reduce-scatter) buckets whose padding is
  world-size dependent;
* ``unpack_from_shard_map`` inverts ``pack_for_shard_map`` exactly —
  tp leaf splits, pp stage stacking, and the interleaved virtual-stage
  permutation;
* :class:`ElasticTrainer` reacts to injected ``topology_change`` faults
  and :class:`HostSignals` requests by drain -> checkpoint(old) ->
  replan -> reshard -> checkpoint(new) -> resume, and a shrink -> grow
  cycle is BITWISE vs. the uninterrupted run (collective world sizes
  stay <= 4: XLA CPU's psum/psum_scatter is exact there, see
  tools/crash_matrix.py);
* a hard :class:`Preemption` mid-shrink restarts into a fresh trainer
  that restores the shrunk manifest, warns, re-shards, and resumes;
* the serving engine's ``preempt()`` requeues in-flight requests with
  the (seed, token-index) sampling stream intact — greedy outputs are
  token-identical across the interruption — and the requeue count
  lands on :class:`ServingMetrics`.
"""

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.inference import InferenceEngine, Request
from apex_tpu.models.gpt import (GPTConfig, GPTModel, pack_for_shard_map,
                                 unpack_from_shard_map)
from apex_tpu.multi_tensor_apply import bucketing as B
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import DistributedFusedAdam
from apex_tpu.resilience import (CheckpointManager, ElasticComponents,
                                 ElasticPlan, ElasticSignal, ElasticTrainer,
                                 Fault, FaultInjector, GuardedTrainStep,
                                 HostSignals, Preemption, TopologySpec,
                                 ZeROGuardAdapter, reshard_optimizer_state)

needs8 = pytest.mark.skipif(len(jax.devices()) < 8,
                            reason="needs the 8-device CPU mesh")


def _loss_fn(p, x, y):
    return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))


def _params(seed=0, scale=1.0):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray((r.randn(8, 4) * scale).astype(np.float32)),
            "b": jnp.zeros((4,), jnp.float32)}


def _batch(step, plan=None):
    r = np.random.RandomState(70_000 + step)
    return (jnp.asarray(r.randn(8, 8).astype(np.float32)),
            jnp.asarray(r.randn(8, 4).astype(np.float32)))


def _tree_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- TopologySpec / ElasticPlan ----------------------------------------------

class TestTopologySpec:
    def test_round_trip(self):
        spec = TopologySpec(dp=4, tp=2, pp=1, sequence_parallel=True,
                            zero_shard=4)
        assert TopologySpec.from_dict(spec.to_dict()) == spec
        assert spec.n_devices == 8
        assert "dp=4" in spec.describe() and "tp=2" in spec.describe()

    def test_validation(self):
        with pytest.raises(ValueError):
            TopologySpec(dp=0)
        with pytest.raises(ValueError):
            TopologySpec(dp=4, zero_shard=2)   # zero_shard must be 1 or dp
        with pytest.raises(ValueError):
            TopologySpec(sequence_parallel=True)   # SP requires tp > 1

    @needs8
    def test_plan_builds_canonical_mesh(self):
        plan = ElasticPlan.build(TopologySpec(dp=4, tp=2))
        assert plan.mesh_shape == {"data": 4, "pipe": 1, "model": 2}
        # put() replicates onto the plan's devices
        t = plan.put({"a": jnp.arange(8.0)})
        assert len(t["a"].sharding.device_set) == 8


# -- manifest topology stamping ----------------------------------------------

class TestManifestTopology:
    def test_stamp_and_read(self, tmp_path):
        spec = TopologySpec(dp=2)
        mgr = CheckpointManager(str(tmp_path), topology=spec)
        mgr.save(3, {"a": jnp.arange(4.0)})
        assert mgr.topology_of(3) == spec.to_dict()
        # mesh_shape rides along for dashboards
        import json
        man = json.loads(
            (tmp_path / "step_00000003" / "MANIFEST.json").read_text())
        assert man["mesh_shape"] == {"data": 2, "pipe": 1, "model": 1}

    def test_mismatch_warns(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), topology=TopologySpec(dp=2))
        mgr.save(1, {"a": jnp.arange(4.0)})
        with pytest.warns(UserWarning, match="topology"):
            mgr.restore({"a": jnp.zeros(4)}, topology=TopologySpec(dp=4))

    def test_match_silent(self, tmp_path):
        spec = TopologySpec(dp=2)
        mgr = CheckpointManager(str(tmp_path), topology=spec)
        mgr.save(1, {"a": jnp.arange(4.0)})
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            restored, step = mgr.restore({"a": jnp.zeros(4)}, topology=spec)
        assert step == 1

    def test_unstamped_manifest_reads_none(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"a": jnp.arange(4.0)})
        assert mgr.topology_of(1) is None


# -- optimizer re-sharding ----------------------------------------------------

@needs8
class TestReshard:
    def test_per_leaf_identity_values(self):
        """dp=8 -> dp=4: per-leaf slots are replicated, so the reshard
        is a re-placement — every slot value bitwise."""
        old = ElasticPlan.build(TopologySpec(dp=8))
        new = ElasticPlan.build(TopologySpec(dp=4))
        opt = FusedAdam(lr=1e-2)
        params = old.put(_params())
        state = opt.init(params)
        g = jax.grad(_loss_fn)(params, *_batch(0))
        params, state = jax.jit(opt.step)(g, params, state)

        out = reshard_optimizer_state(state, old, new, optimizer=opt,
                                      params=params)
        _tree_equal(out, state)
        for leaf in jax.tree_util.tree_leaves(out):
            assert len(leaf.sharding.device_set) == 4

    def test_zero_round_trip_logical_bitwise(self):
        """ws=4 -> ws=2 -> ws=4: the packed padding changes with the
        world size but every LOGICAL m/v/master leaf is bitwise."""
        def mk(ws, dp):
            plan = ElasticPlan.build(TopologySpec(dp=dp, zero_shard=ws))
            opt = DistributedFusedAdam(lr=1e-2, world_size=ws,
                                       axis_name="data", block_rows=8)
            return plan, opt

        plan4, opt4 = mk(4, 4)
        plan2, opt2 = mk(2, 2)
        params = plan4.put(_params(1, scale=0.1))
        adapter = ZeROGuardAdapter(opt4, plan4.mesh)
        state = adapter.init(params)
        g = jax.grad(_loss_fn)(params, *_batch(0))
        params, state = adapter.step(g, params, state)

        def logical(st, ws):
            opt = DistributedFusedAdam(lr=1e-2, world_size=ws,
                                       axis_name="data", block_rows=8)
            lay = opt._layout(params)
            out = []
            for info in lay.buckets:
                for slot in sorted(st["buckets"][info.key]):
                    arr = jnp.asarray(np.asarray(
                        st["buckets"][info.key][slot]))
                    out.extend(np.asarray(x) for x in B.unflatten_bucket(
                        arr, info.meta._replace(dtype=jnp.float32)))
            return out

        ref = logical(state, 4)
        shrunk = reshard_optimizer_state(
            state, plan4, plan2, optimizer=opt4, params=params,
            new_optimizer=opt2)
        for a, b in zip(logical(shrunk, 2), ref):
            np.testing.assert_array_equal(a, b)
        grown = reshard_optimizer_state(
            shrunk, plan2, plan4, optimizer=opt2, params=params,
            new_optimizer=opt4)
        for a, b in zip(logical(grown, 4), ref):
            np.testing.assert_array_equal(a, b)

    def test_zero_to_per_leaf_rejected(self):
        plan = ElasticPlan.build(TopologySpec(dp=2, zero_shard=2))
        opt = DistributedFusedAdam(lr=1e-2, world_size=2,
                                   axis_name="data", block_rows=8)
        params = plan.put(_params(1, scale=0.1))
        adapter = ZeROGuardAdapter(opt, plan.mesh)
        state = adapter.init(params)
        with pytest.raises(ValueError):
            reshard_optimizer_state(
                state, plan, ElasticPlan.build(TopologySpec(dp=2)),
                optimizer=opt, params=params,
                new_optimizer=FusedAdam(lr=1e-2))


# -- pack/unpack round trip ---------------------------------------------------

@needs8
class TestUnpackRoundTrip:
    def _model(self, tp, n_layers=4, sp=False):
        kw = dict(vocab_size=32, hidden_size=16, num_layers=n_layers,
                  num_attention_heads=4, max_seq_len=8)
        serial = GPTModel(GPTConfig(**kw))
        par = GPTModel(GPTConfig(
            tensor_parallel_size=tp,
            axis_name="model" if tp > 1 else None,
            sequence_parallel=sp, **kw))
        return serial, par, serial.init_params(jax.random.PRNGKey(3))

    def test_tp2(self):
        _, par, init = self._model(2, sp=True)
        packed, _, _, _ = pack_for_shard_map(par, init)
        _tree_equal(unpack_from_shard_map(par, packed), init)

    def test_pp2(self):
        _, par, init = self._model(1)
        packed, _, _, _ = pack_for_shard_map(par, init, n_stages=2)
        _tree_equal(unpack_from_shard_map(par, packed, n_stages=2), init)

    def test_pp2_tp2(self):
        _, par, init = self._model(2, sp=True)
        packed, _, _, _ = pack_for_shard_map(par, init, n_stages=2,
                                             tensor_axis="model")
        _tree_equal(unpack_from_shard_map(par, packed, n_stages=2), init)

    def test_interleaved_virtual_stages(self):
        _, par, init = self._model(1, n_layers=8)
        packed, _, _, _ = pack_for_shard_map(par, init, n_stages=2,
                                             n_virtual=2)
        _tree_equal(
            unpack_from_shard_map(par, packed, n_stages=2, n_virtual=2),
            init)


# -- HostSignals --------------------------------------------------------------

class TestHostSignals:
    def test_fifo_and_empty(self):
        s = HostSignals()
        assert s.poll() is None
        s.request_preempt()
        s.request_replan(TopologySpec(dp=2))
        first, second = s.poll(), s.poll()
        assert first.kind == "preempt" and first.spec is None
        assert second.kind == "replan" and second.spec == TopologySpec(dp=2)
        assert s.poll() is None

    def test_replan_requires_spec(self):
        with pytest.raises(ValueError):
            ElasticSignal("replan")
        with pytest.raises(ValueError):
            ElasticSignal("bogus")


# -- fault kind ---------------------------------------------------------------

class TestTopologyChangeFault:
    def test_fires_at_step_and_records(self):
        inj = FaultInjector([Fault(step=2, kind="topology_change",
                                   magnitude=4.0)])
        assert inj.check_topology_change(1) is None
        f = inj.check_topology_change(2)
        assert f is not None and f.magnitude == 4.0
        assert inj.check_topology_change(3) is None
        assert (2, "topology_change") in inj.log


# -- ElasticTrainer -----------------------------------------------------------

def _factory(plan, ckpt, inj):
    opt = FusedAdam(lr=1e-2)
    guard = GuardedTrainStep(_loss_fn, opt, warmup_steps=1,
                             checkpoint=ckpt, fault_injector=inj)
    params = plan.put(_params(5))
    return ElasticComponents(guard, params, opt.init(params),
                             guard.init_state())


def _flat(trainer):
    out = list(jax.tree_util.tree_leaves(trainer.params))
    st = trainer.opt_state
    for key in sorted(st["buckets"]):
        for slot in sorted(st["buckets"][key]):
            v = st["buckets"][key][slot]
            out.extend(v if isinstance(v, list) else [v])
    return [np.asarray(x) for x in out]


@needs8
class TestElasticTrainer:
    N = 5

    def _ref(self, tmp_path, spec=TopologySpec(dp=4)):
        ref = ElasticTrainer(_factory, ElasticPlan.build(spec),
                             directory=str(tmp_path / "ref"))
        ref.train(_batch, self.N)
        return _flat(ref)

    def test_injected_shrink_grow_bitwise(self, tmp_path):
        ref = self._ref(tmp_path)
        inj = FaultInjector([Fault(step=1, kind="topology_change"),
                             Fault(step=3, kind="topology_change")])
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"),
                            fault_injector=inj)
        out = tr.train(_batch, self.N)
        assert out == {"status": "completed", "step": self.N, "replans": 2,
                       "preempt_signals": 2, "rollbacks": 0}
        assert tr.plan.spec == TopologySpec(dp=4)
        for a, b in zip(_flat(tr), ref):
            np.testing.assert_array_equal(a, b)
        assert tr.checkpoint.topology_of(self.N) == \
            TopologySpec(dp=4).to_dict()

    def test_host_signal_replan_and_in_place_rebuild(self, tmp_path):
        """A replan request to the SAME spec is an in-place rebuild —
        it must execute (replans += 1) and be bitwise-neutral."""
        ref = self._ref(tmp_path)
        signals = HostSignals()
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"), signals=signals)

        def batch(step, plan):
            if step == 1:
                signals.request_replan(TopologySpec(dp=4))
            return _batch(step, plan)

        out = tr.train(batch, self.N)
        assert out["status"] == "completed" and out["replans"] == 1
        for a, b in zip(_flat(tr), ref):
            np.testing.assert_array_equal(a, b)

    def test_soft_preempt_drains_and_checkpoints(self, tmp_path):
        signals = HostSignals()
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"), signals=signals)

        def batch(step, plan):
            if step == 1:
                signals.request_preempt()
            return _batch(step, plan)

        out = tr.train(batch, self.N)
        assert out["status"] == "preempted" and out["step"] == 2
        # a fresh trainer resumes from the drain checkpoint and matches
        ref = self._ref(tmp_path)
        tr2 = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                             directory=str(tmp_path / "a"))
        out2 = tr2.train(_batch, self.N)
        assert out2["status"] == "completed"
        for a, b in zip(_flat(tr2), ref):
            np.testing.assert_array_equal(a, b)

    def test_hard_preempt_while_shrunk_restores_and_regrows(self, tmp_path):
        """The restart-as-grow path: shrink at step 1, hard kill at
        step 2, fresh dp=4 trainer restores the dp=2-stamped manifest
        (with a mismatch warning), re-shards, resumes — bitwise."""
        ref = self._ref(tmp_path)
        inj = FaultInjector([Fault(step=1, kind="topology_change"),
                             Fault(step=2, kind="preempt_at_step")])
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"),
                            fault_injector=inj)
        with pytest.raises(Preemption):
            tr.train(_batch, self.N)

        tr2 = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                             directory=str(tmp_path / "a"))
        with pytest.warns(UserWarning, match="topology"):
            out = tr2.train(_batch, self.N)
        assert out["status"] == "completed"
        assert tr2.plan.spec == TopologySpec(dp=4)
        for a, b in zip(_flat(tr2), ref):
            np.testing.assert_array_equal(a, b)

    def test_registry_series(self, tmp_path):
        from apex_tpu.observability import MetricsRegistry
        reg = MetricsRegistry()
        inj = FaultInjector([Fault(step=1, kind="topology_change")])
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"),
                            fault_injector=inj, registry=reg)
        tr.train(_batch, 3)
        assert reg.get("elastic_replans").value() == 1
        assert reg.get("elastic_preempt_signals").value() == 1
        assert reg.get("elastic_resume_step").value() == 1
        assert tr.stats["last_reshard_s"] > 0


@needs8
class TestSteppableAPI:
    """The externally-driven surface the capacity controller consumes:
    start/step_once/replan_to must compose to exactly what train()
    does — same steps, same checkpoints, bitwise-same state."""

    N = 5

    def test_step_once_loop_matches_train_bitwise(self, tmp_path):
        ref = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                             directory=str(tmp_path / "ref"))
        ref.train(_batch, self.N)
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"))
        assert tr.start() == 0
        assert tr.start() == 0                   # idempotent no-op
        while tr.current_step < self.N:
            assert tr.step_once(_batch) == "ran"
        assert tr.current_step == self.N
        for a, b in zip(_flat(tr), _flat(ref)):
            np.testing.assert_array_equal(a, b)

    def test_external_replan_to_matches_injected_shrink_grow(self, tmp_path):
        """Driving the SAME shrink->grow cycle through replan_to() as
        an injected topology_change fault produces must land bitwise on
        the uninterrupted reference — the two drain paths are one."""
        ref = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                             directory=str(tmp_path / "ref"))
        ref.train(_batch, self.N)
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"))
        for step in range(self.N):
            if step == 1:
                tr.replan_to(TopologySpec(dp=2))
                assert tr.plan.spec == TopologySpec(dp=2)
            if step == 3:
                tr.replan_to(TopologySpec(dp=4))
            assert tr.step_once(_batch) == "ran"
        assert tr.plan.spec == TopologySpec(dp=4)
        assert tr.stats["last_reshard_s"] > 0
        assert tr.stats["last_checkpoint_s"] > 0
        for a, b in zip(_flat(tr), _flat(ref)):
            np.testing.assert_array_equal(a, b)

    def test_step_once_surfaces_preempt_then_resumes(self, tmp_path):
        signals = HostSignals()
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"), signals=signals)
        assert tr.step_once(_batch) == "ran"
        signals.request_preempt()
        assert tr.step_once(_batch) == "preempted"
        assert tr.current_step == 1              # drained at the boundary
        # the day-in-the-life restart idiom: fresh trainer, same
        # directory, resumes from the drain checkpoint and matches
        ref = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                             directory=str(tmp_path / "ref"))
        ref.train(_batch, self.N)
        tr2 = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                             directory=str(tmp_path / "a"))
        assert tr2.start() == 1
        while tr2.current_step < self.N:
            tr2.step_once(_batch)
        for a, b in zip(_flat(tr2), _flat(ref)):
            np.testing.assert_array_equal(a, b)

    def test_failed_replan_restores_stamp_and_continues(self, tmp_path):
        ref = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                             directory=str(tmp_path / "ref"))
        ref.train(_batch, self.N)
        tr = ElasticTrainer(_factory, ElasticPlan.build(TopologySpec(dp=4)),
                            directory=str(tmp_path / "a"))
        tr.step_once(_batch)
        with pytest.raises(ValueError, match="devices"):
            tr.replan_to(TopologySpec(dp=16))    # only 8 devices exist
        # the failure left the trainer consistent: stamp still dp=4,
        # training continues and still lands bitwise on the reference
        assert tr.plan.spec == TopologySpec(dp=4)
        assert tr.checkpoint.topology_of(tr.current_step) == \
            TopologySpec(dp=4).to_dict()
        while tr.current_step < self.N:
            assert tr.step_once(_batch) == "ran"
        for a, b in zip(_flat(tr), _flat(ref)):
            np.testing.assert_array_equal(a, b)


# -- serving-engine preemption ------------------------------------------------

class TestEnginePreempt:
    def _model(self):
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_attention_heads=2, max_seq_len=16)
        model = GPTModel(cfg)
        return model, model.init_params(jax.random.PRNGKey(0))

    def _reqs(self, n=3):
        return [Request(request_id=i, prompt=[1 + i, 2, 3],
                        max_new_tokens=5) for i in range(n)]

    def test_requeue_token_parity(self):
        model, params = self._model()
        ref_eng = InferenceEngine(model, params, max_slots=2,
                                  cache_dtype=jnp.float32)
        for r in self._reqs():
            ref_eng.submit(r)
        ref = {r.request_id: r.tokens for r in ref_eng.run()}

        eng = InferenceEngine(model, params, max_slots=2,
                              cache_dtype=jnp.float32)
        for r in self._reqs():
            eng.submit(r)
        eng.step()
        eng.step()
        n = eng.preempt()
        assert n >= 1
        assert eng.metrics.summary()["requeued"] == n
        got = {r.request_id: r.tokens for r in eng.run()}
        assert got == ref
        # no leaks across the interruption
        assert eng.trace.pending == 0
        assert eng._progress == {}

    def test_preempt_overflow_finishes_preempted(self):
        """A request whose prompt + generated no longer fits a cache
        row cannot be requeued: it finishes with reason='preempted'.
        The step loop finishes such requests with 'length' first, so
        the branch is defensive — force the state directly."""
        model, params = self._model()
        eng = InferenceEngine(model, params, max_slots=1,
                              cache_dtype=jnp.float32)
        eng.submit(Request(request_id=0, prompt=[1, 2],
                           max_new_tokens=8))
        eng.step()
        st = next(iter(eng._active.values()))
        pad = eng.cache.max_seq - len(st.request.prompt)
        st.generated.extend([1] * (pad - len(st.generated)))
        assert eng.preempt() == 0
        byid = {r.request_id: r for r in eng.completed}
        assert byid[0].finish_reason == "preempted"

    def test_preempt_idle_noop(self):
        model, params = self._model()
        eng = InferenceEngine(model, params, max_slots=1,
                              cache_dtype=jnp.float32)
        assert eng.preempt() == 0
        assert eng.metrics.summary()["requeued"] == 0
