"""apex_tpu.resilience.autopilot: drift detection -> gated adoption.

The contract under test (ROADMAP item 3):

* too few fresh measurements never even refit — and therefore never
  move a plan (absence of data is not evidence of drift OR stability:
  the confirmation streak holds);
* a one-window drift spike is debounced: ``confirm_windows`` refit
  windows must agree before a drift confirms, and a clean window
  RESETS the streak (the ``CapacityController`` hysteresis discipline);
* a confirmed drift re-ranks the plan space against the refreshed
  profile and commits the winner through the measured
  baseline -> drain -> gate protocol;
* an injected ``plan_regression`` inflates the commit-gate measurements
  past ``gate_tolerance`` and the adoption ROLLS BACK —
  ``replan_to(old)`` — as does a replan that raises mid-adoption;
* drifts confirmed while an adoption is busy or cooling down QUEUE
  (coalesced to the latest refit candidate, never a stale pile-up) and
  never interleave; :meth:`ParallelismAutopilot.audit` stays ``[]``;
* appending ``cost_drift``/``plan_regression`` to ``FAULT_KINDS``
  changed no pre-existing ``from_seed`` schedule (rate-0 kinds consume
  no rng stream state), and the consume-once ``check_*`` hooks are
  window-tolerant (a controller tick polls BETWEEN training steps).

The closed loop on a real :class:`ElasticTrainer` (drain, re-shard,
bitwise rollback vs an uninterrupted reference) runs in
``__graft_entry__._dryrun_autopilot`` and
``tools/loadgen.py --scenario autopilot_drift`` — these tests drive a
fake trainer so the CONTROLLER's state machine is what's under test.
"""

import dataclasses
from types import SimpleNamespace

import pytest

from apex_tpu.observability import MetricsRegistry
from apex_tpu.observability.costmodel import (CostFit, fit_cost_model,
                                              simulate_link_measurements)
from apex_tpu.resilience import (Fault, FaultInjector,
                                 ParallelismAutopilot, TopologySpec)
from apex_tpu.resilience.faults import FAULT_KINDS, seeded_schedule

ALPHA0, BETA0 = 2e-3, 1e-9      # dcn-ish: latency dominates small psums
GRAD_BYTES = 144
SERIAL_S = 0.12


class FakeTrainer:
    """The trainer surface the autopilot drives: a plan with a spec,
    a device pool, replan_to, and the drain/re-shard stats."""

    def __init__(self, dp=4, n_devices=4, fail_replans=0):
        self.plan = SimpleNamespace(spec=TopologySpec(dp=dp))
        self._devices = list(range(n_devices))
        self.stats = {"last_checkpoint_s": 1e-3, "last_reshard_s": 2e-3}
        self.current_step = 0
        self.replans = []
        self.params = {}
        self._fail = fail_replans

    def replan_to(self, spec, **kw):
        if self._fail > 0:
            self._fail -= 1
            raise RuntimeError("injected reshard failure")
        self.replans.append(spec)
        self.plan = SimpleNamespace(spec=spec)


def dcn_profile():
    return fit_cost_model(
        simulate_link_measurements(ALPHA0, BETA0, link_class="dcn",
                                   ops=("psum",)),
        meta={"source": "test"})


def step_dt(dp, scale=1.0):
    """The synthetic machine: dp-scalable serial compute + the
    alpha-beta psum price at the current drift scale."""
    fit = CostFit(ALPHA0 * scale, BETA0 * scale)
    comm = fit.predict("psum", GRAD_BYTES, dp) if dp > 1 else 0.0
    return SERIAL_S / dp + comm


def make_autopilot(trainer, clockv, **kw):
    kw.setdefault("min_dp", 2)
    kw.setdefault("link_class", "dcn")
    kw.setdefault("drift_threshold", 0.3)
    kw.setdefault("confirm_windows", 2)
    kw.setdefault("min_measurements", 8)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("gate_steps", 2)
    kw.setdefault("gate_tolerance", 1.2)
    kw.setdefault("grad_bytes", GRAD_BYTES)
    return ParallelismAutopilot(trainer, dcn_profile(),
                                clock=lambda: clockv[0], **kw)


def drive(tr, ap, clockv, n_steps, scale_at, ticks_per_step=2):
    """The train loop shape: one step, one recorded dt, controller
    ticks; ``scale_at(step)`` is the machine's true drift scale."""
    for step in range(tr.current_step, tr.current_step + n_steps):
        tr.current_step = step + 1
        ap.record_step(step_dt(tr.plan.spec.dp, scale_at(step)))
        for _ in range(ticks_per_step):
            ap.tick()
        clockv[0] += 0.1


# -- detection discipline ----------------------------------------------------


class TestDetection:
    def test_too_few_measurements_never_refit_or_replan(self):
        tr = FakeTrainer()
        clockv = [0.0]
        ap = make_autopilot(tr, clockv, min_measurements=8)
        # a trickle of fresh points that stays below the window floor:
        # ticks keep coming, refits never happen, plans never move
        for i in range(20):
            if i < 5:
                ap.observe(simulate_link_measurements(
                    ALPHA0 * 16, BETA0 * 16, link_class="dcn",
                    ops=("psum",), dtypes=("f32",), sizes=(1 << 12,),
                    group_sizes=(2,))[:1])
            tr.current_step += 1
            ap.record_step(step_dt(4, 16.0))
            ap.tick()
            clockv[0] += 0.1
        assert ap.stats["refits"] == 0
        assert ap.stats["drift_confirmed"] == 0
        assert tr.replans == []
        # the buffer was KEPT: once it crosses the floor, one tick fits
        assert len(ap.profile.fresh_measurements) == 5
        ap.observe(simulate_link_measurements(
            ALPHA0 * 16, BETA0 * 16, link_class="dcn", ops=("psum",)))
        ap.tick()
        assert ap.stats["refits"] == 1

    def test_one_window_spike_debounced(self):
        tr = FakeTrainer()
        clockv = [0.0]
        ap = make_autopilot(tr, clockv, confirm_windows=2)
        drifted = simulate_link_measurements(
            ALPHA0 * 16, BETA0 * 16, link_class="dcn", ops=("psum",))
        clean = simulate_link_measurements(
            ALPHA0, BETA0, link_class="dcn", ops=("psum",))
        for window in [drifted, clean, drifted, clean, drifted]:
            ap.observe(window)
            tr.current_step += 1
            ap.record_step(step_dt(4))
            ap.tick()                   # one refit window per tick
            clockv[0] += 0.1
        # every drifted window was isolated: streak reset each time
        assert ap.stats["refits"] == 5
        assert ap.stats["drift_confirmed"] == 0
        assert ap.stats["adoptions"] == 0 and tr.replans == []

    def test_consecutive_windows_confirm(self):
        tr = FakeTrainer()
        clockv = [0.0]
        ap = make_autopilot(tr, clockv, confirm_windows=2)
        drifted = simulate_link_measurements(
            ALPHA0 * 16, BETA0 * 16, link_class="dcn", ops=("psum",))
        for _ in range(2):
            ap.observe(drifted)
            tr.current_step += 1
            ap.record_step(step_dt(4, 16.0))
            ap.tick()
            clockv[0] += 0.1
        assert ap.stats["drift_confirmed"] == 1
        assert ap.stats["last_drift"] == pytest.approx(15.0, rel=1e-3)


# -- the adoption state machine ----------------------------------------------


class TestAdoption:
    def test_confirmed_drift_commits_through_gate(self):
        tr = FakeTrainer(dp=4)
        clockv = [0.0]
        ap = make_autopilot(tr, clockv)
        inj = FaultInjector([Fault(2, "cost_drift", magnitude=16.0)])
        ap.injector = inj
        drive(tr, ap, clockv, 10,
              lambda s: 16.0 if s >= 2 else 1.0)
        assert ap.stats["adoptions"] == 1 and ap.stats["rollbacks"] == 0
        assert tr.plan.spec.dp == 2
        assert [e["outcome"] for e in ap.adoption_log] == ["commit"]
        e = ap.adoption_log[0]
        assert e["drift"] >= ap.drift_threshold and not e["manual"]
        assert e["gate_s"] <= e["baseline_s"] * ap.gate_tolerance
        assert ap.audit() == []
        assert inj.log == [(2, "cost_drift")]

    def test_plan_regression_rolls_back(self):
        tr = FakeTrainer(dp=4)
        clockv = [0.0]
        reg = MetricsRegistry()
        ap = make_autopilot(tr, clockv, registry=reg)
        ap.injector = FaultInjector([
            Fault(2, "cost_drift", magnitude=16.0),
            Fault(2, "plan_regression", magnitude=4.0)])
        drive(tr, ap, clockv, 10,
              lambda s: 16.0 if s >= 2 else 1.0)
        assert ap.stats["adoptions"] == 0 and ap.stats["rollbacks"] == 1
        # the replan happened, then the gate measured the 4x inflation
        # and replanned straight back: [new, old]
        assert [s.dp for s in tr.replans] == [2, 4]
        assert tr.plan.spec.dp == 4
        e = ap.adoption_log[0]
        assert e["outcome"] == "rollback" and e["fault"]
        assert "measured regression" in e["reason"]
        assert reg.get("autopilot_adoptions_total").value(
            outcome="rollback") == 1
        assert reg.get("autopilot_drift_detected").value() == 0
        assert ap.audit() == []

    def test_replan_failure_rolls_back_without_reshard(self):
        tr = FakeTrainer(dp=4, fail_replans=1)
        clockv = [0.0]
        ap = make_autopilot(tr, clockv)
        ap.injector = FaultInjector([
            Fault(2, "cost_drift", magnitude=16.0)])
        drive(tr, ap, clockv, 10,
              lambda s: 16.0 if s >= 2 else 1.0)
        e = ap.adoption_log[0]
        assert e["outcome"] == "rollback"
        assert e["reason"].startswith("replan failed")
        # the forward replan raised, so there was nothing to reshard
        # back from — the trainer never left the old plan
        assert tr.replans == [] and tr.plan.spec.dp == 4
        assert not ap.adopting and ap.audit() == []

    def test_full_cycle_commit_then_regression_rollback(self):
        # the _dryrun_autopilot choreography on the fake trainer:
        # drift 16x -> commit dp 4 -> 2, links recover + injected
        # regression -> gate rollback to dp=2
        tr = FakeTrainer(dp=4)
        clockv = [0.0]
        reg = MetricsRegistry()
        ap = make_autopilot(tr, clockv, cooldown_s=0.5, registry=reg)
        inj = FaultInjector([Fault(2, "cost_drift", magnitude=16.0),
                             Fault(8, "cost_drift", magnitude=1 / 16),
                             Fault(8, "plan_regression", magnitude=4.0)])
        ap.injector = inj

        def scale_at(step):
            return 16.0 if 2 <= step < 8 else 1.0

        drive(tr, ap, clockv, 24, scale_at)
        assert [e["outcome"] for e in ap.adoption_log] \
            == ["commit", "rollback"]
        assert tr.plan.spec.dp == 2
        assert ap.queued == 0 and not ap.adopting
        assert ap.audit() == []
        # counters match the applied-fault log exactly
        assert sorted(inj.log) == [(2, "cost_drift"), (8, "cost_drift"),
                                   (8, "plan_regression")]
        c = reg.get("autopilot_adoptions_total")
        assert (c.value(outcome="commit"),
                c.value(outcome="rollback")) == (1.0, 1.0)


# -- cooldown + queue discipline ---------------------------------------------


class TestCooldownQueue:
    def test_confirmations_during_cooldown_queue_and_coalesce(self):
        tr = FakeTrainer(dp=4)
        clockv = [0.0]
        ap = make_autopilot(tr, clockv, cooldown_s=100.0)
        # a SECOND drift lands mid-cooldown (relative to the profile
        # adopted at the first commit, the machine moves again)
        ap.injector = FaultInjector([
            Fault(2, "cost_drift", magnitude=16.0),
            Fault(9, "cost_drift", magnitude=16.0)])

        def scale_at(step):
            s = 1.0
            if step >= 2:
                s *= 16.0
            if step >= 9:
                s *= 16.0
            return s

        drive(tr, ap, clockv, 10, scale_at)
        assert ap.stats["adoptions"] == 1       # the first commit
        n_replans = len(tr.replans)
        # the re-drifted environment keeps re-confirming during
        # cooldown; every re-confirmation coalesces into ONE pending
        # request
        drive(tr, ap, clockv, 20, scale_at)
        assert ap.stats["drift_confirmed"] >= 2
        assert ap.queued <= 1
        assert len(tr.replans) == n_replans     # nothing interleaved
        assert ap.audit() == []
        # past cooldown expiry the queued request may start; with the
        # plan already optimal for the drifted machine it's a no_change
        clockv[0] += 200.0
        drive(tr, ap, clockv, 2, scale_at)
        assert ap.queued == 0
        assert ap.stats["no_change"] >= 1
        assert len(tr.replans) == n_replans
        assert ap.audit() == []

    def test_manual_request_is_audit_exempt(self):
        tr = FakeTrainer(dp=4)
        clockv = [0.0]
        ap = make_autopilot(tr, clockv)
        for _ in range(4):
            tr.current_step += 1
            ap.record_step(step_dt(4))
        ap.request_adoption()
        drive(tr, ap, clockv, 4, lambda s: 1.0)
        assert ap.adoption_log and ap.adoption_log[0]["manual"]
        assert ap.adoption_log[0]["drift"] is None
        assert ap.audit() == []                 # manual => exempt


# -- constructor validation --------------------------------------------------


class TestValidation:
    @pytest.mark.parametrize("kw", [
        {"drift_threshold": 0.0},
        {"confirm_windows": 0},
        {"gate_steps": 0},
        {"gate_tolerance": 0.9},
        {"refit_every": 0},
    ])
    def test_bad_knobs_refused(self, kw):
        with pytest.raises(ValueError):
            make_autopilot(FakeTrainer(), [0.0], **kw)


# -- fault plumbing ----------------------------------------------------------


class TestFaultKinds:
    def test_new_kinds_appended_last(self):
        assert FAULT_KINDS[-2:] == ("cost_drift", "plan_regression")

    def test_from_seed_schedule_unchanged_by_new_kinds(self):
        idx = FAULT_KINDS.index("cost_drift")
        rates = {k: 0.15 for k in FAULT_KINDS[:idx]}
        inj = FaultInjector.from_seed(5, 40, rates)
        # byte-identical to the schedule over the PRE-EXISTING kind
        # tuple: a rate-0 kind consumes no rng stream state
        expected = seeded_schedule(5, 40, FAULT_KINDS[:idx], rates)
        assert [(f.step, f.kind) for f in inj.schedule] == expected
        assert expected                         # non-vacuous

    def test_check_hooks_window_tolerant_and_consume_once(self):
        inj = FaultInjector([Fault(3, "cost_drift", magnitude=2.0),
                             Fault(5, "plan_regression")])
        assert inj.check_cost_drift(2) is None          # not due yet
        f = inj.check_cost_drift(5)                     # due (late poll)
        assert f is not None and f.step == 3
        assert inj.check_cost_drift(5) is None          # consumed
        assert inj.check_plan_regression(4) is None
        assert inj.check_plan_regression(7) is not None
        assert inj.check_plan_regression(7) is None
        # recorded at the SCHEDULED step, not the poll step
        assert inj.log == [(3, "cost_drift"), (5, "plan_regression")]

    def test_earliest_due_fault_consumed_first(self):
        inj = FaultInjector([Fault(8, "cost_drift", magnitude=0.5),
                             Fault(2, "cost_drift", magnitude=4.0)])
        assert inj.check_cost_drift(10).magnitude == 4.0
        assert inj.check_cost_drift(10).magnitude == 0.5


# -- drift scale semantics ---------------------------------------------------


class TestDriftEnvironment:
    def test_magnitude_scales_profile_and_zero_defaults(self):
        tr = FakeTrainer()
        clockv = [0.0]
        ap = make_autopilot(tr, clockv)
        ap.injector = FaultInjector([Fault(0, "cost_drift")])  # mag 0
        tr.current_step = 1
        ap.tick()
        key = ("psum", "f32", "dcn")
        assert ap._drift_env[key][0] == pytest.approx(ALPHA0 * 2.0)
        # a second fault compounds on the drifted environment
        ap.injector = FaultInjector([Fault(1, "cost_drift",
                                           magnitude=0.5)])
        ap.tick()
        assert ap._drift_env[key][0] == pytest.approx(ALPHA0)
        assert ap.stats["drift_faults"] == 2
