"""apex_tpu.observability.request_trace: per-request lifecycle tracing.

Unit tests run the lifecycle against a fake clock so every derived
quantity (queue wait, prefill, decode, TTFT, TPOT) is exact; the
integration test drives the real continuous-batching engine with a
tracer attached and checks the spans/metrics/records agree.
"""

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.inference import InferenceEngine, Request
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.observability import (
    MetricsRegistry,
    RequestRecord,
    RequestTracer,
    Tracer,
)
from apex_tpu.utils.profiling import ServingMetrics


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class RecordingMetrics:
    """Duck-typed ServingMetrics sink — records the trace's feed."""

    def __init__(self):
        self.admitted = []
        self.ticks = []

    def request_admitted(self, request_id, queue_wait_s):
        self.admitted.append((request_id, queue_wait_s))

    def request_decode_ticks(self, request_id, ticks):
        self.ticks.append((request_id, ticks))


class TestLifecycle:
    def test_full_lifecycle_derived_quantities(self):
        clk = FakeClock()
        rt = RequestTracer(clock=clk)
        rt.enqueue("r1")
        clk.t = 1.0
        rt.admit("r1")
        clk.t = 3.0
        rt.first_token("r1")
        for _ in range(3):
            rt.decode_tick("r1")
        clk.t = 6.0
        rec = rt.finish("r1", "eos")
        assert isinstance(rec, RequestRecord)
        assert rec.queue_wait_s == 1.0
        assert rec.prefill_s == 2.0
        assert rec.decode_s == 3.0
        assert rec.ticks == 3
        # TTFT/TPOT are DERIVED, not separately measured
        assert rec.ttft_s == 3.0
        assert rec.tpot_s == 1.0
        assert rec.reason == "eos" and rec.error is None
        assert rt.pending == 0

    def test_never_admitted(self):
        clk = FakeClock()
        rt = RequestTracer(clock=clk)
        rt.enqueue("r1")
        clk.t = 5.0
        rec = rt.finish("r1", "evicted")
        # queue phase absorbs the whole life; later phases undefined
        assert rec.queue_wait_s == 5.0
        assert rec.prefill_s is None and rec.decode_s is None
        assert rec.ttft_s is None and rec.tpot_s is None

    def test_admitted_without_first_token(self):
        clk = FakeClock()
        rt = RequestTracer(clock=clk)
        rt.enqueue("r1")
        clk.t = 1.0
        rt.admit("r1")
        clk.t = 4.0
        rec = rt.finish("r1", "error", error="RuntimeError")
        # open prefill absorbs time to finish; no decode phase
        assert rec.prefill_s == 3.0 and rec.decode_s is None
        assert rec.ttft_s is None
        assert rec.error == "RuntimeError"

    def test_unknown_or_double_finish_returns_none(self):
        rt = RequestTracer(clock=FakeClock())
        assert rt.finish("ghost", "eos") is None
        rt.enqueue("r1")
        rt.finish("r1", "eos")
        assert rt.finish("r1", "eos") is None
        assert len(rt.records) == 1

    def test_records_bounded(self):
        rt = RequestTracer(clock=FakeClock(), keep=4)
        for i in range(10):
            rt.enqueue(i)
            rt.finish(i, "eos")
        assert len(rt.records) == 4
        assert [r.request_id for r in rt.records] == [6, 7, 8, 9]

    def test_metrics_feed(self):
        clk = FakeClock()
        m = RecordingMetrics()
        rt = RequestTracer(clock=clk, metrics=m)
        rt.enqueue("a")
        clk.t = 2.0
        rt.admit("a")
        rt.first_token("a")
        rt.decode_tick("a")
        rt.decode_tick("a")
        rt.finish("a", "eos")
        # never-admitted request must NOT report decode ticks
        rt.enqueue("b")
        rt.finish("b", "evicted")
        assert m.admitted == [("a", 2.0)]
        assert m.ticks == [("a", 2)]

    def test_summary_percentiles(self):
        clk = FakeClock()
        rt = RequestTracer(clock=clk)
        for i in range(4):
            rt.enqueue(i)
            clk.t += 1.0
            rt.admit(i)
            clk.t += 1.0
            rt.first_token(i)
            rt.decode_tick(i)
            clk.t += 2.0
            rt.finish(i, "eos")
        s = rt.summary()
        assert s["requests"] == 4
        assert s["ttft_p50_s"] == 2.0          # 1s queue + 1s prefill
        assert s["tpot_p50_s"] == 2.0          # 2s decode / 1 tick
        assert s["queue_wait_p50_s"] == 1.0


class TestSpanEmission:
    def test_tracer_clock_wins(self):
        other = FakeClock(100.0)
        tr = Tracer(clock=FakeClock(5.0))
        rt = RequestTracer(clock=other, tracer=tr)
        assert rt.clock is tr.clock

    def test_nested_async_spans_tile_the_request(self):
        clk = FakeClock()
        tr = Tracer(clock=clk)
        rt = RequestTracer(tracer=tr)
        rt.enqueue(7)
        clk.t = 1.0
        rt.admit(7)
        clk.t = 2.0
        rt.first_token(7)
        rt.decode_tick(7)
        clk.t = 5.0
        rt.finish(7, "eos")
        evs = tr.events
        by_name = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        assert set(by_name) == {"request", "queue_wait", "prefill",
                                "decode"}
        for name, pair in by_name.items():
            assert [e["ph"] for e in pair] == ["b", "e"]
            assert all(e["id"] == f"{tr.id_tag}/7" for e in pair)
            assert all(e["cat"] == "request" for e in pair)
        # µs timestamps tile: queue 0-1s, prefill 1-2s, decode 2-5s
        def span_us(name):
            b, e = by_name[name]
            return b["ts"], e["ts"]
        assert span_us("request") == (0.0, pytest.approx(5e6))
        assert span_us("queue_wait") == (0.0, pytest.approx(1e6))
        assert span_us("prefill") == (pytest.approx(1e6),
                                      pytest.approx(2e6))
        assert span_us("decode") == (pytest.approx(2e6),
                                     pytest.approx(5e6))
        req_b = by_name["request"][0]
        assert req_b["args"] == {"reason": "eos", "ticks": 1}
        assert by_name["decode"][0]["args"] == {"ticks": 1}

    def test_error_recorded_on_request_span(self):
        tr = Tracer(clock=FakeClock())
        rt = RequestTracer(tracer=tr)
        rt.enqueue(1)
        rt.finish(1, "error", error="ValueError")
        req = [e for e in tr.events
               if e["name"] == "request" and e["ph"] == "b"][0]
        assert req["args"]["error"] == "ValueError"
        # no prefill/decode spans for a request that never ran
        assert {e["name"] for e in tr.events} == {"request",
                                                  "queue_wait"}


class TestEngineIntegration:
    def _engine(self, **kw):
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_attention_heads=2, max_seq_len=16)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        return InferenceEngine(model, params, max_slots=2,
                               cache_dtype=jnp.float32, **kw)

    def test_engine_populates_trace_and_spans(self):
        t = [0.0]

        def clock():
            t[0] += 0.25
            return t[0]

        tr = Tracer(clock=clock)
        reg = MetricsRegistry()
        eng = self._engine(
            tracer=tr,
            metrics=ServingMetrics(clock, registry=reg))
        for i in range(3):
            eng.submit(Request(request_id=i, prompt=[1 + i, 2],
                               max_new_tokens=3))
        out = eng.run()
        assert len(out) == 3
        assert eng.trace.pending == 0          # no leaked live entries
        recs = {r.request_id: r for r in eng.trace.records}
        assert set(recs) == {0, 1, 2}
        for r in recs.values():
            assert r.reason == "length"
            assert r.ttft_s is not None and r.ttft_s > 0
            assert r.ticks == 2                # 3 tokens = first + 2
            assert r.tpot_s is not None and r.tpot_s > 0
        # every request got the four nested async spans
        names = [e["name"] for e in tr.events if e["ph"] == "b"]
        assert names.count("request") == 3
        assert names.count("decode") == 3
        # the trace fed ServingMetrics: queue-wait + decode-tick series
        assert eng.metrics._h_queue_wait.count() == 3
        assert list(eng.metrics.decode_ticks) == [2, 2, 2]
        s = eng.metrics.summary()
        assert s["queue_wait_p50_s"] >= 0.0
        assert s["decode_ticks_p50"] == 2

    def test_eviction_reason_reaches_records(self):
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]

        eng = self._engine(clock=clock)
        eng.submit(Request(request_id=0, prompt=[1, 2],
                           max_new_tokens=100, deadline=20.0))
        (r,) = eng.run(max_steps=100)
        assert r.finish_reason == "evicted"
        (rec,) = eng.trace.records
        assert rec.reason == "evicted"
        assert eng.trace.pending == 0

    def test_default_engine_has_trace_without_tracer(self):
        eng = self._engine()
        eng.submit(Request(request_id=0, prompt=[1, 2],
                           max_new_tokens=2))
        eng.run()
        assert eng.trace.tracer is None
        assert len(eng.trace.records) == 1
        assert eng.trace.summary()["requests"] == 1
