"""apex_tpu.observability.costmodel: alpha-beta ring fits + profiles.

The contract under test (ISSUE 7):

* the ring primitives (``ring_hops`` / ``ring_wire_bytes``) apply the
  same factors as ``comms.wire_bytes`` — all-reduce ``2(k-1)`` hops and
  ``2(k-1)/k`` wire, gather/scatter ``k-1`` and ``(k-1)/k``, permute
  one hop at factor 1;
* the least-squares fit recovers planted (alpha, beta) coefficients
  from synthetic measurements exactly, clamps negative coefficients,
  and handles degenerate single-point curves;
* ``CostModel.predict`` falls back across dtypes (missing dtype ->
  f32 -> any curve for the op) but raises on an unknown OP;
* ``validate`` reports the worst two-sided ratio; ``holdout_split``
  never holds out a curve's endpoints;
* the profile JSON round-trips fits + measurements and refuses a
  version it doesn't understand — while a version-LESS (pre-stamp)
  profile still loads, with a warning;
* ``save`` stamps staleness metadata (``probed_at`` +
  ``n_measurements``) and ``profile_age`` / ``is_stale`` gate on it —
  a never-stamped profile is always stale;
* the incremental refit path (ROADMAP item 3): ``update`` buffers
  without fitting, ``refit`` declines below ``min_measurements`` and
  KEEPS the buffer, recovers a planted drift in its ``drift_report``,
  merges un-remeasured curves from the old model, never mutates
  ``self``, and its fits stay within the two-sided ``validate`` ratio
  on a held-out split.

The probe itself (device timing) runs in ``__graft_entry__``'s
``_dryrun_costmodel`` leg on the multi-device CPU mesh — tier-1 runs
single-device, so these tests are host-only math.
"""

import json

import pytest

from apex_tpu.observability.costmodel import (
    COLLECTIVE_OPS,
    HLO_KIND_TO_OP,
    PROFILE_VERSION,
    CostFit,
    CostModel,
    Measurement,
    _lstsq_fit,
    _payload_bytes,
    fit_cost_model,
    holdout_split,
    load_profile,
    ring_hops,
    ring_wire_bytes,
    simulate_link_measurements,
)


def synthetic(op, dtype, alpha, beta, sizes, k=4):
    """Measurements lying exactly on a planted alpha-beta curve."""
    return [Measurement(op=op, dtype=dtype, group_size=k, nbytes=n,
                        time_s=alpha * ring_hops(op, k)
                        + beta * ring_wire_bytes(op, n, k))
            for n in sizes]


class TestRingPrimitives:
    def test_hops(self):
        assert ring_hops("psum", 4) == 6.0          # 2(k-1)
        assert ring_hops("all_gather", 4) == 3.0    # k-1
        assert ring_hops("psum_scatter", 8) == 7.0
        assert ring_hops("ppermute", 8) == 1.0
        with pytest.raises(ValueError):
            ring_hops("all_to_all", 4)

    def test_wire_bytes_factors(self):
        n = 1024
        assert ring_wire_bytes("psum", n, 4) == n * 2 * 3 / 4
        assert ring_wire_bytes("all_gather", n, 4) == n * 3 / 4
        assert ring_wire_bytes("psum_scatter", n, 8) == n * 7 / 8
        assert ring_wire_bytes("ppermute", n, 8) == float(n)
        with pytest.raises(ValueError):
            ring_wire_bytes("bogus", n, 2)

    def test_payload_convention(self):
        # all_gather payload is the gathered RESULT (largest shape on
        # the instruction); everything else the per-device operand
        assert _payload_bytes("all_gather", "f32", 100, 4) == 1600
        assert _payload_bytes("psum", "f32", 100, 4) == 400
        assert _payload_bytes("psum_scatter", "int8", 100, 4) == 100
        assert _payload_bytes("ppermute", "bf16", 100, 4) == 200

    def test_hlo_kind_mapping_covers_comms_kinds(self):
        assert HLO_KIND_TO_OP["all_reduce"] == "psum"
        assert HLO_KIND_TO_OP["reduce_scatter"] == "psum_scatter"
        assert set(HLO_KIND_TO_OP.values()) <= set(COLLECTIVE_OPS)


class TestFit:
    def test_recovers_planted_coefficients(self):
        alpha, beta = 5e-6, 2e-9
        ms = synthetic("psum", "f32", alpha, beta,
                       sizes=(4096, 16384, 65536, 262144))
        model = fit_cost_model(ms)
        fit = model.fits[("psum", "f32")]
        assert fit.alpha_s == pytest.approx(alpha, rel=1e-6)
        assert fit.beta_s_per_byte == pytest.approx(beta, rel=1e-6)
        assert fit.max_rel_err < 1e-9
        assert fit.n_points == 4

    def test_one_curve_per_op_dtype(self):
        ms = (synthetic("psum", "f32", 1e-6, 1e-9, (1024, 4096))
              + synthetic("psum", "int8", 1e-6, 5e-10, (1024, 4096))
              + synthetic("ppermute", "f32", 2e-6, 1e-9, (1024, 4096)))
        model = fit_cost_model(ms)
        assert set(model.fits) == {("psum", "f32"), ("psum", "int8"),
                                   ("ppermute", "f32")}

    def test_negative_beta_clamped(self):
        # times DECREASING with size is noise; beta must clamp to 0 and
        # alpha refit non-negative, never extrapolate negatively
        rows = [(2.0, 100.0, 1.0), (2.0, 1000.0, 0.5)]
        alpha, beta = _lstsq_fit(rows)
        assert beta == 0.0 and alpha >= 0.0

    def test_single_point_latency_only(self):
        alpha, beta = _lstsq_fit([(2.0, 512.0, 1e-3)])
        assert beta == 0.0 and alpha == pytest.approx(5e-4)

    def test_predict_monotone_in_size_and_group(self):
        model = fit_cost_model(
            synthetic("all_gather", "f32", 1e-6, 1e-9,
                      (4096, 65536, 1048576)))
        p1 = model.predict("all_gather", 1 << 12, 2)
        p2 = model.predict("all_gather", 1 << 16, 2)
        p3 = model.predict("all_gather", 1 << 16, 8)
        assert p1 < p2 < p3


class TestCostModel:
    def _model(self):
        return fit_cost_model(
            synthetic("psum", "f32", 1e-6, 2e-9, (4096, 65536))
            + synthetic("psum", "int8", 1e-6, 1e-9, (4096, 65536)))

    def test_dtype_fallback_chain(self):
        model = self._model()
        # exact dtype
        assert model.predict("psum", 4096, 2, dtype="int8") \
            < model.predict("psum", 4096, 2, dtype="f32")
        # un-probed dtype falls back to f32
        assert model.predict("psum", 4096, 2, dtype="bf16") \
            == model.predict("psum", 4096, 2, dtype="f32")
        # op with no f32 curve falls back to any curve for the op
        only_i8 = fit_cost_model(
            synthetic("ppermute", "int8", 1e-6, 1e-9, (4096, 65536)))
        assert only_i8.predict("ppermute", 4096, 2, dtype="bf16") > 0

    def test_unknown_op_raises(self):
        with pytest.raises(ValueError, match="unknown collective op"):
            self._model().predict("all_to_all", 4096, 2)

    def test_validate_two_sided_ratio(self):
        model = self._model()
        good = Measurement("psum", "f32", 2, 4096,
                           model.predict("psum", 4096, 2))
        slow = Measurement("psum", "f32", 2, 4096,
                           model.predict("psum", 4096, 2) * 3.0)
        report = model.validate([good, slow], tolerance=2.0)
        assert report["n"] == 2
        assert report["worst_ratio"] == pytest.approx(3.0)
        assert not report["within_tolerance"]
        # under-prediction counts the same as over-prediction
        fast = Measurement("psum", "f32", 2, 4096,
                           model.predict("psum", 4096, 2) / 3.0)
        assert model.validate([fast])["worst_ratio"] == pytest.approx(3.0)
        assert model.validate([good], tolerance=2.0)["within_tolerance"]

    def test_predict_stats(self):
        model = self._model()
        stats = {"all_reduce": {"count": 2, "bytes": 8192,
                                "ops": [{"bytes": 4096, "group_size": 2},
                                        {"bytes": 4096, "group_size": 0}]},
                 "all_gather": {"count": 0, "bytes": 0, "ops": []}}
        out = model.predict_stats(stats, group_size=4)
        assert out["all_reduce"]["modeled_as"] == "psum"
        assert out["all_reduce"]["count"] == 2
        # second op had no parsed group -> fallback group_size=4
        expect = (model.predict("psum", 4096, 2)
                  + model.predict("psum", 4096, 4))
        assert out["total_s"] == pytest.approx(expect)
        assert "all_gather" not in out       # zero-count kinds skipped


class TestHoldoutSplit:
    def _curve(self, n, op="psum", dtype="f32", k=2):
        return [Measurement(op, dtype, k, 1 << (10 + i), 1e-3 * (i + 1))
                for i in range(n)]

    def test_endpoints_never_held_out(self):
        ms = self._curve(7)
        train, held = holdout_split(ms, every=3)
        assert len(train) + len(held) == 7
        assert held                       # something was held out
        nbytes = sorted(m.nbytes for m in ms)
        held_sizes = {m.nbytes for m in held}
        assert nbytes[0] not in held_sizes
        assert nbytes[-1] not in held_sizes

    def test_tiny_curves_fully_trained(self):
        train, held = holdout_split(self._curve(2), every=3)
        assert len(train) == 2 and not held

    def test_per_curve_isolation(self):
        ms = self._curve(5) + self._curve(5, op="ppermute")
        train, held = holdout_split(ms, every=3)
        assert {m.op for m in held} == {"psum", "ppermute"}


class TestProfileJson:
    def test_round_trip(self, tmp_path):
        ms = synthetic("psum", "f32", 1e-6, 2e-9, (4096, 65536))
        model = fit_cost_model(ms, meta={"backend": "cpu"})
        path = str(tmp_path / "profile.json")
        model.save(path, measurements=ms)
        loaded, lm = load_profile(path)
        assert loaded.meta["backend"] == "cpu"
        assert set(loaded.fits) == set(model.fits)
        assert loaded.predict("psum", 12345, 4) \
            == model.predict("psum", 12345, 4)
        assert [m.to_dict() for m in lm] == [m.to_dict() for m in ms]

    def test_version_refused(self, tmp_path):
        doc = CostModel({("psum", "f32"): CostFit(1e-6, 1e-9)}).to_json()
        assert doc["version"] == PROFILE_VERSION
        doc["version"] = PROFILE_VERSION + 1
        path = tmp_path / "stale.json"
        path.write_text(json.dumps(doc))
        with pytest.raises(ValueError, match="comms_probe"):
            load_profile(str(path))

    def test_measurements_optional(self, tmp_path):
        model = fit_cost_model(
            synthetic("psum", "f32", 1e-6, 2e-9, (4096, 65536)))
        path = str(tmp_path / "bare.json")
        model.save(path)
        _, ms = load_profile(path)
        assert ms == []

    def test_versionless_profile_loads_with_warning(self, tmp_path):
        model = fit_cost_model(
            synthetic("psum", "f32", 1e-6, 2e-9, (4096, 65536)))
        doc = model.to_json()
        del doc["version"]
        path = tmp_path / "prehistoric.json"
        path.write_text(json.dumps(doc))
        with pytest.warns(UserWarning, match="no version"):
            loaded, _ = load_profile(str(path))
        assert loaded.predict("psum", 4096, 2) \
            == model.predict("psum", 4096, 2)


class TestStaleness:
    def test_save_stamps_probe_metadata(self, tmp_path):
        ms = synthetic("psum", "f32", 1e-6, 2e-9, (4096, 65536))
        model = fit_cost_model(ms)
        path = str(tmp_path / "profile.json")
        model.save(path, measurements=ms)
        loaded, _ = load_profile(path)
        assert loaded.meta["n_measurements"] == len(ms)
        t0 = loaded.meta["probed_at"]
        assert loaded.profile_age(now=t0 + 10.0) == pytest.approx(10.0)
        assert not loaded.is_stale(3600.0, now=t0 + 10.0)
        assert loaded.is_stale(3600.0, now=t0 + 7200.0)

    def test_existing_stamp_not_overwritten(self, tmp_path):
        ms = synthetic("psum", "f32", 1e-6, 2e-9, (4096, 65536))
        model = fit_cost_model(ms, meta={"probed_at": 1234.5})
        path = str(tmp_path / "profile.json")
        model.save(path, measurements=ms)
        loaded, _ = load_profile(path)
        assert loaded.meta["probed_at"] == 1234.5

    def test_never_stamped_always_stale(self):
        model = fit_cost_model(
            synthetic("psum", "f32", 1e-6, 2e-9, (4096, 65536)))
        assert model.profile_age() is None
        assert model.is_stale(1e18)     # any gate: no stamp => stale


class TestRefit:
    def _base(self):
        return fit_cost_model(
            simulate_link_measurements(1e-6, 1e-9, link_class="ici",
                                       ops=("psum",))
            + simulate_link_measurements(2e-3, 1e-9, link_class="dcn",
                                         ops=("psum",)))

    def test_update_buffers_without_fitting(self):
        model = self._base()
        before = dict(model.curves())
        n = model.update(simulate_link_measurements(
            2e-6, 2e-9, link_class="ici", ops=("psum",)))
        assert n == len(model.fresh_measurements) > 0
        assert dict(model.curves()) == before   # nothing fitted yet

    def test_too_few_declines_and_keeps_buffer(self):
        model = self._base()
        pts = simulate_link_measurements(
            2e-6, 2e-9, link_class="ici", ops=("psum",))[:3]
        model.update(pts)
        res = model.refit(min_measurements=8)
        assert not res["refitted"]
        assert "3" in res["reason"]
        assert len(model.fresh_measurements) == 3   # buffer KEPT
        # topping up past the floor succeeds and clears the buffer
        model.update(simulate_link_measurements(
            2e-6, 2e-9, link_class="ici", ops=("psum",)))
        assert model.refit(min_measurements=8)["refitted"]
        assert model.fresh_measurements == ()

    def test_recovers_planted_drift(self):
        model = self._base()
        model.update(simulate_link_measurements(
            2e-6, 2e-9, link_class="ici", ops=("psum",)))
        res = model.refit(min_measurements=8)
        assert res["refitted"]
        # everything doubled => worst |t_new/t_old - 1| == 1.0
        assert res["drift"]["max_drift"] == pytest.approx(1.0, rel=1e-3)
        assert ("psum|f32|ici" in res["drift"]["curves"])
        new = res["model"]
        assert new.predict("psum", 1 << 16, 4, link_class="ici") \
            == pytest.approx(
                2 * model.predict("psum", 1 << 16, 4, link_class="ici"),
                rel=1e-3)

    def test_unremeasured_curves_merge_and_self_unmutated(self):
        model = self._base()
        old_dcn = model.predict("psum", 1 << 16, 4, link_class="dcn")
        old_ici = model.predict("psum", 1 << 16, 4, link_class="ici")
        model.update(simulate_link_measurements(
            4e-6, 4e-9, link_class="ici", ops=("psum",)))
        new = model.refit(min_measurements=8)["model"]
        # only ici was re-measured; the dcn tier keeps the old fit
        assert new.predict("psum", 1 << 16, 4, link_class="dcn") \
            == old_dcn
        assert new.predict("psum", 1 << 16, 4, link_class="ici") \
            == pytest.approx(4 * old_ici, rel=1e-3)
        # the caller owns adoption: self never moved
        assert model.predict("psum", 1 << 16, 4, link_class="ici") \
            == old_ici

    def test_refit_stamps_staleness_metadata(self):
        model = self._base()
        model.update(simulate_link_measurements(
            2e-6, 2e-9, link_class="ici", ops=("psum",)))
        n_fresh = len(model.fresh_measurements)
        new = model.refit(min_measurements=8, now=777.0)["model"]
        assert new.meta["probed_at"] == 777.0
        assert new.meta["n_measurements"] == n_fresh
        assert not new.is_stale(10.0, now=780.0)

    def test_refit_within_validate_on_holdout(self):
        model = self._base()
        pts = simulate_link_measurements(
            3e-6, 3e-9, link_class="ici", ops=("psum",),
            sizes=(1 << 12, 1 << 14, 1 << 16, 1 << 18, 1 << 20))
        train, held = holdout_split(pts, every=3)
        assert held
        model.update(train)
        new = model.refit(min_measurements=8)["model"]
        report = new.validate(held, tolerance=2.0)
        assert report["within_tolerance"], report
