"""apex_tpu.observability.anatomy: measured critical-path attribution.

The contract under test (ISSUE 20):

* ``synthesize_events`` -> ``reconstruct`` round-trips a ``simulate()``
  schedule exactly — op census, per-stage order, makespan — from any
  of the three accepted trace forms (event list, Chrome trace dict,
  JSON string);
* ``attribute`` partitions every stage's window into the five
  categories with per-stage sums equal to the makespan (telescoping
  cursor walk — exact, not approximate), and a slow DCN edge shows up
  as ``exposed_dcn``, not as unexplained ``host_gap``;
* ``diff_timelines`` self-diffs clean (drift ~ 0, per-op ratios cover
  EVERY op), divides out a uniform slowdown (median normalization:
  that is curve drift, the cost model's job), and flags the two
  structural failures it exists for — an injected slow-DCN world
  (unpredicted bubbles) and injected op reordering;
* ``ParallelismAutopilot.observe_anatomy`` debounces the structural
  score over ``confirm_windows``, queues ONE coalesced adoption pass
  tagged ``source="anatomy"``, and the audit trail stays clean;
* the ``tools/step_anatomy.py`` ``--json`` schema is pinned — it is
  the machine interface other tooling parses.

The real-engine path (``measure_ops=True`` on a dp2 x pp2 CPU mesh)
runs in ``__graft_entry__._dryrun_anatomy`` and ``bench.py --legs
anatomy``; these tests drive the pure-host layers so they stay cheap.
"""

import importlib
import json
import math
import os
import sys
from types import SimpleNamespace

import pytest

from apex_tpu.mpmd.schedule import SCHEDULES, edge_link_classes, simulate
from apex_tpu.observability.anatomy import (
    CATEGORIES, MeasuredTimeline, attribute, attribution_counter_events,
    diff_timelines, reconstruct, render_attribution_table, render_diff,
    synthesize_events)
from apex_tpu.observability.costmodel import (fit_cost_model,
                                              simulate_link_measurements)
from apex_tpu.resilience import ParallelismAutopilot, TopologySpec

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools")

S, M = 4, 8
T_FWD, T_BWD = 1.0, 2.0
ICI_S, DCN_S = 0.05, 1.5


def _import_tool(name):
    sys.path.insert(0, _TOOLS)
    try:
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


def make_sim(*, t_fwd=T_FWD, t_bwd=T_BWD, ici=ICI_S, dcn=DCN_S,
             schedule="1f1b", s=S, m=M, pods=2):
    classes = edge_link_classes(s, pods)
    link = {e: (dcn if lc == "dcn" else ici)
            for e, lc in classes.items()}
    return simulate(SCHEDULES[schedule](s, m), s, m, t_fwd=t_fwd,
                    t_bwd=t_bwd, link_seconds=link,
                    link_classes=classes, blocking_sends=False)


@pytest.fixture(scope="module")
def sim():
    return make_sim()


@pytest.fixture(scope="module")
def timeline(sim):
    return reconstruct(synthesize_events(sim, n_stages=S,
                                         n_microbatches=M))


# -- reconstruction -----------------------------------------------------------


def test_round_trip_census_and_order(sim, timeline):
    tl = timeline
    assert tl.n_stages == S and tl.n_microbatches == M
    assert len(tl.ops) == 2 * S * M
    assert tl.schedule == "1f1b" and tl.step == 0
    assert tl.makespan == pytest.approx(sim["makespan"])
    # per-stage measured order is the simulated issue order exactly
    sim_order = {}
    for r in sim["op_times"]:
        sim_order.setdefault(int(r["stage"]), []).append(
            (r["kind"], int(r["mb"])))
    for s in range(S):
        got = [(o["kind"], o["mb"]) for o in tl.stage_ops(s)]
        assert got == sim_order[s], f"stage {s} order diverged"
    # and the Op-vocabulary view matches row-for-row
    for op, o in zip(tl.order(), tl.ops, strict=True):
        assert (op.stage, op.kind, op.mb) == (o["stage"], o["kind"],
                                              o["mb"])


def test_reconstruct_accepts_all_trace_forms(sim, timeline):
    evs = synthesize_events(sim, n_stages=S, n_microbatches=M)
    for form in (evs, {"traceEvents": evs},
                 json.dumps({"traceEvents": evs}), json.dumps(evs)):
        tl = reconstruct(form)
        assert len(tl.ops) == len(timeline.ops)
        assert tl.makespan == pytest.approx(timeline.makespan)


def test_reconstruct_step_selection(sim):
    evs = (synthesize_events(sim, n_stages=S, n_microbatches=M, step=3)
           + synthesize_events(sim, n_stages=S, n_microbatches=M,
                               step=7, t0=100.0))
    assert reconstruct(evs).step == 7          # default: newest
    assert reconstruct(evs, step=3).step == 3
    with pytest.raises(ValueError, match="not in trace"):
        reconstruct(evs, step=5)


def test_reconstruct_rejects_bad_traces(sim):
    with pytest.raises(ValueError, match="no 'mpmd_op' events"):
        reconstruct([{"name": "something_else", "ph": "X"}])
    evs = synthesize_events(sim, n_stages=S, n_microbatches=M)
    dup = [e for e in evs if e["name"] == "mpmd_op"][0]
    with pytest.raises(ValueError, match="duplicate op event"):
        reconstruct(evs + [dup])


# -- attribution --------------------------------------------------------------


def test_attribution_sums_exact(timeline):
    attr = attribute(timeline)
    assert attr["makespan"] == pytest.approx(timeline.makespan)
    for st in attr["per_stage"]:
        assert sum(st[c] for c in CATEGORIES) == pytest.approx(
            st["total"])
        err = abs(st["total"] - attr["makespan"]) / attr["makespan"]
        assert err < 1e-9, (st["stage"], err)
        for seg in st["segments"]:        # segments tile monotonically
            assert seg["t1"] >= seg["t0"]
            assert seg["category"] in CATEGORIES
    assert sum(attr["fractions"][c] for c in CATEGORIES) \
        == pytest.approx(1.0)
    for c in CATEGORIES:
        assert attr["totals"][c] == pytest.approx(
            sum(st[c] for st in attr["per_stage"]))


def test_slow_dcn_is_exposed_not_unexplained(timeline):
    attr = attribute(timeline)
    # the 1.5s DCN edge vs 0.05s ICI: waiting on it must be billed to
    # exposed_dcn, dominate exposed_ici, and leave nothing mysterious
    assert attr["fractions"]["exposed_dcn"] > 0.0
    assert (attr["totals"]["exposed_dcn"]
            > attr["totals"]["exposed_ici"])
    assert attr["fractions"]["host_gap"] == pytest.approx(0.0)
    fast = attribute(reconstruct(synthesize_events(
        make_sim(dcn=ICI_S), n_stages=S, n_microbatches=M)))
    assert (attr["fractions"]["exposed_dcn"]
            > fast["fractions"]["exposed_dcn"])


def test_counter_events_one_hot(timeline):
    attr = attribute(timeline)
    evs = attribution_counter_events(attr)
    lanes = {e["name"] for e in evs}
    assert lanes == {f"anatomy/stage{s}" for s in range(S)}
    for e in evs:
        assert e["ph"] == "C"
        assert set(e["args"]) == set(CATEGORIES)
        assert sum(e["args"].values()) in (0, 1)   # one-hot or closing
    n_segs = sum(len(st["segments"]) for st in attr["per_stage"])
    assert len(evs) == n_segs + S                  # + one zero row each


# -- the differ ---------------------------------------------------------------


def test_self_diff_is_clean(sim, timeline):
    d = diff_timelines(timeline, sim)
    assert d["n_ops"] == d["matched"] == 2 * S * M
    assert len(d["ratios"]) == 2 * S * M           # EVERY op has a ratio
    assert not d["missing"] and not d["extra"] and not d["misordered"]
    assert d["median_ratio"] == pytest.approx(1.0)
    assert d["makespan_ratio"] == pytest.approx(1.0)
    assert d["drift_score"] < 1e-9


def test_uniform_slowdown_is_not_structural_drift(sim):
    # 2x everything: curve drift, the cost model's business — the
    # median normalization must divide it out of the structural score
    slow = reconstruct(synthesize_events(
        make_sim(t_fwd=2 * T_FWD, t_bwd=2 * T_BWD, ici=2 * ICI_S,
                 dcn=2 * DCN_S), n_stages=S, n_microbatches=M))
    d = diff_timelines(slow, sim)
    assert d["median_ratio"] == pytest.approx(2.0)
    assert d["max_ratio_deviation"] < 1e-9
    assert d["drift_score"] < 1e-6


def test_differ_flags_injected_slow_dcn(sim):
    # the world's DCN got 4x slower but the prediction still prices it
    # healthy: ops run on time, the stages just WAIT — unpredicted
    # bubbles, a structural signal past the autopilot threshold
    chaos = reconstruct(synthesize_events(
        make_sim(dcn=4 * DCN_S), n_stages=S, n_microbatches=M))
    d = diff_timelines(chaos, sim)
    assert d["matched"] == d["n_ops"]              # same ops, same order
    assert d["max_ratio_deviation"] < 1e-9         # op durations clean
    assert d["unpredicted_bubble_fraction"] > 0.1
    assert d["drift_score"] == pytest.approx(
        d["unpredicted_bubble_fraction"])
    clean = diff_timelines(reconstruct(synthesize_events(
        sim, n_stages=S, n_microbatches=M)), sim)
    assert d["drift_score"] > 100 * max(clean["drift_score"], 1e-12)


def test_differ_flags_injected_reordering(sim, timeline):
    ops = [dict(o) for o in timeline.ops]
    swapped = [i for i, o in enumerate(ops) if o["stage"] == 1][:2]
    a, b = swapped
    for k in ("kind", "mb"):                       # swap identities,
        ops[a][k], ops[b][k] = ops[b][k], ops[a][k]  # keep the slots
    mangled = MeasuredTimeline(
        n_stages=S, n_microbatches=M, ops=ops,
        xfers=timeline.xfers, schedule=timeline.schedule,
        step=timeline.step)
    d = diff_timelines(mangled, sim)
    assert len(d["misordered"]) == 2
    assert all(r["stage"] == 1 for r in d["misordered"])
    assert d["drift_score"] >= 2 / (2 * S * M)


def test_fold_last_fwd_matches_engine_execution_model():
    # the engine runs the last stage as ONE joint fwd+bwd program per
    # microbatch: 2SM - M measured ops; fold_last_fwd merges the
    # prediction to the same shape so the diff covers every op
    s, m = 2, 2
    sim2 = make_sim(s=s, m=m)
    tl = reconstruct(synthesize_events(sim2, n_stages=s,
                                       n_microbatches=m))
    folded = []
    by_key = {(o["stage"], o["kind"], o["mb"]): dict(o)
              for o in tl.ops}
    for o in tl.ops:
        if o["stage"] == s - 1 and o["kind"] == "fwd":
            continue
        row = dict(o)
        if o["stage"] == s - 1 and o["kind"] == "bwd":
            fwd = by_key[(s - 1, "fwd", o["mb"])]
            row["start"] = fwd["start"]            # joint program span
            row["folded_fwd"] = True
        folded.append(row)
    folded.sort(key=lambda o: (o["start"], o["stage"]))
    jtl = MeasuredTimeline(n_stages=s, n_microbatches=m, ops=folded,
                           xfers=tl.xfers, schedule=tl.schedule,
                           step=tl.step)
    assert len(jtl.ops) == 2 * s * m - m
    d = diff_timelines(jtl, sim2, fold_last_fwd=True)
    assert d["n_ops"] == d["matched"] == 2 * s * m - m
    assert not d["missing"] and not d["extra"]
    assert d["drift_score"] < 1e-6
    attr = attribute(jtl)                          # still sums exactly
    for st in attr["per_stage"]:
        assert abs(st["total"] - attr["makespan"]) \
            < 1e-9 * attr["makespan"]


def test_renderers_smoke(sim, timeline):
    attr = attribute(timeline)
    table = render_attribution_table(attr)
    assert "makespan" in table and "exposed_dcn" in table
    assert "1.0000" in table                       # fractions row closes
    text = render_diff(diff_timelines(timeline, sim))
    assert "drift_score" in text
    assert f"ops matched {2 * S * M}/{2 * S * M}" in text


# -- the autopilot's structural channel ---------------------------------------


def _autopilot(**kw):
    cur = TopologySpec(dp=2)
    trainer = SimpleNamespace(
        plan=SimpleNamespace(spec=cur), _devices=list(range(4)),
        stats={"last_checkpoint_s": 1e-3, "last_reshard_s": 2e-3},
        current_step=0, replans=[], params={})
    profile = fit_cost_model(
        simulate_link_measurements(2e-3, 1e-9, link_class="dcn",
                                   ops=("psum",)),
        meta={"source": "test"})
    kw.setdefault("ranker",
                  lambda prof: [{"spec": cur, "predicted_s": 0.1}])
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("structural_threshold", 0.3)
    return ParallelismAutopilot(trainer, profile, min_dp=2,
                                link_class="dcn", **kw)


def test_observe_anatomy_debounces_and_queues(sim):
    chaos = diff_timelines(reconstruct(synthesize_events(
        make_sim(dcn=8 * DCN_S), n_stages=S, n_microbatches=M)), sim)
    assert chaos["drift_score"] >= 0.3
    ap = _autopilot()
    ap.record_step(0.1)
    assert not ap.observe_anatomy(chaos)           # window 1: no confirm
    assert ap.observe_anatomy(chaos)               # window 2: confirmed
    assert ap.stats["structural_confirmed"] == 1
    assert ap.stats["last_structural"] == pytest.approx(
        chaos["drift_score"])
    assert ap.queued == 1
    # an ongoing divergence re-confirms: coalesce, never pile up
    assert not ap.observe_anatomy(chaos)
    assert ap.observe_anatomy(chaos)
    assert ap.stats["structural_confirmed"] == 2
    assert ap.queued == 1
    ap.tick()
    entry = ap.adoption_log[0]
    assert entry["source"] == "anatomy"
    assert entry["outcome"] == "no_change"
    assert entry["drift"] == pytest.approx(chaos["drift_score"])
    assert entry["detail"]["unpredicted_bubble_fraction"] \
        == pytest.approx(chaos["unpredicted_bubble_fraction"])
    assert entry["detail"]["misordered"] == 0
    assert ap.audit() == []


def test_observe_anatomy_clean_window_resets_streak(sim, timeline):
    ap = _autopilot()
    chaos = diff_timelines(reconstruct(synthesize_events(
        make_sim(dcn=8 * DCN_S), n_stages=S, n_microbatches=M)), sim)
    clean = diff_timelines(timeline, sim)
    assert not ap.observe_anatomy(chaos)
    assert not ap.observe_anatomy(clean)           # streak reset
    assert not ap.observe_anatomy(chaos)           # back to window 1
    assert ap.observe_anatomy(chaos)
    assert ap.stats["structural_confirmed"] == 1


def test_observe_anatomy_bare_score_and_threshold():
    ap = _autopilot(structural_threshold=0.5, confirm_windows=1)
    assert not ap.observe_anatomy(0.49)            # below threshold
    assert ap.observe_anatomy(0.5)                 # bare float accepted
    assert ap.stats["structural_confirmed"] == 1
    with pytest.raises(ValueError, match="structural_threshold"):
        _autopilot(structural_threshold=0.0)


# -- the CLI ------------------------------------------------------------------


def _write_trace(tmp_path, sim):
    path = tmp_path / "step.trace.json"
    path.write_text(json.dumps({"traceEvents": synthesize_events(
        sim, n_stages=S, n_microbatches=M)}))
    return str(path)


def test_cli_json_schema_pinned(tmp_path, capsys, sim):
    step_anatomy = _import_tool("step_anatomy")
    rc = step_anatomy.main(["--trace", _write_trace(tmp_path, sim),
                            "--diff-simulated", "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert set(report) == {"schedule", "attribution", "diff",
                           "predicted"}
    assert set(report["schedule"]) == {
        "name", "step", "n_stages", "n_microbatches", "n_ops",
        "makespan_s", "busy_s"}
    assert report["schedule"]["n_stages"] == S
    assert report["schedule"]["n_ops"] == 2 * S * M
    assert set(report["attribution"]) == {"makespan", "totals",
                                          "fractions", "per_stage"}
    assert set(report["attribution"]["totals"]) == set(CATEGORIES)
    for st in report["attribution"]["per_stage"]:
        assert "segments" not in st                # table view, not lanes
    assert report["diff"]["matched"] == 2 * S * M
    assert report["diff"]["drift_score"] < 1e-9
    assert set(report["predicted"]) == {"schedule", "t_fwd", "t_bwd",
                                        "link_seconds"}
    # predicted prices at the measured medians by construction
    assert report["predicted"]["t_fwd"] == pytest.approx(T_FWD)
    assert report["predicted"]["t_bwd"] == pytest.approx(T_BWD)


def test_cli_table_and_merged_out(tmp_path, capsys, sim):
    step_anatomy = _import_tool("step_anatomy")
    out = tmp_path / "merged.trace.json"
    rc = step_anatomy.main(["--trace", _write_trace(tmp_path, sim),
                            "--out", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert f"{S} stages x {M} microbatches" in text
    assert "exposed_dcn" in text
    merged = json.loads(out.read_text())["traceEvents"]
    names = {e["name"] for e in merged}
    assert "mpmd_op" in names                      # original events kept
    assert f"anatomy/stage{S - 1}" in names        # + counter lanes
    assert any(e["ph"] == "C" for e in merged)


def test_cli_plan_stage_mismatch_rejected(tmp_path, sim):
    step_anatomy = _import_tool("step_anatomy")
    plan = tmp_path / "MPMD_PLAN.json"
    plan.write_text(json.dumps({"n_stages": S + 1,
                                "plan": {"schedule": "1f1b"}}))
    with pytest.raises(SystemExit, match="wrong trace/plan pair"):
        step_anatomy.main(["--trace", _write_trace(tmp_path, sim),
                           "--plan", str(plan)])
