"""GPT flagship tests (apex ``tests/L0/run_transformer``'s
``test_pipeline_parallel_fwd_bwd.py`` + ``standalone_gpt.py`` pattern):
serial golden vs an independent jnp reference, TP parity vs serial, GSPMD
parity, and the combined dp x pp x tp step vs serial loss+grads.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu.models.gpt import (GPTConfig, GPTModel, make_stage_fn,
                                 pack_for_shard_map, pipeline_step,
                                 shard_params_for_tp,
                                 stack_layers_for_pipeline)
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.pipeline_parallel import JobInfo


def tiny_cfg(**kw):
    base = dict(vocab_size=32, hidden_size=16, num_layers=2,
                num_attention_heads=2, max_seq_len=8)
    base.update(kw)
    return GPTConfig(**base)


def make_data(rng, cfg, batch, seq):
    tokens = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    targets = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    return tokens, targets


# -- independent jnp reference (no apex_tpu ops) -----------------------------

def _ref_layernorm(x, w, b, eps=1e-5):
    m = x.mean(-1, keepdims=True)
    v = ((x - m) ** 2).mean(-1, keepdims=True)
    return (x - m) / np.sqrt(v + eps) * w + b


def _ref_rope(x, seq, head_dim):
    # half-split rotation, matching ops.rope.rope_freqs conventions
    inv = 1.0 / (10000.0 ** (np.arange(0, head_dim, 2) / head_dim))
    f = np.outer(np.arange(seq), inv)
    f = np.concatenate([f, f], axis=-1)           # (s, hd)
    cos, sin = np.cos(f), np.sin(f)
    x1, x2 = np.split(x, 2, axis=-1)
    rotated = np.concatenate([-x2, x1], axis=-1)
    return x * cos[None, :, None, :] + rotated * sin[None, :, None, :]


def _ref_gpt_loss(params, tokens, targets, cfg):
    """Plain numpy/jnp GPT forward + mean CE, no framework code."""
    p = jax.tree_util.tree_map(np.asarray, params)
    x = p["embedding"]["weight"][np.asarray(tokens)]   # (b, s, h)
    b, s, h = x.shape
    hd = cfg.head_dim
    nh = cfg.num_attention_heads
    for lp in p["layers"]:
        hn = _ref_layernorm(x, lp["input_layernorm"]["weight"],
                            lp["input_layernorm"]["bias"])
        qkv = hn @ lp["attention"]["qkv"]["weight"].T \
            + lp["attention"]["qkv"]["bias"]
        qkv = qkv.reshape(b, s, nh, 3 * hd)
        q, k, v = np.split(qkv, 3, axis=-1)
        q = _ref_rope(q, s, hd)
        k = _ref_rope(k, s, hd)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        scores = q @ k.transpose(0, 1, 3, 2) / np.sqrt(hd)
        mask = np.triu(np.full((s, s), -1e9), k=1)
        probs = jax.nn.softmax(jnp.asarray(scores + mask), axis=-1)
        probs = np.asarray(probs)
        ctx = (probs @ v).transpose(0, 2, 1, 3).reshape(b, s, h)
        attn = ctx @ lp["attention"]["proj"]["weight"].T \
            + lp["attention"]["proj"]["bias"]
        x = x + attn
        hn = _ref_layernorm(x, lp["post_attention_layernorm"]["weight"],
                            lp["post_attention_layernorm"]["bias"])
        ff = np.asarray(jax.nn.gelu(
            jnp.asarray(hn @ lp["mlp"]["fc1"]["weight"].T
                        + lp["mlp"]["fc1"]["bias"]), approximate=True))
        x = x + ff @ lp["mlp"]["fc2"]["weight"].T + lp["mlp"]["fc2"]["bias"]
    x = _ref_layernorm(x, p["final_layernorm"]["weight"],
                       p["final_layernorm"]["bias"])
    logits = x @ p["embedding"]["weight"].T
    logits = jnp.asarray(logits.reshape(b * s, -1))
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, jnp.asarray(targets).reshape(-1, 1), axis=1)
    return float(jnp.mean(nll))


class TestGPTSerial:
    def test_loss_matches_independent_reference(self, rng):
        cfg = tiny_cfg()
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        tokens, targets = make_data(rng, cfg, 2, 8)
        got = float(jax.jit(model.loss)(params, tokens, targets))
        ref = _ref_gpt_loss(params, tokens, targets, cfg)
        np.testing.assert_allclose(got, ref, rtol=2e-4)

    def test_grads_finite_and_nonzero(self, rng):
        cfg = tiny_cfg()
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        tokens, targets = make_data(rng, cfg, 2, 8)
        grads = jax.jit(jax.grad(model.loss))(params, tokens, targets)
        leaves = jax.tree_util.tree_leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in leaves)
        assert any(np.abs(np.asarray(g)).max() > 0 for g in leaves)

    def test_learns(self, rng):
        """Few SGD steps on a fixed batch must reduce the loss."""
        cfg = tiny_cfg()
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        tokens, targets = make_data(rng, cfg, 2, 8)

        @jax.jit
        def step(params):
            loss, g = jax.value_and_grad(model.loss)(params, tokens,
                                                     targets)
            new = jax.tree_util.tree_map(lambda p, gg: p - 0.1 * gg,
                                         params, g)
            return new, loss

        params, first = step(params)
        for _ in range(4):
            params, last = step(params)
        assert float(last) < float(first)


class TestGPTTensorParallel:
    def test_tp2_shard_map_matches_serial(self, rng):
        cfg_s = tiny_cfg()
        serial = GPTModel(cfg_s)
        params = serial.init_params(jax.random.PRNGKey(1))
        tokens, targets = make_data(rng, cfg_s, 2, 8)
        ref_loss = float(jax.jit(serial.loss)(params, tokens, targets))
        ref_grads = jax.jit(jax.grad(serial.loss))(params, tokens, targets)

        cfg_p = tiny_cfg(tensor_parallel_size=2, axis_name="model")
        par = GPTModel(cfg_p)
        mesh = jax.make_mesh((2,), ("model",))
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            par, params)

        def step(sp, tokens, targets):
            loss, g = jax.value_and_grad(par.loss)(local_fn(sp), tokens,
                                                   targets)
            return loss, repack_fn(g)

        loss, grads = jax.jit(shard_map(
            step, mesh=mesh, in_specs=(in_specs, P(), P()),
            out_specs=(P(), in_specs)))(packed, tokens, targets)

        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        # pack the serial grads identically and compare leaf-for-leaf
        ref_packed, _, _, _ = pack_for_shard_map(par, ref_grads)
        for got, ref in zip(jax.tree_util.tree_leaves(grads),
                            jax.tree_util.tree_leaves(ref_packed)):
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=5e-4, atol=1e-5)

    def test_gspmd_jit_matches_serial(self, rng):
        """Idiomatic TPU path: jit the serial form with partition_specs —
        the compiler inserts the collectives."""
        cfg = tiny_cfg()
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(2))
        tokens, targets = make_data(rng, cfg, 4, 8)
        ref = float(jax.jit(model.loss)(params, tokens, targets))

        mesh = jax.make_mesh((2,), ("model",),
                             axis_types=(jax.sharding.AxisType.Auto,))
        specs = model.partition_specs()
        sharded = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs,
            is_leaf=lambda x: isinstance(x, P))
        got = float(jax.jit(model.loss)(sharded, tokens, targets))
        np.testing.assert_allclose(got, ref, rtol=1e-5)


class TestGPTCombinedParallel:
    def test_dp_pp_tp_step_matches_serial(self, rng):
        """The combined 3-axis step: dp=2 x pp=2 x tp=2 over the 8-device
        mesh, loss AND grads vs the serial model on the same global batch
        (apex test_pipeline_parallel_fwd_bwd.py, extended to 3 axes)."""
        parallel_state.destroy_model_parallel()
        mesh = None
        try:
            mesh = parallel_state.initialize_model_parallel(2, 2)
            assert parallel_state.get_data_parallel_world_size() == 2

            cfg_s = tiny_cfg(num_layers=2)
            serial = GPTModel(cfg_s)
            params = serial.init_params(jax.random.PRNGKey(3))
            M, mb, seq = 2, 2, 8          # per-device microbatches
            # global batch: dp=2 shards of (M*mb) rows each
            tokens, targets = make_data(rng, cfg_s, 2 * M * mb, seq)

            # serial reference: mean loss over the same global batch
            def serial_loss(p):
                return serial.loss(p, tokens, targets)
            ref_loss = float(jax.jit(serial_loss)(params))
            ref_grads = jax.jit(jax.grad(serial_loss))(params)

            cfg_p = tiny_cfg(num_layers=2, tensor_parallel_size=2,
                             axis_name="model", sequence_parallel=True)
            par = GPTModel(cfg_p)
            packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
                par, params, n_stages=2)

            def step(sp, tokens, targets):
                # local batch (M*mb, s) -> (M, mb, s) microbatches
                tk = tokens.reshape(M, mb, seq)
                tg = targets.reshape(M, mb, seq)
                loss, g = pipeline_step(par, local_fn(sp), tk, tg,
                                        pipe_axis="pipe",
                                        data_axis="data")
                return loss, repack_fn(g)

            loss, grads = jax.jit(shard_map(
                step, mesh=mesh,
                in_specs=(in_specs, P("data"), P("data")),
                out_specs=(P(), in_specs)))(packed, tokens, targets)

            np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)

            # reference grads, packed identically
            ref_packed, _, _, _ = pack_for_shard_map(par, ref_grads,
                                                     n_stages=2)
            for got, ref in zip(jax.tree_util.tree_leaves(grads),
                                jax.tree_util.tree_leaves(ref_packed)):
                np.testing.assert_allclose(np.asarray(got),
                                           np.asarray(ref),
                                           rtol=5e-4, atol=1e-5)
        finally:
            parallel_state.destroy_model_parallel()


class TestPipelineBitwise:
    """1F1B and interleaved schedules are bitwise-identical (f32 loss AND
    grads) to the same model run at pp=1 — the engine replays the exact
    per-microbatch accumulation order of the no-pipelining reference."""

    def _run(self, model, params, tokens, targets, S, v):
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            model, params, n_stages=S, tensor_axis=None, n_virtual=v)
        mesh = jax.make_mesh((S,), ("pipe",), devices=jax.devices()[:S])

        def step(sp, tk, tg):
            loss, g = pipeline_step(model, local_fn(sp), tk, tg,
                                    pipe_axis="pipe", n_virtual=v)
            return loss, repack_fn(g)

        return jax.jit(shard_map(
            step, mesh=mesh, in_specs=(in_specs, P(), P()),
            out_specs=(P(), in_specs)))(packed, tokens, targets)

    @staticmethod
    def _logical_layers(gl, S, v, num_layers):
        """Packed layer leaves -> logical (num_layers, ...) order."""
        def f(a):
            a = np.asarray(a)
            k, p = 0, 1
            while p < num_layers:      # leading dims multiply to L
                p *= a.shape[k]
                k += 1
            while k < a.ndim - 1 and a.shape[k] == 1:
                k += 1
            a = a.reshape((S, v, -1) + a.shape[k:])
            lpc = a.shape[2]
            out = np.zeros((num_layers,) + a.shape[3:], a.dtype)
            for s in range(S):
                for c in range(v):
                    for j in range(lpc):
                        out[(c * S + s) * lpc + j] = a[s, c, j]
            return out
        return jax.tree_util.tree_map(f, gl)

    @pytest.mark.parametrize("S,v", [(2, 1), (4, 1), (2, 2)])
    def test_pp_matches_pp1_bitwise(self, rng, S, v):
        cfg = tiny_cfg(num_layers=4)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(7))
        M, mb, seq = 4, 2, 8
        tokens = jnp.asarray(rng.randint(0, 32, (M, mb, seq)))
        targets = jnp.asarray(rng.randint(0, 32, (M, mb, seq)))

        loss1, g1 = self._run(model, params, tokens, targets, 1, 1)
        loss, g = self._run(model, params, tokens, targets, S, v)

        assert np.asarray(loss1).tobytes() == np.asarray(loss).tobytes()
        a = self._logical_layers(g["layers"], S, v, 4)
        b = self._logical_layers(g1["layers"], 1, 1, 4)
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b)):
            np.testing.assert_array_equal(x, y)
        for k in ("embedding", "final_layernorm"):
            for x, y in zip(jax.tree_util.tree_leaves(g[k]),
                            jax.tree_util.tree_leaves(g1[k])):
                np.testing.assert_array_equal(np.asarray(x),
                                              np.asarray(y))

    def test_dp_tp_pp_sp_composition_bitwise_in_pp(self, rng):
        """dp=2 x tp=2 x pp=2 with sequence parallelism: the pp=2 run is
        bitwise-identical to pp=1 on the same dp x tp submesh."""
        cfg = tiny_cfg(num_layers=4, tensor_parallel_size=2,
                       axis_name="model", sequence_parallel=True)
        model = GPTModel(cfg)
        serial = GPTModel(tiny_cfg(num_layers=4))
        params = serial.init_params(jax.random.PRNGKey(8))
        M, mb, seq = 2, 2, 8
        tokens = jnp.asarray(rng.randint(0, 32, (2, M, mb, seq)))
        targets = jnp.asarray(rng.randint(0, 32, (2, M, mb, seq)))

        def run(pp):
            packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
                model, params, n_stages=pp)
            mesh = jax.make_mesh((2, 2, pp), ("data", "model", "pipe"),
                                 devices=jax.devices()[:4 * pp])

            def step(sp, tk, tg):
                loss, g = pipeline_step(
                    model, local_fn(sp), tk[0], tg[0],
                    pipe_axis="pipe", data_axis="data", n_virtual=1)
                return loss, repack_fn(g)

            out = jax.jit(shard_map(
                step, mesh=mesh,
                in_specs=(in_specs, P("data"), P("data")),
                out_specs=(P(), in_specs)))(packed, tokens, targets)
            return out[0], out[1], in_specs

        def canon(gl, specs):
            """Merge the (S, lpc) packing dims (located via the leaf's
            pipe-axis spec position) into one logical layer axis so
            pp=1 and pp=2 packings compare leaf-for-leaf."""
            sp_leaves = jax.tree_util.tree_leaves(
                specs, is_leaf=lambda x: isinstance(x, P))
            out = []
            for a, sp in zip(jax.tree_util.tree_leaves(gl), sp_leaves):
                a = np.asarray(a)
                i = list(sp).index("pipe")
                out.append(a.reshape(a.shape[:i] + (-1,)
                                     + a.shape[i + 2:]))
            return out

        loss1, g1, specs1 = run(1)
        loss2, g2, specs2 = run(2)
        assert np.asarray(loss1).tobytes() == np.asarray(loss2).tobytes()
        for x, y in zip(canon(g2["layers"], specs2["layers"]),
                        canon(g1["layers"], specs1["layers"])):
            np.testing.assert_array_equal(x, y)


class TestStageStacking:
    def test_stack_shapes(self, rng):
        cfg = tiny_cfg(num_layers=4)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(4))
        stacked = stack_layers_for_pipeline(params["layers"], 2)
        w = stacked["attention"]["qkv"]["weight"]
        assert w.shape[:2] == (2, 2)
        np.testing.assert_array_equal(
            np.asarray(w[1, 0]),
            np.asarray(params["layers"][2]["attention"]["qkv"]["weight"]))

    def test_indivisible_raises(self, rng):
        cfg = tiny_cfg(num_layers=2)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(5))
        with pytest.raises(ValueError):
            stack_layers_for_pipeline(params["layers"], 3)

    def test_stage_fn_matches_layer_loop(self, rng):
        cfg = tiny_cfg(num_layers=2)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(6))
        x = jnp.asarray(rng.randn(2, 8, cfg.hidden_size).astype(np.float32))
        stacked = stack_layers_for_pipeline(params["layers"], 1)
        info = JobInfo(jnp.int32(0), jnp.int32(0), jnp.int32(0))
        got = make_stage_fn(model)(
            jax.tree_util.tree_map(lambda p: p[0], stacked), x, info)
        ref, _ = model.backbone(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


class TestAttentionDropout:
    """Train-time attention dropout through the fused flash path end to
    end in the flagship (VERDICT r3 weak item 5): config plumbing,
    eval determinism, per-step mask freshness, a short convergence run,
    and the pipeline seed-carry."""

    def test_config_validation(self):
        with pytest.raises(ValueError, match="attention_dropout"):
            tiny_cfg(attention_dropout=1.5)
        with pytest.raises(ValueError, match="context"):
            tiny_cfg(attention_dropout=0.1, context_axis="context")

    def test_eval_ignores_dropout_and_train_differs(self, rng):
        cfg = tiny_cfg(attention_dropout=0.3, hidden_size=32,
                       num_attention_heads=2, max_seq_len=16)
        plain = GPTModel(tiny_cfg(hidden_size=32, num_attention_heads=2,
                                  max_seq_len=16))
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        tokens, targets = make_data(rng, cfg, 2, 16)
        # no seed => eval semantics, identical to a dropout-free config
        eval_loss = float(model.loss(params, tokens, targets))
        plain_loss = float(plain.loss(params, tokens, targets))
        np.testing.assert_allclose(eval_loss, plain_loss, rtol=1e-6)
        # seeded train losses: deterministic per seed, fresh across seeds
        l7a = float(model.loss(params, tokens, targets, dropout_seed=7))
        l7b = float(model.loss(params, tokens, targets, dropout_seed=7))
        l8 = float(model.loss(params, tokens, targets, dropout_seed=8))
        assert l7a == l7b
        assert l7a != l8
        assert l7a != eval_loss

    def test_short_training_run_converges(self, rng):
        from apex_tpu.optimizers import FusedAdam

        cfg = tiny_cfg(attention_dropout=0.1, hidden_size=32,
                       num_attention_heads=2, max_seq_len=16)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(1))
        tokens, targets = make_data(rng, cfg, 4, 16)
        adam = FusedAdam(lr=1e-2)
        state = adam.init(params)

        @jax.jit
        def step(params, state, seed):
            loss, g = jax.value_and_grad(model.loss)(
                params, tokens, targets, dropout_seed=seed)
            params, state = adam.step(g, params, state)
            return loss, params, state

        losses = []
        for i in range(8):
            # the step counter IS the seed: layer streams stride the
            # seed space, so +1 per step gives fresh masks
            loss, params, state = step(params, state, jnp.int32(i))
            losses.append(float(loss))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0], losses

    def test_pipeline_seed_carry(self, rng):
        """Per-job dropout seeds are derived arithmetically from
        (microbatch, stage): a 2-stage pipelined step with dropout runs,
        is deterministic per seed, and differs from the dropout-free
        pipeline."""
        cfg = tiny_cfg(attention_dropout=0.3, num_layers=2,
                       hidden_size=32, num_attention_heads=2,
                       max_seq_len=16)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(2))
        M, mb, seq = 2, 2, 16
        tokens = jnp.asarray(rng.randint(0, 32, (M, mb, seq)))
        targets = jnp.asarray(rng.randint(0, 32, (M, mb, seq)))
        pp = 2
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            model, params, n_stages=pp, tensor_axis=None)
        mesh = jax.make_mesh((pp,), ("pipe",),
                             devices=jax.devices()[:pp])

        def run(seed):
            def fn(sp, tk, tg):
                loss, _ = pipeline_step(model, local_fn(sp), tk, tg,
                                        pipe_axis="pipe",
                                        dropout_seed=seed)
                return loss
            return float(jax.jit(shard_map(
                fn, mesh=mesh, in_specs=(in_specs, P(), P()),
                out_specs=P()))(packed, tokens, targets))

        a, b, c, none = run(5), run(5), run(6), run(None)
        assert a == b
        assert a != c
        assert a != none
        assert np.isfinite([a, c, none]).all()

    def test_tp_ranks_draw_independent_masks(self, rng):
        """Under tensor parallelism each rank holds DIFFERENT global
        heads, so the attention-dropout streams must differ per rank
        (ADVICE r4: the counter hash keys on the LOCAL head index; the
        model folds a per-rank stride into the seed, like Megatron's
        per-TP-rank dropout RNG offset)."""
        cfg = tiny_cfg(attention_dropout=0.5, hidden_size=32,
                       num_attention_heads=4, max_seq_len=16,
                       tensor_parallel_size=2, axis_name="model")
        model = GPTModel(cfg)
        layer_attn = model.layers[0].attention
        serial = GPTModel(tiny_cfg(hidden_size=32, num_attention_heads=4,
                                   max_seq_len=16))
        params = serial.layers[0].attention.init_params(
            jax.random.PRNGKey(3))
        mesh = jax.make_mesh((2,), ("model",))
        x = jnp.asarray(rng.randn(2, 16, 32).astype(np.float32))

        # give BOTH ranks the same local qkv/proj shard: any output
        # difference between ranks can then only come from the dropout
        # mask stream
        half = {"qkv": {"weight": params["qkv"]["weight"][:48],
                        "bias": params["qkv"]["bias"][:48]},
                "proj": {"weight": params["proj"]["weight"][:, :16],
                         "bias": params["proj"]["bias"]}}

        def fn(p, x):
            return layer_attn(p, x, None, None, dropout_seed=jnp.int32(9))

        out = jax.jit(shard_map(
            fn, mesh=mesh, in_specs=(P(), P()), out_specs=P()))(half, x)

        # serial twin on the same half shard draws rank-0's stream
        # (offset 0, seed 9); with IDENTICAL masks across ranks the
        # RowParallel psum would make the TP output exactly 2x the
        # serial partial (bias is zero) — independence breaks that
        scfg = tiny_cfg(attention_dropout=0.5, hidden_size=32,
                        num_attention_heads=4, max_seq_len=16)
        twin = GPTModel(scfg).layers[0].attention
        ref = twin(half, x, None, None, dropout_seed=jnp.int32(9))
        assert not np.allclose(np.asarray(out), 2 * np.asarray(ref)), (
            "identical dropout masks across TP ranks")


class TestSelectiveRemat:
    """Megatron 'selective activation recompute' parity: remat_policy=
    'dots' saves GEMM outputs through jax.checkpoint while 'full' saves
    nothing; numerics must be identical, memory residency must differ."""

    def test_policies_numerically_identical(self, rng):
        cfg_kw = dict(vocab_size=32, hidden_size=32, num_layers=2,
                      num_attention_heads=2, max_seq_len=16, remat=True)
        tokens, targets = make_data(
            rng, GPTConfig(**cfg_kw), 2, 16)
        out = {}
        for pol in ("full", "dots"):
            m = GPTModel(GPTConfig(remat_policy=pol, **cfg_kw))
            p = m.init_params(jax.random.PRNGKey(0))
            loss, g = jax.jit(jax.value_and_grad(m.loss))(p, tokens,
                                                          targets)
            out[pol] = (float(loss), g)
        np.testing.assert_allclose(out["full"][0], out["dots"][0],
                                   rtol=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(out["full"][1]),
                        jax.tree_util.tree_leaves(out["dots"][1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_dots_policy_saves_more(self, rng):
        from apex_tpu.utils.profiling import memory_stats

        cfg_kw = dict(vocab_size=64, hidden_size=64, num_layers=4,
                      num_attention_heads=4, max_seq_len=64, remat=True)
        tokens, targets = make_data(rng, GPTConfig(**cfg_kw), 4, 64)
        temps = {}
        for pol in ("full", "dots"):
            m = GPTModel(GPTConfig(remat_policy=pol, **cfg_kw))
            p = m.init_params(jax.random.PRNGKey(0))
            stats = memory_stats(
                lambda p: jax.value_and_grad(m.loss)(p, tokens, targets),
                p)
            if not stats:
                pytest.skip("backend lacks memory_analysis")
            temps[pol] = stats["temp"]
        # saving dot outputs must change the compiled residency
        assert temps["full"] != temps["dots"], temps

    def test_invalid_policy_rejected(self):
        with pytest.raises(ValueError, match="remat_policy"):
            GPTConfig(vocab_size=8, hidden_size=16, num_layers=1,
                      num_attention_heads=2, max_seq_len=8,
                      remat_policy="everything")
