"""Transformer stack tests (apex ``tests/L0/run_transformer`` analogue).

Every parallel feature is validated against its serial equivalent on the
fake 8-device CPU mesh: TP layers vs dense layers, vocab-parallel xent vs
plain xent, mappings fwd+bwd, SPMD pipeline vs no-pipelining.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer import parallel_state
from apex_tpu.transformer import tensor_parallel as tp
from apex_tpu.transformer.pipeline_parallel import (
    pipeline_forward, pipeline_value_and_grad,
    forward_backward_no_pipelining, get_forward_backward_func)
from apex_tpu.transformer.pipeline_parallel import p2p_communication as p2p
from apex_tpu.transformer import (ConstantNumMicroBatches,
                                  build_num_microbatches_calculator)

TP_SIZE = 8


@pytest.fixture
def tp_mesh():
    return jax.make_mesh((TP_SIZE,), ("model",))


@pytest.fixture
def pp_mesh():
    return jax.make_mesh((4,), ("pipe",))


def _rep(y, axis="model"):
    """Convert a value that is identical on all devices (e.g. all-gather
    output) into a provably-replicated one so out_specs=P() type-checks."""
    from apex_tpu.utils.collectives import axis_size
    return jax.lax.psum(y, axis) / axis_size(axis)


def shard_tp(fn, mesh, in_specs, out_specs):
    # jit-wrapped: eager shard_map + advanced indexing trips a mesh-context
    # bug in this JAX version
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs))


class TestParallelState:
    def test_initialize_and_sizes(self):
        parallel_state.initialize_model_parallel(2, 2)
        assert parallel_state.model_parallel_is_initialized()
        assert parallel_state.get_tensor_model_parallel_world_size() == 2
        assert parallel_state.get_pipeline_model_parallel_world_size() == 2
        assert parallel_state.get_data_parallel_world_size() == 2
        parallel_state.destroy_model_parallel()
        assert not parallel_state.model_parallel_is_initialized()

    def test_invalid_sizes_raise(self):
        with pytest.raises(RuntimeError):
            parallel_state.initialize_model_parallel(3, 1)
        parallel_state.destroy_model_parallel()

    def test_virtual_rank(self):
        parallel_state.initialize_model_parallel(
            1, 2, virtual_pipeline_model_parallel_size_=2)
        assert parallel_state.\
            get_virtual_pipeline_model_parallel_world_size() == 2
        parallel_state.set_virtual_pipeline_model_parallel_rank(1)
        assert parallel_state.\
            get_virtual_pipeline_model_parallel_rank() == 1
        parallel_state.destroy_model_parallel()


class TestMappings:
    """apex tests/L0/run_transformer/test_mappings.py: each mapping fwd and
    its grad."""

    def test_copy_fwd_identity_bwd_allreduce(self, tp_mesh):
        x = jnp.arange(8.0)

        def f(x):
            y = tp.copy_to_tensor_model_parallel_region(x[0] * jnp.ones(()))
            return jax.lax.psum(y * 0, "model") + y  # keep varying

        def g(x):
            # grad of sum over devices of x → allreduced grad = world size
            def inner(x):
                y = tp.copy_to_tensor_model_parallel_region(x)
                return y  # per-device scalar
            # total = sum over devices handled via psum of per-device loss
            val = inner(x[0])
            return jax.lax.psum(val * 0, "model") + val

        grad = shard_tp(
            lambda x: jax.grad(
                lambda v: tp.copy_to_tensor_model_parallel_region(v).sum()
            )(x[0])[None],
            tp_mesh, (P("model"),), P("model"))(x)
        # each device's bwd all-reduces the per-device cotangent of 1
        np.testing.assert_allclose(np.asarray(grad), TP_SIZE)

    def test_reduce_fwd(self, tp_mesh):
        x = jnp.arange(8.0)
        out = shard_tp(
            lambda x: tp.reduce_from_tensor_model_parallel_region(x),
            tp_mesh, (P("model"),), P())(x)
        np.testing.assert_allclose(float(out[0]), 28.0)

    def test_scatter_gather_roundtrip(self, tp_mesh):
        x = jnp.arange(16.0).reshape(2, 8)

        def f(x):
            local = tp.scatter_to_tensor_model_parallel_region(x)
            assert local.shape == (2, 1)
            return _rep(tp.gather_from_tensor_model_parallel_region(local))

        out = shard_tp(f, tp_mesh, (P(),), P())(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_sequence_scatter_gather_roundtrip(self, tp_mesh):
        x = jnp.arange(32.0).reshape(8, 4)

        def f(x):
            local = tp.scatter_to_sequence_parallel_region(x)
            return _rep(tp.gather_from_sequence_parallel_region(local))

        out = shard_tp(f, tp_mesh, (P(),), P())(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_reduce_scatter_matches_manual(self, tp_mesh):
        x = jnp.ones((8, 2))

        def f(x):
            return tp.reduce_scatter_to_sequence_parallel_region(x)

        out = shard_tp(f, tp_mesh, (P(),), P("model"))(x)
        # each row: sum over 8 devices of 1 = 8
        np.testing.assert_allclose(np.asarray(out), 8.0)


def _dense_forward(w, b, x):
    return x @ w.T + b


class TestTensorParallelLayers:
    """apex test_layers.py: Column/RowParallelLinear vs dense reference."""

    def test_column_parallel_matches_dense(self, rng, tp_mesh):
        in_f, out_f, batch = 16, 32, 4
        col = tp.ColumnParallelLinear(in_f, out_f, world_size=TP_SIZE,
                                      gather_output=True)
        w = jnp.asarray(rng.randn(out_f, in_f).astype(np.float32))
        b = jnp.asarray(rng.randn(out_f).astype(np.float32))
        x = jnp.asarray(rng.randn(batch, in_f).astype(np.float32))
        ref = _dense_forward(w, b, x)

        def f(w, b, x):
            y, _ = col({"weight": w, "bias": b}, x)
            return _rep(y)

        out = shard_tp(f, tp_mesh, (P("model", None), P("model"), P()),
                       P())(w, b, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_column_parallel_grads_match(self, rng, tp_mesh):
        in_f, out_f, batch = 8, 16, 4
        col = tp.ColumnParallelLinear(in_f, out_f, world_size=TP_SIZE,
                                      gather_output=True)
        w = jnp.asarray(rng.randn(out_f, in_f).astype(np.float32))
        b = jnp.zeros((out_f,), jnp.float32)
        x = jnp.asarray(rng.randn(batch, in_f).astype(np.float32))

        def sharded_grads(w, b, x):
            def loss(w, b, x):
                y, _ = col({"weight": w, "bias": b}, x)
                return jnp.sum(y ** 2)
            gw, gb, gx = jax.grad(loss, argnums=(0, 1, 2))(w, b, x)
            return gw, gb, gx

        gw, gb, gx = shard_tp(
            sharded_grads, tp_mesh,
            (P("model", None), P("model"), P()),
            (P("model", None), P("model"), P()))(w, b, x)
        ref_gw, ref_gb, ref_gx = jax.grad(
            lambda w, b, x: jnp.sum(_dense_forward(w, b, x) ** 2),
            argnums=(0, 1, 2))(w, b, x)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(ref_gw),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gb), np.asarray(ref_gb),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(ref_gx),
                                   rtol=1e-4, atol=1e-4)

    def test_row_parallel_matches_dense(self, rng, tp_mesh):
        in_f, out_f, batch = 32, 16, 4
        row = tp.RowParallelLinear(in_f, out_f, world_size=TP_SIZE,
                                   input_is_parallel=False)
        w = jnp.asarray(rng.randn(out_f, in_f).astype(np.float32))
        b = jnp.asarray(rng.randn(out_f).astype(np.float32))
        x = jnp.asarray(rng.randn(batch, in_f).astype(np.float32))
        ref = _dense_forward(w, b, x)

        def f(w, b, x):
            y, _ = row({"weight": w, "bias": b}, x)
            return y

        out = shard_tp(f, tp_mesh, (P(None, "model"), P(), P()),
                       P())(w, b, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_column_row_mlp_sequence_parallel(self, rng, tp_mesh):
        """Col(+SP gather) → gelu → Row(+SP reduce-scatter) round trip vs
        dense (the Megatron SP block edge pattern)."""
        seq, hidden, ffn = 16, 8, 32
        col = tp.ColumnParallelLinear(hidden, ffn, world_size=TP_SIZE,
                                      gather_output=False,
                                      sequence_parallel_enabled=True)
        row = tp.RowParallelLinear(ffn, hidden, world_size=TP_SIZE,
                                   input_is_parallel=True,
                                   sequence_parallel_enabled=True)
        w1 = jnp.asarray(rng.randn(ffn, hidden).astype(np.float32))
        b1 = jnp.zeros((ffn,), jnp.float32)
        w2 = jnp.asarray(rng.randn(hidden, ffn).astype(np.float32))
        b2 = jnp.zeros((hidden,), jnp.float32)
        x = jnp.asarray(rng.randn(seq, hidden).astype(np.float32))

        def f(w1, b1, w2, b2, x):
            h, _ = col({"weight": w1, "bias": b1}, x)
            h = jax.nn.gelu(h, approximate=True)
            y, _ = row({"weight": w2, "bias": b2}, h)
            return y

        out = shard_tp(
            f, tp_mesh,
            (P("model", None), P("model"), P(None, "model"), P(),
             P("model", None)),
            P("model", None))(w1, b1, w2, b2, x)
        ref = jax.nn.gelu(x @ w1.T + b1, approximate=True) @ w2.T + b2
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_vocab_parallel_embedding(self, rng, tp_mesh):
        vocab, dim = 64, 16
        emb = tp.VocabParallelEmbedding(vocab, dim, world_size=TP_SIZE)
        w = jnp.asarray(rng.randn(vocab, dim).astype(np.float32))
        ids = jnp.asarray(rng.randint(0, vocab, (4, 6)))

        out = shard_tp(lambda w, i: emb({"weight": w}, i),
                       tp_mesh, (P("model", None), P()), P())(w, ids)
        ref = jnp.take(w, ids, axis=0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


class TestVocabParallelCrossEntropy:
    """apex test_cross_entropy.py: vocab-parallel vs plain xent."""

    @pytest.mark.parametrize("smoothing", [0.0, 0.1])
    def test_matches_serial(self, rng, tp_mesh, smoothing):
        n, vocab = 8, 32
        logits = jnp.asarray(rng.randn(n, vocab).astype(np.float32) * 2)
        target = jnp.asarray(rng.randint(0, vocab, n))

        out = shard_tp(
            lambda l, t: tp.vocab_parallel_cross_entropy(l, t, smoothing),
            tp_mesh, (P(None, "model"), P()), P())(logits, target)
        logp = jax.nn.log_softmax(logits)
        nll = -logp[jnp.arange(n), target]
        if smoothing > 0:
            # apex scales the mix by V/(V-1)
            s_adj = smoothing * vocab / (vocab - 1)
            smooth = -jnp.mean(logp, axis=-1)
            ref = (1 - s_adj) * nll + s_adj * smooth
        else:
            ref = nll
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_grad_matches_serial(self, rng, tp_mesh):
        n, vocab = 4, 16
        logits = jnp.asarray(rng.randn(n, vocab).astype(np.float32))
        target = jnp.asarray(rng.randint(0, vocab, n))

        def sharded(l, t):
            return jax.grad(
                lambda l: jnp.sum(
                    tp.vocab_parallel_cross_entropy(l, t)))(l)

        g = shard_tp(sharded, tp_mesh, (P(None, "model"), P()),
                     P(None, "model"))(logits, target)
        ref = jax.grad(lambda l: jnp.sum(
            -jax.nn.log_softmax(l)[jnp.arange(n), target]))(logits)
        np.testing.assert_allclose(np.asarray(g), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


def _stack_stage_params(rng, n_stages, width):
    return {
        "w": jnp.asarray(rng.randn(n_stages, width, width)
                         .astype(np.float32)) / np.sqrt(width),
        "b": jnp.zeros((n_stages, width), jnp.float32),
    }


class TestPipeline:
    """apex test_pipeline_parallel_fwd_bwd.py: pipelined loss/grads vs the
    no-pipelining reference on the same data."""

    def _serial_loss(self, params, microbatches, targets, n_stages):
        def full(x):
            for i in range(n_stages):
                x = _stage_fn({"w": params["w"][i], "b": params["b"][i]}, x)
            return x
        per = [
            _loss_fn(full(microbatches[m]), targets[m])
            for m in range(microbatches.shape[0])
        ]
        return jnp.mean(jnp.stack(per))

    def test_forward_matches_serial(self, rng, pp_mesh):
        S, width, M, mb = 4, 8, 4, 2
        params = _stack_stage_params(rng, S, width)
        x = jnp.asarray(rng.randn(M, mb, width).astype(np.float32))

        def f(params, x):
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            return pipeline_forward(
                lambda p, z, info: _stage_fn(p, z), local, x,
                axis_name="pipe")

        # outputs come back (M, mb, width), replicated over the pipe axis
        got = np.asarray(jax.jit(shard_map(
            f, mesh=pp_mesh,
            in_specs=({"w": P("pipe", None, None),
                       "b": P("pipe", None)}, P()),
            out_specs=P()))(params, x))
        def full(xx):
            for i in range(S):
                xx = _stage_fn({"w": params["w"][i], "b": params["b"][i]},
                               xx)
            return xx
        for m in range(M):
            np.testing.assert_allclose(got[m], np.asarray(full(x[m])),
                                       rtol=1e-5, atol=1e-5)

    def test_value_and_grad_matches_serial(self, rng, pp_mesh):
        S, width, M, mb = 4, 8, 4, 2
        params = _stack_stage_params(rng, S, width)
        x = jnp.asarray(rng.randn(M, mb, width).astype(np.float32))
        t = jnp.asarray(rng.randn(M, mb, width).astype(np.float32))

        def f(params, x, t):
            local = jax.tree_util.tree_map(lambda p: p[0], params)
            loss, grads = pipeline_value_and_grad(
                _stage_fn, _loss_fn, local, x, t, axis_name="pipe")
            return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

        loss, grads = jax.jit(shard_map(
            f, mesh=pp_mesh,
            in_specs=({"w": P("pipe", None, None), "b": P("pipe", None)},
                      P(), P()),
            out_specs=(P(), {"w": P("pipe", None, None),
                             "b": P("pipe", None)})))(params, x, t)
        ref_loss = self._serial_loss(params, x, t, S)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        ref_grads = jax.grad(
            lambda p: self._serial_loss(p, x, t, S))(params)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(ref_grads[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_interleaved_matches_serial(self, rng):
        # 2 devices x 2 virtual chunks = 4 logical stages; the
        # interleaved schedule needs M % S == 0
        mesh = jax.make_mesh((2,), ("pipe",))
        S, v, width, M, mb = 2, 2, 8, 4, 2
        rng2 = np.random.RandomState(7)
        params = _stack_stage_params(rng2, S * v, width)
        x = jnp.asarray(rng2.randn(M, mb, width).astype(np.float32))
        t = jnp.asarray(rng2.randn(M, mb, width).astype(np.float32))
        # interleaved placement: device s holds chunks [s, s+S]
        # logical stage c*S + s ⇒ device s's chunk c is logical c*S+s
        w_dev = jnp.stack([params["w"][jnp.asarray([s, s + S])]
                           for s in range(S)])   # (S, v, width, width)
        b_dev = jnp.stack([params["b"][jnp.asarray([s, s + S])]
                           for s in range(S)])

        def f(w, b, x, t):
            local = {"w": w[0], "b": b[0]}     # (v, ...)
            loss, grads = pipeline_value_and_grad(
                _stage_fn, _loss_fn, local, x, t, axis_name="pipe",
                n_virtual=v)
            return loss, jax.tree_util.tree_map(lambda g: g[None], grads)

        loss, grads = jax.jit(shard_map(
            f, mesh=mesh,
            in_specs=(P("pipe"), P("pipe"), P(), P()),
            out_specs=(P(), {"w": P("pipe"), "b": P("pipe")})))(
                w_dev, b_dev, x, t)
        ref_loss = self._serial_loss(params, x, t, S * v)
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
        ref_grads = jax.grad(
            lambda p: self._serial_loss(p, x, t, S * v))(params)
        got_w = np.asarray(grads["w"]).reshape(S, v, width, width)
        for s in range(S):
            for c in range(v):
                np.testing.assert_allclose(
                    got_w[s, c], np.asarray(ref_grads["w"][c * S + s]),
                    rtol=1e-4, atol=1e-5)

    def test_no_pipelining_schedule(self, rng):
        width, M, mb = 8, 4, 2
        params = {"w": jnp.asarray(
            rng.randn(width, width).astype(np.float32)) / 3,
            "b": jnp.zeros((width,), jnp.float32)}
        x = jnp.asarray(rng.randn(M, mb, width).astype(np.float32))
        t = jnp.asarray(rng.randn(M, mb, width).astype(np.float32))
        loss, grads = forward_backward_no_pipelining(
            _stage_fn, _loss_fn, params, x, t)
        per = jnp.mean(jnp.stack([
            _loss_fn(_stage_fn(params, x[m]), t[m]) for m in range(M)]))
        np.testing.assert_allclose(float(loss), float(per), rtol=1e-5)
        ref = jax.grad(lambda p: jnp.mean(jnp.stack([
            _loss_fn(_stage_fn(p, x[m]), t[m])
            for m in range(M)])))(params)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(grads[k]),
                                       np.asarray(ref[k]), rtol=1e-4,
                                       atol=1e-5)

    def test_get_forward_backward_func_dispatch(self):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_pipelining_without_interleaving as f1f1b,
        )
        assert get_forward_backward_func(None, 1) is \
            forward_backward_no_pipelining
        assert get_forward_backward_func(None, 4) is f1f1b
        fn = get_forward_backward_func(2, 4)
        assert fn.func.__name__ == \
            "forward_backward_pipelining_with_interleaving"


class TestP2P:
    def test_forward_shift(self, pp_mesh):
        x = jnp.arange(4.0)
        out = jax.jit(shard_map(
            lambda x: p2p.send_forward_recv_forward(x, axis_name="pipe"),
            mesh=pp_mesh, in_specs=(P("pipe"),),
            out_specs=P("pipe")))(x)
        np.testing.assert_allclose(np.asarray(out), [0, 0, 1, 2])

    def test_backward_shift(self, pp_mesh):
        x = jnp.arange(4.0)
        out = jax.jit(shard_map(
            lambda x: p2p.send_backward_recv_backward(x, axis_name="pipe"),
            mesh=pp_mesh, in_specs=(P("pipe"),),
            out_specs=P("pipe")))(x)
        np.testing.assert_allclose(np.asarray(out), [1, 2, 3, 0])


class TestMicrobatches:
    def test_constant(self):
        c = build_num_microbatches_calculator(0, None, 64, 4, 2)
        assert isinstance(c, ConstantNumMicroBatches)
        assert c.get() == 8
        assert c.get_current_global_batch_size() == 64

    def test_rampup(self):
        c = build_num_microbatches_calculator(0, [16, 16, 1000], 64, 4, 2)
        assert c.get_current_global_batch_size() == 16
        c.update(500, True)
        assert 16 <= c.get_current_global_batch_size() <= 64
        c.update(2000, True)
        assert c.get_current_global_batch_size() == 64

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            build_num_microbatches_calculator(0, None, 30, 4, 2)
