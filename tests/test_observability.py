"""Profiling/observability utilities + pipeline memory accounting
(reference: SURVEY §5 — nvtx ranges -> named scopes, pyprof -> jax
profiler traces, race detection -> program-hash assertion; plus the
pipeline engine's remat memory claim, measured here instead of asserted
in a docstring)."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.models.gpt import (GPTConfig, GPTModel, pack_for_shard_map,
                                 pipeline_step)
from apex_tpu.transformer import parallel_state
from apex_tpu.transformer.log_util import (get_transformer_logger,
                                           set_logging_level)
from apex_tpu.utils import profiling


class TestLogUtil:
    def test_logger_namespace(self):
        lg = get_transformer_logger("pipeline_parallel.py")
        assert lg.name == "apex_tpu.transformer.pipeline_parallel"

    def test_set_level(self):
        set_logging_level(logging.DEBUG)
        assert logging.getLogger("apex_tpu").level == logging.DEBUG
        set_logging_level(logging.WARNING)


class TestNamedScopes:
    def test_annotate_in_hlo_metadata(self):
        def f(x):
            with profiling.annotate("my_hot_block"):
                return jnp.sin(x) * 2

        # scope names live in HLO op metadata (the compiled text), which
        # is what xprof reads
        text = jax.jit(f).lower(jnp.ones((4,))).compile().as_text()
        assert "my_hot_block" in text

    def test_range_push_pop(self):
        def f(x):
            profiling.range_push("pushed_range")
            y = x + 1
            profiling.range_pop()
            return y

        text = jax.jit(f).lower(jnp.ones((4,))).compile().as_text()
        assert "pushed_range" in text

    def test_model_scopes_present(self):
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=1,
                        num_attention_heads=2, max_seq_len=8)
        model = GPTModel(cfg)
        params = model.init_params(jax.random.PRNGKey(0))
        tokens = jnp.zeros((1, 8), jnp.int32)
        text = jax.jit(model.loss).lower(params, tokens,
                                         tokens).compile().as_text()
        assert "attention" in text and "mlp" in text


class TestProgramHash:
    def test_deterministic(self):
        def f(x):
            return x * 2 + 1

        x = jnp.ones((8,))
        assert profiling.program_hash(f, x) == profiling.program_hash(f, x)

    def test_differs_across_programs(self):
        x = jnp.ones((8,))
        h1 = profiling.program_hash(lambda v: v * 2, x)
        h2 = profiling.program_hash(lambda v: v * 3, x)
        assert h1 != h2

    def test_assert_same_program_single_controller(self):
        x = jnp.ones((8,))
        h = profiling.assert_same_program(lambda v: v + 1, x)
        assert isinstance(h, str) and len(h) == 64
        # precomputed-hash form
        assert profiling.assert_same_program(h) == h


class TestMemoryStats:
    def test_basic_fields(self):
        stats = profiling.memory_stats(
            lambda x: jnp.sin(x @ x).sum(), jnp.ones((64, 64)))
        if not stats:
            pytest.skip("backend lacks memory_analysis")
        assert stats["argument"] == 64 * 64 * 4
        assert stats["temp"] >= 0

    def test_remat_cuts_grad_residency(self):
        """Per-layer jax.checkpoint trades temp memory for recompute —
        measured.  (Wrapping a whole scan in checkpoint does NOT cut the
        peak: the recomputed forward's residuals are all live at once;
        the win comes from remat at layer granularity.)"""
        w = jnp.ones((128, 128))

        def deep(w, x, ckpt):
            def layer(h, _):
                def f(h):
                    h = jnp.tanh(h @ w)
                    h = jnp.tanh(h @ w)
                    h = jnp.tanh(h @ w)
                    return h
                if ckpt:
                    f = jax.checkpoint(f)
                return f(h), None
            return jax.lax.scan(layer, x, None, length=16)[0].sum()

        x = jnp.ones((256, 128))
        grad_plain = lambda w, x: jax.grad(deep)(w, x, False)
        grad_remat = lambda w, x: jax.grad(deep)(w, x, True)
        plain = profiling.memory_stats(grad_plain, w, x)
        remat = profiling.memory_stats(grad_remat, w, x)
        if not plain:
            pytest.skip("backend lacks memory_analysis")
        assert remat["temp"] < plain["temp"], (remat, plain)


class TestPipelineMemoryProfile:
    """The round-1/2 open question, re-measured on the ring engine: the
    scan saves only stage INPUTS in a fixed ``2L-1`` ring buffer and
    recomputes each stage forward inside the per-tick vjp, so activation
    residency is bounded in M — temp grows only by the ``(M, ...)``
    microbatch I/O buffers — and ``remat`` (per-layer checkpoint inside
    the tick vjp) cuts the within-tick residuals.  Measured via XLA's own
    accounting."""

    def _pipeline_grad_temp(self, M, remat):
        parallel_state.destroy_model_parallel()
        try:
            mesh = parallel_state.initialize_model_parallel(1, 2)
            cfg_kw = dict(vocab_size=32, hidden_size=64, num_layers=4,
                          num_attention_heads=4, max_seq_len=32)
            model = GPTModel(GPTConfig(**cfg_kw))
            params = model.init_params(jax.random.PRNGKey(0))
            packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
                model, params, n_stages=2, tensor_axis=None)
            mb, seq = 2, 32
            tokens = jnp.zeros((M * mb, seq), jnp.int32)

            def step(sp, tokens):
                tk = tokens.reshape(M, mb, seq)
                loss, g = pipeline_step(model, local_fn(sp), tk, tk,
                                        pipe_axis="pipe", remat=remat)
                return loss, repack_fn(g)

            fn = shard_map(step, mesh=mesh,
                           in_specs=(in_specs, P()),
                           out_specs=(P(), in_specs))
            stats = profiling.memory_stats(fn, packed, tokens)
            return stats.get("temp")
        finally:
            parallel_state.destroy_model_parallel()

    def test_remat_cuts_tick_residuals_and_growth_stays_io_bound(self):
        t2_plain = self._pipeline_grad_temp(2, remat=False)
        if t2_plain is None:
            pytest.skip("backend lacks memory_analysis")
        t6_plain = self._pipeline_grad_temp(6, remat=False)
        t2_remat = self._pipeline_grad_temp(2, remat=True)
        t6_remat = self._pipeline_grad_temp(6, remat=True)
        print(f"\npipeline grad temp bytes: M=2 plain={t2_plain} "
              f"remat={t2_remat}; M=6 plain={t6_plain} remat={t6_remat}")
        # remat shrinks the per-tick residual set at fixed M
        assert t2_remat < t2_plain, (t2_remat, t2_plain)
        assert t6_remat < t6_plain, (t6_remat, t6_plain)
        # residency growth with M is the microbatch I/O term only — the
        # saved-activation set is the fixed ring buffer, so the growth is
        # no larger under plain than under remat (both ~= the I/O term)
        assert (t6_plain - t2_plain) <= (t6_remat - t2_remat) * 2, (
            (t2_plain, t6_plain), (t2_remat, t6_remat))

    def _interleaved_grad_temp(self, M, remat):
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            forward_backward_pipelining_with_interleaving)

        width, S, v, mb = 64, 2, 2, 2
        mesh = jax.make_mesh((S,), ("pipe",))
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(S, v, width, width) * 0.1, jnp.float32)
        b = jnp.zeros((S, v, width), jnp.float32)
        x = jnp.asarray(rng.randn(M, mb, width), jnp.float32)
        t = jnp.asarray(rng.randn(M, mb, width), jnp.float32)

        def stage(p, x):
            return jnp.tanh(x @ p["w"] + p["b"])

        def loss(y, t):
            return jnp.mean((y - t) ** 2)

        def f(w, b, x, t):
            local = {"w": w[0], "b": b[0]}
            lv, g = forward_backward_pipelining_with_interleaving(
                stage, loss, local, x, t, axis_name="pipe",
                n_virtual=v, remat=remat)
            return lv, jax.tree_util.tree_map(lambda g: g[None], g)

        fn = shard_map(f, mesh=mesh,
                       in_specs=(P("pipe"), P("pipe"), P(), P()),
                       out_specs=(P(), {"w": P("pipe"), "b": P("pipe")}))
        return profiling.memory_stats(fn, w, b, x, t).get("temp")

    def test_interleaved_residency_bounded_in_m(self):
        """Same measurement for the interleaved (virtual-chunk) schedule:
        the ring buffer is sized by L = S*v, not by M, so tripling M must
        not triple the temp residency."""
        t2 = self._interleaved_grad_temp(2, remat=False)
        if t2 is None:
            pytest.skip("backend lacks memory_analysis")
        t6 = self._interleaved_grad_temp(6, remat=False)
        print(f"\ninterleaved grad temp bytes: M=2 {t2}; M=6 {t6}")
        assert t6 < 3 * t2, (t2, t6)
