"""EQuARX-style block-quantized collectives (utils/compressed_allreduce)
on the fake 8-device CPU mesh, plus the byte-capped bucket splitter the
distributed optimizers use (apex ``message_size`` semantics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.multi_tensor_apply import bucketing as B
from apex_tpu.utils import compressed_allreduce as CA
from apex_tpu.utils.collectives import shard_map_compat

N = 8


@pytest.fixture
def mesh():
    return jax.make_mesh((N,), ("data",))


class TestQuantizeInt8:
    def test_roundtrip_error_bound(self, rng):
        x = jnp.asarray(rng.randn(64, 128).astype(np.float32))
        q, s = CA.quantize_int8(x)
        assert q.dtype == jnp.int8 and s.shape == (64, 1)
        err = np.abs(np.asarray(CA.dequantize_int8(q, s)) - np.asarray(x))
        # symmetric rounding: error ≤ scale/2 = blockmax/254 per element
        bound = np.max(np.abs(np.asarray(x)), axis=1, keepdims=True) / 254
        assert np.all(err <= bound + 1e-7)

    def test_zero_block_exact(self):
        q, s = CA.quantize_int8(jnp.zeros((4, 128)))
        np.testing.assert_array_equal(np.asarray(q), 0)
        np.testing.assert_array_equal(np.asarray(s), 1.0)
        np.testing.assert_array_equal(
            np.asarray(CA.dequantize_int8(q, s)), 0.0)

    def test_extremes_saturate_cleanly(self):
        x = jnp.concatenate([jnp.full((1, 64), 3.0),
                             jnp.full((1, 64), -3.0)], axis=1)
        q, s = CA.quantize_int8(x)
        out = np.asarray(CA.dequantize_int8(q, s))
        np.testing.assert_allclose(out, np.asarray(x), rtol=1e-6)

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="allreduce_dtype"):
            CA.check_mode("fp8")


def _run(mesh, body, x, out_specs=P()):
    return jax.jit(shard_map_compat(body, mesh=mesh,
                                    in_specs=(P("data"),),
                                    out_specs=out_specs))(x)


class TestReduceScatter:
    def test_f32_bitwise_matches_psum_scatter(self, rng, mesh):
        x = jnp.asarray(rng.randn(N, 16, 128).astype(np.float32))

        def exact(v):
            return jax.lax.psum_scatter(v[0], "data", scatter_dimension=0,
                                        tiled=True)

        def ours(v):
            return CA.reduce_scatter(v[0], "data", N, "f32")

        np.testing.assert_array_equal(
            np.asarray(_run(mesh, exact, x, P("data"))),
            np.asarray(_run(mesh, ours, x, P("data"))))

    @pytest.mark.parametrize("mode,tol", [("bf16", 1e-2), ("int8", 1e-2)])
    def test_quantized_close(self, rng, mesh, mode, tol):
        x = jnp.asarray(rng.randn(N, 16, 128).astype(np.float32))

        def body(v):
            s = CA.reduce_scatter(v[0], "data", N, mode)
            return CA.all_gather_rows(s, "data", mode)

        out = np.asarray(_run(mesh, body, x))
        ref = np.sum(np.asarray(x), axis=0)
        err = np.max(np.abs(out - ref)) / np.max(np.abs(ref))
        assert err < tol, err

    def test_indivisible_rows_raise(self, mesh):
        opts = dict(mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))

        def body(v):
            return CA.reduce_scatter(v[0], "data", N, "int8")

        with pytest.raises(ValueError, match="divisible"):
            jax.jit(shard_map_compat(body, **opts))(
                jnp.zeros((N, 12, 128)))  # 12 % 8 != 0

    def test_pad_rows(self):
        x = jnp.ones((12, 128))
        y = CA.pad_rows(x, N)
        assert y.shape == (16, 128)
        np.testing.assert_array_equal(np.asarray(y[12:]), 0.0)
        assert CA.pad_rows(y, N) is y


class TestPsumCompressed:
    @pytest.mark.parametrize("shape", [(33, 7), (128,), (1,)])
    def test_arbitrary_shapes(self, rng, mesh, shape):
        x = jnp.asarray(rng.randn(N, *shape).astype(np.float32))

        def body(v):
            return CA.psum_compressed(v[0], "data", N, "int8")

        out = np.asarray(_run(mesh, body, x))
        ref = np.sum(np.asarray(x), axis=0)
        scale = max(np.max(np.abs(ref)), 1e-6)
        assert np.max(np.abs(out - ref)) / scale < 2e-2
        assert out.shape == tuple(shape)

    def test_f32_is_plain_psum(self, rng, mesh):
        x = jnp.asarray(rng.randn(N, 9, 5).astype(np.float32))

        def body(v):
            return CA.psum_compressed(v[0], "data", N, None)

        def ref_body(v):
            return jax.lax.psum(v[0], "data")

        np.testing.assert_array_equal(np.asarray(_run(mesh, body, x)),
                                      np.asarray(_run(mesh, ref_body, x)))

    def test_tree_skips_int_leaves(self, mesh):
        tree = {"g": jnp.ones((N, 4, 128)),
                "count": jnp.ones((N,), jnp.int32)}

        def body(v):
            v = jax.tree_util.tree_map(lambda x: x[0], v)
            return CA.psum_tree_compressed(v, "data", N, "int8")

        out = jax.jit(shard_map_compat(
            body, mesh=mesh,
            in_specs=({"g": P("data"), "count": P("data")},),
            out_specs=P()))(tree)
        assert out["count"].dtype == jnp.int32
        assert int(out["count"]) == N          # exact integer psum
        np.testing.assert_allclose(np.asarray(out["g"]), 8.0, rtol=1e-6)


class TestSplitByMessageSize:
    def test_bytes_are_dtype_aware(self):
        # four 128-element tensors: f32 = 512 B each, bf16 = 256 B each.
        # A 1 KiB cap holds 2 f32 tensors per bucket but 4 bf16 ones.
        shapes = [(128,)] * 4
        assert B.split_by_message_size(shapes, jnp.float32, 1024) == \
            [[0, 1], [2, 3]]
        assert B.split_by_message_size(shapes, jnp.bfloat16, 1024) == \
            [[0, 1, 2, 3]]

    def test_padded_footprint_counts(self):
        # a 1-element tensor still ships a full LANE-padded row (512 B f32)
        assert B.split_by_message_size([(1,), (1,)], jnp.float32, 512) == \
            [[0], [1]]

    def test_oversize_tensor_gets_own_group(self):
        shapes = [(64,), (1024,), (64,)]
        groups = B.split_by_message_size(shapes, jnp.float32, 1024)
        assert groups == [[0], [1], [2]]     # 4 KiB tensor > 1 KiB cap

    def test_nonpositive_cap_rejected(self):
        with pytest.raises(ValueError, match="message_size"):
            B.split_by_message_size([(4,)], jnp.float32, 0)
