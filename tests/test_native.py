"""Native host runtime + gpu_direct_storage (reference:
``apex/contrib/csrc/gpu_direct_storage``, ``csrc/flatten_unflatten.cpp``).

The native .so is compiled on demand by ``apex_tpu.utils.native``; every
API must also work with the library disabled (pure-Python fallback), so
each test runs both paths.
"""

import importlib

import numpy as np
import pytest

from apex_tpu.utils import native


@pytest.fixture(params=["native", "fallback"])
def native_mode(request, monkeypatch):
    if request.param == "native":
        if native.lib() is None:
            pytest.skip("native host runtime unavailable (no g++?)")
    else:
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", True)
    return request.param


class TestPack:
    def test_roundtrip_mixed_dtypes(self, native_mode):
        rng = np.random.RandomState(0)
        arrs = [rng.randn(17, 3).astype(np.float32),
                rng.randint(0, 100, (5,)).astype(np.int64),
                rng.randn(2, 2, 2).astype(np.float16),
                np.asarray(3.0, np.float64)]
        buf = native.pack(arrs)
        assert buf.dtype == np.uint8
        assert buf.size == sum(a.nbytes for a in arrs)
        outs = [np.empty_like(a) for a in arrs]
        native.unpack(buf, outs)
        for a, o in zip(arrs, outs):
            np.testing.assert_array_equal(a, o)

    def test_matches_concatenate(self, native_mode):
        rng = np.random.RandomState(1)
        arrs = [rng.randn(n).astype(np.float32) for n in (1, 1000, 77)]
        buf = native.pack(arrs)
        ref = np.concatenate([a.view(np.uint8).reshape(-1) for a in arrs])
        np.testing.assert_array_equal(buf, ref)

    def test_large_multithreaded(self, native_mode):
        rng = np.random.RandomState(2)
        arrs = [rng.randn(300_000).astype(np.float32) for _ in range(4)]
        buf = native.pack(arrs)  # >1 MiB: native path goes threaded
        outs = [np.empty_like(a) for a in arrs]
        native.unpack(buf, outs)
        for a, o in zip(arrs, outs):
            np.testing.assert_array_equal(a, o)

    def test_empty_list(self, native_mode):
        assert native.pack([]).size == 0


class TestFileIO:
    def test_roundtrip(self, native_mode, tmp_path):
        rng = np.random.RandomState(3)
        data = rng.randint(0, 256, (123457,)).astype(np.uint8)
        p = str(tmp_path / "blob.bin")
        native.file_write(p, data)
        out = native.file_read(p)
        np.testing.assert_array_equal(data, out)

    def test_large_parallel(self, native_mode, tmp_path):
        data = np.arange(9 << 20, dtype=np.uint8)  # >8 MiB: threaded
        p = str(tmp_path / "big.bin")
        native.file_write(p, data, threads=4)
        out = native.file_read(p, threads=4)
        np.testing.assert_array_equal(data, out)


class TestGDS:
    def _gds(self):
        return importlib.import_module(
            "apex_tpu.contrib.gpu_direct_storage")

    def test_numpy_roundtrip(self, native_mode, tmp_path):
        gds = self._gds()
        rng = np.random.RandomState(4)
        a = rng.randn(33, 7).astype(np.float32)
        p = str(tmp_path / "t.apxt")
        gds.save(p, a)
        out = gds.load(p)
        assert out.dtype == a.dtype and out.shape == a.shape
        np.testing.assert_array_equal(a, out)

    def test_pytree_roundtrip(self, native_mode, tmp_path):
        gds = self._gds()
        rng = np.random.RandomState(5)
        tree = {"w": rng.randn(8, 8).astype(np.float32),
                "stats": [rng.randn(3).astype(np.float64),
                          np.asarray(7, np.int32)]}
        p = str(tmp_path / "tree.apxt")
        gds.save(p, tree)
        out = gds.load(p, tree_like=tree)
        assert set(out) == {"w", "stats"}
        np.testing.assert_array_equal(out["w"], tree["w"])
        np.testing.assert_array_equal(out["stats"][0], tree["stats"][0])
        np.testing.assert_array_equal(out["stats"][1], tree["stats"][1])

    def test_overwrite_pytree_with_array(self, native_mode, tmp_path):
        """save(array) over a pytree checkpoint must clear the sidecar so
        load() dispatches on the new format."""
        gds = self._gds()
        p = str(tmp_path / "ck.apxt")
        gds.save(p, {"w": np.arange(4.0)})
        a = np.arange(10.0).reshape(2, 5)
        gds.save(p, a)
        out = gds.load(p)
        assert out.shape == (2, 5)
        np.testing.assert_array_equal(out, a)

    def test_jax_array(self, native_mode, tmp_path):
        import jax.numpy as jnp
        gds = self._gds()
        a = jnp.arange(16.0).reshape(4, 4)
        p = str(tmp_path / "jx.apxt")
        gds.save(p, a)
        np.testing.assert_array_equal(gds.load(p), np.asarray(a))


def test_gds_scalar_leaves_roundtrip(tmp_path):
    """0-d leaves must round-trip as 0-d: np.ascontiguousarray promotes
    scalars to 1-d, which used to corrupt optimizer step counters and
    scaler state in checkpoints (caught by the resume recipe)."""
    import jax.numpy as jnp

    from apex_tpu.contrib import gpu_direct_storage as gds

    obj = {"a": jnp.zeros((3, 4)), "step": jnp.int32(7),
           "scale": jnp.float32(2.5)}
    path = str(tmp_path / "scalars.bin")
    gds.save(path, obj)
    back = gds.load(path, tree_like=obj)
    assert np.asarray(back["step"]).shape == ()
    assert np.asarray(back["scale"]).shape == ()
    assert int(back["step"]) == 7 and float(back["scale"]) == 2.5
    np.testing.assert_array_equal(np.asarray(back["a"]),
                                  np.asarray(obj["a"]))
