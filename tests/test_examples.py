"""Example-script smoke tests (reference: apex has no CI for examples —
its L0 test philosophy applied here: every shipped entry point must run
end-to-end, on the 8-virtual-device CPU mesh so the GSPMD/DDP paths are
real multi-device executions)."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_example(rel_path, argv, timeout=600):
    """Run an example under forced-CPU with 8 virtual devices.

    The axon TPU plugin ignores ``JAX_PLATFORMS=cpu`` from the
    environment, so the child sets the platform via jax.config BEFORE the
    example's imports initialize a backend (tests/conftest.py does the
    same for this process).
    """
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "import sys, runpy; sys.argv = [sys.argv[0]] + %r;"
        "runpy.run_path(%r, run_name='__main__')"
        % (argv, os.path.join(_ROOT, rel_path)))
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=timeout)


def _check(res):
    assert res.returncode == 0, res.stderr[-3000:]
    assert "DONE" in res.stdout, res.stdout[-2000:]
    return res.stdout


class TestExamples:
    def test_simple_ddp(self):
        out = _check(_run_example(
            "examples/simple/distributed/distributed_data_parallel.py", []))
        assert "devices=8" in out

    @pytest.mark.parametrize("opt_level", ["O0", "O1", "O2"])
    def test_imagenet(self, opt_level):
        out = _check(_run_example(
            "examples/imagenet/main_amp.py",
            ["--arch", "resnet18", "--batch-size", "16", "--image-size",
             "32", "--num-classes", "10", "--steps", "2", "--print-freq",
             "1", "--opt-level", opt_level]))
        assert "devices=8" in out

    def test_dcgan(self):
        _check(_run_example(
            "examples/dcgan/main_amp.py",
            ["--batch-size", "8", "--image-size", "64", "--steps", "2",
             "--print-freq", "1", "--ngf", "8", "--ndf", "8",
             "--nz", "16"]))

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_switch_gpt(self, top_k):
        out = _check(_run_example(
            "examples/moe/train_switch_gpt.py",
            ["--n-experts", "8", "--batch-per-device", "2",
             "--seq-len", "32", "--hidden", "32", "--layers", "1",
             "--heads", "4", "--vocab", "64", "--steps", "2",
             "--print-freq", "1", "--top-k", str(top_k)]))
        assert "devices=8" in out

    @pytest.mark.parametrize("mechanism", ["ring", "ulysses"])
    def test_long_context(self, mechanism):
        out = _check(_run_example(
            "examples/long_context/train_long_gpt.py",
            ["--seq-len", "64", "--hidden", "32", "--layers", "1",
             "--heads", "8", "--vocab", "64", "--steps", "2",
             "--print-freq", "1", "--mechanism", mechanism]))
        assert "devices=8" in out

    def test_conformer_rnnt(self):
        _check(_run_example(
            "examples/conformer/train_rnnt.py",
            ["--steps", "2", "--print-freq", "1", "--batch-size", "2",
             "--layers", "1", "--hidden", "32", "--heads", "2",
             "--audio-len", "40", "--target-len", "6", "--vocab", "16",
             "--pred-hidden", "32", "--n-mels", "8"]))

    @pytest.mark.parametrize("opt_level", ["O0", "O2"])
    def test_bert_pretrain(self, opt_level):
        out = _check(_run_example(
            "examples/bert/pretrain_bert.py",
            ["--config", "tiny", "--batch-size", "8", "--seq-len", "64",
             "--steps", "2", "--print-freq", "1",
             "--opt-level", opt_level]))
        assert "devices=8" in out

    def test_serving_engine(self):
        """The inference subsystem end-to-end: continuous batching over
        2 cache slots with a mixed greedy/top-k workload."""
        out = _check(_run_example(
            "examples/serving/generate_gpt.py",
            ["--requests", "4", "--max-slots", "2", "--hidden", "32",
             "--layers", "1", "--heads", "2", "--vocab", "64",
             "--max-seq", "32", "--max-new-tokens", "6",
             "--temperature", "0.7"]))
        assert "served 4 requests" in out

    def test_gpt7b_recipe_smoke(self):
        """BASELINE row 2's runnable artifact: the 7B TP x PP recipe at
        --smoke keeps the full tp=2 x pp=2 x dp=2 topology and every
        collective family, shrinking only shapes."""
        out = _check(_run_example(
            "examples/gpt7b/pretrain_gpt7b.py", ["--smoke", "--steps", "2"]))
        assert "mesh=(dp=2, pp=2, tp=2)" in out

    def test_checkpoint_resume_bitwise(self, tmp_path):
        """SURVEY §5 checkpoint/resume: the resumed process continues the
        EXACT trajectory of the uninterrupted run — full state (params,
        packed optimizer buckets, dynamic scaler, step) round-trips
        through the framework's own parallel-IO runtime."""
        import re
        ck = str(tmp_path / "ck.bin")
        full = _check(_run_example(
            "examples/checkpoint/train_resume.py",
            ["--steps", "6", "--save-at", "3", "--ckpt", ck]))
        resumed = _check(_run_example(
            "examples/checkpoint/train_resume.py",
            ["--steps", "6", "--resume", "--ckpt", ck]))

        def losses(out):
            return {int(m[0]): m[1] for m in
                    re.findall(r"step (\d+): loss=([0-9.]+)", out)}

        lf, lr = losses(full), losses(resumed)
        assert set(lr) == {3, 4, 5}, resumed
        for s in lr:
            assert lf[s] == lr[s], (s, lf[s], lr[s])  # bitwise identical
