"""Contrib wave 2 + RNN tier (reference: ``apex/contrib/{conv_bias_relu,
cudnn_gbn,nccl_p2p,nccl_allocator,openfold_triton}``, ``apex/RNN``) —
each surface against a composed jnp reference, shard_map paths on the
8-device mesh."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import PartitionSpec as P


@pytest.fixture
def rng():
    return np.random.RandomState(0)


class TestConvBiasReLU:
    def _ref_conv(self, x, w, stride, padding):
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), ((padding, padding),) * 2,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def test_conv_bias_relu(self, rng):
        from apex_tpu.contrib.conv_bias_relu import ConvBias, ConvBiasReLU
        x = jnp.asarray(rng.randn(2, 8, 8, 3), jnp.float32)
        w = jnp.asarray(rng.randn(3, 3, 3, 16) * 0.1, jnp.float32)
        b = jnp.asarray(rng.randn(16) * 0.1, jnp.float32)
        got = ConvBiasReLU(x, w, b, padding=1, stride=2)
        ref = jax.nn.relu(self._ref_conv(x, w, 2, 1) + b)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6)
        got_nb = ConvBias(x, w, b, padding=1, stride=1)
        assert got_nb.shape == (2, 8, 8, 16)
        assert float(jnp.min(got)) >= 0.0

    def test_mask_and_frozen_scale(self, rng):
        from apex_tpu.contrib.conv_bias_relu import (
            ConvBiasMaskReLU, ConvFrozenScaleBiasReLU)
        x = jnp.asarray(rng.randn(1, 6, 6, 2), jnp.float32)
        w = jnp.asarray(rng.randn(1, 1, 2, 4) * 0.3, jnp.float32)
        b = jnp.zeros((4,), jnp.float32)
        mask = jnp.asarray(rng.rand(1, 6, 6, 4) > 0.5, jnp.float32)
        y = ConvBiasMaskReLU(x, w, b, mask, padding=0, stride=1)
        np.testing.assert_array_equal(
            np.asarray(y == 0.0) | np.asarray(mask > 0), True)
        scale = jnp.asarray(rng.rand(4) + 0.5, jnp.float32)
        bias = jnp.asarray(rng.randn(4), jnp.float32)
        z = ConvFrozenScaleBiasReLU(x, w, scale, bias)
        ref = jax.nn.relu(self._ref_conv(x, w, 1, 0) * scale + bias)
        np.testing.assert_allclose(np.asarray(z), np.asarray(ref),
                                   rtol=1e-6)

    def test_grad_flows(self, rng):
        from apex_tpu.contrib.conv_bias_relu import ConvBiasReLU
        x = jnp.asarray(rng.randn(1, 4, 4, 2), jnp.float32)
        w = jnp.asarray(rng.randn(3, 3, 2, 2) * 0.1, jnp.float32)
        b = jnp.zeros((2,), jnp.float32)
        g = jax.grad(lambda w: ConvBiasReLU(x, w, b, 1, 1).sum())(w)
        assert bool(jnp.any(g != 0))


class TestCudnnGBN:
    def test_matches_groupbn(self, rng):
        from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
        x = jnp.asarray(rng.randn(4, 4, 4, 8), jnp.float32)
        a = GroupBatchNorm2d(8)
        b = BatchNorm2d_NHWC(8)
        pa, sa = a.init_params(), a.init_state()
        pb, sb = b.init_params(), b.init_state()
        ya, _ = a(pa, sa, x, training=True)
        yb, _ = b(pb, sb, x, training=True)
        np.testing.assert_allclose(np.asarray(ya), np.asarray(yb))

    def test_group_requires_axis(self):
        from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
        with pytest.raises(ValueError):
            GroupBatchNorm2d(8, group_size=4)
        GroupBatchNorm2d(8, group_size=4, axis_name="data")  # ok

    def test_cross_device_stats(self, rng):
        from apex_tpu.contrib.cudnn_gbn import GroupBatchNorm2d
        mesh = jax.make_mesh((4,), ("data",))
        m = GroupBatchNorm2d(8, group_size=4, axis_name="data")
        params, state = m.init_params(), m.init_state()
        x = jnp.asarray(rng.randn(8, 4, 4, 8), jnp.float32)

        y = jax.jit(shard_map(
            lambda p, s, x: m(p, s, x, training=True)[0],
            mesh=mesh, in_specs=(P(), P(), P("data")),
            out_specs=P("data")))(params, state, x)
        # group stats == global-batch stats: output is exactly the
        # serial BN over the full batch
        serial = GroupBatchNorm2d(8)
        y_ref, _ = serial(params, state, x, training=True)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   rtol=2e-4, atol=2e-4)


class TestNcclP2P:
    def test_left_right_halo_exchange(self, rng):
        from apex_tpu.contrib.nccl_p2p import left_right_halo_exchange
        mesh = jax.make_mesh((4,), ("spatial",))
        x = jnp.asarray(rng.randn(4, 3, 5), jnp.float32)  # rank-major

        def step(x):
            left_out = x[:, :1]          # my top rows
            right_out = x[:, -1:]        # my bottom rows
            li, ri = left_right_halo_exchange(left_out, right_out,
                                              "spatial")
            return li, ri

        li, ri = jax.jit(shard_map(
            step, mesh=mesh, in_specs=P("spatial"),
            out_specs=(P("spatial"), P("spatial"))))(x)
        li, ri = np.asarray(li), np.asarray(ri)
        x = np.asarray(x)
        # rank r's left input == rank r-1's right output; edge rank gets 0
        np.testing.assert_array_equal(li[0], 0.0)
        for r in range(1, 4):
            np.testing.assert_array_equal(li[r], x[r - 1, -1:])
        np.testing.assert_array_equal(ri[3], 0.0)
        for r in range(3):
            np.testing.assert_array_equal(ri[r], x[r + 1, :1])

    def test_nccl_allocator_shim(self):
        import apex_tpu.contrib.nccl_allocator as na
        with pytest.raises(RuntimeError):
            with na.nccl_mem():
                pass
        na.init()
        pool = na.create_nccl_mem_pool()
        with na.nccl_mem(pool):
            buf = jnp.zeros((8,))
        assert buf.shape == (8,)


class TestOpenfold:
    def test_attention_core_no_bias_matches_reference(self, rng):
        from apex_tpu.contrib.openfold_triton import attention_core
        q = jnp.asarray(rng.randn(2, 2, 16, 8), jnp.float32)
        k = jnp.asarray(rng.randn(2, 2, 16, 8), jnp.float32)
        v = jnp.asarray(rng.randn(2, 2, 16, 8), jnp.float32)
        got = attention_core(q, k, v)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * 8 ** -0.5
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_attention_core_bias_mask(self, rng):
        from apex_tpu.contrib.openfold_triton import attention_core
        # extra leading (evoformer row) batch dim + pair bias + mask
        q = jnp.asarray(rng.randn(2, 3, 2, 8, 4), jnp.float32)
        k = jnp.asarray(rng.randn(2, 3, 2, 8, 4), jnp.float32)
        v = jnp.asarray(rng.randn(2, 3, 2, 8, 4), jnp.float32)
        bias = jnp.asarray(rng.randn(2, 1, 2, 8, 8), jnp.float32)
        mask = jnp.ones((2, 3, 1, 1, 8)).at[..., 6:].set(0)
        got = attention_core(q, k, v, mask=mask, bias=bias)
        s = jnp.einsum("...qd,...kd->...qk", q, k) * 4 ** -0.5 + bias
        s = s - (1 - mask) * 1e9
        ref = jnp.einsum("...qk,...kd->...qd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_layer_norm_impl(self, rng):
        from apex_tpu.contrib.openfold_triton import (
            LayerNormSmallShapeOptImpl)
        x = jnp.asarray(rng.randn(4, 7, 64), jnp.float32)
        w = jnp.asarray(rng.rand(64) + 0.5, jnp.float32)
        b = jnp.asarray(rng.randn(64), jnp.float32)
        got = LayerNormSmallShapeOptImpl.apply(x, w, b)
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        ref = (x - mu) / jnp.sqrt(var + 1e-5) * w + b
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_fused_adam_swa(self, rng):
        from apex_tpu.contrib.openfold_triton import FusedAdamSWA
        params = {"w": jnp.asarray(rng.randn(16, 16), jnp.float32)}
        grads = {"w": jnp.asarray(rng.randn(16, 16) * 0.1, jnp.float32)}
        opt = FusedAdamSWA(lr=1e-2, swa_start=2, swa_freq=1)
        state = opt.init(params)
        p = params
        snapshots = []
        for _ in range(5):
            p, state = opt.step(grads, p, state)
            snapshots.append(np.asarray(p["w"]))
        # swa averages steps 3..5 (count 3)
        assert int(state["n_avg"]) == 3
        swa = opt.swa_params(state, like=params)
        ref = np.mean(snapshots[2:], axis=0)
        np.testing.assert_allclose(np.asarray(swa["w"]), ref,
                                   rtol=1e-5, atol=1e-6)


class TestRNN:
    def test_lstm_matches_torch_formula(self, rng):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from apex_tpu.RNN import LSTM
            m = LSTM(4, 6, num_layers=2)
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(5, 3, 4), jnp.float32)
        out, states = m.apply(params, x)
        assert out.shape == (5, 3, 6)
        assert len(states) == 2 and len(states[0]) == 2

        # manual recurrence for layer 0, step 0
        p = params[0]
        g = x[0] @ p["i2h"]["weight"] + p["i2h"]["bias"] \
            + jnp.zeros((3, 6)) @ p["h2h"]["weight"] + p["h2h"]["bias"]
        i, f, gc, o = jnp.split(g, 4, -1)
        c = jax.nn.sigmoid(i) * jnp.tanh(gc)
        h0 = jax.nn.sigmoid(o) * jnp.tanh(c)
        # layer-0 output at t=0 feeds layer 1; verify via re-running scan
        out1, _ = m.apply(params[:1], x)
        np.testing.assert_allclose(np.asarray(out1[0]), np.asarray(h0),
                                   rtol=1e-5, atol=1e-6)

    def test_gru_and_rnn_run(self, rng):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from apex_tpu.RNN import GRU, RNNReLU, RNNTanh
            for factory in (GRU, RNNReLU, RNNTanh):
                m = factory(3, 5)
                params = m.init_params(jax.random.PRNGKey(1))
                out, _ = m.apply(params,
                                 jnp.asarray(rng.randn(4, 2, 3),
                                             jnp.float32))
                assert out.shape == (4, 2, 5)
                assert bool(jnp.all(jnp.isfinite(out)))

    def test_deprecation_warning(self):
        from apex_tpu.RNN import LSTM
        with pytest.warns(DeprecationWarning):
            LSTM(2, 2)

    def test_grad_through_scan(self, rng):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            from apex_tpu.RNN import LSTM
            m = LSTM(3, 4)
        params = m.init_params(jax.random.PRNGKey(2))
        x = jnp.asarray(rng.randn(6, 2, 3), jnp.float32)

        def loss(params):
            out, _ = m.apply(params, x)
            return jnp.mean(out ** 2)

        g = jax.jit(jax.grad(loss))(params)
        assert all(bool(jnp.any(l != 0))
                   for l in jax.tree_util.tree_leaves(g))
