"""apex_tpu.observability: registry, spans, training monitor, comms.

The contract under test (ISSUE 5):

* the metrics registry enforces Prometheus label semantics (declared
  label NAMES, full label VALUES per sample, mismatches raise), is
  thread-safe, and exports through two lossless surfaces — the JSONL
  event stream round-trips byte-identically through ``replay_jsonl``,
  and the text snapshot is valid Prometheus exposition format
  (cumulative histogram buckets, ``_sum``/``_count``);
* spans nest per-thread, emit valid Chrome trace-event JSON, and
  compose with ``jax.named_scope`` so the span name lands in the
  lowered HLO of ops traced inside;
* ``TrainingMonitor`` on a guarded GPT step reports anomaly counts
  that MATCH ``GuardedTrainStep.stats``, emits per-step JSONL records
  with the alerting keys, and taps grad-norm/loss/loss-scale without
  adding device->host syncs (the series come from StepResult's host
  fields);
* ``collective_stats`` byte counts match hand-computed payloads for
  tp=2 shard_map collectives;
* ``ServingMetrics`` drops per-request transient state at every
  terminal transition (the leak fix) while ``summary()`` values are
  unchanged; ``range_pop`` warns once on an unmatched pop.
"""

import io
import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.observability import (Counter, Gauge, Histogram,
                                    MetricsRegistry, Tracer,
                                    TrainingMonitor, collective_stats,
                                    format_stats, hlo_collective_stats,
                                    replay_jsonl, wire_bytes)
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import Fault, FaultInjector, GuardedTrainStep
from apex_tpu.utils import profiling
from apex_tpu.utils.collectives import shard_map_compat
from apex_tpu.utils.profiling import ServingMetrics


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_label_semantics(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total", "reqs", labelnames=("route",))
        c.inc(route="a")
        c.inc(2, route="b")
        assert c.value(route="a") == 1 and c.value(route="b") == 2
        with pytest.raises(ValueError):
            c.inc()                       # missing label
        with pytest.raises(ValueError):
            c.inc(route="a", extra="x")   # unknown label
        with pytest.raises(ValueError):
            c.inc(-1, route="a")          # counters only go up

    def test_redeclaration(self):
        reg = MetricsRegistry()
        c1 = reg.counter("c_total", "c")
        assert reg.counter("c_total") is c1      # idempotent
        with pytest.raises(ValueError):
            reg.gauge("c_total")                 # kind mismatch
        with pytest.raises(ValueError):
            reg.counter("c_total", labelnames=("x",))  # labels mismatch
        with pytest.raises(ValueError):
            reg.counter("bad name")              # invalid name

    def test_gauge_and_histogram(self):
        reg = MetricsRegistry()
        g = reg.gauge("g")
        g.set(5.0)
        g.inc(2.0)
        g.dec(3.0)
        assert g.value() == 4.0
        h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 2.0):
            h.observe(v)
        assert h.count() == 3 and h.sum() == pytest.approx(2.55)

    def test_prometheus_format(self):
        reg = MetricsRegistry()
        reg.counter("a_total", "things", labelnames=("k",)).inc(k="x")
        h = reg.histogram("lat_seconds", "lat", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(2.0)
        prom = reg.prometheus()
        assert "# HELP a_total things\n# TYPE a_total counter" in prom
        assert 'a_total{k="x"} 1' in prom
        # cumulative buckets + +Inf == count
        assert 'lat_seconds_bucket{le="0.1"} 1' in prom
        assert 'lat_seconds_bucket{le="1"} 2' in prom
        assert 'lat_seconds_bucket{le="+Inf"} 3' in prom
        assert "lat_seconds_sum 2.55" in prom
        assert "lat_seconds_count 3" in prom

    def test_jsonl_replay_round_trip(self):
        reg = MetricsRegistry(clock=lambda: 1.0)
        buf = io.StringIO()
        reg.attach_stream(buf)
        c = reg.counter("reqs_total", "requests", labelnames=("route",))
        c.inc(route="a")
        c.inc(3, route="b")
        reg.gauge("tps", "throughput").set(123.5)
        h = reg.histogram("lat_seconds", "lat", buckets=(0.1, 1.0))
        h.observe(0.5)
        reg.event("train_step", step=1, loss=2.5)
        lines = buf.getvalue().splitlines()
        for ln in lines:
            json.loads(ln)               # every line is one JSON object
        reg2, records = replay_jsonl(lines)
        # byte-identical snapshot: declares carry help text + buckets
        assert reg2.prometheus() == reg.prometheus()
        assert reg2.get("lat_seconds").buckets == (0.1, 1.0)
        assert records == [{"ts": 1.0, "event": "train_step",
                            "step": 1, "loss": 2.5}]

    def test_late_attach_emits_declares(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", "help text")
        buf = io.StringIO()
        reg.attach_stream(buf)           # after declaration
        c.inc()
        reg2, _ = replay_jsonl(buf.getvalue().splitlines())
        assert reg2.get("c_total").help == "help text"
        assert reg2.get("c_total").value() == 1

    def test_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("n_total")
        h = reg.histogram("h")

        def work():
            for _ in range(200):
                c.inc()
                h.observe(0.01)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value() == 800
        assert h.count() == 800

    def test_snapshot(self):
        reg = MetricsRegistry()
        reg.counter("c_total").inc(2)
        reg.histogram("h").observe(1.5)
        snap = reg.snapshot()
        assert snap["c_total"]["series"][()] == 2
        assert snap["h"]["series"][()] == {"count": 1, "sum": 1.5}


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class TestSpans:
    def test_nesting_and_trace_json(self):
        t = [0.0]

        def clk():
            t[0] += 0.25
            return t[0]

        tr = Tracer(clock=clk)
        assert tr.depth() == 0
        with tr.span("outer", device=False):
            assert tr.depth() == 1
            with tr.span("inner", device=False, shard=3):
                assert tr.depth() == 2
        tr.instant("mark")
        assert tr.depth() == 0
        doc = json.loads(tr.to_json())
        evs = doc["traceEvents"]
        # inner closes (and records) first
        assert [e["name"] for e in evs] == ["inner", "outer", "mark"]
        inner, outer, mark = evs
        assert inner["ph"] == "X" and outer["ph"] == "X"
        assert mark["ph"] == "i"
        assert inner["args"] == {"shard": 3, "depth": 2}
        # microsecond complete events, inner contained within outer
        assert outer["ts"] <= inner["ts"]
        assert (inner["ts"] + inner["dur"]
                <= outer["ts"] + outer["dur"] + 1e-6)
        for e in (inner, outer, mark):
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)

    def test_save_and_clear(self, tmp_path):
        tr = Tracer()
        with tr.span("s", device=False):
            pass
        p = tr.save(str(tmp_path / "trace.json"))
        assert json.load(open(p))["traceEvents"]
        tr.clear()
        assert tr.events == []

    def test_out_of_order_close_raises(self):
        tr = Tracer()
        a = tr.span("a", device=False)
        b = tr.span("b", device=False)
        a.__enter__()
        b.__enter__()
        with pytest.raises(RuntimeError):
            a.__exit__(None, None, None)
        tr._stack()[:] = ["b"]           # restore so b can close cleanly
        b.__exit__(None, None, None)

    def test_named_scope_composition(self):
        """ops traced inside a span carry its name into compiled-HLO
        metadata (StableHLO drops debug locations; the compiled text is
        where profilers read scope names from)."""
        tr = Tracer()

        def fn(x):
            with tr.span("my_unique_scope"):
                return x * 2.0

        text = jax.jit(fn).lower(jnp.ones((4,))).compile().as_text()
        assert "my_unique_scope" in text


# ---------------------------------------------------------------------------
# training monitor
# ---------------------------------------------------------------------------

def _tiny_gpt_guard(scaler=None, injector=None):
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                    num_attention_heads=4, max_seq_len=8)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    adam = FusedAdam(lr=1e-3)
    guard = GuardedTrainStep(model.loss, adam, scaler=scaler,
                             fault_injector=injector)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, 32, (2, 8)))
    targets = jnp.asarray(rng.randint(0, 32, (2, 8)))
    return guard, params, adam.init(params), tokens, targets


class TestTrainingMonitor:
    def test_guarded_step_series_and_anomaly_parity(self):
        inj = FaultInjector([Fault(step=1, kind="nan_grads")])
        guard, params, opt_state, tokens, targets = _tiny_gpt_guard(
            injector=inj)
        buf = io.StringIO()
        reg = MetricsRegistry()
        reg.attach_stream(buf)
        mon = TrainingMonitor(reg, tokens_per_step=16)
        h = {"p": params, "o": opt_state, "g": guard.init_state()}

        def step(tokens, targets, step):
            r = guard(h["p"], h["o"], h["g"], tokens, targets, step=step)
            h["p"], h["o"], h["g"] = r.params, r.opt_state, r.guard_state
            return r

        monitored = mon.wrap(step)
        for i in range(3):
            monitored(tokens, targets, step=i)

        # anomaly accounting agrees with the guard's own counters
        assert guard.stats["steps"] == 3 and guard.stats["skipped"] == 1
        assert mon.stats["steps"] == 3
        assert mon.stats["skipped"] == guard.stats["skipped"]

        # per-step JSONL records carry the alerting keys
        records = [json.loads(ln) for ln in buf.getvalue().splitlines()]
        steps = [r for r in records if r.get("event") == "train_step"]
        assert len(steps) == 3
        for r in steps:
            assert {"step", "step_time_s", "tokens_per_s", "grad_norm",
                    "loss", "anomalies"} <= set(r)
        anomalous = [r for r in steps if r.get("anomaly")]
        assert len(anomalous) == 1
        assert anomalous[0]["anomaly"] == "nonfinite"
        assert steps[-1]["anomalies"] == 1

        # Prometheus snapshot exposes the series
        prom = reg.prometheus()
        for series in ("train_step_time_seconds", "train_tokens_per_s",
                       "train_grad_norm", "train_loss",
                       "train_steps_total"):
            assert series in prom
        assert 'train_anomalies_total{kind="nonfinite"} 1' in prom

    def test_loss_scale_series_with_scaler(self):
        scaler = LossScaler("dynamic", init_scale=8.0)
        guard, params, opt_state, tokens, targets = _tiny_gpt_guard(
            scaler=scaler)
        reg = MetricsRegistry()
        mon = TrainingMonitor(reg)
        h = {"p": params, "o": opt_state, "g": guard.init_state(),
             "s": scaler.init()}

        def step(tokens, targets):
            r = guard(h["p"], h["o"], h["g"], tokens, targets,
                      scaler_state=h["s"])
            h["p"], h["o"], h["g"], h["s"] = (r.params, r.opt_state,
                                              r.guard_state,
                                              r.scaler_state)
            return r

        monitored = mon.wrap(step)
        monitored(tokens, targets)
        assert reg.get("train_loss_scale").value() == 8.0
        rep = mon.report(guard=guard, scaler=scaler, scaler_state=h["s"])
        assert rep["scaler"]["loss_scale"] == 8.0
        assert rep["guard"]["steps"] == 1

    def test_plain_step_and_mfu(self):
        clock = iter([0.0, 0.5, 1.0, 1.5]).__next__
        mon = TrainingMonitor(tokens_per_step=100,
                              flops_per_token=1000.0, peak_flops=1e6,
                              clock=clock)

        monitored = mon.wrap(lambda: 2.5)   # plain step returning a loss
        assert monitored() == 2.5
        r = mon.registry
        assert r.get("train_step_time_s_last").value() == 0.5
        assert r.get("train_tokens_per_s").value() == 200.0
        # mfu = 200 tok/s * 1000 flops/tok / 1e6 peak
        assert r.get("train_mfu").value() == pytest.approx(0.2)
        assert r.get("train_loss").value() == 2.5

    def test_stream_path_opens_file(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        mon = TrainingMonitor(stream_path=path)
        mon.record(0.1)
        mon.close()
        reg, records = replay_jsonl(open(path))
        assert reg.get("train_steps_total").value() == 1
        assert any(r.get("event") == "train_step" for r in records)


# ---------------------------------------------------------------------------
# comms accounting
# ---------------------------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
class TestComms:
    def test_psum_bytes_hand_computed(self):
        mesh = jax.make_mesh((2,), ("tp",), devices=jax.devices()[:2])
        fn = shard_map_compat(lambda x: jax.lax.psum(x, "tp"),
                              mesh=mesh, in_specs=P("tp"), out_specs=P())
        st = collective_stats(fn, jnp.ones((8, 16), jnp.float32))
        # per-shard operand f32[4,16]: 4*16*4 payload bytes, one op
        assert st["all_reduce"]["count"] == 1
        assert st["all_reduce"]["bytes"] == 4 * 16 * 4
        assert st["total"]["count"] == 1
        assert st["all_reduce"]["ops"][0]["group_size"] == 2

    def test_all_gather_bytes(self):
        mesh = jax.make_mesh((2,), ("tp",), devices=jax.devices()[:2])
        fn = shard_map_compat(
            lambda x: jax.lax.all_gather(x, "tp", tiled=True),
            mesh=mesh, in_specs=P("tp"), out_specs=P())
        st = collective_stats(fn, jnp.ones((8, 16), jnp.float32))
        # gathered RESULT f32[8,16] is the payload
        assert st["all_gather"]["count"] == 1
        assert st["all_gather"]["bytes"] == 8 * 16 * 4

    def test_format_and_wire(self):
        mesh = jax.make_mesh((2,), ("tp",), devices=jax.devices()[:2])
        fn = shard_map_compat(lambda x: jax.lax.psum(x, "tp"),
                              mesh=mesh, in_specs=P("tp"), out_specs=P())
        st = collective_stats(fn, jnp.ones((8, 16), jnp.float32))
        table = format_stats(st)
        assert "all_reduce" in table and "total" in table
        # ring all-reduce over k=2: 2*(k-1)/k = 1.0x payload
        assert wire_bytes(st) == st["all_reduce"]["bytes"]


class TestHloParsing:
    def test_synthetic_hlo(self):
        text = """
  %ar = f32[4,16]{1,0} all-reduce(f32[4,16]{1,0} %dot), channel_id=1, replica_groups={{0,1}}
  %ag-start = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %x), replica_groups={{0,1}}
  %ag-done = f32[8]{0} all-gather-done((f32[4]{0}, f32[8]{0}) %ag-start)
"""
        st = hlo_collective_stats(text)
        assert st["all_reduce"]["count"] == 1
        assert st["all_reduce"]["bytes"] == 4 * 16 * 4
        # async pair counts once, on the start; payload = gathered result
        assert st["all_gather"]["count"] == 1
        assert st["all_gather"]["bytes"] == 8 * 4
        assert st["total"]["count"] == 2

    def test_bf16_width(self):
        st = hlo_collective_stats(
            "%r = bf16[8,8]{1,0} all-reduce(bf16[8,8]{1,0} %a), "
            "replica_groups={{0,1,2,3}}")
        assert st["all_reduce"]["bytes"] == 8 * 8 * 2
        assert st["all_reduce"]["ops"][0]["group_size"] == 4


# ---------------------------------------------------------------------------
# serving metrics migration (satellite 1) + profiling (satellite 2)
# ---------------------------------------------------------------------------

class TestServingMetrics:
    def _clock(self):
        t = [0.0]

        def clk():
            t[0] += 0.1
            return t[0]

        return clk

    def test_terminal_states_drop_transient_state(self):
        m = ServingMetrics(clock=self._clock())
        for rid, end in (("a", "finished"), ("b", "evicted"),
                         ("c", "error"), ("d", "timeout")):
            m.request_submitted(rid)
            m.first_token(rid)
            getattr(m, f"request_{end}"
                    if end != "finished" else "request_finished")(rid)
        # the leak fix: no per-request residue after terminal states
        assert m.pending_requests == 0
        assert m._last_token == {}
        assert m.evicted == 1 and m.errors == 1 and m.timeouts == 1
        c = m.registry.get("serving_finished_total")
        assert c.value(reason="done") == 1
        assert c.value(reason="evicted") == 1
        assert c.value(reason="error") == 1
        assert c.value(reason="timeout") == 1

    def test_summary_values_unchanged(self):
        """summary() still computes exact percentiles over raw samples —
        the registry mirror must not perturb the public values."""
        m = ServingMetrics(clock=self._clock())
        m.request_submitted("r")
        m.first_token("r")               # ttft = 0.1
        m.token("r")                     # latency = 0.1
        m.token("r")
        m.step(2, 4)
        s = m.summary()
        assert s["requests"] == 1 and s["tokens"] == 3
        assert s["ttft_p50_s"] == pytest.approx(0.1)
        assert s["token_latency_p50_s"] == pytest.approx(0.1)
        assert s["slot_occupancy_mean"] == pytest.approx(0.5)
        # and the registry saw the same samples
        assert m.registry.get("serving_tokens_total").value() == 3
        assert m.registry.get("serving_ttft_seconds").count() == 1
        assert m.registry.get(
            "serving_token_latency_seconds").count() == 2

    def test_shared_registry(self):
        reg = MetricsRegistry()
        m = ServingMetrics(clock=self._clock(), registry=reg)
        m.request_submitted("r")
        assert reg.get("serving_requests_total").value() == 1


class TestProfilingSatellites:
    def test_range_pop_warns_once_on_empty_stack(self):
        profiling._POP_MISMATCH_WARNED = False
        try:
            with pytest.warns(RuntimeWarning, match="no matching"):
                profiling.range_pop()
            import warnings as _w
            with _w.catch_warnings():
                _w.simplefilter("error")     # second pop must NOT warn
                profiling.range_pop()
        finally:
            profiling._POP_MISMATCH_WARNED = False

    def test_range_depth_balanced(self):
        assert profiling.range_depth() == 0
        profiling.range_push("a")
        profiling.range_push("b")
        assert profiling.range_depth() == 2
        profiling.range_pop()
        profiling.range_pop()
        assert profiling.range_depth() == 0


# ---------------------------------------------------------------------------
# exports
# ---------------------------------------------------------------------------

def test_public_exports():
    import apex_tpu

    obs = apex_tpu.observability
    for name in ("MetricsRegistry", "Counter", "Gauge", "Histogram",
                 "replay_jsonl", "Tracer", "default_tracer", "span",
                 "TrainingMonitor", "calibrated_peak_flops",
                 "collective_stats", "hlo_collective_stats",
                 "wire_bytes", "format_stats",
                 "CostModel", "Measurement", "fit_cost_model",
                 "load_profile", "probe_collectives",
                 "RequestRecord", "RequestTracer",
                 "BurnWindow", "RollingPercentiles",
                 "SLOMonitor", "SLOTarget"):
        assert hasattr(obs, name), name
    assert isinstance(obs.MetricsRegistry().counter("x_total"), Counter)
    assert isinstance(obs.MetricsRegistry().gauge("g"), Gauge)
    assert isinstance(obs.MetricsRegistry().histogram("h"), Histogram)


# ---------------------------------------------------------------------------
# Prometheus exporter edge cases (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

class TestPrometheusEdgeCases:
    def test_empty_registry_renders_empty(self):
        assert MetricsRegistry().prometheus() == ""

    def test_label_value_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("esc_total", "esc", labelnames=("v",))
        c.inc(v='say "hi"')
        c.inc(v="back\\slash")
        c.inc(v="two\nlines")
        prom = reg.prometheus()
        assert r'esc_total{v="say \"hi\""} 1' in prom
        assert r'esc_total{v="back\\slash"} 1' in prom
        assert r'esc_total{v="two\nlines"} 1' in prom
        assert "\nlines" not in prom.replace("\\nlines", "")

    def test_no_help_omits_help_line(self):
        reg = MetricsRegistry()
        reg.gauge("bare").set(1)
        prom = reg.prometheus()
        assert "# HELP" not in prom and "# TYPE bare gauge" in prom

    def test_labeled_histogram_rendering(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", "lat", labelnames=("op",),
                          buckets=(0.25, 0.5))
        for v in (0.1, 0.3, 9.0):
            h.observe(v, op="read")
        h.observe(0.4, op="write")
        prom = reg.prometheus()
        # per-label-set cumulative buckets, le last inside the braces
        assert 'lat_seconds_bucket{op="read",le="0.25"} 1' in prom
        assert 'lat_seconds_bucket{op="read",le="0.5"} 2' in prom
        assert 'lat_seconds_bucket{op="read",le="+Inf"} 3' in prom
        assert 'lat_seconds_bucket{op="write",le="+Inf"} 1' in prom
        assert 'lat_seconds_sum{op="read"} 9.4' in prom
        assert 'lat_seconds_count{op="read"} 3' in prom
        assert 'lat_seconds_count{op="write"} 1' in prom

    def test_inf_and_int_value_formatting(self):
        reg = MetricsRegistry()
        reg.gauge("pos").set(float("inf"))
        reg.gauge("neg").set(float("-inf"))
        reg.gauge("whole").set(3.0)
        prom = reg.prometheus()
        assert "pos +Inf" in prom and "neg -Inf" in prom
        assert "whole 3\n" in prom            # 3.0 renders as 3


class TestHistogramPercentile:
    def test_interpolated_within_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.6, 3.0):
            h.observe(v)
        # rank 2 of 4 lands at the top of the (1,2] bucket's first half
        assert 0.0 < h.percentile(0.25) <= 1.0
        assert 1.0 < h.percentile(0.5) <= 2.0
        assert 2.0 < h.percentile(1.0) <= 4.0

    def test_empty_and_overflow(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", buckets=(1.0, 2.0))
        assert h.percentile(0.5) == 0.0
        h.observe(100.0)                      # overflow bucket
        assert h.percentile(0.99) == 2.0      # saturates at top boundary

    def test_labeled(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", labelnames=("k",), buckets=(1.0, 2.0))
        h.observe(0.5, k="a")
        h.observe(1.5, k="b")
        assert h.percentile(1.0, k="a") <= 1.0
        assert h.percentile(1.0, k="b") > 1.0
        with pytest.raises(ValueError):
            h.percentile(0.5)                 # missing label


# ---------------------------------------------------------------------------
# Tracer exception-path nesting (ISSUE 7 satellite)
# ---------------------------------------------------------------------------

class TestTracerExceptionPath:
    def test_span_closes_and_flags_on_raise(self):
        t = [0.0]

        def clk():
            t[0] += 1.0
            return t[0]

        tr = Tracer(clock=clk)
        with pytest.raises(RuntimeError, match="boom"):
            with tr.span("work"):
                raise RuntimeError("boom")
        assert tr.depth() == 0                # stack popped
        (ev,) = tr.events
        assert ev["name"] == "work" and ev["dur"] == pytest.approx(1e6)
        assert ev["args"]["error"] == "RuntimeError"
        json.loads(tr.to_json())              # still valid Chrome JSON

    def test_inner_exception_does_not_flag_outer(self):
        tr = Tracer()
        with tr.span("outer"):
            try:
                with tr.span("inner"):
                    raise ValueError("x")
            except ValueError:
                pass
        inner, outer = tr.events              # inner closes first
        assert inner["name"] == "inner"
        assert inner["args"]["error"] == "ValueError"
        assert outer["name"] == "outer"
        assert "error" not in outer.get("args", {})
        assert tr.depth() == 0

    def test_nesting_survives_exception_for_next_span(self):
        tr = Tracer()
        try:
            with tr.span("a"):
                raise KeyError("k")
        except KeyError:
            pass
        with tr.span("b"):
            pass
        names = [e["name"] for e in tr.events]
        assert names == ["a", "b"]
        assert all(e.get("args", {}).get("depth", 1) == 1
                   for e in tr.events)

    def test_async_span_event_shape(self):
        tr = Tracer(clock=lambda: 0.0)
        tr.async_span("request", 7, ts=1.0, dur=0.5, reason="eos")
        tr.async_instant("tick", 7, ts=1.2)
        b, e, n = tr.events
        assert (b["ph"], e["ph"], n["ph"]) == ("b", "e", "n")
        # async ids are namespaced by the tracer's replica tag so two
        # replicas' id counters never collide in a merged trace
        assert b["id"] == e["id"] == n["id"] == f"{tr.id_tag}/7"
        assert b["cat"] == "request" and b["ts"] == pytest.approx(1e6)
        assert e["ts"] == pytest.approx(1.5e6)
        assert b["args"] == {"reason": "eos"}
        json.loads(tr.to_json())

    def test_async_ids_unique_across_tracers(self):
        a, b = Tracer(clock=lambda: 0.0), Tracer(clock=lambda: 0.0)
        a.async_span("request", 7, ts=0.0, dur=1.0)
        b.async_span("request", 7, ts=0.0, dur=1.0)
        ids_a = {e["id"] for e in a.events}
        ids_b = {e["id"] for e in b.events}
        assert not ids_a & ids_b

    def test_flow_events(self):
        tr = Tracer(clock=lambda: 3.0)
        s = tr.flow("s", "req:1", phase="dispatch")
        t = tr.flow("t", "req:1", 4.0, phase="admit")
        f = tr.flow("f", "req:1", phase="finish")
        assert [e["ph"] for e in tr.events] == ["s", "t", "f"]
        # flow ids are NOT tag-prefixed: they must match across
        # replicas — that is how migrated fragments stitch
        assert all(e["id"] == "req:1" for e in (s, t, f))
        assert all(e["cat"] == Tracer.FLOW_CAT for e in (s, t, f))
        assert all(e["name"] == Tracer.FLOW_NAME for e in (s, t, f))
        assert t["ts"] == pytest.approx(4e6)
        assert s["ts"] == f["ts"] == pytest.approx(3e6)
        assert f["bp"] == "e"
        with pytest.raises(ValueError):
            tr.flow("x", "req:1")
