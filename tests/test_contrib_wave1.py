"""contrib wave 1 (focal_loss, index_mul_2d, group_norm, transducer,
sparsity, layer_norm surface) vs unfused/numpy references — the apex
``contrib/test/<pkg>`` pattern."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.focal_loss import FocalLoss, focal_loss
from apex_tpu.contrib.group_norm import GroupNorm, group_norm_nhwc
from apex_tpu.contrib.index_mul_2d import index_mul_2d
from apex_tpu.contrib.layer_norm import FastLayerNorm
from apex_tpu.contrib.sparsity import ASP, create_mask
from apex_tpu.contrib.transducer import (
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
)


class TestFocalLoss:
    def test_matches_manual_reference(self, rng):
        logits = jnp.asarray(rng.randn(6, 5).astype(np.float32))
        targets = jnp.asarray([0, 2, -1, 4, -1, 1])
        alpha, gamma = 0.25, 2.0
        out = focal_loss(logits, targets, num_positives_sum=4.0,
                         alpha=alpha, gamma=gamma)
        # manual per-element sigmoid focal loss
        onehot = np.zeros((6, 5), np.float32)
        for i, t in enumerate([0, 2, -1, 4, -1, 1]):
            if t >= 0:
                onehot[i, t] = 1.0
        x = np.asarray(logits)
        p = 1.0 / (1.0 + np.exp(-x))
        bce = np.maximum(x, 0) - x * onehot + np.log1p(np.exp(-np.abs(x)))
        p_t = p * onehot + (1 - p) * (1 - onehot)
        a_t = alpha * onehot + (1 - alpha) * (1 - onehot)
        ref = (a_t * (1 - p_t) ** gamma * bce).sum() / 4.0
        np.testing.assert_allclose(float(out), ref, rtol=1e-5)

    def test_ignore_and_padded_classes(self, rng):
        logits = jnp.asarray(rng.randn(4, 8).astype(np.float32))
        targets = jnp.asarray([1, -2, 3, -2])
        full = focal_loss(logits, targets, 2.0, num_real_classes=6)
        # ignored rows contribute nothing: zeroing them changes nothing
        logits2 = logits.at[1].set(100.0).at[3].set(-100.0)
        again = focal_loss(logits2, targets, 2.0, num_real_classes=6)
        np.testing.assert_allclose(float(full), float(again), rtol=1e-6)

    def test_apply_wrapper_and_grad(self, rng):
        logits = jnp.asarray(rng.randn(4, 5).astype(np.float32))
        targets = jnp.asarray([0, 1, 2, -1])
        v = FocalLoss.apply(logits, targets, 3.0, 5, 0.25, 2.0)
        g = jax.grad(lambda x: focal_loss(x, targets, 3.0))(logits)
        assert np.isfinite(float(v))
        assert np.all(np.isfinite(g))


class TestIndexMul2d:
    def test_matches_reference(self, rng):
        in1 = jnp.asarray(rng.randn(10, 7).astype(np.float32))
        in2 = jnp.asarray(rng.randn(4, 7).astype(np.float32))
        idx = jnp.asarray([3, 0, 9, 3])
        out = index_mul_2d(in1, in2, idx)
        np.testing.assert_allclose(out, np.asarray(in1)[[3, 0, 9, 3]]
                                   * np.asarray(in2), rtol=1e-6)

    def test_grad_scatter_adds_duplicates(self, rng):
        in1 = jnp.asarray(rng.randn(5, 3).astype(np.float32))
        in2 = jnp.asarray(rng.randn(2, 3).astype(np.float32))
        idx = jnp.asarray([1, 1])  # duplicate row: grads must accumulate
        g = jax.grad(lambda a: jnp.sum(index_mul_2d(a, in2, idx)))(in1)
        np.testing.assert_allclose(np.asarray(g)[1],
                                   np.asarray(in2).sum(0), rtol=1e-6)
        assert np.all(np.asarray(g)[[0, 2, 3, 4]] == 0)


class TestGroupNorm:
    def test_matches_reference(self, rng):
        x = jnp.asarray(rng.randn(2, 4, 4, 8).astype(np.float32))
        m = GroupNorm(num_groups=4, num_channels=8)
        params = m.init_params()
        out = m(params, x)
        xr = np.asarray(x).reshape(2, 16, 4, 2)
        mean = xr.mean(axis=(1, 3), keepdims=True)
        var = xr.var(axis=(1, 3), keepdims=True)
        ref = ((xr - mean) / np.sqrt(var + 1e-5)).reshape(2, 4, 4, 8)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_swish_and_affine(self, rng):
        x = jnp.asarray(rng.randn(2, 3, 3, 8).astype(np.float32))
        m = GroupNorm(2, 8, act="swish")
        params = {"weight": jnp.asarray(rng.rand(8).astype(np.float32)),
                  "bias": jnp.asarray(rng.randn(8).astype(np.float32))}
        out = m(params, x)
        plain = group_norm_nhwc(x, 2, params["weight"], params["bias"])
        ref = np.asarray(plain) / (1 + np.exp(-np.asarray(plain)))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_bf16_io(self, rng):
        x = jnp.asarray(rng.randn(1, 4, 4, 16), jnp.bfloat16)
        m = GroupNorm(4, 16)
        out = m(m.init_params(), x)
        assert out.dtype == jnp.bfloat16


class TestTransducer:
    def _numpy_rnnt_loss(self, x, label, t_len, u_len, blank=0):
        """Textbook O(T·U) DP in numpy."""
        T, U1, V = x.shape
        alpha = np.full((t_len, u_len + 1), -np.inf)
        alpha[0, 0] = 0.0
        for t in range(t_len):
            for u in range(u_len + 1):
                if t == 0 and u == 0:
                    continue
                cands = []
                if t > 0:
                    cands.append(alpha[t - 1, u] + x[t - 1, u, blank])
                if u > 0:
                    cands.append(alpha[t, u - 1]
                                 + x[t, u - 1, label[u - 1]])
                alpha[t, u] = np.logaddexp.reduce(cands)
        return -(alpha[t_len - 1, u_len] + x[t_len - 1, u_len, blank])

    def test_loss_matches_numpy_dp(self, rng):
        B, T, U, V = 3, 7, 4, 6
        x = jax.nn.log_softmax(
            jnp.asarray(rng.randn(B, T, U + 1, V).astype(np.float32)),
            axis=-1)
        label = jnp.asarray(rng.randint(1, V, (B, U)))
        f_len = jnp.asarray([7, 5, 6])
        y_len = jnp.asarray([4, 2, 3])
        out = transducer_loss(x, label, f_len, y_len, blank_idx=0)
        for b in range(B):
            ref = self._numpy_rnnt_loss(np.asarray(x[b]),
                                        np.asarray(label[b]),
                                        int(f_len[b]), int(y_len[b]))
            np.testing.assert_allclose(float(out[b]), ref, rtol=1e-4)

    def test_loss_grad_finite(self, rng):
        B, T, U, V = 2, 5, 3, 4
        raw = jnp.asarray(rng.randn(B, T, U + 1, V).astype(np.float32))
        label = jnp.asarray(rng.randint(1, V, (B, U)))
        f_len = jnp.asarray([5, 4])
        y_len = jnp.asarray([3, 2])

        def loss(raw):
            x = jax.nn.log_softmax(raw, axis=-1)
            return jnp.sum(transducer_loss(x, label, f_len, y_len))

        g = jax.jit(jax.grad(loss))(raw)
        assert np.all(np.isfinite(g))
        # grads beyond f_len must be zero (frozen lattice rows)
        np.testing.assert_allclose(np.asarray(g)[1, 4], 0.0, atol=1e-6)

    def test_joint_dense_and_relu(self, rng):
        f = jnp.asarray(rng.randn(2, 5, 8).astype(np.float32))
        g = jnp.asarray(rng.randn(2, 3, 8).astype(np.float32))
        joint = TransducerJoint(relu=True)
        out = joint(f, g)
        ref = np.maximum(np.asarray(f)[:, :, None, :]
                         + np.asarray(g)[:, None, :, :], 0)
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_joint_packed(self, rng):
        f = jnp.asarray(rng.randn(2, 4, 6).astype(np.float32))
        g = jnp.asarray(rng.randn(2, 3, 6).astype(np.float32))
        f_len = jnp.asarray([3, 4])
        g_len = jnp.asarray([2, 3])
        sizes = [3 * 2, 4 * 3]
        offsets = jnp.asarray([0, sizes[0]])
        total = sum(sizes)
        out = transducer_joint(f, g, f_len, g_len, pack_output=True,
                               batch_offsets=offsets, packed_batch=total)
        dense = np.asarray(f)[:, :, None, :] + np.asarray(g)[:, None, :, :]
        pos = 0
        for b in range(2):
            for t in range(int(f_len[b])):
                for u in range(int(g_len[b])):
                    np.testing.assert_allclose(out[pos], dense[b, t, u],
                                               rtol=1e-6)
                    pos += 1

    def test_loss_module_surface(self, rng):
        x = jax.nn.log_softmax(
            jnp.asarray(rng.randn(1, 4, 3, 5).astype(np.float32)), -1)
        loss = TransducerLoss()(x, jnp.asarray([[1, 2]]),
                                jnp.asarray([4]), jnp.asarray([2]))
        assert loss.shape == (1,)


class TestASP:
    def test_mask_pattern_2_of_4(self, rng):
        w = jnp.asarray(rng.randn(32, 64).astype(np.float32))
        mask = create_mask(w)
        m = np.asarray(mask).reshape(32, 16, 4)
        assert (m.sum(-1) == 2).all()
        # kept entries are the 2 largest magnitudes per group
        mag = np.abs(np.asarray(w)).reshape(32, 16, 4)
        kept_min = np.where(m, mag, np.inf).min(-1)
        dropped_max = np.where(~m, mag, -np.inf).max(-1)
        assert (kept_min >= dropped_max).all()

    def test_compute_and_apply_masks(self, rng):
        params = {"w": jnp.asarray(rng.randn(64, 64).astype(np.float32)),
                  "b": jnp.asarray(rng.randn(64).astype(np.float32))}
        asp = ASP()
        masks = asp.compute_sparse_masks(params)
        sparse = asp.apply_masks(params, masks)
        assert float(jnp.mean(sparse["w"] == 0)) == 0.5
        np.testing.assert_array_equal(np.asarray(sparse["b"]),
                                      np.asarray(params["b"]))  # not pruned

    def test_wrapped_step_remasks(self, rng):
        from apex_tpu.optimizers import FusedSGD

        params = {"w": jnp.asarray(rng.randn(32, 32).astype(np.float32))}
        asp = ASP()
        masks = asp.compute_sparse_masks(params)
        params = asp.apply_masks(params, masks)
        opt = FusedSGD(lr=0.1, block_rows=8)
        state = opt.init(params)
        step = asp.wrap_optimizer_step(opt.step, masks)
        grads = {"w": jnp.asarray(rng.randn(32, 32).astype(np.float32))}
        new_params, _ = step(grads, params, state)
        m = np.asarray(masks["w"])
        assert (np.asarray(new_params["w"])[~m] == 0).all()
        assert (np.asarray(new_params["w"])[m] != 0).any()


class TestGroupBN:
    def test_train_matches_reference_and_running_stats(self, rng):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        x = jnp.asarray(rng.randn(4, 3, 3, 8).astype(np.float32))
        m = BatchNorm2d_NHWC(8, momentum=0.8)
        params, state = m.init_params(), m.init_state()
        y, new_state = m(params, state, x, training=True)
        xn = np.asarray(x)
        mean = xn.mean(axis=(0, 1, 2))
        var = xn.var(axis=(0, 1, 2))
        ref = (xn - mean) / np.sqrt(var + 1e-5)
        np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)
        n = xn.size // 8
        np.testing.assert_allclose(np.asarray(new_state["running_var"]),
                                   0.8 * 1.0 + 0.2 * var * n / (n - 1),
                                   rtol=1e-4)

    def test_fused_addrelu(self, rng):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        x = jnp.asarray(rng.randn(2, 3, 3, 4).astype(np.float32))
        z = jnp.asarray(rng.randn(2, 3, 3, 4).astype(np.float32))
        m = BatchNorm2d_NHWC(4)
        params, state = m.init_params(), m.init_state()
        y, _ = m(params, state, x, z=z, training=True)
        y_plain, _ = m(params, state, x, training=True)
        ref = np.maximum(np.asarray(y_plain) + np.asarray(z), 0)
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)

    def test_eval_uses_running_stats(self, rng):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC

        x = jnp.asarray(rng.randn(2, 2, 2, 4).astype(np.float32))
        m = BatchNorm2d_NHWC(4)
        params = m.init_params()
        state = {"running_mean": jnp.asarray([1.0, 0, 0, 0]),
                 "running_var": jnp.full((4,), 2.0)}
        y, same = m(params, state, x, training=False)
        ref = (np.asarray(x) - np.asarray([1.0, 0, 0, 0])) / np.sqrt(
            2.0 + 1e-5)
        np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)
        assert same is state

    def test_sync_over_mesh_axis(self, rng):
        from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
        from jax.sharding import PartitionSpec as P

        mesh = jax.make_mesh((8,), ("data",))
        x = jnp.asarray(rng.randn(8, 2, 2, 4).astype(np.float32))
        m = BatchNorm2d_NHWC(4, axis_name="data")
        params, state = m.init_params(), m.init_state()

        def f(x):
            y, st = m(params, state, x, training=True)
            return y, st["running_mean"]

        y, rmean = jax.shard_map(
            f, mesh=mesh, in_specs=(P("data"),),
            out_specs=(P("data"), P()), check_vma=False)(x)
        # stats over the GLOBAL batch == serial reference
        m_serial = BatchNorm2d_NHWC(4)
        y_ref, st_ref = m_serial(params, state, x, training=True)
        np.testing.assert_allclose(y, y_ref, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(rmean,
                                   np.asarray(st_ref["running_mean"]),
                                   rtol=1e-5, atol=1e-6)


class TestFastLayerNorm:
    def test_surface(self, rng):
        m = FastLayerNorm(64)
        params = m.init_params()
        x = jnp.asarray(rng.randn(8, 64).astype(np.float32))
        out = m(params, x)
        ref = (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
            x.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestPermutationSearch:
    """ASP channel-permutation search (reference permutation_lib.py)."""

    def test_improves_retained_magnitude(self):
        import numpy as np
        from apex_tpu.contrib.sparsity import (
            apply_input_permutation, invert_permutation,
            magnitude_retained, permutation_search)

        rng = np.random.RandomState(0)
        w = rng.randn(32, 64).astype(np.float32)
        base = magnitude_retained(w)
        perm, improved = permutation_search(w, max_passes=4)
        assert sorted(perm.tolist()) == list(range(64))   # valid perm
        assert improved >= base - 1e-9
        wp = np.asarray(apply_input_permutation(w, perm))
        assert abs(magnitude_retained(wp) - improved) < 1e-6
        inv = invert_permutation(perm)
        np.testing.assert_array_equal(wp[:, inv], w)

    def test_indivisible_raises(self):
        import numpy as np
        from apex_tpu.contrib.sparsity import permutation_search
        with pytest.raises(ValueError):
            permutation_search(np.ones((4, 6), np.float32))
