"""Multi-tensor engine tests.

Pattern copied from apex L0 (``tests/L0/run_optimizers``): every fused op is
checked against an unfused reference implementation on the same inputs, and
the Pallas path is additionally checked against the jnp fallback in
interpret mode on small shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import (
    bucket_meta, flatten_bucket, unflatten_bucket, row_tensor_ids,
    multi_tensor_scale, multi_tensor_axpby, multi_tensor_l2norm,
)
from apex_tpu.ops import multi_tensor as K
from apex_tpu.utils import set_force_pallas

SHAPES = [(3, 5), (130,), (2, 3, 7), (1,), (257,)]


def make_tensors(rng, shapes=SHAPES, dtype=np.float32, scale=1.0):
    return [jnp.asarray(rng.randn(*s).astype(dtype) * scale) for s in shapes]


class TestBucketing:
    def test_roundtrip(self, rng):
        ts = make_tensors(rng)
        meta = bucket_meta(tuple(t.shape for t in ts), jnp.float32,
                           block_rows=8)
        packed = flatten_bucket(ts, meta)
        assert packed.shape[1] == 128
        assert packed.shape[0] % 8 == 0
        out = unflatten_bucket(packed, meta)
        for a, b in zip(ts, out):
            np.testing.assert_array_equal(a, b)

    def test_row_ids_cover_tensors(self):
        meta = bucket_meta(((256,), (100,), (400,)), jnp.float32,
                           block_rows=8)
        ids = row_tensor_ids(meta)
        assert ids.shape == (meta.nrows,)
        # 256 -> 2 rows of id 0; 100 -> 1 row id 1; 400 -> 4 rows id 2
        assert list(ids[:7]) == [0, 0, 1, 2, 2, 2, 2]

    def test_padding_is_zero(self, rng):
        ts = make_tensors(rng, [(100,)])
        meta = bucket_meta(((100,),), jnp.float32, block_rows=8)
        packed = np.asarray(flatten_bucket(ts, meta))
        assert np.all(packed.reshape(-1)[100:] == 0)


class TestScaleAxpbyL2norm:
    def test_scale(self, rng):
        ts = make_tensors(rng)
        outs, finf = jax.jit(lambda t: multi_tensor_scale(t, 0.5))(ts)
        for a, b in zip(ts, outs):
            np.testing.assert_allclose(np.asarray(a) * 0.5, b, rtol=1e-6)
        assert float(finf) == 0.0

    def test_scale_detects_inf_and_nan(self, rng):
        for bad in (np.inf, np.nan):
            ts = make_tensors(rng)
            ts[2] = ts[2].at[0, 0, 0].set(bad)
            _, finf = multi_tensor_scale(ts, 1.0)
            assert float(finf) == 1.0

    def test_scale_out_dtype(self, rng):
        ts = make_tensors(rng, dtype=np.float32)
        outs, _ = multi_tensor_scale(ts, 2.0, out_dtype=jnp.bfloat16)
        assert all(o.dtype == jnp.bfloat16 for o in outs)

    def test_scale_mixed_dtypes(self, rng):
        ts = make_tensors(rng)[:2] + [
            jnp.asarray(rng.randn(64).astype(np.float16))]
        outs, finf = multi_tensor_scale(ts, 3.0)
        assert outs[2].dtype == jnp.float16
        np.testing.assert_allclose(np.asarray(ts[0]) * 3.0, outs[0],
                                   rtol=1e-6)

    def test_axpby(self, rng):
        xs = make_tensors(rng)
        ys = make_tensors(rng)
        outs, finf = multi_tensor_axpby(2.0, xs, -1.0, ys)
        for x, y, o in zip(xs, ys, outs):
            np.testing.assert_allclose(2 * np.asarray(x) - np.asarray(y),
                                       o, rtol=1e-5)

    def test_l2norm(self, rng):
        ts = make_tensors(rng)
        norm, per, finf = multi_tensor_l2norm(ts, per_tensor=True)
        ref = np.sqrt(sum(float(jnp.sum(t.astype(jnp.float32) ** 2))
                          for t in ts))
        np.testing.assert_allclose(float(norm), ref, rtol=1e-5)
        for t, n in zip(ts, per):
            np.testing.assert_allclose(
                float(jnp.linalg.norm(t.astype(jnp.float32))), float(n),
                rtol=1e-5)
        assert float(finf) == 0.0


def _packed(rng, n=1024, block_rows=8, dtype=np.float32):
    return jnp.asarray(rng.randn(n // 128, 128).astype(dtype))


class TestPackedOptimizerKernels:
    """Fallback-path numerics for the packed optimizer update rules."""

    def test_adam_matches_loop(self, rng):
        g, p = _packed(rng), _packed(rng)
        m = jnp.zeros_like(p)
        v = jnp.zeros_like(p)
        lr, b1, b2, eps, wd = 1e-3, 0.9, 0.999, 1e-8, 0.01
        pp, mm, vv = np.asarray(p), np.zeros_like(p), np.zeros_like(p)
        for t in range(1, 4):
            p, m, v = K.adam_packed(
                g, p, m, v, lr=lr, beta1=b1, beta2=b2, eps=eps,
                weight_decay=wd, bias_correction1=1 - b1 ** t,
                bias_correction2=1 - b2 ** t, adam_w_mode=True, block_rows=8)
            gg = np.asarray(g)
            mm = b1 * mm + (1 - b1) * gg
            vv = b2 * vv + (1 - b2) * gg * gg
            upd = (mm / (1 - b1 ** t)) / (np.sqrt(vv / (1 - b2 ** t)) + eps)
            pp = pp - lr * (upd + wd * pp)
            np.testing.assert_allclose(np.asarray(p), pp, rtol=2e-5,
                                       atol=1e-6)

    def test_adam_l2_mode(self, rng):
        g, p = _packed(rng), _packed(rng)
        m = v = jnp.zeros_like(p)
        p1, m1, v1 = K.adam_packed(
            g, p, m, v, lr=1e-2, beta1=0.9, beta2=0.99, eps=1e-8,
            weight_decay=0.1, bias_correction1=1.0, bias_correction2=1.0,
            adam_w_mode=False, block_rows=8)
        gg = np.asarray(g) + 0.1 * np.asarray(p)
        mm = 0.1 * gg
        vv = 0.01 * gg * gg
        ref = np.asarray(p) - 1e-2 * mm / (np.sqrt(vv) + 1e-8)
        np.testing.assert_allclose(np.asarray(p1), ref, rtol=2e-5, atol=1e-6)

    def test_adam_noop_skips(self, rng):
        g, p = _packed(rng), _packed(rng)
        m = v = jnp.zeros_like(p)
        p1, m1, v1 = K.adam_packed(
            g, p, m, v, lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
            weight_decay=0.0, bias_correction1=1.0, bias_correction2=1.0,
            noop_flag=jnp.ones((1,), jnp.int32), block_rows=8)
        np.testing.assert_array_equal(np.asarray(p1), np.asarray(p))
        np.testing.assert_array_equal(np.asarray(m1), np.asarray(m))

    def test_sgd_momentum_nesterov(self, rng):
        g, p = _packed(rng), _packed(rng)
        mom = jnp.zeros_like(p)
        lr, mu = 0.1, 0.9
        # first run: buf = g ; nesterov update = g + mu*buf
        p1, mom1 = K.sgd_packed(g, p, mom, lr=lr, weight_decay=0.0,
                                momentum=mu, dampening=0.0, nesterov=True,
                                first_run=True, block_rows=8)
        ref_buf = np.asarray(g)
        ref_p = np.asarray(p) - lr * (np.asarray(g) + mu * ref_buf)
        np.testing.assert_allclose(np.asarray(p1), ref_p, rtol=1e-6)
        p2, mom2 = K.sgd_packed(g, p1, mom1, lr=lr, weight_decay=0.0,
                                momentum=mu, dampening=0.0, nesterov=True,
                                first_run=False, block_rows=8)
        ref_buf2 = mu * ref_buf + np.asarray(g)
        ref_p2 = ref_p - lr * (np.asarray(g) + mu * ref_buf2)
        np.testing.assert_allclose(np.asarray(p2), ref_p2, rtol=1e-6)

    def test_adagrad(self, rng):
        g, p = _packed(rng), _packed(rng)
        h = jnp.zeros_like(p)
        p1, h1 = K.adagrad_packed(g, p, h, lr=0.1, eps=1e-10,
                                  weight_decay=0.0, block_rows=8)
        hh = np.asarray(g) ** 2
        ref = np.asarray(p) - 0.1 * np.asarray(g) / (np.sqrt(hh) + 1e-10)
        np.testing.assert_allclose(np.asarray(p1), ref, rtol=1e-5)


class TestPallasInterpretParity:
    """Pallas kernels (interpret mode on CPU) vs the jnp fallback."""

    @pytest.fixture(autouse=True)
    def _force(self):
        set_force_pallas(True)
        yield
        set_force_pallas(None)

    def test_scale_kernel(self, rng):
        x = _packed(rng)
        set_force_pallas(False)
        ref, ref_f = K.scale_packed(x, 0.25, block_rows=8)
        set_force_pallas(True)
        out, finf = K.scale_packed(x, 0.25, block_rows=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-6)
        assert float(finf) == float(ref_f)

    def test_adam_kernel(self, rng):
        g, p = _packed(rng), _packed(rng)
        m = jnp.abs(_packed(rng)) * 0.1
        v = jnp.abs(_packed(rng)) * 0.1
        kw = dict(lr=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
                  weight_decay=0.01, bias_correction1=0.5,
                  bias_correction2=0.3, block_rows=8)
        set_force_pallas(False)
        ref = K.adam_packed(g, p, m, v, **kw)
        set_force_pallas(True)
        out = K.adam_packed(g, p, m, v, **kw)
        for a, b in zip(out, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)

    def test_l2norm_kernel(self, rng):
        x = _packed(rng)
        set_force_pallas(False)
        ref, _ = K.l2norm_rowsq_packed(x, block_rows=8)
        set_force_pallas(True)
        out, finf = K.l2norm_rowsq_packed(x, block_rows=8)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5)
