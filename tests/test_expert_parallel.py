"""Expert-parallel MoE (beyond-reference; EP completes the
tp/pp/dp/sp/cp/ep axis set).  Parity: the EP=4 all_to_all dataflow must
equal the serial per-shard computation exactly, forward and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from apex_tpu.utils.collectives import shard_map_compat as shard_map
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer.expert_parallel import MoEConfig, MoEMLP


@pytest.fixture
def rng():
    return np.random.RandomState(0)


def serial_cfg(**kw):
    kw.setdefault("hidden_size", 16)
    kw.setdefault("ffn_hidden_size", 32)
    kw.setdefault("n_experts", 8)
    return MoEConfig(**kw)


class TestSerialMoE:
    def test_output_shape_and_aux(self, rng):
        m = MoEMLP(serial_cfg())
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(64, 16), jnp.float32)
        out, aux = jax.jit(m)(params, x)
        assert out.shape == x.shape
        assert float(aux) > 0.0

    def test_capacity_drops_tokens(self, rng):
        # capacity 1 per expert: at most n_experts tokens survive
        m = MoEMLP(serial_cfg(capacity_factor=8.0 / 64.0))
        params = m.init_params(jax.random.PRNGKey(1))
        x = jnp.asarray(rng.randn(64, 16), jnp.float32)
        out, _ = m(params, x)
        nonzero = np.sum(np.any(np.asarray(out) != 0.0, axis=-1))
        assert nonzero <= 8

    def test_matches_dense_reference_when_uncapped(self, rng):
        """With capacity >= tokens nothing is dropped: out ==
        gate_prob * FFN_{argmax expert}(x) for every token."""
        cfg = serial_cfg(capacity_factor=float(8))   # cap = tokens
        m = MoEMLP(cfg)
        params = m.init_params(jax.random.PRNGKey(2))
        x = jnp.asarray(rng.randn(32, 16), jnp.float32)
        out, _ = jax.jit(m)(params, x)

        logits = np.asarray(x @ params["gate"])
        probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), -1))
        idx = probs.argmax(-1)
        ref = np.zeros_like(np.asarray(x))
        for t in range(32):
            e = idx[t]
            h1 = np.maximum(np.asarray(x)[t] @ np.asarray(
                params["w1"])[e], 0.0)
            ref[t] = (h1 @ np.asarray(params["w2"])[e]) * probs[t, e]
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)


class TestExpertParallel:
    def _setup(self, rng, ep=4, tokens_per_dev=16):
        cfg_s = serial_cfg()
        serial = MoEMLP(cfg_s)
        params = serial.init_params(jax.random.PRNGKey(3))
        x = jnp.asarray(rng.randn(ep * tokens_per_dev, 16), jnp.float32)
        cfg_p = serial_cfg(expert_parallel_size=ep, axis_name="expert")
        par = MoEMLP(cfg_p)
        nl = cfg_p.local_experts
        # shard the expert stacks over the leading axis; gate replicated
        sharded = {"gate": params["gate"],
                   "w1": params["w1"].reshape(ep, nl, *params["w1"].shape[1:]),
                   "w2": params["w2"].reshape(ep, nl, *params["w2"].shape[1:])}
        specs = {"gate": P(), "w1": P("expert"), "w2": P("expert")}
        return serial, params, par, sharded, specs, x

    def test_forward_matches_serial_shards(self, rng):
        serial, params, par, sharded, specs, x = self._setup(rng)
        mesh = jax.make_mesh((4,), ("expert",))

        def local(p, xl):
            p = dict(p, w1=p["w1"][0], w2=p["w2"][0])
            out, aux = par(p, xl)
            return out, aux[None]          # per-device aux, stacked

        out, aux = jax.jit(shard_map(
            local, mesh=mesh, in_specs=(specs, P("expert")),
            out_specs=(P("expert"), P("expert"))))(sharded, x)

        # serial reference: same per-shard capacity semantics
        refs, auxes = [], []
        for s in range(4):
            o, a = serial(params, x[s * 16:(s + 1) * 16])
            refs.append(np.asarray(o))
            auxes.append(float(a))
        np.testing.assert_allclose(np.asarray(out),
                                   np.concatenate(refs), rtol=2e-5,
                                   atol=2e-5)
        np.testing.assert_allclose(np.asarray(aux), np.asarray(auxes),
                                   rtol=1e-5)

    def test_grads_match_serial_shards(self, rng):
        serial, params, par, sharded, specs, x = self._setup(rng)
        mesh = jax.make_mesh((4,), ("expert",))

        def ep_loss(p, xl):
            p = dict(p, w1=p["w1"][0], w2=p["w2"][0])
            out, aux = par(p, xl)
            loss = jnp.sum(out.astype(jnp.float32) ** 2)
            return jax.lax.psum(loss, "expert") + 0.01 * jax.lax.pmean(
                aux, "expert")

        def local(p, xl):
            # expert-stack grads are PER-SHARD (sharded params -> no
            # reduction); the replicated gate's grad is auto-psummed
            return jax.grad(ep_loss)(p, xl)

        grads = jax.jit(shard_map(
            local, mesh=mesh, in_specs=(specs, P("expert")),
            out_specs=specs))(sharded, x)

        def serial_loss(p):
            total = 0.0
            for s in range(4):
                out, aux = serial(p, x[s * 16:(s + 1) * 16])
                total = total + jnp.sum(out.astype(jnp.float32) ** 2) \
                    + 0.01 * aux / 4
            return total

        ref = jax.grad(serial_loss)(params)
        np.testing.assert_allclose(
            np.asarray(grads["gate"]), np.asarray(ref["gate"]),
            rtol=5e-4, atol=1e-5)
        for k in ("w1", "w2"):
            got = np.asarray(grads[k]).reshape(np.asarray(ref[k]).shape)
            np.testing.assert_allclose(got, np.asarray(ref[k]),
                                       rtol=5e-4, atol=1e-5)


class TestTopKRouting:
    """top_k=2 (GShard) routing: renormalized gates, second choices
    claim slots after all first choices."""

    def test_top2_uncapped_matches_dense(self, rng):
        cfg = serial_cfg(top_k=2, capacity_factor=float(8))
        m = MoEMLP(cfg)
        params = m.init_params(jax.random.PRNGKey(5))
        x = jnp.asarray(rng.randn(16, 16), jnp.float32)
        out, _ = jax.jit(m)(params, x)

        probs = np.asarray(jax.nn.softmax(
            jnp.asarray(np.asarray(x @ params["gate"])), -1))
        ref = np.zeros((16, 16), np.float32)
        for t in range(16):
            top2 = np.argsort(probs[t])[::-1][:2]
            norm = probs[t, top2].sum()
            for e in top2:
                h1 = np.maximum(np.asarray(x)[t] @ np.asarray(
                    params["w1"])[e], 0.0)
                ref[t] += (h1 @ np.asarray(params["w2"])[e]) \
                    * probs[t, e] / norm
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5,
                                   atol=2e-5)

    def test_top2_ep_matches_serial(self, rng):
        cfg_s = serial_cfg(top_k=2)
        serial = MoEMLP(cfg_s)
        params = serial.init_params(jax.random.PRNGKey(6))
        x = jnp.asarray(rng.randn(64, 16), jnp.float32)
        cfg_p = serial_cfg(top_k=2, expert_parallel_size=4,
                           axis_name="expert")
        par = MoEMLP(cfg_p)
        nl = cfg_p.local_experts
        sharded = {"gate": params["gate"],
                   "w1": params["w1"].reshape(4, nl, *params["w1"].shape[1:]),
                   "w2": params["w2"].reshape(4, nl, *params["w2"].shape[1:])}
        specs = {"gate": P(), "w1": P("expert"), "w2": P("expert")}
        mesh = jax.make_mesh((4,), ("expert",))

        def local(p, xl):
            p = dict(p, w1=p["w1"][0], w2=p["w2"][0])
            return par(p, xl)[0]

        out = jax.jit(shard_map(
            local, mesh=mesh, in_specs=(specs, P("expert")),
            out_specs=P("expert")))(sharded, x)
        refs = [np.asarray(serial(params, x[s * 16:(s + 1) * 16])[0])
                for s in range(4)]
        np.testing.assert_allclose(np.asarray(out),
                                   np.concatenate(refs), rtol=2e-5,
                                   atol=2e-5)

    def test_second_choice_capacity_after_first(self, rng):
        """capacity 1: each expert serves exactly the first token that
        claims it — a second choice lands only on experts no FIRST
        choice claimed (slot ordering, checked against a reference)."""
        m = MoEMLP(serial_cfg(top_k=2,
                              capacity_factor=8.0 / (2 * 64.0)))
        params = m.init_params(jax.random.PRNGKey(7))
        x = jnp.asarray(rng.randn(64, 16), jnp.float32)
        out, _ = m(params, x)

        probs = np.asarray(jax.nn.softmax(
            jnp.asarray(np.asarray(x @ params["gate"])), -1))
        order = np.argsort(probs, axis=-1)[:, ::-1]
        first, second = order[:, 0], order[:, 1]
        # reference slot assignment: first choices in token order, then
        # second choices in token order; capacity 1 per expert
        served = {}          # expert -> (token, choice_prob_weight)
        for t in range(64):
            if first[t] not in served:
                norm = probs[t, first[t]] + probs[t, second[t]]
                served[first[t]] = (t, 0, probs[t, first[t]] / norm)
        for t in range(64):
            if second[t] not in served:
                norm = probs[t, first[t]] + probs[t, second[t]]
                served[second[t]] = (t, 1, probs[t, second[t]] / norm)
        expected = {t for (t, _c, _w) in served.values()}
        got = set(np.where(np.any(np.asarray(out) != 0.0, axis=-1))[0])
        assert got == expected, (sorted(got), sorted(expected))

    def test_invalid_topk_raises(self):
        with pytest.raises(ValueError):
            serial_cfg(top_k=0)
        with pytest.raises(ValueError):
            serial_cfg(top_k=9)


class TestSwitchGPT:
    """MoE wired into the GPT flagship (cfg.n_experts > 0)."""

    def _cfg(self, **kw):
        from apex_tpu.models.gpt import GPTConfig
        kw.setdefault("vocab_size", 32)
        kw.setdefault("hidden_size", 16)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("max_seq_len", 16)
        kw.setdefault("n_experts", 4)
        return GPTConfig(**kw)

    def test_trains_and_aux_contributes(self, rng):
        from apex_tpu.models.gpt import GPTModel

        model = GPTModel(self._cfg())
        params = model.init_params(jax.random.PRNGKey(0))
        tokens = jnp.asarray(rng.randint(0, 32, (2, 16)))
        targets = jnp.asarray(rng.randint(0, 32, (2, 16)))
        loss = float(jax.jit(model.loss)(params, tokens, targets))
        assert np.isfinite(loss)

        # aux weight changes the loss (the MoE term is really in there)
        model0 = GPTModel(self._cfg(moe_aux_weight=0.0))
        loss0 = float(jax.jit(model0.loss)(params, tokens, targets))
        assert loss > loss0

        @jax.jit
        def step(params):
            l, g = jax.value_and_grad(model.loss)(params, tokens, targets)
            return l, jax.tree_util.tree_map(
                lambda p, gr: p - 0.1 * gr, params, g)

        losses = []
        for _ in range(6):
            l, params = step(params)
            losses.append(float(l))
        assert losses[-1] < losses[0], losses

    def test_gspmd_replicated_moe(self, rng):
        from jax.sharding import NamedSharding
        from apex_tpu.models.gpt import GPTModel

        model = GPTModel(self._cfg())
        params = model.init_params(jax.random.PRNGKey(1))
        tokens = jnp.asarray(rng.randint(0, 32, (2, 16)))
        ref = float(jax.jit(model.loss)(params, tokens, tokens))
        mesh = jax.make_mesh((2,), ("model",),
                             axis_types=(jax.sharding.AxisType.Auto,))
        specs = model.partition_specs()
        sharded = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        got = float(jax.jit(model.loss)(sharded, tokens, tokens))
        np.testing.assert_allclose(got, ref, rtol=1e-5)

    def test_moe_tp_divisibility_validated(self):
        with pytest.raises(ValueError,
                           match="MoE ffn_hidden_size must be divisible"):
            self._cfg(ffn_hidden_size=30, tensor_parallel_size=4,
                      axis_name="model")

    def test_ep_sharded_switch_gpt(self, rng):
        """GPT with experts sharded over an expert axis: tokens are
        per-device shards (the EP group doubles as DP), loss pmeans."""
        from apex_tpu.models.gpt import GPTModel

        ep = 4
        serial = GPTModel(self._cfg())
        params = serial.init_params(jax.random.PRNGKey(3))
        tokens = jnp.asarray(rng.randint(0, 32, (ep * 2, 16)))
        targets = jnp.asarray(rng.randint(0, 32, (ep * 2, 16)))
        # serial golden: per-shard losses averaged (same per-shard MoE
        # capacity semantics)
        refs = [float(jax.jit(serial.loss)(
            params, tokens[s * 2:(s + 1) * 2], targets[s * 2:(s + 1) * 2]))
            for s in range(ep)]

        par = GPTModel(self._cfg(expert_axis="expert",
                                 expert_parallel_size=ep))
        nl = 1
        def shard_moe(path, x):
            ks = jax.tree_util.keystr(path)
            if "mlp" in ks and ("w1" in ks or "w2" in ks):
                return x.reshape(ep, nl, *x.shape[1:])
            return x
        sharded = jax.tree_util.tree_map_with_path(shard_moe, params)
        def spec_moe(path, x):
            ks = jax.tree_util.keystr(path)
            if "mlp" in ks and ("w1" in ks or "w2" in ks):
                return P("expert")
            return P()
        specs = jax.tree_util.tree_map_with_path(spec_moe, params)
        mesh = jax.make_mesh((ep,), ("expert",))

        def local(p, tk, tg):
            def fix(path, x):
                ks = jax.tree_util.keystr(path)
                if "mlp" in ks and ("w1" in ks or "w2" in ks):
                    return x[0]
                return x
            p = jax.tree_util.tree_map_with_path(fix, p)
            return jax.lax.pmean(par.loss(p, tk, tg), "expert")

        loss = float(jax.jit(shard_map(
            local, mesh=mesh,
            in_specs=(specs, P("expert"), P("expert")),
            out_specs=P()))(sharded, tokens, targets))
        np.testing.assert_allclose(loss, np.mean(refs), rtol=1e-5)


class TestMoETensorParallel:
    """MoE x TP: each expert's FFN dim Column/Row-sharded over the
    tensor axis must match the serial full-width expert exactly."""

    def test_moe_tp_fwd_and_grads_match_serial(self, rng):
        serial = MoEMLP(serial_cfg(n_experts=4))
        params = serial.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(32, 16), jnp.float32)

        def serial_loss(p):
            out, aux = serial(p, x)
            return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux

        ref_loss = float(jax.jit(serial_loss)(params))
        ref_g = jax.jit(jax.grad(serial_loss))(params)

        tpn = 2
        par = MoEMLP(serial_cfg(n_experts=4, tensor_parallel_size=tpn,
                                tensor_axis="model"))
        fl = par.cfg.local_ffn
        sharded = {
            "gate": params["gate"],
            "w1": jnp.stack([params["w1"][:, :, r * fl:(r + 1) * fl]
                             for r in range(tpn)]),
            "w2": jnp.stack([params["w2"][:, r * fl:(r + 1) * fl, :]
                             for r in range(tpn)])}
        specs = {"gate": P(), "w1": P("model"), "w2": P("model")}
        mesh = jax.make_mesh((tpn,), ("model",))

        def grad_fn(p):
            def local_loss(p):
                p = dict(p, w1=p["w1"][0], w2=p["w2"][0])
                out, aux = par(p, x)
                return jnp.sum(out.astype(jnp.float32) ** 2) + 0.01 * aux
            return jax.value_and_grad(local_loss)(p)

        loss, g = jax.jit(shard_map(
            grad_fn, mesh=mesh, in_specs=(specs,),
            out_specs=(P(), specs)))(sharded)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g["gate"]),
                                   np.asarray(ref_g["gate"]),
                                   rtol=5e-4, atol=1e-5)
        for k, sl in (("w1", lambda a, r: a[:, :, r * fl:(r + 1) * fl]),
                      ("w2", lambda a, r: a[:, r * fl:(r + 1) * fl, :])):
            ref_sh = np.stack([sl(np.asarray(ref_g[k]), r)
                               for r in range(tpn)])
            np.testing.assert_allclose(np.asarray(g[k]), ref_sh,
                                       rtol=5e-4, atol=1e-5)


def _per_microbatch_golden(model, params, tokens, targets, mb):
    """Serial golden for sharded-batch MoE runs: mean of per-microbatch
    losses (MoE capacity is a per-dispatch-group statistic, so each
    device-microbatch is computed independently)."""
    n = tokens.shape[0] // mb

    def loss(p):
        losses = [model.loss(p, tokens[i * mb:(i + 1) * mb],
                             targets[i * mb:(i + 1) * mb])
                  for i in range(n)]
        return jnp.mean(jnp.stack(losses))

    return loss


def _assert_grad_tree_allclose(grads, ref):
    for (path, g), (_, r) in zip(
            jax.tree_util.tree_flatten_with_path(grads)[0],
            jax.tree_util.tree_flatten_with_path(ref)[0], strict=True):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), rtol=5e-4, atol=1e-5,
            err_msg=jax.tree_util.keystr(path))


class TestMoEComposition:
    """The round-4 axis-product lanes: MoE composes with TP and with the
    SPMD pipeline (and all three at once) with exact loss+grad parity
    against the per-microbatch serial golden."""

    def _models(self, n_experts=2, num_layers=2, **par_kw):
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        kw = dict(vocab_size=32, hidden_size=16, num_layers=num_layers,
                  num_attention_heads=4, max_seq_len=16,
                  n_experts=n_experts)
        return GPTModel(GPTConfig(**kw)), GPTModel(GPTConfig(**kw,
                                                             **par_kw))

    def test_ep_tp_switch_gpt_grad_parity(self, rng):
        from apex_tpu.models.gpt import pack_for_shard_map
        from apex_tpu.transformer.expert_parallel import (
            vary_params_over_axis)

        ep, tpn = 2, 2
        serial, par = self._models(
            n_experts=4, tensor_parallel_size=tpn, axis_name="model",
            expert_axis="expert", expert_parallel_size=ep)
        params = serial.init_params(jax.random.PRNGKey(0))
        tokens = jnp.asarray(rng.randint(0, 32, (ep * 2, 16)))
        targets = jnp.asarray(rng.randint(0, 32, (ep * 2, 16)))
        golden = _per_microbatch_golden(serial, params, tokens, targets, 2)
        ref_loss = float(jax.jit(golden)(params))
        ref_g = jax.jit(jax.grad(golden))(params)

        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            par, params, tensor_axis="model", expert_axis="expert")
        mesh = jax.make_mesh((ep, tpn), ("expert", "model"))

        def grad_fn(sp, tk, tg):
            def loss_fn(p):
                p = vary_params_over_axis(p, "expert")
                return jax.lax.pmean(par.loss(p, tk, tg), "expert")
            loss, g = jax.value_and_grad(loss_fn)(local_fn(sp))
            return loss, repack_fn(g)

        loss, grads = jax.jit(shard_map(
            grad_fn, mesh=mesh,
            in_specs=(in_specs, P("expert"), P("expert")),
            out_specs=(P(), in_specs)))(packed, tokens, targets)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        ref_packed, _, _, _ = pack_for_shard_map(
            par, ref_g, tensor_axis="model", expert_axis="expert")
        _assert_grad_tree_allclose(grads, ref_packed)

    def _pipeline_case(self, rng, tpn, pp, ep, dp):
        from apex_tpu.models.gpt import pack_for_shard_map, pipeline_step

        Mb, mb, seq = 2, 2, 16
        tensor_axis = "model" if tpn > 1 else None
        serial, par = self._models(
            tensor_parallel_size=tpn, axis_name=tensor_axis,
            expert_axis="expert", expert_parallel_size=ep)
        params = serial.init_params(jax.random.PRNGKey(0))
        nshard = dp * ep * Mb
        tokens = jnp.asarray(rng.randint(0, 32, (nshard * mb, seq)))
        targets = jnp.asarray(rng.randint(0, 32, (nshard * mb, seq)))
        golden = _per_microbatch_golden(serial, params, tokens, targets,
                                        mb)
        ref_loss = float(jax.jit(golden)(params))
        ref_g = jax.jit(jax.grad(golden))(params)

        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            par, params, n_stages=pp, tensor_axis=tensor_axis,
            expert_axis="expert")
        axes, sizes = [], []
        if dp > 1:
            axes.append("data"); sizes.append(dp)
        if tpn > 1:
            axes.append("model"); sizes.append(tpn)
        axes += ["pipe", "expert"]; sizes += [pp, ep]
        mesh = jax.make_mesh(tuple(sizes), tuple(axes))
        batch_axes = (("data", "expert") if dp > 1 else ("expert",))

        def grad_step(sp, tk, tg):
            tk = tk.reshape(Mb, mb, seq)
            tg = tg.reshape(Mb, mb, seq)
            loss, g = pipeline_step(
                par, local_fn(sp), tk, tg, pipe_axis="pipe",
                data_axis="data" if dp > 1 else None)
            return loss, repack_fn(g)

        loss, grads = jax.jit(shard_map(
            grad_step, mesh=mesh,
            in_specs=(in_specs, P(batch_axes), P(batch_axes)),
            out_specs=(P(), in_specs)))(packed, tokens, targets)
        np.testing.assert_allclose(float(loss), ref_loss, rtol=1e-5)
        ref_packed, _, _, _ = pack_for_shard_map(
            par, ref_g, n_stages=pp, tensor_axis=tensor_axis,
            expert_axis="expert")
        _assert_grad_tree_allclose(grads, ref_packed)

    def test_dp_pp_ep_pipeline_grad_parity(self, rng):
        self._pipeline_case(rng, tpn=1, pp=2, ep=2, dp=2)

    def test_tp_pipeline_without_sp_rejected(self):
        """The ring engine requires sequence_parallel for TP (the SP
        custom-VJP mappings reduce replicated-leaf grads inside the local
        vjp), and SP does not compose with MoE — so TP x PP x MoE is an
        explicit ValueError, not a silently-wrong grad."""
        from apex_tpu.models.gpt import pipeline_step

        _, par = self._models(tensor_parallel_size=2, axis_name="model",
                              expert_axis="expert",
                              expert_parallel_size=2)
        params = par.init_params(jax.random.PRNGKey(0))
        tk = jnp.zeros((2, 2, 16), jnp.int32)
        with pytest.raises(ValueError, match="sequence_parallel"):
            pipeline_step(par, params, tk, tk, pipe_axis="pipe")


class TestSwitchGPTGradParity:
    """The EP training wiring used by examples/moe/train_switch_gpt.py:
    local-loss grads + explicit reductions must equal the serial
    per-shard golden exactly (dense = mean of shard grads, expert =
    sum/ep routed to the owner by the all_to_all transpose)."""

    def test_ep_grads_match_serial(self, rng):
        from apex_tpu.models.gpt import GPTConfig, GPTModel

        ep = 4
        kw = dict(vocab_size=32, hidden_size=16, num_layers=1,
                  num_attention_heads=4, max_seq_len=16, n_experts=4)
        serial = GPTModel(GPTConfig(**kw))
        params = serial.init_params(jax.random.PRNGKey(0))
        tokens = jnp.asarray(rng.randint(0, 32, (ep * 2, 16)))
        targets = jnp.asarray(rng.randint(0, 32, (ep * 2, 16)))

        # serial golden: mean over per-shard losses (same per-shard MoE
        # capacity semantics as the EP run)
        def serial_loss(p):
            losses = [serial.loss(p, tokens[s * 2:(s + 1) * 2],
                                  targets[s * 2:(s + 1) * 2])
                      for s in range(ep)]
            return jnp.mean(jnp.stack(losses))

        ref = jax.jit(jax.grad(serial_loss))(params)

        par = GPTModel(GPTConfig(expert_axis="expert",
                                 expert_parallel_size=ep, **kw))

        from apex_tpu.transformer.expert_parallel import (
            is_gpt_expert_leaf as is_expert, localize_expert_params,
            reduce_moe_grads)

        sharded = jax.tree_util.tree_map_with_path(
            lambda p, x: x.reshape(ep, 1, *x.shape[1:])
            if is_expert(p) else x, params)
        specs = jax.tree_util.tree_map_with_path(
            lambda p, x: P("expert") if is_expert(p) else P(), params)
        mesh = jax.make_mesh((ep,), ("expert",))

        def grad_fn(p, tk, tg):
            local = localize_expert_params(p)
            loss, grads = jax.value_and_grad(par.loss)(local, tk, tg)
            grads = reduce_moe_grads(grads, "expert")
            return jax.lax.pmean(loss, "expert"), grads

        loss, grads = jax.jit(shard_map(
            grad_fn, mesh=mesh,
            in_specs=(specs, P("expert"), P("expert")),
            out_specs=(P(), specs), check_vma=False))(
                sharded, tokens, targets)
        np.testing.assert_allclose(
            float(loss), float(jax.jit(serial_loss)(params)), rtol=1e-5)

        ref_shaped = jax.tree_util.tree_map_with_path(
            lambda p, x: x.reshape(ep, 1, *x.shape[1:])
            if is_expert(p) else x, ref)
        for (path, g), (_, r) in zip(
                jax.tree_util.tree_flatten_with_path(grads)[0],
                jax.tree_util.tree_flatten_with_path(ref_shaped)[0],
                strict=True):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), rtol=5e-4, atol=1e-5,
                err_msg=jax.tree_util.keystr(path))
