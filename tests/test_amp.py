"""amp engine tests (apex ``tests/L0/run_amp`` analogue).

Covers: O1 autocast primitive classification (basic casts + promotion),
dynamic loss scaler dynamics, checkpoint round-trip, and the minimum
end-to-end slice from SURVEY §7 — a 2-layer MLP trained to convergence with
``amp.initialize`` + FusedAdam + loss scaling under one jit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam, FusedSGD


class TestLegacyAmpSurface:
    """apex ``amp.py``/``opt.py``/``rnn_compat.py`` (the pre-initialize
    API, VERDICT r3 missing item 7)."""

    def test_casting_decorators(self):
        @amp.half_function
        def mm(a, b):
            return a @ b

        @amp.float_function
        def ex(x):
            return x * 2

        @amp.promote_function
        def add(a, b):
            return a + b

        a = jnp.ones((4, 4), jnp.float32)
        assert mm(a, a).dtype == jnp.bfloat16
        assert ex(jnp.ones((2,), jnp.bfloat16)).dtype == jnp.float32
        out = add(jnp.ones((2,), jnp.bfloat16), jnp.ones((2,), jnp.float32))
        assert out.dtype == jnp.float32

    def test_register_patches_and_restores(self):
        import types
        fake = types.SimpleNamespace(f=lambda x: x)
        amp.register_half_function(fake, "f")
        handle = amp.init(loss_scale=128.0)
        try:
            assert fake.f(jnp.ones((2,), jnp.float32)).dtype == jnp.bfloat16
        finally:
            handle._deactivate()
        assert fake.f(jnp.ones((2,), jnp.float32)).dtype == jnp.float32

    def test_init_disabled_noop(self):
        handle = amp.init(enabled=False)
        assert not handle.is_active
        with handle.scale_loss(jnp.float32(2.0)) as scaled:
            assert float(scaled) == 2.0

    def test_handle_scale_loss_and_optim_wrapper(self):
        handle = amp.init(loss_scale=64.0)
        try:
            with handle.scale_loss(jnp.float32(3.0)) as scaled:
                assert float(scaled) == 3.0 * 64.0
            opt = FusedAdam(lr=1e-2)
            params = {"w": jnp.ones((8, 8), jnp.float32)}
            state = opt.init(params)
            wrapper = handle.wrap_optimizer(opt)
            grads = {"w": jnp.full((8, 8), 0.5 * 64.0)}  # scaled grads
            new_p, _ = wrapper.step(grads, params, state)
            # unscaled inside: matches a plain step on UNscaled grads
            ref_p, _ = opt.step({"w": jnp.full((8, 8), 0.5)}, params,
                                opt.init(params))
            np.testing.assert_allclose(new_p["w"], ref_p["w"], rtol=1e-6)
        finally:
            handle._deactivate()

    def test_rnn_compat_surface(self):
        from apex_tpu.amp import legacy
        assert legacy.has_old_rnns is False
        legacy.whitelist_rnn_cells()       # validated no-op


class TestAutocastO1:
    def test_matmul_runs_half(self):
        # apex test_basic_casts: whitelist ops produce half outputs
        def f(a, b):
            return a @ b

        fa = amp.autocast(f, compute_dtype=jnp.bfloat16)
        a = jnp.ones((16, 16), jnp.float32)
        out = fa(a, a)
        assert out.dtype == jnp.bfloat16

    def test_blacklist_runs_fp32(self):
        def f(x):
            return jnp.exp(x)

        fa = amp.autocast(f, compute_dtype=jnp.bfloat16)
        out = fa(jnp.ones((8, 8), jnp.bfloat16))
        assert out.dtype == jnp.float32

    def test_promotion_widest(self):
        # apex test_promotion: mixed-dtype add promotes to the wider type
        def f(a, b):
            return a + b

        fa = amp.autocast(f)
        out = fa(jnp.ones((4,), jnp.bfloat16), jnp.ones((4,), jnp.float32))
        assert out.dtype == jnp.float32

    def test_grad_through_autocast(self):
        def loss_fn(w, x):
            h = x @ w                     # bf16 matmul under O1
            return jnp.sum(jax.nn.softmax(h.astype(jnp.float32)))

        fa = amp.autocast(loss_fn)
        w = jnp.ones((8, 8), jnp.float32) * 0.1
        x = jnp.ones((2, 8), jnp.float32)
        g = jax.grad(lambda w: fa(w, x))(w)
        assert g.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(g)))

    def test_scan_body_autocast_hlo(self):
        """VERDICT r3 item 4: O1 must descend into scan bodies — the only
        dots in this model live inside a ``lax.scan``, so a bf16
        dot_general in the lowered HLO proves the interior was cast
        (apex ``amp/wrap.py`` semantics apply inside loops)."""
        w = jnp.full((3, 16, 16), 0.1, jnp.float32)

        def model(w, x):
            def body(h, wi):
                return jnp.tanh(h @ wi), ()
            h, _ = jax.lax.scan(body, x, w)
            return jnp.sum(h)

        fa = amp.autocast(model, compute_dtype=jnp.bfloat16)
        x = jnp.ones((4, 16), jnp.float32)
        hlo = jax.jit(fa).lower(w, x).as_text()
        dots = [l for l in hlo.splitlines() if "dot_general" in l]
        assert dots, "model lost its dots"
        assert any("bf16" in l for l in dots), (
            "no bf16 dot in the scanned body:\n" + "\n".join(dots))
        # numerics still track fp32
        ref = float(model(w, x))
        out = float(fa(w, x))
        assert abs(out - ref) < 1e-2 * max(abs(ref), 1.0)

    def test_while_and_cond_bodies_autocast(self):
        w = jnp.full((16, 16), 0.1, jnp.float32)

        def model(w, x):
            def body(c):
                h, i = c
                return jnp.tanh(h @ w), i + 1
            h, _ = jax.lax.while_loop(lambda c: c[1] < 3, body, (x, 0))
            return jnp.sum(jax.lax.cond(jnp.sum(h) > 0,
                                        lambda y: y @ w, lambda y: y, h))

        fa = amp.autocast(model, compute_dtype=jnp.bfloat16)
        x = jnp.ones((4, 16), jnp.float32)
        hlo = jax.jit(fa).lower(w, x).as_text()
        assert "bf16" in hlo
        ref, out = float(model(w, x)), float(fa(w, x))
        assert abs(out - ref) < 1e-2 * max(abs(ref), 1.0)
        # grad composes through the autocast cond (while_loop is not
        # reverse-differentiable in JAX with or without autocast)
        def cond_only(w, x):
            return jnp.sum(jax.lax.cond(jnp.sum(x) > 0,
                                        lambda y: y @ w, lambda y: y, x))
        fc = amp.autocast(cond_only, compute_dtype=jnp.bfloat16)
        g = jax.grad(lambda w: fc(w, x))(w)
        assert g.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(g)))

    def test_rnn_under_o1(self):
        """The RNN tier is scan cells — under O1 it must (a) run, (b) emit
        half-precision dots, (c) track the fp32 trajectory."""
        from apex_tpu.RNN import LSTM

        import warnings
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", DeprecationWarning)
            m = LSTM(16, 32)
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(3).randn(8, 2, 16),
                        jnp.float32)

        def run(params, x):
            out, _ = m.apply(params, x)
            return jnp.sum(out)

        fa = amp.autocast(run, compute_dtype=jnp.bfloat16)
        hlo = jax.jit(fa).lower(params, x).as_text()
        dots = [l for l in hlo.splitlines() if "dot_general" in l]
        assert any("bf16" in l for l in dots), "LSTM cell dots stayed fp32"
        ref, out = float(run(params, x)), float(fa(params, x))
        assert abs(out - ref) < 5e-2 * max(abs(ref), 1.0)
        g = jax.grad(lambda p: fa(p, x))(params)
        assert all(np.all(np.isfinite(np.asarray(l)))
                   for l in jax.tree_util.tree_leaves(g))

    def test_autocast_inside_shard_map(self):
        """O1 x DDP composition: autocast the per-device function, wrap
        in shard_map — collectives pass through, grads compose, and the
        interior dots run bf16."""
        from jax.sharding import PartitionSpec as P

        from apex_tpu.utils.collectives import shard_map_compat as shard_map

        mesh = jax.make_mesh((jax.device_count(),), ("data",))
        w = jnp.full((16, 16), 0.1, jnp.float32)
        x = jnp.ones((jax.device_count() * 2, 16), jnp.float32)

        def loss(w, x):
            h = jnp.tanh(x @ w)
            return jax.lax.pmean(jnp.sum(h), "data")

        ac = amp.autocast(loss, compute_dtype=jnp.bfloat16)
        sm = shard_map(ac, mesh=mesh, in_specs=(P(), P("data")),
                       out_specs=P())
        ref = float(jax.jit(shard_map(
            loss, mesh=mesh, in_specs=(P(), P("data")),
            out_specs=P()))(w, x))
        out = float(jax.jit(sm)(w, x))
        assert abs(out - ref) < 1e-2 * max(abs(ref), 1.0)
        hlo = jax.jit(sm).lower(w, x).as_text()
        assert any("bf16" in l for l in hlo.splitlines()
                   if "dot_general" in l), "dot stayed fp32 in the region"
        def grad_of(fn):
            return jax.jit(shard_map(
                lambda w, x: jax.grad(lambda w: fn(w, x))(w), mesh=mesh,
                in_specs=(P(), P("data")), out_specs=P()))(w, x)

        g = grad_of(ac)
        assert g.dtype == jnp.float32
        # the composition claim is numeric: autocast grads must track the
        # un-autocast shard_map grads (same pmean transpose/psum wiring)
        np.testing.assert_allclose(np.asarray(g),
                                   np.asarray(grad_of(loss)),
                                   rtol=2e-2, atol=2e-2)

    def test_composite_network_numerics(self):
        # autocast output should approximate the f32 reference
        def net(params, x):
            h = jnp.tanh(x @ params["w1"])
            return jnp.sum(jax.nn.log_softmax(h @ params["w2"]))

        rng = np.random.RandomState(0)
        params = {"w1": jnp.asarray(rng.randn(16, 32).astype(np.float32)),
                  "w2": jnp.asarray(rng.randn(32, 8).astype(np.float32))}
        x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
        ref = net(params, x)
        out = amp.autocast(net)(params, x)
        np.testing.assert_allclose(float(out), float(ref), rtol=2e-2)

    def test_jit_compose(self):
        def f(a, b):
            return a @ b

        fa = jax.jit(amp.autocast(f))
        out = fa(jnp.ones((8, 8)), jnp.ones((8, 8)))
        assert out.dtype == jnp.bfloat16


class TestAutocastPallasComposition:
    """Round-2 regression: ``jax.grad(amp.autocast(loss))`` over the
    library's own Pallas custom_vjp ops must work — the interpreter keeps
    custom-derivative calls opaque so the VJP rule survives (on TPU the
    inlined body would be a bare ``pallas_call`` with no autodiff)."""

    @pytest.fixture(autouse=True)
    def _force_pallas(self):
        from apex_tpu.utils import set_force_pallas
        set_force_pallas(True)
        yield
        set_force_pallas(None)

    def test_grad_autocast_fused_layer_norm(self, rng):
        from apex_tpu.normalization import FusedLayerNorm

        ln = FusedLayerNorm(32)
        params = {"ln": ln.init_params(),
                  "w": jnp.asarray(rng.randn(32, 32).astype(np.float32))}
        x = jnp.asarray(rng.randn(4, 32).astype(np.float32))

        def loss(params, x):
            h = x @ params["w"]
            return jnp.sum(ln(params["ln"], h) ** 2)

        fa = amp.autocast(loss)
        g = jax.grad(fa)(params, x)
        ref = jax.grad(loss)(params, x)
        for leaf, rleaf in zip(jax.tree_util.tree_leaves(g),
                               jax.tree_util.tree_leaves(ref)):
            assert np.all(np.isfinite(np.asarray(leaf, np.float32)))
            np.testing.assert_allclose(np.asarray(leaf, np.float32),
                                       np.asarray(rleaf, np.float32),
                                       rtol=5e-2, atol=5e-2)

    def test_grad_autocast_flash_attention(self, rng):
        from apex_tpu.ops.flash_attention import flash_attention

        q = jnp.asarray(rng.randn(1, 2, 128, 64).astype(np.float32))

        def loss(q):
            return jnp.sum(flash_attention(q, q, q, causal=True))

        g = jax.grad(amp.autocast(loss))(q)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_jit_grad_autocast_pallas(self, rng):
        from apex_tpu.ops.layer_norm import fused_rms_norm_affine

        w = jnp.ones((64,), jnp.float32)
        x = jnp.asarray(rng.randn(8, 64).astype(np.float32))

        def loss(x, w):
            return jnp.sum(fused_rms_norm_affine(x, w) ** 2)

        g = jax.jit(jax.grad(amp.autocast(loss)))(x, w)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_matmul_still_autocasts_around_pallas(self, rng):
        """The whitelist cast must still fire for ops OUTSIDE the opaque
        custom call (matmul output bf16), while the Pallas op keeps its
        traced dtype."""
        from apex_tpu.normalization import FusedLayerNorm

        ln = FusedLayerNorm(16)
        lp = ln.init_params()

        def f(x, w):
            return ln(lp, x @ w)

        fa = amp.autocast(f)
        out = fa(jnp.ones((4, 16)), jnp.ones((16, 16)))
        # LN was traced at f32 (inputs restored at the opaque boundary)
        assert out.dtype == jnp.float32


class TestLossScaler:
    def test_dynamic_halves_on_overflow(self):
        s = amp.LossScaler("dynamic", init_scale=2.0 ** 8)
        st = s.init()
        st2 = s.update(st, jnp.asarray(1.0))
        assert float(st2.loss_scale) == 2.0 ** 7
        assert int(st2.unskipped) == 0
        assert int(st2.overflows) == 1

    def test_dynamic_grows_after_window(self):
        s = amp.LossScaler("dynamic", init_scale=4.0, scale_window=3)
        st = s.init()
        for _ in range(3):
            st = s.update(st, jnp.asarray(0.0))
        assert float(st.loss_scale) == 8.0
        assert int(st.unskipped) == 0

    def test_static_never_changes(self):
        s = amp.LossScaler(128.0)
        st = s.init()
        st = s.update(st, jnp.asarray(1.0))
        assert float(st.loss_scale) == 128.0

    def test_found_inf(self):
        g = {"a": jnp.ones((4,)), "b": jnp.asarray([1.0, np.inf])}
        assert float(amp.LossScaler.found_inf(g)) == 1.0
        g = {"a": jnp.ones((4,)), "b": jnp.asarray([1.0, 2.0])}
        assert float(amp.LossScaler.found_inf(g)) == 0.0

    def test_checkpoint_roundtrip(self):
        # apex tests/L0/run_amp/test_checkpointing.py: amp state_dict survives
        s = amp.LossScaler("dynamic", init_scale=2.0 ** 10)
        st = s.update(s.init(), jnp.asarray(1.0))
        d = s.state_dict(st)
        st2 = s.load_state_dict(d)
        assert float(st2.loss_scale) == float(st.loss_scale)
        assert int(st2.unskipped) == int(st.unskipped)


class TestEndToEndSlice:
    """SURVEY §7 minimum slice: amp.initialize + FusedAdam + scale_loss,
    2-layer MLP on synthetic data, trained to convergence under one jit."""

    @pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
    def test_mlp_converges(self, opt_level, rng):
        def apply_fn(params, x):
            h = jax.nn.relu(x @ params["w1"] + params["b1"])
            return h @ params["w2"] + params["b2"]

        params = {
            "w1": jnp.asarray(rng.randn(8, 32).astype(np.float32) * 0.3),
            "b1": jnp.zeros((32,), jnp.float32),
            "w2": jnp.asarray(rng.randn(32, 4).astype(np.float32) * 0.3),
            "b2": jnp.zeros((4,), jnp.float32),
        }
        w_true = rng.randn(8, 4).astype(np.float32)
        x = rng.randn(256, 8).astype(np.float32)
        y = np.argmax(x @ w_true, axis=1)
        x, y = jnp.asarray(x), jnp.asarray(y)

        optimizer = FusedAdam(lr=5e-3)
        state = amp.initialize(apply_fn, optimizer, opt_level=opt_level,
                               half_dtype=jnp.bfloat16)
        params = state.cast_params(params)
        opt_state = optimizer.init(params)
        scaler_state = state.scaler.init()

        def loss_fn(params, x, y, scaler_state):
            (x,) = state.cast_inputs(x)
            logits = state.apply_fn(params, x).astype(jnp.float32)
            loss = -jnp.mean(jax.nn.log_softmax(logits)[jnp.arange(len(y)),
                                                        y])
            return amp.scale_loss(loss, scaler_state), loss

        @jax.jit
        def train_step(params, opt_state, scaler_state, x, y):
            grads, loss = jax.grad(loss_fn, has_aux=True)(
                params, x, y, scaler_state)
            params, opt_state, scaler_state, _ = amp.unscale_step(
                optimizer, grads, params, opt_state, state.scaler,
                scaler_state)
            return params, opt_state, scaler_state, loss

        losses = []
        for i in range(150):
            params, opt_state, scaler_state, loss = train_step(
                params, opt_state, scaler_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (opt_level, losses[::30])
        # O2: params stayed half precision except none (no norm layers)
        if opt_level in ("O2", "O3"):
            assert params["w1"].dtype == jnp.bfloat16

    def test_overflow_skip_then_recover(self, rng):
        """Inject an inf gradient; the step must be skipped and the scale
        halved (apex dynamic loss scaling semantics)."""
        params = {"w": jnp.ones((16, 16), jnp.float32)}
        optimizer = FusedAdam(lr=0.1)
        opt_state = optimizer.init(params)
        scaler = amp.LossScaler("dynamic", init_scale=2.0 ** 8)
        sstate = scaler.init()
        bad_grads = {"w": jnp.full((16, 16), np.inf, jnp.float32)}
        p1, o1, s1, finf = amp.unscale_step(
            optimizer, bad_grads, params, opt_state, scaler, sstate)
        assert float(finf) == 1.0
        np.testing.assert_array_equal(np.asarray(p1["w"]),
                                      np.asarray(params["w"]))
        assert float(s1.loss_scale) == 2.0 ** 7
        assert int(o1["step"]) == 0
        good = {"w": jnp.ones((16, 16), jnp.float32)}
        p2, o2, s2, finf2 = amp.unscale_step(
            optimizer, good, p1, o1, scaler, s1)
        assert float(finf2) == 0.0
        assert int(o2["step"]) == 1
        assert not np.allclose(np.asarray(p2["w"]), np.asarray(p1["w"]))
