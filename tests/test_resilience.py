"""apex_tpu.resilience: checkpointing, anomaly guard, fault injection.

The contract under test (ISSUE 4):

* checkpoint round-trips are BITWISE across optimizer-state layouts —
  per-leaf FusedAdam, packed ZeRO DistributedFusedAdam (dp=2, state
  row-sharded under shard_map), and TP=2 sequence-parallel params — and
  the restored state produces bitwise-identical next-step grads;
* the commit protocol survives a kill at any point: tmp dirs and
  manifest-less dirs are never candidates, a corrupted payload is
  caught by the content hash and restore falls back to the previous
  complete checkpoint;
* kill-and-resume parity: training interrupted by an injected
  :class:`Preemption` and resumed from the latest checkpoint is
  bitwise identical (f32 params AND optimizer slots) to the
  uninterrupted run — at dp=2 and at dp=2 x tp=2 + sequence parallel;
* the guard skips NaN/inf/spike steps with optimizer state untouched
  (the loss-scaler overflow-skip semantics) and rolls back after K
  consecutive anomalies;
* the serving engine quarantines poison requests (reason="error"),
  enforces per-request timeouts distinct from deadline eviction, and
  applies bounded-queue backpressure (QueueFull).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from apex_tpu.amp.scaler import LossScaler
from apex_tpu.contrib.optimizers import DistributedFusedAdam
from apex_tpu.inference import (InferenceEngine, QueueFull, Request,
                                SamplingParams)
from apex_tpu.models.gpt import GPTConfig, GPTModel, pack_for_shard_map
from apex_tpu.optimizers import FusedAdam
from apex_tpu.resilience import (CheckpointManager, CheckpointNotFound,
                                 Fault, FaultInjector, GuardedTrainStep,
                                 Preemption)
from apex_tpu.utils.collectives import shard_map_compat as shard_map

DIN, DOUT, BATCH = 8, 4, 8


def _params(seed=0):
    r = np.random.RandomState(seed)
    return {"w": jnp.asarray(r.randn(DIN, DOUT).astype(np.float32)),
            "b": jnp.asarray(r.randn(DOUT).astype(np.float32))}


def _loss_fn(p, x, y):
    return jnp.mean(jnp.square(x @ p["w"] + p["b"] - y))


def _batch(step, batch=BATCH, din=DIN, dout=DOUT):
    """Per-step seeded batch: both arms of a parity test replay the
    exact same data stream."""
    r = np.random.RandomState(10_000 + step)
    return (jnp.asarray(r.randn(batch, din).astype(np.float32)),
            jnp.asarray(r.randn(batch, dout).astype(np.float32)))


def _tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# -- checkpoint round-trips across state layouts ------------------------------

class TestCheckpointRoundTrip:
    def test_per_leaf_fused_adam(self, tmp_path):
        """Default layout: FusedAdam per-leaf moments.  Restored state is
        bitwise AND the next optimizer step from it is bitwise."""
        params = _params()
        opt = FusedAdam(lr=1e-2)
        state = opt.init(params)
        x, y = _batch(0)
        grads = jax.grad(_loss_fn)(params, x, y)
        params, state = jax.jit(opt.step)(grads, params, state)

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"params": params, "opt": state})
        template = jax.tree_util.tree_map(
            jnp.zeros_like, {"params": params, "opt": state})
        restored, step = mgr.restore(template)
        assert step == 1
        _tree_equal(restored, {"params": params, "opt": state})

        x, y = _batch(1)
        g = jax.grad(_loss_fn)(params, x, y)
        g_r = jax.grad(_loss_fn)(restored["params"], x, y)
        _tree_equal(g, g_r)
        p1, s1 = jax.jit(opt.step)(g, params, state)
        p2, s2 = jax.jit(opt.step)(g_r, restored["params"],
                                   restored["opt"])
        _tree_equal(p1, p2)
        _tree_equal(s1, s2)

    def test_packed_zero_dp2(self, tmp_path):
        """ZeRO layout: DistributedFusedAdam's packed (rows, 128) buckets
        are row-sharded over dp=2 — each shard saves its slice, restore
        re-places onto the template's sharding, and the next distributed
        step is bitwise."""
        mesh = jax.make_mesh((2,), ("data",))
        params = _params()
        opt = DistributedFusedAdam(lr=1e-2, world_size=2, block_rows=8)
        state = opt.make_init(mesh)(params)
        step = opt.make_step(mesh)
        r = np.random.RandomState(7)
        stacked = jax.tree_util.tree_map(
            lambda p: jnp.asarray(
                r.randn(2, *p.shape).astype(np.float32) * 0.1), params)
        params, state = step(stacked, params, state)

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(1, {"params": params, "opt": state})
        # the live state is the template: structure + target shardings
        restored, _ = mgr.restore({"params": params, "opt": state})
        _tree_equal(restored, {"params": params, "opt": state})
        for got, want in zip(
                jax.tree_util.tree_leaves(restored["opt"]),
                jax.tree_util.tree_leaves(state)):
            if hasattr(want, "sharding"):
                assert got.sharding == want.sharding

        p1, s1 = step(stacked, params, state)
        p2, s2 = step(stacked, restored["params"], restored["opt"])
        _tree_equal(p1, p2)
        _tree_equal(s1, s2)

    def test_tp2_sequence_parallel_params(self, tmp_path):
        """TP=2 + SP: packed params (TP leaves stacked over the model
        axis) round-trip bitwise and the restored pack produces bitwise
        next-step grads through the sequence-parallel step."""
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_attention_heads=4, max_seq_len=8,
                        tensor_parallel_size=2, axis_name="model",
                        sequence_parallel=True)
        par = GPTModel(cfg)
        serial = GPTModel(GPTConfig(vocab_size=32, hidden_size=16,
                                    num_layers=2, num_attention_heads=4,
                                    max_seq_len=8))
        params = serial.init_params(jax.random.PRNGKey(1))
        mesh = jax.make_mesh((2,), ("model",))
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            par, params)
        packed = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            packed, in_specs, is_leaf=lambda x: isinstance(x, P))

        mgr = CheckpointManager(str(tmp_path))
        mgr.save(0, packed)
        restored, _ = mgr.restore(packed)
        _tree_equal(restored, packed)

        r = np.random.RandomState(3)
        tokens = jnp.asarray(r.randint(0, 32, (2, 8)))
        targets = jnp.asarray(r.randint(0, 32, (2, 8)))

        def body(sp, tk, tg):
            loss, g = jax.value_and_grad(par.loss)(local_fn(sp), tk, tg)
            return loss, repack_fn(g)

        f = jax.jit(shard_map(body, mesh=mesh,
                              in_specs=(in_specs, P(), P()),
                              out_specs=(P(), in_specs)))
        loss1, g1 = f(packed, tokens, targets)
        loss2, g2 = f(restored, tokens, targets)
        assert float(loss1) == float(loss2)
        _tree_equal(g1, g2)

    def test_restore_onto_different_topology(self, tmp_path):
        """A checkpoint saved from 2-way-sharded arrays restores onto an
        unsharded template (gather) and onto a 4-way mesh (re-shard)."""
        mesh2 = jax.make_mesh((2,), ("data",))
        arr = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
        sharded = jax.device_put(arr, NamedSharding(mesh2, P("data")))
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(0, {"a": sharded})

        gathered, _ = mgr.restore({"a": jnp.zeros_like(arr)})
        np.testing.assert_array_equal(np.asarray(gathered["a"]),
                                      np.asarray(arr))

        mesh4 = jax.make_mesh((4,), ("data",))
        tmpl = jax.device_put(jnp.zeros_like(arr),
                              NamedSharding(mesh4, P("data")))
        resharded, _ = mgr.restore({"a": tmpl})
        np.testing.assert_array_equal(np.asarray(resharded["a"]),
                                      np.asarray(arr))
        assert resharded["a"].sharding == tmpl.sharding


# -- commit protocol / corruption ---------------------------------------------

class TestCommitProtocol:
    def test_corrupt_payload_falls_back(self, tmp_path):
        state0 = {"a": jnp.arange(4.0)}
        state1 = {"a": jnp.arange(4.0) + 100.0}
        mgr = CheckpointManager(str(tmp_path), keep=3)
        mgr.save(1, state0)
        path2 = mgr.save(2, state1)
        with open(os.path.join(path2, "state.bin"), "r+b") as f:
            f.seek(4)
            f.write(b"\xff\xff\xff\xff")
        with pytest.warns(UserWarning, match="corrupt"):
            restored, step = mgr.restore({"a": jnp.zeros(4)})
        assert step == 1
        np.testing.assert_array_equal(np.asarray(restored["a"]),
                                      np.asarray(state0["a"]))

    def test_injected_corruption(self, tmp_path):
        """The corrupt_checkpoint fault flips bytes after commit; the
        hash must catch it and the injector log must show it landed."""
        inj = FaultInjector([Fault(step=2, kind="corrupt_checkpoint")])
        mgr = CheckpointManager(str(tmp_path), keep=3,
                                fault_injector=inj)
        mgr.save(1, {"a": jnp.arange(6.0)})
        mgr.save(2, {"a": jnp.arange(6.0) * 2})
        assert (2, "corrupt_checkpoint") in inj.log
        with pytest.warns(UserWarning, match="corrupt"):
            _, step = mgr.restore({"a": jnp.zeros(6)})
        assert step == 1

    def test_torn_and_manifestless_dirs_ignored(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(3, {"a": jnp.ones(2)})
        # a kill mid-write leaves a tmp dir; a kill between payload and
        # manifest leaves a dir without a manifest — neither is a
        # candidate
        os.makedirs(tmp_path / "step_00000007.tmp")
        (tmp_path / "step_00000007.tmp" / "state.bin").write_bytes(b"xx")
        os.makedirs(tmp_path / "step_00000009")
        (tmp_path / "step_00000009" / "state.bin").write_bytes(b"yy")
        assert mgr.all_steps() == [3]
        _, step = mgr.restore({"a": jnp.zeros(2)})
        assert step == 3

    def test_latest_symlink_and_retire(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for s in (1, 2, 3):
            mgr.save(s, {"a": jnp.full((2,), float(s))})
        assert os.readlink(tmp_path / "latest") == "step_00000003"
        assert mgr.all_steps() == [2, 3]      # keep=2 retired step 1

    def test_empty_dir_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(CheckpointNotFound):
            mgr.restore({"a": jnp.zeros(2)})

    def test_async_double_buffered(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=4)
        states = [{"a": jnp.full((3,), float(s))} for s in range(3)]
        for s, st in enumerate(states):
            mgr.save_async(s, st)
        mgr.wait()
        assert mgr.all_steps() == [0, 1, 2]
        restored, step = mgr.restore({"a": jnp.zeros(3)})
        assert step == 2
        np.testing.assert_array_equal(np.asarray(restored["a"]),
                                      np.asarray(states[2]["a"]))


# -- fault injector ------------------------------------------------------------

class TestFaultInjector:
    def test_from_seed_deterministic(self):
        rates = {"nan_grads": 0.3, "grad_spike": 0.3, "slow_host": 0.2}
        a = FaultInjector.from_seed(11, 50, rates)
        b = FaultInjector.from_seed(11, 50, rates)
        assert a.schedule == b.schedule
        assert len(a.schedule) > 0
        c = FaultInjector.from_seed(12, 50, rates)
        assert c.schedule != a.schedule

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            Fault(step=0, kind="cosmic_ray")
        with pytest.raises(ValueError, match="unknown fault kinds"):
            FaultInjector.from_seed(0, 10, {"cosmic_ray": 1.0})

    def test_grad_flags_identity_on_clean_steps(self):
        inj = FaultInjector([Fault(step=3, kind="nan_grads")])
        assert inj.grad_flags(0) == {"nan_grads": 0.0, "inf_loss": 0.0,
                                     "spike_scale": 1.0}
        flags = inj.grad_flags(3)
        assert flags["nan_grads"] == 1.0
        assert inj.log == [(3, "nan_grads")]

    def test_preempt_raises(self):
        inj = FaultInjector([Fault(step=5, kind="preempt_at_step")])
        inj.check_preempt(4)
        with pytest.raises(Preemption) as e:
            inj.check_preempt(5)
        assert e.value.step == 5


# -- anomaly guard ------------------------------------------------------------

def _make_guard(**kw):
    opt = FusedAdam(lr=1e-2)
    guard = GuardedTrainStep(_loss_fn, opt, **kw)
    params = _params()
    return guard, params, opt.init(params), guard.init_state()


class TestGuardedTrainStep:
    def test_clean_steps_update_params(self):
        guard, params, opt_state, gstate = _make_guard()
        for step in range(3):
            x, y = _batch(step)
            res = guard(params, opt_state, gstate, x, y, step=step)
            assert not res.skipped and res.anomaly is None
            params, opt_state, gstate = (res.params, res.opt_state,
                                         res.guard_state)
        assert guard.stats["skipped"] == 0
        assert int(gstate.clean_steps) == 3

    @pytest.mark.parametrize("kind,field", [("nan_grads", "nonfinite"),
                                            ("inf_loss", "nonfinite")])
    def test_nonfinite_step_skipped(self, kind, field):
        inj = FaultInjector([Fault(step=1, kind=kind)])
        guard, params, opt_state, gstate = _make_guard(fault_injector=inj)
        x, y = _batch(0)
        res = guard(params, opt_state, gstate, x, y, step=0)
        p1, o1, g1 = res.params, res.opt_state, res.guard_state
        x, y = _batch(1)
        res = guard(p1, o1, g1, x, y, step=1)
        assert res.skipped and res.anomaly == "nonfinite"
        # the skip left params AND optimizer slots untouched (the
        # loss-scaler overflow-skip contract, on-device)
        _tree_equal(res.params, p1)
        _tree_equal(res.opt_state, o1)
        assert guard.stats[field] == 1
        assert int(res.guard_state.anomalies) == 1

    def test_grad_spike_skipped_after_warmup(self):
        inj = FaultInjector([Fault(step=4, kind="grad_spike",
                                   magnitude=1000.0)])
        guard, params, opt_state, gstate = _make_guard(
            fault_injector=inj, warmup_steps=2, spike_factor=10.0)
        for step in range(5):
            x, y = _batch(step)
            res = guard(params, opt_state, gstate, x, y, step=step)
            if step < 4:
                assert not res.skipped
                params, opt_state, gstate = (res.params, res.opt_state,
                                             res.guard_state)
        assert res.skipped and res.anomaly == "spike"
        assert guard.stats["spikes"] == 1
        # the spike did not feed the EMA
        assert int(res.guard_state.clean_steps) == 4

    def test_rollback_after_k_consecutive(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        inj = FaultInjector([Fault(step=s, kind="nan_grads")
                             for s in (2, 3, 4)])
        guard, params, opt_state, gstate = _make_guard(
            fault_injector=inj, max_consecutive=3, checkpoint=mgr)
        step = 0
        while step < 2:
            x, y = _batch(step)
            res = guard(params, opt_state, gstate, x, y, step=step)
            params, opt_state, gstate = (res.params, res.opt_state,
                                         res.guard_state)
            step = res.next_step
        guard.save(2, params, opt_state, gstate)
        good = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
        for step in (2, 3, 4):
            x, y = _batch(step)
            res = guard(params, opt_state, gstate, x, y, step=step)
            params, opt_state, gstate = (res.params, res.opt_state,
                                         res.guard_state)
        assert res.rolled_back and res.restored_from == 2
        assert res.next_step == 2
        assert guard.stats["rollbacks"] == 1
        _tree_equal(params, good)

    def test_scaler_skip_and_checkpoint_roundtrip(self, tmp_path):
        """Dynamic loss scaling through the guard: an injected inf loss
        counts as an overflow (scale halves, cumulative skipped
        increments) and the scaler state round-trips through the
        checkpoint."""
        scaler = LossScaler("dynamic", init_scale=2.0 ** 8)
        inj = FaultInjector([Fault(step=1, kind="inf_loss")])
        opt = FusedAdam(lr=1e-2)
        guard = GuardedTrainStep(_loss_fn, opt, scaler=scaler,
                                 fault_injector=inj)
        params = _params()
        opt_state, gstate = opt.init(params), guard.init_state()
        sstate = scaler.init()
        for step in range(2):
            x, y = _batch(step)
            res = guard(params, opt_state, gstate, x, y,
                        scaler_state=sstate, step=step)
            params, opt_state, gstate, sstate = (
                res.params, res.opt_state, res.guard_state,
                res.scaler_state)
        assert float(sstate.loss_scale) == 2.0 ** 7       # halved
        assert int(sstate.skipped) == 1
        assert guard.stats["scaler_skipped_steps"] == 1

        mgr = CheckpointManager(str(tmp_path))
        guard.checkpoint = mgr
        guard.save(2, params, opt_state, gstate, sstate)
        restored, _ = mgr.restore(guard._template(params, opt_state,
                                                  gstate, sstate))
        assert int(restored["scaler"].skipped) == 1
        _tree_equal(restored["scaler"], sstate)

    def test_misuse_raises(self):
        opt = FusedAdam(lr=1e-2)
        with pytest.raises(ValueError, match="exactly one"):
            GuardedTrainStep(_loss_fn, opt, grad_fn=lambda p: None)
        with pytest.raises(ValueError, match="loss_fn form"):
            GuardedTrainStep(None, opt, grad_fn=lambda p: None,
                             scaler=LossScaler())
        guard, params, opt_state, gstate = _make_guard()
        x, y = _batch(0)
        with pytest.raises(ValueError, match="scaler_state"):
            guard(params, opt_state, gstate, x, y,
                  scaler_state=LossScaler().init())


# -- kill-and-resume parity (the tentpole proof) ------------------------------

def _dp_grad_fn(mesh, loss_fn=_loss_fn):
    """Data-parallel grads: batch sharded over 'data', loss and grads
    pmean-reduced inside the shard_map region."""
    def body(p, x, y):
        loss, g = jax.value_and_grad(loss_fn)(p, x, y)
        loss = jax.lax.pmean(loss, "data")
        g = jax.tree_util.tree_map(
            lambda a: jax.lax.pmean(a, "data"), g)
        return loss, g
    return shard_map(body, mesh=mesh,
                     in_specs=(P(), P("data"), P("data")),
                     out_specs=(P(), P()))


def _drive(guard, n_steps, params, opt_state, gstate, batch_fn,
           start=0, save_every=1):
    """The train loop a resilient job runs: step, then checkpoint the
    state ABOUT TO run ``next_step``.  Raises Preemption through."""
    step = start
    while step < n_steps:
        x, y = batch_fn(step)
        res = guard(params, opt_state, gstate, x, y, step=step)
        params, opt_state, gstate = (res.params, res.opt_state,
                                     res.guard_state)
        step = res.next_step
        if step % save_every == 0:
            guard.save(step, params, opt_state, gstate)
    return params, opt_state, gstate


class TestKillAndResumeDP2:
    N_STEPS = 5
    KILL_AT = 3

    def _fresh(self, ckpt_dir, injector=None):
        mesh = jax.make_mesh((2,), ("data",))
        opt = FusedAdam(lr=1e-2)
        mgr = CheckpointManager(str(ckpt_dir)) if ckpt_dir else None
        guard = GuardedTrainStep(grad_fn=_dp_grad_fn(mesh), optimizer=opt,
                                 checkpoint=mgr, fault_injector=injector)
        # the train state lives on the mesh (replicated), like a real
        # dp job's — single-device-committed arrays can't enter a jit
        # whose shard_map spans the mesh
        rep = NamedSharding(mesh, P())
        params = jax.device_put(_params(), rep)
        return (guard, params, jax.device_put(opt.init(params), rep),
                jax.device_put(guard.init_state(), rep))

    def test_resume_is_bitwise(self, tmp_path):
        # arm A: uninterrupted
        guard, params, opt_state, gstate = self._fresh(tmp_path / "a")
        ref_p, ref_o, _ = _drive(guard, self.N_STEPS, params, opt_state,
                                 gstate, _batch)

        # arm B: preempted at KILL_AT, resumed from the checkpoint
        inj = FaultInjector([Fault(step=self.KILL_AT,
                                   kind="preempt_at_step")])
        guard, params, opt_state, gstate = self._fresh(tmp_path / "b",
                                                       injector=inj)
        with pytest.raises(Preemption):
            _drive(guard, self.N_STEPS, params, opt_state, gstate, _batch)

        # restart: a FRESH process has only the checkpoint directory
        guard2, params0, opt0, g0 = self._fresh(tmp_path / "b")
        restored, step = guard2.checkpoint.restore(
            guard2._template(params0, opt0, g0, None))
        assert step == self.KILL_AT
        got_p, got_o, _ = _drive(guard2, self.N_STEPS, restored["params"],
                                 restored["opt"], restored["guard"],
                                 _batch, start=int(
                                     np.asarray(restored["step"])))
        _tree_equal(got_p, ref_p)         # f32 params: bitwise
        _tree_equal(got_o, ref_o)         # optimizer slots: bitwise


class TestKillAndResumeDP2TP2SP:
    """dp=2 x tp=2 + sequence parallelism on the (2, 2) mesh: the
    checkpoint carries TP-stacked params and per-leaf Adam slots; resume
    must be bitwise against the uninterrupted run."""
    N_STEPS = 3
    KILL_AT = 2
    B, S = 4, 8

    @staticmethod
    def _gpt_batch(step):
        r = np.random.RandomState(20_000 + step)
        return (jnp.asarray(r.randint(0, 32, (4, 8))),
                jnp.asarray(r.randint(0, 32, (4, 8))))

    def _fresh(self, ckpt_dir, injector=None):
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_attention_heads=4, max_seq_len=8,
                        tensor_parallel_size=2, axis_name="model",
                        sequence_parallel=True)
        par = GPTModel(cfg)
        serial_params = GPTModel(GPTConfig(
            vocab_size=32, hidden_size=16, num_layers=2,
            num_attention_heads=4,
            max_seq_len=8)).init_params(jax.random.PRNGKey(5))
        mesh = jax.make_mesh((2, 2), ("data", "model"))
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            par, serial_params)

        def body(sp, tk, tg):
            loss, g = jax.value_and_grad(par.loss)(local_fn(sp), tk, tg)
            loss = jax.lax.pmean(loss, "data")
            g = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, "data"), g)
            return loss, repack_fn(g)

        grad_fn = shard_map(body, mesh=mesh,
                            in_specs=(in_specs, P("data"), P("data")),
                            out_specs=(P(), in_specs))
        opt = FusedAdam(lr=1e-2)
        mgr = CheckpointManager(str(ckpt_dir))
        guard = GuardedTrainStep(grad_fn=grad_fn, optimizer=opt,
                                 checkpoint=mgr, fault_injector=injector)
        rep = NamedSharding(mesh, P())
        packed = jax.device_put(packed, rep)
        return (guard, packed, jax.device_put(opt.init(packed), rep),
                jax.device_put(guard.init_state(), rep))

    def test_resume_is_bitwise(self, tmp_path):
        guard, params, opt_state, gstate = self._fresh(tmp_path / "a")
        ref_p, ref_o, _ = _drive(guard, self.N_STEPS, params, opt_state,
                                 gstate, self._gpt_batch)

        inj = FaultInjector([Fault(step=self.KILL_AT,
                                   kind="preempt_at_step")])
        guard, params, opt_state, gstate = self._fresh(tmp_path / "b",
                                                       injector=inj)
        with pytest.raises(Preemption):
            _drive(guard, self.N_STEPS, params, opt_state, gstate,
                   self._gpt_batch)

        guard2, params0, opt0, g0 = self._fresh(tmp_path / "b")
        restored, step = guard2.checkpoint.restore(
            guard2._template(params0, opt0, g0, None))
        assert step == self.KILL_AT
        got_p, got_o, _ = _drive(guard2, self.N_STEPS,
                                 restored["params"], restored["opt"],
                                 restored["guard"], self._gpt_batch,
                                 start=int(np.asarray(restored["step"])))
        _tree_equal(got_p, ref_p)
        _tree_equal(got_o, ref_o)


class TestKillAndResumeDP2PP2:
    """dp=2 x pp=2 ring pipeline: the checkpoint carries stage-stacked
    params and the grad_fn is a 1F1B scan under shard_map; resume must
    be bitwise against the uninterrupted run.  (tools/crash_matrix.py
    sweeps the full kill-step x fault matrix for this component and the
    tp=2 x pp=2 + SP one.)"""
    N_STEPS = 3
    KILL_AT = 2
    M, MB, SEQ = 2, 2, 8

    @staticmethod
    def _gpt_batch(step):
        r = np.random.RandomState(30_000 + step)
        return (jnp.asarray(r.randint(0, 32, (8, 8))),
                jnp.asarray(r.randint(0, 32, (8, 8))))

    def _fresh(self, ckpt_dir, injector=None):
        from apex_tpu.models.gpt import pipeline_step

        model = GPTModel(GPTConfig(
            vocab_size=32, hidden_size=16, num_layers=2,
            num_attention_heads=4, max_seq_len=8))
        init = model.init_params(jax.random.PRNGKey(7))
        mesh = jax.make_mesh((2, 2), ("data", "pipe"))
        packed, in_specs, local_fn, repack_fn = pack_for_shard_map(
            model, init, n_stages=2, tensor_axis=None)
        M, mb, seq = self.M, self.MB, self.SEQ

        def body(sp, tk, tg):
            # pipeline_step reduces loss/grads over data_axis itself
            loss, g = pipeline_step(model, local_fn(sp),
                                    tk.reshape(M, mb, seq),
                                    tg.reshape(M, mb, seq),
                                    pipe_axis="pipe", data_axis="data")
            return loss, repack_fn(g)

        grad_fn = shard_map(body, mesh=mesh,
                            in_specs=(in_specs, P("data"), P("data")),
                            out_specs=(P(), in_specs))
        opt = FusedAdam(lr=1e-2)
        mgr = CheckpointManager(str(ckpt_dir))
        guard = GuardedTrainStep(grad_fn=grad_fn, optimizer=opt,
                                 checkpoint=mgr, fault_injector=injector)
        rep = NamedSharding(mesh, P())
        packed = jax.device_put(packed, rep)
        return (guard, packed, jax.device_put(opt.init(packed), rep),
                jax.device_put(guard.init_state(), rep))

    def test_resume_is_bitwise(self, tmp_path):
        guard, params, opt_state, gstate = self._fresh(tmp_path / "a")
        ref_p, ref_o, _ = _drive(guard, self.N_STEPS, params, opt_state,
                                 gstate, self._gpt_batch)

        inj = FaultInjector([Fault(step=self.KILL_AT,
                                   kind="preempt_at_step")])
        guard, params, opt_state, gstate = self._fresh(tmp_path / "b",
                                                       injector=inj)
        with pytest.raises(Preemption):
            _drive(guard, self.N_STEPS, params, opt_state, gstate,
                   self._gpt_batch)

        guard2, params0, opt0, g0 = self._fresh(tmp_path / "b")
        restored, step = guard2.checkpoint.restore(
            guard2._template(params0, opt0, g0, None))
        assert step == self.KILL_AT
        got_p, got_o, _ = _drive(guard2, self.N_STEPS,
                                 restored["params"], restored["opt"],
                                 restored["guard"], self._gpt_batch,
                                 start=int(np.asarray(restored["step"])))
        _tree_equal(got_p, ref_p)
        _tree_equal(got_o, ref_o)


# -- serving-engine resilience ------------------------------------------------

def _engine(**kw):
    cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                    num_attention_heads=2, max_seq_len=16)
    model = GPTModel(cfg)
    params = model.init_params(jax.random.PRNGKey(0))
    return InferenceEngine(model, params, cache_dtype=jnp.float32, **kw)


class TestEngineResilience:
    def test_submit_validation(self):
        eng = _engine(max_slots=1)
        with pytest.raises(ValueError, match="prompt token"):
            eng.submit(Request(request_id=0, prompt=[1, 99]))   # >= vocab
        with pytest.raises(ValueError, match="prompt token"):
            eng.submit(Request(request_id=1, prompt=[1, 2.5]))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(Request(request_id=2, prompt=[1], max_new_tokens=0))
        with pytest.raises(ValueError, match="SamplingParams"):
            eng.submit(Request(request_id=3, prompt=[1],
                               sampling={"temperature": 1.0}))
        with pytest.raises(ValueError, match="timeout"):
            eng.submit(Request(request_id=4, prompt=[1], timeout=0.0))
        with pytest.raises(ValueError, match="eos_id"):
            eng.submit(Request(request_id=5, prompt=[1], eos_id=1.5))
        assert eng.queue_depth == 0      # nothing slipped through

    def test_bounded_queue_backpressure(self):
        eng = _engine(max_slots=1, max_queue=2)
        eng.submit(Request(request_id=0, prompt=[1], max_new_tokens=1))
        eng.submit(Request(request_id=1, prompt=[2], max_new_tokens=1))
        with pytest.raises(QueueFull):
            eng.submit(Request(request_id=2, prompt=[3],
                               max_new_tokens=1))
        eng.step()                        # drains one into a slot
        eng.submit(Request(request_id=2, prompt=[3], max_new_tokens=1))
        out = eng.run()
        assert sorted(r.request_id for r in out) == [0, 1, 2]
        with pytest.raises(ValueError, match="max_queue"):
            _engine(max_slots=1, max_queue=0)

    def test_poison_request_quarantined(self):
        """A sampling config that passes static validation but detonates
        at decode time finishes with reason="error"; its slot frees and
        every other request completes normally."""
        eng = _engine(max_slots=2)
        # top_k=2.5 passes SamplingParams' >0 check but breaks sampling
        eng.submit(Request(request_id=0, prompt=[1, 2],
                           max_new_tokens=3,
                           sampling=SamplingParams(temperature=1.0,
                                                   top_k=2.5)))
        eng.submit(Request(request_id=1, prompt=[3, 4], max_new_tokens=3))
        out = {r.request_id: r for r in eng.run()}
        assert out[0].finish_reason == "error"
        assert out[0].error is not None
        assert out[1].finish_reason == "length"
        assert len(out[1].tokens) == 3
        assert eng.cache.free_slots == 2         # the slot was freed
        assert eng.metrics.summary()["errors"] == 1

    def test_per_request_timeout_distinct_from_eviction(self):
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]

        eng = _engine(max_slots=3, clock=clock)
        eng.submit(Request(request_id=0, prompt=[1, 2],
                           max_new_tokens=100, timeout=25.0))
        eng.submit(Request(request_id=1, prompt=[3, 4],
                           max_new_tokens=100, deadline=40.0))
        eng.submit(Request(request_id=2, prompt=[5, 6], max_new_tokens=2))
        out = {r.request_id: r for r in eng.run(max_steps=200)}
        assert out[0].finish_reason == "timeout"
        assert 0 < len(out[0].tokens) < 100      # partial output kept
        assert out[1].finish_reason == "evicted"
        assert out[2].finish_reason == "length"
        s = eng.metrics.summary()
        assert s["timeouts"] == 1 and s["evicted"] == 1

    def test_queued_timeout_expires_empty(self):
        t = [0.0]

        def clock():
            t[0] += 1.0
            return t[0]

        eng = _engine(max_slots=1, clock=clock)
        eng.submit(Request(request_id=0, prompt=[1], max_new_tokens=50))
        eng.submit(Request(request_id=1, prompt=[2], max_new_tokens=50,
                           timeout=5.0))        # starved in the queue
        out = {r.request_id: r for r in eng.run(max_steps=200)}
        assert out[1].finish_reason == "timeout" and out[1].tokens == []
        assert eng.metrics.summary()["timeouts"] == 1
