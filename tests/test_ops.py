"""Fused op library tests (apex ``tests/L0/run_fused_layer_norm``,
``run_mlp``, contrib xentropy tests).  Every fused op is compared against a
plain-jnp reference (values and grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.normalization import (FusedLayerNorm, FusedRMSNorm,
                                    MixedFusedLayerNorm,
                                    fused_layer_norm_affine,
                                    fused_rms_norm_affine)
from apex_tpu.ops.softmax import (scaled_masked_softmax, scaled_softmax,
                                  scaled_upper_triang_masked_softmax)
from apex_tpu.ops.rope import (fused_apply_rotary_pos_emb, rope_freqs,
                               fused_apply_rotary_pos_emb_thd)
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss, \
    SoftmaxCrossEntropyLoss
from apex_tpu.mlp import MLP
from apex_tpu.fused_dense import FusedDense, FusedDenseGeluDense
from apex_tpu.utils import set_force_pallas


def ref_layer_norm(x, w, b, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * w + b


def ref_rms_norm(x, w, eps=1e-5):
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(ms + eps) * w


class TestFusedLayerNorm:
    @pytest.mark.parametrize("shape,hidden", [((4, 8, 256), 256),
                                              ((16, 100), 100),
                                              ((3, 384), 384)])
    def test_forward_matches_reference(self, rng, shape, hidden):
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        w = jnp.asarray(rng.rand(hidden).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(hidden).astype(np.float32) * 0.1)
        out = fused_layer_norm_affine(x, w, b, (hidden,))
        ref = ref_layer_norm(x, w, b)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("memory_efficient", [False, True])
    def test_grads_match_autodiff(self, rng, memory_efficient):
        hidden = 192
        x = jnp.asarray(rng.randn(8, hidden).astype(np.float32))
        w = jnp.asarray(rng.rand(hidden).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(hidden).astype(np.float32) * 0.1)

        def fused_loss(x, w, b):
            return jnp.sum(fused_layer_norm_affine(
                x, w, b, (hidden,), memory_efficient=memory_efficient) ** 2)

        def ref_loss(x, w, b):
            return jnp.sum(ref_layer_norm(x, w, b) ** 2)

        g1 = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
        g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
        for a, r in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=2e-4, atol=2e-4)

    def test_rms_norm(self, rng):
        hidden = 256
        x = jnp.asarray(rng.randn(6, hidden).astype(np.float32))
        w = jnp.asarray(rng.rand(hidden).astype(np.float32) + 0.5)
        out = fused_rms_norm_affine(x, w, (hidden,))
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ref_rms_norm(x, w)),
                                   rtol=1e-5, atol=1e-5)
        g1 = jax.grad(lambda x: jnp.sum(
            fused_rms_norm_affine(x, w, (hidden,)) ** 2))(x)
        g2 = jax.grad(lambda x: jnp.sum(ref_rms_norm(x, w) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=2e-4, atol=2e-4)

    def test_modules(self, rng):
        m = FusedLayerNorm(64)
        p = m.init_params()
        x = jnp.asarray(rng.randn(4, 64).astype(np.float32))
        y = m(p, x)
        assert y.shape == x.shape
        mm = MixedFusedLayerNorm(64)
        y2 = mm(mm.init_params(), x.astype(jnp.bfloat16))
        assert y2.dtype == jnp.bfloat16
        r = FusedRMSNorm(64)
        pr = r.init_params()
        assert "bias" not in pr
        assert r(pr, x).shape == x.shape

    def test_pallas_interpret_parity(self, rng):
        hidden = 256
        x = jnp.asarray(rng.randn(16, hidden).astype(np.float32))
        w = jnp.asarray(rng.rand(hidden).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(hidden).astype(np.float32) * 0.1)

        def loss(x, w, b, me):
            return jnp.sum(fused_layer_norm_affine(
                x, w, b, (hidden,), memory_efficient=me) ** 2)

        for me in (False, True):
            set_force_pallas(False)
            ref = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, me)
            refy = fused_layer_norm_affine(x, w, b, (hidden,),
                                           memory_efficient=me)
            set_force_pallas(True)
            try:
                got = jax.grad(loss, argnums=(0, 1, 2))(x, w, b, me)
                goty = fused_layer_norm_affine(x, w, b, (hidden,),
                                               memory_efficient=me)
            finally:
                set_force_pallas(None)
            np.testing.assert_allclose(np.asarray(goty), np.asarray(refy),
                                       rtol=1e-5, atol=1e-5)
            for a, r in zip(got, ref):
                np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                           rtol=1e-4, atol=1e-4)


class TestFusedSoftmax:
    def test_masked_matches_reference(self, rng):
        x = jnp.asarray(rng.randn(2, 4, 8, 16).astype(np.float32))
        mask = jnp.asarray(rng.rand(2, 1, 8, 16) > 0.7)
        out = scaled_masked_softmax(x, mask, scale=0.5)
        ref = jax.nn.softmax(jnp.where(mask, -10000.0, x * 0.5), axis=-1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_grad_uses_saved_output(self, rng):
        x = jnp.asarray(rng.randn(2, 4, 8, 16).astype(np.float32))
        g1 = jax.grad(lambda x: jnp.sum(scaled_softmax(x, 2.0) ** 2))(x)
        g2 = jax.grad(lambda x: jnp.sum(
            jax.nn.softmax(x * 2.0, axis=-1) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)

    def test_causal(self, rng):
        x = jnp.asarray(rng.randn(3, 8, 8).astype(np.float32))
        out = scaled_upper_triang_masked_softmax(x, 1.0)
        out = np.asarray(out)
        for q in range(8):
            assert np.allclose(out[:, q, q + 1:], 0.0, atol=1e-4)
            np.testing.assert_allclose(out[:, q, :q + 1].sum(-1), 1.0,
                                       rtol=1e-5)

    def test_causal_grad(self, rng):
        x = jnp.asarray(rng.randn(2, 6, 6).astype(np.float32))

        def ref(x):
            m = np.triu(np.ones((6, 6), bool), 1)
            return jax.nn.softmax(jnp.where(jnp.asarray(m), -10000.0, x),
                                  axis=-1)

        g1 = jax.grad(lambda x: jnp.sum(
            scaled_upper_triang_masked_softmax(x, 1.0) ** 2))(x)
        g2 = jax.grad(lambda x: jnp.sum(ref(x) ** 2))(x)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)


class TestRoPE:
    def test_matches_reference(self, rng):
        s, b, h, d = 12, 2, 4, 32
        t = jnp.asarray(rng.randn(s, b, h, d).astype(np.float32))
        freqs = rope_freqs(s, d)
        out = fused_apply_rotary_pos_emb(t, freqs)
        cos, sin = jnp.cos(freqs), jnp.sin(freqs)

        def rotate_half(u):
            u1, u2 = u[..., :d // 2], u[..., d // 2:]
            return jnp.concatenate([-u2, u1], axis=-1)

        ref = t * cos + rotate_half(t) * sin
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_norm_preserved(self, rng):
        # rotations preserve pairwise norms
        s, b, h, d = 8, 1, 2, 16
        t = jnp.asarray(rng.randn(s, b, h, d).astype(np.float32))
        out = fused_apply_rotary_pos_emb(t, rope_freqs(s, d))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out), axis=-1),
            np.linalg.norm(np.asarray(t), axis=-1), rtol=1e-4)

    def test_analytic_grad_matches_autodiff(self, rng):
        s, b, h, d = 6, 2, 2, 8
        t = jnp.asarray(rng.randn(s, b, h, d).astype(np.float32))
        freqs = rope_freqs(s, d)
        cos, sin = jnp.cos(freqs), jnp.sin(freqs)

        def rotate_half(u):
            u1, u2 = u[..., :d // 2], u[..., d // 2:]
            return jnp.concatenate([-u2, u1], axis=-1)

        g1 = jax.grad(lambda t: jnp.sum(
            fused_apply_rotary_pos_emb(t, freqs) ** 2))(t)
        g2 = jax.grad(lambda t: jnp.sum(
            (t * cos + rotate_half(t) * sin) ** 2))(t)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)

    def test_partial_rotary_dim(self, rng):
        s, b, h, d = 6, 1, 2, 32
        t = jnp.asarray(rng.randn(s, b, h, d).astype(np.float32))
        freqs = rope_freqs(s, 16)
        out = fused_apply_rotary_pos_emb(t, freqs)
        np.testing.assert_array_equal(np.asarray(out[..., 16:]),
                                      np.asarray(t[..., 16:]))

    def test_thd_restarts_positions(self, rng):
        d = 16
        freqs = rope_freqs(10, d)
        t = jnp.asarray(rng.randn(7, 2, d).astype(np.float32))
        cu = jnp.asarray([0, 3, 7], jnp.int32)
        out = fused_apply_rotary_pos_emb_thd(t, cu, freqs.reshape(10, 1, d))
        # second sequence's first token (index 3) uses position 0 → identity
        np.testing.assert_allclose(np.asarray(out[3]), np.asarray(t[3]),
                                   rtol=1e-5)


class TestXentropy:
    def test_matches_reference(self, rng):
        logits = jnp.asarray(rng.randn(32, 50).astype(np.float32) * 3)
        labels = jnp.asarray(rng.randint(0, 50, 32))
        loss = softmax_cross_entropy_loss(logits, labels)
        ref = -jax.nn.log_softmax(logits)[jnp.arange(32), labels]
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_label_smoothing(self, rng):
        logits = jnp.asarray(rng.randn(8, 10).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 10, 8))
        s = 0.1
        loss = softmax_cross_entropy_loss(logits, labels, s)
        logp = jax.nn.log_softmax(logits)
        nll = -logp[jnp.arange(8), labels]
        smooth = -jnp.mean(logp, axis=-1)
        ref = (1 - s) * nll + s * smooth
        np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_grad_matches(self, rng):
        logits = jnp.asarray(rng.randn(16, 20).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, 20, 16))
        for s in (0.0, 0.2):
            g1 = jax.grad(lambda l: jnp.sum(
                softmax_cross_entropy_loss(l, labels, s)))(logits)
            logp = jax.nn.log_softmax
            if s == 0.0:
                ref_fn = lambda l: jnp.sum(
                    -logp(l)[jnp.arange(16), labels])
            else:
                ref_fn = lambda l: jnp.sum(
                    (1 - s) * -logp(l)[jnp.arange(16), labels]
                    + s * -jnp.mean(logp(l), axis=-1))
            g2 = jax.grad(ref_fn)(logits)
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       rtol=1e-4, atol=1e-5)

    def test_ignore_index(self, rng):
        logits = jnp.asarray(rng.randn(4, 10).astype(np.float32))
        labels = jnp.asarray([1, -100, 3, -100])
        loss = softmax_cross_entropy_loss(logits, labels)
        assert float(loss[1]) == 0.0 and float(loss[3]) == 0.0
        g = jax.grad(lambda l: jnp.sum(
            softmax_cross_entropy_loss(l, labels)))(logits)
        np.testing.assert_array_equal(np.asarray(g[1]), 0.0)

    def test_half_to_float(self, rng):
        logits = jnp.asarray(rng.randn(4, 10)).astype(jnp.bfloat16)
        labels = jnp.asarray([1, 2, 3, 4])
        loss = SoftmaxCrossEntropyLoss.apply(logits, labels,
                                             half_to_float=True)
        assert loss.dtype == jnp.float32


class TestMLPAndFusedDense:
    def test_mlp_matches_reference(self, rng):
        m = MLP([16, 32, 8], activation="relu")
        params = m.init_params(jax.random.PRNGKey(0))
        x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
        y = m(params, x)
        h = jax.nn.relu(x @ params["weights"][0].T + params["biases"][0])
        ref = h @ params["weights"][1].T + params["biases"][1]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-6)

    def test_fused_dense_gelu_dense(self, rng):
        m = FusedDenseGeluDense(16, 64, 16)
        params = m.init_params(jax.random.PRNGKey(1))
        x = jnp.asarray(rng.randn(4, 16).astype(np.float32))
        y = m(params, x)
        h = jax.nn.gelu(x @ params["weight1"].T + params["bias1"],
                        approximate=True)
        ref = h @ params["weight2"].T + params["bias2"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                                   rtol=1e-6)

    def test_fused_dense_no_bias(self, rng):
        m = FusedDense(8, 8, bias=False)
        p = m.init_params(jax.random.PRNGKey(2))
        assert "bias" not in p
        x = jnp.ones((2, 8))
        np.testing.assert_allclose(np.asarray(m(p, x)),
                                   np.asarray(x @ p["weight"].T), rtol=1e-6)
