"""apex_tpu.serving.disagg: disaggregated prefill/decode serving with
a quantized paged KV cache.

The subsystem's correctness contract:

* ``export_kv()``/``adopt_kv()`` move a request between engines WITH
  its KV blocks, and the resumed stream is TOKEN-BITWISE the
  uninterrupted single-engine run — greedy and seeded sampling, f32
  and int8 storage alike (paged attention only ever gathers block
  storage, and the payload is a literal copy of it);
* the int8 scale-per-block cache stays within a pinned numeric
  tolerance of the f32 cache and agrees with it greedily on the CI
  configs; round-trip error is bounded by half a quantization step;
* prefix-shared blocks survive quantization: published trie blocks are
  never requantized (COW copies scales), so sharers decode bitwise;
* the DisaggregatedFleet serves token-bitwise vs a single-pool
  reference — including a prefill replica killed mid-handoff (death
  migration re-prefills the parked work) and a lost channel transfer
  (re-prefill fallback on the decode pool) — with an exactly-once
  response ledger and int8 handoffs under 0.3x the f32 bytes;
* the per-pool capacity controller sizes prefill vs decode on
  TTFT-burn vs TPOT-burn and never flaps (``audit() == []``);
* the degradation ladder acts on the DECODE pool's burn in a
  disaggregated fleet, not fleet-wide occupancy.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.inference import QueueFull, Request, SamplingParams
from apex_tpu.models.gpt import GPTConfig, GPTModel
from apex_tpu.observability import FleetCollector, Tracer
from apex_tpu.observability.slo import SLOMonitor, SLOTarget
from apex_tpu.ops.flash_attention import (dequantize_kv_blocks,
                                          quantize_kv_blocks)
from apex_tpu.resilience import Fault, FaultInjector, PoolCapacityController
from apex_tpu.serving import (DegradationLadder, DisaggregatedFleet,
                              KvChannel, PagedInferenceEngine,
                              PagedKVCache, QuantizedPagedKVCache,
                              ServingFault, ServingFaultInjector,
                              VirtualClock)
from apex_tpu.utils.profiling import ServingMetrics

# int8 scale-per-block decode must stay this close to the f32 cache on
# the CI config (measured worst |dlogits| is ~5e-4; 10x margin)
QUANT_LOGITS_TOL = 5e-3


def tiny_cfg(**kw):
    base = dict(vocab_size=32, hidden_size=16, num_layers=2,
                num_attention_heads=2, max_seq_len=32)
    base.update(kw)
    return GPTConfig(**base)


@pytest.fixture(scope="module")
def tiny():
    model = GPTModel(tiny_cfg())
    return model, model.init_params(jax.random.PRNGKey(0))


def _clone(req: Request) -> Request:
    return dataclasses.replace(req)


def _mixed_requests():
    return [
        Request(0, [1, 2, 3, 4, 5], max_new_tokens=6),
        Request(1, [1, 2, 3, 9], max_new_tokens=5, seed=7,
                sampling=SamplingParams(temperature=0.8, top_k=5)),
        Request(2, [1, 2, 3, 4, 5, 6, 7], max_new_tokens=4, seed=3,
                sampling=SamplingParams(temperature=1.1, top_p=0.9)),
        Request(3, [4, 4, 4], max_new_tokens=5, seed=11),
    ]


def _engine(model, params, clock, **kw):
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("chunked_prefill", True)
    return PagedInferenceEngine(model, params, max_slots=4, block_size=4,
                                metrics=ServingMetrics(clock),
                                clock=clock, **kw)


def _drain(engine, clock, dt=0.01):
    while engine.step():
        clock.advance(dt)
    return {r.request_id: (r.tokens, r.finish_reason)
            for r in engine.completed}


def _reference(model, params, reqs, **kw):
    clock = VirtualClock()
    ref = _engine(model, params, clock, **kw)
    for r in reqs:
        ref.submit(_clone(r))
    return _drain(ref, clock)


def _prefill_all(pf, clock, n, dt=0.01):
    """Step a prefill_only engine until n handoffs are parked.

    (``step()`` keeps returning True while parked slots occupy
    ``_active`` — termination is the handoff count, not idleness.)
    """
    for _ in range(200):
        if len(pf.handoffs_ready()) >= n:
            return pf.handoffs_ready()
        pf.step()
        clock.advance(dt)
    raise AssertionError("prefill never parked %d handoffs" % n)


def _disagg(model, params, *, n_prefill=2, n_decode=2, quant=None,
            **fleet_kw):
    clock = VirtualClock()
    pf = [_engine(model, params, clock, kv_quant=quant,
                  prefill_only=True) for _ in range(n_prefill)]
    dc = [_engine(model, params, clock, kv_quant=quant)
          for _ in range(n_decode)]
    fleet = DisaggregatedFleet(pf, dc, clock=clock, **fleet_kw)
    return fleet, clock


def _run_fleet(fleet, clock, max_steps=400, dt=0.01):
    for _ in range(max_steps):
        busy = fleet.step()
        clock.advance(dt)
        if not busy and fleet.pending == 0:
            break
    return {r.request_id: (r.tokens, r.finish_reason)
            for r in fleet.completed}


# -- quantized cache ---------------------------------------------------------

class TestQuantizedCache:
    def test_round_trip_error_bound(self):
        """|x - dequant(quant(x))| <= scale/2 = amax/254 per
        (block, layer, k/v, head) group — the textbook symmetric-int8
        bound, asserted exactly."""
        rng = np.random.RandomState(0)
        blocks = jnp.asarray(rng.randn(5, 2, 2, 8, 3, 16) * 3.0,
                             jnp.float32)
        q8, scales = quantize_kv_blocks(blocks)
        deq = dequantize_kv_blocks(q8, scales)
        err = jnp.abs(deq - blocks)
        bound = scales[..., None, :, None] * 0.5 + 1e-7
        assert bool(jnp.all(err <= bound))
        amax = jnp.max(jnp.abs(blocks), axis=(-3, -1))
        np.testing.assert_allclose(np.asarray(scales),
                                   np.asarray(amax) / 127.0, rtol=1e-6)

    def test_all_zero_block_is_exact(self):
        q8, scales = quantize_kv_blocks(jnp.zeros((2, 1, 2, 4, 2, 8)))
        assert bool(jnp.all(scales == 1.0))      # never divide by zero
        assert bool(jnp.all(dequantize_kv_blocks(q8, scales) == 0.0))

    def test_pool_compression_and_zero_on_alloc(self):
        f32 = PagedKVCache(8, 4, layers=2, kv_heads=2, head_dim=16,
                           dtype=jnp.float32)
        q = QuantizedPagedKVCache(8, 4, layers=2, kv_heads=2,
                                  head_dim=16, dtype=jnp.float32)
        assert q.kind == "paged_int8" and f32.kind == "paged"
        # int8 data + f32 scale per (layer, k/v, head): well under 0.3x
        assert q.block_bytes < 0.3 * f32.block_bytes
        # zero-on-alloc: a reused block comes back clean
        q.data = q.data.at[:].set(7)
        q.scales = q.scales.at[:].set(9.0)
        seq = q.acquire([1, 2, 3, 4, 5])
        for bid in seq.block_ids:
            assert bool(jnp.all(q.data[bid] == 0))
            assert bool(jnp.all(q.scales[bid] == 1.0))

    def test_export_import_blocks_bitwise(self):
        src = QuantizedPagedKVCache(8, 4, layers=2, kv_heads=2,
                                    head_dim=8)
        dst = QuantizedPagedKVCache(8, 4, layers=2, kv_heads=2,
                                    head_dim=8)
        rng = np.random.RandomState(1)
        src.data = jnp.asarray(rng.randint(-127, 128, src.data.shape),
                               jnp.int8)
        src.scales = jnp.asarray(rng.rand(*src.scales.shape),
                                 jnp.float32)
        payload = src.export_blocks([2, 5])
        dst.import_blocks([1, 3], payload)
        assert bool(jnp.all(dst.data[1] == src.data[2]))
        assert bool(jnp.all(dst.data[3] == src.data[5]))
        assert bool(jnp.all(dst.scales[1] == src.scales[2]))
        # a payload round-trips through host bytes unchanged
        assert payload["data"].dtype == np.int8

    def test_quant_requires_chunked_prefill_and_no_spec(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        with pytest.raises(ValueError, match="chunked"):
            _engine(model, params, clock, kv_quant="int8",
                    chunked_prefill=False)
        with pytest.raises(ValueError, match="kv_quant"):
            PagedInferenceEngine(model, params, kv_quant="fp4")


# -- quantized decode quality ------------------------------------------------

class TestQuantDecodeQuality:
    def test_logits_within_pinned_tolerance(self, tiny):
        """The quantized chunk path's logits vs the f32 paged path,
        token-position by token-position, within QUANT_LOGITS_TOL."""
        model, params = tiny
        rng = np.random.RandomState(0)
        toks = rng.randint(1, 32, (1, 16)).astype(np.int32)
        bs = 4
        f = PagedKVCache(16, bs, layers=2, kv_heads=2, head_dim=8,
                         dtype=jnp.float32)
        q = QuantizedPagedKVCache(16, bs, layers=2, kv_heads=2,
                                  head_dim=8, dtype=jnp.float32)
        sf, sq = f.acquire(list(toks[0])), q.acquire(list(toks[0]))
        pos = np.arange(16, dtype=np.int32)[None]
        wo = (np.arange(16, dtype=np.int32) % bs)[None]
        wb_f = np.asarray([sf.block_ids[p // bs] for p in range(16)],
                          np.int32)[None]
        wb_q = np.asarray([sq.block_ids[p // bs] for p in range(16)],
                          np.int32)[None]
        lf, _ = model.decode_chunk(
            params, jnp.asarray(toks), f.data,
            jnp.asarray(f.table_row(sf, 8)[None]), jnp.asarray(pos),
            jnp.asarray(wb_f), jnp.asarray(wo))
        lq, _, _ = model.decode_chunk_quant(
            params, jnp.asarray(toks), q.data, q.scales,
            jnp.asarray(q.table_row(sq, 8)[None]), jnp.asarray(pos),
            jnp.asarray(wb_q), jnp.asarray(wo))
        err = float(jnp.max(jnp.abs(lf.astype(jnp.float32)
                                    - lq.astype(jnp.float32))))
        assert err <= QUANT_LOGITS_TOL

    def test_greedy_agreement_vs_f32_engine(self, tiny):
        """Greedy streams from the int8 engine match the f32 engine on
        the CI config (the acceptance gate for quantized serving)."""
        model, params = tiny
        reqs = [Request(i, [1 + i, 2, 3 + i, 4], max_new_tokens=6)
                for i in range(4)]
        want = _reference(model, params, reqs)
        got = _reference(model, params, reqs, kv_quant="int8")
        assert got == want

    def test_quant_stream_is_deterministic(self, tiny):
        """Same workload, two independent int8 engines: identical
        streams (zero-on-alloc makes requantization reproducible
        across allocation histories)."""
        model, params = tiny
        reqs = _mixed_requests()
        a = _reference(model, params, reqs, kv_quant="int8")
        b = _reference(model, params, reqs, kv_quant="int8")
        assert a == b


# -- engine handoff primitives -----------------------------------------------

class TestHandoffPrimitives:
    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_export_adopt_kv_resumes_bitwise(self, tiny, quant):
        """Prefill on a prefill_only engine, ship KV, decode elsewhere:
        bitwise the single-engine streams, greedy and seeded."""
        model, params = tiny
        reqs = _mixed_requests()
        want = _reference(model, params, reqs, kv_quant=quant)
        clock = VirtualClock()
        pf = _engine(model, params, clock, kv_quant=quant,
                     prefill_only=True)
        dc = _engine(model, params, clock, kv_quant=quant)
        for r in reqs:
            pf.submit(_clone(r))
        ready = _prefill_all(pf, clock, len(reqs))
        assert len(ready) == len(reqs)
        for _slot, rid in ready:
            handoff = pf.export_kv(rid)
            assert handoff.kv_len == len(handoff.kv_tokens)
            dc.adopt_kv(handoff)
        assert pf.handoffs_ready() == [] and pf.active_requests == 0
        got = _drain(dc, clock)
        assert got == want

    def test_export_kv_validation(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        pf = _engine(model, params, clock, prefill_only=True)
        with pytest.raises(KeyError):
            pf.export_kv("nope")
        # mid-prefill: KV incomplete, must re-prefill instead
        pf.submit(Request(0, list(range(1, 21)), max_new_tokens=2))
        pf.step()           # first chunk only (token budget)
        if 0 in pf._prefilling:
            with pytest.raises(ValueError, match="mid-prefill"):
                pf.export_kv(0)

    def test_adopt_kv_rejects_mismatches(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        pf = _engine(model, params, clock, prefill_only=True)
        pf.submit(Request(0, [1, 2, 3, 4, 5], max_new_tokens=4))
        _prefill_all(pf, clock, 1)
        handoff = pf.export_kv(0)
        # kind mismatch: bf16->int8 install is not bitwise-possible
        quant = _engine(model, params, clock, kv_quant="int8")
        with pytest.raises(ValueError, match="kind"):
            quant.adopt_kv(handoff)
        # block geometry mismatch
        other = PagedInferenceEngine(
            model, params, max_slots=2, block_size=8,
            metrics=ServingMetrics(clock), clock=clock,
            chunked_prefill=True, cache_dtype=jnp.float32)
        with pytest.raises(ValueError, match="block_size"):
            other.adopt_kv(handoff)
        # the handoff is still installable where the tags match
        dc = _engine(model, params, clock)
        dc.adopt_kv(handoff)
        assert dc.active_requests == 1

    def test_adopt_kv_queuefull_when_no_slot(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        pf = _engine(model, params, clock, prefill_only=True)
        for i in range(3):
            pf.submit(Request(i, [1 + i, 2, 3], max_new_tokens=3))
        _prefill_all(pf, clock, 3)
        dc = PagedInferenceEngine(
            model, params, max_slots=2, block_size=4,
            metrics=ServingMetrics(clock), clock=clock,
            chunked_prefill=True, cache_dtype=jnp.float32)
        handoffs = [pf.export_kv(rid) for _, rid in pf.handoffs_ready()]
        dc.adopt_kv(handoffs[0])
        dc.adopt_kv(handoffs[1])
        with pytest.raises(QueueFull):
            dc.adopt_kv(handoffs[2])
        # the handoff is host state — still installable after a drain
        _drain(dc, clock)
        dc.adopt_kv(handoffs[2])
        got = _drain(dc, clock)
        assert 2 in got

    def test_prefix_shared_blocks_survive_quantization(self, tiny):
        """Two requests sharing a block-aligned prefix on an int8 pool:
        the trie shares quantized blocks (never requantized once
        published) and both streams match the unshared runs."""
        model, params = tiny
        prefix = [5, 6, 7, 8]                    # exactly one block
        reqs = [Request(0, prefix + [1, 2], max_new_tokens=4),
                Request(1, prefix + [3], max_new_tokens=4)]
        want = _reference(model, params, reqs, kv_quant="int8")
        clock = VirtualClock()
        pf = _engine(model, params, clock, kv_quant="int8",
                     prefill_only=True)
        dc = _engine(model, params, clock, kv_quant="int8")
        # sequential: request 0's published prefix is live in the trie
        # (on BOTH pools) when request 1 arrives
        for n, r in enumerate(reqs):
            pf.submit(_clone(r))
            _prefill_all(pf, clock, n + 1)
        for _slot, rid in pf.handoffs_ready():
            dc.adopt_kv(pf.export_kv(rid))
        assert dc.pool.prefix_hit_tokens >= len(prefix)  # shared install
        got = _drain(dc, clock)
        assert got == want


# -- the disaggregated fleet -------------------------------------------------

class TestDisaggregatedFleet:
    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_fleet_matches_single_pool_reference(self, tiny, quant):
        model, params = tiny
        reqs = _mixed_requests()
        want = _reference(model, params, reqs, kv_quant=quant)
        fleet, clock = _disagg(model, params, quant=quant)
        for r in reqs:
            fleet.submit(_clone(r))
        got = _run_fleet(fleet, clock)
        assert got == want
        assert fleet.pending == 0
        assert fleet.handoffs == len(reqs) and fleet.fallbacks == 0
        assert fleet.duplicate_responses == 0

    def test_pool_validation(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        ordinary = _engine(model, params, clock)
        parked = _engine(model, params, clock, prefill_only=True)
        with pytest.raises(ValueError, match="prefill_only"):
            DisaggregatedFleet([ordinary], [ordinary], clock=clock)
        with pytest.raises(ValueError, match="decode-pool"):
            DisaggregatedFleet([parked], [parked], clock=clock)

    def test_prefill_replica_killed_mid_handoff(self, tiny):
        """Kill a prefill replica while it still holds parked and
        mid-prefill work: death migration re-prefills on the peer, the
        handoff ships from there, and every stream is bitwise the
        single-pool run — exactly once."""
        model, params = tiny
        reqs = _mixed_requests()
        want = _reference(model, params, reqs)
        inj = ServingFaultInjector([
            ServingFault(2, 0, "replica_crash", duration=10 ** 6)])
        fleet, clock = _disagg(model, params, prefill_injector=inj,
                               prefill_kw=dict(suspect_after=1,
                                               dead_after=2),
                               handoff_retry_ticks=4)
        for r in reqs:
            fleet.submit(_clone(r))
        got = _run_fleet(fleet, clock)
        assert got == want
        assert fleet.pending == 0 and fleet.duplicate_responses == 0
        assert inj.log       # the crash actually fired
        # nothing was answered twice, nothing lost
        assert sorted(got) == sorted(r.request_id for r in reqs)

    def test_lost_handoff_falls_back_to_reprefill(self, tiny):
        """Exhaust the channel's retries on the first transfer: the
        request re-prefills on the decode pool — slower, still
        bitwise, never lost."""
        model, params = tiny
        reqs = _mixed_requests()
        want = _reference(model, params, reqs)
        ch = KvChannel(fault_injector=FaultInjector(
            [Fault(step=s, kind="dcn_fault") for s in range(1, 40)]),
            max_retries=0)
        fleet, clock = _disagg(model, params, channel=ch)
        for r in reqs:
            fleet.submit(_clone(r))
        got = _run_fleet(fleet, clock)
        assert got == want
        assert fleet.fallbacks >= 1
        assert fleet.fallbacks + fleet.handoffs == len(reqs)
        assert ch.lost_handoffs == fleet.fallbacks

    def test_int8_handoff_bytes_under_030x_f32(self, tiny):
        """The series the CI leg gates: int8 handoffs ship < 0.3x the
        f32 bytes for the same workload."""
        model, params = tiny
        reqs = _mixed_requests()
        sizes = {}
        for quant in (None, "int8"):
            fleet, clock = _disagg(model, params, quant=quant)
            for r in reqs:
                fleet.submit(_clone(r))
            _run_fleet(fleet, clock)
            assert fleet.handoffs == len(reqs)
            sizes[quant] = fleet.channel.handoff_bytes
        assert sizes["int8"] < 0.30 * sizes[None]

    def test_flow_chain_stitches_across_pools(self, tiny):
        """One Perfetto arrow chain per request: prefill hop →
        kv_handoff → decode hop, continuity-checked over the merged
        timeline."""
        from apex_tpu.observability import FlightRecorder

        model, params = tiny
        clock = VirtualClock()
        tracers = {"p0": Tracer(clock=clock, id_tag="p0"),
                   "d0": Tracer(clock=clock, id_tag="d0"),
                   "router": Tracer(clock=clock, id_tag="router")}
        pf = [_engine(model, params, clock, prefill_only=True,
                      tracer=tracers["p0"])]
        dc = [_engine(model, params, clock, tracer=tracers["d0"])]
        fleet = DisaggregatedFleet(pf, dc, clock=clock,
                                   tracer=tracers["router"],
                                   recorder=FlightRecorder(clock=clock))
        for r in _mixed_requests():
            fleet.submit(_clone(r))
        _run_fleet(fleet, clock)
        fc = FleetCollector()
        for name, tr in tracers.items():
            fc.add_replica(name, tracer=tr)
        cont = fc.continuity()
        assert not cont["broken"] and not cont["orphans"]
        assert len(cont["complete"]) == 4
        for tid, chain in cont["chains"].items():
            assert "kv_handoff" in chain["phases"]
            # the chain spans both pools
            assert {"p0", "d0"} <= set(chain["replicas"])


# -- per-pool capacity -------------------------------------------------------

def _slo_engine(model, params, clock, **kw):
    slo = SLOMonitor(
        [SLOTarget("ttft", 0.5, objective=0.9),
         SLOTarget("token_latency", 0.5, objective=0.9)], clock=clock)
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("chunked_prefill", True)
    return PagedInferenceEngine(model, params, max_slots=4, block_size=4,
                                metrics=ServingMetrics(clock, slo=slo),
                                clock=clock, **kw)


class TestPoolCapacity:
    def _stack(self, tiny, n_prefill=3, n_decode=2, **ctl_kw):
        model, params = tiny
        clock = VirtualClock()
        pf = [_slo_engine(model, params, clock, prefill_only=True)
              for _ in range(n_prefill)]
        dc = [_slo_engine(model, params, clock)
              for _ in range(n_decode)]
        fleet = DisaggregatedFleet(pf, dc, clock=clock)
        ctl_kw.setdefault("burn_high", 2.0)
        ctl_kw.setdefault("burn_low", 0.5)
        ctl_kw.setdefault("confirm_ticks", 2)
        ctl_kw.setdefault("cooldown_s", 1.0)
        ctl = PoolCapacityController(
            {"prefill": fleet.prefill, "decode": fleet.decode},
            lambda pool: _slo_engine(model, params, clock,
                                     prefill_only=(pool == "prefill")),
            clock=clock, **ctl_kw)
        return fleet, ctl, clock

    def test_manual_shift_two_phase_and_audit_clean(self, tiny):
        fleet, ctl, clock = self._stack(tiny)
        assert ctl.split == {"prefill": 3, "decode": 2}
        ctl.request_shift("to_decode")
        for _ in range(60):
            fleet.step()
            ctl.tick()
            clock.advance(0.05)
            if ctl.stats["shifts"] == 1 and not ctl.shifting:
                break
        assert ctl.split == {"prefill": 2, "decode": 3}
        assert ctl.audit() == []
        # the reshaped fleet still serves, with handoffs intact
        for r in _mixed_requests():
            fleet.submit(_clone(r))
        got = _run_fleet(fleet, clock)
        assert len(got) == 4 and fleet.pending == 0

    def test_burn_driven_shift_requires_confirmation(self, tiny):
        """One hot tick must not move a chip; confirm_ticks of
        sustained TPOT burn (with a calm donor) must."""
        fleet, ctl, clock = self._stack(tiny)
        dec = [e for _, e in fleet.decode._live()]
        # one hot tick: below confirm_ticks, no shift
        for e in dec:
            for _ in range(20):
                e.metrics.slo.observe("token_latency", 5.0)
        ctl.tick()
        assert ctl.stats["shifts"] == 0 and not ctl.shifting
        # sustained burn: the controller commits exactly one shift
        for _ in range(30):
            for e in dec:
                for _ in range(5):
                    e.metrics.slo.observe("token_latency", 5.0)
            fleet.step()
            ctl.tick()
            clock.advance(0.05)
            if ctl.stats["shifts"] == 1 and not ctl.shifting:
                break
        assert ctl.stats["shifts"] == 1
        assert ctl.split == {"prefill": 2, "decode": 3}
        assert ctl.audit() == []

    def test_shifts_never_flap(self, tiny):
        """A long oscillating-burn run: every committed shift started
        outside the hysteresis band and after cooldown —
        ``audit() == []`` — and the min-replica floor holds."""
        fleet, ctl, clock = self._stack(tiny, cooldown_s=0.5)
        rng = np.random.RandomState(0)
        for t in range(120):
            hot = (t // 20) % 2 == 0             # flips every 20 ticks
            pool = fleet.decode if hot else fleet.prefill
            metric = "token_latency" if hot else "ttft"
            for _, e in pool._live():
                for _ in range(4):
                    e.metrics.slo.observe(
                        metric, 5.0 + float(rng.rand()))
            fleet.step()
            ctl.tick()
            clock.advance(0.05)
        assert ctl.audit() == []
        split = ctl.split
        assert split["prefill"] >= 1 and split["decode"] >= 1
        assert split["prefill"] + split["decode"] == 5

    def test_floor_blocks_donation(self, tiny):
        fleet, ctl, clock = self._stack(tiny, n_prefill=1, n_decode=1,
                                        min_replicas=1)
        ctl.request_shift("to_decode")
        for _ in range(10):
            fleet.step()
            ctl.tick()
            clock.advance(0.05)
        # the only prefill replica is the floor: nothing moved
        assert ctl.split == {"prefill": 1, "decode": 1}
        assert ctl.stats["shifts"] == 0

    def test_validation(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        fleet, ctl, _ = self._stack(tiny)
        with pytest.raises(ValueError):
            PoolCapacityController(
                {"prefill": fleet.prefill}, lambda p: None, clock=clock)
        with pytest.raises(ValueError):
            PoolCapacityController(
                {"a": fleet.prefill, "b": fleet.decode},
                lambda p: None, burn_high=1.0, burn_low=2.0, clock=clock)
        with pytest.raises(ValueError, match="to_"):
            ctl.request_shift("decode")


# -- the ladder's per-pool burn source ---------------------------------------

class TestLadderBurnSource:
    def test_ladder_follows_decode_pool_not_fleet_max(self, tiny):
        """Prefill pool burning TTFT alone must NOT trip the ladder
        (its L2 actions flush the DECODE cache); decode-pool TPOT burn
        must."""
        model, params = tiny
        clock = VirtualClock()
        pf = [_slo_engine(model, params, clock, prefill_only=True)]
        dc = [_slo_engine(model, params, clock)]
        ladder = DegradationLadder(thresholds=(1.0, 2.0, 4.0),
                                   step_down_s=0.5)
        fleet = DisaggregatedFleet(pf, dc, clock=clock, ladder=ladder)
        assert ladder.burn_source is not None    # auto-wired to decode
        # prefill-pool burn only: ladder stays at 0
        for _ in range(40):
            pf[0].metrics.slo.observe("ttft", 5.0)
        fleet.step()
        assert ladder.level == 0
        # decode-pool burn: ladder escalates
        for _ in range(40):
            dc[0].metrics.slo.observe("token_latency", 5.0)
        fleet.step()
        assert ladder.level > 0

    def test_explicit_burn_source_wins(self, tiny):
        model, params = tiny
        clock = VirtualClock()
        ladder = DegradationLadder(thresholds=(1.0, 2.0, 4.0),
                                   burn_source=lambda: 100.0)
        pf = [_slo_engine(model, params, clock, prefill_only=True)]
        dc = [_slo_engine(model, params, clock)]
        DisaggregatedFleet(pf, dc, clock=clock, ladder=ladder)
        assert ladder.burn_source() == 100.0     # not overwritten
