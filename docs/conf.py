# Sphinx configuration for apex_tpu (reference: apex docs/source/conf.py,
# a standard sphinx + autodoc project over .rst sources; here the sources
# are MyST markdown and the API pages are autodoc-generated).
#
# Build:  sphinx-build -b html docs docs/_build/html
# The environment this repo develops in has no sphinx wheel; the build is
# exercised by tests/test_docs.py when sphinx is importable, and
# docs/build.py provides a dependency-free fallback renderer.

import os
import sys

sys.path.insert(0, os.path.abspath(".."))

project = "apex-tpu"
author = "apex-tpu contributors"
from apex_tpu._version import __version__ as release  # single source

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
    "myst_parser",
]

source_suffix = {".rst": "restructuredtext", ".md": "markdown"}
master_doc = "index"
exclude_patterns = ["_build"]

autodoc_member_order = "bysource"
autodoc_typehints = "description"

# keep the import side effects light: the library lazy-imports heavy
# subpackages, but autodoc still needs jax importable
autodoc_mock_imports = []

html_theme = "alabaster"
