"""Dependency-free docs builder — the fallback for environments without a
sphinx wheel (like the TPU image this repo develops in).

``python docs/build.py [outdir]`` renders:

* every ``docs/source/*.md`` page into a minimal HTML shell (markdown is
  embedded verbatim in a ``<pre>``-free readable layout — headings,
  code fences and lists pass through as text; the goal is greppable,
  linkable API/user docs without a renderer dependency), and
* one generated API page per documented package
  (``apex_tpu.{amp,optimizers,transformer,parallel}``) from live
  introspection: public classes/functions with signatures and
  docstrings — the same inventory sphinx autodoc would emit.

When sphinx IS available, ``sphinx-build -b html docs docs/_build/html``
uses ``docs/conf.py`` instead; ``tests/test_docs.py`` exercises
whichever path the environment supports.
"""

from __future__ import annotations

import html
import inspect
import pathlib
import sys

# runnable from anywhere: the repo root (one level up) must be importable
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

PACKAGES = ["apex_tpu.amp", "apex_tpu.optimizers", "apex_tpu.transformer",
            "apex_tpu.parallel", "apex_tpu.inference",
            "apex_tpu.serving", "apex_tpu.resilience",
            "apex_tpu.observability"]

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>{title}</title>
<style>
 body {{ font-family: sans-serif; max-width: 56rem; margin: 2rem auto;
        line-height: 1.5; padding: 0 1rem; }}
 pre, code {{ background: #f6f8fa; }}
 pre {{ padding: .75rem; overflow-x: auto; }}
 h2 {{ border-bottom: 1px solid #ddd; padding-bottom: .2rem; }}
 .sig {{ background: #f6f8fa; padding: .4rem .6rem; display: block;
        font-family: monospace; white-space: pre-wrap; }}
</style></head><body>
<p><a href="index.html">index</a></p>
{body}
</body></html>
"""


def _md_page(path: pathlib.Path) -> str:
    text = html.escape(path.read_text())
    return f"<h1>{html.escape(path.stem)}</h1>\n<pre>{text}</pre>"


def _doc(obj) -> str:
    d = inspect.getdoc(obj) or ""
    return f"<pre>{html.escape(d)}</pre>" if d else ""


def _sig(obj) -> str:
    try:
        return html.escape(str(inspect.signature(obj)))
    except (TypeError, ValueError):
        return "(...)"


def _api_page(modname: str) -> str:
    import importlib

    mod = importlib.import_module(modname)
    names = getattr(mod, "__all__", None) or [
        n for n in sorted(vars(mod)) if not n.startswith("_")]
    parts = [f"<h1>{modname} API</h1>", _doc(mod)]
    for name in names:
        try:
            obj = getattr(mod, name)
        except AttributeError:
            continue
        if inspect.isclass(obj):
            parts.append(f"<h2>class {name}</h2>"
                         f"<span class='sig'>{name}{_sig(obj)}</span>"
                         f"{_doc(obj)}")
            for mname, meth in sorted(vars(obj).items()):
                if mname.startswith("_") or not callable(meth):
                    continue
                parts.append(f"<h3>{name}.{mname}</h3>"
                             f"<span class='sig'>{mname}{_sig(meth)}</span>"
                             f"{_doc(meth)}")
        elif callable(obj):
            parts.append(f"<h2>{name}</h2>"
                         f"<span class='sig'>{name}{_sig(obj)}</span>"
                         f"{_doc(obj)}")
        else:
            parts.append(f"<h2>{name}</h2><p>constant "
                         f"<code>{html.escape(repr(obj))}</code></p>")
    return "\n".join(parts)


def build(outdir: str = "docs/_build/fallback") -> list:
    root = pathlib.Path(__file__).resolve().parent
    out = pathlib.Path(outdir)
    out.mkdir(parents=True, exist_ok=True)
    written = []

    links = []
    for md in sorted((root / "source").glob("*.md")):
        # the generated site index owns index.html; the user index page
        # renders as overview.html so neither clobbers the other
        stem = "overview" if md.stem == "index" else md.stem
        page = out / f"{stem}.html"
        page.write_text(_PAGE.format(title=stem, body=_md_page(md)))
        written.append(page)
        links.append(f'<li><a href="{stem}.html">{stem}</a></li>')
    for pkg in PACKAGES:
        slug = pkg.replace(".", "_")
        page = out / f"{slug}.html"
        page.write_text(_PAGE.format(title=pkg, body=_api_page(pkg)))
        written.append(page)
        links.append(f'<li><a href="{slug}.html">{pkg} API</a></li>')

    index = out / "index.html"
    index.write_text(_PAGE.format(
        title="apex-tpu docs",
        body="<h1>apex-tpu documentation</h1><ul>" + "\n".join(links)
             + "</ul>"))
    written.append(index)
    return written


if __name__ == "__main__":
    pages = build(*sys.argv[1:2])
    print(f"wrote {len(pages)} pages -> {pages[-1].parent}")
