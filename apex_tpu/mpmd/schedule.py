"""Host-driven MPMD pipeline schedules and their event-driven simulator.

The ring engine (:mod:`apex_tpu.transformer.pipeline_parallel.ring`)
compiles the whole 1F1B schedule into one ``lax.scan`` of uniform SPMD
ticks — every stage advances in lockstep, which is exactly right when
the stage-to-stage hop is an ICI ``ppermute``.  Across pods the hop is
a DCN transfer that is orders of magnitude slower than a tick, and a
lockstep schedule would expose every hop on the critical path.  The
MPMD engine therefore runs each stage as its own compiled program and
the *host* issues jobs in an explicit total order; this module owns
that order.

Two schedules:

* :func:`schedule_1f1b` — the classic schedule (stage ``s`` warms up
  with ``min(S-1-s, M)`` forwards, then alternates 1 forward / 1
  backward, then drains).  With *blocking* sends (the SPMD analogue:
  the sender stalls while the hop is in flight) every cross-pod edge
  sits on the critical path.
* :func:`schedule_dcn_hiding` — the same alternation with
  ``extra_inflight`` additional warmup forwards per stage, run with
  *asynchronous* sends.  The extra in-flight microbatches buffer the
  slow hop: a stage keeps computing while the DCN transfer drains,
  which is the near-zero-bubble regime (arXiv 2412.14374's
  pre-shifted-buffer observation, executed host-side).

:func:`simulate` prices a schedule against per-stage compute times and
per-edge link times and returns makespan / bubble fraction / exposed
vs. hidden link seconds per link class — the objective
``tools/autotune.py`` minimises when enumerating two-tier plans, and
what ``bench.py::bench_mpmd`` records.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

__all__ = [
    "Op", "stage_ops_1f1b", "merge_stage_ops", "schedule_1f1b",
    "schedule_dcn_hiding", "validate_order", "edge_link_classes",
    "simulate", "SCHEDULES",
]


class Op(NamedTuple):
    """One unit of stage work: run microbatch ``mb`` through stage
    ``stage``'s forward (``kind == "fwd"``) or backward
    (``kind == "bwd"``) program."""
    stage: int
    kind: str
    mb: int


def stage_ops_1f1b(n_stages: int, n_microbatches: int, *,
                   extra_inflight: int = 0) -> List[List[Op]]:
    """Per-stage op lists: warmup ``min(S-1-s+extra_inflight, M)``
    forwards, then alternate 1 forward / 1 backward, then drain
    backwards.  ``extra_inflight == 0`` is classic 1F1B."""
    S, M = int(n_stages), int(n_microbatches)
    if S < 1 or M < 1:
        raise ValueError(f"need n_stages >= 1 and n_microbatches >= 1, "
                         f"got S={n_stages}, M={n_microbatches}")
    if extra_inflight < 0:
        raise ValueError(f"extra_inflight must be >= 0, "
                         f"got {extra_inflight}")
    per_stage: List[List[Op]] = []
    for s in range(S):
        w = min(S - 1 - s + extra_inflight, M)
        ops = [Op(s, "fwd", m) for m in range(w)]
        for k in range(M - w):
            ops.append(Op(s, "fwd", w + k))
            ops.append(Op(s, "bwd", k))
        ops.extend(Op(s, "bwd", k) for k in range(M - w, M))
        per_stage.append(ops)
    return per_stage


def merge_stage_ops(per_stage: Sequence[Sequence[Op]]) -> List[Op]:
    """Merge per-stage op lists into one dependency-valid total order.

    Greedy: repeatedly scan stages from the LAST to the first and take
    the head op whose dependencies (``fwd`` needs the upstream ``fwd``,
    ``bwd`` needs the downstream ``bwd`` and the local ``fwd``) are
    already in the order.  Scanning deep-first drains cotangents as
    early as they exist, which is what 1F1B wants.  Raises if no
    progress can be made (an invalid per-stage interleaving)."""
    S = len(per_stage)
    heads = [0] * S
    done = set()
    order: List[Op] = []

    def ready(op: Op) -> bool:
        s, kind, m = op
        if kind == "fwd":
            return s == 0 or (s - 1, "fwd", m) in done
        return ((s, "fwd", m) in done
                and (s == S - 1 or (s + 1, "bwd", m) in done))

    total = sum(len(ops) for ops in per_stage)
    while len(order) < total:
        progressed = False
        for s in reversed(range(S)):
            if heads[s] < len(per_stage[s]):
                op = per_stage[s][heads[s]]
                if ready(op):
                    order.append(op)
                    done.add(tuple(op))
                    heads[s] += 1
                    progressed = True
        if not progressed:
            stuck = [per_stage[s][heads[s]] for s in range(S)
                     if heads[s] < len(per_stage[s])]
            raise ValueError(
                f"per-stage op lists deadlock; next-up ops with "
                f"unsatisfied dependencies: {stuck}")
    return order


def schedule_1f1b(n_stages: int, n_microbatches: int) -> List[Op]:
    """Classic 1F1B as one host-executable total order."""
    return merge_stage_ops(stage_ops_1f1b(n_stages, n_microbatches))


def schedule_dcn_hiding(n_stages: int, n_microbatches: int, *,
                        extra_inflight: int = 1) -> List[Op]:
    """1F1B with ``extra_inflight`` extra warmup forwards per stage —
    run with asynchronous sends, the extra in-flight microbatches keep
    every stage busy while a DCN hop drains.  ``extra_inflight`` is
    the depth knob the autotuner sizes to
    ``ceil(link_seconds / stage_seconds)``."""
    return merge_stage_ops(stage_ops_1f1b(
        n_stages, n_microbatches, extra_inflight=extra_inflight))


SCHEDULES = {"1f1b": schedule_1f1b, "dcn_hiding": schedule_dcn_hiding}


def validate_order(order: Sequence[Op], n_stages: int,
                   n_microbatches: int) -> None:
    """Check a total order is executable: every (stage, microbatch)
    runs exactly one fwd and one bwd, and every op's dependencies
    precede it.  Raises ``ValueError`` with the offending op."""
    S, M = int(n_stages), int(n_microbatches)
    done = set()
    for op in order:
        s, kind, m = op
        if not (0 <= s < S and 0 <= m < M and kind in ("fwd", "bwd")):
            raise ValueError(f"op {op} out of range for S={S}, M={M}")
        if tuple(op) in done:
            raise ValueError(f"op {op} issued twice")
        if kind == "fwd" and s > 0 and (s - 1, "fwd", m) not in done:
            raise ValueError(f"{op} before upstream fwd")
        if kind == "bwd":
            if (s, "fwd", m) not in done:
                raise ValueError(f"{op} before its own fwd")
            if s < S - 1 and (s + 1, "bwd", m) not in done:
                raise ValueError(f"{op} before downstream bwd")
        done.add(tuple(op))
    if len(done) != 2 * S * M:
        raise ValueError(
            f"order has {len(done)} ops, want {2 * S * M} "
            f"(one fwd + one bwd per stage per microbatch)")


def edge_link_classes(n_stages: int, n_pods: int) -> Dict[int, str]:
    """Link class of each stage boundary: edge ``e`` joins stage ``e``
    to ``e+1`` and is ``"dcn"`` exactly when it crosses a pod boundary
    (stages are split into ``n_pods`` contiguous blocks)."""
    S, p = int(n_stages), max(int(n_pods), 1)
    if S % p:
        raise ValueError(f"n_pods ({p}) must divide n_stages ({S})")
    per_pod = S // p
    return {e: ("dcn" if (e + 1) % per_pod == 0 else "ici")
            for e in range(S - 1)}


def simulate(order: Sequence[Op], n_stages: int, n_microbatches: int, *,
             t_fwd: float, t_bwd: float,
             link_seconds: Optional[Dict[int, float]] = None,
             link_classes: Optional[Dict[int, str]] = None,
             blocking_sends: bool = True) -> Dict[str, object]:
    """Event-driven price of a schedule.

    Each stage is a serial executor; op start = max(stage free,
    message arrival).  ``link_seconds[e]`` is the one-way transfer time
    over edge ``e`` (both directions); ``blocking_sends=True`` stalls
    the SENDER for the transfer too — the SPMD/ppermute model where
    the hop sits inside the program — while ``False`` is the MPMD
    async-send model (the host hands the payload to the channel and
    the stage keeps computing).

    Returns ``makespan``, ``busy`` (per-stage busy seconds — the
    per-stage granularity the anatomy differ aligns measured stages
    against), ``bubble_fraction`` (1 − mean busy / makespan),
    per-link-class totals ``link_time`` and ``exposed`` (seconds a
    stage actually waited on a hop beyond its own readiness),
    ``hidden_fraction`` per class, plus the full predicted timeline:
    ``op_times`` (one ``{stage, kind, mb, start, end}`` row per op, in
    issue order) and ``xfers`` (one ``{src, dst, kind, mb, link_class,
    start, end}`` row per stage-boundary transfer) — the records
    :mod:`apex_tpu.observability.anatomy` reconstructs and diffs a
    measured run against."""
    S, M = int(n_stages), int(n_microbatches)
    validate_order(order, S, M)
    link_seconds = dict(link_seconds or {})
    link_classes = dict(link_classes if link_classes is not None
                        else edge_link_classes(S, 1))
    free = [0.0] * S
    busy = [0.0] * S
    out_t: Dict[Tuple[int, str, int], float] = {}
    link_time = {"ici": 0.0, "dcn": 0.0}
    exposed = {"ici": 0.0, "dcn": 0.0}
    op_times: List[Dict[str, object]] = []
    xfers: List[Dict[str, object]] = []

    for op in order:
        s, kind, m = op
        dur = float(t_fwd if kind == "fwd" else t_bwd)
        # the incoming message, if any: fwd from s-1, bwd from s+1
        src = s - 1 if kind == "fwd" else s + 1
        edge = min(s, src)
        if 0 <= src < S:
            link = float(link_seconds.get(edge, 0.0))
            lc = link_classes.get(edge, "ici")
            produced = out_t[(src, kind, m)]
            arrival = produced + link
            link_time[lc] += link
            start = max(free[s], arrival)
            exposed[lc] += max(0.0, arrival - max(free[s], produced))
            xfers.append({"src": src, "dst": s, "kind": kind, "mb": m,
                          "link_class": lc, "start": produced,
                          "end": arrival})
        else:
            start = free[s]
        end = start + dur
        busy[s] += dur
        out_t[(s, kind, m)] = end
        op_times.append({"stage": s, "kind": kind, "mb": m,
                         "start": start, "end": end})
        sends = (kind == "fwd" and s < S - 1) or (kind == "bwd" and s > 0)
        if sends and blocking_sends:
            dst_edge = s if kind == "fwd" else s - 1
            free[s] = end + float(link_seconds.get(dst_edge, 0.0))
        else:
            free[s] = end

    makespan = max(out_t.values())
    hidden = {lc: (1.0 - exposed[lc] / link_time[lc]
                   if link_time[lc] > 0 else 1.0)
              for lc in link_time}
    return {
        "makespan": makespan,
        "busy": list(busy),
        "bubble_fraction": 1.0 - (sum(busy) / S) / makespan,
        "link_time": link_time,
        "exposed": exposed,
        "hidden_fraction": hidden,
        "op_times": op_times,
        "xfers": xfers,
    }
